"""Batched TPU kernel vs scalar oracle: exact-equivalence property tests.

The scalar engine (core/rate_limiter.py, itself pinned against the reference
semantics by test_gcra_math.py) processes each batch request-at-a-time in
arrival order; the batched kernel must produce identical outputs AND
identical table state — including intra-batch duplicate keys, degenerate
corners (burst=1, quantity=0, sub-ns emission), mid-batch parameter changes,
expiry, and sweeps.
"""

import numpy as np
import pytest

from throttlecrab_tpu import RateLimiter
from throttlecrab_tpu.core.errors import CellError
from throttlecrab_tpu.core.i64 import I64_MAX
from throttlecrab_tpu.core.store.mapstore import MapStore
from throttlecrab_tpu.tpu import (
    EMPTY_EXPIRY,
    STATUS_INVALID_PARAMS,
    STATUS_NEGATIVE_QUANTITY,
    STATUS_OK,
    TpuRateLimiter,
)

NS = 1_000_000_000
BASE = 1_753_700_000 * NS


class OracleStore(MapStore):
    """Dict store with cleanup disabled: pure CAS/TTL semantics."""

    def _maybe_cleanup(self, now_ns):
        pass


def oracle_batch(limiter, keys, burst, count, period, qty, now_ns):
    n = len(keys)
    out = {
        "allowed": np.zeros(n, bool),
        "remaining": np.zeros(n, np.int64),
        "reset": np.zeros(n, np.int64),
        "retry": np.zeros(n, np.int64),
        "status": np.zeros(n, np.uint8),
    }
    for i in range(n):
        try:
            a, r = limiter.rate_limit(
                keys[i], int(burst[i]), int(count[i]), int(period[i]),
                int(qty[i]), now_ns,
            )
        except CellError:
            out["status"][i] = (
                STATUS_NEGATIVE_QUANTITY if qty[i] < 0 else STATUS_INVALID_PARAMS
            )
            continue
        out["allowed"][i] = a
        out["remaining"][i] = r.remaining
        out["reset"][i] = min(r.reset_after_ns, I64_MAX)
        out["retry"][i] = min(r.retry_after_ns, I64_MAX)
    return out


def assert_batch_equal(tpu_res, oracle_res, context=""):
    np.testing.assert_array_equal(
        tpu_res.status, oracle_res["status"], err_msg=f"status {context}"
    )
    ok = oracle_res["status"] == STATUS_OK
    np.testing.assert_array_equal(
        tpu_res.allowed[ok], oracle_res["allowed"][ok], err_msg=f"allowed {context}"
    )
    np.testing.assert_array_equal(
        tpu_res.remaining[ok], oracle_res["remaining"][ok],
        err_msg=f"remaining {context}",
    )
    np.testing.assert_array_equal(
        tpu_res.reset_after_ns[ok], oracle_res["reset"][ok],
        err_msg=f"reset_after {context}",
    )
    np.testing.assert_array_equal(
        tpu_res.retry_after_ns[ok], oracle_res["retry"][ok],
        err_msg=f"retry_after {context}",
    )


def assert_state_equal(tpu: TpuRateLimiter, store: OracleStore, context=""):
    tat = np.asarray(tpu.table.tat)
    expiry = np.asarray(tpu.table.expiry)
    for key, (tat_o, exp_o) in store._data.items():
        slot = tpu.keymap._map.get(key)
        assert slot is not None, f"{context}: oracle has {key!r}, keymap doesn't"
        assert tat[slot] == tat_o, f"{context}: tat mismatch for {key!r}"
        exp_clamped = min(exp_o, I64_MAX) if exp_o is not None else I64_MAX
        assert expiry[slot] == exp_clamped, f"{context}: expiry mismatch for {key!r}"
    # Keys the oracle never wrote must be vacant (or untouched) in the table.
    for key, slot in tpu.keymap._map.items():
        if key not in store._data:
            assert expiry[slot] == EMPTY_EXPIRY, (
                f"{context}: table has state for unwritten key {key!r}"
            )


@pytest.fixture
def pair():
    return TpuRateLimiter(capacity=256), RateLimiter(OracleStore())


def run_and_compare(tpu, oracle, keys, burst, count, period, qty, now, ctx=""):
    n = len(keys)
    burst = np.broadcast_to(np.asarray(burst, np.int64), (n,))
    count = np.broadcast_to(np.asarray(count, np.int64), (n,))
    period = np.broadcast_to(np.asarray(period, np.int64), (n,))
    qty = np.broadcast_to(np.asarray(qty, np.int64), (n,))
    res = tpu.rate_limit_batch(keys, burst, count, period, qty, now)
    exp = oracle_batch(oracle, keys, burst, count, period, qty, now)
    assert_batch_equal(res, exp, ctx)
    assert_state_equal(tpu, oracle.store, ctx)
    return res


class TestBasics:
    def test_unique_keys_burst(self, pair):
        tpu, oracle = pair
        keys = [f"k{i}" for i in range(8)]
        run_and_compare(tpu, oracle, keys, 5, 10, 60, 1, BASE, "batch0")

    def test_sequential_batches_exhaust_burst(self, pair):
        tpu, oracle = pair
        for b in range(7):
            run_and_compare(
                tpu, oracle, ["user:1"], 5, 10, 60, 1, BASE, f"batch{b}"
            )

    def test_replenishment_across_batches(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["k"] * 5, 5, 10, 60, 1, BASE, "exhaust")
        for dt in (1, 3, 6, 7, 12, 60):
            run_and_compare(
                tpu, oracle, ["k"], 5, 10, 60, 1, BASE + dt * NS, f"+{dt}s"
            )


class TestDuplicates:
    def test_duplicate_key_serialized(self, pair):
        tpu, oracle = pair
        # 8 requests for one key, burst 5: exactly 5 allowed, in order.
        res = run_and_compare(
            tpu, oracle, ["hot"] * 8, 5, 10, 60, 1, BASE, "dup"
        )
        assert res.allowed.sum() == 5
        assert res.allowed[:5].all() and not res.allowed[5:].any()

    def test_duplicates_interleaved_with_others(self, pair):
        tpu, oracle = pair
        keys = ["a", "hot", "b", "hot", "c", "hot", "hot", "d", "hot"]
        run_and_compare(tpu, oracle, keys, 3, 30, 60, 1, BASE, "interleaved")

    def test_duplicate_quantities(self, pair):
        tpu, oracle = pair
        # Same key, same quantity per batch (uniformity holds), quantity 2.
        run_and_compare(tpu, oracle, ["q"] * 6, 10, 100, 60, 2, BASE, "q2")

    def test_param_change_mid_batch(self, pair):
        tpu, oracle = pair
        # Key 'x' appears with different params within one batch: the
        # conflict-round path must preserve arrival-order semantics.
        keys = ["x", "x", "x", "y", "x"]
        burst = np.array([5, 5, 3, 4, 5], np.int64)
        count = np.array([10, 10, 30, 40, 10], np.int64)
        period = np.array([60, 60, 60, 60, 60], np.int64)
        qty = np.array([1, 1, 1, 1, 1], np.int64)
        res = tpu.rate_limit_batch(keys, burst, count, period, qty, BASE)
        exp = oracle_batch(oracle, keys, burst, count, period, qty, BASE)
        assert_batch_equal(res, exp, "param-change")
        assert_state_equal(tpu, oracle.store, "param-change")


class TestDegenerateCorners:
    def test_burst_one_never_denies(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["b1"] * 6, 1, 1, 60, 1, BASE, "b1q1")
        run_and_compare(tpu, oracle, ["b1"] * 3, 1, 1, 60, 1, BASE + 1, "b1q1+1ns")

    def test_burst_one_quantity_two(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["b1"] * 4, 1, 60, 60, 2, BASE, "b1q2")

    def test_burst_one_quantity_zero(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["b1"] * 4, 1, 60, 60, 0, BASE, "b1q0")

    def test_quantity_zero_probe(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["p"] * 3, 5, 10, 60, 0, BASE, "q0-fresh")
        run_and_compare(tpu, oracle, ["p"] * 2, 5, 10, 60, 1, BASE, "q1-after")
        run_and_compare(tpu, oracle, ["p"] * 3, 5, 10, 60, 0, BASE, "q0-live")

    def test_zero_emission_interval(self, pair):
        tpu, oracle = pair
        # count > period * 1e9 → emission interval 0 ns.
        run_and_compare(
            tpu, oracle, ["z"] * 4, 5, 2_000_000_000, 1, 1, BASE, "E0"
        )

    def test_stale_key_clamped(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["s"] * 3, 4, 60, 60, 1, BASE, "fill")
        # Far in the future (but within TTL? no — past TTL it's a miss;
        # use a long period so the entry survives) the TAT clamp applies.
        run_and_compare(
            tpu, oracle, ["s"] * 2, 4, 4, 3600, 1, BASE + 30 * NS, "clamped"
        )


class TestValidation:
    def test_status_codes(self, pair):
        tpu, oracle = pair
        keys = ["ok", "neg", "bad", "ok2"]
        burst = np.array([5, 5, 0, 5], np.int64)
        count = np.array([10, 10, 10, 10], np.int64)
        period = np.array([60, 60, 60, 60], np.int64)
        qty = np.array([1, -1, 1, 1], np.int64)
        res = tpu.rate_limit_batch(keys, burst, count, period, qty, BASE)
        exp = oracle_batch(oracle, keys, burst, count, period, qty, BASE)
        assert list(res.status) == [
            STATUS_OK,
            STATUS_NEGATIVE_QUANTITY,
            STATUS_INVALID_PARAMS,
            STATUS_OK,
        ]
        assert_batch_equal(res, exp, "validation")

    def test_scalar_compat_api_raises(self, pair):
        tpu, _ = pair
        with pytest.raises(CellError):
            tpu.rate_limit("k", 5, 10, 60, -1, BASE)
        with pytest.raises(CellError):
            tpu.rate_limit("k", 0, 10, 60, 1, BASE)
        allowed, result = tpu.rate_limit("k", 5, 10, 60, 1, BASE)
        assert allowed and result.remaining == 4 and result.limit == 5


class TestTableLifecycle:
    def test_growth(self):
        tpu = TpuRateLimiter(capacity=16)
        oracle = RateLimiter(OracleStore())
        keys = [f"g{i}" for i in range(100)]
        run_and_compare(tpu, oracle, keys, 5, 10, 60, 1, BASE, "grow")
        assert tpu.table.capacity >= 100
        assert len(tpu) == 100

    def test_sweep_frees_and_recycles(self):
        tpu = TpuRateLimiter(capacity=64)
        keys = [f"e{i}" for i in range(32)]
        # 10/60s → tolerance 4*6s=24s; TTL ≈ 30s.
        tpu.rate_limit_batch(keys, [5] * 32, [10] * 32, [60] * 32, [1] * 32, BASE)
        assert len(tpu) == 32
        freed = tpu.sweep(BASE + 120 * NS)
        assert freed == 32
        assert len(tpu) == 0
        # Recycled slots behave as fresh keys.
        res = tpu.rate_limit_batch(
            ["fresh"], [5], [10], [60], [1], BASE + 121 * NS
        )
        assert res.allowed[0] and res.remaining[0] == 4

    def test_expired_key_is_miss_before_sweep(self, pair):
        tpu, oracle = pair
        run_and_compare(tpu, oracle, ["x"] * 5, 5, 10, 60, 1, BASE, "fill")
        # Way past the TTL, no sweep has run: both see a fresh key.
        run_and_compare(
            tpu, oracle, ["x"], 5, 10, 60, 1, BASE + 3600 * NS, "post-ttl"
        )


class TestRandomizedEquivalence:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_scenarios(self, seed):
        rng = np.random.RandomState(seed)
        tpu = TpuRateLimiter(capacity=64)
        oracle = RateLimiter(OracleStore())
        pool = [f"key{i}" for i in range(12)]
        # Per-key fixed params (heterogeneous across keys), including
        # degenerate bursts and quantities.
        params = {
            k: (
                int(rng.randint(1, 8)),        # burst (incl. 1)
                int(rng.randint(1, 2000)),     # count
                int(rng.choice([1, 10, 60, 3600])),
            )
            for k in pool
        }
        now = BASE
        for step in range(12):
            n = int(rng.randint(1, 24))
            keys = [pool[rng.randint(len(pool))] for _ in range(n)]
            burst = np.array([params[k][0] for k in keys], np.int64)
            count = np.array([params[k][1] for k in keys], np.int64)
            period = np.array([params[k][2] for k in keys], np.int64)
            # One quantity per key per batch (uniformity), 0..3.
            qty_by_key = {k: int(rng.randint(0, 4)) for k in set(keys)}
            qty = np.array([qty_by_key[k] for k in keys], np.int64)
            run_and_compare(
                tpu, oracle, keys, burst, count, period, qty, now,
                f"seed{seed}-step{step}",
            )
            now += int(rng.randint(0, 5 * NS))

    @pytest.mark.parametrize("seed", range(3))
    def test_random_scenarios_native_keymap(self, seed):
        from throttlecrab_tpu.native import native_available

        if not native_available():
            pytest.skip("native keymap unavailable")
        rng = np.random.RandomState(50 + seed)
        tpu = TpuRateLimiter(capacity=64, keymap="native")
        oracle = RateLimiter(OracleStore())
        pool = [f"n{i}" for i in range(10)]
        params = {
            k: (int(rng.randint(1, 8)), int(rng.randint(1, 500)), 60)
            for k in pool
        }
        now = BASE
        for step in range(10):
            n_req = int(rng.randint(1, 20))
            keys = [pool[rng.randint(len(pool))] for _ in range(n_req)]
            burst = np.array([params[k][0] for k in keys], np.int64)
            count = np.array([params[k][1] for k in keys], np.int64)
            period = np.array([params[k][2] for k in keys], np.int64)
            qty_by_key = {k: int(rng.randint(0, 3)) for k in set(keys)}
            qty = np.array([qty_by_key[k] for k in keys], np.int64)
            res = tpu.rate_limit_batch(keys, burst, count, period, qty, now)
            exp = oracle_batch(oracle, keys, burst, count, period, qty, now)
            assert_batch_equal(res, exp, f"native{seed}-step{step}")
            now += int(rng.randint(0, 5 * NS))
        # Sweep path through the native free list.
        freed = tpu.sweep(now + 7200 * NS)
        assert freed == len(oracle.store._data) or freed <= 10

    @pytest.mark.parametrize("seed", range(3))
    def test_random_with_param_churn(self, seed):
        # Params RE-randomized per request (same key may carry different
        # params within one batch) → exercises the conflict-round path.
        rng = np.random.RandomState(100 + seed)
        tpu = TpuRateLimiter(capacity=64)
        oracle = RateLimiter(OracleStore())
        pool = [f"c{i}" for i in range(6)]
        now = BASE
        for step in range(8):
            n = int(rng.randint(2, 16))
            keys = [pool[rng.randint(len(pool))] for _ in range(n)]
            burst = rng.randint(1, 6, n).astype(np.int64)
            count = rng.randint(1, 500, n).astype(np.int64)
            period = rng.choice([1, 60, 600], n).astype(np.int64)
            qty = rng.randint(0, 3, n).astype(np.int64)
            run_and_compare(
                tpu, oracle, keys, burst, count, period, qty, now,
                f"churn{seed}-step{step}",
            )
            now += int(rng.randint(0, 3 * NS))


class TestWrappedBurstLimit:
    """Differential-fuzz regression (round 4): a tolerance big enough
    that now + tol overflows i64 must WRAP (reference burst_limit
    semantics, rate_limiter.rs via core wrap_i64) — the saturating add
    reported ~1.2e8 remaining where the reference reports 0."""

    # seed-37 fuzz case: em*(burst-1) wraps to a huge POSITIVE tol
    # (7.66e18), so now + tol > i64::MAX while nothing is degenerate.
    BURST = 169_785_306_178
    COUNT = 559_666
    PERIOD = 1 << 25
    NOW = 1_753_700_000 * NS

    def params(self):
        from throttlecrab_tpu.tpu.limiter import derive_params

        em, tol, invalid = derive_params(
            np.array([self.BURST], np.int64),
            np.array([self.COUNT], np.int64),
            np.array([self.PERIOD], np.int64),
        )
        assert not invalid[0] and tol[0] > 0
        assert self.NOW + int(tol[0]) > (1 << 63) - 1  # really overflows
        return em, tol

    def oracle(self, qty):
        from throttlecrab_tpu.core.rate_limiter import RateLimiter as Oracle
        from throttlecrab_tpu.core.store.periodic import PeriodicStore

        lim = Oracle(PeriodicStore())
        return lim.rate_limit(
            "w", self.BURST, self.COUNT, self.PERIOD, qty, self.NOW
        )

    def test_exact_path_wraps(self):
        """The default (with_degen=True) kernel must wrap burst_limit."""
        em, tol = self.params()
        tpu = TpuRateLimiter(capacity=64)
        res = tpu.rate_limit_batch(
            ["w"], self.BURST, self.COUNT, self.PERIOD, 3, self.NOW
        )
        allowed, want = self.oracle(3)
        assert bool(res.allowed[0]) == allowed
        assert int(res.remaining[0]) == want.remaining == 0
        assert int(res.reset_after_ns[0]) == want.reset_after_ns

    def test_degenerate_batch_wraps(self):
        """A qty-0 batchmate routes the same key through the degenerate
        3-view kernel; remaining must still wrap to 0."""
        tpu = TpuRateLimiter(capacity=64)
        res = tpu.rate_limit_batch(
            ["w", "probe"],
            [self.BURST, 5],
            [self.COUNT, 10],
            [self.PERIOD, 60],
            [3, 0],
            self.NOW,
        )
        allowed, want = self.oracle(3)
        assert bool(res.allowed[0]) == allowed
        assert int(res.remaining[0]) == want.remaining == 0

    def test_certified_wire_path_wraps(self):
        """wire=True on non-degenerate traffic compiles the certificate
        in (with_degen=False, limiter.py) — the CERTIFIED kernel must
        wrap too, and every wire field must match the oracle's."""
        from throttlecrab_tpu.tpu.limiter import has_degenerate

        em, tol = self.params()
        assert not has_degenerate(
            np.array([True]), em, tol, np.array([3], np.int64)
        )
        tpu = TpuRateLimiter(capacity=64)
        res = tpu.rate_limit_batch(
            ["w"], self.BURST, self.COUNT, self.PERIOD, 3, self.NOW,
            wire=True,
        )
        allowed, want = self.oracle(3)
        assert bool(res.allowed[0]) == allowed
        assert int(res.remaining[0]) == want.remaining == 0
        assert int(res.reset_after_s[0]) == min(
            want.reset_after_ns // NS, (1 << 31) - 1
        )
        assert int(res.retry_after_s[0]) == min(
            want.retry_after_ns // NS, (1 << 31) - 1
        )


@pytest.mark.parametrize("seed", range(1000, 1012))
def test_random_scenarios_wild_params(seed):
    """Differential fuzz with occasionally-extreme parameters (bursts to
    2^40, counts to 2^20, periods to 2^25 s): the class that caught the
    wrapped-burst-limit bug.  Virtual clocks near 0 included."""
    rng = np.random.RandomState(seed)
    native = bool(seed % 2)
    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore

    try:
        tpu = TpuRateLimiter(
            capacity=128, keymap="native" if native else "python"
        )
    except RuntimeError:
        pytest.skip("native keymap unavailable")
    oracle = RateLimiter(PeriodicStore())
    pool = [
        (f"w{seed}k{i}".encode() if native else f"w{seed}k{i}")
        for i in range(int(rng.randint(2, 12)))
    ]
    params = {}
    for k in pool:
        wild = rng.rand() < 0.2
        params[k] = (
            int(rng.randint(1, 1 << 40)) if wild else int(rng.randint(1, 30)),
            int(rng.randint(1, 1 << 20)) if wild else int(rng.randint(1, 3000)),
            int(rng.choice([1, 10, 3600, 1 << 25])) if wild
            else int(rng.choice([1, 10, 60, 3600])),
        )
    now = BASE if seed % 3 else int(rng.randint(0, 10 * NS))
    for step in range(10):
        if rng.rand() < 0.25:
            # Interleave an expiry sweep (slot recycling); the oracle's
            # store expires on read, so only the engine needs the call.
            # Occasionally jump time so the sweep actually collects.
            if rng.rand() < 0.5:
                now += int(rng.randint(1, 7200)) * NS
            tpu.sweep(now)
        n = int(rng.randint(1, 28))
        keys = [pool[rng.randint(len(pool))] for _ in range(n)]
        b = np.array([params[k][0] for k in keys], np.int64)
        c = np.array([params[k][1] for k in keys], np.int64)
        p = np.array([params[k][2] for k in keys], np.int64)
        q = np.array([int(rng.randint(0, 5)) for _ in keys], np.int64)
        qm: dict = {}
        for i, k in enumerate(keys):
            q[i] = qm.setdefault(k, int(q[i]))
        res = tpu.rate_limit_batch(keys, b, c, p, q, now)
        exp = oracle_batch(oracle, keys, b, c, p, q, now)
        assert_batch_equal(res, exp, f"wild seed{seed} step{step}")
        now += int(rng.randint(0, 3 * NS))
