"""Always-on slice of the round-5 tier-ladder fuzz campaign.

scripts/fuzz_wire_tiers.py is the full campaign (hundreds of seeds);
this keeps a few seeds — one per traffic profile — running in the
regular suite so the differential class (w32/cur/4-plane tier
selection, hwm crossings, poison keys, degenerate probes, clock
regressions, sweeps, snapshot round trips vs the scalar oracle) can
never silently rot.
"""

import importlib.util
import pathlib

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "fuzz_wire_tiers",
    pathlib.Path(__file__).resolve().parent.parent
    / "scripts"
    / "fuzz_wire_tiers.py",
)
fuzz = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(fuzz)


@pytest.mark.parametrize("seed", [3000, 3001, 3002])  # benign/edges/hostile
def test_tier_ladder_fuzz_slice(seed):
    from conftest import require_devices

    try:
        require_devices(2)
        from throttlecrab_tpu.parallel.sharded import make_mesh

        mesh = make_mesh(2)
    except Exception:
        mesh = None
    before = fuzz.TOTAL["requests"]
    fuzz.run_seed(seed, steps=8, sharded_mesh=mesh)
    assert fuzz.TOTAL["requests"] > before


@pytest.mark.parametrize("seed", [3100, 3101])  # edges/hostile profiles
def test_tier_ladder_fuzz_fused_alternation(seed, monkeypatch):
    """The fused Pallas decision kernel alternated with the composed-XLA
    path across consecutive windows of the tier-ladder corpus: both stay
    pinned to the scalar oracle request-by-request, and each continues
    exactly from the table state the other left (the kill-switch
    stored-state compatibility contract).  Odd seeds arm the insight
    tier on BOTH the single-device limiter and the mesh, covering the
    fused kernel's 6-wide row template; even seeds pin the 4-wide one.
    The hostile profile (3101) drives the degenerate three-view orbit
    and the tier ladder's mid-stream downgrades through the fused path.
    """
    monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", "0")
    from conftest import require_devices

    try:
        require_devices(2)
        from throttlecrab_tpu.parallel.sharded import make_mesh

        mesh = make_mesh(2)
    except Exception:
        mesh = None
    before = fuzz.TOTAL["requests"]
    fuzz.run_seed(
        seed, steps=6, sharded_mesh=mesh,
        fused_alternate=True, insight_single=bool(seed % 2),
    )
    assert fuzz.TOTAL["requests"] > before


def test_hotkey_abuse_deny_cache_slice():
    """One seed of the hot-key abuse profile (harness `hotkey-abuse`
    pattern) through the front tier's deny cache: cache-on and cache-off
    decisions pinned equal request-by-request, and the cache must have
    actually served (hits > 0 — equality alone would be vacuous)."""
    before = fuzz.TOTAL["requests"]
    hits = fuzz.run_hotkey_deny_seed(4000, steps=24)
    assert fuzz.TOTAL["requests"] > before
    assert hits > 0


@pytest.mark.parametrize("seed", [6000, 6001])
def test_trace_codec_fuzz_slice(seed):
    """Always-on slice of the record/replay trace-codec mutation fuzz
    (truncation, corruption, count-vs-size lies): every rejection must
    be the typed TraceError — the full campaign lives in
    scripts/fuzz_wire_tiers.py alongside the cluster-codec fuzzer."""
    n = fuzz.run_trace_frame_fuzz(seed, iters=250)
    assert n == 250
