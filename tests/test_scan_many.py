"""rate_limit_many (K sub-batches per launch) must be observationally
identical to K sequential rate_limit_batch calls — the scan carry is the
same table state the sequential path would thread through."""

import asyncio

import numpy as np

from throttlecrab_tpu.server.engine import BatchingEngine
from throttlecrab_tpu.server.types import ThrottleRequest
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


def result_tuple(res):
    return (
        res.allowed.tolist(),
        res.remaining.tolist(),
        res.reset_after_ns.tolist(),
        res.retry_after_ns.tolist(),
        res.status.tolist(),
    )


def test_scan_matches_sequential():
    rng = np.random.default_rng(5)
    batches = []
    for k in range(6):
        keys = [f"k{int(x)}" for x in rng.integers(0, 25, 64)]
        batches.append((keys, 5, 100, 60, 1, T0 + k * 10_000_000))

    seq = TpuRateLimiter(capacity=256)
    want = [seq.rate_limit_batch(*b) for b in batches]

    scan = TpuRateLimiter(capacity=256)
    got = scan.rate_limit_many(batches)

    for k, (w, g) in enumerate(zip(want, got)):
        assert result_tuple(w) == result_tuple(g), f"sub-batch {k}"


def test_scan_cross_batch_state_carries():
    # Burst 10, 4 sub-batches x 4 hits on one key: exactly 10 allowed, in
    # arrival order across the whole window.
    batches = [
        (["hot"] * 4, 10, 100, 3600, 1, T0 + k) for k in range(4)
    ]
    lim = TpuRateLimiter(capacity=64)
    results = lim.rate_limit_many(batches)
    allowed = [bool(a) for r in results for a in r.allowed]
    assert allowed == [True] * 10 + [False] * 6


def test_scan_with_invalid_requests():
    batches = [
        (["a", "b"], [5, -1], 100, 60, 1, T0),
        (["a"], 5, 100, 60, [-3], T0 + 1),
    ]
    lim = TpuRateLimiter(capacity=64)
    r0, r1 = lim.rate_limit_many(batches)
    assert r0.allowed[0] and not r0.allowed[1]
    assert r0.status[1] != 0
    assert r1.status[0] != 0


def test_scan_param_conflict_falls_back():
    # Same key changes params mid-batch: exact sequential semantics still.
    batches = [
        (["p", "p"], [5, 2], [10, 10], [60, 60], 1, T0),
        (["p"], 2, 10, 60, 1, T0 + 1),
    ]
    seq = TpuRateLimiter(capacity=64)
    want = [seq.rate_limit_batch(*b) for b in batches]
    scan = TpuRateLimiter(capacity=64)
    got = scan.rate_limit_many(batches)
    for w, g in zip(want, got):
        assert result_tuple(w) == result_tuple(g)


def test_scan_uneven_batch_sizes():
    batches = [
        ([f"a{i}" for i in range(40)], 5, 100, 60, 1, T0),
        ([f"a{i}" for i in range(3)], 5, 100, 60, 1, T0 + 1),
        ([f"b{i}" for i in range(130)], 5, 100, 60, 1, T0 + 2),
    ]
    seq = TpuRateLimiter(capacity=512)
    want = [seq.rate_limit_batch(*b) for b in batches]
    scan = TpuRateLimiter(capacity=512)
    got = scan.rate_limit_many(batches)
    for w, g in zip(want, got):
        assert result_tuple(w) == result_tuple(g)


def test_engine_backlog_drains_through_scan_path():
    async def main():
        limiter = TpuRateLimiter(capacity=2048)
        engine = BatchingEngine(
            limiter, batch_size=32, max_linger_us=100_000,
            now_fn=lambda: T0,
        )
        # 300 requests >> batch_size: the flush loop takes the _decide_many
        # path (n_batches > 1).
        results = await asyncio.gather(
            *[
                engine.throttle(
                    ThrottleRequest(f"w{i % 40}", 50, 100, 3600, 1)
                )
                for i in range(300)
            ]
        )
        return results

    results = asyncio.run(main())
    # 300 requests over 40 keys = 7-8 per key < burst 50: all allowed.
    assert all(r.allowed for r in results)
    assert all(r.limit == 50 for r in results)


def test_engine_double_buffers_sharded_limiter():
    """The flush loop's dispatch/fetch split must work against the
    sharded limiter too (dispatch_many on the mesh): exactness across
    overlapped windows on the 8-device CPU mesh."""
    from throttlecrab_tpu.parallel.sharded import ShardedTpuRateLimiter

    async def main():
        limiter = ShardedTpuRateLimiter(capacity_per_shard=512)
        engine = BatchingEngine(
            limiter, batch_size=16, max_linger_us=500,
            now_fn=lambda: T0, max_scan_depth=2,
        )
        results = await asyncio.gather(
            *[
                engine.throttle(
                    ThrottleRequest("sharded:hot", 24, 100, 3600, 1)
                )
                for _ in range(64)
            ]
        )
        return results

    results = asyncio.run(main())
    assert sum(r.allowed for r in results) == 24
