"""Insight tier (L3.75): device analytics vs a host recount, sketch
bounds, the feedback loop, and truthful /stats across degrade→recover.

The acceptance contract (ISSUE 5):

  * with insight OFF the decision path is bit-identical to a limiter
    built without the subsystem (differential, every output tier);
  * with it ON, the device aggregates — running [allowed, denied]
    totals and the per-slot denied-hit counter column — match a host
    scalar recount of the very same results EXACTLY, under the
    tier-fuzz key patterns (hot-key abuse, flash crowd, chaos mix);
  * the space-saving sketch honors its documented error bound
    (estimate - error <= true <= estimate) and is exact below capacity;
  * /stats stays truthful across a chaos degrade→recover cycle: the
    host-oracle path keeps accounting while the device is down, and
    nothing is lost or double-counted over the whole lifecycle;
  * the feedback loop: confirmed hot-denied keys are refreshed against
    deny-cache eviction, and hot-set concentration tightens admission's
    peek shedding (weight 0 = exact old behavior).
"""

import json

import numpy as np
import pytest

from throttlecrab_tpu import faults
from throttlecrab_tpu.front import AdmissionController, DenyCache, FrontTier
from throttlecrab_tpu.harness.workload import flash_crowd_hot_sets, make_keys
from throttlecrab_tpu.insight import InsightTier, SpaceSavingSketch
from throttlecrab_tpu.insight.collector import RateWindow, SlotKeyResolver
from throttlecrab_tpu.server.supervisor import (
    STATE_DEGRADED,
    STATE_OK,
    SupervisedLimiter,
)
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


def _recount(keys, results):
    """Host oracle recount: per-key denied counts + totals from the
    decided result planes themselves."""
    allowed = denied = 0
    per_key: dict = {}
    for ks, res in zip(keys, results):
        ok = res.status == 0
        for k, a, o in zip(ks, res.allowed, ok):
            if not o:
                continue
            if a:
                allowed += 1
            else:
                denied += 1
                per_key[k] = per_key.get(k, 0) + 1
    return allowed, denied, per_key


def _slot_counts(lim):
    """Fetch the whole denied-hit column as {key: count}."""
    tk = lim.table.insight_topk(lim.table.capacity)
    vals = np.asarray(tk[0]).tolist()
    ids = np.asarray(tk[1]).tolist()
    rev = lim.keymap._rev
    return {rev[s]: v for v, s in zip(vals, ids) if v > 0}


# --------------------------------------------------------------------- #
# Differential: device aggregates vs host recount, decisions unchanged.


@pytest.mark.parametrize(
    "pattern", ["hotkey-abuse", "flash-crowd", "chaos", "zipfian"]
)
def test_device_aggregates_match_host_recount(pattern):
    lim = TpuRateLimiter(capacity=1 << 12, keymap="python", insight=True)
    twin = TpuRateLimiter(capacity=1 << 12, keymap="python")
    stream = make_keys(pattern, 1024, 2000, seed=3)
    batches, results = [], []
    for i in range(8):
        ks = stream[i * 128 : (i + 1) * 128]
        now = T0 + i * NS // 10
        wire = i % 2 == 0
        res = lim.rate_limit_batch(
            ks, 3, 10, 60, 1, now, wire=wire, collect_cur=wire
        )
        ref = twin.rate_limit_batch(
            ks, 3, 10, 60, 1, now, wire=wire, collect_cur=wire
        )
        assert (res.allowed == ref.allowed).all()
        assert (res.remaining == ref.remaining).all()
        batches.append(ks)
        results.append(res)
    allowed, denied, per_key = _recount(batches, results)
    assert lim.table.insight_counts() == (allowed, denied)
    assert _slot_counts(lim) == per_key


def test_aggregates_exact_on_scan_and_degenerate_paths():
    lim = TpuRateLimiter(capacity=1 << 10, keymap="python", insight=True)
    batches, results = [], []
    # Scan path (rate_limit_many), duplicate keys within batches.
    wins = [
        ([f"d{i % 7}" for i in range(64)], 2, 6, 60, 1, T0),
        ([f"d{i % 3}" for i in range(64)], 2, 6, 60, 1, T0 + NS),
    ]
    for (ks, *_), res in zip(wins, lim.rate_limit_many(wins, wire=True)):
        batches.append(ks)
        results.append(res)
    # Degenerate path: burst-1 (tolerance 0) and quantity-0 probes.
    ks = [f"d{i % 5}" for i in range(32)]
    results.append(
        lim.rate_limit_batch(ks, 1, 10, 60, 1, T0 + 2 * NS)
    )
    batches.append(ks)
    results.append(
        lim.rate_limit_batch(ks, 2, 6, 60, 0, T0 + 3 * NS)
    )
    batches.append(ks)
    # Invalid rows must count nowhere.
    ks_bad = ["x", "y"]
    results.append(
        lim.rate_limit_batch(ks_bad, 0, 0, 0, 1, T0 + 4 * NS)
    )
    batches.append(ks_bad)
    allowed, denied, per_key = _recount(batches, results)
    assert lim.table.insight_counts() == (allowed, denied)
    assert _slot_counts(lim) == per_key


def test_kill_switch_decisions_bit_identical_and_state_layout():
    on = TpuRateLimiter(capacity=1 << 8, keymap="python", insight=True)
    off = TpuRateLimiter(capacity=1 << 8, keymap="python")
    assert off.table.state.shape[-1] == 4  # pre-insight layout intact
    assert on.table.state.shape[-1] > 4
    stream = make_keys("hotkey-abuse", 512, 500, seed=9)
    for i in range(4):
        ks = stream[i * 128 : (i + 1) * 128]
        a = on.rate_limit_batch(ks, 3, 10, 60, 1, T0 + i, wire=True)
        b = off.rate_limit_batch(ks, 3, 10, 60, 1, T0 + i, wire=True)
        for f in ("allowed", "remaining", "reset_after_s", "retry_after_s",
                  "status"):
            assert (getattr(a, f) == getattr(b, f)).all(), f
    # And the stored GCRA state is bit-identical column for column.
    cap = off.table.capacity
    np.testing.assert_array_equal(
        np.asarray(on.table.state)[:cap, :4],
        np.asarray(off.table.state)[:cap],
    )


def test_sweep_clears_heat_and_decay_halves():
    lim = TpuRateLimiter(capacity=1 << 8, keymap="python", insight=True)
    ks = ["a"] * 10
    # burst 2, 1/100s: the 10-deep segment allows 2 and denies 8.
    lim.rate_limit_batch(ks, 2, 1, 100, 1, T0)
    assert _slot_counts(lim) == {"a": 8}
    lim.table.insight_decay()
    assert _slot_counts(lim) == {"a": 4}
    lim.sweep(T0 + 10**15)  # everything expires; heat dies with slots
    assert _slot_counts(lim) == {}
    al, de = lim.table.insight_counts()
    assert (al, de) == (2, 8)  # totals are lifetime, not per-slot


# --------------------------------------------------------------------- #
# Space-saving sketch bounds.


def test_sketch_exact_below_capacity():
    s = SpaceSavingSketch(8)
    truth = {}
    for i, n in enumerate([5, 3, 8, 1]):
        for _ in range(n):
            s.record(f"k{i}")
        truth[f"k{i}"] = n
    assert dict(s.top(10)) == truth
    assert s.error_bound == 0
    assert all(e == 0 for _, _, e in s.top_with_error(10))


def test_sketch_error_bounds_hold_under_pressure():
    rng = np.random.default_rng(4)
    s = SpaceSavingSketch(16)
    truth: dict = {}
    # Zipf-ish stream over 10x the capacity.
    keys = rng.zipf(1.3, 5000) % 160
    for k in keys:
        s.record(int(k))
        truth[int(k)] = truth.get(int(k), 0) + 1
    for key, est, err in s.top_with_error(16):
        assert est >= truth.get(key, 0)          # never undercounts
        assert est - err <= truth.get(key, 0)    # documented bound
    # The heaviest true key survives compaction.
    heavy = max(truth, key=truth.get)
    assert heavy in dict(s.top(16))
    assert len(s) <= 16 * 3


def test_sketch_merge_partials_via_record_counts():
    s = SpaceSavingSketch(8)
    s.record("a", 10)
    s.record("b", 3)
    s.record("a", 5)
    assert dict(s.top(2)) == {"a": 15, "b": 3}


# --------------------------------------------------------------------- #
# Collector pieces.


def test_rate_window_rates_and_clock_regression():
    w = RateWindow(10.0)
    w.sample(T0, 0, 0)
    w.sample(T0 + 5 * NS, 50, 100)
    assert w.rates() == (10.0, 20.0)
    # Old samples roll out of the window.
    w.sample(T0 + 20 * NS, 50, 100)
    a, d = w.rates()
    assert a < 10.0
    # Regression restarts cleanly instead of emitting garbage.
    w.sample(T0, 60, 110)
    assert w.rates() == (0.0, 0.0)


def test_slot_key_resolver_python_and_items_backends():
    lim = TpuRateLimiter(capacity=64, keymap="python")
    lim.rate_limit_batch(["x", "y"], 2, 5, 60, 1, T0)
    r = SlotKeyResolver(lim.keymap)
    slot_x = lim.keymap._map["x"]
    assert r.keys_for([slot_x, 9999]) == ["x", None]

    class ItemsOnly:
        mutations = 0

        def items(self):
            return [(b"k", 3)]

    r2 = SlotKeyResolver(ItemsOnly())
    assert r2.keys_for([3, 4]) == [b"k", None]


# --------------------------------------------------------------------- #
# InsightTier: polling, /stats shape, feedback loop.


def _make_tier(front=None, **kw):
    lim = TpuRateLimiter(capacity=1 << 10, keymap="python", insight=True)
    defaults = dict(poll_ms=1000, window_s=10.0, decay_s=0.0)
    defaults.update(kw)
    return lim, InsightTier(limiter=lim, front=front, **defaults)


def test_poll_is_throttled_and_stats_truthful():
    lim, ins = _make_tier()
    ks = ["h"] * 50
    lim.rate_limit_batch(ks, 2, 5, 60, 1, T0, wire=True)
    assert ins.maybe_poll(T0)
    assert not ins.maybe_poll(T0 + ins.poll_ns - 1)  # throttled
    lim.rate_limit_batch(ks, 2, 5, 60, 1, T0 + NS, wire=True)
    assert ins.maybe_poll(T0 + 2 * NS)
    s = ins.stats(state="ok")
    assert s["totals"]["allowed"] + s["totals"]["denied"] == 100
    assert s["top_denied"][0]["key"] == "h"
    assert s["engine_state"] == "ok"
    assert json.loads(ins.stats_json(state="ok")) == s


def test_prewarm_refreshes_hot_keys_against_eviction():
    cache = DenyCache(capacity=4)
    front = FrontTier(cache, None)
    seq = cache.next_seq()
    # Certify a denial for the hot key.
    cache.observe("hot", 2, 5, 60, 1, T0, True, seq, cur_ns=T0 + 10 * NS)
    cache.observe("hot", 2, 5, 60, 1, T0, False, seq, cur_ns=T0 + 10 * NS)
    assert len(cache) == 1
    assert front.prewarm(["hot", "absent"]) == 1
    # Fill past capacity with other certified denials: without the
    # refresh "hot" (the oldest insert) would be evicted first.
    for i in range(4):
        k = f"cold{i}"
        cache.observe(k, 2, 5, 60, 1, T0, True, seq, cur_ns=T0 + 10 * NS)
        front.prewarm(["hot"])
        cache.observe(k, 2, 5, 60, 1, T0, False, seq, cur_ns=T0 + 10 * NS)
    assert cache.lookup("hot", 2, 5, 60, 1, T0 + NS) is not None


def test_hot_concentration_tightens_peek_shedding_only():
    adm = AdmissionController(max_pending=100, peek_frac=0.9)
    # Weight 0 (the kill-switch state): behavior is exactly the old one.
    adm.set_hot_concentration(1.0)
    assert adm.admit(89, peek=True)
    adm.hot_shed_weight = 0.5
    assert not adm.admit(89, peek=True)   # 0.9 * (1 - .5) = 0.45 bound
    assert adm.admit(99, peek=False)      # consume bound untouched
    assert not adm.admit(100, peek=False)


def test_topk_dropout_and_reentry_not_double_counted():
    # topk=1: a slot that leaves the top-K and later re-enters must
    # diff against its carried last-seen count, not restart from zero.
    lim = TpuRateLimiter(capacity=1 << 8, keymap="python", insight=True)
    ins = InsightTier(limiter=lim, poll_ms=1, topk=1)

    def deny(key, n, t):
        # burst 2 over 100 s: everything past the first 2 is denied.
        lim.rate_limit_batch([key] * n, 2, 1, 100, 1, T0 + t, wire=True)

    deny("a", 12, 0)              # a: 10 denied
    ins.poll(T0 + NS)             # top-1 = a(10)
    deny("b", 15, 2 * NS)         # b: 13 denied > a
    ins.poll(T0 + 3 * NS)         # top-1 = b(13); a drops out
    deny("a", 10, 4 * NS)         # a: 20 denied, re-enters top-1
    ins.poll(T0 + 5 * NS)
    counts = dict(ins.sketch.top(4))
    assert counts["a"] == 20      # not 30 (10 + full 20 re-record)
    assert counts["b"] == 13


def test_cache_served_denials_count_into_stats_totals():
    cache = DenyCache(capacity=64)
    front = FrontTier(cache, None)
    lim = TpuRateLimiter(capacity=1 << 8, keymap="python", insight=True)
    ins = InsightTier(limiter=lim, front=front, poll_ms=1000)
    assert front.insight is ins
    seq = cache.next_seq()
    cache.observe("hot", 2, 5, 60, 1, T0, True, seq, cur_ns=T0 + 10 * NS)
    cache.observe("hot", 2, 5, 60, 1, T0, False, seq, cur_ns=T0 + 10 * NS)
    # Scalar and bulk lookup paths both report their hits.
    assert front.lookup("hot", 2, 5, 60, 1, T0 + NS) is not None
    rows, n_hits = front.lookup_window(
        ["hot", "cold"], [2, 2], [5, 5], [60, 60], [1, 1], T0 + NS,
        mark_inflight=False,
    )
    assert n_hits == 1
    s = ins.stats()
    assert s["front_path"]["denied"] == 2
    assert s["totals"]["denied"] == 2
    assert dict((d["key"], d["count"]) for d in s["top_denied"]) == {
        "hot": 2
    }


def test_insight_feedback_sets_concentration_on_admission():
    front = FrontTier(DenyCache(64), AdmissionController(max_pending=100))
    lim = TpuRateLimiter(capacity=1 << 10, keymap="python", insight=True)
    ins = InsightTier(
        limiter=lim, front=front, poll_ms=1000, hot_denies=5,
        shed_weight=0.7, prewarm=8,
    )
    assert front.admission.hot_shed_weight == 0.7
    ks = ["hot0", "hot1"] * 32
    for t in range(4):
        lim.rate_limit_batch(ks, 2, 5, 60, 1, T0 + t * NS, wire=True)
        ins.maybe_poll(T0 + t * NS)
    assert front.admission.hot_concentration > 0.5
    assert ins.stats()["hot"]["concentration"] > 0.5


# --------------------------------------------------------------------- #
# Chaos: truthful accounting across a degrade→recover cycle.


def test_stats_truthful_across_degrade_recover_cycle():
    lim = TpuRateLimiter(capacity=1 << 10, keymap="python", insight=True)
    sup = SupervisedLimiter(
        lim, retries=1, backoff_us=0, probe_interval_ms=1,
        sleep_fn=lambda s: None,
    )
    ins = InsightTier(limiter=sup, poll_ms=1000)
    sup.insight = ins
    ks = ["c0", "c1"] * 16
    total = 0
    now = T0

    def decide(n_batches):
        nonlocal now, total
        for _ in range(n_batches):
            res = sup.rate_limit_batch(ks, 2, 5, 60, 1, now, wire=True)
            assert (res.status == 0).all()
            total += len(ks)
            now += NS
            ins.maybe_poll(now)

    decide(3)
    assert sup.state == STATE_OK
    # Device dies persistently: retries exhaust, host oracle takes over.
    faults.arm(faults.FaultInjector(
        faults.parse_spec("launch:persistent"), seed=1,
    ))
    decide(3)
    assert sup.state == STATE_DEGRADED
    # While degraded the host path keeps /stats truthful.
    s = ins.stats()
    assert s["totals"]["allowed"] + s["totals"]["denied"] == total
    assert s["host_path"]["allowed"] + s["host_path"]["denied"] > 0
    # Device heals; the next probe re-promotes.
    faults.disarm()
    decide(3)
    assert sup.state == STATE_OK
    s = ins.stats()
    # Nothing lost, nothing double-counted over the whole cycle.  The
    # one extra allowed row is the supervisor's successful recovery
    # probe — a real quantity-0 decision on the device, counted like
    # any other (the failed probes while faults were armed raised
    # before any device commit and count nowhere).
    assert s["totals"]["allowed"] + s["totals"]["denied"] == total + 1
    assert s["top_denied"][0]["key"] in ("c0", "c1")


def test_poll_survives_dead_device_mid_outage():
    lim, ins = _make_tier()
    lim.rate_limit_batch(["k"] * 8, 2, 5, 60, 1, T0, wire=True)
    ins.maybe_poll(T0)

    class Boom:
        def insight_counts(self):
            raise ConnectionError("UNAVAILABLE: device gone")

    real_table = ins.limiter.table
    ins.limiter.table = Boom()
    try:
        assert ins.maybe_poll(T0 + 2 * NS)  # no raise
        assert ins.poll_failures == 1
    finally:
        ins.limiter.table = real_table
    # Stats still answer from the last good data + host counters.
    assert ins.stats()["totals"]["allowed"] >= 1


# --------------------------------------------------------------------- #
# Server surfaces: /stats over HTTP, metrics export, config, factory.


def test_http_stats_route_shapes():
    import asyncio

    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.http import HttpTransport
    from throttlecrab_tpu.server.metrics import Metrics

    lim, ins = _make_tier()
    lim.rate_limit_batch(["s"] * 20, 2, 5, 60, 1, T0, wire=True)
    ins.maybe_poll(T0)

    async def run():
        engine = BatchingEngine(lim, insight=ins, now_fn=lambda: T0)
        t = HttpTransport("127.0.0.1", 0, engine, Metrics())
        status, payload, ctype = await t._route("GET", "/stats", b"")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(payload)
        assert doc["insight"]["enabled"] is True
        assert doc["engine_state"] == "ok"
        # Disabled tier still answers with a stable shape.
        engine2 = BatchingEngine(lim, now_fn=lambda: T0)
        t2 = HttpTransport("127.0.0.1", 0, engine2, Metrics())
        _, payload2, _ = await t2._route("GET", "/stats", b"")
        assert json.loads(payload2) == {"insight": {"enabled": False}}

    asyncio.run(run())


def test_metrics_export_insight_gauges_and_top_denied_compat():
    from throttlecrab_tpu.server.metrics import Metrics

    m = Metrics(max_denied_keys=10)
    m.record_request_with_key("http", False, "u:1")
    m.record_request_with_key("http", False, "u:1")
    text = m.export_prometheus()
    # Byte-compatible leaderboard export on the sketch backend.
    assert 'throttlecrab_top_denied_keys{key="u:1",rank="1"} 2' in text
    for name in (
        "throttlecrab_tpu_insight_allowed_rate",
        "throttlecrab_tpu_insight_denied_rate",
        "throttlecrab_tpu_insight_hot_concentration",
        "throttlecrab_tpu_insight_tracked_keys",
        "throttlecrab_tpu_insight_prewarmed_total",
        "throttlecrab_tpu_insight_polls",
    ):
        assert name in text, name
    lim, ins = _make_tier()
    m.set_insight_stats_provider(ins.metric_stats)
    assert "throttlecrab_tpu_insight_polls 0" in m.export_prometheus()


def test_config_knobs_and_factory_wiring():
    from throttlecrab_tpu.server.config import Config, ConfigError
    from throttlecrab_tpu.server.metrics import Metrics
    from throttlecrab_tpu.server.store import (
        create_front_tier,
        create_insight,
        create_limiter,
    )

    cfg = Config(http=True, store_capacity=1 << 10)
    cfg.validate()
    limiter = create_limiter(cfg)
    assert limiter.table.insight  # default on
    metrics = Metrics()
    front = create_front_tier(cfg, metrics, limiter)
    ins = create_insight(cfg, metrics, limiter, front)
    assert ins is not None and ins.limiter is limiter
    # Kill switch: no insight table, no tier, 4-wide rows.
    cfg_off = Config(http=True, store_capacity=1 << 10, insight=False)
    lim_off = create_limiter(cfg_off)
    assert not lim_off.table.insight
    assert lim_off.table.state.shape[-1] == 4
    assert create_insight(cfg_off, metrics, lim_off, front) is None
    # Validation.
    with pytest.raises(ConfigError):
        Config(http=True, insight_shed_weight=1.5).validate()
    with pytest.raises(ConfigError):
        Config(http=True, insight_topk=0).validate()


def test_flash_crowd_pattern_shifts_hot_set():
    ks = make_keys("flash-crowd", 2000, 10_000, seed=1)
    set_a, set_b = flash_crowd_hot_sets(10_000)
    first, second = ks[:1000], ks[1000:]
    assert sum(k in set_a for k in first) > 700
    assert sum(k in set_b for k in first) == 0  # disjoint by design
    assert sum(k in set_b for k in second) > 700
    assert sum(k in set_a for k in second) == 0
    assert set_a.isdisjoint(set_b)
