"""Adaptive control plane (ISSUE 16, throttlecrab_tpu/control/).

Contracts under test:

- **AIMD convergence under virtual time** — with the queue saturated
  at the admission bound (sustained overload), the bound converges
  into a band around target_wait/cost and stays there: multiplicative
  decrease pulls an overshoot back within one tick, additive increase
  reclaims headroom, and the forced shed equilibrium never runs away.
- **Hill-climb monotone improvement with hysteresis** — every accepted
  move raises the baseline by more than the hysteresis margin (the
  accepted-baseline sequence is strictly increasing), rejected probes
  are reverted *exactly*, and a flat objective accepts nothing.
- **Kill-switch bit-identity** — controller-off simulation outcomes
  are byte-identical to a plain scalar-oracle replay (no shed, no knob
  moved), and the default config builds no plane at all.
- **Actuator bounds / rate limits** — hard clamps at [lo, hi], per-tick
  max_step slew limiting, integer rounding, no-op writes unlogged, and
  the bounded actuation log.
- **`rank` reproducibility** — the K=8 candidate grid ranked twice is
  byte-identical (canonical JSON), in-process and through the CLI.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

from throttlecrab_tpu.control import (
    Actuator,
    ActuatorRegistry,
    AIMDController,
    ControlPlane,
    ControlReplayer,
    HillClimber,
    LOG_CAP,
    Objective,
    Policy,
    Telemetry,
    build_registry,
    default_candidates,
    jain_fairness,
    rank,
    rank_json,
    shed_fraction,
)
from throttlecrab_tpu.front.admission import AdmissionController
from throttlecrab_tpu.replay.generators import save, synthesize
from throttlecrab_tpu.replay.player import (
    make_target,
    outcome_vector,
    replay,
)
from throttlecrab_tpu.server.config import Config

NS = 1_000_000_000
T0 = 1_753_700_000 * NS


class _Box:
    """Bare attribute holder for actuator getter/setter closures."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


def _knob(box, attr, lo, hi, max_step, integer=False, name=None):
    return Actuator(
        name=name or attr, unit="x", lo=lo, hi=hi, max_step=max_step,
        get=lambda: getattr(box, attr),
        set=lambda v: setattr(box, attr, v),
        integer=integer,
    )


def _tel(i, wait_us=0.0, shed=0, served=0, hot=0.0, tenants=None):
    return Telemetry(
        now_ns=T0 + i * NS,
        est_wait_us=wait_us,
        shed_consume=shed,
        allowed_total=served,
        hot_concentration=hot,
        tenant_served=tenants or {},
    )


# --------------------------------------------------------------------
# actuator registry: bounds, rate limits, logging
# --------------------------------------------------------------------


def test_actuator_clamps_to_hard_bounds():
    box = _Box(v=50.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "v", lo=10.0, hi=100.0, max_step=1000.0))
    assert reg.apply("v", 5000.0, T0) == 100.0
    assert box.v == 100.0
    assert reg.apply("v", -3.0, T0) == 10.0
    assert box.v == 10.0
    assert reg.clamps == 2
    assert all(e["clamped"] for e in reg.log)


def test_actuator_rate_limits_per_tick_step():
    box = _Box(v=50.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "v", lo=0.0, hi=1000.0, max_step=10.0))
    # In-bounds target, but 450 away: one tick may only move 10.
    assert reg.apply("v", 500.0, T0) == 60.0
    assert reg.apply("v", 0.0, T0) == 50.0  # and back down, same limit
    assert reg.actuations == 2


def test_actuator_integer_rounds_and_sets_int():
    box = _Box(v=100)
    reg = ActuatorRegistry()
    reg.register(
        _knob(box, "v", lo=0, hi=1000, max_step=500, integer=True)
    )
    applied = reg.apply("v", 123.7, T0)
    assert applied == 124.0
    assert box.v == 124 and isinstance(box.v, int)


def test_actuator_noop_write_is_not_logged():
    box = _Box(v=7.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "v", lo=0.0, hi=10.0, max_step=5.0))
    assert reg.apply("v", 7.0, T0) == 7.0
    assert reg.actuations == 0 and len(reg.log) == 0


def test_actuation_log_is_bounded():
    box = _Box(v=0.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "v", lo=0.0, hi=1e9, max_step=1.0))
    for i in range(LOG_CAP + 50):
        reg.apply("v", box.v + 1.0, T0 + i)
    assert len(reg.log) == LOG_CAP
    assert reg.actuations == LOG_CAP + 50


def test_registry_rejects_bad_declarations():
    reg = ActuatorRegistry()
    box = _Box(v=0.0)
    with pytest.raises(ValueError):
        reg.register(_knob(box, "v", lo=10.0, hi=5.0, max_step=1.0))
    with pytest.raises(ValueError):
        reg.register(_knob(box, "v", lo=0.0, hi=5.0, max_step=0.0))


def test_build_registry_anchors_bounds_to_configured_point():
    adm = AdmissionController(max_pending=10_000, max_wait_us=50_000)
    reg = build_registry(admission=adm)
    lo, hi = reg.bounds("admission.max_pending")
    assert lo == max(10_000 // 64, 64) and hi == 10_000
    lo, hi = reg.bounds("admission.max_wait_us")
    assert lo == max(50_000 // 64, 100) and hi == 50_000
    # The controller may tighten below config but never relax past it.
    assert reg.apply("admission.max_pending", 10**9, T0) == 10_000


# --------------------------------------------------------------------
# AIMD: convergence under virtual time
# --------------------------------------------------------------------


def test_aimd_converges_to_target_band_under_overload():
    """Closed loop under sustained overload: the queue saturates at the
    bound (wait_us == bound at SIM cost 1 µs/row), arrivals always
    exceed capacity (shed every tick).  The bound must fall from 100 k
    into a band around the 5 ms target and stay there."""
    box = _Box(bound=100_000)
    reg = ActuatorRegistry()
    reg.register(_knob(
        box, "bound", lo=64, hi=100_000, max_step=100_000,
        integer=True, name="admission.max_pending",
    ))
    aimd = AIMDController(target_wait_us=5000.0)
    prev = None
    history = []
    for i in range(60):
        cur = _tel(i, wait_us=float(box.bound), shed=i + 1, served=i)
        aimd.tick(prev, cur, reg, T0 + i * NS)
        prev = cur
        history.append(box.bound)
    target, step, factor = 5000.0, 256, 0.7
    tail = history[30:]
    # Band: one additive step above target, one multiplicative cut
    # below the highest healthy point.
    lo_band = (target + step) * factor - step
    hi_band = target + step
    assert all(lo_band <= b <= hi_band for b in tail), tail
    # And it is live regulation, not a frozen knob.
    assert len(set(tail)) > 1
    assert reg.actuations > 0


def test_aimd_additive_increase_only_when_shedding():
    """Healthy and not shedding: the bound is not binding, so AIMD must
    leave it alone (no pointless drift toward the ceiling)."""
    box = _Box(bound=1000)
    reg = ActuatorRegistry()
    reg.register(_knob(
        box, "bound", lo=64, hi=100_000, max_step=100_000,
        integer=True, name="admission.max_pending",
    ))
    aimd = AIMDController(target_wait_us=5000.0)
    prev = _tel(0, wait_us=100.0, shed=0, served=10)
    cur = _tel(1, wait_us=100.0, shed=0, served=20)
    aimd.tick(prev, cur, reg, T0)
    assert box.bound == 1000
    # Same telemetry but with fresh shed: bound relaxes additively.
    cur2 = _tel(2, wait_us=100.0, shed=5, served=30)
    aimd.tick(cur, cur2, reg, T0 + NS)
    assert box.bound == 1256


def test_aimd_hot_weight_rises_under_hot_congestion_then_decays():
    box = _Box(w=0.0)
    reg = ActuatorRegistry()
    reg.register(_knob(
        box, "w", lo=0.0, hi=1.0, max_step=0.1,
        name="admission.hot_shed_weight",
    ))
    aimd = AIMDController(target_wait_us=5000.0)
    congested_hot = _tel(1, wait_us=50_000.0, hot=0.9)
    aimd.tick(None, congested_hot, reg, T0)
    assert box.w == pytest.approx(0.05)
    aimd.tick(congested_hot, _tel(2, wait_us=50_000.0, hot=0.9), reg, T0)
    assert box.w == pytest.approx(0.10)
    # Pressure gone: multiplicative decay back toward zero.
    aimd.tick(None, _tel(3, wait_us=100.0), reg, T0)
    assert box.w == pytest.approx(0.07)


# --------------------------------------------------------------------
# hill climber: monotone improvement, hysteresis, exact revert
# --------------------------------------------------------------------


def _hill_loop(hill, reg, box, score_of, ticks):
    """Drive the climber with the score measured at the CURRENT knob
    value each virtual tick; returns the accepted-baseline history."""
    baselines = []
    last = None
    for i in range(ticks):
        hill.tick(score_of(box.x), reg, T0 + i * NS)
        b = hill.stats()["baseline"]
        if b is not None and b != last:
            baselines.append(b)
            last = b
    return baselines


def test_hill_climbs_to_optimum_with_monotone_baselines():
    box = _Box(x=2.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "x", lo=0.0, hi=10.0, max_step=10.0))
    hill = HillClimber(["x"], step_frac=0.125, eval_ticks=2,
                       hysteresis=0.01)
    score_of = lambda x: -((x - 7.0) ** 2)  # optimum at x = 7
    baselines = _hill_loop(hill, reg, box, score_of, 60)
    # Strictly increasing accepted baselines: every kept move improved
    # the objective (the monotone-improvement contract).
    assert all(b > a for a, b in zip(baselines, baselines[1:]))
    assert hill.moves_accepted >= 3
    # Converged next to the optimum (within one probe step of 1.25).
    assert abs(box.x - 7.0) <= 1.25 + 1e-9
    assert hill.moves_reverted > 0  # overshoot probes were rejected


def test_hill_hysteresis_blocks_noise_and_reverts_exactly():
    """Flat objective: no probe can beat baseline + hysteresis, so
    nothing is ever accepted and every probe is reverted to the exact
    starting value."""
    box = _Box(x=4.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "x", lo=0.0, hi=10.0, max_step=10.0))
    hill = HillClimber(["x"], eval_ticks=2, hysteresis=0.01)
    for i in range(40):
        hill.tick(1.0, reg, T0 + i * NS)
    assert hill.moves_accepted == 0
    assert hill.moves_reverted > 0
    # Exact revert: after any settled (non-probing) tick the knob is
    # back at its original value.
    hill.tick(1.0, reg, T0 + 100 * NS)
    settled = {4.0, 4.0 + 1.25, 4.0 - 1.25}
    assert box.x in settled  # mid-probe at worst, never drifted


def test_hill_skips_pinned_coordinate_without_burning_a_window():
    box = _Box(x=10.0, y=5.0)
    reg = ActuatorRegistry()
    reg.register(_knob(box, "x", lo=10.0, hi=10.0,
                       max_step=1.0))  # lo == hi: every probe a no-op
    reg.register(_knob(box, "y", lo=0.0, hi=10.0, max_step=10.0))
    hill = HillClimber(["x", "y"], eval_ticks=1, hysteresis=1e9)
    for i in range(12):
        hill.tick(0.0, reg, T0 + i * NS)
    # The pinned coordinate never produced an actuation; the live one
    # did (probes), all reverted under the impossible hysteresis.
    assert all(e["actuator"] == "y" for e in reg.log)


# --------------------------------------------------------------------
# objective
# --------------------------------------------------------------------


def test_objective_scores_throughput_wait_fairness():
    obj = Objective()
    base = _tel(0, served=0)
    fast = _tel(1, wait_us=0.0, served=1000)
    slow = _tel(1, wait_us=50_000.0, served=1000)
    assert obj.score(base, fast) > obj.score(base, slow)
    unfair = _tel(1, served=1000,
                  tenants={"a": 990, "b": 5, "c": 5})
    fair = _tel(1, served=1000,
                tenants={"a": 334, "b": 333, "c": 333})
    assert obj.score(base, fair) > obj.score(base, unfair)


def test_jain_fairness_bounds():
    assert jain_fairness({}) == 1.0
    assert jain_fairness({"a": 10}) == 1.0
    assert jain_fairness({"a": 5, "b": 5}) == pytest.approx(1.0)
    skew = jain_fairness({"a": 1000, "b": 1})
    assert 0.5 <= skew < 0.51


def test_shed_fraction_differences_consecutive_records():
    prev = _tel(0, shed=10, served=90)
    cur = _tel(1, shed=30, served=150)
    # This tick: 20 shed, 60 served -> 20/80.
    assert shed_fraction(prev, cur) == pytest.approx(0.25)
    assert shed_fraction(None, cur) == pytest.approx(30 / 180)


# --------------------------------------------------------------------
# control plane: cadence, lock plumbing, stats
# --------------------------------------------------------------------


class _StubBus:
    def snapshot(self, now_ns, queue_depth=0):
        return _tel(0)


class _RecordingLock:
    def __init__(self):
        self.entries = 0
        self._lock = threading.Lock()

    def __enter__(self):
        self.entries += 1
        return self._lock.__enter__()

    def __exit__(self, *exc):
        return self._lock.__exit__(*exc)


def _plane(mode="both", tick_ms=1000):
    box = _Box(bound=1000)
    reg = ActuatorRegistry()
    reg.register(_knob(
        box, "bound", lo=64, hi=100_000, max_step=100_000,
        integer=True, name="admission.max_pending",
    ))
    return ControlPlane(_StubBus(), reg, mode=mode, tick_ms=tick_ms)


def test_plane_tick_cadence_is_throttled():
    plane = _plane(tick_ms=1000)
    assert plane.maybe_tick(T0) is True
    assert plane.maybe_tick(T0 + NS // 2) is False
    assert plane.maybe_tick(T0 + NS) is True
    assert plane.ticks == 2


def test_plane_tick_lock_overrides_caller_lock():
    plane = _plane()
    caller, cluster = _RecordingLock(), _RecordingLock()
    plane.maybe_tick(T0, caller)
    assert caller.entries == 1
    plane.tick_lock = cluster  # cluster mode: device_lock wins
    plane.maybe_tick(T0 + 2 * NS, caller)
    assert caller.entries == 1 and cluster.entries == 1


def test_plane_stats_document_shape():
    plane = _plane(mode="both")
    plane.maybe_tick(T0)
    doc = json.loads(plane.stats_json())
    assert doc["control"]["enabled"] is True
    assert doc["control"]["mode"] == "both"
    assert doc["control"]["ticks"] == 1
    assert set(doc["objective"]["weights"]) == {
        "throughput", "wait", "fairness"
    }
    assert "admission.max_pending" in doc["actuators"]
    assert "hill" in doc
    assert set(plane.metric_stats()) == {
        "ticks", "actuations", "clamped", "objective", "shed_rate"
    }


def test_plane_rejects_unknown_mode():
    with pytest.raises(ValueError):
        _plane(mode="banana")


# --------------------------------------------------------------------
# kill switch: bit-identity + nothing built
# --------------------------------------------------------------------


def _small_trace(windows=24, batch=128, seed=23):
    return synthesize("flash-crowd", windows=windows, batch=batch,
                      key_space=2048, seed=seed)


def test_default_config_builds_no_control_plane():
    from throttlecrab_tpu.control import create_control_plane

    cfg = Config.from_env_and_args(["--http"])
    assert cfg.control is False
    assert create_control_plane(cfg) is None


def test_controller_off_is_bit_identical_to_plain_replay():
    trace = _small_trace()
    off = ControlReplayer(trace, Policy(name="static", mode="off")).run()
    plain = outcome_vector(replay(trace, make_target("oracle", trace)))
    assert off.vector() == plain
    assert off.shed == 0
    assert off.actuations == 0 and off.actuation_log == []
    # The default knobs never moved.
    assert off.final_max_pending == 100_000


def test_armed_controller_tightens_bound_and_caps_wait():
    trace = _small_trace(windows=32, batch=1024)
    # Harsh overload (4x) so even the small trace pressures the loop.
    rate = 0.25 * trace.n_rows() / ControlReplayer._duration_s(trace)
    off = ControlReplayer(
        trace, Policy(name="static", mode="off"), service_rate=rate
    ).run()
    on = ControlReplayer(
        trace, Policy(name="aimd", mode="aimd"), service_rate=rate
    ).run()
    assert on.actuations > 0
    assert on.shed > 0
    assert on.final_max_pending < 100_000
    assert on.max_wait_us_seen < off.max_wait_us_seen


def test_config_validates_control_knobs():
    with pytest.raises(ValueError):
        Config.from_env_and_args(["--http", "--control-mode", "banana"])
    with pytest.raises(ValueError):
        Config.from_env_and_args(["--http", "--control-tick-ms", "0"])
    with pytest.raises(ValueError):
        Config.from_env_and_args(["--http", "--control-w-wait", "-1"])


# --------------------------------------------------------------------
# offline policy search: rank reproducibility
# --------------------------------------------------------------------


def test_rank_is_reproducible_and_complete():
    trace = _small_trace()
    cands = default_candidates(8)
    assert len(cands) == 8
    assert len({p.name for p in cands}) == 8
    r1 = rank(trace, cands)
    r2 = rank(trace, cands)
    assert rank_json(r1) == rank_json(r2)
    assert [row["rank"] for row in r1] == list(range(1, 9))
    names = {row["policy"]["name"] for row in r1}
    assert "static" in names
    scores = [row["score"] for row in r1]
    assert scores == sorted(scores, reverse=True)


def test_default_candidates_extend_past_fixed_head():
    cands = default_candidates(11)
    assert len(cands) == 11
    assert len({p.name for p in cands}) == 11


def test_rank_cli_emits_canonical_json(tmp_path):
    trace = _small_trace(windows=12, batch=64)
    path = os.path.join(tmp_path, "t.tctr")
    save(trace, path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    outs = []
    for _ in range(2):
        p = subprocess.run(
            [sys.executable, "-m", "throttlecrab_tpu.control", "rank",
             path, "-k", "8", "--json"],
            capture_output=True, env=env, timeout=240,
        )
        assert p.returncode == 0, p.stderr.decode()
        outs.append(p.stdout)
    assert outs[0] == outs[1]
    ranking = json.loads(outs[0])
    assert len(ranking) == 8 and ranking[0]["rank"] == 1
