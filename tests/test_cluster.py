"""Cross-process cluster sharding: limits must hold across process
boundaries (SURVEY §2.4's DCN obligation; the reference's answer was
client-side sharding, README.md:247-249 — here the server does it).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from throttlecrab_tpu.parallel.cluster import (
    ClusterLimiter,
    decode_batch,
    decode_reply,
    encode_batch,
    encode_reply,
    node_of_key,
)
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


# ------------------------------------------------------------- protocol #


def test_frame_roundtrip():
    keys = [b"alpha", b"b" * 300, b"", "ünïcode".encode()]
    params = [(10, 100, 60, 1), (5, 50, 30, 2), (1, 1, 1, 0),
              (2 ** 40, 2 ** 41, 2 ** 42, 2 ** 43)]
    frame = encode_batch(keys, params, T0)
    # strip header
    body = frame[5:]
    dkeys, dparams, dnow = decode_batch(body)
    assert dkeys == keys
    assert dparams.tolist() == [list(p) for p in params]
    assert dnow == T0


def test_reply_roundtrip():
    frame = encode_reply(
        np.array([0, 2, 0], np.uint8),
        np.array([True, False, False]),
        np.array([10, 0, 5], np.int64),
        np.array([9, 0, 0], np.int64),
        np.array([6 * NS, 0, 2 ** 62], np.int64),
        np.array([0, 0, 3 * NS], np.int64),
    )
    rep = decode_reply(frame[5:])
    assert rep["status"].tolist() == [0, 2, 0]
    assert rep["allowed"].tolist() == [1, 0, 0]
    assert rep["reset_ns"][2] == 2 ** 62


def test_malformed_frames_rejected():
    from throttlecrab_tpu.parallel.cluster import (
        ClusterProtocolError,
        _HDR,
        _REP_HEAD,
        _REQ_HEAD,
    )
    import struct

    # Attacker-controlled count must not size an allocation: n=2^32-1 in a
    # tiny frame.
    with pytest.raises(ClusterProtocolError):
        decode_batch(_REQ_HEAD.pack(0xFFFFFFFF, T0))
    with pytest.raises(ClusterProtocolError):
        decode_reply(_REP_HEAD.pack(0xFFFFFFFF))
    # Truncated reply body.
    with pytest.raises(ClusterProtocolError):
        decode_reply(_REP_HEAD.pack(2) + b"\x00" * 10)
    # Item overrunning the frame.
    bad = _REQ_HEAD.pack(1, T0) + struct.pack("<H", 500) + b"k"
    with pytest.raises(ClusterProtocolError):
        decode_batch(bad)
    assert _HDR.size == 5


def test_migrate_replica_frames_hardened():
    """OP_MIGRATE/OP_REPLICA frames carry the same malformed-frame
    contract as OP_THROTTLE_BATCH: attacker-controlled counts cannot
    size allocations, truncation raises the typed error, trailing
    garbage is rejected."""
    import struct

    from throttlecrab_tpu.parallel.cluster import (
        OP_MIGRATE,
        ClusterProtocolError,
        _ROWS_HEAD,
        decode_ring,
        decode_route,
        decode_rows,
        encode_ring,
        encode_rows,
    )

    # Round trip.
    f = encode_rows(OP_MIGRATE, 1, 9, [b"k1", b""], [10, -5], [20, 1 << 61])
    origin, epoch, keys, tats, exps = decode_rows(f[5:])
    assert (origin, epoch, keys) == (1, 9, [b"k1", b""])
    assert tats.tolist() == [10, -5] and exps.tolist() == [20, 1 << 61]
    # Oversized count in a tiny frame.
    with pytest.raises(ClusterProtocolError):
        decode_rows(_ROWS_HEAD.pack(0, 0, 0xFFFFFFFF))
    # Truncated item.
    bad = _ROWS_HEAD.pack(0, 0, 1) + struct.pack("<H", 500) + b"k"
    with pytest.raises(ClusterProtocolError):
        decode_rows(bad)
    # Trailing garbage after a valid frame.
    with pytest.raises(ClusterProtocolError):
        decode_rows(f[5:] + b"\x00")
    # Short/mismatched ring frames.
    with pytest.raises(ClusterProtocolError):
        decode_ring(b"\x01")
    with pytest.raises(ClusterProtocolError):
        decode_ring(encode_ring(5, 3, [1.0, 1.0])[5:] + b"\x00\x00")
    # Route frame: too short for even the hop byte.
    with pytest.raises(ClusterProtocolError):
        decode_route(b"")


def test_ring_vectorized_matches_oracle_and_excludes():
    from throttlecrab_tpu.parallel.ring import HashRing, batch_crc32

    nodes = [f"10.0.0.{i}:9000" for i in range(5)]
    ring = HashRing(nodes, 128)
    keys = [b"rk:%d" % i for i in range(3000)]
    owners = ring.owners_of(batch_crc32(keys))
    # Vectorized lookup is bit-identical to the per-key oracle.
    for i in (0, 1, 7, 100, 999, 2999):
        assert ring.owner_of(keys[i]) == owners[i]
    # Roughly balanced (5 nodes x 128 vnodes).
    counts = np.bincount(owners, minlength=5)
    assert counts.min() > 300, counts
    # Excluding a node moves ONLY its keys, each to its successor.
    o2 = ring.owners_of(batch_crc32(keys), exclude=frozenset({2}))
    moved = owners != o2
    assert (owners[moved] == 2).all() and (o2 != 2).all()
    # successor_of agrees with exclusion routing.
    for i in np.flatnonzero(moved)[:50]:
        assert ring.successor_of(keys[int(i)], 2) == o2[int(i)]
    # Weights scale ownership monotonically; weight 0 owns nothing.
    half = HashRing(nodes, 128, weights={0: 0.5}).owners_of(
        batch_crc32(keys)
    )
    zero = HashRing(nodes, 128, weights={0: 0.0}).owners_of(
        batch_crc32(keys)
    )
    full0 = int((owners == 0).sum())
    assert int((half == 0).sum()) < full0
    assert int((zero == 0).sum()) == 0
    # A membership change moves ~1/N of the space, not ~all of it (the
    # modulo failure mode the ring exists to fix).
    o4 = HashRing(nodes[:4], 128).owners_of(batch_crc32(keys))
    stayed = o4 == owners
    assert stayed.mean() > 0.70, stayed.mean()


def test_oversized_key_fails_only_itself():
    local = TpuRateLimiter(capacity=64)
    cl = ClusterLimiter(local, ["127.0.0.1:1"], 0)
    keys = ["ok1", "x" * 70_000, "ok2"]
    res = cl.rate_limit_batch(keys, 5, 100, 60, 1, T0)
    assert res.allowed.tolist() == [True, False, True]
    assert res.status[1] != 0 and res.status[0] == 0 and res.status[2] == 0


def test_node_routing_stable_and_decorrelated():
    keys = [b"user:%d" % i for i in range(2000)]
    owners = [node_of_key(k, 4) for k in keys]
    # Deterministic.
    assert owners == [node_of_key(k, 4) for k in keys]
    # Roughly balanced.
    counts = np.bincount(owners, minlength=4)
    assert counts.min() > 300
    # Decorrelated from the intra-node device-shard hash: keys owned by
    # node 0 of 2 must still spread over 2 local shards.
    from throttlecrab_tpu.parallel.sharded import shard_of_key

    node0 = [k for k in keys if node_of_key(k, 2) == 0]
    local = np.bincount([shard_of_key(k, 2) for k in node0], minlength=2)
    assert local.min() > len(node0) // 4


# -------------------------------------------------- single-node passthru #


def test_single_node_cluster_is_passthrough():
    plain = TpuRateLimiter(capacity=256)
    local = TpuRateLimiter(capacity=256)
    cl = ClusterLimiter(local, ["127.0.0.1:1"], 0)  # only node: no RPC
    keys = [f"k{i % 20}" for i in range(64)]
    a = plain.rate_limit_batch(keys, 5, 100, 60, 1, T0)
    b = cl.rate_limit_batch(keys, 5, 100, 60, 1, T0)
    assert a.allowed.tolist() == b.allowed.tolist()
    assert a.remaining.tolist() == b.remaining.tolist()
    assert a.reset_after_ns.tolist() == b.reset_after_ns.tolist()
    # wire path too
    w = cl.rate_limit_batch(keys, 5, 100, 60, 1, T0 + NS, wire=True)
    assert w.reset_after_s.dtype == np.int64


# ------------------------------------------------------- two processes #

HTTP_A, HTTP_B = 28180, 28181
RPC_A, RPC_B = 28190, 28191
NODES = f"127.0.0.1:{RPC_A},127.0.0.1:{RPC_B}"


def spawn_node(index: int, http_port: int):
    env = dict(os.environ)
    env["THROTTLECRAB_PLATFORM"] = "cpu"
    # First-touch jit compiles on the CPU backend take 10-40 s; the
    # serving-grade 250 ms forward deadline would expire mid-compile.
    env["THROTTLECRAB_CLUSTER_TIMEOUT_MS"] = "60000"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "throttlecrab_tpu.server",
            "--http", "--http-port", str(http_port),
            "--cluster-nodes", NODES, "--cluster-index", str(index),
            "--store", "adaptive", "--log-level", "warn",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def wait_healthy(proc, port, deadline_s=120):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            pytest.fail(f"node exited early rc={proc.returncode}:\n{out}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=1
            ) as r:
                assert r.read() == b"OK"
                return
        except Exception:
            time.sleep(0.5)
    pytest.fail("node never became healthy")


def throttle_via(port, key, burst=3):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/throttle",
        data=json.dumps(
            {"key": key, "max_burst": burst, "count_per_period": 10,
             "period": 60}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def two_nodes():
    a = spawn_node(0, HTTP_A)
    b = spawn_node(1, HTTP_B)
    try:
        wait_healthy(a, HTTP_A)
        wait_healthy(b, HTTP_B)
        # Warm every decide path (first-touch jit compiles take 10-40 s
        # on this host): local decides on each node AND the cross-node
        # forward in both directions.  Without this, a starved host can
        # push the first forwarded decide past the 60 s deadline and
        # ring failover masks it as a fresh local decision — an
        # over-allow the real assertions below would misattribute.
        warm_a = key_owned_by(0, "warm0")
        warm_b = key_owned_by(1, "warm1")
        for port in (HTTP_A, HTTP_B):
            for k in (warm_a, warm_b):
                throttle_via(port, k, burst=100)
        yield a, b
    finally:
        for p in (a, b):
            if p.poll() is None:
                p.terminate()
        for p in (a, b):
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


#: Servers spawned with the default config route on the consistent-hash
#: ring (THROTTLECRAB_CLUSTER_VNODES=128), so ownership probes must use
#: the same ring the servers build from the same node list.
def _default_ring(n_nodes: int = 2):
    from throttlecrab_tpu.parallel.ring import HashRing

    return HashRing(NODES.split(",")[:n_nodes], 128)


def key_owned_by(node_idx: int, prefix: str) -> str:
    ring = _default_ring()
    for i in range(10_000):
        k = f"{prefix}:{i}"
        if ring.owner_of(k.encode()) == node_idx:
            return k
    raise AssertionError("no key found")


def test_limits_hold_across_processes(two_nodes):
    """Burst 3 on one key, driven through BOTH nodes' HTTP frontends:
    exactly 3 allowed in total — the owner decides no matter which node
    the client hit."""
    key = key_owned_by(1, "xproc")  # owned by node B
    results = [
        throttle_via(HTTP_A, key)["allowed"],  # A forwards to B
        throttle_via(HTTP_A, key)["allowed"],
        throttle_via(HTTP_B, key)["allowed"],  # B decides locally
        throttle_via(HTTP_A, key)["allowed"],
        throttle_via(HTTP_B, key)["allowed"],
    ]
    assert results == [True, True, True, False, False]


def test_both_directions_route(two_nodes):
    """A key owned by node A driven via node B (reverse forwarding)."""
    key = key_owned_by(0, "revproc")
    results = [throttle_via(HTTP_B, key, burst=2)["allowed"]
               for _ in range(3)]
    assert results == [True, True, False]


def test_remaining_consistent_across_frontends(two_nodes):
    key = key_owned_by(1, "remproc")
    r1 = throttle_via(HTTP_A, key, burst=5)
    r2 = throttle_via(HTTP_B, key, burst=5)
    r3 = throttle_via(HTTP_A, key, burst=5)
    assert (r1["remaining"], r2["remaining"], r3["remaining"]) == (4, 3, 2)


def test_bidirectional_concurrent_traffic_no_deadlock(two_nodes):
    """Both frontends forwarding to each other simultaneously must not
    deadlock: each node's reply production (its ClusterServer) only needs
    the device lock, never the engine lock held across outbound RPCs.
    Regression for the cross-node lock cycle."""
    import concurrent.futures

    key_a = key_owned_by(0, "bidiA")  # A-owned, driven via B
    key_b = key_owned_by(1, "bidiB")  # B-owned, driven via A

    t0 = time.time()
    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        futs = []
        for i in range(12):
            futs.append(
                pool.submit(throttle_via, HTTP_A, f"{key_b}:{i}", 100)
            )
            futs.append(
                pool.submit(throttle_via, HTTP_B, f"{key_a}:{i}", 100)
            )
        results = [f.result(timeout=60) for f in futs]
    elapsed = time.time() - t0
    assert all(r["allowed"] for r in results)
    # Well under the 30s RPC timeout a deadlock would burn per round.
    assert elapsed < 20, f"bidirectional traffic took {elapsed:.1f}s"


def test_peer_failure_successor_takes_over(two_nodes):
    """Killing node B no longer costs its key range: the ring routes
    B-owned keys to their successor (A, in a 2-node ring), which
    absorbs the warm replica and keeps deciding — zero client-visible
    failures, the elastic upgrade over the legacy modulo tier's
    STATUS_INTERNAL (that behavior is pinned separately in-process with
    vnodes=0)."""
    a, b = two_nodes
    key_b = key_owned_by(1, "failproc")
    key_a = key_owned_by(0, "okproc")
    # SIGKILL: this test pins *unplanned* death (SIGTERM now runs the
    # graceful drain + planned leave, which hands off without a
    # takeover — that path is pinned in test_cluster_chaos.py).
    b.kill()
    b.wait(timeout=30)
    # B-owned key via A: decided by A as B's ring successor (no 500).
    results = [throttle_via(HTTP_A, key_b)["allowed"] for _ in range(5)]
    assert results == [True, True, True, False, False]
    # A-owned key unaffected.
    assert throttle_via(HTTP_A, key_a)["allowed"] is True
    # The takeover is observable on the cluster view.
    with urllib.request.urlopen(
        f"http://127.0.0.1:{HTTP_A}/health/cluster", timeout=10
    ) as r:
        view = json.loads(r.read())
    assert view["mode"] == "ring"
    assert view["takeovers"] >= 1
    assert f"127.0.0.1:{RPC_B}" in view["absorbed"]


def test_legacy_modulo_dead_peer_fails_only_its_range():
    """vnodes=0 (the kill switch) keeps the pre-ring contract: a dead
    peer's keys fail with STATUS_INTERNAL, everything else decides."""
    from throttlecrab_tpu.tpu.limiter import STATUS_INTERNAL

    local = TpuRateLimiter(capacity=256)
    cl = ClusterLimiter(
        local, ["127.0.0.1:1", "127.0.0.1:2"], 1,
        io_timeout_s=0.2, connect_timeout_s=0.2,
    )
    assert cl.ring is None and cl._pump is None
    key_remote = next(
        f"lm:{i}" for i in range(10_000)
        if node_of_key(f"lm:{i}".encode(), 2) == 0
    )
    key_local = next(
        f"ll:{i}" for i in range(10_000)
        if node_of_key(f"ll:{i}".encode(), 2) == 1
    )
    res = cl.rate_limit_batch([key_remote, key_local], 5, 100, 60, 1, T0)
    assert res.allowed.tolist() == [False, True]
    assert res.status[0] == STATUS_INTERNAL and res.status[1] == 0


def test_unencodable_key_fails_only_itself():
    """A lone surrogate outside U+DC80-DCFF (JSON can deliver one) cannot
    cross the wire; it must fail individually, not 500 its batchmates."""
    local = TpuRateLimiter(capacity=64)
    cl = ClusterLimiter(local, ["127.0.0.1:1"], 0)
    keys = ["good1", "\ud800bad", "good2"]
    res = cl.rate_limit_batch(keys, 5, 100, 60, 1, T0)
    assert res.allowed.tolist() == [True, False, True]
    assert res.status[1] != 0 and res.status[0] == 0 and res.status[2] == 0


# ------------------------------------------------ failure containment #


def _silent_listener():
    """A TCP listener that accepts and then never replies (a hung peer —
    worse than a dead one, because connect succeeds)."""
    import socket as _socket
    import threading as _threading

    srv = _socket.socket()
    srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    conns = []
    stop = _threading.Event()

    def loop():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                conns.append(c)
            except OSError:
                continue

    t = _threading.Thread(target=loop, daemon=True)
    t.start()

    def close():
        stop.set()
        t.join(timeout=2)
        for c in conns:
            c.close()
        srv.close()

    return srv.getsockname()[1], close


def test_silent_peer_fails_within_deadline_local_keys_unaffected():
    """An accepted-but-silent peer must cost at most the configured
    forward deadline, fail ONLY its own keys, and leave local keys
    deciding at full speed (round-3 weakness #6: the old 30 s IO timeout
    stalled every batch)."""
    port, close = _silent_listener()
    try:
        local = TpuRateLimiter(capacity=256)
        cl = ClusterLimiter(
            local, [f"127.0.0.1:{port}", "127.0.0.1:1"], 1,
            io_timeout_s=0.3, breaker_failures=99,  # breaker off: pure deadline
        )
        key_remote = next(
            f"sp:{i}" for i in range(10_000)
            if node_of_key(f"sp:{i}".encode(), 2) == 0
        )
        key_local = next(
            f"sl:{i}" for i in range(10_000)
            if node_of_key(f"sl:{i}".encode(), 2) == 1
        )
        # Warm the local compile outside the timed window.
        cl.rate_limit_batch([key_local], 5, 100, 60, 1, T0)

        t0 = time.monotonic()
        res = cl.rate_limit_batch(
            [key_remote, key_local], 5, 100, 60, 1, T0 + NS
        )
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0, f"hung peer stalled the batch {elapsed:.1f}s"
        assert res.allowed.tolist() == [False, True]
        assert res.status[0] != 0 and res.status[1] == 0
    finally:
        close()


def test_circuit_breaker_opens_and_recovers():
    """After N consecutive failures the breaker opens (fail-fast, no
    network touch); after the cooldown one probe goes through again."""
    from throttlecrab_tpu.parallel.cluster import PeerConnection, PeerUnavailable

    fake_now = [0.0]
    peer = PeerConnection(
        "127.0.0.1", 1, io_timeout_s=0.1, connect_timeout_s=0.1,
        breaker_failures=3, breaker_cooldown_s=5.0,
        clock=lambda: fake_now[0],
    )
    # Three real failures arm the breaker (connection refused each time).
    for i in range(3):
        fake_now[0] += 10.0  # clear any backoff between attempts
        with pytest.raises(OSError):
            peer.send_frame(b"x")
        peer.record_failure()
    # Inside the cooldown: fail-fast without touching the network.
    with pytest.raises(PeerUnavailable):
        peer.send_frame(b"x")
    # After the cooldown a probe attempt is allowed through again (and
    # hits the real refused connection, not the gate).
    fake_now[0] += 5.1
    with pytest.raises(OSError) as exc:
        peer.send_frame(b"x")
    assert not isinstance(exc.value, PeerUnavailable)


def test_reconnect_backoff_gates_attempts():
    from throttlecrab_tpu.parallel.cluster import PeerConnection, PeerUnavailable

    fake_now = [100.0]
    peer = PeerConnection(
        "127.0.0.1", 1, connect_timeout_s=0.1,
        breaker_failures=99, clock=lambda: fake_now[0],
    )
    with pytest.raises(OSError):
        peer.send_frame(b"x")
    peer.record_failure()
    # Immediately after the failure: gated, no network touch.
    with pytest.raises(PeerUnavailable):
        peer.send_frame(b"x")
    # Past the first backoff window (50 ms): real attempt again.
    fake_now[0] += 0.06
    with pytest.raises(OSError) as exc:
        peer.send_frame(b"x")
    assert not isinstance(exc.value, PeerUnavailable)


def test_cluster_batch_failfast_when_breaker_open():
    """A whole batch with a breaker-open peer resolves instantly: remote
    keys STATUS_INTERNAL, local keys decided."""
    local = TpuRateLimiter(capacity=256)
    cl = ClusterLimiter(
        local, ["127.0.0.1:1", "127.0.0.1:2"], 1,
        io_timeout_s=0.1, connect_timeout_s=0.1,
        breaker_failures=1, breaker_cooldown_s=60.0,
    )
    key_remote = next(
        f"bf:{i}" for i in range(10_000)
        if node_of_key(f"bf:{i}".encode(), 2) == 0
    )
    key_local = next(
        f"bl:{i}" for i in range(10_000)
        if node_of_key(f"bl:{i}".encode(), 2) == 1
    )
    cl.rate_limit_batch([key_local], 5, 100, 60, 1, T0)  # warm compile
    cl.rate_limit_batch([key_remote], 5, 100, 60, 1, T0)  # arms breaker
    t0 = time.monotonic()
    res = cl.rate_limit_batch(
        [key_remote, key_local], 5, 100, 60, 1, T0 + NS
    )
    assert time.monotonic() - t0 < 0.5
    assert res.allowed.tolist() == [False, True]
    stats = cl.peer_stats()
    assert stats["127.0.0.1:1"]["failed"] >= 2


def test_cluster_wire_window_delegates_when_local():
    """Single-node clusters (and all-local windows) keep the fully-native
    wire path; a window containing a remote-owned key returns None and
    routes through the forwarding path instead."""
    from throttlecrab_tpu.native import native_available

    if not native_available():
        pytest.skip("no C++ keymap")

    def make_frames(keys):
        blob = b"".join(keys)
        offsets = np.zeros(len(keys) + 1, np.int64)
        np.cumsum([len(k) for k in keys], out=offsets[1:])
        params = np.array([[3, 10, 3600, 1]] * len(keys), np.int64)
        return [(blob, offsets, params)]

    # Single node: always delegates.
    cl1 = ClusterLimiter(
        TpuRateLimiter(capacity=128, keymap="native"), ["127.0.0.1:1"], 0
    )
    handle = cl1.dispatch_wire_window(make_frames([b"w:a", b"w:b"]), T0)
    assert handle is not None
    res = handle.fetch()[0]
    assert res.allowed.tolist() == [True, True]

    # Two nodes: all-local window delegates, remote-containing one won't.
    local_key = next(
        b"wl:%d" % i for i in range(10_000)
        if node_of_key(b"wl:%d" % i, 2) == 0
    )
    remote_key = next(
        b"wr:%d" % i for i in range(10_000)
        if node_of_key(b"wr:%d" % i, 2) == 1
    )
    cl2 = ClusterLimiter(
        TpuRateLimiter(capacity=128, keymap="native"),
        ["127.0.0.1:1", "127.0.0.1:2"], 0,
    )
    assert cl2.dispatch_wire_window(make_frames([local_key]), T0) is not None
    assert (
        cl2.dispatch_wire_window(make_frames([local_key, remote_key]), T0)
        is None
    )


def test_cluster_differential_vs_oracle():
    """Random traffic (incl. wild parameter draws) through an in-process
    ClusterLimiter with a real spawned peer must match the scalar oracle
    value-for-value — the RPC encode/decode path carries exact i64
    params and exact wire results for keys owned by either node."""
    import numpy as np

    from test_tpu_batch import oracle_batch
    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    I32 = (1 << 31) - 1
    b_proc = spawn_node(1, HTTP_B)
    try:
        wait_healthy(b_proc, HTTP_B)
        local = TpuRateLimiter(capacity=1 << 12, keymap="auto")
        cl = ClusterLimiter(local, NODES.split(","), 0, io_timeout_s=60.0)
        for seed in range(3):
            rng = np.random.RandomState(9000 + seed)
            oracle = RateLimiter(PeriodicStore())
            pool = [b"cd%dk%d" % (seed, i) for i in range(8)]
            params = {}
            for k in pool:
                wild = rng.rand() < 0.2
                params[k] = (
                    int(rng.randint(1, 1 << 40)) if wild
                    else int(rng.randint(1, 30)),
                    int(rng.randint(1, 1 << 20)) if wild
                    else int(rng.randint(1, 3000)),
                    int(rng.choice([1, 10, 3600, 1 << 25])) if wild
                    else int(rng.choice([1, 10, 60, 3600])),
                )
            now = 1_753_700_000 * 10**9 + seed * 3600 * 10**9
            for step in range(5):
                n = int(rng.randint(1, 20))
                keys = [pool[rng.randint(len(pool))] for _ in range(n)]
                b = np.array([params[k][0] for k in keys], np.int64)
                c = np.array([params[k][1] for k in keys], np.int64)
                p = np.array([params[k][2] for k in keys], np.int64)
                q = np.array(
                    [int(rng.randint(0, 5)) for _ in keys], np.int64
                )
                qm: dict = {}
                for i, k in enumerate(keys):
                    q[i] = qm.setdefault(k, int(q[i]))
                res = cl.rate_limit_many(
                    [(keys, b, c, p, q, now)], wire=True
                )[0]
                exp = oracle_batch(oracle, keys, b, c, p, q, now)
                ok = exp["status"] == 0
                ctx = f"seed{seed} step{step}"
                np.testing.assert_array_equal(
                    res.status, exp["status"], err_msg=ctx
                )
                np.testing.assert_array_equal(
                    res.allowed[ok], exp["allowed"][ok], err_msg=ctx
                )
                np.testing.assert_array_equal(
                    res.remaining[ok],
                    np.minimum(exp["remaining"], I32)[ok], err_msg=ctx,
                )
                np.testing.assert_array_equal(
                    res.reset_after_s[ok],
                    np.minimum(exp["reset"] // 10**9, I32)[ok],
                    err_msg=ctx,
                )
                now += int(rng.randint(0, 10**9))
        stats = cl.peer_stats()[NODES.split(",")[1]]
        assert stats["forwarded"] > 0 and stats["failed"] == 0
    finally:
        b_proc.terminate()
        try:
            b_proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            b_proc.kill()
            b_proc.wait()
