"""Fused Pallas decision kernel (tpu/pallas_fused.py) edges.

The oracle differential lives in the tier fuzzer
(test_tier_fuzz.py::test_tier_ladder_fuzz_fused_alternation); this file
pins the kernel-specific contracts: the i32-pair arithmetic against the
i64 originals, the fused window against the composed-XLA twins across
widths / output tiers / ring-vs-batch shapes, shard_map composition,
the insight coexistence that retires the downgrade warning, and the
kill switch (THROTTLECRAB_PALLAS_FUSED unset = byte-identical current
behavior, fused code never invoked).
"""

import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from throttlecrab_tpu.tpu import pallas_fused as pf
from throttlecrab_tpu.tpu import sat
from throttlecrab_tpu.tpu.kernel import (
    EMPTY_EXPIRY,
    INS_WIDTH,
    gcra_scan_packed_acc,
    gcra_scan_packed_ins,
    pack_requests,
    pack_state,
)

NS = 1_000_000_000
T0 = 1_753_700_000 * NS

I64_EDGES = np.array(
    [
        0, 1, -1, 2, -2, (1 << 31) - 1, 1 << 31, -(1 << 31),
        (1 << 32) - 1, 1 << 32, (1 << 62), -(1 << 62),
        (1 << 63) - 1, -(1 << 63), NS, -NS, (1 << 61), 977,
    ],
    dtype=np.int64,
)


def _pairs(x):
    x = np.asarray(x, np.int64)
    lo = (x & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    hi = (x >> 32).astype(np.int32)
    return jnp.asarray(lo), jnp.asarray(hi)


def _join(p):
    return (np.asarray(p[1]).astype(np.int64) << 32) | (
        np.asarray(p[0]).astype(np.int64) & 0xFFFFFFFF
    )


def _rand_i64(rng, n):
    vals = rng.integers(-(1 << 63), 1 << 63, n, dtype=np.int64)
    # splice the edge values in so every run covers them
    idx = rng.choice(n, size=min(len(I64_EDGES), n), replace=False)
    vals[idx] = I64_EDGES[: len(idx)]
    return vals


def test_pair_math_matches_i64():
    """Every pair helper bit-identical to its i64 original (sat.py /
    numpy wrapping semantics) over random values spliced with the
    2^31/2^32/2^63 boundary edges."""
    rng = np.random.default_rng(42)
    n = 512
    a = _rand_i64(rng, n)
    b = _rand_i64(rng, n)
    pa, pb = _pairs(a), _pairs(b)

    with np.errstate(over="ignore"):
        assert (_join(pf._add64(pa, pb)) == a + b).all()
        assert (_join(pf._sub64(pa, pb)) == a - b).all()
        assert (_join(pf._mul64_lo(pa, pb)) == a * b).all()
    assert (np.asarray(pf._lt64(pa, pb)) == (a < b)).all()
    assert (np.asarray(pf._le64(pa, pb)) == (a <= b)).all()
    assert (np.asarray(pf._eq64(pa, pa)) == np.ones(n, bool)).all()
    assert (
        np.asarray(pf._ult64(pa, pb))
        == (a.view(np.uint64) < b.view(np.uint64))
    ).all()
    assert (_join(pf._max64(pa, pb)) == np.maximum(a, b)).all()
    assert (_join(pf._min64(pa, pb)) == np.minimum(a, b)).all()

    assert (
        _join(pf._sat_add64(pa, pb))
        == np.asarray(sat.sat_add(jnp.asarray(a), jnp.asarray(b)))
    ).all()
    assert (
        _join(pf._sat_sub64(pa, pb))
        == np.asarray(sat.sat_sub(jnp.asarray(a), jnp.asarray(b)))
    ).all()
    bn = np.abs(b) % (1 << 62)  # nn forms: b >= 0 contract
    assert (
        _join(pf._sat_add_nn64(pa, _pairs(bn)))
        == np.asarray(sat.sat_add_nn(jnp.asarray(a), jnp.asarray(bn)))
    ).all()
    assert (
        _join(pf._sat_sub_nn64(pa, _pairs(bn)))
        == np.asarray(sat.sat_sub_nn(jnp.asarray(a), jnp.asarray(bn)))
    ).all()
    an = np.abs(a) % ((1 << 63) - 1)  # nonneg-mul contract
    assert (
        _join(pf._sat_mul_nonneg64(_pairs(an), _pairs(bn)))
        == np.asarray(
            sat.sat_mul_nonneg(jnp.asarray(an), jnp.asarray(bn))
        )
    ).all()
    den = np.maximum(bn, 1)
    assert (
        _join(pf._udiv64(_pairs(an), _pairs(den))) == an // den
    ).all(), "unsigned long division"


def _fresh_state(rows, width):
    st = pack_state(
        jnp.zeros((rows,), jnp.int64),
        jnp.full((rows,), EMPTY_EXPIRY, jnp.int64),
    )
    if width > 4:
        st = jnp.concatenate(
            [st, jnp.zeros((rows, width - 4), jnp.int32)], axis=-1
        )
    return st


def _rand_window(rng, K, B, cap, degen):
    """A hostile packed window: duplicate segments, degenerate params
    (when `degen`), invalid lanes, saturating-scale values."""
    slots = rng.integers(0, cap, (K, B)).astype(np.int32)
    em = rng.choice([0, 1, 1000, NS, 7 * NS, 1 << 62], (K, B)).astype(
        np.int64
    )
    tol = rng.choice(
        [0, 5, NS, 100 * NS, (1 << 61) + 7, -(3 * NS)], (K, B)
    ).astype(np.int64)
    q = rng.choice([0, 1, 2, 50], (K, B)).astype(np.int64)
    if not degen:
        em = np.maximum(em % (10 * NS), 1)
        tol = np.abs(tol) % (100 * NS) + 1
        q = np.maximum(q, 1)
    valid = rng.random((K, B)) < 0.9
    rank = np.zeros((K, B), np.int32)
    is_last = np.ones((K, B), bool)
    for k in range(K):
        first: dict = {}
        state: dict = {}
        for i in range(B):
            if not valid[k, i]:
                continue
            s = int(slots[k, i])
            if s in state:
                cnt, last = state[s]
                rank[k, i] = cnt
                is_last[k, last] = False
                state[s] = (cnt + 1, i)
                j = first[s]  # uniform params per segment
                em[k, i], tol[k, i], q[k, i] = (
                    em[k, j], tol[k, j], q[k, j],
                )
            else:
                state[s] = (1, i)
                first[s] = i
    now = T0 + np.sort(rng.integers(0, 100 * NS, K)).astype(np.int64)
    return pack_requests(slots, rank, is_last, em, tol, q, valid), now, valid


def _run_pair(seed, K, B, cap, width, compact, with_degen, steps=2):
    """Drive the fused and XLA packed-scan twins over the same windows;
    assert valid-lane outputs, real-slot state, and both accumulators
    stay bit-identical at every step."""
    rng = np.random.default_rng(seed)
    N = cap + B
    st_x, st_f = _fresh_state(N, width), _fresh_state(N, width)
    exp_x, exp_f = jnp.zeros((), jnp.int64), jnp.zeros((), jnp.int64)
    ic_x, ic_f = jnp.zeros((2,), jnp.int64), jnp.zeros((2,), jnp.int64)
    for step in range(steps):
        packed, now, valid = _rand_window(rng, K, B, cap, with_degen)
        now = now + step * 200 * NS
        if width > 4:
            st_x, exp_x, ic_x, out_x = gcra_scan_packed_ins(
                st_x, exp_x, ic_x, jnp.asarray(packed), jnp.asarray(now),
                with_degen=with_degen, compact=compact,
            )
            st_f, exp_f, ic_f, out_f = pf.gcra_scan_packed_fused_ins(
                st_f, exp_f, ic_f, packed, now,
                with_degen=with_degen, compact=compact,
            )
            assert (np.asarray(ic_x) == np.asarray(ic_f)).all()
        else:
            st_x, exp_x, out_x = gcra_scan_packed_acc(
                st_x, exp_x, jnp.asarray(packed), jnp.asarray(now),
                with_degen=with_degen, compact=compact,
            )
            st_f, exp_f, out_f = pf.gcra_scan_packed_fused_acc(
                st_f, exp_f, packed, now,
                with_degen=with_degen, compact=compact,
            )
        ox, of = np.asarray(out_x), np.asarray(out_f)
        mask = valid if compact in ("cur", "w32") else valid[:, None, :]
        bad = (ox != of) & mask
        assert not bad.any(), (
            f"out diverged ({compact=}, {with_degen=}, {width=}): "
            f"{np.argwhere(bad)[:4]}"
        )
        assert (
            np.asarray(st_x)[:cap] == np.asarray(st_f)[:cap]
        ).all(), "stored state diverged"
        assert int(exp_x) == int(exp_f), "expired-hit accumulator"


@pytest.mark.parametrize("width", [4, INS_WIDTH])
@pytest.mark.parametrize(
    "compact,with_degen",
    [(False, True), (True, True), (True, False), ("cur", False),
     ("w32", False)],
)
def test_fused_window_bit_identical_to_xla(width, compact, with_degen):
    """The fused window against the composed-XLA twin on hostile random
    windows: every output tier, both row widths, exact and certified
    paths, duplicate segments + degenerate orbits + invalid lanes,
    state carried across consecutive windows."""
    _run_pair(
        7 * width + len(str(compact)), K=2, B=16, cap=32,
        width=width, compact=compact, with_degen=with_degen,
    )


@pytest.mark.parametrize("K,B", [(1, 4), (1, 16), (3, 8), (2, 48)])
def test_ring_and_shape_edges(K, B):
    """Batch widths below / at / above the DMA ring depth (RING=16) and
    non-power-of-two lane counts all pipeline correctly — the fused
    grid walks any K, and the rings degrade to whatever depth B
    allows."""
    _run_pair(99 + K * B, K=K, B=B, cap=64, width=4,
              compact=True, with_degen=True, steps=1)


def test_scratch_tail_takes_suppressed_writes():
    """A denied-everywhere window must leave the real rows bit-identical
    under both dispatches AND land its redirects inside the scratch
    tail, never on a real slot (the unique-index contract)."""
    B, cap = 16, 8
    st = _fresh_state(cap + B, 4)
    # one key, burst 1 (tol 0), quantity 2: every request denied after
    # the orbit's first write
    slots = np.zeros((1, B), np.int32)
    rank = np.arange(B, dtype=np.int32)[None]
    is_last = np.zeros((1, B), bool)
    is_last[0, -1] = True
    em = np.full((1, B), NS, np.int64)
    tol = np.zeros((1, B), np.int64)
    q = np.full((1, B), 2, np.int64)
    valid = np.ones((1, B), bool)
    packed = pack_requests(slots, rank, is_last, em, tol, q, valid)
    now = np.array([T0], np.int64)
    st_f, _, out_f = pf.gcra_scan_packed_fused_acc(
        st, jnp.zeros((), jnp.int64), packed, now,
        with_degen=True, compact=True,
    )
    st_x, _, out_x = gcra_scan_packed_acc(
        _fresh_state(cap + B, 4), jnp.zeros((), jnp.int64),
        jnp.asarray(packed), jnp.asarray(now),
        with_degen=True, compact=True,
    )
    assert (np.asarray(out_f) == np.asarray(out_x)).all()
    assert (np.asarray(st_f)[:cap] == np.asarray(st_x)[:cap]).all()


def test_insight_coexists_no_downgrade_warning(monkeypatch, caplog):
    """THROTTLECRAB_PALLAS_FUSED=1 + insight: the width-polymorphic
    kernel carries the 6-wide rows natively, so enable_insight must NOT
    emit the legacy downgrade warning — while a legacy-only
    THROTTLECRAB_PALLAS=1 configuration still warns."""
    from throttlecrab_tpu.tpu.table import BucketTable

    monkeypatch.setenv("THROTTLECRAB_PALLAS", "1")
    monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", "1")
    with caplog.at_level(logging.WARNING, logger="throttlecrab.table"):
        BucketTable(64, insight=True)
    assert not [
        r for r in caplog.records if "disable" in r.getMessage()
    ], "fused path must not warn about an insight downgrade"
    caplog.clear()
    monkeypatch.delenv("THROTTLECRAB_PALLAS_FUSED")
    with caplog.at_level(logging.WARNING, logger="throttlecrab.table"):
        BucketTable(64, insight=True)
    assert [
        r for r in caplog.records if "legacy Pallas DMA" in r.getMessage()
    ], "legacy-only configuration must keep warning"


def test_env_parse_matches_config_bool(monkeypatch):
    """kernel.pallas_fused_enabled and config._env_bool must never
    disagree about the kill switch: THROTTLECRAB_PALLAS_FUSED=off/
    false/no must be OFF everywhere (a lax 'not in (\"\", \"0\")' parse
    once ran the fused kernel while every config surface reported it
    disabled)."""
    from throttlecrab_tpu.server.config import _env_bool
    from throttlecrab_tpu.tpu.kernel import pallas_fused_enabled

    for v in ("", "0", "1", "true", "false", "yes", "no", "on", "off",
              "TRUE", "oFF", "2"):
        monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", v)
        assert pallas_fused_enabled() == _env_bool(v), v
    monkeypatch.delenv("THROTTLECRAB_PALLAS_FUSED")
    assert pallas_fused_enabled() is False


def test_create_limiter_arms_env_both_directions(monkeypatch):
    """store.create_limiter writes the RESOLVED config value to the env
    in both directions — a stale '1' from an earlier limiter in the
    same process must not defeat a later config's kill switch."""
    from throttlecrab_tpu.server.config import Config
    from throttlecrab_tpu.server.store import create_limiter

    monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", "1")
    create_limiter(Config(http=True, store_capacity=1024))
    assert os.environ["THROTTLECRAB_PALLAS_FUSED"] == "0"
    create_limiter(
        Config(http=True, store_capacity=1024, pallas_fused=True)
    )
    assert os.environ["THROTTLECRAB_PALLAS_FUSED"] == "1"


@pytest.mark.slow
def test_flag_unset_never_imports_fused_module():
    """With the knob unset, a serving dispatch must not import
    tpu.pallas_fused at all — the default composed-XLA path stays
    isolated from the experimental pallas stack (fresh process, since
    this suite imports the module itself)."""
    code = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from throttlecrab_tpu.tpu.limiter import TpuRateLimiter\n"
        "lim = TpuRateLimiter(capacity=64, keymap='python')\n"
        f"lim.rate_limit_batch(['a', 'b'], 5, 10, 60, 1, {T0}, wire=True)\n"
        "assert 'throttlecrab_tpu.tpu.pallas_fused' not in sys.modules\n"
        "print('isolated')\n"
    )
    env = {k: v for k, v in os.environ.items()
           if k != "THROTTLECRAB_PALLAS_FUSED"}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0 and "isolated" in r.stdout, r.stderr[-2000:]


def test_kill_switch_fused_never_invoked(monkeypatch):
    """With THROTTLECRAB_PALLAS_FUSED unset the fused module must never
    be entered — current behavior stays byte-identical by construction."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    monkeypatch.delenv("THROTTLECRAB_PALLAS_FUSED", raising=False)

    def boom(*a, **k):  # pragma: no cover - fails the test if reached
        raise AssertionError("fused kernel invoked with the flag unset")

    monkeypatch.setattr(pf, "fused_window", boom)
    lim = TpuRateLimiter(capacity=256, keymap="python")
    res = lim.rate_limit_batch(
        ["a", "b", "a"], 5, 10, 60, 1, T0, wire=True
    )
    assert res.status.tolist() == [0, 0, 0]
    h = lim.dispatch_many(
        [(["a", "c"], 5, 10, 60, 1, T0 + NS)], wire=True
    )
    h.fetch()


def test_limiter_end_to_end_fused_equals_xla(monkeypatch):
    """Whole-limiter equality across the serving dispatchers
    (rate_limit_batch, dispatch_many incl. the w32/cur tier ladder)
    with the fused kernel on vs off, including stored state."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    rng = np.random.default_rng(11)
    monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", "0")
    lims = {}
    for fused in (False, True):
        lims[fused] = TpuRateLimiter(capacity=256, keymap="python")
    keys = [f"k{i}" for i in range(24)]
    now = T0
    for step in range(5):
        n = int(rng.integers(2, 20))
        ks = [keys[rng.integers(len(keys))] for _ in range(n)]
        b = rng.integers(1, 2500, n)
        c = rng.integers(1, 100, n)
        p = rng.integers(1, 60, n)
        q = np.where(rng.random(n) < 0.15, 0, 1)
        batches = [(ks, b, c, p, q, now + j * NS // 5) for j in range(2)]
        got = {}
        for fused in (False, True):
            monkeypatch.setenv(
                "THROTTLECRAB_PALLAS_FUSED", "1" if fused else "0"
            )
            got[fused] = lims[fused].dispatch_many(
                batches, wire=True
            ).fetch()
        for g0, g1 in zip(got[False], got[True]):
            for f in ("allowed", "remaining", "reset_after_s",
                      "retry_after_s", "status"):
                assert (
                    np.asarray(getattr(g0, f))
                    == np.asarray(getattr(g1, f))
                ).all(), (step, f)
        assert (
            np.asarray(lims[False].table.state)[:256]
            == np.asarray(lims[True].table.state)[:256]
        ).all(), "table state diverged between dispatches"
        now += int(rng.integers(1, 3 * NS))


def test_shard_map_tenant_counters_ride_fused_launch(monkeypatch):
    """Tenant-armed mesh: the in-launch per-tenant [T, 2] psum fold
    reads the fused kernel's output planes — counters and decisions
    must match the XLA mesh exactly."""
    from conftest import require_devices

    require_devices(2)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )
    from throttlecrab_tpu.parallel.tenants import TenantRegistry

    rng = np.random.default_rng(31)
    monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", "0")
    lims = {}
    for fused in (False, True):
        lims[fused] = ShardedTpuRateLimiter(
            capacity_per_shard=128, mesh=make_mesh(2), insight=True,
            tenants=TenantRegistry(max_tenants=4, delim=":"),
        )
    keys = [f"t{i % 3}:k{i}" for i in range(30)]
    now = T0
    for step in range(3):
        n = int(rng.integers(4, 20))
        ks = [keys[rng.integers(len(keys))] for _ in range(n)]
        b = rng.integers(1, 30, n)
        c = rng.integers(1, 80, n)
        p = rng.integers(1, 50, n)
        batches = [(ks, b, c, p, 1, now + j * NS // 10) for j in range(2)]
        got = {}
        for fused in (False, True):
            monkeypatch.setenv(
                "THROTTLECRAB_PALLAS_FUSED", "1" if fused else "0"
            )
            got[fused] = lims[fused].dispatch_many(
                batches, wire=True
            ).fetch()
        for g0, g1 in zip(got[False], got[True]):
            for f in ("allowed", "remaining", "status"):
                assert (
                    np.asarray(getattr(g0, f))
                    == np.asarray(getattr(g1, f))
                ).all(), (step, f)
        assert lims[False].tenant_stats() == lims[True].tenant_stats()
        now += NS


def test_shard_map_composition(monkeypatch):
    """ShardedBucketTable runs the identical fused program per shard:
    decisions, per-shard stored state, and the psum'd insight totals
    all bit-identical to the composed-XLA mesh, at both row widths."""
    from conftest import require_devices

    require_devices(2)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    rng = np.random.default_rng(23)
    for insight in (False, True):
        monkeypatch.setenv("THROTTLECRAB_PALLAS_FUSED", "0")
        lims = {}
        for fused in (False, True):
            lims[fused] = ShardedTpuRateLimiter(
                capacity_per_shard=128, mesh=make_mesh(2), insight=insight
            )
        keys = [f"k{i}" for i in range(32)]
        now = T0
        for step in range(3):
            n = int(rng.integers(3, 22))
            ks = [keys[rng.integers(len(keys))] for _ in range(n)]
            b = rng.integers(1, 40, n)
            c = rng.integers(1, 100, n)
            p = rng.integers(1, 60, n)
            q = np.where(rng.random(n) < 0.1, 0, 1)
            batches = [
                (ks, b, c, p, q, now + j * NS // 10) for j in range(2)
            ]
            got = {}
            for fused in (False, True):
                monkeypatch.setenv(
                    "THROTTLECRAB_PALLAS_FUSED", "1" if fused else "0"
                )
                got[fused] = lims[fused].dispatch_many(
                    batches, wire=True
                ).fetch()
            for g0, g1 in zip(got[False], got[True]):
                for f in ("allowed", "remaining", "reset_after_s",
                          "retry_after_s", "status"):
                    assert (
                        np.asarray(getattr(g0, f))
                        == np.asarray(getattr(g1, f))
                    ).all(), (insight, step, f)
            assert (
                np.asarray(lims[False].table.state)[:, :128]
                == np.asarray(lims[True].table.state)[:, :128]
            ).all(), (insight, step, "shard state")
            if insight:
                assert (
                    lims[False].table.insight_counts()
                    == lims[True].table.insight_counts()
                ), "psum'd mesh insight totals"
            now += int(rng.integers(1, 2 * NS))
