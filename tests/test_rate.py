"""Rate conversion tests, ported from the reference's `rate/tests.rs`."""

from throttlecrab_tpu import Rate
from throttlecrab_tpu.core.i64 import U64_MAX

NS = 1_000_000_000


class TestConstructors:
    def test_per_second(self):
        assert Rate.per_second(10).period() == 100_000_000  # 100ms
        assert Rate.per_second(1).period() == NS
        assert Rate.per_second(1000).period() == 1_000_000

    def test_per_minute(self):
        assert Rate.per_minute(60).period() == NS  # 1/s
        assert Rate.per_minute(1).period() == 60 * NS

    def test_per_hour(self):
        assert Rate.per_hour(3600).period() == NS
        assert Rate.per_hour(1).period() == 3600 * NS

    def test_per_day(self):
        assert Rate.per_day(86400).period() == NS
        assert Rate.per_day(1).period() == 86400 * NS

    def test_new_custom(self):
        assert Rate.new(2_500_000_000).period() == 2_500_000_000


class TestFromCountAndPeriod:
    def test_simple(self):
        # 100 requests per 60s = 0.6s per token
        assert Rate.from_count_and_period(100, 60).period() == 600_000_000

    def test_one_per_second(self):
        assert Rate.from_count_and_period(60, 60).period() == NS

    def test_fractional(self):
        # 7 per 60s: 60e9/7 = 8571428571.43 -> truncated
        assert Rate.from_count_and_period(7, 60).period() == 8571428571

    def test_invalid_count_blocks_all(self):
        r = Rate.from_count_and_period(0, 60)
        assert r.period() == U64_MAX * NS
        r = Rate.from_count_and_period(-5, 60)
        assert r.period() == U64_MAX * NS

    def test_invalid_period_blocks_all(self):
        r = Rate.from_count_and_period(10, 0)
        assert r.period() == U64_MAX * NS
        r = Rate.from_count_and_period(10, -1)
        assert r.period() == U64_MAX * NS

    def test_f64_truncation_matches_reference(self):
        # The reference computes (period * 1e9) / count in f64 then
        # truncates (rate/mod.rs:172).  Spot-check a case where exact
        # integer division would differ in the last digit.
        assert Rate.from_count_and_period(3, 1).period() == int(1e9 / 3.0)
