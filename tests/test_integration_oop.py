"""Out-of-process integration tests: spawn the real server module as a
subprocess and drive it with real clients over all three transports.

The reference's equivalent spawns the server binary with `cargo run` and
asserts allow/deny counts through a real Redis client
(integration-tests/tests/redis_integration_test.rs:8-23, 140-160: burst 3
→ 3 allowed / 2 denied).  One server process serves the whole module.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

HTTP_PORT = 28080
GRPC_PORT = 28070
REDIS_PORT = 28060


def spawn_server(*extra_args):
    """Spawn the real server module on the CPU backend (shared by the
    module fixture and the restart tests)."""
    env = dict(os.environ)
    env["THROTTLECRAB_PLATFORM"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "throttlecrab_tpu.server",
            "--store", "adaptive", "--log-level", "warn", *extra_args,
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def wait_health(proc, http_port, deadline_s=120):
    deadline = time.time() + deadline_s
    last_err = None
    while time.time() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            pytest.fail(f"server exited early rc={proc.returncode}:\n{out}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/health", timeout=1
            ) as r:
                assert r.read() == b"OK"
            return
        except Exception as e:  # noqa: BLE001 - retry until deadline
            last_err = e
            time.sleep(0.5)
    proc.terminate()
    pytest.fail(f"server never became healthy: {last_err}")


@pytest.fixture(scope="module")
def server():
    proc = spawn_server(
        "--http", "--http-port", str(HTTP_PORT),
        "--grpc", "--grpc-port", str(GRPC_PORT),
        "--redis", "--redis-port", str(REDIS_PORT),
    )
    wait_health(proc, HTTP_PORT)
    yield proc
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("server did not shut down gracefully within 30s")


def resp_frame(*parts: bytes) -> bytes:
    out = b"*%d\r\n" % len(parts)
    for p in parts:
        out += b"$%d\r\n%s\r\n" % (len(p), p)
    return out


def read_resp_reply(sock: socket.socket) -> bytes:
    """One RESP reply (integer-array, simple string, or error)."""
    data = b""
    sock.settimeout(10)
    while True:
        data += sock.recv(4096)
        if data.startswith((b"+", b"-")):
            if data.endswith(b"\r\n"):
                return data
        elif data.startswith(b"*"):
            # 5-integer array: 6 CRLF-terminated lines total.
            if data.count(b"\r\n") >= 6:
                return data
        else:
            raise AssertionError(f"unexpected reply: {data!r}")


def test_redis_burst3_three_allowed_two_denied(server):
    """redis_integration_test.rs:140-160, byte for byte over a real socket."""
    with socket.create_connection(("127.0.0.1", REDIS_PORT), 10) as s:
        allowed = []
        for _ in range(5):
            s.sendall(
                resp_frame(b"THROTTLE", b"oop:redis", b"3", b"10", b"60")
            )
            reply = read_resp_reply(s)
            assert reply.startswith(b"*5\r\n")
            allowed.append(reply.split(b"\r\n")[1] == b":1")
        assert allowed == [True, True, True, False, False]
        # PING still answers on the same connection.
        s.sendall(resp_frame(b"PING"))
        assert read_resp_reply(s) == b"+PONG\r\n"


def test_http_burst3_three_allowed_two_denied(server):
    body = json.dumps(
        {"key": "oop:http", "max_burst": 3, "count_per_period": 10,
         "period": 60}
    ).encode()
    results = []
    for _ in range(5):
        req = urllib.request.Request(
            f"http://127.0.0.1:{HTTP_PORT}/throttle",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            results.append(json.loads(r.read())["allowed"])
    assert results == [True, True, True, False, False]


def test_grpc_burst3_three_allowed_two_denied(server):
    grpc = pytest.importorskip("grpc")
    from throttlecrab_tpu.server.proto import throttlecrab_pb2 as pb

    channel = grpc.insecure_channel(f"127.0.0.1:{GRPC_PORT}")
    throttle = channel.unary_unary(
        "/throttlecrab.RateLimiter/Throttle",
        request_serializer=pb.ThrottleRequest.SerializeToString,
        response_deserializer=pb.ThrottleResponse.FromString,
    )
    results = []
    for _ in range(5):
        reply = throttle(
            pb.ThrottleRequest(
                key="oop:grpc", max_burst=3, count_per_period=10, period=60,
                quantity=1,
            ),
            timeout=10,
        )
        results.append(reply.allowed)
    channel.close()
    assert results == [True, True, True, False, False]


def test_limits_shared_across_transports(server):
    """One key hit over HTTP then RESP shares one bucket
    (multi_transport.rs:159-225, but across a process boundary)."""
    body = json.dumps(
        {"key": "oop:shared", "max_burst": 2, "count_per_period": 10,
         "period": 60}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{HTTP_PORT}/throttle",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert json.loads(r.read())["allowed"] is True
    with socket.create_connection(("127.0.0.1", REDIS_PORT), 10) as s:
        s.sendall(resp_frame(b"THROTTLE", b"oop:shared", b"2", b"10", b"60"))
        assert read_resp_reply(s).split(b"\r\n")[1] == b":1"
        s.sendall(resp_frame(b"THROTTLE", b"oop:shared", b"2", b"10", b"60"))
        assert read_resp_reply(s).split(b"\r\n")[1] == b":0"  # exhausted


def test_metrics_visible_after_traffic(server):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{HTTP_PORT}/metrics", timeout=10
    ) as r:
        text = r.read().decode()
    assert "throttlecrab_requests_total" in text
    assert "throttlecrab_requests_by_transport" in text


def test_snapshot_survives_restart(tmp_path):
    """--snapshot-path: exhaust a burst, SIGTERM the server, restart with
    the same path — the key must still be exhausted (state restored).
    Uses a suffix-less path on purpose: numpy appends .npz on save, and
    the restore side must normalize identically or silently start cold."""
    snap = str(tmp_path / "state")  # note: no .npz suffix
    port = 28085

    def throttle():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/throttle",
            data=json.dumps(
                {"key": "snap:k", "max_burst": 3,
                 "count_per_period": 10, "period": 3600}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["allowed"]

    args = ("--http", "--http-port", str(port), "--snapshot-path", snap)
    proc = spawn_server(*args)
    try:
        wait_health(proc, port)
        assert [throttle() for _ in range(4)] == [True, True, True, False]
    finally:
        proc.terminate()
    assert proc.wait(timeout=60) == 0
    assert os.path.exists(snap + ".npz")

    proc = spawn_server(*args)
    try:
        wait_health(proc, port)
        # Still exhausted across the restart.
        assert throttle() is False
    finally:
        proc.terminate()
        proc.wait(timeout=60)
