"""Native C++ HTTP transport tests: wire behavior must match the asyncio
HTTP transport (test_transports.py) for the same requests."""

import asyncio
import json

import pytest

from throttlecrab_tpu.native import (
    toolchain_available,
    wire_available,
    wire_build_error,
)
from throttlecrab_tpu.server.metrics import Metrics
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

if not wire_available() and toolchain_available():
    pytest.fail(
        "C++ wire server failed to build with g++ present:\n"
        f"{wire_build_error()}",
        pytrace=False,
    )
pytestmark = pytest.mark.skipif(
    not wire_available(),
    reason=f"no C++ toolchain for the wire server: {wire_build_error()}",
)

T0 = 1_700_000_000 * 1_000_000_000


def make_transport(**kwargs):
    from throttlecrab_tpu.server.native_http import NativeHttpTransport

    metrics = Metrics(max_denied_keys=10)
    limiter = TpuRateLimiter(capacity=1024)
    transport = NativeHttpTransport(
        "127.0.0.1", 0, limiter, metrics,
        batch_size=kwargs.pop("batch_size", 64),
        max_linger_us=kwargs.pop("max_linger_us", 500),
        now_fn=lambda: T0,
        **kwargs,
    )
    return transport, metrics


async def http_request(port, method, path, body=None, close=True,
                       reader=None, writer=None):
    if reader is None:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    head = (
        f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
        f"Content-Length: {len(payload)}\r\n"
        + ("Connection: close\r\n" if close else "")
        + "\r\n"
    ).encode()
    writer.write(head + payload)
    await writer.drain()
    status_line = await asyncio.wait_for(
        reader.readuntil(b"\r\n"), timeout=5.0
    )
    status = int(status_line.split(b" ")[1])
    headers = await asyncio.wait_for(
        reader.readuntil(b"\r\n\r\n"), timeout=5.0
    )
    length = 0
    for line in headers.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":")[1])
    data = await asyncio.wait_for(reader.readexactly(length), timeout=5.0)
    if close:
        writer.close()
    return status, data


def test_native_http_throttle_flow():
    async def main():
        transport, metrics = make_transport()
        await transport.start()
        port = transport.bound_port
        body = {"key": "nh:1", "max_burst": 3, "count_per_period": 10,
                "period": 60}
        allowed = []
        for _ in range(5):
            status, raw = await http_request(port, "POST", "/throttle", body)
            assert status == 200
            r = json.loads(raw)
            allowed.append(r["allowed"])
        assert r["limit"] == 3 and r["retry_after"] >= 1
        await transport.stop()
        return allowed, metrics

    allowed, metrics = asyncio.run(main())
    assert allowed == [True, True, True, False, False]
    assert metrics.requests_total == 5
    assert metrics.requests_denied == 2


def test_native_http_health_and_metrics():
    async def main():
        transport, metrics = make_transport()
        await transport.start()
        port = transport.bound_port
        status, raw = await http_request(port, "GET", "/health")
        assert (status, raw) == (200, b"OK")
        # Generate some traffic, then wait for the 1s metrics refresh.
        body = {"key": "m", "max_burst": 1, "count_per_period": 1,
                "period": 60}
        for _ in range(3):
            await http_request(port, "POST", "/throttle", body)
        await asyncio.sleep(1.2)
        status, raw = await http_request(port, "GET", "/metrics")
        assert status == 200
        text = raw.decode()
        assert "throttlecrab_requests_total 3" in text
        assert 'transport="http"} 3' in text
        await transport.stop()

    asyncio.run(main())


def test_native_http_error_shapes():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        bad = b"not json"
        writer.write(
            b"POST /throttle HTTP/1.1\r\nHost: x\r\nContent-Length: "
            + str(len(bad)).encode() + b"\r\nConnection: close\r\n\r\n"
            + bad
        )
        await writer.drain()
        raw = await reader.read(-1)
        assert b" 400 " in raw.split(b"\r\n", 1)[0]
        assert b"error" in raw
        writer.close()

        status, raw = await http_request(
            port, "POST", "/throttle",
            {"key": "k", "max_burst": -1, "count_per_period": 10,
             "period": 60},
        )
        assert status == 500
        assert b"invalid rate limit parameters" in raw

        status, _ = await http_request(port, "GET", "/nope")
        assert status == 404
        await transport.stop()

    asyncio.run(main())


def test_native_http_quantity_default_and_escapes():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        port = transport.bound_port
        # No quantity → defaults to 1 (http.rs:135).
        status, raw = await http_request(
            port, "POST", "/throttle",
            {"key": "q", "max_burst": 10, "count_per_period": 100,
             "period": 60},
        )
        assert json.loads(raw)["remaining"] == 9
        # Escaped key: json.dumps produces \" and \n escapes; both engines
        # must see the same unescaped identity.
        weird = 'a"b\nc'
        body = {"key": weird, "max_burst": 2, "count_per_period": 10,
                "period": 3600}
        seq = []
        for _ in range(3):
            _, raw = await http_request(port, "POST", "/throttle", body)
            seq.append(json.loads(raw)["allowed"])
        assert seq == [True, True, False]  # one bucket, burst 2
        await transport.stop()

    asyncio.run(main())


def test_native_http_keep_alive_pipelining():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        results = []
        for i in range(4):
            status, raw = await http_request(
                port, "POST", "/throttle",
                {"key": f"ka{i}", "max_burst": 5, "count_per_period": 10,
                 "period": 60},
                close=False, reader=reader, writer=writer,
            )
            results.append(status)
        writer.close()
        await transport.stop()
        return results

    assert asyncio.run(main()) == [200, 200, 200, 200]
