"""Fast (no-degen) and compact kernel variants vs the exact kernel."""

import numpy as np
import pytest

import jax.numpy as jnp

from throttlecrab_tpu.tpu.kernel import EMPTY_EXPIRY, gcra_batch, pack_state

NS = 1_000_000_000
BASE = 1_753_700_000 * NS
N = 512  # includes scratch tail for the 64-request batches below


def make_table():
    return pack_state(
        jnp.zeros((N,), jnp.int64),
        jnp.full((N,), EMPTY_EXPIRY, jnp.int64),
    )


def run(state, slots, rank, is_last, em, tol, q, valid, now, **kw):
    return gcra_batch(
        state,
        jnp.asarray(slots, jnp.int32), jnp.asarray(rank, jnp.int32),
        jnp.asarray(is_last, bool), jnp.asarray(em, jnp.int64),
        jnp.asarray(tol, jnp.int64), jnp.asarray(q, jnp.int64),
        jnp.asarray(valid, bool), now, **kw,
    )


@pytest.fixture
def nondegen_batch():
    rng = np.random.RandomState(7)
    B = 64
    slots = rng.randint(0, 32, B).astype(np.int32)
    # Host-style segment info.
    rank = np.zeros(B, np.int32)
    is_last = np.ones(B, bool)
    seen: dict = {}
    for i in range(B):
        s = int(slots[i])
        if s in seen:
            rank[i] = seen[s][0]
            seen[s][0] += 1
            is_last[seen[s][1]] = False
            seen[s][1] = i
        else:
            seen[s] = [1, i]
    em = np.full(B, 600_000_000, np.int64)
    tol = em * rng.randint(1, 9, B)  # burst >= 2 → tol > 0
    q = rng.randint(1, 3, B).astype(np.int64)
    # Uniform (em, tol, q) per slot, as the engine guarantees.
    for i in range(B):
        first = [j for j in range(B) if slots[j] == slots[i]][0]
        tol[i] = tol[first]
        q[i] = q[first]
    valid = np.ones(B, bool)
    return slots, rank, is_last, em, tol, q, valid


def test_fast_variant_matches_exact(nondegen_batch):
    st1 = make_table()
    st2 = make_table()
    for now in (BASE, BASE + NS, BASE + 30 * NS):
        st1, out_e = run(st1, *nondegen_batch, now)
        st2, out_f = run(st2, *nondegen_batch, now, with_degen=False)
        np.testing.assert_array_equal(np.asarray(out_e), np.asarray(out_f))
    # Real-slot rows identical (scratch tail may differ by construction).
    np.testing.assert_array_equal(np.asarray(st1)[:64], np.asarray(st2)[:64])


def test_compact_variant_truncates_to_seconds(nondegen_batch):
    st1 = make_table()
    st2 = make_table()
    outs_e, outs_c = [], []
    for now in (BASE, BASE, BASE + 2 * NS):
        st1, out_e = run(st1, *nondegen_batch, now)
        st2, out_c = run(st2, *nondegen_batch, now, compact=True)
        outs_e.append(np.asarray(out_e))
        outs_c.append(np.asarray(out_c))
    for out_e, out_c in zip(outs_e, outs_c):
        assert out_c.dtype == np.int32
        np.testing.assert_array_equal(out_c[0], out_e[0].astype(np.int32))
        np.testing.assert_array_equal(out_c[1], out_e[1].astype(np.int32))
        np.testing.assert_array_equal(out_c[2], (out_e[2] // NS).astype(np.int32))
        np.testing.assert_array_equal(out_c[3], (out_e[3] // NS).astype(np.int32))
    # Real-slot table state identical regardless of output format.
    np.testing.assert_array_equal(np.asarray(st1)[:64], np.asarray(st2)[:64])


def test_wrapped_negative_tolerance_certified_to_exact_path():
    """derive_params can produce a negative (wrapped) tolerance from the
    reference's truncating u64 product; such batches must be certified
    degenerate so the fast path's nonneg saturating ops are never used
    on them."""
    from throttlecrab_tpu.tpu.limiter import derive_params, has_degenerate

    # burst huge enough that emission * (burst-1) wraps negative.
    em, tol, invalid = derive_params(
        np.array([1 << 33], np.int64),
        np.array([1], np.int64),
        np.array([1 << 30], np.int64),
    )
    assert not invalid[0]
    assert tol[0] < 0  # the wrap actually happened
    assert has_degenerate(
        np.array([True]), em, tol, np.array([1], np.int64)
    )


def test_huge_increment_certified_to_exact_path():
    """An increment big enough that segment products could overflow i64
    must fail the fast-path certificate (both the Python and the C++
    certifier), so the kernel's certified plain multiplies are never fed
    overflowing products."""
    from throttlecrab_tpu.tpu.limiter import derive_params, has_degenerate

    # period huge, count 1 -> emission ~ period * 1e9 ns, near i64 max.
    em, tol, invalid = derive_params(
        np.array([2], np.int64),
        np.array([1], np.int64),
        np.array([1 << 33], np.int64),
    )
    assert not invalid[0] and tol[0] > 0
    assert has_degenerate(
        np.array([True]), em, tol, np.array([1], np.int64)
    )

    from throttlecrab_tpu.native import PREP_DEGEN, toolchain_available

    if toolchain_available():
        from throttlecrab_tpu.native import NativeKeyMap

        km = NativeKeyMap(16)
        packed, status, flags = km.prepare_batch(
            b"big", np.array([0, 3], np.int64),
            np.array([[2, 1, 1 << 33, 1]], np.int64),
        )
        assert status[0] == 0 and (flags & PREP_DEGEN)


def test_mul_certificate_bounds_pinned_across_certifiers():
    """MAX_SEGMENT derives from the table scratch bound, and the C++
    certifier's hardcoded constants must agree with the Python one at
    the boundary."""
    from throttlecrab_tpu.tpu.limiter import MAX_SEGMENT, has_degenerate
    from throttlecrab_tpu.tpu.table import BucketTable

    assert MAX_SEGMENT == BucketTable.SCRATCH
    from throttlecrab_tpu.parallel.sharded import ShardedBucketTable

    assert MAX_SEGMENT == ShardedBucketTable.SCRATCH

    from throttlecrab_tpu.native import toolchain_available

    if not toolchain_available():
        return
    from throttlecrab_tpu.native import NativeKeyMap, PREP_DEGEN

    # Probe both sides of the boundary with (burst=2, count=1, period=p):
    # emission = p * 1e9, quantity 1.
    for period, expect_degen in ((1 << 14, False), (1 << 28, True)):
        em = np.array([float(period) * 1e9], np.float64).astype(np.int64)
        tol = em.copy()
        py = has_degenerate(
            np.array([True]), em, tol, np.array([1], np.int64)
        )
        km = NativeKeyMap(16)
        _, status, flags = km.prepare_batch(
            b"b", np.array([0, 1], np.int64),
            np.array([[2, 1, period, 1]], np.int64),
        )
        assert status[0] == 0
        assert bool(flags & PREP_DEGEN) == py == expect_degen, period


def test_cur_variant_matches_compact(nondegen_batch):
    """compact="cur" (one i64/request, host-finished) must reproduce the
    4-plane compact wire output bit-for-bit and leave identical state."""
    from throttlecrab_tpu.tpu.kernel import finish_cur, fits_cur_wire

    slots, rank, is_last, em, tol, q, valid = nondegen_batch
    assert fits_cur_wire(tol, BASE + 30 * NS)
    st1 = make_table()
    st2 = make_table()
    for now in (BASE, BASE, BASE + 2 * NS, BASE + 30 * NS):
        st1, out_c = run(
            st1, *nondegen_batch, now, with_degen=False, compact=True
        )
        st2, cur2 = run(
            st2, *nondegen_batch, now, with_degen=False, compact="cur"
        )
        cur2 = np.asarray(cur2)
        assert cur2.dtype == np.int64 and cur2.shape == (64,)
        out_c = np.asarray(out_c)
        al, rem, res, ret = finish_cur(cur2, em, tol, q, now)
        np.testing.assert_array_equal(al, out_c[0])
        np.testing.assert_array_equal(rem, out_c[1])
        np.testing.assert_array_equal(res, out_c[2])
        np.testing.assert_array_equal(ret, out_c[3])
    np.testing.assert_array_equal(np.asarray(st1)[:64], np.asarray(st2)[:64])


def test_cur_variant_negative_cur_roundtrip():
    """A denied fresh segment at a virtual now=0 clock observes
    cur = t0 = -emission < 0 (quantity 3 against burst 2 → m_raw = 0, all
    denied); the *2+allowed packing must survive the sign (arithmetic
    shift decode) and still finish exactly."""
    from throttlecrab_tpu.tpu.kernel import finish_cur

    B = 8
    slots = np.arange(B, dtype=np.int32)
    rank = np.zeros(B, np.int32)
    is_last = np.ones(B, bool)
    em = np.full(B, 600_000_000, np.int64)
    tol = em.copy()  # burst 2
    q = np.full(B, 3, np.int64)  # inc = 3*em > now + tol → m_raw = 0
    valid = np.ones(B, bool)
    batch = (slots, rank, is_last, em, tol, q, valid)
    for now in (0, 1):
        st1, out_c = run(
            make_table(), *batch, now, with_degen=False, compact=True
        )
        st2, cur2 = run(
            make_table(), *batch, now, with_degen=False, compact="cur"
        )
        cur2 = np.asarray(cur2)
        assert (cur2 >> 1).min() < 0  # the negative case actually occurs
        assert not (np.asarray(out_c)[0]).any()  # and everything is denied
        al, rem, res, ret = finish_cur(cur2, em, tol, q, now)
        out_c = np.asarray(out_c)
        np.testing.assert_array_equal(al, out_c[0])
        np.testing.assert_array_equal(rem, out_c[1])
        np.testing.assert_array_equal(res, out_c[2])
        np.testing.assert_array_equal(ret, out_c[3])


def test_native_finish_matches_numpy(nondegen_batch):
    """C++ tk_finish == kernel.finish_cur on the same packed rows."""
    from throttlecrab_tpu.native import toolchain_available

    if not toolchain_available():
        import pytest

        pytest.skip("no C++ toolchain")
    from throttlecrab_tpu.native import NativeKeyMap
    from throttlecrab_tpu.tpu.kernel import finish_cur, pack_requests

    slots, rank, is_last, em, tol, q, valid = nondegen_batch
    st = make_table()
    now = BASE + 5 * NS
    st, cur2 = run(
        st, *nondegen_batch, now, with_degen=False, compact="cur"
    )
    cur2 = np.asarray(cur2)
    packed = pack_requests(slots, rank, is_last, em, tol, q, valid)
    km = NativeKeyMap(16)
    out = km.finish(packed, cur2, now)
    al, rem, res, ret = finish_cur(cur2, em, tol, q, now)
    np.testing.assert_array_equal(out[:, 0], al)
    np.testing.assert_array_equal(out[:, 1], rem)
    np.testing.assert_array_equal(out[:, 2], res)
    np.testing.assert_array_equal(out[:, 3], ret)


def test_fits_cur_wire_bounds():
    from throttlecrab_tpu.tpu.kernel import fits_cur_wire

    assert fits_cur_wire(np.array([0, (1 << 61) - 1], np.int64), (1 << 61) - 1)
    assert not fits_cur_wire(np.array([1 << 61], np.int64), BASE)
    assert not fits_cur_wire(np.array([1], np.int64), 1 << 61)
    assert fits_cur_wire(np.array([], np.int64), BASE)  # empty batch


def test_byid_word_path_masks_unresolved_slot():
    """Both by-id kernels must treat an id row carrying slot -1 (the
    resolve_all marker for a full table) as invalid, even when the
    request word's valid bit is set — never clip it onto slot 0 and
    corrupt another key's bucket (ADVICE r4)."""
    from throttlecrab_tpu.tpu.kernel import (
        IDROW_WIDTH,
        gcra_scan_byid,
        gcra_scan_ids,
        pack_id_rows,
        unpack_state,
    )

    # Distinct emission for the unresolved id: a clipped-to-slot-0 write
    # from it would land a visibly different TAT than id 0's own.
    em = np.array([600_000_000, 5_000_000_000], np.int64)
    tol = em * 8
    rows = pack_id_rows(np.array([0, -1], np.int32), em, tol)
    assert rows.shape[1] == IDROW_WIDTH

    def word(idx, rank=0, is_last=True, valid=True):
        meta = rank | (int(is_last) << 14) | (int(valid) << 15)
        return np.int64(idx | (meta << 32))

    for scan, reqs in (
        (gcra_scan_byid, np.array([[word(0), word(1)]], np.int64)),
        (gcra_scan_ids, np.array([[0, 1]], np.int32)),
    ):
        state = pack_state(
            jnp.zeros((64,), jnp.int64),
            jnp.full((64,), EMPTY_EXPIRY, jnp.int64),
        )
        state, out = scan(
            state, jnp.asarray(rows), jnp.asarray(reqs),
            np.array([BASE], np.int64), 2,
        )
        out = np.asarray(out)
        tat, _ = unpack_state(np.asarray(state))
        tat = np.asarray(tat)
        # id 0 decided normally against slot 0...
        assert out[0, 0, 0] == 1
        # ...and the unresolved id 1 is invalid: denied, no state write.
        assert out[0, 0, 1] == 0
        # Slot 0 holds exactly id 0's own advance (first touch, q=2:
        # now - em + 2*em); a clipped write from id 1 would differ by em.
        assert tat[0] == BASE + em[0]
        # No other REAL slot is touched (suppressed writes are absorbed
        # by the scratch tail at the high end of the state array).
        assert (tat[1:32] == 0).all()


def test_w32_variant_matches_compact(nondegen_batch):
    """compact="w32" (one i32/request, device-packed wire values) must
    reproduce the 4-plane compact output bit-for-bit under its
    certificate and leave identical state."""
    from throttlecrab_tpu.tpu.kernel import finish_w32, fits_w32_wire

    slots, rank, is_last, em, tol, q, valid = nondegen_batch
    assert fits_w32_wire(valid, em, tol, q, BASE + 30 * NS, int(tol.max()))
    st1 = make_table()
    st2 = make_table()
    for now in (BASE, BASE, BASE + 2 * NS, BASE + 30 * NS):
        st1, out_c = run(
            st1, *nondegen_batch, now, with_degen=False, compact=True
        )
        st2, w = run(
            st2, *nondegen_batch, now, with_degen=False, compact="w32"
        )
        w = np.asarray(w)
        assert w.dtype == np.int32 and w.shape == (64,)
        out_c = np.asarray(out_c)
        al, rem, res, ret = finish_w32(w)
        np.testing.assert_array_equal(al, out_c[0])
        np.testing.assert_array_equal(rem, out_c[1])
        np.testing.assert_array_equal(res, out_c[2])
        np.testing.assert_array_equal(ret, out_c[3])
    np.testing.assert_array_equal(np.asarray(st1)[:64], np.asarray(st2)[:64])


def test_w32_field_edges_roundtrip():
    """Wire values driven to their field maxima (remaining near 1023,
    reset_s near 2047, retry_s > 0) survive the 32-bit packing exactly;
    parameters past the bounds fail the certificate."""
    from throttlecrab_tpu.tpu.kernel import (
        W32_REM_MAX,
        W32_RESET_MAX,
        finish_w32,
        fits_w32_wire,
    )

    B = 8
    slots = np.arange(B, dtype=np.int32)
    rank = np.zeros(B, np.int32)
    is_last = np.ones(B, bool)
    # burst 500 → fresh remaining 499; em 1s, tol 499s → reset ~500s.
    # (The certificate's remaining bound is ~2x burst — a nearly-expired
    # bucket's room approaches 2*tol — so burst 500 is the class of
    # largest bursts w32 accepts: 2*499 = 998 <= 1023.)
    em = np.full(B, NS, np.int64)
    tol = em * 499
    q = np.full(B, 1, np.int64)
    valid = np.ones(B, bool)
    assert fits_w32_wire(valid, em, tol, q, BASE, int(tol.max()))
    st1, out_c = run(
        make_table(), slots, rank, is_last, em, tol, q, valid, BASE,
        with_degen=False, compact=True,
    )
    st2, w = run(
        make_table(), slots, rank, is_last, em, tol, q, valid, BASE,
        with_degen=False, compact="w32",
    )
    out_c = np.asarray(out_c)
    al, rem, res, ret = finish_w32(np.asarray(w))
    assert rem.max() == 499  # fresh-bucket headroom at the largest
    assert res.max() >= 499  # reset holds whole seconds, not clipped
    np.testing.assert_array_equal(al, out_c[0])
    np.testing.assert_array_equal(rem, out_c[1])
    np.testing.assert_array_equal(res, out_c[2])
    np.testing.assert_array_equal(ret, out_c[3])

    # remaining bound: burst 2000 → 1999 > W32_REM_MAX: must refuse.
    assert not fits_w32_wire(
        valid, em, em * 1999, q, BASE, int(em[0] * 1999)
    )
    # reset bound: tol 1100s twice over > W32_RESET_MAX seconds: refuse.
    big = em * 1100
    assert (2 * 1100) > W32_RESET_MAX
    assert not fits_w32_wire(valid, em * 100, big, q, BASE, int(big[0]))
    # A huge tolerance on an INVALID lane must not matter.
    tol_mixed = tol.copy()
    tol_mixed[3] = 1 << 62
    v_mixed = valid.copy()
    v_mixed[3] = False
    assert fits_w32_wire(v_mixed, em, tol_mixed, q, BASE, int(tol.max()))
    assert W32_REM_MAX == 1023 and W32_RESET_MAX == 2047


def test_w32_refuses_valid_bigtol_lane():
    """Regression (ADVICE high): a VALID lane with tol >= 2^62 used to
    wrap the certificate's int64 bound sums negative — tol + max(em, tol)
    = 2^63 overflows — falsely certifying w32 for a lane whose true
    reset is orders of magnitude past the 2047 s field (and whose stored
    TAT >= 2^62 would corrupt cur_safe for later launches).  The bound
    math must refuse at tol >= 2^61 before any arithmetic can wrap."""
    from throttlecrab_tpu.tpu.kernel import fits_w32_wire

    B = 4
    em = np.full(B, NS, np.int64)
    tol = em * 499  # in-field lanes: reset ~500 s < 2047 s
    q = np.full(B, 1, np.int64)
    valid = np.ones(B, bool)
    # burst 5e6 at em 1000 s: a legal request whose tol crosses 2^62.
    big = tol.copy()
    em_big = em.copy()
    em_big[2] = 1000 * NS
    big[2] = em_big[2] * 5_000_000
    assert int(big[2]) >= (1 << 62)
    assert not fits_w32_wire(valid, em_big, big, q, BASE, 0)
    # The exact refusal threshold is 2^61 (the wrap-free safety bound).
    at_edge = tol.copy()
    at_edge[2] = 1 << 61
    assert not fits_w32_wire(valid, em, at_edge, q, BASE, 0)
    below = tol.copy()
    below[2] = (1 << 61) - 1
    # Below the wrap bound the field-width checks decide (and a ~73-year
    # tolerance overflows the 2047 s reset field anyway): still refused,
    # but by the right check, without int64 wrap.
    assert not fits_w32_wire(valid, em, below, q, BASE, 0)
    # Sanity: the small-tol batch alone still certifies.
    assert fits_w32_wire(valid, em, tol, q, BASE, int(tol.max()))


def test_w32_respects_cross_launch_tol_hwm():
    """A stored TAT from an earlier big-tolerance launch can push a later
    launch's reset_s past the field width; the tol_hwm term in the
    certificate must force the fallback, and the fallback values must
    match the 4-plane path (differential on the same key)."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    T = 1_753_700_000 * NS
    lim = TpuRateLimiter(capacity=256)
    twin = TpuRateLimiter(capacity=256)
    # burst 4000 with em 1s → tol ~3999s: valid, exceeds w32 widths →
    # the launch itself is cur/4-plane, and tol_hwm records ~3999s.
    for L in (lim, twin):
        r = L.rate_limit_batch(["k"], 4000, 60, 60, 3999, T, wire=True)
        assert bool(r.allowed[0])
    assert lim.table.tol_hwm >= 3000 * NS

    # Small-tol traffic on the SAME key: its stored TAT is ~T + 3999s,
    # so reset_s ≈ 4000 s > 2047 — w32 must NOT be chosen.
    h = lim.dispatch_many([(["k"], 10, 100, 60, 1, T + NS)], wire=True)
    assert not getattr(h, "_w32", True)
    res = h.fetch()[0]
    ref = twin.rate_limit_batch(["k"], 10, 100, 60, 1, T + NS, wire=True)
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_s)
    assert int(res.reset_after_s[0]) > 2047  # the field would have clipped


def test_w32_refuses_clock_regression():
    """A launch timestamped earlier than a prior launch can carry
    reset_s past the w32 field width (stored TAT ~ prior now + tol);
    the now_hwm guard must forfeit w32 and the fallback must match the
    4-plane twin exactly."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    T = 1_753_700_000 * NS
    lim = TpuRateLimiter(capacity=128)
    twin = TpuRateLimiter(capacity=128)
    # Fill "k" at a LATER clock with tol ~1000s (w32-certifiable).
    for L in (lim, twin):
        r = L.rate_limit_batch(
            ["k"], 1000, 60, 60, 999, T + 3600 * NS, wire=True
        )
        assert bool(r.allowed[0])
    assert lim.table.now_hwm == T + 3600 * NS

    # Regressed clock: stored TAT ~ T+4600s → reset_s ~ 4600 > 2047.
    h = lim.dispatch_many([(["k"], 10, 100, 60, 1, T)], wire=True)
    assert not getattr(h, "_w32", True)
    res = h.fetch()[0]
    ref = twin.rate_limit_batch(["k"], 10, 100, 60, 1, T, wire=True)
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_s)
    assert int(res.reset_after_s[0]) > 2047  # would not have fit w32


def test_w32_snapshot_restore_carries_tol_hwm(tmp_path):
    """Restored state must carry its write-time tolerances into the
    restored table's tol_hwm (recovered as expiry - tat), or a later
    small-tol w32 launch would wrap its reset field against the
    restored TATs."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter
    from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

    T = 1_753_700_000 * NS
    lim = TpuRateLimiter(capacity=128)
    # tol ~2099s: past the w32 reset field on its own, so any restored
    # TAT near T + 2099s forces the fallback for small-tol traffic too.
    r = lim.rate_limit_batch(["k"], 2100, 60, 60, 2099, T, wire=True)
    assert bool(r.allowed[0])
    path = tmp_path / "bigtol.npz"
    save_snapshot(lim, path)

    lim2 = TpuRateLimiter(capacity=128)
    assert load_snapshot(lim2, path, now_ns=T + NS) == 1
    assert lim2.table.tol_hwm >= 2000 * NS  # write-time tol recovered

    twin = TpuRateLimiter(capacity=128)
    twin.rate_limit_batch(["k"], 2100, 60, 60, 2099, T, wire=True)
    h = lim2.dispatch_many([(["k"], 10, 100, 60, 1, T + NS)], wire=True)
    assert not getattr(h, "_w32", True)
    res = h.fetch()[0]
    ref = twin.rate_limit_batch(["k"], 10, 100, 60, 1, T + NS, wire=True)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.remaining, ref.remaining)


def test_w32_snapshot_restore_carries_writer_clock(tmp_path):
    """A snapshot written at a LATER clock embeds the writer's now in
    its TATs; a reader whose clock lags must not take w32 (reset would
    overflow its field by the skew).  Restore seeds now_hwm with the
    max restored TAT, so w32 stays off until the reader catches up."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter
    from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

    T = 1_753_700_000 * NS
    writer = TpuRateLimiter(capacity=128)
    twin = TpuRateLimiter(capacity=128)
    later = T + 5000 * NS
    for L in (writer, twin):
        r = L.rate_limit_batch(["k"], 10, 100, 60, 1, later, wire=True)
        assert bool(r.allowed[0])
    path = tmp_path / "skew.npz"
    save_snapshot(writer, path)

    reader = TpuRateLimiter(capacity=128)
    assert load_snapshot(reader, path, now_ns=later) == 1
    assert reader.table.now_hwm >= later  # writer clock recovered

    # Reader's clock lags the writer by ~5000 s: w32 must be refused
    # and the values must match the never-snapshotted twin at the same
    # (skewed) timestamp.
    h = reader.dispatch_many([(["k"], 10, 100, 60, 1, T)], wire=True)
    assert not getattr(h, "_w32", True)
    res = h.fetch()[0]
    ref = twin.rate_limit_batch(["k"], 10, 100, 60, 1, T, wire=True)
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_s)
    assert int(res.reset_after_s[0]) > 2047  # the skew-inflated value
