"""Fast (no-degen) and compact kernel variants vs the exact kernel."""

import numpy as np
import pytest

import jax.numpy as jnp

from throttlecrab_tpu.tpu.kernel import EMPTY_EXPIRY, gcra_batch, pack_state

NS = 1_000_000_000
BASE = 1_753_700_000 * NS
N = 512  # includes scratch tail for the 64-request batches below


def make_table():
    return pack_state(
        jnp.zeros((N,), jnp.int64),
        jnp.full((N,), EMPTY_EXPIRY, jnp.int64),
    )


def run(state, slots, rank, is_last, em, tol, q, valid, now, **kw):
    return gcra_batch(
        state,
        jnp.asarray(slots, jnp.int32), jnp.asarray(rank, jnp.int32),
        jnp.asarray(is_last, bool), jnp.asarray(em, jnp.int64),
        jnp.asarray(tol, jnp.int64), jnp.asarray(q, jnp.int64),
        jnp.asarray(valid, bool), now, **kw,
    )


@pytest.fixture
def nondegen_batch():
    rng = np.random.RandomState(7)
    B = 64
    slots = rng.randint(0, 32, B).astype(np.int32)
    # Host-style segment info.
    rank = np.zeros(B, np.int32)
    is_last = np.ones(B, bool)
    seen: dict = {}
    for i in range(B):
        s = int(slots[i])
        if s in seen:
            rank[i] = seen[s][0]
            seen[s][0] += 1
            is_last[seen[s][1]] = False
            seen[s][1] = i
        else:
            seen[s] = [1, i]
    em = np.full(B, 600_000_000, np.int64)
    tol = em * rng.randint(1, 9, B)  # burst >= 2 → tol > 0
    q = rng.randint(1, 3, B).astype(np.int64)
    # Uniform (em, tol, q) per slot, as the engine guarantees.
    for i in range(B):
        first = [j for j in range(B) if slots[j] == slots[i]][0]
        tol[i] = tol[first]
        q[i] = q[first]
    valid = np.ones(B, bool)
    return slots, rank, is_last, em, tol, q, valid


def test_fast_variant_matches_exact(nondegen_batch):
    st1 = make_table()
    st2 = make_table()
    for now in (BASE, BASE + NS, BASE + 30 * NS):
        st1, out_e = run(st1, *nondegen_batch, now)
        st2, out_f = run(st2, *nondegen_batch, now, with_degen=False)
        np.testing.assert_array_equal(np.asarray(out_e), np.asarray(out_f))
    # Real-slot rows identical (scratch tail may differ by construction).
    np.testing.assert_array_equal(np.asarray(st1)[:64], np.asarray(st2)[:64])


def test_compact_variant_truncates_to_seconds(nondegen_batch):
    st1 = make_table()
    st2 = make_table()
    outs_e, outs_c = [], []
    for now in (BASE, BASE, BASE + 2 * NS):
        st1, out_e = run(st1, *nondegen_batch, now)
        st2, out_c = run(st2, *nondegen_batch, now, compact=True)
        outs_e.append(np.asarray(out_e))
        outs_c.append(np.asarray(out_c))
    for out_e, out_c in zip(outs_e, outs_c):
        assert out_c.dtype == np.int32
        np.testing.assert_array_equal(out_c[0], out_e[0].astype(np.int32))
        np.testing.assert_array_equal(out_c[1], out_e[1].astype(np.int32))
        np.testing.assert_array_equal(out_c[2], (out_e[2] // NS).astype(np.int32))
        np.testing.assert_array_equal(out_c[3], (out_e[3] // NS).astype(np.int32))
    # Real-slot table state identical regardless of output format.
    np.testing.assert_array_equal(np.asarray(st1)[:64], np.asarray(st2)[:64])


def test_wrapped_negative_tolerance_certified_to_exact_path():
    """derive_params can produce a negative (wrapped) tolerance from the
    reference's truncating u64 product; such batches must be certified
    degenerate so the fast path's nonneg saturating ops are never used
    on them."""
    from throttlecrab_tpu.tpu.limiter import derive_params, has_degenerate

    # burst huge enough that emission * (burst-1) wraps negative.
    em, tol, invalid = derive_params(
        np.array([1 << 33], np.int64),
        np.array([1], np.int64),
        np.array([1 << 30], np.int64),
    )
    assert not invalid[0]
    assert tol[0] < 0  # the wrap actually happened
    assert has_degenerate(
        np.array([True]), em, tol, np.array([1], np.int64)
    )
