"""Multi-device sharded limiter tests (8 virtual CPU devices, see conftest).

The sharded engine must be observationally identical to the scalar oracle
(core.RateLimiter over a dict store): same allow/deny stream, same
remaining/reset/retry accounting, regardless of how keys hash across the
mesh.  Mirrors the reference's store-agnostic shared suite
(`store_test_suite.rs`) at the cluster level.
"""

import jax
import numpy as np

from conftest import require_devices
import pytest

from throttlecrab_tpu.core.rate_limiter import RateLimiter
from throttlecrab_tpu.core.store.periodic import PeriodicStore
from throttlecrab_tpu.parallel import ShardedTpuRateLimiter, shard_of_key
from throttlecrab_tpu.parallel.sharded import make_mesh

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


@pytest.fixture(scope="module")
def mesh():
    require_devices(8)  # single-chip THROTTLECRAB_TPU_TEST_REAL runs skip
    return make_mesh(8)


@pytest.fixture()
def limiter(mesh):
    return ShardedTpuRateLimiter(capacity_per_shard=256, mesh=mesh)


def oracle():
    return RateLimiter(PeriodicStore())


def test_keys_spread_across_shards():
    ids = {shard_of_key(f"key-{i}".encode(), 8) for i in range(256)}
    assert len(ids) == 8  # CRC32 routing actually uses the whole mesh


def test_scalar_parity_across_shards(limiter):
    ora = oracle()
    for i in range(40):
        key = f"user-{i % 7}"
        now = T0 + i * 137_000_000
        got = limiter.rate_limit(key, 3, 10, 60, 1, now)
        want = ora.rate_limit(key, 3, 10, 60, 1, now)
        assert got == want, f"step {i} key {key}"


def test_batch_parity_uniform_params(limiter):
    ora = oracle()
    rng = np.random.default_rng(42)
    keys = [f"k{int(x)}" for x in rng.integers(0, 50, 300)]
    now = T0
    res = limiter.rate_limit_batch(keys, 5, 100, 60, 1, now)
    for i, key in enumerate(keys):
        allowed, r = ora.rate_limit(key, 5, 100, 60, 1, now)
        assert bool(res.allowed[i]) == allowed, f"req {i} key {key}"
        assert int(res.remaining[i]) == r.remaining
        assert int(res.reset_after_ns[i]) == r.reset_after_ns
        assert int(res.retry_after_ns[i]) == r.retry_after_ns


def test_batch_parity_heterogeneous_params(limiter):
    ora = oracle()
    rng = np.random.default_rng(7)
    n = 200
    keys = [f"k{int(x)}" for x in rng.integers(0, 30, n)]
    burst = rng.integers(1, 6, n)
    count = rng.integers(1, 50, n)
    period = rng.integers(1, 120, n)
    qty = rng.integers(0, 3, n)
    now = T0
    res = limiter.rate_limit_batch(keys, burst, count, period, qty, now)
    for i, key in enumerate(keys):
        allowed, r = ora.rate_limit(
            key, int(burst[i]), int(count[i]), int(period[i]), int(qty[i]), now
        )
        assert bool(res.allowed[i]) == allowed, f"req {i}"
        assert int(res.remaining[i]) == r.remaining, f"req {i}"


def test_psum_counters_are_global(limiter):
    keys = [f"c{i}" for i in range(64)]
    res = limiter.rate_limit_batch(keys, 1, 1, 60, 2, T0)
    # quantity 2 > burst 1: every request denied.
    assert not res.allowed.any()
    assert limiter.total_allowed == 0
    assert limiter.total_denied == 64
    res = limiter.rate_limit_batch(keys, 10, 10, 60, 1, T0)
    assert res.allowed.all()
    assert limiter.total_allowed == 64


def test_sweep_frees_across_all_shards(limiter):
    keys = [f"s{i}" for i in range(80)]
    limiter.rate_limit_batch(keys, 2, 10, 1, 1, T0)
    assert len(limiter) == 80
    freed = limiter.sweep(T0 + 3600 * NS)
    assert freed == 80
    assert len(limiter) == 0


def test_duplicate_keys_serialize_within_batch(limiter):
    # 20 hits on one key with burst 10 in a single batch: exactly 10 allowed.
    keys = ["dup"] * 20
    res = limiter.rate_limit_batch(keys, 10, 100, 3600, 1, T0)
    assert int(res.allowed.sum()) == 10
    assert res.allowed[:10].all() and not res.allowed[10:].any()


def test_param_change_mid_batch(limiter):
    ora = oracle()
    keys = ["p", "p", "p", "p"]
    burst = [5, 5, 2, 2]
    count = [10, 10, 10, 10]
    period = [60, 60, 60, 60]
    qty = [1, 1, 1, 1]
    res = limiter.rate_limit_batch(keys, burst, count, period, qty, T0)
    for i in range(4):
        allowed, r = ora.rate_limit(
            "p", burst[i], count[i], period[i], qty[i], T0
        )
        assert bool(res.allowed[i]) == allowed, f"req {i}"
        assert int(res.remaining[i]) == r.remaining, f"req {i}"


def test_invalid_requests_do_not_poison_batch(limiter):
    keys = ["a", "b", "c"]
    res = limiter.rate_limit_batch(keys, [5, -1, 5], 10, 60, [1, 1, -2], T0)
    assert res.status[0] == 0
    assert res.status[1] != 0
    assert res.status[2] != 0
    assert res.allowed[0] and not res.allowed[1] and not res.allowed[2]


def test_table_grow_preserves_state(mesh):
    lim = ShardedTpuRateLimiter(capacity_per_shard=4, mesh=mesh)
    # Exhaust burst for one key, then overflow capacity to force growth.
    for _ in range(3):
        lim.rate_limit("grow-key", 3, 10, 3600, 1, T0)
    keys = [f"g{i}" for i in range(200)]
    lim.rate_limit_batch(keys, 3, 10, 3600, 1, T0)
    # State must survive the reallocation: the key is still exhausted.
    allowed, _ = lim.rate_limit("grow-key", 3, 10, 3600, 1, T0 + 1)
    assert not allowed
