"""Native C++ RESP transport tests: same wire behavior as the asyncio
transport (test_transports.py), driven over real sockets."""

import asyncio

import pytest

from throttlecrab_tpu.native import (
    toolchain_available,
    wire_available,
    wire_build_error,
)
from throttlecrab_tpu.server.metrics import Metrics
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

# A broken build with a compiler present is a bug, not an environment gap:
# fail the whole module loudly instead of skipping.
if not wire_available() and toolchain_available():
    pytest.fail(
        "C++ wire server failed to build with g++ present:\n"
        f"{wire_build_error()}",
        pytrace=False,
    )
pytestmark = pytest.mark.skipif(
    not wire_available(),
    reason=f"no C++ toolchain for the wire server: {wire_build_error()}",
)

T0 = 1_700_000_000 * 1_000_000_000


def make_transport(**kwargs):
    from throttlecrab_tpu.server.native_redis import NativeRedisTransport

    metrics = Metrics(max_denied_keys=10)
    limiter = TpuRateLimiter(capacity=1024)
    transport = NativeRedisTransport(
        "127.0.0.1", 0, limiter, metrics,
        batch_size=kwargs.pop("batch_size", 64),
        max_linger_us=kwargs.pop("max_linger_us", 500),
        now_fn=lambda: T0,
        **kwargs,
    )
    return transport, metrics


async def resp_command(reader, writer, *parts):
    frame = b"*%d\r\n" % len(parts)
    for part in parts:
        data = part.encode() if isinstance(part, str) else part
        frame += b"$%d\r\n%s\r\n" % (len(data), data)
    writer.write(frame)
    await writer.drain()
    return await asyncio.wait_for(reader.read(4096), timeout=5.0)


def test_native_ping_throttle_quit():
    async def main():
        transport, metrics = make_transport()
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)

        assert await resp_command(reader, writer, "PING") == b"+PONG\r\n"
        assert await resp_command(reader, writer, "PING", "hey") == (
            b"$3\r\nhey\r\n"
        )
        out = await resp_command(reader, writer, "throttle", "nk", "3",
                                 "10", "60")
        assert out == b"*5\r\n:1\r\n:3\r\n:2\r\n:12\r\n:0\r\n"
        for _ in range(2):
            out = await resp_command(reader, writer, "THROTTLE", "nk", "3",
                                     "10", "60")
        assert out.startswith(b"*5\r\n:1\r\n")
        out = await resp_command(reader, writer, "THROTTLE", "nk", "3",
                                 "10", "60")
        assert out.startswith(b"*5\r\n:0\r\n")  # exhausted

        assert await resp_command(reader, writer, "QUIT") == b"+OK\r\n"
        assert await reader.read(16) == b""

        await transport.stop()
        return metrics

    metrics = asyncio.run(main())
    assert metrics.requests_total == 4
    assert metrics.requests_denied == 1


def test_native_error_cases():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        out = await resp_command(reader, writer, "BOGUS")
        assert out == b"-ERR unknown command 'BOGUS'\r\n"
        out = await resp_command(reader, writer, "THROTTLE", "k")
        assert b"wrong number of arguments" in out
        out = await resp_command(reader, writer, "THROTTLE", "k", "x",
                                 "10", "60")
        assert out == b"-ERR invalid max_burst\r\n"
        # Engine-level validation error surfaces as -ERR.
        out = await resp_command(reader, writer, "THROTTLE", "k", "-5",
                                 "10", "60")
        assert out == b"-ERR invalid rate limit parameters\r\n"
        # Quantity arg.
        out = await resp_command(reader, writer, "THROTTLE", "qk", "10",
                                 "100", "60", "5")
        assert out == b"*5\r\n:1\r\n:10\r\n:5\r\n:7\r\n:0\r\n"
        writer.close()
        await transport.stop()

    asyncio.run(main())


def test_native_pipelined_commands():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        one = b"*4\r\n$8\r\nTHROTTLE\r\n$2\r\npk\r\n$2\r\n10\r\n$3\r\n100\r\n"
        # Malformed on purpose? No: THROTTLE needs 4-5 args after the name;
        # build a full valid frame instead.
        one = (b"*5\r\n$8\r\nTHROTTLE\r\n$2\r\npk\r\n$2\r\n10\r\n"
               b"$3\r\n100\r\n$2\r\n60\r\n")
        writer.write(one * 20)  # 20 pipelined commands in one write
        await writer.drain()
        data = b""
        while data.count(b"*5\r\n") < 20:
            chunk = await asyncio.wait_for(reader.read(8192), timeout=5.0)
            if not chunk:
                break
            data += chunk
        writer.close()
        await transport.stop()
        return data

    data = asyncio.run(main())
    assert data.count(b"*5\r\n:1\r\n") == 10  # burst 10
    assert data.count(b"*5\r\n:0\r\n") == 10  # the rest denied


def test_native_partial_frames():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        frame = b"*1\r\n$4\r\nPING\r\n"
        writer.write(frame[:6])
        await writer.drain()
        await asyncio.sleep(0.05)
        writer.write(frame[6:])
        await writer.drain()
        out = await asyncio.wait_for(reader.read(64), timeout=5.0)
        writer.close()
        await transport.stop()
        return out

    assert asyncio.run(main()) == b"+PONG\r\n"


def test_native_protocol_attack_vectors():
    async def main():
        outs = []
        for payload in (
            b"*999999999999\r\n",
            b"!inline\r\n",
            b"*1\r\n$99999999999999\r\n",
        ):
            transport, _ = make_transport()
            await transport.start()
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", transport.bound_port
            )
            writer.write(payload)
            await writer.drain()
            outs.append(
                await asyncio.wait_for(reader.read(256), timeout=5.0)
            )
            writer.close()
            await transport.stop()
        return outs

    for out in asyncio.run(main()):
        assert out.startswith(b"-ERR")


def _frame(*parts):
    """RESP array frame; None parts encode as null bulk strings ($-1)."""
    frame = b"*%d\r\n" % len(parts)
    for part in parts:
        if part is None:
            frame += b"$-1\r\n"
        else:
            data = part.encode() if isinstance(part, str) else part
            frame += b"$%d\r\n%s\r\n" % (len(data), data)
    return frame


def test_native_pipelined_inline_after_throttle_stays_ordered():
    """A PING pipelined behind a THROTTLE must answer after it: inline
    replies wait for the driver-answered slots ahead of them."""

    async def main():
        transport, _ = make_transport()
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        writer.write(
            _frame("THROTTLE", "ok1", "10", "100", "60") + _frame("PING")
        )
        await writer.drain()
        data = b""
        while b"+PONG\r\n" not in data:
            chunk = await asyncio.wait_for(reader.read(4096), timeout=5.0)
            assert chunk, f"connection closed early: {data!r}"
            data += chunk
        writer.close()
        await transport.stop()
        return data

    data = asyncio.run(main())
    assert data.index(b"*5\r\n:1\r\n") < data.index(b"+PONG\r\n")


def test_native_quit_waits_for_pipelined_throttle():
    """QUIT pipelined behind THROTTLEs must deliver their responses, then
    +OK, then close — not close early and drop them."""

    async def main():
        transport, _ = make_transport()
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        writer.write(
            _frame("THROTTLE", "qk1", "10", "100", "60")
            + _frame("THROTTLE", "qk2", "10", "100", "60")
            + _frame("QUIT")
        )
        await writer.drain()
        data = b""
        while True:
            chunk = await asyncio.wait_for(reader.read(4096), timeout=5.0)
            if not chunk:
                break
            data += chunk
        await transport.stop()
        return data

    data = asyncio.run(main())
    assert data.count(b"*5\r\n:1\r\n") == 2
    assert data.endswith(b"+OK\r\n")


def test_native_half_close_still_delivers_pipelined_responses():
    """Client pipelines THROTTLE+THROTTLE+QUIT then shutdown(SHUT_WR)
    (printf | nc style): all responses and the +OK must still arrive —
    EOF with pending slots must not drop the connection early."""
    import socket as socket_mod

    async def main():
        transport, _ = make_transport()
        await transport.start()
        loop = __import__("asyncio").get_running_loop()

        def client():
            s = socket_mod.create_connection(
                ("127.0.0.1", transport.bound_port), 5
            )
            s.sendall(
                _frame("THROTTLE", "hc1", "10", "100", "60")
                + _frame("THROTTLE", "hc2", "10", "100", "60")
                + _frame("QUIT")
            )
            s.shutdown(socket_mod.SHUT_WR)  # half-close before reading
            s.settimeout(5)
            data = b""
            while True:
                try:
                    chunk = s.recv(4096)
                except socket_mod.timeout:
                    break
                if not chunk:
                    break
                data += chunk
            s.close()
            return data

        data = await loop.run_in_executor(None, client)
        await transport.stop()
        return data

    data = asyncio.run(main())
    assert data.count(b"*5\r\n:1\r\n") == 2
    assert data.endswith(b"+OK\r\n")


def test_native_null_bulk_arguments_rejected():
    async def main():
        transport, _ = make_transport()
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )

        async def roundtrip(frame):
            writer.write(frame)
            await writer.drain()
            return await asyncio.wait_for(reader.read(4096), timeout=5.0)

        outs = {
            "null_key": await roundtrip(
                _frame("THROTTLE", None, "10", "100", "60")
            ),
            "null_cmd": await roundtrip(_frame(None, "x")),
            "null_burst": await roundtrip(
                _frame("THROTTLE", "k", None, "100", "60")
            ),
            "null_ping": await roundtrip(_frame("PING", None)),
        }
        writer.close()
        await transport.stop()
        return outs

    outs = asyncio.run(main())
    assert outs["null_key"] == b"-ERR invalid key\r\n"
    assert outs["null_cmd"] == b"-ERR invalid command format\r\n"
    assert outs["null_burst"] == b"-ERR invalid max_burst\r\n"
    assert outs["null_ping"] == b"$-1\r\n"  # echoes null like asyncio


def test_native_concurrent_clients_share_limits():
    async def main():
        transport, metrics = make_transport()
        await transport.start()
        port = transport.bound_port

        async def client(n):
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            allowed = 0
            for _ in range(n):
                out = await resp_command(reader, writer, "THROTTLE",
                                         "shared", "20", "100", "3600")
                allowed += out.startswith(b"*5\r\n:1\r\n")
            writer.close()
            return allowed

        counts = await asyncio.gather(*[client(10) for _ in range(4)])
        await transport.stop()
        return counts

    counts = asyncio.run(main())
    assert sum(counts) == 20  # burst 20 across 40 attempts on 4 conns


def test_stop_wakes_parked_driver_promptly():
    """Drain-correct shutdown: with a huge linger the driver parks deep
    inside ws_next_batch — stop() must wake it via the C++ poison pill
    (running flag + condvar notify) and join within a bounded time, not
    sleep out the linger or silently leak the thread."""
    import time

    async def main():
        transport, _ = make_transport(max_linger_us=30_000_000)  # 30 s
        await transport.start()
        await asyncio.sleep(0.3)  # let the driver park in ws_next_batch
        t0 = time.monotonic()
        await transport.stop()
        elapsed = time.monotonic() - t0
        return elapsed, transport._driver

    elapsed, driver = asyncio.run(main())
    assert elapsed < 5.0, f"stop took {elapsed:.1f}s (linger not interrupted)"
    assert not driver.is_alive()


def test_native_http_health_reflects_supervisor_state():
    """The native HTTP wire layer serves /health from the pushed
    failure-domain state, not a hardcoded OK."""
    from throttlecrab_tpu.server.native_http import NativeHttpTransport
    from throttlecrab_tpu.server.supervisor import SupervisedLimiter

    async def http_get(port, path):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
        )
        await writer.drain()
        head = await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout=5.0
        )
        length = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                length = int(line.split(b":", 1)[1])
        body = await reader.readexactly(length)
        writer.close()
        return body

    async def main():
        metrics = Metrics()
        limiter = SupervisedLimiter(TpuRateLimiter(capacity=256))
        transport = NativeHttpTransport(
            "127.0.0.1", 0, limiter, metrics,
            batch_size=16, max_linger_us=500, now_fn=lambda: T0,
        )
        await transport.start()
        try:
            await asyncio.sleep(0.2)  # first _push_metrics ran
            ok_body = await http_get(transport.bound_port, "/health")
            # Force the state machine into degraded and push again.
            limiter._set_state("degraded")
            transport._push_metrics()
            degraded_body = await http_get(
                transport.bound_port, "/health"
            )
            return ok_body, degraded_body
        finally:
            await transport.stop()

    ok_body, degraded_body = asyncio.run(main())
    assert ok_body == b"OK"
    assert degraded_body == b"degraded"
