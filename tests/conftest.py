"""Test harness configuration.

Unit tests run hermetically on CPU with 8 virtual XLA devices so the
multi-device sharding paths compile and execute without TPU hardware
(the driver dry-runs the multi-chip path the same way).  Benchmarks run
separately on the real chip via bench.py.
"""

import os

# Must be set before jax initialises its backends.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import throttlecrab_tpu  # noqa: E402,F401  (enables x64 before any tracing)
