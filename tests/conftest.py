"""Test harness configuration.

Unit tests run hermetically on CPU with 8 virtual XLA devices so the
multi-device sharding paths compile and execute without TPU hardware
(the driver dry-runs the multi-chip path the same way).  Benchmarks run
separately on the real chip via bench.py.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Force the CPU platform via jax.config (not the env var: accelerator PJRT
# plugins loaded from sitecustomize can re-point JAX_PLATFORMS at real
# hardware after the environment is read).  Set THROTTLECRAB_TPU_TEST_REAL=1
# to run the suite on whatever backend the environment provides instead.
import throttlecrab_tpu  # noqa: E402,F401  (enables x64 before any tracing)

if not os.environ.get("THROTTLECRAB_TPU_TEST_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def require_devices(n: int) -> None:
    """Skip the calling test when the backend exposes fewer than `n`
    devices — only happens under THROTTLECRAB_TPU_TEST_REAL on
    single-chip hardware (the default CPU harness always has 8 virtual
    devices).  make_mesh(n) raises in that situation rather than
    silently shrinking the mesh."""
    import jax
    import pytest

    have = len(jax.devices())
    if have < n:
        pytest.skip(f"needs {n} devices, backend has {have}")
