"""Crash-durability tests (persist/): checkpoint format, generation
chains, torn-write recovery fallback, fault modes on the snapshot site,
and the SIGKILL-mid-checkpoint soak with an over-allow-only differential
against a scalar oracle.

The safety argument under test everywhere: restored TATs are only ever
*older* than live state was, and GCRA clamps an old TAT up to `now` —
so a stale checkpoint, a torn generation, or a dropped delta is strictly
over-allow-only.  Recovery may forget spends; it must never manufacture
a deny the live server would not have issued.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from conftest import require_devices
from throttlecrab_tpu.persist import (
    Checkpointer,
    CheckpointCorrupt,
    MANIFEST_NAME,
    checkpoint_name,
    decode_checkpoint,
    encode_checkpoint,
    parse_checkpoint_name,
    read_checkpoint,
    read_manifest,
    recover_into,
    scan_chains,
)
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


def _ck(lim, directory, **kw) -> Checkpointer:
    kw.setdefault("interval_ns", 1)  # every explicit tick is due
    kw.setdefault("now_fn", lambda: T0)
    return Checkpointer(lim, directory, **kw)


def _spend(lim, key, n, t=T0, burst=3, period=3600):
    for _ in range(n):
        lim.rate_limit(key, burst, 10, period, 1, t)


# ------------------------------------------------------------------ #
# Format


def test_format_round_trip():
    keys = ["plain", b"\x00raw\xffbytes", "utf8-é"]
    tat = np.array([T0 + 1, T0 + 2, T0 + 3], np.int64)
    exp = np.array([T0 + 10, T0 + 20, T0 + 30], np.int64)
    blob = encode_checkpoint(
        "base", 7, 7, T0, 256, 1, False, keys, tat, exp
    )
    rec = decode_checkpoint(blob)
    assert rec.kind == "base"
    assert rec.generation == 7 and rec.base_generation == 7
    assert rec.created_ns == T0
    assert (rec.capacity, rec.n_shards) == (256, 1)
    assert rec.source_bytes_keys is False
    assert list(rec.tat) == list(tat) and list(rec.expiry) == list(exp)
    # Raw key bytes + flags round-trip (identity decode happens at
    # restore via translate_key, not here).
    assert rec.keys_raw[1] == b"\x00raw\xffbytes"
    assert bool(rec.key_is_bytes[1]) and not bool(rec.key_is_bytes[0])


def test_decode_rejects_every_damage_shape():
    blob = encode_checkpoint(
        "delta", 3, 0, T0, 64, 1, False,
        ["k1", "k2"],
        np.array([1, 2], np.int64), np.array([3, 4], np.int64),
    )
    # Torn prefixes at every interesting boundary.
    for cut in (0, 2, 4, 10, len(blob) // 2, len(blob) - 1):
        with pytest.raises(CheckpointCorrupt):
            decode_checkpoint(blob[:cut])
    # A single flipped body byte trips the CRC.
    flipped = bytearray(blob)
    flipped[len(blob) // 2] ^= 0x40
    with pytest.raises(CheckpointCorrupt, match="CRC"):
        decode_checkpoint(bytes(flipped))
    with pytest.raises(CheckpointCorrupt, match="magic"):
        decode_checkpoint(b"XXXX" + blob[4:])
    # Trailing garbage is torn too (length field disagrees).
    with pytest.raises(CheckpointCorrupt):
        decode_checkpoint(blob + b"junk")


def test_checkpoint_name_round_trip():
    assert checkpoint_name(42, "base") == "ckpt-000000000042-base.tck"
    assert parse_checkpoint_name("ckpt-000000000042-base.tck") == (
        42, "base",
    )
    for bad in (
        "ckpt-12-wat.tck", "snap.npz", "ckpt-xx-base.tck",
        "ckpt-1-base.tmp", "MANIFEST.json",
    ):
        assert parse_checkpoint_name(bad) is None


# ------------------------------------------------------------------ #
# Chain write + recovery


def test_base_delta_chain_round_trips_decisions(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "hot", 3)  # exhausted
    for i in range(20):
        _spend(lim, f"k{i}", 1)
    ck = _ck(lim, tmp_path)
    assert ck.checkpoint_now(T0) == 21  # base: full table
    _spend(lim, "hot2", 3)  # exhausted after the base
    ck.note_keys(["hot2"])
    assert ck.checkpoint_now(T0) == 1  # delta: just the dirty row
    assert ck.last_generation == 1

    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + NS)
    assert res is not None and res.restored == 22
    assert res.generation == 1 and res.chain == [0, 1]
    assert res.corrupt_skipped == 0 and res.used_manifest
    # Decisions continue where the chain left off: both exhausted keys
    # still deny, a singly-spent key has exactly one token spent.
    assert not lim2.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)[0]
    assert not lim2.rate_limit("hot2", 3, 10, 3600, 1, T0 + NS)[0]
    allowed, r = lim2.rate_limit("k0", 3, 10, 3600, 1, T0 + NS)
    assert allowed and r.remaining == 1


def test_delta_contains_only_dirty_rows(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    for i in range(10):
        _spend(lim, f"k{i}", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    ck.note_keys(["k3", "k7", "never-decided"])
    ck.checkpoint_now(T0)
    rec = read_checkpoint(tmp_path / checkpoint_name(1, "delta"))
    # Dirty ∩ live table: the never-decided key is simply absent.
    assert sorted(k.decode() for k in rec.keys_raw) == ["k3", "k7"]
    assert rec.base_generation == 0


def test_delta_dirty_marks_match_across_key_encodings(tmp_path):
    """Transports note wire (str) keys but a bytes-keyed keymap exports
    bytes — the delta's dirty∩table match is on canonical byte
    identity, never on Python object equality (regression: str marks
    against a native bytes keymap produced only empty deltas, so every
    incremental generation silently carried zero rows)."""
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, b"enc-a", 1)
    _spend(lim, "enc-b", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    # Note each key in the OPPOSITE encoding from how the table holds it.
    ck.note_keys(["enc-a", b"enc-b"])
    ck.checkpoint_now(T0)
    rec = read_checkpoint(tmp_path / checkpoint_name(1, "delta"))
    assert sorted(k.decode() for k in rec.keys_raw) == ["enc-a", "enc-b"]


def test_all_expired_dirty_set_still_writes_empty_delta(tmp_path):
    """No generation holes: an empty delta is a real generation, or a
    later recovery would misread the gap as a torn chain tail."""
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "a", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    ck.note_keys(["gone-key"])  # dirty, but absent from the export
    assert ck.checkpoint_now(T0) == 0
    assert (tmp_path / checkpoint_name(1, "delta")).exists()
    res = recover_into(TpuRateLimiter(capacity=256), tmp_path, T0 + NS)
    assert res.chain == [0, 1] and res.restored == 1


def test_idle_interval_writes_no_file(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "a", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    assert ck.checkpoint_now(T0) == 0  # nothing dirty, base not due
    assert not (tmp_path / checkpoint_name(1, "delta")).exists()
    assert ck.last_generation == 0


def test_recovery_corrupt_manifest_falls_back_to_scan(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "hot", 3)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    (tmp_path / MANIFEST_NAME).write_bytes(b'{"chains": [[torn')
    assert read_manifest(tmp_path) is None

    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + NS)
    assert res.restored == 1 and not res.used_manifest
    assert not lim2.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)[0]


def test_recovery_corrupt_newest_delta_drops_one_generation(tmp_path):
    """A torn newest delta costs exactly its generation: the chain
    restores one generation shorter, and the key whose newer row was
    lost comes back with its OLDER row — over-allow-only."""
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "fall", 1)  # one spend in the base
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    _spend(lim, "other", 1)
    ck.note_keys(["other"])
    ck.checkpoint_now(T0)  # delta gen 1, intact
    _spend(lim, "fall", 2)  # now exhausted...
    ck.note_keys(["fall"])
    ck.checkpoint_now(T0)  # ...captured only in delta gen 2
    path2 = tmp_path / checkpoint_name(2, "delta")
    blob = path2.read_bytes()
    path2.write_bytes(blob[: len(blob) // 2])  # torn

    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + NS)
    assert res.generation == 1 and res.chain == [0, 1]
    assert res.corrupt_skipped == 1
    # The lost generation forgot two spends of "fall": the restored row
    # must ALLOW (older TAT = more permissive), never wrongly deny.
    allowed, r = lim2.rate_limit("fall", 3, 10, 3600, 1, T0 + NS)
    assert allowed and r.remaining == 1


def test_recovery_corrupt_base_abandons_chain_for_previous(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "hot", 3)
    ck = _ck(lim, tmp_path, retain=2)
    ck.checkpoint_now(T0)
    ck.note_keys(["hot"])
    ck.checkpoint_now(T0)  # chain [0, 1]
    _spend(lim, "late", 1)
    ck.checkpoint_now(T0, force_base=True)  # chain [2]
    path2 = tmp_path / checkpoint_name(2, "base")
    path2.write_bytes(b"TCKPgarbage")

    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + NS)
    # The whole newest chain is gone; the previous chain restores.
    assert res.chain == [0, 1] and res.corrupt_skipped == 1
    assert not lim2.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)[0]
    # "late" existed only in the abandoned chain: forgotten → allowed.
    assert lim2.rate_limit("late", 3, 10, 3600, 1, T0 + NS)[0]


def test_recovery_nothing_usable_boots_empty(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "hot", 3)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    for entry in tmp_path.iterdir():
        if entry.name != MANIFEST_NAME:
            entry.write_bytes(b"\x00" * 16)
    lim2 = TpuRateLimiter(capacity=256)
    assert recover_into(lim2, tmp_path, T0 + NS) is None
    assert len(lim2) == 0


def test_recovery_missing_dir_and_empty_dir(tmp_path):
    assert recover_into(
        TpuRateLimiter(capacity=64), tmp_path / "absent", T0
    ) is None
    assert recover_into(TpuRateLimiter(capacity=64), tmp_path, T0) is None


def test_recovery_requires_empty_limiter(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "hot", 1)
    _ck(lim, tmp_path).checkpoint_now(T0)
    with pytest.raises(ValueError, match="empty"):
        recover_into(lim, tmp_path, T0 + NS)


def test_restore_time_ttl_sweep_across_chain(tmp_path):
    """Expiry gates restoration per-merged-row across base + deltas."""
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "short", 1, period=2)  # expires ~T0 + 2s
    _spend(lim, "long", 1, period=3600)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    _spend(lim, "short2", 1, t=T0 + NS, period=2)
    ck.note_keys(["short2"])
    ck.checkpoint_now(T0)

    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + 100 * NS)
    assert res.restored == 1  # both short-TTL rows swept at restore
    assert len(lim2) == 1


def test_chain_restores_across_shard_counts(tmp_path):
    """Shard topology is not part of the checkpoint contract: a chain
    written on 4 shards restores onto 2 shards and onto a single
    device — keys re-route through the target's own hash."""
    require_devices(4)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    lim = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=make_mesh(4))
    _spend(lim, "hot", 3)
    for i in range(20):
        _spend(lim, f"k{i}", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    _spend(lim, "hot2", 3)
    ck.note_keys(["hot2"])
    ck.checkpoint_now(T0)

    for target in (
        ShardedTpuRateLimiter(capacity_per_shard=128, mesh=make_mesh(2)),
        TpuRateLimiter(capacity=512),
    ):
        res = recover_into(target, tmp_path, T0 + NS)
        assert res.restored == 22
        assert not target.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)[0]
        assert not target.rate_limit("hot2", 3, 10, 3600, 1, T0 + NS)[0]


def test_retention_prunes_to_newest_chains(tmp_path):
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "a", 1)
    ck = _ck(lim, tmp_path, retain=2, mode="full")
    # full mode: every generation is a base → 5 chains written.
    for _ in range(5):
        assert ck.checkpoint_now(T0) == 1
    gens_on_disk = sorted(
        parse_checkpoint_name(e.name)[0]
        for e in tmp_path.iterdir()
        if parse_checkpoint_name(e.name) is not None
    )
    assert gens_on_disk == [3, 4]  # newest 2 chains survive
    assert read_manifest(tmp_path) == [[4], [3]]
    assert scan_chains(tmp_path) == [[4], [3]]


def test_generation_numbering_resumes_past_disk(tmp_path):
    """After recovery the writer must never reuse an on-disk generation
    number, and its first new write is a fresh base (chain re-anchor)."""
    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "a", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)
    ck.note_keys(["a"])
    ck.checkpoint_now(T0)  # chain [0, 1]

    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + NS)
    ck2 = _ck(lim2, tmp_path)
    ck2.note_recovery(res.restored, res.corrupt_skipped, res.chains)
    assert ck2.generation == 2
    ck2.checkpoint_now(T0 + NS)
    assert (tmp_path / checkpoint_name(2, "base")).exists()
    assert recover_into(
        TpuRateLimiter(capacity=256), tmp_path, T0 + NS
    ).chain == [2]


# ------------------------------------------------------------------ #
# Fault modes on the snapshot site


@pytest.fixture
def disarm_faults():
    yield
    from throttlecrab_tpu.faults import disarm

    disarm()


def test_truncate_fault_tears_final_file_and_recovery_survives(
    tmp_path, disarm_faults
):
    """An injected torn write leaves a GENUINELY torn file under the
    final checkpoint name (the rename-journaled-first crash shape); the
    writer re-merges its dirty set, and recovery falls back to the last
    good generation."""
    from throttlecrab_tpu.faults import (
        FaultInjector,
        arm,
        disarm,
        parse_spec,
    )

    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "safe", 1)
    ck = _ck(lim, tmp_path)
    ck.checkpoint_now(T0)  # good base, gen 0
    _spend(lim, "torn-row", 3)
    ck.note_keys(["torn-row"])

    arm(FaultInjector(parse_spec("snapshot:truncate:0.4")))
    with pytest.raises(OSError, match="torn write"):
        ck.checkpoint_now(T0)
    disarm()

    torn = tmp_path / checkpoint_name(1, "delta")
    assert torn.exists()  # promoted into the final path, torn
    with pytest.raises(CheckpointCorrupt):
        read_checkpoint(torn)
    assert ck.write_errors == 1
    assert ck.dirty_count() == 1  # re-merged: nothing lost
    assert ck.last_generation == 0  # generation did not advance

    # The manifest (written before the torn generation) does not name
    # it — recovery via the manifest skips the torn file entirely.
    # Drop the manifest to force the directory scan against the torn
    # file itself: the worst case a real crash leaves behind.
    (tmp_path / MANIFEST_NAME).unlink()
    lim2 = TpuRateLimiter(capacity=256)
    res = recover_into(lim2, tmp_path, T0 + NS)
    assert not res.used_manifest
    assert res.generation == 0 and res.corrupt_skipped == 1
    # Forgotten spends allow; the covered row restored.
    assert lim2.rate_limit("torn-row", 3, 10, 3600, 1, T0 + NS)[0]
    allowed, r = lim2.rate_limit("safe", 3, 10, 3600, 1, T0 + NS)
    assert allowed and r.remaining == 1

    # The next healthy tick retries the SAME generation number with the
    # re-merged dirty set and overwrites the torn file.
    assert ck.checkpoint_now(T0) == 1
    assert read_checkpoint(torn).kind == "delta"
    assert recover_into(
        TpuRateLimiter(capacity=256), tmp_path, T0 + NS
    ).generation == 1


def test_fsyncfail_fault_fails_cleanly_before_rename(
    tmp_path, disarm_faults
):
    from throttlecrab_tpu.faults import (
        FaultInjector,
        arm,
        disarm,
        parse_spec,
    )

    lim = TpuRateLimiter(capacity=256)
    _spend(lim, "a", 1)
    ck = _ck(lim, tmp_path)
    arm(FaultInjector(parse_spec("snapshot:fsyncfail")))
    with pytest.raises(OSError, match="fsync"):
        ck.checkpoint_now(T0)
    disarm()
    # Durability was never promised: no final file, no stray tmp.
    assert list(tmp_path.iterdir()) == []
    assert ck.write_errors == 1
    # Healed, the same state writes durably.
    assert ck.checkpoint_now(T0) == 1
    assert (tmp_path / checkpoint_name(0, "base")).exists()


def test_snapshot_save_fault_modes_degrade_cleanly(
    tmp_path, disarm_faults
):
    """The .npz save path (save_snapshot) has no torn-promote step: both
    new modes surface as a clean OSError with the destination absent."""
    from throttlecrab_tpu.faults import (
        FaultInjector,
        arm,
        disarm,
        parse_spec,
    )
    from throttlecrab_tpu.tpu.snapshot import save_snapshot

    for spec in ("snapshot:truncate:0.5", "snapshot:fsyncfail"):
        lim = TpuRateLimiter(capacity=64)
        _spend(lim, "a", 1)
        path = tmp_path / f"{spec.split(':')[1]}.npz"
        arm(FaultInjector(parse_spec(spec)))
        with pytest.raises(OSError):
            save_snapshot(lim, path)
        disarm()
        assert not path.exists()
        assert not path.with_name(path.name + ".tmp").exists()
        assert save_snapshot(lim, path) == 1  # healed


def test_parse_spec_validates_new_modes():
    from throttlecrab_tpu.faults import parse_spec

    assert parse_spec("snapshot:truncate:0.5")[0].arg == 0.5
    assert parse_spec("snapshot:fsyncfail")[0].mode == "fsyncfail"
    with pytest.raises(ValueError):
        parse_spec("snapshot:truncate")  # frac required
    with pytest.raises(ValueError):
        parse_spec("snapshot:truncate:1.5")  # frac out of range


# ------------------------------------------------------------------ #
# Server wiring


def test_config_checkpoint_knobs_validate():
    from throttlecrab_tpu.server.config import Config, ConfigError

    Config(
        http=True, checkpoint_dir="/tmp/x", checkpoint_interval_ms=100
    ).validate()
    with pytest.raises(ConfigError, match="checkpoint-dir"):
        Config(http=True, checkpoint_interval_ms=100).validate()
    with pytest.raises(ConfigError):
        Config(
            http=True, checkpoint_dir="/tmp/x", checkpoint_interval_ms=-1
        ).validate()
    with pytest.raises(ConfigError):
        Config(
            http=True, checkpoint_dir="/tmp/x", checkpoint_retain=0
        ).validate()
    with pytest.raises(ConfigError):
        Config(
            http=True, checkpoint_dir="/tmp/x", checkpoint_mode="weekly"
        ).validate()


def test_restore_on_boot_prefers_checkpoint_over_snapshot(tmp_path):
    """Boot precedence: the checkpoint chain wins when usable; an
    unusable chain falls through to the snapshot (strict policy and
    all)."""
    import time

    from throttlecrab_tpu.server.__main__ import restore_on_boot
    from throttlecrab_tpu.server.config import Config
    from throttlecrab_tpu.tpu.snapshot import save_snapshot

    now = time.time_ns()
    # Snapshot: 1 key.  Checkpoint chain: 2 keys.
    src = TpuRateLimiter(capacity=256)
    _spend(src, "snap-key", 1, t=now)
    snap = tmp_path / "snap.npz"
    save_snapshot(src, snap)
    src2 = TpuRateLimiter(capacity=256)
    _spend(src2, "ck-a", 1, t=now)
    _spend(src2, "ck-b", 1, t=now)
    ckdir = tmp_path / "ckpt"
    ck = Checkpointer(src2, ckdir, interval_ns=1, now_fn=lambda: now)
    ck.checkpoint_now(now)

    cfg = Config(
        http=True, snapshot_path=str(snap), checkpoint_dir=str(ckdir),
    )
    lim = TpuRateLimiter(capacity=256)
    ck2 = Checkpointer(lim, ckdir, interval_ns=1)
    assert restore_on_boot(lim, cfg, ck2) == 2
    assert ck2.recoveries == 1 and ck2.generation == 1

    # Chain unusable → snapshot path restores instead.
    for entry in ckdir.iterdir():
        entry.write_bytes(b"\x00")
    lim2 = TpuRateLimiter(capacity=256)
    ck3 = Checkpointer(lim2, ckdir, interval_ns=1)
    assert restore_on_boot(lim2, cfg, ck3) == 1
    assert ck3.recoveries == 0


def test_metrics_export_checkpoint_gauges():
    from throttlecrab_tpu.server.metrics import METRIC_NAMES, Metrics

    m = Metrics.builder().build()
    text = m.export_prometheus()
    # Disarmed: the names still emit (registry contract) with defaults.
    assert "throttlecrab_tpu_checkpoint_generation -1" in text

    lim = TpuRateLimiter(capacity=64)
    _spend(lim, "a", 1)
    ck = Checkpointer(
        lim, "/nonexistent-unused", interval_ns=1, now_fn=lambda: T0
    )
    m.set_checkpoint_stats_provider(ck.metric_stats)
    text = m.export_prometheus()
    for name in METRIC_NAMES:
        if name.startswith("throttlecrab_tpu_checkpoint"):
            assert name + " " in text


def test_health_suffix_states():
    lim = TpuRateLimiter(capacity=64)
    clock = {"t": T0}
    ck = Checkpointer(
        lim, "/unused", interval_ns=1, now_fn=lambda: clock["t"]
    )
    assert ck.health_suffix() == "checkpoint_age_s=never"
    ck.last_checkpoint_ns = T0
    clock["t"] = T0 + 2 * NS
    assert ck.health_suffix() == "checkpoint_age_s=2.0"


def test_engine_marks_decided_keys_dirty(tmp_path):
    """The dirty hook rides the engine observe path: decided keys (and
    only decided keys) land in the next delta."""
    import asyncio

    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.types import ThrottleRequest

    lim = TpuRateLimiter(capacity=256)
    ck = _ck(lim, tmp_path, interval_ns=1 << 62)  # ticks never due
    engine = BatchingEngine(lim, batch_size=8, checkpointer=ck)

    async def drive():
        reqs = [
            ThrottleRequest(
                key=f"e{i}", max_burst=3, count_per_period=10,
                period=3600, quantity=1,
            )
            for i in range(5)
        ]
        await asyncio.gather(*(engine.throttle(r) for r in reqs))
        await engine.shutdown()

    asyncio.run(drive())
    assert ck.dirty_count() == 5
    ck.checkpoint_now(T0)  # first write: full base
    ck.note_keys(["e0"])
    ck.checkpoint_now(T0)
    rec = read_checkpoint(tmp_path / checkpoint_name(1, "delta"))
    assert [k.decode() for k in rec.keys_raw] == ["e0"]


def test_run_server_checkpoint_lifecycle_off_the_loop(tmp_path):
    """End-to-end run_server lifecycle on the checkpoint path alone (no
    snapshot): serve → SIGINT (final flush) → reboot restores from the
    chain and decisions continue."""
    import asyncio
    import signal
    import socket as _socket

    from throttlecrab_tpu.server.__main__ import run_server
    from throttlecrab_tpu.server.config import Config

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ckdir = tmp_path / "chain"

    async def _post_throttle(key):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        payload = json.dumps(
            {
                "key": key, "max_burst": 3, "count_per_period": 1,
                "period": 3600, "quantity": 1,
            }
        ).encode()
        writer.write(
            (
                "POST /throttle HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()
        raw = await reader.read(-1)
        writer.close()
        return json.loads(raw.partition(b"\r\n\r\n")[2])

    async def _get(path):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: x\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        await writer.drain()
        raw = await reader.read(-1)
        writer.close()
        return raw.partition(b"\r\n\r\n")[2]

    async def lifecycle(expect_remaining):
        cfg = Config(
            http=True,
            http_host="127.0.0.1",
            http_port=port,
            checkpoint_dir=str(ckdir),
            checkpoint_interval_ms=50,
        )
        task = asyncio.create_task(run_server(cfg))
        body = None
        for _ in range(400):
            if task.done():
                task.result()
            try:
                body = await _post_throttle("lifecycle-key")
                break
            except OSError:
                await asyncio.sleep(0.05)
        assert body is not None, "server never came up"
        assert body["allowed"] is True
        assert body["remaining"] == expect_remaining
        # /health carries the checkpoint age only when armed.
        health = await _get("/health")
        assert health.startswith(b"OK checkpoint_age_s=")
        os.kill(os.getpid(), signal.SIGINT)
        await asyncio.wait_for(task, timeout=60)

    asyncio.run(lifecycle(expect_remaining=2))
    assert scan_chains(ckdir), "shutdown flush wrote no chain"
    asyncio.run(lifecycle(expect_remaining=1))


# ------------------------------------------------------------------ #
# Harness crash-restart workload + warm-start ledger


def test_crash_restart_workload_and_ledger():
    from throttlecrab_tpu.harness.loadgen import PerfResult
    from throttlecrab_tpu.harness.workload import (
        crash_restart_ledger,
        make_keys,
    )

    ks = make_keys("crash-restart", 2000, 10_000, seed=1)
    assert ks == make_keys("crash-restart", 2000, 10_000, seed=1)
    ledger = crash_restart_ledger(10_000)
    hits = [k for k in ks if k in ledger]
    # Both bands drawn: the audited ledger and the warm tail.
    assert hits and len(hits) < len(ks)
    r = PerfResult("http", 0, 0.0, 0, 0, 0, key_pattern="crash-restart")
    r.ledger_burst = 3
    for k, a in (
        [("key:0", True)] * 5 + [("key:1", True)] * 2 + [("key:1", False)]
    ):
        r.track_ledger(k, a)
    assert r.warm_start_summary() == {
        "ledger_keys": 2,
        "ledger_burst": 3,
        "keys_over_burst": 1,
        "extra_allows_total": 2,
        "max_allows_per_key": 5,
    }


# ------------------------------------------------------------------ #
# SIGKILL soak


BURST = 5


def _spawn_ck_server(port, ckdir):
    import subprocess
    import sys

    env = dict(os.environ)
    env["THROTTLECRAB_PLATFORM"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "throttlecrab_tpu.server",
            "--http", "--http-port", str(port),
            "--checkpoint-dir", str(ckdir),
            "--checkpoint-interval-ms", "40",
            "--log-level", "warn",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _http_throttle(port, key, quantity=1):
    import urllib.request

    body = json.dumps(
        {
            "key": key, "max_burst": BURST, "count_per_period": BURST,
            "period": 3600, "quantity": quantity,
        }
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/throttle", data=body, method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _wait_ck_health(proc, port, deadline_s=120):
    import time
    import urllib.request

    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            pytest.fail(f"server exited early rc={proc.returncode}:\n{out}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=1
            ) as r:
                body = r.read()
            # Durability armed: the age suffix rides the OK body.
            assert body.startswith(b"OK checkpoint_age_s="), body
            return
        except (OSError, AssertionError):
            time.sleep(0.25)
    proc.kill()
    pytest.fail("server never became healthy")


def _metric(port, name) -> float:
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as r:
        for line in r.read().decode().splitlines():
            if line.startswith(name + " "):
                return float(line.split()[1])
    raise AssertionError(f"metric {name} not exported")


def test_sigkill_mid_checkpoint_soak(tmp_path):
    """SIGKILL a checkpointing server mid-load, restart it on the same
    chain, and differential-check every post-restart decision against
    the scalar GCRA oracle: a warm restore may FORGET spends (restored
    TATs are older → strictly more permissive) but must never
    manufacture a deny the oracle would not issue — zero client-visible
    wrong decisions.

    Kill timing is adversarial by construction: the 40ms checkpoint
    interval keeps a generation write in flight essentially always, and
    a background spender keeps load running at the kill instant."""
    import signal
    import socket as _socket
    import threading
    import time

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ckdir = tmp_path / "chain"
    proc = _spawn_ck_server(port, ckdir)
    try:
        _wait_ck_health(proc, port)
        gen_metric = "throttlecrab_tpu_checkpoint_generation"

        # Phase 1: spend 3 of BURST on each tracked key (all acked).
        keys = [f"soak-{i}" for i in range(12)]
        for key in keys:
            for _ in range(3):
                assert _http_throttle(port, key)["allowed"] is True

        # Phase 2: make those spends durable — wait for TWO generation
        # advances past the post-ack reading.  The first advance may
        # come from a tick whose dirty swap predated some acks; the
        # second advance's swap strictly follows the first's write, so
        # it covers every phase-1 spend.  Fresh sentinel spends keep
        # the dirty set non-empty so ticks keep writing generations.
        g0 = _metric(port, gen_metric)
        deadline = time.time() + 60
        i = 0
        while _metric(port, gen_metric) < g0 + 2:
            _http_throttle(port, f"sentinel-{i}")
            i += 1
            assert time.time() < deadline, "checkpoint ticks stalled"
            time.sleep(0.05)

        # Phase 3: background load at the kill instant ("mid-load"),
        # counting acked allows per key for the oracle bound.
        acked = {}
        stop = threading.Event()

        def pound():
            j = 0
            while not stop.is_set():
                key = f"live-{j % 4}"
                try:
                    if _http_throttle(port, key)["allowed"]:
                        acked[key] = acked.get(key, 0) + 1
                except OSError:
                    return  # the kill landed mid-request
                j += 1

        t = threading.Thread(target=pound)
        t.start()
        time.sleep(0.3)  # several checkpoint intervals of live load
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        stop.set()
        t.join(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    # Phase 4: restart on the same chain.
    proc = _spawn_ck_server(port, ckdir)
    try:
        _wait_ck_health(proc, port)
        assert _metric(
            port, "throttlecrab_tpu_checkpoint_recoveries_total"
        ) == 1

        def allows_until_denied(key):
            n = 0
            while n <= BURST and _http_throttle(port, key)["allowed"]:
                n += 1
            return n

        # Tracked keys: 3 spends were durably checkpointed pre-kill.
        # Oracle remaining = BURST - 3 = 2.  Over-allow-only means the
        # server grants AT LEAST the oracle's remaining (never a wrong
        # deny) and at most a fresh bucket (worst-case staleness); the
        # +1 tolerates sub-token GCRA leak across the test's runtime.
        for key in keys:
            n = allows_until_denied(key)
            assert 2 <= n <= 3, (key, n)
        # Mid-load keys: durability at the kill instant is unknowable,
        # but the differential bound still holds — forgetting acked
        # spends only ever ALLOWS more.
        for key, spent in acked.items():
            n = allows_until_denied(key)
            assert n >= max(0, BURST - spent), (key, spent, n)
            assert n <= BURST, (key, spent, n)
        # Warm start, not cold: the tracked keys above already proved
        # restored state gated decisions (n < BURST with zero denials
        # of oracle-allowed requests).
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
