"""Snapshot/restore tests (a TPU-framework extension; the reference keeps
all state ephemeral by design — SURVEY §5 checkpoint row)."""

import numpy as np

from conftest import require_devices
import pytest

from throttlecrab_tpu.tpu.limiter import TpuRateLimiter
from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


@pytest.mark.parametrize("keymap", ["python", "native"])
def test_snapshot_round_trip(tmp_path, keymap):
    if keymap == "native":
        from throttlecrab_tpu.native import native_available

        if not native_available():
            pytest.skip("no C++ toolchain")
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=256, keymap=keymap)
    # Exhaust one key, touch others, with long TTLs.
    for _ in range(3):
        lim.rate_limit("hot", 3, 10, 3600, 1, T0)
    keys = [f"k{i}" for i in range(50)]
    lim.rate_limit_batch(keys, 5, 10, 3600, 1, T0)

    n = save_snapshot(lim, path)
    assert n == 51

    lim2 = TpuRateLimiter(capacity=256, keymap=keymap)
    restored = load_snapshot(lim2, path, now_ns=T0 + NS)
    assert restored == 51
    # Decisions continue exactly where the snapshot left off.
    allowed, r = lim2.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)
    assert not allowed  # still exhausted after restore
    allowed, r = lim2.rate_limit("k0", 5, 10, 3600, 1, T0 + NS)
    assert allowed
    assert r.remaining == 3  # one of five tokens was used pre-snapshot


def test_restore_carries_cur_state_certificate(tmp_path):
    """A snapshot holding a TAT >= 2^62 (written by a big-tolerance
    launch) must restore with table.cur_safe False — restored state is
    foreign and the cur wire mode's cross-launch certificate only holds
    for proven-safe values — while a normal snapshot restores safe."""
    big = (3_000_000_000, 1, 1, 3_000_000_000)  # tol ~3e18, inc ~3e18
    lim = TpuRateLimiter(capacity=256)
    res = lim.rate_limit_batch(["k"], *big, T0, wire=True)
    assert bool(res.allowed[0]) and lim.table.cur_safe is False
    path = tmp_path / "poison.npz"
    save_snapshot(lim, path)

    lim2 = TpuRateLimiter(capacity=256)
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 1
    assert lim2.table.cur_safe is False
    h = lim2.dispatch_many([(["k"], 10, 100, 60, 1, T0 + NS)], wire=True)
    assert not getattr(h, "_cur", True)
    assert not bool(h.fetch()[0].allowed[0])

    safe = TpuRateLimiter(capacity=256)
    safe.rate_limit_batch(["a", "b"], 5, 10, 3600, 1, T0, wire=True)
    assert safe.table.cur_safe is True
    path2 = tmp_path / "safe.npz"
    save_snapshot(safe, path2)
    lim3 = TpuRateLimiter(capacity=256)
    load_snapshot(lim3, path2, now_ns=T0 + NS)
    assert lim3.table.cur_safe is True
    h = lim3.dispatch_many([(["a"], 5, 10, 3600, 1, T0 + NS)], wire=True)
    assert getattr(h, "_cur", False)
    h.fetch()


def test_restore_pathological_foreign_tol_saturates(tmp_path):
    """Regression (ADVICE low): `_bulk_insert` recovers each restored
    entry's tolerance as expiry - tat to seed the w32 high-water mark.
    A pathological foreign entry (negative tat under an I64_MAX expiry)
    makes that difference exceed i64 — the vectorized numpy path must
    saturate to note(None) (w32 off) instead of wrapping negative and
    under-seeding the mark.  Normal entries still seed the exact max."""
    import json

    from throttlecrab_tpu.tpu.table import I64_MAX

    def craft(path, keys, tats, expiries):
        offsets = np.zeros(len(keys) + 1, np.int64)
        np.cumsum([len(k) for k in keys], out=offsets[1:])
        np.savez_compressed(
            path,
            version=np.int64(2),
            capacity=np.int64(256),
            slots=np.arange(len(keys), dtype=np.int64),
            shard=np.zeros(len(keys), np.int32),
            n_shards=np.int64(1),
            tat=np.asarray(tats, np.int64),
            expiry=np.asarray(expiries, np.int64),
            key_offsets=offsets,
            key_blob=np.frombuffer(b"".join(keys), np.uint8),
            key_is_bytes=np.zeros(len(keys), np.uint8),
            key_codec=np.zeros(len(keys), np.uint8),
            source_bytes_keys=np.uint8(0),
            meta=np.frombuffer(
                json.dumps({"n_keys": len(keys)}).encode(), np.uint8
            ),
        )

    path = tmp_path / "foreign.npz"
    craft(
        path,
        [b"ok", b"poison"],
        [T0, -(1 << 62)],
        [T0 + 3600 * NS, I64_MAX],
    )
    lim = TpuRateLimiter(capacity=256)
    with np.errstate(over="raise"):  # a wrap would raise, not corrupt
        assert load_snapshot(lim, path, now_ns=T0) == 2
    assert lim.table.tol_hwm == I64_MAX  # saturated: w32 stays off

    # A well-formed snapshot still seeds the exact recovered max.
    path2 = tmp_path / "normal.npz"
    craft(
        path2,
        [b"a", b"b"],
        [T0, T0 + NS],
        [T0 + 60 * NS, T0 + 121 * NS],
    )
    lim2 = TpuRateLimiter(capacity=256)
    assert load_snapshot(lim2, path2, now_ns=T0) == 2
    assert lim2.table.tol_hwm == 120 * NS


def test_restore_drops_expired_entries(tmp_path):
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=64)
    lim.rate_limit("short", 2, 10, 1, 1, T0)  # TTL ~1s
    lim.rate_limit("long", 2, 10, 3600, 1, T0)  # TTL ~1h
    save_snapshot(lim, path)

    lim2 = TpuRateLimiter(capacity=64)
    restored = load_snapshot(lim2, path, now_ns=T0 + 100 * NS)
    assert restored == 1  # only "long" survives
    assert len(lim2) == 1


def test_restore_requires_empty_limiter(tmp_path):
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=64)
    lim.rate_limit("a", 2, 10, 60, 1, T0)
    save_snapshot(lim, path)
    with pytest.raises(ValueError):
        load_snapshot(lim, path, now_ns=T0)


def test_empty_snapshot(tmp_path):
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=64)
    assert save_snapshot(lim, path) == 0
    lim2 = TpuRateLimiter(capacity=64)
    assert load_snapshot(lim2, path, now_ns=T0) == 0


def test_snapshot_binary_safe_keys(tmp_path):
    """Keys with NUL bytes and non-UTF-8 bytes keys survive round trip."""
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=64, keymap="python")
    weird = ["a\x00b", "plain"]
    weird_bytes = b"\xff\xfe"
    for k in weird:
        lim.rate_limit(k, 3, 10, 3600, 1, T0)
        lim.rate_limit(k, 3, 10, 3600, 1, T0)
    lim.rate_limit(weird_bytes, 3, 10, 3600, 1, T0)
    assert save_snapshot(lim, path) == 3

    lim2 = TpuRateLimiter(capacity=64, keymap="python")
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 3
    # Identity preserved: str stays str, bytes stays bytes, state continues.
    _, r = lim2.rate_limit("a\x00b", 3, 10, 3600, 1, T0 + NS)
    assert r.remaining == 0  # two of three tokens used pre-snapshot
    _, r = lim2.rate_limit(weird_bytes, 3, 10, 3600, 1, T0 + NS)
    assert r.remaining == 1
    assert len(lim2) == 3  # no duplicate identities allocated


def test_native_keymap_items_export():
    from throttlecrab_tpu.native import native_available

    if not native_available():
        pytest.skip("no C++ toolchain")
    from throttlecrab_tpu.native import NativeKeyMap

    km = NativeKeyMap(32)
    keys = [b"alpha", b"beta", b"gamma"]
    slots, _, _, _ = km.resolve(keys, np.ones(3, bool))
    exported = dict(km.items())
    assert exported == {k: int(s) for k, s in zip(keys, slots)}


@pytest.mark.parametrize(
    "src_keymap,dst_keymap",
    [("native", "python"), ("python", "native")],
)
def test_cross_backend_restore_preserves_key_identity(
    tmp_path, src_keymap, dst_keymap
):
    """A snapshot taken with one keymap backend must restore into the
    other with reachable buckets: native keymaps store str transport keys
    as bytes, so restore translates identities (surrogateescape both
    ways)."""
    from throttlecrab_tpu.native import native_available

    if not native_available():
        pytest.skip("no C++ toolchain")
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=256, keymap=src_keymap)
    for _ in range(3):
        lim.rate_limit("hot", 3, 10, 3600, 1, T0)  # exhaust via str key

    save_snapshot(lim, path)
    lim2 = TpuRateLimiter(capacity=256, keymap=dst_keymap)
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 1
    # The SAME str key must hit the restored bucket, not a fresh one.
    allowed, _ = lim2.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)
    assert not allowed, "restored bucket unreachable: key identity lost"


def test_snapshot_survives_lone_surrogate_key(tmp_path):
    """One JSON-delivered lone-surrogate key must not lose the whole
    snapshot; it round-trips via the per-key codec marker."""
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=64)  # python keymap holds any str
    weird = "\ud800weird"
    lim.rate_limit(weird, 3, 10, 3600, 1, T0)
    lim.rate_limit("normal", 3, 10, 3600, 1, T0)
    assert save_snapshot(lim, path) == 2

    lim2 = TpuRateLimiter(capacity=64)
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 2
    # Identity preserved: the same weird str hits the restored bucket.
    _, r = lim2.rate_limit(weird, 3, 10, 3600, 1, T0 + NS)
    assert r.remaining == 1  # 3 - 1 (pre-snapshot) - 1 (now)


# -------------------------------------------------- sharded / cluster #


def _exercise(lim):
    """Burn state into a limiter: one exhausted key + 50 touched keys."""
    for _ in range(3):
        lim.rate_limit("hot", 3, 10, 3600, 1, T0)
    lim.rate_limit_batch(
        [f"k{i}" for i in range(50)], 5, 10, 3600, 1, T0
    )


def _check_continuity(lim):
    allowed, _ = lim.rate_limit("hot", 3, 10, 3600, 1, T0 + NS)
    assert not allowed  # still exhausted after restore
    allowed, r = lim.rate_limit("k0", 5, 10, 3600, 1, T0 + NS)
    assert allowed and r.remaining == 3


def test_sharded_snapshot_round_trip(tmp_path):
    require_devices(4)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    path = tmp_path / "snap.npz"
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=make_mesh(4)
    )
    _exercise(lim)
    assert save_snapshot(lim, path) == 51

    lim2 = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=make_mesh(4)
    )
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 51
    _check_continuity(lim2)


def test_sharded_snapshot_restores_across_shard_counts(tmp_path):
    """A 8-shard snapshot restores onto 2 shards (and the reverse):
    shard topology is not part of the snapshot contract — keys re-route
    through the target's own hash."""
    require_devices(8)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    path = tmp_path / "snap.npz"
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=make_mesh(8)
    )
    _exercise(lim)
    save_snapshot(lim, path)

    lim2 = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=make_mesh(2)
    )
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 51
    _check_continuity(lim2)


def test_sharded_snapshot_restores_to_single_device(tmp_path):
    require_devices(4)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    path = tmp_path / "snap.npz"
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=make_mesh(4)
    )
    _exercise(lim)
    save_snapshot(lim, path)

    lim2 = TpuRateLimiter(capacity=1024)
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 51
    _check_continuity(lim2)


def test_single_device_snapshot_restores_to_sharded(tmp_path):
    require_devices(4)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=256)
    _exercise(lim)
    save_snapshot(lim, path)

    lim2 = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=make_mesh(4)
    )
    assert load_snapshot(lim2, path, now_ns=T0 + NS) == 51
    _check_continuity(lim2)


def test_sharded_restore_drops_expired(tmp_path):
    require_devices(2)
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    path = tmp_path / "snap.npz"
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=64, mesh=make_mesh(2)
    )
    lim.rate_limit("short", 2, 10, 1, 1, T0)
    lim.rate_limit("long", 2, 10, 3600, 1, T0)
    save_snapshot(lim, path)

    lim2 = ShardedTpuRateLimiter(
        capacity_per_shard=64, mesh=make_mesh(2)
    )
    assert load_snapshot(lim2, path, now_ns=T0 + 100 * NS) == 1
    assert len(lim2) == 1


def test_cluster_snapshot_delegates_to_local(tmp_path):
    """ClusterLimiter snapshots its local node's state (one file per
    node — each node owns its key range)."""
    from throttlecrab_tpu.parallel.cluster import ClusterLimiter

    path = tmp_path / "snap.npz"
    cl = ClusterLimiter(
        TpuRateLimiter(capacity=256), ["127.0.0.1:1"], 0
    )
    _exercise(cl)
    assert save_snapshot(cl, path) == 51

    cl2 = ClusterLimiter(
        TpuRateLimiter(capacity=256), ["127.0.0.1:1"], 0
    )
    assert load_snapshot(cl2, path, now_ns=T0 + NS) == 51
    _check_continuity(cl2)


# ------------------------------------------------------------------ #
# Corruption hardening (failure-domain PR): a bad snapshot must raise
# one typed SnapshotError, and the boot path must apply the
# THROTTLECRAB_SNAPSHOT_STRICT policy instead of crashing.


def _write_real_snapshot(tmp_path, now_ns=T0):
    path = tmp_path / "snap.npz"
    lim = TpuRateLimiter(capacity=256)
    lim.rate_limit_batch(
        [f"k{i}" for i in range(40)], 5, 10, 3600, 1, now_ns
    )
    save_snapshot(lim, path)
    return path


@pytest.mark.parametrize("keep_frac", [0.1, 0.5, 0.9])
def test_truncated_snapshot_raises_snapshot_error(tmp_path, keep_frac):
    """Truncate a real snapshot mid-file at several points: every cut
    must surface as SnapshotError, never a raw zipfile/zlib crash."""
    from throttlecrab_tpu.tpu.snapshot import SnapshotError

    path = _write_real_snapshot(tmp_path)
    blob = path.read_bytes()
    path.write_bytes(blob[: max(int(len(blob) * keep_frac), 1)])
    lim = TpuRateLimiter(capacity=256)
    with pytest.raises(SnapshotError):
        load_snapshot(lim, path, now_ns=T0 + NS)


def test_garbage_and_mismatched_snapshots_raise_snapshot_error(tmp_path):
    from throttlecrab_tpu.tpu.snapshot import SnapshotError

    garbage = tmp_path / "garbage.npz"
    garbage.write_bytes(b"\x00not a zip at all")
    lim = TpuRateLimiter(capacity=256)
    with pytest.raises(SnapshotError):
        load_snapshot(lim, garbage, now_ns=T0)

    # Internally inconsistent column lengths.
    bad = tmp_path / "bad.npz"
    np.savez_compressed(
        bad,
        version=np.int64(2),
        capacity=np.int64(256),
        slots=np.zeros(2, np.int64),
        shard=np.zeros(2, np.int32),
        n_shards=np.int64(1),
        tat=np.zeros(2, np.int64),
        expiry=np.zeros(1, np.int64),  # mismatched
        key_offsets=np.zeros(3, np.int64),
        key_blob=np.zeros(0, np.uint8),
        key_is_bytes=np.zeros(2, np.uint8),
        key_codec=np.zeros(2, np.uint8),
        source_bytes_keys=np.uint8(0),
        meta=np.frombuffer(b'{"n_keys": 2}', np.uint8),
    )
    with pytest.raises(SnapshotError):
        load_snapshot(TpuRateLimiter(capacity=256), bad, now_ns=T0)


def test_boot_restore_strict_refuses_nonstrict_starts_empty(tmp_path):
    """server/__main__.py restore-on-boot: strict (default) refuses to
    start on a corrupt snapshot with a clear error; non-strict
    (THROTTLECRAB_SNAPSHOT_STRICT=0) logs and starts empty."""
    from throttlecrab_tpu.server.__main__ import (
        SnapshotRefused,
        restore_snapshot_on_boot,
    )
    from throttlecrab_tpu.server.config import Config

    path = _write_real_snapshot(tmp_path)
    blob = path.read_bytes()
    path.write_bytes(blob[: len(blob) // 2])

    strict = Config(http=True, snapshot_path=str(path))
    with pytest.raises(SnapshotRefused, match="SNAPSHOT_STRICT"):
        restore_snapshot_on_boot(TpuRateLimiter(capacity=256), strict)

    lax = Config(http=True, snapshot_path=str(path), snapshot_strict=False)
    lim = TpuRateLimiter(capacity=256)
    assert restore_snapshot_on_boot(lim, lax) == 0
    assert len(lim) == 0  # empty table, but the server boots

    # And a healthy snapshot restores normally through the same path
    # (stamped with the real clock: restore-on-boot's TTL gate uses
    # wall time).
    import time

    good = _write_real_snapshot(tmp_path, now_ns=time.time_ns())
    lim2 = TpuRateLimiter(capacity=256)
    assert restore_snapshot_on_boot(lim2, Config(
        http=True, snapshot_path=str(good)
    )) == 40


def test_run_server_snapshot_lifecycle_off_the_loop(tmp_path):
    """End-to-end run_server lifecycle: the boot restore and the
    shutdown save now run on the executor instead of the event loop
    (PR 11 async-boundary fix) — the snapshot must still round-trip
    through a full serve/SIGINT/reboot cycle, and the second boot must
    serve with the restored table."""
    import asyncio
    import json as _json
    import os
    import signal
    import socket as _socket

    from throttlecrab_tpu.server.__main__ import run_server
    from throttlecrab_tpu.server.config import Config

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    snap = tmp_path / "lifecycle.npz"

    async def _post_throttle(key):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        payload = _json.dumps(
            {
                "key": key, "max_burst": 3, "count_per_period": 1,
                "period": 3600, "quantity": 1,
            }
        ).encode()
        writer.write(
            (
                "POST /throttle HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()
        raw = await reader.read(-1)
        writer.close()
        return _json.loads(raw.partition(b"\r\n\r\n")[2])

    async def lifecycle(expect_remaining):
        cfg = Config(
            http=True,
            http_host="127.0.0.1",
            http_port=port,
            snapshot_path=str(snap),
        )
        task = asyncio.create_task(run_server(cfg))
        body = None
        for _ in range(400):
            if task.done():
                task.result()  # surface boot failures
            try:
                body = await _post_throttle("lifecycle-key")
                break
            except OSError:
                await asyncio.sleep(0.05)
        assert body is not None, "server never came up"
        # burst 3, one emission per hour: a fresh bucket's first allow
        # leaves remaining=2; a RESTORED bucket already spent one, so
        # its first allow on the rebooted server leaves remaining=1.
        assert body["allowed"] is True
        assert body["remaining"] == expect_remaining
        os.kill(os.getpid(), signal.SIGINT)
        await asyncio.wait_for(task, timeout=60)

    asyncio.run(lifecycle(expect_remaining=2))
    assert snap.exists()
    asyncio.run(lifecycle(expect_remaining=1))
