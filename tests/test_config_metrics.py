"""Config (CLI/env precedence, validation) and metrics (counters,
Prometheus export) tests, mirroring the reference's config validation
(`config.rs:435-454`), env-var surface (`config.rs:174-340`), metric export
(`metrics.rs:233-310`) and the counter invariant suite
(`metrics.rs:383-411`, `tests/metrics_test.rs`, `tests/denied_keys_test.rs`).
"""

import pytest

from throttlecrab_tpu.server.config import Config, ConfigError
from throttlecrab_tpu.server.metrics import (
    MAX_KEY_LENGTH,
    Metrics,
    TopDeniedKeys,
    escape_label_value,
)

# ----------------------------------------------------------------- config #


def test_defaults_match_reference():
    cfg = Config.from_env_and_args(["--http"])
    assert cfg.http_port == 8080
    assert cfg.grpc_port == 8070
    assert cfg.redis_port == 6379
    assert cfg.store == "periodic"
    assert cfg.store_capacity == 100_000
    assert cfg.store_cleanup_interval == 300
    assert cfg.store_cleanup_probability == 10_000
    assert cfg.store_min_interval == 5
    assert cfg.store_max_interval == 300
    assert cfg.store_max_operations == 1_000_000
    assert cfg.buffer_size == 100_000
    assert cfg.max_denied_keys == 100
    assert cfg.log_level == "info"


def test_requires_at_least_one_transport():
    with pytest.raises((ConfigError, SystemExit)):
        Config.from_env_and_args([])


def test_env_fallback_and_cli_precedence(monkeypatch):
    monkeypatch.setenv("THROTTLECRAB_HTTP", "true")
    monkeypatch.setenv("THROTTLECRAB_HTTP_PORT", "9999")
    monkeypatch.setenv("THROTTLECRAB_STORE", "adaptive")
    cfg = Config.from_env_and_args([])
    assert cfg.http is True
    assert cfg.http_port == 9999
    assert cfg.store == "adaptive"
    # CLI wins over env (config.rs:356-361).
    cfg = Config.from_env_and_args(["--http-port", "1234"])
    assert cfg.http_port == 1234


def test_invalid_store_rejected():
    with pytest.raises(ConfigError):
        Config.from_env_and_args(["--http", "--store", "bogus"])


def test_max_denied_keys_range():
    with pytest.raises(ConfigError):
        Config.from_env_and_args(["--http", "--max-denied-keys", "20000"])


def test_list_env_vars_exits_zero(capsys):
    with pytest.raises(SystemExit) as excinfo:
        Config.from_env_and_args(["--list-env-vars"])
    assert excinfo.value.code == 0
    out = capsys.readouterr().out
    assert "THROTTLECRAB_HTTP_PORT" in out
    assert "THROTTLECRAB_STORE_CLEANUP_INTERVAL" in out


def test_tpu_knobs():
    cfg = Config.from_env_and_args(
        ["--http", "--batch-size", "512", "--shards", "4",
         "--keymap", "python"]
    )
    assert cfg.batch_size == 512
    assert cfg.shards == 4
    with pytest.raises(ConfigError):
        Config.from_env_and_args(["--http", "--keymap", "rust"])
    with pytest.raises(ConfigError):
        Config.from_env_and_args(["--http", "--shards", "0"])


# ---------------------------------------------------------------- metrics #


def test_counter_invariant():
    """allowed + denied + errors == total (metrics.rs:383-411)."""
    m = Metrics()
    for i in range(10):
        m.record_request("http", allowed=i % 3 != 0)
    m.record_error("redis")
    assert (
        m.requests_allowed + m.requests_denied + m.requests_errors
        == m.requests_total
    )


def test_prometheus_export_names():
    m = Metrics(max_denied_keys=5)
    m.record_request_with_key("http", False, "bad-key")
    text = m.export_prometheus()
    for name in (
        "throttlecrab_uptime_seconds",
        "throttlecrab_requests_total",
        "throttlecrab_requests_by_transport",
        "throttlecrab_requests_allowed",
        "throttlecrab_requests_denied",
        "throttlecrab_requests_errors",
        "throttlecrab_top_denied_keys",
        "throttlecrab_tpu_device_launches",
        "throttlecrab_tpu_expired_hits",
    ):
        assert name in text, name
    assert 'throttlecrab_top_denied_keys{key="bad-key",rank="1"} 1' in text


def test_cluster_metrics_export():
    """Elastic-cluster surfaces: per-peer breaker/migration counters
    and the epoch/replica/takeover gauges, exported exactly when the
    providers are wired (cluster deployments only)."""
    m = Metrics()
    base = m.export_prometheus()
    assert "throttlecrab_cluster_epoch" not in base
    m.set_cluster_stats_provider(lambda: {
        "10.0.0.1:9" : {"forwarded": 7, "failed": 2, "breaker_open": 1,
                        "migrated_keys": 40},
    })
    m.set_cluster_view_provider(lambda: {
        "epoch": 3, "migrated_in": 12, "replica_rows": 5, "takeovers": 1,
    })
    text = m.export_prometheus()
    assert 'throttlecrab_cluster_forwarded_total{peer="10.0.0.1:9"} 7' in text
    assert 'throttlecrab_cluster_breaker_open{peer="10.0.0.1:9"} 1' in text
    assert 'throttlecrab_cluster_migrated_keys{peer="10.0.0.1:9"} 40' in text
    assert "throttlecrab_cluster_epoch 3" in text
    assert "throttlecrab_cluster_migrated_in_total 12" in text
    assert "throttlecrab_cluster_replica_rows 5" in text
    assert "throttlecrab_cluster_takeovers_total 1" in text


def test_cluster_config_knobs_validate():
    from throttlecrab_tpu.server.config import Config, ConfigError

    cfg = Config(http=True)
    assert cfg.cluster_vnodes == 128 and cfg.cluster_replicate is True
    cfg.validate()
    cfg.cluster_vnodes = 0  # legacy kill switch is a valid setting
    cfg.validate()
    cfg.cluster_vnodes = -1
    with pytest.raises(ConfigError):
        cfg.validate()
    cfg.cluster_vnodes = 128
    cfg.cluster_handoff_timeout_ms = 0
    with pytest.raises(ConfigError):
        cfg.validate()


def test_top_denied_keys_ranking_and_caps():
    """denied_keys_test.rs: ranking by count, prune at 3x, key-length cap."""
    t = TopDeniedKeys(max_keys=3)
    for key, n in [("a", 5), ("b", 3), ("c", 8), ("d", 1)]:
        for _ in range(n):
            t.record(key)
    top = t.top()
    assert [k for k, _ in top] == ["c", "a", "b"]

    long_key = "x" * 1000
    t.record(long_key)
    assert all(len(k) <= MAX_KEY_LENGTH for k in t.counts)

    # Grow-then-prune: more than 3x max_keys distinct keys triggers prune.
    t2 = TopDeniedKeys(max_keys=2)
    for i in range(10):
        for _ in range(i + 1):
            t2.record(f"k{i}")
    assert len(t2.counts) <= 6
    assert [k for k, _ in t2.top()] == ["k9", "k8"]


def test_top_denied_disabled_at_zero():
    m = Metrics(max_denied_keys=0)
    m.record_request_with_key("http", False, "k")
    assert m.top_denied is None
    assert "throttlecrab_top_denied_keys" not in m.export_prometheus()


def test_label_escaping():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    m = Metrics(max_denied_keys=2)
    m.record_request_with_key("http", False, 'key"with\nstuff')
    text = m.export_prometheus()
    assert 'key="key\\"with\\nstuff"' in text
