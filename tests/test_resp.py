"""RESP parser/serializer tests, including the reference's security suite.

Ports `transport/redis/resp.rs` unit tests and the attack vectors from
`transport/redis_security_test.rs:8-165`: huge/negative bulk and array
sizes, deep nesting vs the depth cap, i64-overflow casts, NUL bytes,
invalid UTF-8, and incremental/partial-frame parsing.
"""

import pytest

from throttlecrab_tpu.server.resp import (
    MAX_ARRAY_DEPTH,
    Array,
    BulkString,
    Error,
    Integer,
    RespError,
    RespParser,
    SimpleString,
    serialize,
)


def parse_one(data: bytes):
    return RespParser().parse(data)


# ---------------------------------------------------------------- basics #


def test_parse_simple_string():
    value, consumed = parse_one(b"+OK\r\n")
    assert value == SimpleString("OK")
    assert consumed == 5


def test_parse_error():
    value, consumed = parse_one(b"-ERR bad\r\n")
    assert value == Error("ERR bad")
    assert consumed == 10


def test_parse_integer():
    value, _ = parse_one(b":42\r\n")
    assert value == Integer(42)
    value, _ = parse_one(b":-7\r\n")
    assert value == Integer(-7)


def test_parse_bulk_string():
    value, consumed = parse_one(b"$6\r\nfoobar\r\n")
    assert value == BulkString("foobar")
    assert consumed == 12


def test_parse_null_bulk_string():
    value, _ = parse_one(b"$-1\r\n")
    assert value == BulkString(None)


def test_parse_empty_bulk_string():
    value, _ = parse_one(b"$0\r\n\r\n")
    assert value == BulkString("")


def test_parse_array():
    value, consumed = parse_one(b"*2\r\n$3\r\nfoo\r\n$3\r\nbar\r\n")
    assert value == Array((BulkString("foo"), BulkString("bar")))
    assert consumed == 22


def test_parse_null_array():
    value, _ = parse_one(b"*-1\r\n")
    assert value == Array(())


def test_incomplete_frames_return_none():
    assert parse_one(b"") is None
    assert parse_one(b"+OK") is None
    assert parse_one(b"$6\r\nfoo") is None
    assert parse_one(b"*2\r\n$3\r\nfoo\r\n") is None
    assert parse_one(b"*2\r\n$3\r\nfoo\r\n$3\r\nba") is None


def test_incremental_parse_across_chunks():
    # The connection loop accumulates; the parser must eventually accept.
    frame = b"*2\r\n$4\r\nPING\r\n$5\r\nhello\r\n"
    for cut in range(len(frame)):
        partial = frame[:cut]
        assert RespParser().parse(partial) is None
    value, consumed = RespParser().parse(frame)
    assert value == Array((BulkString("PING"), BulkString("hello")))
    assert consumed == len(frame)


def test_pipelined_commands_consume_exactly_one():
    data = b"+A\r\n+B\r\n"
    value, consumed = parse_one(data)
    assert value == SimpleString("A")
    value2, _ = parse_one(data[consumed:])
    assert value2 == SimpleString("B")


# ------------------------------------------------------------- security #


def test_huge_bulk_string_length_rejected():
    with pytest.raises(RespError):
        parse_one(b"$999999999999\r\n")


def test_negative_bulk_string_length_rejected():
    with pytest.raises(RespError):
        parse_one(b"$-2\r\n")


def test_huge_array_size_rejected():
    with pytest.raises(RespError):
        parse_one(b"*999999999999\r\n")


def test_negative_array_size_rejected():
    with pytest.raises(RespError):
        parse_one(b"*-2\r\n")


def test_i64_overflow_length_rejected():
    with pytest.raises(RespError):
        parse_one(b"$92233720368547758070\r\n")


def test_depth_cap_blocks_deep_nesting():
    # 200 nested arrays vs the depth-128 cap (redis_security_test.rs).
    data = b"*1\r\n" * 200 + b":1\r\n"
    with pytest.raises(RespError):
        parse_one(data)


def test_depth_under_cap_parses():
    depth = MAX_ARRAY_DEPTH - 1
    data = b"*1\r\n" * depth + b":1\r\n"
    value, _ = parse_one(data)
    for _ in range(depth):
        assert isinstance(value, Array) and len(value.value) == 1
        value = value.value[0]
    assert value == Integer(1)


def test_invalid_type_marker_rejected():
    with pytest.raises(RespError):
        parse_one(b"!bad\r\n")


def test_invalid_utf8_rejected():
    with pytest.raises(RespError):
        parse_one(b"$2\r\n\xff\xfe\r\n")


def test_nul_bytes_in_bulk_string_survive():
    value, _ = parse_one(b"$3\r\na\x00b\r\n")
    assert value == BulkString("a\x00b")


def test_non_numeric_length_rejected():
    with pytest.raises(RespError):
        parse_one(b"$abc\r\n")
    with pytest.raises(RespError):
        parse_one(b":12x\r\n")


# ----------------------------------------------------------- serializer #


def test_serialize_round_trip():
    for value in (
        SimpleString("OK"),
        Error("ERR x"),
        Integer(-123),
        BulkString("hello"),
        BulkString(None),
        Array((Integer(1), BulkString("a"), Array((Integer(2),)))),
    ):
        data = serialize(value)
        parsed, consumed = parse_one(data)
        assert parsed == value
        assert consumed == len(data)


def test_serialize_throttle_response_shape():
    resp = Array(tuple(Integer(n) for n in (1, 10, 9, 60, 0)))
    assert serialize(resp) == b"*5\r\n:1\r\n:10\r\n:9\r\n:60\r\n:0\r\n"
