"""Elastic-cluster chaos: join, kill, rejoin, reweight, partition heal.

The contracts under test (ISSUE 8 / ROADMAP item 4):

- **Join exactness** — a node joining under load moves key ranges via
  OP_MIGRATE with a handoff gate: zero lost or double-counted decisions
  across the migration epoch, pinned differentially against the scalar
  single-node oracle.
- **Warm-standby failover** — killing a node costs no client-visible
  failures on replicated ranges: its ring successor absorbs the
  OP_REPLICA rows and continues from the replicated TATs (stale by at
  most the replication lag + 1 s wire truncation; GCRA's clamp-against-
  now makes a low TAT strictly more permissive, never wrong-denying).
- **Rejoin** — the recovered node re-enters via the same OP_JOIN path:
  successors migrate the freshest absorbed state back, overwriting its
  stale table.
- **Reweight** — a degraded node announces a reduced ring weight; the
  lost vnode ranges migrate out before the flip, so decisions stay
  exact.
- **Migration chaos** — injected `migrate` faults lose the handoff;
  the joiner's gate deadline unblocks loudly and serving continues.

All in-process tests drive real TCP sockets between in-process nodes
(one event loop thread per node) with explicit timestamps, so runs are
deterministic up to thread scheduling.  The 3-process acceptance soak
(join -> kill -> rejoin against spawned servers) is `slow` and also run
as an explicit CI step.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from throttlecrab_tpu.parallel.cluster import ClusterLimiter, ClusterServer
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_760_000_000 * NS
CAP = 2048


def free_ports(n: int):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class Node:
    """One in-process cluster node: device limiter + ring cluster tier +
    RPC listener on its own event-loop thread."""

    def __init__(self, index, nodes, **kw):
        kw.setdefault("vnodes", 64)
        kw.setdefault("replicate", True)
        # The reply timeout must stay ABOVE the handoff gate's worst
        # case or a peer legitimately blocked waiting for an inbound
        # migrate is falsely declared dead and its range re-decided
        # from the warm replica (a double count the exactness tests
        # catch).  Tests that inject a 20x-slowed gate clock stretch
        # the 4 s gate to 80 real seconds, so give the reply wait 3x
        # that; genuinely dead nodes refuse connections instantly, so
        # the long timeout never runs in a healthy teardown.
        kw.setdefault("io_timeout_s", 240.0)
        kw.setdefault("handoff_timeout_s", 4.0)
        self.index = index
        self.limiter = TpuRateLimiter(capacity=CAP)
        # First-touch jit compile outside any cluster deadline.
        self.limiter.rate_limit_batch(["__warm__"], 5, 100, 60, 1, T0 - NS)
        self.cl = ClusterLimiter(self.limiter, nodes, index, **kw)
        port = int(nodes[index].rpartition(":")[2])
        self.srv = ClusterServer(
            "127.0.0.1", port, self.cl.local, self.cl.device_lock,
            cluster=self.cl,
        )
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=f"node{index}-loop", daemon=True
        )
        self._thread.start()
        asyncio.run_coroutine_threadsafe(
            self.srv.start(), self.loop
        ).result(timeout=10)

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def join_cluster(self):
        self.cl.announce_join_all()

    def kill(self):
        """Hard stop: RPC listener down, pump stopped, sockets dropped.
        Idempotent — test teardowns may race an in-test kill."""
        if getattr(self, "_dead", False):
            return
        self._dead = True
        asyncio.run_coroutine_threadsafe(
            self.srv.stop(), self.loop
        ).result(timeout=10)
        self.cl.close()
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)


@pytest.fixture
def two_ring_nodes():
    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)
    b = Node(1, nodes)
    a.join_cluster()
    b.join_cluster()
    try:
        yield a, b
    finally:
        for n in (a, b):
            try:
                n.kill()
            except Exception:
                pass


def settle_handoffs(*nodes_, deadline_s=300.0):
    """Block (real time) until every node's inbound-handoff gate has
    drained.  `apply_migrate` pops a pending entry whenever the rows
    land — only a decide thread inside `_wait_handoff` can abandon one
    at the gate deadline — so polling here instead of deciding makes a
    join exact no matter how long the joiner's JIT-compiling bulk
    inserts take on a loaded CI box.  A migrate that never lands
    (genuinely lost) still fails loudly at `deadline_s`."""
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        if all(not n.cl._pending_from for n in nodes_):
            return
        time.sleep(0.01)
    pytest.fail(
        "handoff never settled: "
        + repr([dict(n.cl._pending_from) for n in nodes_])
    )


def oracle_check(oracle, node, keys, burst, count, period, now, ctx):
    """One batch through the cluster vs the scalar oracle, exact."""
    from test_tpu_batch import oracle_batch

    n = len(keys)
    b = np.full(n, burst, np.int64)
    c = np.full(n, count, np.int64)
    p = np.full(n, period, np.int64)
    q = np.ones(n, np.int64)
    res = node.cl.rate_limit_batch(keys, b, c, p, q, now)
    exp = oracle_batch(oracle, keys, b, c, p, q, now)
    np.testing.assert_array_equal(res.status, exp["status"], err_msg=ctx)
    np.testing.assert_array_equal(res.allowed, exp["allowed"], err_msg=ctx)
    np.testing.assert_array_equal(
        res.remaining, exp["remaining"], err_msg=ctx
    )
    return res


# ------------------------------------------------------------- join #


def test_join_under_load_zero_lost_or_double_counted():
    """A third node joins mid-stream: every decision before, during and
    after the migration epoch matches the single-node scalar oracle
    value-for-value — nothing lost (a key's state survives the range
    handoff) and nothing double-decided (old owner stops exactly when
    the new owner starts)."""
    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore

    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    # The handoff gate measures its deadline on the injectable cluster
    # clock: slow it 20x so a loaded CI box can never expire the 4 s
    # gate while the migrate is genuinely in flight (the flake this
    # replaces), while a genuinely lost handoff still unblocks eventually.
    t_base = time.monotonic()
    slow_clock = lambda: t_base + (time.monotonic() - t_base) * 0.05  # noqa: E731
    a = Node(0, nodes, clock=slow_clock)
    b = Node(1, nodes, clock=slow_clock)
    c = None
    try:
        a.join_cluster()
        b.join_cluster()
        settle_handoffs(a, b)
        oracle = RateLimiter(PeriodicStore())
        pool = [f"jn:{i}" for i in range(48)]
        now = T0
        frontends = [a, b]
        for step in range(24):
            if step == 8:
                # Join under load: node 2 boots and announces (same
                # slowed gate clock — it is the joiner whose handoff
                # deadline the flake used to race).  The settle makes
                # the exactness claim load-proof: the gate clears when
                # the migrates LAND, not when a decide polls it, so
                # waiting here cannot mask an abandoned handoff (that
                # would hang the gate and trip the settle deadline).
                c = Node(2, nodes, clock=slow_clock)
                c.join_cluster()
                settle_handoffs(a, b, c)
                frontends = [a, b, c]
            via = frontends[step % len(frontends)]
            oracle_check(
                oracle, via, pool, 4, 10, 60, now, f"step{step}"
            )
            now += NS // 4
        # The joiner actually took over ranges: it received migrated
        # keys and now decides its share locally (peers forward to it).
        assert c.cl.migrated_in > 0
        assert any(
            p is not None and p.forwarded > 0
            for p in (a.cl.peers[2], b.cl.peers[2])
        )
        # And the handoff gate never abandoned a migration.
        assert c.cl.handoff_timeouts == 0
    finally:
        for n in (a, b, c):
            if n is not None:
                try:
                    n.kill()
                except Exception:
                    pass


def test_migrate_fault_abandons_handoff_loudly():
    """Injected `migrate` faults lose the handoff: the joiner's gate
    deadline unblocks (handoff_timeouts counts it) and serving
    continues without client-visible failures."""
    from throttlecrab_tpu.faults import FaultInjector, arm, disarm, parse_spec

    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes, handoff_timeout_s=0.8)
    b = None
    try:
        # Seed state on A for keys B will own, so B's join has ranges
        # to (fail to) migrate.
        keys = [f"mf:{i}" for i in range(64)]
        a.cl.rate_limit_batch(keys, 4, 10, 60, 1, T0)
        arm(FaultInjector(parse_spec("migrate:persistent"), seed=7))
        b = Node(1, nodes, handoff_timeout_s=0.8)
        b.join_cluster()
        res = b.cl.rate_limit_batch(keys, 4, 10, 60, 1, T0 + NS)
        assert (res.status == 0).all()
        assert b.cl.handoff_timeouts >= 1
    finally:
        disarm()
        for n in (a, b):
            if n is not None:
                try:
                    n.kill()
                except Exception:
                    pass


# ------------------------------------------------- kill / failover #


def exhaust_key(node, key, now, burst=2):
    """Drive one key to denial; returns the now used last."""
    for i in range(burst + 2):
        node.cl.rate_limit_batch([key], burst, 2, 600, 1, now + i)
    return now + burst + 2


def test_node_kill_replica_takeover_no_client_failures(two_ring_nodes):
    """Killing a node costs zero client-visible failures on its range:
    the successor absorbs the warm replica and — the warm-standby
    point — an exhausted key STAYS denied after takeover (the replica
    carried its TAT; a fresh table would wrongly re-allow it)."""
    a, b = two_ring_nodes
    ring = a.cl.ring
    b_keys = [
        k for k in (f"kv:{i}" for i in range(4000))
        if ring.owner_of(k.encode()) == 1
    ]
    hot, fresh = b_keys[0], b_keys[1]
    now = T0
    # Decide on the owner so replicas flow B -> A.
    now = exhaust_key(b, hot, now)
    res = b.cl.rate_limit_batch([hot], 2, 2, 600, 1, now)
    assert not res.allowed[0], "precondition: key exhausted on B"
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and hot.encode() not in a.cl.replica_store:
        time.sleep(0.1)
    assert hot.encode() in a.cl.replica_store, "replica never reached A"

    b.kill()
    # Exhausted key: served by A from the replica, still denied.
    res = a.cl.rate_limit_batch([hot, fresh], 2, 2, 600, 1, now + 1)
    assert (res.status == 0).all(), "client-visible failure on failover"
    assert not res.allowed[0], "replica TAT lost: takeover re-allowed"
    assert res.allowed[1], "fresh key on dead range must serve"
    assert a.cl.takeover_count >= 1
    stats = a.cl.peer_stats()[a.cl.nodes[1]]
    assert stats["breaker_open"] in (0, 1)  # breaker state surfaced
    view = a.cl.cluster_view()
    assert view["mode"] == "ring" and view["takeovers"] >= 1


def test_breaker_open_failover_is_fast(two_ring_nodes):
    """Once the breaker opens, a dead peer's keys cost ~nothing: the
    partition routes them straight to the successor without touching
    the network."""
    a, b = two_ring_nodes
    ring = a.cl.ring
    b_key = next(
        k for k in (f"bf:{i}" for i in range(4000))
        if ring.owner_of(k.encode()) == 1
    )
    b.kill()
    # Open the breaker (default 3 consecutive failures).  Attempts
    # inside the reconnect backoff don't count (by design), so space
    # them out until it trips.
    deadline = time.monotonic() + 10
    i = 0
    while (
        not a.cl.peers[1].breaker_open and time.monotonic() < deadline
    ):
        a.cl.rate_limit_batch([b_key], 5, 100, 60, 1, T0 + i)
        i += 1
        time.sleep(0.15)
    assert a.cl.peers[1].breaker_open
    t0 = time.monotonic()
    res = a.cl.rate_limit_batch([b_key], 5, 100, 60, 1, T0 + 10)
    assert res.status[0] == 0
    assert time.monotonic() - t0 < 0.5, "breaker-open path touched the net"


def test_rejoin_migrates_absorbed_state_back():
    """Kill -> serve via the successor -> rejoin: the successor
    migrates the absorbed (freshest) rows back, so the rejoined node
    continues from the state decided during its absence — its stale
    table is overwritten, not trusted."""
    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)
    b = Node(1, nodes)
    b2 = None
    try:
        a.join_cluster()
        b.join_cluster()
        ring = a.cl.ring
        hot = next(
            k for k in (f"rj:{i}" for i in range(4000))
            if ring.owner_of(k.encode()) == 1
        )
        now = T0
        # B owns the key and has replicated it; then B dies.
        now = exhaust_key(b, hot, now)
        deadline = time.monotonic() + 5
        while (
            time.monotonic() < deadline
            and hot.encode() not in a.cl.replica_store
        ):
            time.sleep(0.1)
        b.kill()
        # A serves the range during the outage (takeover).
        res = a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now)
        assert res.status[0] == 0 and not res.allowed[0]
        # B restarts fresh (empty table) and rejoins.
        b2 = Node(1, nodes)
        b2.join_cluster()
        # The rejoined node decides from the migrated state: still
        # denied, not re-allowed from an empty row.
        res = b2.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + 1)
        assert res.status[0] == 0
        assert not res.allowed[0], "rejoin lost the absorbed state"
        assert b2.cl.migrated_in >= 1
        # A routes to B again (absorbed flag cleared).
        assert 1 not in a.cl._absorbed or not a.cl.peers[1].breaker_open
        res = a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + 2)
        assert res.status[0] == 0 and not res.allowed[0]
    finally:
        for n in (a, b2):
            if n is not None:
                try:
                    n.kill()
                except Exception:
                    pass


def test_crash_rejoin_restores_checkpoint_then_reconciles(tmp_path):
    """Crash-rejoin with durability: the restarted node restores its
    local checkpoint BEFORE announcing, then the successor's
    migrate-back reconciles per key newest-wins — inbound rows that are
    not newer than the restored local row are counted and dropped, and
    a key only the checkpoint knew (never replicated, never absorbed)
    keeps its spent budget across the crash."""
    from throttlecrab_tpu.persist import Checkpointer, recover_into
    from throttlecrab_tpu.tpu.snapshot import export_state

    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)
    b = Node(1, nodes)
    b2 = None
    try:
        a.join_cluster()
        b.join_cluster()
        ring = a.cl.ring
        gen = (k for k in (f"cj:{i}" for i in range(8000))
               if ring.owner_of(k.encode()) == 1)
        hot, cold = next(gen), next(gen)
        now = T0
        # hot: exhausted on B and replicated to A (the takeover path).
        now = exhaust_key(b, hot, now)
        deadline = time.monotonic() + 5
        while (
            time.monotonic() < deadline
            and hot.encode() not in a.cl.replica_store
        ):
            time.sleep(0.1)
        # cold: 1 of burst 2 spent on B, then checkpointed.  Replication
        # may or may not have pushed it by the kill — the checkpoint is
        # what guarantees the spend survives.
        res = b.cl.rate_limit_batch([cold], 2, 2, 600, 1, now)
        assert res.status[0] == 0 and res.allowed[0]
        ck = Checkpointer(b.limiter, tmp_path, interval_ns=1 << 62)
        assert ck.checkpoint_now(now, force_base=True) >= 2
        b.kill()
        # A serves hot during the outage from the absorbed replica.
        res = a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + 1)
        assert res.status[0] == 0 and not res.allowed[0]
        # B restarts on the same disk: restore the chain FIRST (into a
        # swept-empty table), then announce.
        b2 = Node(1, nodes)
        b2.limiter.sweep(1 << 62)  # clear the constructor's warm-up row
        rres = recover_into(b2.cl, tmp_path, now + 2)
        assert rres is not None and rres.restored >= 2
        b2.join_cluster()
        settle_handoffs(a, b2)
        # hot: migrate-back (same-or-newer than the checkpoint) kept it
        # denied — no re-allow from the crash.
        res = b2.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + 3)
        assert res.status[0] == 0 and not res.allowed[0]
        # cold: the checkpointed spend survived — exactly one token
        # left, not a fresh bucket.
        res = b2.cl.rate_limit_batch([cold], 2, 2, 600, 1, now + 3)
        assert res.status[0] == 0 and res.allowed[0]
        res = b2.cl.rate_limit_batch([cold], 2, 2, 600, 1, now + 4)
        assert res.status[0] == 0 and not res.allowed[0]
        # Newest-wins reconcile, directly: replay a STALE inbound row
        # for cold (older TAT than the live local row).  It must be
        # counted + dropped, never clobber the newer local state.
        k_col, _s, _sh, t_col, _e, _c, _d = export_state(b2.cl.local)
        rows = {k: int(t_col[i]) for i, k in enumerate(k_col)}
        cold_local = rows[
            cold if cold in rows else cold.encode()
        ]
        stale_before = b2.cl.reconciled_stale
        b2.cl.apply_migrate(
            0, b2.cl.epoch, [cold.encode()], [cold_local - 1], [now + 600 * NS]
        )
        assert b2.cl.reconciled_stale == stale_before + 1
        assert b2.cl.cluster_view()["reconciled_stale"] >= 1
        res = b2.cl.rate_limit_batch([cold], 2, 2, 600, 1, now + 5)
        assert res.status[0] == 0 and not res.allowed[0], (
            "stale migrate-back clobbered the newer restored row"
        )
    finally:
        for n in (a, b, b2):
            if n is not None:
                try:
                    n.kill()
                except Exception:
                    pass


def test_wire_window_fast_path_feeds_replication():
    """The native transports' dispatch_wire_window fast path decides
    exactly the locally-owned rows warm replication exists to protect;
    its decisions must reach the successor's replica store like every
    other path (regression: the fast path silently skipped the pump)."""
    from throttlecrab_tpu.native import native_available

    if not native_available():
        pytest.skip("no C++ keymap")

    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]

    class NativeNode(Node):
        def __init__(self, index):
            from throttlecrab_tpu.parallel.cluster import (
                ClusterLimiter,
                ClusterServer,
            )

            self.index = index
            self.limiter = TpuRateLimiter(capacity=CAP, keymap="native")
            self.limiter.rate_limit_batch(
                ["__warm__"], 5, 100, 60, 1, T0 - NS
            )
            self.cl = ClusterLimiter(
                self.limiter, nodes, index, vnodes=64, replicate=True,
                io_timeout_s=60.0, handoff_timeout_s=4.0,
            )
            self.srv = ClusterServer(
                "127.0.0.1", int(nodes[index].rpartition(":")[2]),
                self.cl.local, self.cl.device_lock, cluster=self.cl,
            )
            self.loop = asyncio.new_event_loop()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            asyncio.run_coroutine_threadsafe(
                self.srv.start(), self.loop
            ).result(timeout=10)

    a = NativeNode(0)
    b = NativeNode(1)
    try:
        a.join_cluster()
        b.join_cluster()
        ring = a.cl.ring
        keys = [
            b"ww:%d" % i for i in range(6000)
            if ring.owner_of(b"ww:%d" % i) == 0
        ][:32]
        blob = b"".join(keys)
        offsets = np.zeros(len(keys) + 1, np.int64)
        np.cumsum([len(k) for k in keys], out=offsets[1:])
        params = np.array([[3, 10, 3600, 1]] * len(keys), np.int64)
        handle = a.cl.dispatch_wire_window([(blob, offsets, params)], T0)
        assert handle is not None, "all-local window must take fast path"
        res = handle.fetch()[0]
        assert res.allowed.all()
        # The decided rows must reach B's replica store via the pump.
        deadline = time.monotonic() + 8
        while (
            time.monotonic() < deadline
            and keys[0] not in b.cl.replica_store
        ):
            time.sleep(0.1)
        assert keys[0] in b.cl.replica_store, (
            "wire fast path bypassed warm replication"
        )
    finally:
        for n in (a, b):
            try:
                n.kill()
            except Exception:
                pass


def test_takeover_traffic_replicates_to_live_successor():
    """Keys decided during a takeover must keep a second copy: their
    ring successor-excluding-self is the DEAD node, so the replica
    pump must route them to the next LIVE node instead of dropping
    them (regression: during an outage the absorbed range was
    single-copy, and a second failure would have lost it)."""
    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)
    b = Node(1, nodes)
    c = Node(2, nodes)
    try:
        for n in (a, b, c):
            n.join_cluster()
        ring = a.cl.ring
        # A key owned by C whose failover target (exclude C) is A.
        hot = next(
            k for k in (f"ts:{i}" for i in range(8000))
            if ring.owner_of(k.encode()) == 2
            and ring.owner_of(k.encode(), exclude=frozenset({2})) == 0
        )
        c.kill()
        # Drive it through A: breaker opens, A takes over and decides.
        for i in range(6):
            res = a.cl.rate_limit_batch([hot], 5, 100, 60, 1, T0 + i)
            assert res.status[0] == 0
        # The replica of the absorbed key must reach the live third
        # node (B), not be dropped toward dead C.
        deadline = time.monotonic() + 8
        while (
            time.monotonic() < deadline
            and hot.encode() not in b.cl.replica_store
        ):
            time.sleep(0.1)
        assert hot.encode() in b.cl.replica_store, (
            "takeover traffic left the absorbed range single-copy"
        )
    finally:
        for n in (a, b, c):
            try:
                n.kill()
            except Exception:
                pass


# --------------------------------------------------------- reweight #


def test_reweight_migrates_ranges_and_stays_exact():
    """announce_weight (the supervisor's degraded-capacity hook target)
    moves vnode ranges out before the flip: decisions across the
    reweight stay oracle-exact and the peer adopts the new weights."""
    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore

    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)
    b = Node(1, nodes)
    try:
        a.join_cluster()
        b.join_cluster()
        oracle = RateLimiter(PeriodicStore())
        pool = [f"rw:{i}" for i in range(64)]
        now = T0
        for step in range(6):
            oracle_check(oracle, (a, b)[step % 2], pool, 4, 10, 60, now,
                         f"pre{step}")
            now += NS // 4
        owned_before = int(
            (a.cl.ring.owners_of(
                np.asarray([__import__("zlib").crc32(k.encode())
                            for k in pool], np.uint32)
            ) == 0).sum()
        )
        a.cl.announce_weight(0.5)
        # Peer adopts the broadcast weights.
        deadline = time.monotonic() + 5
        while (
            time.monotonic() < deadline
            and b.cl.ring.weights.get(0) != 0.5
        ):
            time.sleep(0.05)
        assert b.cl.ring.weights.get(0) == 0.5
        owned_after = int(
            (a.cl.ring.owners_of(
                np.asarray([__import__("zlib").crc32(k.encode())
                            for k in pool], np.uint32)
            ) == 0).sum()
        )
        assert owned_after < owned_before
        assert a.cl.peers[1].migrated > 0 or owned_before == owned_after
        for step in range(8):
            oracle_check(oracle, (a, b)[step % 2], pool, 4, 10, 60, now,
                         f"post{step}")
            now += NS // 4
        # Restore: ranges migrate back, still exact.
        a.cl.announce_weight(1.0)
        for step in range(6):
            oracle_check(oracle, (a, b)[step % 2], pool, 4, 10, 60, now,
                         f"back{step}")
            now += NS // 4
    finally:
        for n in (a, b):
            try:
                n.kill()
            except Exception:
                pass


def test_supervisor_degrade_calls_capacity_hooks():
    """The supervisor's degrade/re-promote paths fire the capacity
    hooks run_server wires to the cluster's schedule_reweight."""
    from throttlecrab_tpu.faults import FaultInjector, arm, disarm, parse_spec
    from throttlecrab_tpu.server.supervisor import SupervisedLimiter

    calls = []
    lim = TpuRateLimiter(capacity=256)
    lim.rate_limit_batch(["__warm__"], 5, 100, 60, 1, T0 - NS)
    sup = SupervisedLimiter(
        lim, retries=0, probe_interval_ms=1, sleep_fn=lambda s: None
    )
    sup.on_degrade = lambda: calls.append("degrade")
    sup.on_repromote = lambda: calls.append("repromote")
    try:
        arm(FaultInjector(parse_spec("launch:count:1"), seed=3))
        res = sup.rate_limit_batch(["k"], 5, 100, 60, 1, T0)
        assert res.allowed[0]
        assert sup.state == "degraded"
        assert calls == ["degrade"]
        # Device heals; the next decide past the probe interval
        # re-promotes and fires the restore hook.
        res = sup.rate_limit_batch(["k"], 5, 100, 60, 1, T0 + 10**9)
        assert sup.state == "ok"
        assert calls == ["degrade", "repromote"]
    finally:
        disarm()


# ---------------------------------------------- partition heal (slow) #


@pytest.mark.slow
def test_partition_heal_reannounce_converges():
    """A 'partitioned' node (listener down, process alive) is declared
    dead and its range absorbed; when its listener returns, the pump's
    periodic re-announce heals the link and both sides converge back to
    single-owner routing."""
    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes, breaker_cooldown_s=0.3)
    b = Node(1, nodes, breaker_cooldown_s=0.3)
    try:
        a.join_cluster()
        b.join_cluster()
        ring = a.cl.ring
        hot = next(
            k for k in (f"ph:{i}" for i in range(4000))
            if ring.owner_of(k.encode()) == 1
        )
        now = exhaust_key(b, hot, T0)
        # Partition: B's listener goes away (sockets drop), B itself
        # keeps running (its pump will later re-announce).
        asyncio.run_coroutine_threadsafe(b.srv.stop(), b.loop).result(10)
        # Attempts inside the reconnect backoff don't count toward the
        # breaker (by design); space them out until it trips.
        deadline = time.monotonic() + 10
        i = 0
        while (
            not a.cl.peers[1].breaker_open
            and time.monotonic() < deadline
        ):
            a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + i)
            i += 1
            time.sleep(0.15)
        assert a.cl.peers[1].breaker_open
        # Heal: the listener returns on the same port.
        b.srv = ClusterServer(
            "127.0.0.1", ports[1], b.cl.local, b.cl.device_lock,
            cluster=b.cl,
        )
        asyncio.run_coroutine_threadsafe(b.srv.start(), b.loop).result(10)
        # The pumps' re-announce probes run on the breaker cooldown
        # cadence; wait for the link to heal in both directions.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and (
            a.cl.peers[1].breaker_open or 1 in a.cl._absorbed
        ):
            time.sleep(0.2)
        assert not a.cl.peers[1].breaker_open, "link never healed"
        # Routing restored: A forwards to B and the state converged
        # (the key is still denied wherever it is decided).
        res = a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + 10)
        assert res.status[0] == 0 and not res.allowed[0]
        res = b.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + 11)
        assert res.status[0] == 0 and not res.allowed[0]
    finally:
        for n in (a, b):
            try:
                n.kill()
            except Exception:
                pass


# --------------------------------------- 3-process acceptance (slow) #

HTTP_PORTS = (28480, 28481, 28482)
RPC_PORTS = (28490, 28491, 28492)
NODES3 = ",".join(f"127.0.0.1:{p}" for p in RPC_PORTS)


def spawn_node3(index: int, trace_dir: str = ""):
    env = dict(os.environ)
    env["THROTTLECRAB_PLATFORM"] = "cpu"
    env["THROTTLECRAB_CLUSTER_TIMEOUT_MS"] = "60000"
    if trace_dir:
        # Full-capture flight recorder: every decided window lands in
        # this node's trace file (finalized on graceful shutdown), so
        # the soak's timeline is replayable after the fact.
        env["THROTTLECRAB_TRACE_DIR"] = trace_dir
        env["THROTTLECRAB_TRACE_MODE"] = "full"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "throttlecrab_tpu.server",
            "--http", "--http-port", str(HTTP_PORTS[index]),
            "--cluster-nodes", NODES3, "--cluster-index", str(index),
            "--store", "adaptive", "--log-level", "warn",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def wait_healthy3(proc, port, deadline_s=180):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            pytest.fail(f"node exited early rc={proc.returncode}:\n{out}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=1
            ) as r:
                if r.status == 200:
                    return
        except Exception:
            time.sleep(0.5)
    pytest.fail("node never became healthy")


def throttle3(port, key, burst=3, count=2, period=600):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/throttle",
        data=json.dumps(
            {"key": key, "max_burst": burst, "count_per_period": count,
             "period": period}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def cluster_view3(port):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/health/cluster", timeout=10
    ) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_three_node_join_kill_rejoin_acceptance(tmp_path):
    """The end-to-end elastic lifecycle on three real server processes:
    sustained load survives a node join (zero failed requests, ranges
    migrate) and a node exit via SIGTERM — now the graceful drain +
    planned leave, with the kill-path takeover as its bounded fallback
    (zero failed requests on the range either way — an exhausted key
    stays denied through the handoff), and the departed node rejoins
    with the state migrated back.  This is the CI acceptance gate for
    the elastic path.

    Record -> replay pass (ISSUE 14): every node runs with the
    full-capture flight recorder armed; after the soak, the three
    nodes' traces are merged by server timestamp and checked for
    conservation against the client's own observation — every decision
    the client saw appears in the recorded timeline exactly once, with
    the same outcome, in the same per-key order (zero lost or
    double-counted decisions across join, kill and rejoin)."""
    from collections import defaultdict

    from throttlecrab_tpu.parallel.ring import HashRing

    trace_dirs = [str(tmp_path / f"node{i}") for i in range(3)]
    for d in trace_dirs:
        os.makedirs(d, exist_ok=True)
    #: Client ground truth: key -> [allowed, ...] in request order.
    client_log = defaultdict(list)

    def throttle3t(port, key, **kw):
        doc = throttle3(port, key, **kw)
        client_log[key].append(bool(doc["allowed"]))
        return doc

    ring3 = HashRing(NODES3.split(","), 128)
    procs = [
        spawn_node3(0, trace_dirs[0]), spawn_node3(1, trace_dirs[1]),
        None,
    ]
    try:
        wait_healthy3(procs[0], HTTP_PORTS[0])
        wait_healthy3(procs[1], HTTP_PORTS[1])

        pool = [f"acc:{i}" for i in range(60)]
        failures = 0
        # Steady state through both frontends (also warms compiles).
        for step in range(4):
            for k in pool:
                throttle3t(HTTP_PORTS[step % 2], k, burst=50, count=100,
                          period=60)

        # ---- JOIN under load ---------------------------------------- #
        procs[2] = spawn_node3(2, trace_dirs[2])
        join_allowed = []
        deadline = time.time() + 180
        joined = False
        while time.time() < deadline:
            for k in pool:
                try:
                    join_allowed.append(
                        throttle3t(HTTP_PORTS[0], k, burst=50, count=100,
                                  period=60)["allowed"]
                    )
                except urllib.error.HTTPError:
                    failures += 1
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{HTTP_PORTS[2]}/health", timeout=1
                ) as r:
                    if r.status == 200:
                        joined = True
            except Exception:
                pass
            if joined:
                break
        assert joined, "node 2 never became healthy"
        assert failures == 0, f"{failures} client failures during join"
        # One more pass so traffic flows through the 3-node ring.
        for k in pool:
            throttle3t(HTTP_PORTS[2], k, burst=50, count=100, period=60)
        view = cluster_view3(HTTP_PORTS[0])
        assert view["mode"] == "ring"

        # ---- LEAVE (SIGTERM drain) with warm replica ----------------- #
        hot = next(
            k for k in (f"hotacc:{i}" for i in range(10_000))
            if ring3.owner_of(k.encode()) == 2
        )
        # Exhaust it on the 3-node cluster (burst 2): 2 allowed, rest
        # denied; replica deltas flow to the successor.
        seq = [throttle3t(HTTP_PORTS[2], hot, burst=2)["allowed"]
               for _ in range(4)]
        assert seq == [True, True, False, False]
        time.sleep(2.0)  # replica pump cadence
        # SIGTERM now drains gracefully: planned leave (zero-staleness
        # handoff) with the kill-path takeover as its bounded fallback;
        # either way the exit must cost zero client-visible failures.
        procs[2].terminate()
        procs[2].wait(timeout=30)
        # Zero client-visible failures on the departed range, and the
        # exhausted key STAYS denied — the leave handoff (or, on the
        # fallback path, the warm replica) carried its TAT.
        for i in range(3):
            r = throttle3t(HTTP_PORTS[i % 2], hot, burst=2)
            assert r["allowed"] is False, (
                "node exit lost the handed-off state"
            )
        fresh = next(
            k for k in (f"freshacc:{i}" for i in range(10_000))
            if ring3.owner_of(k.encode()) == 2
        )
        assert throttle3t(HTTP_PORTS[0], fresh, burst=5)["allowed"] is True
        views = [cluster_view3(HTTP_PORTS[i]) for i in range(2)]
        # The survivors observed the exit: a planned leave (the SIGTERM
        # drain's normal path) or a takeover (its bounded fallback).
        assert any(
            v["leaves"] >= 1 or v["takeovers"] >= 1 for v in views
        ), views

        # ---- REJOIN ------------------------------------------------- #
        procs[2] = spawn_node3(2, trace_dirs[2])
        wait_healthy3(procs[2], HTTP_PORTS[2])
        time.sleep(1.0)
        # The rejoined node serves its range from the migrated-back
        # state: still denied on its own frontend.
        assert throttle3t(HTTP_PORTS[2], hot, burst=2)["allowed"] is False
        assert throttle3t(HTTP_PORTS[0], hot, burst=2)["allowed"] is False

        # ---- RECORD -> REPLAY: conservation over the merged traces -- #
        # Graceful shutdown finalizes each node's full-capture trace
        # file (incl. node 2's pre-kill file: SIGTERM closed it).
        for p in procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in procs:
            if p is not None:
                p.wait(timeout=60)
        import glob as _glob

        from throttlecrab_tpu.replay.trace import Trace

        rows = []
        for d in trace_dirs:
            for path in _glob.glob(os.path.join(d, "*.tctr")):
                for w in Trace.load(path).windows:
                    for j in range(len(w)):
                        rows.append((
                            w.now_ns,
                            w.keys[j].decode(),
                            bool(w.allowed[j]),
                            int(w.status[j]),
                        ))
        # Merge the three nodes' timelines by the server-side window
        # timestamp (one wall clock: same host).  The client is serial,
        # so per-key order is total.
        rows.sort(key=lambda r: r[0])
        recorded = defaultdict(list)
        for _t, key, was_allowed, status in rows:
            assert status == 0, (key, status)
            recorded[key].append(was_allowed)
        # Conservation: every decision the client observed appears in
        # the recorded timeline exactly once (nothing lost to the kill
        # or the migrations, nothing double-counted by forwarding),
        # with the same outcome, in the same per-key order.
        assert set(recorded) == set(client_log), (
            set(recorded) ^ set(client_log)
        )
        for key, seq_client in client_log.items():
            assert recorded[key] == seq_client, (
                f"replayed timeline for {key!r} diverged: "
                f"{recorded[key]} != {seq_client}"
            )
    finally:
        for p in procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in procs:
            if p is not None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()


# --------------------------------------------- record -> replay #


def test_cluster_record_replay_join_kill_rejoin():
    """Record/replay over the elastic lifecycle (ISSUE 14): a 3-node
    in-process cluster captures its client-visible decisions and
    membership timeline into one trace (join -> kill -> rejoin), and a
    ClusterReplayer reconstructs the membership from the recorded
    events and replays the identical outcome vector — zero lost or
    double-counted decisions from the replayed timeline (an exhausted
    key must stay denied across the takeover in the replay too)."""
    from throttlecrab_tpu.replay.player import (
        ClusterReplayer,
        outcome_vector,
    )
    from throttlecrab_tpu.replay.recorder import FlightRecorder, arm, disarm
    from throttlecrab_tpu.replay.trace import Trace

    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    recorder = FlightRecorder(capacity=4096, out_dir="/tmp")
    arm(recorder)
    a = Node(0, nodes)
    b = Node(1, nodes)
    c = None
    b2 = None
    replayer = None
    try:
        a.join_cluster()
        b.join_cluster()
        for n in (a, b):
            n.cl.capture = True
        ring = a.cl.ring
        pool = [f"rr:{i}" for i in range(32)]
        hot = next(
            k for k in (f"rrhot:{i}" for i in range(4000))
            if ring.owner_of(k.encode()) == 1
        )
        now = T0
        frontends = [a, b]
        for step in range(6):
            via = frontends[step % len(frontends)]
            via.cl.rate_limit_batch(pool, 8, 100, 60, 1, now)
            now += NS // 4

        # JOIN under load: node 2 boots, announces, serves.
        c = Node(2, nodes)
        c.cl.capture = True
        c.join_cluster()
        frontends = [a, b, c]
        for step in range(6):
            via = frontends[step % len(frontends)]
            via.cl.rate_limit_batch(pool, 8, 100, 60, 1, now)
            now += NS // 4

        # Exhaust the hot key on its owner; replica flows to successor.
        for i in range(4):
            res = b.cl.rate_limit_batch([hot], 2, 2, 600, 1, now + i)
        now += 4
        assert not res.allowed[0], "precondition: hot key exhausted"
        successor = ring.owner_of(hot.encode(), exclude=frozenset({1}))
        succ_node = {0: a, 2: c}[successor]
        deadline = time.monotonic() + 8
        while (
            time.monotonic() < deadline
            and hot.encode() not in succ_node.cl.replica_store
        ):
            time.sleep(0.1)
        assert hot.encode() in succ_node.cl.replica_store

        # KILL: the successor absorbs; exhausted key stays denied.
        b.kill()
        for i in range(3):
            res = a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now)
            assert res.status[0] == 0 and not res.allowed[0]
            now += NS // 4
        a.cl.rate_limit_batch(pool, 8, 100, 60, 1, now)
        now += NS // 4

        # REJOIN: fresh node 1, state migrated back, still denied.
        b2 = Node(1, nodes)
        b2.cl.capture = True
        b2.join_cluster()
        res = b2.cl.rate_limit_batch([hot], 2, 2, 600, 1, now)
        assert res.status[0] == 0 and not res.allowed[0]
        now += NS // 4
        b2.cl.rate_limit_batch(pool, 8, 100, 60, 1, now)

        path, _n = recorder.dump()
        disarm()
        trace = Trace.load(path)
        kinds = [e.kind for e in trace.events]
        assert "cluster-join" in kinds and "cluster-takeover" in kinds

        # Replay the whole timeline on a fresh in-process cluster.
        replayer = ClusterReplayer(3, capacity=CAP)
        replayed = replayer.replay(trace, settle_s=1.0)
        assert outcome_vector(replayed) == trace.outcome_vector(), (
            "replayed cluster timeline drifted from the recorded "
            "outcomes (lost or double-counted decisions)"
        )
    finally:
        disarm()
        if replayer is not None:
            replayer.close()
        for n in (a, b, c, b2):
            if n is not None:
                try:
                    n.kill()
                except Exception:
                    pass


# ------------------------------------------------------------------ #
# Lifecycle ops off the event loop (PR 11 async-boundary fix)


def test_ring_and_join_ops_adopt_through_server():
    """OP_RING and the OP_JOIN ack now run apply_ring / ring_state on
    the lifecycle executor instead of the server's event loop (the
    async-boundary checker pins the static half; this pins behavior):
    per-connection ordering must survive the move — a ring broadcast
    followed by a join on the SAME connection must see the adopted
    weights in the ack."""
    from throttlecrab_tpu.parallel.cluster import (
        _HDR,
        OP_RING,
        OP_RING_STATE,
        decode_ring,
        encode_join,
        encode_ring,
    )

    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)  # peer 1 never starts: only the frames matter
    try:
        with a.cl._mu:
            epoch0 = a.cl.epoch
        s = socket.create_connection(("127.0.0.1", ports[0]), 5)
        s.settimeout(30)
        try:
            # Weight broadcast, then a join announcement, pipelined on
            # one connection.  The server must apply the ring BEFORE
            # answering the join (op order == reply order).
            s.sendall(encode_ring(OP_RING, epoch0 + 7, [1.0, 0.25]))
            s.sendall(encode_join(1))
            head = b""
            while len(head) < _HDR.size:
                head += s.recv(_HDR.size - len(head))
            body_len, op = _HDR.unpack(head)
            assert op == OP_RING_STATE
            body = b""
            while len(body) < body_len:
                body += s.recv(body_len - len(body))
            epoch, weights = decode_ring(body)
            assert epoch >= epoch0 + 7
            # Peer 1's announced weight was adopted; node 0 stays the
            # authority for its own (1.0).
            assert weights == [1.0, 0.25]
        finally:
            s.close()
        with a.cl._mu:
            assert a.cl.ring.weights[1] == 0.25
    finally:
        a.kill()


def test_replica_push_failure_retries_next_live_successor():
    """A replica push that fails (successor just died, or a stale
    OP_JOIN heal re-closed its breaker before re-detection) must retry
    once on the NEXT live successor instead of dropping the rows —
    otherwise the absorbed range stays single-copy for the whole
    re-detection window (the deterministic twin of the timing-
    sensitive takeover test above)."""
    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    lim = TpuRateLimiter(capacity=CAP)
    cl = ClusterLimiter(lim, nodes, 0, vnodes=64, replicate=True)
    try:
        ring = cl.ring
        # A key whose first successor (excluding self) is node 2 and
        # whose next successor is node 1.
        hot = next(
            k for k in (f"rt:{i}".encode() for i in range(8000))
            if ring.owner_of(k, exclude=frozenset({0})) == 2
            and ring.owner_of(k, exclude=frozenset({0, 2})) == 1
        )
        sent = {1: [], 2: []}

        class _P:
            def __init__(self, idx, fail):
                self.idx = idx
                self.fail = fail
                self.lock = threading.Lock()
                self.breaker_open = False
                self.failed = 0

            def send_frame(self, frame):
                if self.fail:
                    raise ConnectionRefusedError(111, "refused")
                sent[self.idx].append(frame)

            def record_failure(self):
                self.failed += 1

            def close(self):
                pass

        cl.peers[1] = _P(1, fail=False)
        cl.peers[2] = _P(2, fail=True)  # dies on the push
        entry = (
            [hot],
            np.asarray([5], np.int64), np.asarray([100], np.int64),
            np.asarray([60], np.int64), T0,
            np.asarray([6 * NS], np.int64),
            np.asarray([0], np.uint8), np.asarray([True], bool),
            False,
        )
        cl._flush_replicas([entry])
        assert sent[2] == []  # the first successor's push failed...
        assert len(sent[1]) == 1  # ...and the rows landed on the next
        from throttlecrab_tpu.parallel.cluster import decode_rows

        _origin, _epoch, keys, _tats, _exps = decode_rows(
            sent[1][0][5:]
        )
        assert keys == [hot]
    finally:
        cl.close()

# ------------------------------------------------------------------ #
# Planned leave / rolling restart (PR 17 graceful lifecycle)


def test_leave_under_load_exact_differential():
    """A node leaves mid-stream (planned departure): every decision
    before, during and after the handoff matches the single-node
    scalar oracle value-for-value.  The leave path is OP_JOIN run in
    reverse, so the join test's zero-lost / zero-double-counted
    contract holds — with zero staleness, unlike the kill path whose
    replica handoff tolerates the replication lag."""
    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore

    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    # Same slowed gate clock as the join test: the receivers' handoff
    # deadlines must not expire under CI load while the leave stream
    # is genuinely in flight.
    t_base = time.monotonic()
    slow_clock = lambda: t_base + (time.monotonic() - t_base) * 0.05  # noqa: E731
    a = Node(0, nodes, clock=slow_clock)
    b = Node(1, nodes, clock=slow_clock)
    c = Node(2, nodes, clock=slow_clock)
    try:
        for n in (a, b, c):
            n.join_cluster()
        settle_handoffs(a, b, c)
        oracle = RateLimiter(PeriodicStore())
        pool = [f"lv:{i}" for i in range(48)]
        now = T0
        frontends = [a, b, c]
        for step in range(24):
            if step == 10:
                # Planned leave under load: B hands its whole table
                # off and goes lame-duck; A and C keep the stream
                # exact through the flip (B stays up, so any frontend
                # racing the announcement still reaches it and B
                # re-forwards — decisions never fork).  leave() returns
                # once every range was SENT; settle until the receivers
                # APPLIED them, so a loaded box can't expire a gate on
                # rows that are genuinely in flight.
                assert b.cl.leave(), "leave with live peers must ack"
                settle_handoffs(a, c)
                frontends = [a, c]
            via = frontends[step % len(frontends)]
            oracle_check(
                oracle, via, pool, 4, 10, 60, now, f"step{step}"
            )
            now += NS // 4
        # The departing node's state actually moved: receivers
        # installed its migrated rows, and no handoff gate expired
        # (an expired gate means the exactness above was luck).
        assert b.cl.leave_count >= 1
        assert a.cl.leave_count >= 1 and c.cl.leave_count >= 1
        assert a.cl.migrated_in + c.cl.migrated_in > 0
        assert a.cl.handoff_timeouts == 0
        assert c.cl.handoff_timeouts == 0
    finally:
        for n in (a, b, c):
            try:
                n.kill()
            except Exception:
                pass


def test_lame_duck_forwards_not_decides(two_ring_nodes):
    """After leave() the departed node still answers every request —
    lame-duck mode forwards to the new owner instead of deciding from
    its exported (now-authoritative-elsewhere) table."""
    a, b = two_ring_nodes
    keys = [f"ld:{i}" for i in range(16)]
    res = a.cl.rate_limit_batch(keys, 4, 10, 60, 1, T0)
    assert (res.status == 0).all() and res.allowed.all()
    assert a.cl.leave(), "leave with a live peer must ack"
    assert a.cl._lame_duck
    fwd0 = a.cl.peers[1].forwarded
    res = a.cl.rate_limit_batch(keys, 4, 10, 60, 1, T0 + NS)
    assert (res.status == 0).all() and res.allowed.all()
    # The batch went over the wire: nothing decides locally on a
    # weight-0 lame duck (forwarded counts forward RPCs).
    assert a.cl.peers[1].forwarded > fwd0
    # And the handoff carried the pre-leave TATs: the second hit on a
    # burst-4 key sees the first one (remaining 2, not a fresh 3).
    assert (res.remaining == 2).all(), "leave handoff lost state"


def test_leave_fault_falls_back_to_kill_path():
    """Injected `leave` faults break the announcement: leave() reports
    the partial handoff (returns False) instead of pretending, and the
    ordinary kill-path takeover still covers the exit — the survivor
    serves the departed range from its warm replica with zero
    client-visible failures (bounded staleness, not lost decisions)."""
    from throttlecrab_tpu.faults import FaultInjector, arm, disarm, parse_spec

    ports = free_ports(2)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    a = Node(0, nodes)
    b = Node(1, nodes)
    try:
        a.join_cluster()
        b.join_cluster()
        ring = a.cl.ring
        hot = next(
            k for k in (f"lf:{i}" for i in range(4000))
            if ring.owner_of(k.encode()) == 1
        )
        now = T0
        now = exhaust_key(b, hot, now)
        # Wait for the warm replica so the fallback has state to serve.
        deadline = time.monotonic() + 5
        while (
            time.monotonic() < deadline
            and hot.encode() not in a.cl.replica_store
        ):
            time.sleep(0.1)
        assert hot.encode() in a.cl.replica_store
        arm(FaultInjector(parse_spec("leave:persistent"), seed=3))
        assert b.cl.leave() is False, "broken announce must not ack"
        disarm()
        b.kill()
        # Kill path: the survivor absorbs the range and an exhausted
        # key STAYS denied (the replica carried its TAT).
        res = a.cl.rate_limit_batch([hot], 2, 2, 600, 1, now)
        assert res.status[0] == 0 and not res.allowed[0]
    finally:
        disarm()
        for n in (a, b):
            try:
                n.kill()
            except Exception:
                pass


def test_deadline_shed_differential(two_ring_nodes):
    """Rows already past their client deadline shed with
    STATUS_DEADLINE before any device dispatch or forward — and a shed
    row must NOT consume quota: the batchmates and every later
    decision match an oracle that never saw the shed requests."""
    from test_tpu_batch import oracle_batch

    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore
    from throttlecrab_tpu.tpu.limiter import STATUS_DEADLINE

    a, b = two_ring_nodes
    oracle = RateLimiter(PeriodicStore())
    pool = [f"dl:{i}" for i in range(32)]
    now = T0
    oracle_check(oracle, a, pool, 4, 10, 60, now, "warm")
    now += NS
    # Half the batch arrives already expired (even rows); the live
    # half must still decide exactly, locally and across forwards.
    dl = np.zeros(len(pool), np.int64)
    dl[::2] = now - 1
    dl[1::2] = now + 5 * NS
    res = a.cl.rate_limit_batch(pool, 4, 10, 60, 1, now, deadlines_ns=dl)
    assert (res.status[::2] == STATUS_DEADLINE).all()
    assert not res.allowed[::2].any()
    live_ix = np.arange(1, len(pool), 2)
    live_keys = [pool[i] for i in live_ix]
    nl = len(live_keys)
    exp = oracle_batch(
        oracle, live_keys,
        np.full(nl, 4, np.int64), np.full(nl, 10, np.int64),
        np.full(nl, 60, np.int64), np.ones(nl, np.int64), now,
    )
    np.testing.assert_array_equal(res.status[live_ix], exp["status"])
    np.testing.assert_array_equal(res.allowed[live_ix], exp["allowed"])
    np.testing.assert_array_equal(
        res.remaining[live_ix], exp["remaining"]
    )
    # The shed rows left no trace: the full pool keeps matching an
    # oracle that never saw them, from either frontend.
    now += NS
    oracle_check(oracle, b, pool, 4, 10, 60, now, "post-shed-b")
    now += NS
    oracle_check(oracle, a, pool, 4, 10, 60, now, "post-shed-a")


def test_rolling_restart_soak():
    """Zero-staleness rolling restart: each node in turn leaves
    (planned handoff), dies, restarts empty and rejoins — under a
    continuous oracle-pinned stream.  Every decision across all three
    restart epochs matches the scalar oracle value-for-value, so a
    full fleet roll costs zero staleness and zero lost decisions."""
    from throttlecrab_tpu.core.rate_limiter import RateLimiter
    from throttlecrab_tpu.core.store.periodic import PeriodicStore

    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    t_base = time.monotonic()
    slow_clock = lambda: t_base + (time.monotonic() - t_base) * 0.05  # noqa: E731
    ns = [Node(i, nodes, clock=slow_clock) for i in range(3)]
    try:
        for n in ns:
            n.join_cluster()
        settle_handoffs(*ns)
        oracle = RateLimiter(PeriodicStore())
        pool = [f"rr:{i}" for i in range(48)]
        state = {"now": T0, "step": 0}

        def drive(k_steps):
            for _ in range(k_steps):
                live = [n for n in ns if n is not None]
                via = live[state["step"] % len(live)]
                oracle_check(
                    oracle, via, pool, 4, 10, 60, state["now"],
                    f"step{state['step']}",
                )
                state["now"] += NS // 4
                state["step"] += 1

        drive(3)
        for victim in range(3):
            assert ns[victim].cl.leave(), f"node {victim} leave must ack"
            # The kill below only stays invisible once both survivors
            # have processed the OP_LEAVE announcement (before that
            # they would route at a corpse and fail over to replicas —
            # the kill path, not the one under test here).
            others = [
                n for i, n in enumerate(ns)
                if n is not None and i != victim
            ]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not all(
                victim in n.cl._departed for n in others
            ):
                time.sleep(0.05)
            assert all(victim in n.cl._departed for n in others)
            settle_handoffs(*others)
            ns[victim].kill()
            ns[victim] = None
            drive(3)
            ns[victim] = Node(victim, nodes, clock=slow_clock)
            ns[victim].join_cluster()
            settle_handoffs(*[n for n in ns if n is not None])
            drive(3)
        for n in ns:
            assert n.cl.handoff_timeouts == 0
    finally:
        for n in ns:
            if n is not None:
                try:
                    n.kill()
                except Exception:
                    pass


def test_cluster_record_replay_planned_leave():
    """The rolling-restart soak's trace ingredient: a planned leave is
    captured as a `cluster-leave` event and the ClusterReplayer
    reconstructs it — the replayed outcome vector matches the recorded
    one exactly, because the replay runs the same state-preserving
    handoff the live node did (not the kill path's replica fallback)."""
    from throttlecrab_tpu.replay.player import (
        ClusterReplayer,
        outcome_vector,
    )
    from throttlecrab_tpu.replay.recorder import FlightRecorder, arm, disarm
    from throttlecrab_tpu.replay.trace import Trace

    ports = free_ports(3)
    nodes = [f"127.0.0.1:{p}" for p in ports]
    recorder = FlightRecorder(capacity=4096, out_dir="/tmp")
    arm(recorder)
    a = Node(0, nodes)
    b = Node(1, nodes)
    c = Node(2, nodes)
    replayer = None
    try:
        for n in (a, b, c):
            n.join_cluster()
            n.cl.capture = True
        settle_handoffs(a, b, c)
        pool = [f"rl:{i}" for i in range(32)]
        now = T0
        frontends = [a, b, c]
        for step in range(6):
            frontends[step % 3].cl.rate_limit_batch(
                pool, 4, 10, 60, 1, now
            )
            now += NS // 4
        # Planned leave under load; the lame duck then goes away for
        # good (burst-4 keys driven past their limit, so any replayed
        # staleness would flip a deny to an allow).
        assert b.cl.leave()
        settle_handoffs(a, c)
        frontends = [a, c]
        for step in range(6):
            frontends[step % 2].cl.rate_limit_batch(
                pool, 4, 10, 60, 1, now
            )
            now += NS // 4
        b.kill()
        for step in range(4):
            frontends[step % 2].cl.rate_limit_batch(
                pool, 4, 10, 60, 1, now
            )
            now += NS // 4

        path, _n = recorder.dump()
        disarm()
        trace = Trace.load(path)
        assert "cluster-leave" in [e.kind for e in trace.events]
        replayer = ClusterReplayer(3, capacity=CAP)
        replayed = replayer.replay(trace, settle_s=1.0)
        assert outcome_vector(replayed) == trace.outcome_vector(), (
            "replayed planned-leave timeline drifted from the "
            "recorded outcomes"
        )
    finally:
        disarm()
        if replayer is not None:
            replayer.close()
        for n in (a, b, c):
            try:
                n.kill()
            except Exception:
                pass


def test_leave_and_droute_codecs_roundtrip_and_harden():
    """The two PR 17 wire frames follow the cluster codec contract:
    exact roundtrip, and truncated/corrupt bodies raise the typed
    protocol error instead of mis-decoding."""
    from throttlecrab_tpu.parallel.cluster import (
        ClusterProtocolError,
        _HDR,
        decode_droute,
        decode_leave,
        encode_droute,
        encode_leave,
    )

    frame = encode_leave(3, 17)
    assert decode_leave(frame[_HDR.size:]) == (3, 17)
    with pytest.raises(ClusterProtocolError):
        decode_leave(frame[_HDR.size:-1])

    keys = [b"a", b"bb", b"ccc"]
    params = np.array(
        [[4, 10, 60, 1], [5, 11, 61, 2], [6, 12, 62, 3]], np.int64
    )
    budgets = np.array([7 * NS, 0, 3 * NS], np.int64)
    frame = encode_droute(keys, params, T0, 2, budgets)
    hops, k2, p2, now2, b2 = decode_droute(frame[_HDR.size:])
    assert hops == 2 and k2 == keys and now2 == T0
    np.testing.assert_array_equal(p2, params)
    np.testing.assert_array_equal(b2, budgets)
    # Truncation anywhere in the budget column or batch body raises.
    for cut in (1, 10, 30):
        with pytest.raises(ClusterProtocolError):
            decode_droute(frame[_HDR.size:-cut])
