"""Chaos suite: failure-domain supervision under injected faults.

Drives the fault registry (throttlecrab_tpu/faults/) through the launch
supervisor (server/supervisor.py) and pins the acceptance contract:

  * transient launch/fetch faults are absorbed by retries — the client
    sees ZERO failed requests;
  * a persistent device failure degrades to the host scalar oracle,
    whose decisions are byte-identical to core/ GCRA (differential,
    virtual time), and the server keeps serving;
  * recovery re-promotes host-mutated state with nothing lost or
    double-counted, invalidating the front tier via on_restore;
  * deterministic errors (keymap capacity, bad params) are never
    retried and never degrade — they are the request's fault;
  * everything is observable: /health and the supervisor metrics.

The fast slice here runs in tier-1 CI; the long soak is marked slow.
"""

import asyncio

import pytest

from throttlecrab_tpu import faults
from throttlecrab_tpu.core.rate_limiter import RateLimiter
from throttlecrab_tpu.core.store.mapstore import MapStore
from throttlecrab_tpu.server.engine import BatchingEngine, ThrottleError
from throttlecrab_tpu.server.metrics import Metrics
from throttlecrab_tpu.server.supervisor import (
    STATE_DEGRADED,
    STATE_OK,
    SupervisedLimiter,
    classify_exception,
    supervisor_state,
)
from throttlecrab_tpu.server.types import ThrottleRequest
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


class VirtualClock:
    def __init__(self, start_ns=T0):
        self.now = start_ns

    def __call__(self):
        return self.now


class _PlainStore(MapStore):
    def _maybe_cleanup(self, now_ns):
        pass


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


def arm(spec: str, seed: int = 1) -> faults.FaultInjector:
    inj = faults.FaultInjector(
        faults.parse_spec(spec), seed=seed, sleep_fn=lambda s: None
    )
    faults.arm(inj)
    return inj


def make_supervised(capacity=1024, **kw):
    kw.setdefault("sleep_fn", lambda s: None)  # no real backoff waits
    return SupervisedLimiter(TpuRateLimiter(capacity=capacity), **kw)


def make_engine(limiter, clock=None, metrics=None, **kw):
    clock = clock or VirtualClock()
    engine = BatchingEngine(
        limiter, now_fn=clock, metrics=metrics, **kw
    )
    return engine, clock


def req(key="k", burst=10, count=100, period=60, quantity=1):
    return ThrottleRequest(key, burst, count, period, quantity)


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------------ #
# The registry itself.


def test_parse_spec_validates():
    specs = faults.parse_spec("launch:transient:0.5, fetch:count:3")
    assert [s.site for s in specs] == ["launch", "fetch"]
    for bad in (
        "nope:persistent",
        "launch:explode",
        "launch:transient",     # missing required arg
        "launch:transient:2.0",  # p out of range
        "launch",
    ):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_injection_is_deterministic():
    """Same seed → same fault sequence; that is the replay contract."""

    def firing_pattern(seed):
        inj = faults.FaultInjector(
            faults.parse_spec("launch:transient:0.5"), seed=seed
        )
        out = []
        for _ in range(64):
            try:
                inj.check("launch")
                out.append(False)
            except faults.InjectedDeviceError:
                out.append(True)
        return out

    assert firing_pattern(7) == firing_pattern(7)
    assert firing_pattern(7) != firing_pattern(8)


def test_hang_mode_uses_injected_sleep():
    slept = []
    inj = faults.FaultInjector(
        faults.parse_spec("launch:hang:0.25"), sleep_fn=slept.append
    )
    inj.check("launch")  # stalls, then passes
    assert slept == [0.25]


def test_classifier_taxonomy():
    assert classify_exception(
        faults.InjectedDeviceError("UNAVAILABLE: device lost")
    ) == "transient"
    assert classify_exception(ConnectionError("peer gone")) == "transient"
    from throttlecrab_tpu.core.errors import InternalError

    assert classify_exception(InternalError("bucket table full")) == (
        "deterministic"
    )
    assert classify_exception(ValueError("bad input")) == "deterministic"


# ------------------------------------------------------------------ #
# Transient faults: retries absorb them — zero client failures.


def test_transient_launch_faults_zero_client_failures():
    arm("launch:count:3")
    metrics = Metrics()
    sup = make_supervised(retries=3, metrics=metrics)

    async def main():
        engine, _ = make_engine(
            sup, metrics=metrics, batch_size=32, max_linger_us=500
        )
        return await asyncio.gather(
            *[engine.throttle(req(key=f"t{i}")) for i in range(32)]
        )

    results = run(main())  # gather raises if any future failed
    assert all(r.allowed for r in results)
    assert sup.state == STATE_OK
    assert sup.retry_count == 3
    assert metrics.supervisor_retries == 3
    assert metrics.supervisor_degrades == 0


def test_transient_probability_faults_zero_client_failures():
    inj = arm("launch:transient:0.3", seed=42)
    sup = make_supervised(retries=8)

    async def main():
        engine, clock = make_engine(sup, batch_size=16, max_linger_us=500)
        out = []
        for wave in range(5):
            clock.now += NS
            out.extend(
                await asyncio.gather(
                    *[
                        engine.throttle(req(key=f"p{wave}-{i}"))
                        for i in range(16)
                    ]
                )
            )
        return out

    results = run(main())
    assert all(r.allowed for r in results)
    assert sup.state == STATE_OK
    assert inj.stats()["launch"] > 0  # faults really fired
    assert sup.degrade_count == 0


def test_transient_fetch_faults_zero_client_failures():
    """A fetch is a committed-state read: retrying it is always safe,
    so transient fetch faults are absorbed exactly like launch faults."""
    arm("fetch:count:2")
    sup = make_supervised(retries=3)

    async def main():
        engine, _ = make_engine(sup, batch_size=8, max_linger_us=500)
        return await asyncio.gather(
            *[engine.throttle(req(key=f"f{i}")) for i in range(8)]
        )

    results = run(main())
    assert all(r.allowed for r in results)
    assert sup.state == STATE_OK
    assert sup.retry_count == 2


# ------------------------------------------------------------------ #
# Persistent failure: degrade, serve, stay observable.


def test_persistent_failure_degrades_and_keeps_serving():
    arm("launch:persistent")
    metrics = Metrics()
    sup = make_supervised(retries=2, metrics=metrics)
    metrics.set_engine_state_provider(lambda: sup.state)

    async def main():
        engine, _ = make_engine(
            sup, metrics=metrics, batch_size=16, max_linger_us=500
        )
        results = await asyncio.gather(
            *[engine.throttle(req(key=f"d{i}", burst=5)) for i in range(16)]
        )
        return engine, results

    engine, results = run(main())
    # The device never answered — and the client never noticed.
    assert all(r.allowed for r in results)
    assert sup.state == STATE_DEGRADED
    assert engine.health_state() == "degraded"
    assert metrics.supervisor_degrades == 1
    text = metrics.export_prometheus()
    assert "throttlecrab_tpu_engine_state 2" in text
    assert "throttlecrab_tpu_supervisor_degrades 1" in text


def test_supervisor_mode_fail_raises_instead_of_degrading():
    arm("launch:persistent")
    sup = make_supervised(retries=1, mode="fail")

    async def main():
        engine, _ = make_engine(sup, batch_size=4, max_linger_us=500)
        return await asyncio.gather(
            *[engine.throttle(req(key=f"x{i}")) for i in range(4)],
            return_exceptions=True,
        )

    results = run(main())
    assert all(isinstance(r, ThrottleError) for r in results)
    assert sup.degrade_count == 0


def test_deterministic_error_not_retried_not_degraded():
    """Keymap capacity exhaustion is the request pattern's fault, not
    the device's: no retry (it cannot help), no degrade."""
    arm("keymap:persistent")
    sup = make_supervised(retries=3)

    async def main():
        engine, _ = make_engine(sup, batch_size=4, max_linger_us=500)
        return await asyncio.gather(
            *[engine.throttle(req(key=f"c{i}")) for i in range(4)],
            return_exceptions=True,
        )

    results = run(main())
    assert all(isinstance(r, ThrottleError) for r in results)
    assert sup.state == STATE_OK
    assert sup.retry_count == 0
    assert sup.degrade_count == 0


# ------------------------------------------------------------------ #
# Degraded-mode exactness and recovery (the tentpole's contract).


def _scalar_ref():
    return RateLimiter(_PlainStore())


def test_degraded_decisions_byte_identical_to_scalar_oracle():
    """Under a persistent device failure every field of every decision
    — allow bit, remaining, reset_after_ns, retry_after_ns — matches
    an uninterrupted scalar-oracle run of the same request sequence:
    the degrade handoff loses nothing."""
    arm("launch:count:2")
    sup = make_supervised(retries=0, probe_interval_ms=10_000_000)
    ref = _scalar_ref()

    t = T0
    for i in range(30):
        t += 3 * NS // 10
        keys = ["hot", f"cold{i % 7}"]
        res = sup.rate_limit_batch(keys, 3, 10, 60, 1, t)
        for j, key in enumerate(keys):
            ok, r = ref.rate_limit(key, 3, 10, 60, 1, t)
            assert bool(res.allowed[j]) == ok, (i, key)
            assert int(res.remaining[j]) == r.remaining, (i, key)
            assert int(res.reset_after_ns[j]) == r.reset_after_ns, (i, key)
            assert int(res.retry_after_ns[j]) == r.retry_after_ns, (i, key)
    assert sup.state == STATE_DEGRADED  # faults hit on launch 1, degraded
    assert len(sup) == len(ref.store._data)


def test_recovery_repromotes_no_lost_or_double_counted_state():
    """ok → degraded → recovering → ok, differentially against an
    uninterrupted scalar run: decisions before, during, and after the
    outage all match, so nothing was lost or double-counted across
    either transition; the front tier is invalidated via on_restore."""

    class FakeFront:
        restores = 0

        def on_restore(self):
            FakeFront.restores += 1

    arm("launch:count:6")
    sup = make_supervised(retries=1, probe_interval_ms=1000)
    sup.front = FakeFront()
    ref = _scalar_ref()

    t = T0
    saw = set()
    for i in range(40):
        t += 3 * NS // 10
        keys = ["hot", f"user{i % 5}"]
        res = sup.rate_limit_batch(keys, 3, 10, 60, 1, t)
        saw.add(sup.state)
        for j, key in enumerate(keys):
            ok, r = ref.rate_limit(key, 3, 10, 60, 1, t)
            assert bool(res.allowed[j]) == ok, (i, key, sup.state)
            assert int(res.remaining[j]) == r.remaining, (i, key)
    assert STATE_DEGRADED in saw
    assert sup.state == STATE_OK
    assert sup.degrade_count == 1
    assert sup.repromote_count == 1
    assert FakeFront.restores == 1  # re-promotion invalidated the cache


def test_degrade_wire_results_match_scalar_truncation():
    """Degraded-mode wire results apply the same seconds truncation and
    i32 clamps every transport emits."""
    arm("launch:persistent")
    sup = make_supervised(retries=0)
    ref = _scalar_ref()
    t = T0
    for i in range(8):
        t += NS // 5
        res = sup.rate_limit_batch(["w"], 2, 3, 1, 1, t, wire=True)
        ok, r = ref.rate_limit("w", 2, 3, 1, 1, t)
        assert bool(res.allowed[0]) == ok
        assert int(res.reset_after_s[0]) == r.reset_after_ns // NS
        assert int(res.retry_after_s[0]) == r.retry_after_ns // NS


def test_degraded_snapshot_exports_host_state(tmp_path):
    """A shutdown snapshot taken mid-outage captures the host oracle's
    state (the freshest view), and restores into a healthy limiter."""
    from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

    arm("launch:persistent")
    sup = make_supervised(retries=0)
    t = T0
    for i in range(5):
        t += NS // 10
        sup.rate_limit_batch([f"s{i}"], 5, 10, 60, 1, t)
    assert sup.state == STATE_DEGRADED
    path = tmp_path / "degraded.npz"
    n = save_snapshot(sup, path)
    assert n == 5
    faults.disarm()
    fresh = TpuRateLimiter(capacity=256)
    assert load_snapshot(fresh, path, t) == 5


# ------------------------------------------------------------------ #
# The other fault surfaces.


def test_peer_socket_fault_shape():
    """The peer site raises the ConnectionError shape the cluster
    forwarder's failure-containment path (breaker/backoff) catches."""
    from throttlecrab_tpu.parallel.cluster import PeerConnection

    arm("peer:persistent")
    peer = PeerConnection("127.0.0.1", 1)
    with pytest.raises(ConnectionError):
        peer.send_frame(b"frame")
    with pytest.raises(ConnectionError):
        peer.recv_frame()


def test_snapshot_io_fault_shape(tmp_path):
    from throttlecrab_tpu.tpu.snapshot import save_snapshot

    arm("snapshot:persistent")
    lim = TpuRateLimiter(capacity=64)
    lim.rate_limit_batch(["a"], 5, 10, 60, 1, T0)
    with pytest.raises(OSError):
        save_snapshot(lim, tmp_path / "s.npz")


# ------------------------------------------------------------------ #
# Observability end to end.


def test_health_route_reports_state_machine():
    from throttlecrab_tpu.server.http import HttpTransport

    arm("launch:persistent")
    metrics = Metrics()
    sup = make_supervised(retries=0, metrics=metrics)

    async def main():
        engine, _ = make_engine(
            sup, metrics=metrics, batch_size=4, max_linger_us=500
        )
        transport = HttpTransport("127.0.0.1", 0, engine, metrics)
        ok_body = await transport._route("GET", "/health", b"")
        await asyncio.gather(
            *[engine.throttle(req(key=f"h{i}")) for i in range(4)]
        )
        degraded_body = await transport._route("GET", "/health", b"")
        return ok_body, degraded_body

    ok_body, degraded_body = run(main())
    assert ok_body == (200, b"OK", "text/plain")
    assert degraded_body == (200, b"degraded", "text/plain")


def test_supervisor_state_helper_walks_wrappers():
    sup = make_supervised(retries=0)

    class ClusterLike:
        local = sup

    assert supervisor_state(sup) == "ok"
    assert supervisor_state(ClusterLike()) == "ok"
    assert supervisor_state(TpuRateLimiter(capacity=64)) == "ok"


# ------------------------------------------------------------------ #
# Soak (not in tier-1: marked slow).


@pytest.mark.slow
def test_chaos_soak_mixed_transient_faults():
    """2 000 requests through the engine under mixed transient launch
    and fetch faults: zero client failures, exact burst accounting on
    the hot key, state machine back at ok."""
    arm("launch:transient:0.05,fetch:transient:0.05", seed=9)
    sup = make_supervised(capacity=8192, retries=8)

    async def main():
        engine, clock = make_engine(
            sup, batch_size=128, max_linger_us=500
        )
        results = []
        for wave in range(20):
            clock.now += NS
            results.extend(
                await asyncio.gather(
                    *[
                        engine.throttle(
                            req(key=f"soak{wave}-{i}", burst=3,
                                period=3600)
                        )
                        for i in range(100)
                    ]
                )
            )
        return results

    results = run(main())
    assert len(results) == 2000
    assert all(r.allowed for r in results)
    assert sup.state == STATE_OK
    assert sup.degrade_count == 0
