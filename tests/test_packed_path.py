"""Packed-launch path: one fused i32 buffer must decide identically to the
eight-array scan path, and the C++ assembler must emit exactly what the
Python resolve + numpy packing emits."""

import numpy as np
import pytest

import jax.numpy as jnp

from throttlecrab_tpu.tpu.kernel import (
    EMPTY_EXPIRY,
    PACK_WIDTH,
    gcra_scan,
    gcra_scan_packed,
    pack_requests,
    pack_state,
    unpack_state,
)

NS = 1_000_000_000
BASE = 1_753_700_000 * NS
N = 1024  # table rows incl. scratch tail
K, B = 4, 64


def make_table():
    return pack_state(
        jnp.zeros((N,), jnp.int64),
        jnp.full((N,), EMPTY_EXPIRY, jnp.int64),
    )


def segment_info(slots_2d, valid_2d):
    rank = np.zeros_like(slots_2d, np.int32)
    is_last = np.ones(slots_2d.shape, bool)
    for k in range(slots_2d.shape[0]):
        seen: dict = {}
        for i in range(slots_2d.shape[1]):
            if not valid_2d[k, i]:
                continue
            s = int(slots_2d[k, i])
            if s in seen:
                rank[k, i] = seen[s][0]
                seen[s][0] += 1
                is_last[k, seen[s][1]] = False
                seen[s][1] = i
            else:
                seen[s] = [1, i]
    return rank, is_last


def random_launch(rng, degen=False):
    slots = rng.integers(0, 48, (K, B)).astype(np.int32)
    valid = rng.random((K, B)) > 0.1
    rank, is_last = segment_info(slots, valid)
    em = np.full((K, B), 600_000_000, np.int64)
    tol = em * rng.integers(0 if degen else 1, 9, (K, B))
    q = rng.integers(0 if degen else 1, 3, (K, B)).astype(np.int64)
    # Uniform params per slot within each micro-batch (engine invariant).
    for k in range(K):
        first: dict = {}
        for i in range(B):
            s = int(slots[k, i])
            if s in first:
                tol[k, i] = tol[k, first[s]]
                q[k, i] = q[k, first[s]]
            else:
                first[s] = i
    now = BASE + np.arange(K, dtype=np.int64) * 50_000_000
    return slots, rank, is_last, em, tol, q, valid, now


@pytest.mark.parametrize("degen", [False, True])
@pytest.mark.parametrize("compact", [False, True])
def test_packed_scan_matches_unpacked(degen, compact):
    rng = np.random.default_rng(11)
    slots, rank, is_last, em, tol, q, valid, now = random_launch(rng, degen)

    st_a, out_a = gcra_scan(
        make_table(),
        jnp.asarray(slots), jnp.asarray(rank), jnp.asarray(is_last),
        jnp.asarray(em), jnp.asarray(tol), jnp.asarray(q),
        jnp.asarray(valid), jnp.asarray(now),
        with_degen=True, compact=compact,
    )

    packed = pack_requests(slots, rank, is_last, em, tol, q, valid)
    assert packed.shape == (K, B, PACK_WIDTH)
    st_b, out_b = gcra_scan_packed(
        make_table(), jnp.asarray(packed), jnp.asarray(now),
        with_degen=True, compact=compact,
    )

    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
    tat_a, exp_a = (np.asarray(x) for x in unpack_state(st_a))
    tat_b, exp_b = (np.asarray(x) for x in unpack_state(st_b))
    np.testing.assert_array_equal(tat_a, tat_b)
    np.testing.assert_array_equal(exp_a, exp_b)


def test_pack_requests_roundtrips_i64_extremes():
    I64_MAX = (1 << 63) - 1
    vals = np.array([0, 1, -1, I64_MAX, -I64_MAX - 1, 1 << 33], np.int64)
    n = len(vals)
    packed = pack_requests(
        np.zeros(n, np.int32), np.zeros(n, np.int32), np.ones(n, bool),
        vals, vals, vals, np.ones(n, bool),
    )
    lo = packed[:, 3].view(np.uint32).astype(np.int64)
    hi = packed[:, 4].astype(np.int64)
    np.testing.assert_array_equal((hi << 32) | lo, vals)


# ---------------------------------------------------------------------- #
# C++ assembler vs Python resolve + numpy packing.

from throttlecrab_tpu.native import (  # noqa: E402
    keymap_build_error,
    native_available,
    toolchain_available,
)

needs_native = pytest.mark.skipif(
    not toolchain_available(), reason="no C++ toolchain in environment"
)


@needs_native
def test_native_assemble_matches_resolve():
    assert native_available(), keymap_build_error()
    from throttlecrab_tpu.native import NativeKeyMap

    keys = [b"key:%d" % i for i in range(200)]
    em_by_id = (np.arange(200, dtype=np.int64) + 1) * 1_000_000
    tol_by_id = em_by_id * 4

    km_a = NativeKeyMap(512)
    first = km_a.intern(keys)
    assert first == 0

    rng = np.random.default_rng(5)
    ids = rng.integers(0, 200, K * B).astype(np.int32)
    packed, n_full = km_a.assemble(ids, B, em_by_id, tol_by_id, quantity=2)
    assert n_full == 0
    assert packed.shape == (K * B, PACK_WIDTH)

    # Reference: per-micro-batch resolve through a fresh keymap + numpy pack.
    km_b = NativeKeyMap(512)
    for k in range(K):
        sel = ids[k * B : (k + 1) * B]
        batch_keys = [keys[i] for i in sel]
        slots, rank, is_last, nf = km_b.resolve(
            batch_keys, np.ones(B, bool)
        )
        assert nf == 0
        expect = pack_requests(
            slots, rank, is_last,
            em_by_id[sel], tol_by_id[sel],
            np.full(B, 2, np.int64), np.ones(B, bool),
        )
        np.testing.assert_array_equal(
            packed[k * B : (k + 1) * B], expect,
            err_msg=f"micro-batch {k}",
        )


@needs_native
def test_native_assemble_padding_and_full():
    from throttlecrab_tpu.native import NativeKeyMap

    km = NativeKeyMap(4)  # only 4 slots
    km.intern([b"a", b"b", b"c", b"d", b"e", b"f"])
    em = np.full(6, 1_000_000, np.int64)
    ids = np.array([0, 1, 2, 3, 4, 5, -1, 0], np.int32)
    packed, n_full = km.assemble(ids, len(ids), em, em, quantity=1)
    assert n_full == 2  # e, f dropped: table full
    valid = (packed[:, 2] & 2) != 0
    np.testing.assert_array_equal(
        valid, [True, True, True, True, False, False, False, True]
    )
    assert packed[4, 0] == -1 and packed[6, 0] == -1
    # id 0 re-used after padding: same slot as its first occurrence,
    # rank 1, and the first occurrence lost its is_last flag.
    assert packed[7, 0] == packed[0, 0]
    assert packed[7, 1] == 1
    assert (packed[0, 2] & 1) == 0 and (packed[7, 2] & 1) == 1
    # Un-interned (out-of-range) ids are counted as failures, not padding.
    packed2, n_full2 = km.assemble(
        np.array([0, 99], np.int32), 2, em, em, quantity=1
    )
    assert n_full2 == 1 and packed2[1, 0] == -1 and packed2[1, 2] == 0


@needs_native
def test_native_assemble_multiple_intern_calls():
    from throttlecrab_tpu.native import NativeKeyMap

    km = NativeKeyMap(64)
    assert km.intern([b"x", b"y"]) == 0
    assert km.intern([b"z"]) == 2
    em = np.array([10, 20, 30], np.int64) * 1_000_000
    packed, n_full = km.assemble(
        np.array([2, 0, 1], np.int32), 3, em, em * 2
    )
    assert n_full == 0
    # Params follow the id, not the slot.
    lo = packed[:, 3].view(np.uint32).astype(np.int64)
    hi = packed[:, 4].astype(np.int64)
    np.testing.assert_array_equal((hi << 32) | lo, em[[2, 0, 1]])
    # Same keys through resolve agree on slots.
    slots, _, _, _ = km.resolve([b"z", b"x", b"y"], np.ones(3, bool))
    np.testing.assert_array_equal(packed[:, 0], slots)


class TestByIdPath:
    """The 8 B/request by-id launch path (tk_assemble_ids +
    gcra_scan_byid + tk_finish_ids) must match the packed path exactly."""

    @pytest.fixture
    def native_km(self):
        from throttlecrab_tpu.native import toolchain_available

        if not toolchain_available():
            pytest.skip("no C++ toolchain")
        from throttlecrab_tpu.native import NativeKeyMap

        return NativeKeyMap(256)

    def test_words_match_packed_rows(self, native_km):
        """assemble_ids emits the same slot/rank/is_last/valid structure
        as assemble, in 8 bytes instead of 36."""
        km = native_km
        n = 64
        km.intern([b"key:%d" % i for i in range(n)])
        em = np.arange(1, n + 1, dtype=np.int64) * 1000
        tol = np.arange(1, n + 1, dtype=np.int64) * 7000
        rng = np.random.RandomState(3)
        ids = rng.randint(0, n, 96).astype(np.int32)
        ids[5] = -1  # padding
        packed, n_full = km.assemble(ids, 32, em, tol, 1)
        assert n_full == 0
        words, n_bad = km.assemble_ids(ids, 32)
        assert n_bad == 0
        slots = km.resolve_all()

        meta = words >> 32
        w_rank = (meta & 0x3FFF).astype(np.int32)
        w_last = (meta & (1 << 14)) != 0
        w_valid = (meta & (1 << 15)) != 0
        w_id = (words & 0xFFFFFFFF).astype(np.int64)

        p_valid = (packed[:, 2] & 2) != 0
        np.testing.assert_array_equal(w_valid, p_valid)
        np.testing.assert_array_equal(w_rank[w_valid], packed[p_valid, 1])
        np.testing.assert_array_equal(
            w_last[w_valid], (packed[p_valid, 2] & 1) != 0
        )
        # The id in each word resolves to the packed row's slot.
        np.testing.assert_array_equal(
            slots[w_id[w_valid]], packed[p_valid, 0]
        )

    def test_end_to_end_matches_packed(self, native_km):
        """Same workload through check_many_byid + finish_ids and through
        check_many_packed + finish: identical wire values and identical
        table state."""
        from throttlecrab_tpu.tpu.kernel import PACK_WIDTH
        from throttlecrab_tpu.tpu.table import BucketTable

        km = native_km
        n, B, K = 40, 32, 4
        km.intern([b"k:%d" % i for i in range(n)])
        em = (np.arange(n, dtype=np.int64) % 7 + 1) * 250_000_000
        tol = em * (np.arange(n, dtype=np.int64) % 5 + 2)
        rng = np.random.RandomState(11)
        ids = rng.randint(0, n, K * B).astype(np.int32)
        now = np.full(K, 1_753_000_000_000_000_000, np.int64)

        packed, n_full = km.assemble(ids, B, em, tol, 1)
        assert not n_full
        words, n_bad = km.assemble_ids(ids, B)
        assert not n_bad

        t1 = BucketTable(128)
        out_p = np.asarray(
            t1.check_many_packed(
                packed.reshape(K, B, PACK_WIDTH), now,
                with_degen=False, compact="cur",
            )
        )
        wire_p = km.finish(packed, out_p.reshape(-1), int(now[0]))

        t2 = BucketTable(128)
        rows = t2.upload_id_rows(km.resolve_all(), em, tol)
        out_w = np.asarray(
            t2.check_many_byid(
                rows, words.reshape(K, B), now,
                quantity=1, with_degen=False, compact="cur",
            )
        )
        wire_w = km.finish_ids(
            words, em, tol, 1, out_w.reshape(-1), int(now[0])
        )

        np.testing.assert_array_equal(out_p, out_w)
        np.testing.assert_array_equal(wire_p, wire_w)
        np.testing.assert_array_equal(
            np.asarray(t1.state)[:64], np.asarray(t2.state)[:64]
        )

    def test_assemble_ids_rejects_oversized_batch(self, native_km):
        with pytest.raises(ValueError):
            native_km.assemble_ids(np.zeros(4, np.int32), 1 << 15)

    def test_uninterned_id_reported_bad(self, native_km):
        km = native_km
        km.intern([b"a", b"b"])
        words, n_bad = km.assemble_ids(
            np.array([0, 1, 7, -1], np.int32), 4
        )
        assert n_bad == 1
        meta = words >> 32
        valid = (meta & (1 << 15)) != 0
        np.testing.assert_array_equal(valid, [True, True, False, False])

    def test_stale_id_rows_guarded(self, native_km):
        """A sweep or growth remaps slots; the ResidentIdRows guard must
        refuse to launch against the stale device rows."""
        from throttlecrab_tpu.tpu.table import (
            BucketTable,
            StaleIdRowsError,
        )

        km = native_km
        km.intern([b"g:%d" % i for i in range(8)])
        slots = km.resolve_all()
        em = np.full(8, 10**9, np.int64)
        tol = em * 4
        table = BucketTable(64)
        rows = table.upload_id_rows(slots, em, tol, keymap=km)
        words, bad = km.assemble_ids(np.arange(8, dtype=np.int32), 8)
        assert not bad
        now = np.array([1_753_000_000_000_000_000], np.int64)
        table.check_many_byid(
            rows, words.reshape(1, 8), now, 1,
            with_degen=False, compact="cur",
        )  # fresh rows serve fine

        km.free_slots(slots[:2])  # sweep analog: slots recycled
        with pytest.raises(StaleIdRowsError):
            table.check_many_byid(
                rows, words.reshape(1, 8), now, 1,
                with_degen=False, compact="cur",
            )
        # Re-upload refreshes the guard.
        rows2 = table.upload_id_rows(km.resolve_all(), em, tol, keymap=km)
        words2, bad2 = km.assemble_ids(np.arange(8, dtype=np.int32), 8)
        assert not bad2
        table.check_many_byid(
            rows2, words2.reshape(1, 8), now, 1,
            with_degen=False, compact="cur",
        )

    def test_raw_ids_matches_host_words(self, native_km):
        """gcra_scan_ids (4 B raw ids, on-device segmenting) must match
        gcra_scan_byid (host-built words) on duplicate-heavy traffic
        with padding holes: same cur words, same wire values, same
        table state."""
        from throttlecrab_tpu.tpu.table import BucketTable

        km = native_km
        n, B, K = 40, 32, 4
        km.intern([b"k:%d" % i for i in range(n)])
        em = (np.arange(n, dtype=np.int64) % 7 + 1) * 250_000_000
        tol = em * (np.arange(n, dtype=np.int64) % 5 + 2)
        rng = np.random.RandomState(11)
        ids = rng.randint(0, n, K * B).astype(np.int32)
        ids[[3, 17, 40, 100]] = -1  # padding holes mid-batch
        now = np.full(K, 1_753_000_000_000_000_000, np.int64)

        words, bad = km.assemble_ids(ids, B)
        assert not bad
        slots = km.resolve_all()

        t1 = BucketTable(128)
        r1 = t1.upload_id_rows(slots, em, tol)
        out_w = np.asarray(
            t1.check_many_byid(
                r1, words.reshape(K, B), now, 1,
                with_degen=False, compact="cur",
            )
        ).reshape(-1)
        wire_w = km.finish_ids(words, em, tol, 1, out_w, int(now[0]))

        t2 = BucketTable(128)
        r2 = t2.upload_id_rows(slots, em, tol)
        out_r = np.asarray(
            t2.check_many_ids(
                r2, ids.reshape(K, B), now, 1,
                with_degen=False, compact="cur",
            )
        ).reshape(-1)
        wire_r = km.finish_raw(ids, em, tol, 1, out_r, int(now[0]))

        valid = ids >= 0
        np.testing.assert_array_equal(out_w[valid], out_r[valid])
        np.testing.assert_array_equal(wire_w[valid], wire_r[valid])
        # Allowed bit masked off on padding lanes in both paths.
        assert not (out_r[~valid] & 1).any()
        np.testing.assert_array_equal(
            np.asarray(t1.state)[:64], np.asarray(t2.state)[:64]
        )

    def test_raw_ids_hot_key_burst_semantics(self, native_km):
        """One key duplicated across a whole raw-ids batch must admit
        exactly `burst` requests in rank order — the on-device segment
        derivation reproducing the reference's sequential semantics."""
        from throttlecrab_tpu.tpu.table import BucketTable

        km = native_km
        km.intern([b"hot", b"cold"])
        slots = km.resolve_all()
        burst = 10
        em = np.full(2, 6_000_000_000, np.int64)  # period/count = 6s
        tol = em * (burst - 1)
        table = BucketTable(64)
        rows = table.upload_id_rows(slots, em, tol)
        ids = np.zeros(64, np.int32)  # 63x hot + 1 cold in the middle
        ids[31] = 1
        now = np.array([1_753_000_000_000_000_000], np.int64)
        out = np.asarray(
            table.check_many_ids(
                rows, ids.reshape(1, 64), now, 1,
                with_degen=False, compact="cur",
            )
        ).reshape(-1)
        allowed = (out & 1) != 0
        hot = ids == 0
        assert int(allowed[hot].sum()) == burst
        # Prefix property: the first `burst` hot occurrences are the
        # allowed ones (arrival order preserved through the sort).
        assert allowed[hot][:burst].all() and not allowed[hot][burst:].any()
        assert allowed[31]  # the cold key is its own segment

    def test_finish_raw_rejects_out_of_table_ids(self, native_km):
        km = native_km
        km.intern([b"a", b"b"])
        em = np.array([10**9, 10**9], np.int64)
        tol = em * 3
        cur2 = np.zeros(3, np.int64)
        with pytest.raises(ValueError):
            km.finish_raw(
                np.array([0, 1, 2], np.int32), em, tol, 1, cur2, 0
            )

    def test_intern_after_upload_invalidates_rows(self, native_km):
        """Ids interned after upload are not covered by the resident
        rows; the guard must force a re-upload rather than let the
        kernel clip the new id onto another key's row."""
        from throttlecrab_tpu.tpu.table import (
            BucketTable,
            StaleIdRowsError,
        )

        km = native_km
        km.intern([b"old"])
        em = np.array([10**9], np.int64)
        tol = em * 3
        table = BucketTable(64)
        rows = table.upload_id_rows(km.resolve_all(), em, tol, keymap=km)
        km.intern([b"new"])
        now = np.array([1_753_000_000_000_000_000], np.int64)
        with pytest.raises(StaleIdRowsError):
            table.check_many_ids(
                rows, np.array([[1]], np.int32), now, 1,
                with_degen=False, compact="cur",
            )


class TestIds20Stream:
    def test_pack_ids20_layout_and_guards(self):
        from throttlecrab_tpu.tpu.kernel import (
            IDS20_SENTINEL,
            pack_ids20,
        )

        ids = np.array([[0, 1, 0xFFFF, 0x9FFFE, -1, 7, 8, 9]], np.int32)
        buf = pack_ids20(ids)
        assert buf.dtype == np.uint16 and buf.shape == (1, 8 + 2)
        # Low 16 bits verbatim; padding becomes the all-ones sentinel.
        assert buf[0, 2] == 0xFFFF and buf[0, 3] == 0xFFFE
        assert buf[0, 4] == IDS20_SENTINEL & 0xFFFF
        # High nibbles packed 4-per-u16 in lane order.
        assert buf[0, 8] == (0x9 << 12)  # lanes 0..3: 0,0,0,0x9
        assert buf[0, 9] == 0xF          # lane 4 (sentinel hi) in slot 0
        with pytest.raises(ValueError):
            pack_ids20(np.full((1, 8), IDS20_SENTINEL, np.int32))
        with pytest.raises(ValueError):
            pack_ids20(np.zeros((1, 6), np.int32))  # width % 4 != 0

    @pytest.mark.parametrize("compact", [False, "cur", "w32"])
    def test_ids20_matches_raw_ids(self, compact):
        """The 2.5 B/request stream must decide identically to the raw
        i32 ids path — same outputs, same table state — on
        duplicate-heavy traffic with padding holes."""
        from throttlecrab_tpu.tpu.table import BucketTable

        rng = np.random.RandomState(17)
        n, B, K = 600, 32, 4
        em = (np.arange(n, dtype=np.int64) % 7 + 1) * 250_000_000
        tol = em * (np.arange(n, dtype=np.int64) % 5 + 2)
        slots = np.arange(n, dtype=np.int32)
        ids = rng.randint(0, n, (K, B)).astype(np.int32)
        ids[0, 3] = ids[1, 8] = ids[3, 31] = -1  # padding holes
        now = np.full(K, 1_753_000_000_000_000_000, np.int64)
        wd = compact is False  # exact path exercises degen machinery too

        from throttlecrab_tpu.tpu.kernel import pack_ids20

        t1 = BucketTable(1024)
        r1 = t1.upload_id_rows(slots, em, tol)
        out_raw = np.asarray(
            t1.check_many_ids(r1, ids, now, 1, with_degen=wd, compact=compact)
        )
        t2 = BucketTable(1024)
        r2 = t2.upload_id_rows(slots, em, tol)
        out_20 = np.asarray(
            t2.check_many_ids20(
                r2, pack_ids20(ids), now, 1, with_degen=wd, compact=compact
            )
        )
        # Padding lanes are don't-care (the two paths clip them onto
        # different rows before masking); every VALID lane must match,
        # and the allowed bit must be off on padding in both.
        valid = ids >= 0
        if compact is False:
            np.testing.assert_array_equal(
                out_raw[:, :, :][np.broadcast_to(valid[:, None, :],
                                                 out_raw.shape)],
                out_20[np.broadcast_to(valid[:, None, :], out_20.shape)],
            )
            assert not out_raw[:, 0, :][~valid].any()
            assert not out_20[:, 0, :][~valid].any()
        else:
            np.testing.assert_array_equal(out_raw[valid], out_20[valid])
            assert not (out_raw[~valid] & 1).any()
            assert not (out_20[~valid] & 1).any()
        np.testing.assert_array_equal(
            np.asarray(t1.state)[:700], np.asarray(t2.state)[:700]
        )

    def test_ids20_plain_entry_matches_acc_twin(self):
        """The public non-accumulating gcra_scan_ids20 must decide
        identically to the _acc twin the table routes through (same
        pinning the other plain/acc pairs get)."""
        from throttlecrab_tpu.tpu.kernel import (
            EMPTY_EXPIRY,
            gcra_scan_ids20,
            gcra_scan_ids20_acc,
            pack_id_rows,
            pack_ids20,
            pack_state,
        )

        n, B, K = 40, 16, 3
        em = np.full(n, 400_000_000, np.int64)
        tol = em * 5
        rows = jnp.asarray(pack_id_rows(np.arange(n, dtype=np.int32), em, tol))
        rng = np.random.RandomState(3)
        ids = rng.randint(0, n, (K, B)).astype(np.int32)
        buf = pack_ids20(ids)
        now = np.full(K, 1_753_000_000_000_000_000, np.int64)

        def fresh():
            return pack_state(
                jnp.zeros((256,), jnp.int64),
                jnp.full((256,), EMPTY_EXPIRY, jnp.int64),
            )

        st1, out1 = gcra_scan_ids20(
            fresh(), rows, jnp.asarray(buf), now, 1,
            with_degen=False, compact="cur",
        )
        st2, acc, out2 = gcra_scan_ids20_acc(
            fresh(), jnp.zeros((), jnp.int64), rows, jnp.asarray(buf),
            now, 1, with_degen=False, compact="cur",
        )
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        np.testing.assert_array_equal(np.asarray(st1), np.asarray(st2))
        assert int(acc) == 0  # fresh table: no expired hits

    def test_ids20_rejects_malformed_buffer(self):
        from throttlecrab_tpu.tpu.kernel import pack_id_rows
        from throttlecrab_tpu.tpu.table import BucketTable

        t = BucketTable(64)
        rows = t.upload_id_rows(
            np.arange(4, dtype=np.int32),
            np.full(4, NS, np.int64),
            np.full(4, 2 * NS, np.int64),
        )
        # Raw i32 ids where the u16 stream belongs: loud, not garbage.
        with pytest.raises(ValueError, match="pack_ids20"):
            t.check_many_ids20(
                rows, np.zeros((1, 5), np.int32), np.array([1], np.int64)
            )
        # Wrong width (not a multiple of 5 lanes).
        with pytest.raises(ValueError, match="pack_ids20"):
            t.check_many_ids20(
                rows, np.zeros((1, 8), np.uint16), np.array([1], np.int64)
            )

    def test_ids20_kernels_reject_misaligned_width(self):
        """Regression (ADVICE low): the raw kernels derived B = W*4//5
        without validating W % 5 == 0, so a direct caller handing a
        misaligned buffer (e.g. a raw id stream) got its high-nibble
        plane mis-split into plausible-but-wrong ids and decided against
        the wrong buckets.  Both entry points must fail loudly instead."""
        from throttlecrab_tpu.tpu.kernel import (
            EMPTY_EXPIRY,
            gcra_scan_ids20,
            gcra_scan_ids20_acc,
            pack_id_rows,
            pack_state,
        )

        n = 4
        em = np.full(n, NS, np.int64)
        rows = jnp.asarray(
            pack_id_rows(np.arange(n, dtype=np.int32), em, em * 2)
        )
        state = pack_state(
            jnp.zeros((64,), jnp.int64),
            jnp.full((64,), EMPTY_EXPIRY, jnp.int64),
        )
        bad = jnp.zeros((1, 8), jnp.uint16)  # 8 % 5 != 0
        now = np.array([NS], np.int64)
        with pytest.raises(ValueError, match="multiple of 5"):
            gcra_scan_ids20(state, rows, bad, now, 1)
        with pytest.raises(ValueError, match="multiple of 5"):
            gcra_scan_ids20_acc(
                state, jnp.zeros((), jnp.int64), rows, bad, now, 1
            )

    def test_ids20_rejects_oversized_table(self):
        from throttlecrab_tpu.tpu.kernel import pack_id_rows, pack_ids20
        from throttlecrab_tpu.tpu.table import BucketTable

        t = BucketTable(64)
        n = (1 << 20)  # one past the sentinel bound
        rows = np.zeros((n, 8), np.int32)
        with pytest.raises(ValueError, match="sentinel"):
            t.check_many_ids20(
                jnp.asarray(rows),
                pack_ids20(np.zeros((1, 4), np.int32)),
                np.array([1], np.int64),
            )
