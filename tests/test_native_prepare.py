"""tk_prepare_batch (the fully-native serving prep) vs the Python path:
derivation bit-parity, status taxonomy, segment structure, and end-to-end
decision equality."""

import numpy as np
import pytest

from throttlecrab_tpu.native import (
    PREP_CONFLICT,
    PREP_DEGEN,
    PREP_FULL,
    toolchain_available,
)

pytestmark = pytest.mark.skipif(
    not toolchain_available(), reason="no C++ toolchain"
)

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


def frame(keys):
    blob = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    return blob, offsets


def unpack_i64(packed, base):
    lo = packed[:, base].view(np.uint32).astype(np.int64)
    hi = packed[:, base + 1].astype(np.int64)
    return (hi << 32) | lo


def test_derivation_bit_parity_extremes():
    """C++ f64 derivation must equal derive_params bit-for-bit, including
    the truncating cast, clamp-to-I64_MAX, and wrapping tolerance."""
    from throttlecrab_tpu.native import NativeKeyMap
    from throttlecrab_tpu.tpu.limiter import derive_params

    rng = np.random.default_rng(9)
    n = 500
    burst = np.concatenate([
        rng.integers(1, 1 << 20, n - 8),
        np.array([1, 2, 1 << 32, (1 << 33) + 5, 1 << 62, 3, 7, 1]),
    ]).astype(np.int64)
    count = np.concatenate([
        rng.integers(1, 1 << 30, n - 8),
        np.array([1, 1, 1, 1, 1 << 50, 1, 2, 10**15]),
    ]).astype(np.int64)
    period = np.concatenate([
        rng.integers(1, 1 << 20, n - 8),
        np.array([1, 1 << 40, 1 << 30, 1 << 30, 1, 1 << 55, 1, 1]),
    ]).astype(np.int64)

    em_py, tol_py, invalid = derive_params(burst, count, period)
    assert not invalid.any()

    km = NativeKeyMap(2048)
    keys = [b"dp:%d" % i for i in range(n)]
    blob, offsets = frame(keys)
    params = np.stack(
        [burst, count, period, np.ones(n, np.int64)], axis=1
    )
    packed, status, flags = km.prepare_batch(blob, offsets, params)
    assert (status == 0).all()
    np.testing.assert_array_equal(unpack_i64(packed, 3), em_py)
    np.testing.assert_array_equal(unpack_i64(packed, 5), tol_py)


def test_status_taxonomy_and_validity():
    from throttlecrab_tpu.native import NativeKeyMap

    km = NativeKeyMap(64)
    keys = [b"ok", b"negq", b"zb", b"zc", b"zp"]
    blob, offsets = frame(keys)
    params = np.array(
        [
            [10, 100, 60, 1],
            [10, 100, 60, -1],   # negative quantity
            [0, 100, 60, 1],     # burst <= 0
            [10, 0, 60, 1],      # count <= 0
            [10, 100, -5, 1],    # period <= 0
        ],
        np.int64,
    )
    packed, status, flags = km.prepare_batch(blob, offsets, params)
    assert status.tolist() == [0, 1, 2, 2, 2]
    valid = (packed[:, 2] & 2) != 0
    assert valid.tolist() == [True, False, False, False, False]
    # Invalid requests must not allocate slots.
    assert len(km) == 1


def test_prepare_matches_python_decisions():
    """Decisions through prepare_batch + packed kernel == the Python
    rate_limit_batch path, duplicates included."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    rng = np.random.default_rng(17)
    B = 128
    key_ids = rng.integers(0, 40, B)
    keys = [b"pp:%d" % i for i in key_ids]
    burst = 5 + (key_ids % 13)
    count = 50 + (key_ids % 97)
    period = 30 + (key_ids % 11)

    # Python path.
    lim_py = TpuRateLimiter(capacity=512, keymap="native")
    res_py = lim_py.rate_limit_batch(
        keys, burst, count, period, 1, T0, wire=True
    )

    # Native-prep path: prepare + packed scan on a fresh table.
    lim_nat = TpuRateLimiter(capacity=512, keymap="native")
    blob, offsets = frame(keys)
    params = np.stack(
        [burst, count, period, np.ones(B, np.int64)], axis=1
    ).astype(np.int64)
    packed, status, flags = lim_nat.keymap.prepare_batch(
        blob, offsets, params
    )
    assert flags & (PREP_CONFLICT | PREP_FULL) == 0
    out = np.asarray(
        lim_nat.table.check_many_packed(
            packed.reshape(1, B, 9),
            np.array([T0], np.int64),
            with_degen=bool(flags & PREP_DEGEN),
            compact=True,
        )
    )[0]
    np.testing.assert_array_equal(out[0] != 0, res_py.allowed)
    np.testing.assert_array_equal(out[1], res_py.remaining)
    np.testing.assert_array_equal(out[2], res_py.reset_after_s)
    np.testing.assert_array_equal(out[3], res_py.retry_after_s)
    assert status.tolist() == res_py.status.tolist()


def test_prepare_full_table_flagged():
    from throttlecrab_tpu.native import NativeKeyMap

    km = NativeKeyMap(2)
    keys = [b"f1", b"f2", b"f3"]
    blob, offsets = frame(keys)
    params = np.array([[10, 100, 60, 1]] * 3, np.int64)
    packed, status, flags = km.prepare_batch(blob, offsets, params)
    assert flags & PREP_FULL
    assert packed[2, 0] == -1 and (packed[2, 2] & 2) == 0


def test_wire_window_differential_random():
    """dispatch_wire_window vs the Python path over many randomized
    windows on twin limiters: same keys, params (including degenerate
    mixes), duplicates, and interleaved sweeps — every output field must
    match exactly, window after window (state carried on both sides)."""
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    rng = np.random.default_rng(23)
    lim_a = TpuRateLimiter(capacity=512, keymap="native")
    lim_b = TpuRateLimiter(capacity=512, keymap="native")

    now = T0
    for round_i in range(12):
        now += int(rng.integers(0, 3 * NS))
        k_batches = int(rng.integers(1, 4))
        frames = []
        windows = []
        for _ in range(k_batches):
            n = int(rng.integers(1, 48))
            key_ids = rng.integers(0, 30, n)
            keys = [b"dw:%d" % i for i in key_ids]
            burst = (1 + (key_ids % 7)).astype(np.int64)      # incl. burst 1
            count = (1 + (key_ids % 19)).astype(np.int64)
            period = (1 + (key_ids % 5)).astype(np.int64)
            qty = (key_ids % 3).astype(np.int64)              # incl. qty 0
            params = np.stack([burst, count, period, qty], axis=1)
            blob, offsets = frame(keys)
            frames.append((blob, offsets, params))
            windows.append((keys, burst, count, period, qty, now))

        handle = lim_a.dispatch_wire_window(frames, now)
        assert handle is not None
        res_a = handle.fetch()
        res_b = [
            lim_b.rate_limit_batch(*w, wire=True) for w in windows
        ]
        for j, (a, b) in enumerate(zip(res_a, res_b)):
            msg = f"round {round_i} window {j}"
            np.testing.assert_array_equal(a.allowed, b.allowed, msg)
            np.testing.assert_array_equal(a.remaining, b.remaining, msg)
            np.testing.assert_array_equal(
                a.reset_after_s, b.reset_after_s, msg
            )
            np.testing.assert_array_equal(
                a.retry_after_s, b.retry_after_s, msg
            )
            np.testing.assert_array_equal(a.status, b.status, msg)
            np.testing.assert_array_equal(a.limit, b.limit, msg)
        if round_i % 4 == 3:
            now += 10 * NS
            assert lim_a.sweep(now) == lim_b.sweep(now)


def test_prepare_batch_flags_big_tolerance():
    """tol >= 2^61 must raise PREP_BIGTOL (the fits_cur_wire half the C++
    prep can certify) without tripping the degeneracy flag — the limiter
    then serves the window through the 4-plane compact output."""
    from throttlecrab_tpu.native import (
        PREP_BIGTOL,
        PREP_DEGEN,
        NativeKeyMap,
        toolchain_available,
    )

    if not toolchain_available():
        import pytest

        pytest.skip("no C++ toolchain")
    km = NativeKeyMap(16)
    packed, status, flags = km.prepare_batch(
        b"big", np.array([0, 3], np.int64),
        np.array([[3_000_000_000, 1, 1, 1]], np.int64),
    )
    assert status[0] == 0
    assert flags & PREP_BIGTOL
    assert not (flags & PREP_DEGEN)

    packed, status, flags = km.prepare_batch(
        b"ok", np.array([0, 2], np.int64),
        np.array([[10, 100, 60, 1]], np.int64),
    )
    assert status[0] == 0 and not (flags & PREP_BIGTOL)


def test_prepare_batch_agg_matches_python_certificate():
    """tk_prepare_batch's agg output must reproduce the Python-side
    valid-lane aggregates, and the O(1) certificate built from it must
    agree with the array-form fits_w32_wire on the same batch."""
    from throttlecrab_tpu.native import NativeKeyMap, native_available
    from throttlecrab_tpu.tpu.kernel import (
        fits_w32_wire,
        fits_w32_wire_agg,
    )
    from throttlecrab_tpu.tpu.limiter import derive_params

    if not native_available():
        pytest.skip("no C++ toolchain")
    now = 1_753_700_000 * 1_000_000_000
    cases = [
        # (burst, count, period, qty) rows incl. invalid + degen lanes
        [(10, 100, 60, 1), (0, 1, 1, 1), (500, 60, 60, 2)],
        [(2100, 60, 60, 1), (5, 10, 10, 1)],     # big tol: w32 refused
        [(3, 3, 3, 1)],
        [(0, 0, 0, 0)],                           # all-invalid frame
    ]
    for rows in cases:
        km = NativeKeyMap(64)
        keys = [b"a%d" % i for i in range(len(rows))]
        blob = b"".join(keys)
        offsets = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
        params = np.array(rows, np.int64).reshape(len(rows), 4)
        agg = np.empty(4, np.int64)
        _, status, flags = km.prepare_batch(blob, offsets, params, agg=agg)

        valid = status == 0
        em, tol, _ = derive_params(params[:, 0], params[:, 1], params[:, 2])
        q = params[:, 3]
        # Python twins of the C aggregates (valid lanes only).
        if valid.any():
            vt = tol[valid]
            assert int(agg[0]) == int(vt.max())
            assert int(agg[1]) == int(vt.min())
            # Integer-domain saturating em*qty twin (a float clamp at
            # (1<<63)-1 rounds to 2^63 and the i64 cast would wrap).
            inc = [
                min(int(e) * int(qq), (1 << 63) - 1)
                for e, qq in zip(em[valid], q[valid])
            ]
            assert int(agg[2]) == max(inc)
        else:
            assert agg[0] == 0 and agg[1] == 0 and agg[2] == 0

        got = fits_w32_wire_agg(
            agg[0], agg[1], agg[2], agg[3], now, 0, 0
        )
        want = fits_w32_wire(valid, em, tol, q, now, 0, 0)
        # The agg form may only be MORE conservative, never less; on
        # these cases (uniform-ish lanes) it matches exactly.
        assert got == want, rows
