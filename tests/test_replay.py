"""Record/replay subsystem (ISSUE 14, throttlecrab_tpu/replay/).

Contracts under test:

- **Trace codec hardening** — the cluster codecs' malformed-frame
  contract verbatim: count-vs-size before allocation, typed TraceError
  (never struct.error), trailing-bytes rejection, version gating.
- **Record -> replay determinism** — a workload captured through the
  real batching engine replays byte-identically (two replays produce
  identical outcome vectors) and faithfully (replay == recorded).
- **Differential replay** — replayed outcomes match the scalar oracle
  row-for-row under tier-fuzz-shaped traffic (degenerate probes,
  param churn, hostile params).
- **Deterministic fault replay** — a chaos run's fired-injection
  sequence is captured into the trace, and replaying it through
  FaultInjector.from_schedule reproduces the identical outcome vector
  AND the identical fired sequence (degrade -> recover lifecycle
  included).
- **Flight recorder** — bounded ring, dump-on-degrade through the
  supervisor, GET /trace/dump admin route, fired-injection metrics.
"""

from __future__ import annotations

import asyncio
import glob
import os
import struct
import time

import numpy as np
import pytest

from throttlecrab_tpu.replay.generators import save, synthesize
from throttlecrab_tpu.replay.player import (
    differential_replay,
    injector_from_trace,
    make_target,
    outcome_vector,
    replay,
)
from throttlecrab_tpu.replay.recorder import (
    FlightRecorder,
    arm,
    disarm,
)
from throttlecrab_tpu.replay.trace import (
    SOURCE_ENGINE,
    Trace,
    TraceError,
    TraceWriter,
    decode_event,
    decode_injection,
    decode_window,
    encode_event,
    encode_injection,
    encode_window,
)

NS = 1_000_000_000
T0 = 1_753_700_000 * NS


@pytest.fixture(autouse=True)
def _disarm_recorder():
    yield
    disarm()


# ------------------------------------------------------------ codec #


def test_window_roundtrip_preserves_everything():
    keys = [b"a", b"tenant:zz", b"", b"x" * 300]
    params = np.array(
        [[5, 100, 60, 1], [2, 2, 600, 0], [1, 1, 1, 1],
         [3_000_000_000, 1, 1, 1]],
        np.int64,
    )
    frame = encode_window(
        T0, 7, keys, params, [1, 0, 1, 0], [0, 0, 2, 3], [0, 3, 0, 1]
    )
    w = decode_window(frame[5:])
    assert w.now_ns == T0 and w.source == 7
    assert w.keys == keys
    np.testing.assert_array_equal(w.params, params)
    assert w.allowed.tolist() == [1, 0, 1, 0]
    assert w.status.tolist() == [0, 0, 2, 3]
    assert w.tenants.tolist() == [0, 3, 0, 1]


def test_event_and_injection_roundtrip():
    e = decode_event(encode_event(T0, "degrade", "UNAVAILABLE: boom")[5:])
    assert (e.now_ns, e.kind, e.detail) == (
        T0, "degrade", "UNAVAILABLE: boom"
    )
    i = decode_injection(encode_injection("launch", "count", 7, 2.0)[5:])
    assert (i.site, i.mode, i.index, i.arg) == ("launch", "count", 7, 2.0)


def test_trace_file_roundtrip_and_order():
    writer = TraceWriter()
    writer.add_event(T0, "cluster-join", "1")
    writer.add_window(
        T0 + 1, SOURCE_ENGINE, [b"k"], [[5, 100, 60, 1]], [1], [0]
    )
    writer.add_injection("launch", "transient", 3, 0.5)
    writer.add_window(
        T0 + 2, SOURCE_ENGINE, [b"k"], [[5, 100, 60, 1]], [0], [0]
    )
    trace = Trace.loads(writer.to_bytes())
    kinds = [k for k, _ in trace.records]
    assert kinds == [2, 1, 3, 1]  # capture order survives
    assert len(trace.windows) == 2
    assert trace.injection_schedule() == [("launch", "transient", 3, 0.5)]


def test_codec_rejection_fixtures():
    """Every malformed shape raises the typed TraceError — never a raw
    struct.error/IndexError (the PR-8 decode_batch leak class)."""
    writer = TraceWriter()
    writer.add_window(
        T0, SOURCE_ENGINE, [b"ab", b"c"],
        [[5, 100, 60, 1], [5, 100, 60, 1]], [1, 1], [0, 0],
    )
    data = writer.to_bytes()

    with pytest.raises(TraceError):  # bad magic
        Trace.loads(b"XXXX" + data[4:])
    with pytest.raises(TraceError):  # unsupported version
        Trace.loads(data[:4] + b"\x63\x00" + data[6:])
    with pytest.raises(TraceError):  # truncated frame header
        Trace.loads(data[:8])
    with pytest.raises(TraceError):  # truncated frame body
        Trace.loads(data[:-3])
    with pytest.raises(TraceError):  # unknown record kind
        bad = bytearray(data)
        bad[10] = 200
        Trace.loads(bytes(bad))

    # Count-vs-size lie: n patched huge must be refused BEFORE any
    # allocation sized from it.
    lie = bytearray(data)
    struct.pack_into("<I", lie, 6 + 5 + 9, 1 << 30)
    with pytest.raises(TraceError):
        Trace.loads(bytes(lie))

    # Trailing bytes inside a window frame body.
    frame = encode_window(
        T0, 0, [b"k"], [[5, 100, 60, 1]], [1], [0]
    )
    with pytest.raises(TraceError):
        decode_window(frame[5:] + b"\x00")
    with pytest.raises(TraceError):
        decode_window(frame[5: -1])
    with pytest.raises(TraceError):
        decode_event(b"")
    with pytest.raises(TraceError):
        decode_injection(b"\x01")
    ev = encode_event(T0, "x", "y")
    with pytest.raises(TraceError):
        decode_event(ev[5:] + b"z")


def test_oversized_key_refused_at_encode():
    with pytest.raises(TraceError):
        encode_window(
            T0, 0, [b"x" * 70_000], [[5, 100, 60, 1]], [1], [0]
        )


# ------------------------------------------------- flight recorder #


def test_ring_keeps_last_n_windows_and_all_events(tmp_path):
    rec = FlightRecorder(capacity=4, out_dir=str(tmp_path))
    for i in range(10):
        rec.record_window(
            T0 + i, [f"k{i}"], [[5, 100, 60, 1]], [1], [0]
        )
    rec.record_event("degrade", "boom", now_ns=T0 + 99)
    path, n = rec.dump()
    assert n == 4
    trace = Trace.load(path)
    assert [w.keys[0] for w in trace.windows] == [
        b"k6", b"k7", b"k8", b"k9"
    ]
    # The event survives ring overflow (bounded side list).
    assert [e.kind for e in trace.events] == ["degrade"]


def test_full_mode_records_every_window(tmp_path):
    path = str(tmp_path / "full.tctr")
    rec = FlightRecorder(
        capacity=2, mode="full", out_dir=str(tmp_path), path=path
    )
    for i in range(9):
        rec.record_window(
            T0 + i, [b"k%d" % i], [[5, 100, 60, 1]], [1], [0]
        )
    rec.close()
    trace = Trace.load(path)
    assert len(trace.windows) == 9  # full mode ignores the ring bound


def test_full_mode_late_capture_never_truncates(tmp_path):
    """Review-fix regression: a capture arriving after close() must be
    dropped — reopening the finalized file would truncate the artifact
    the recorder exists to preserve."""
    path = str(tmp_path / "late.tctr")
    rec = FlightRecorder(
        mode="full", out_dir=str(tmp_path), path=path
    )
    for i in range(3):
        rec.record_window(T0 + i, [b"k"], [[5, 100, 60, 1]], [1], [0])
    rec.close()
    rec.record_window(T0 + 9, [b"late"], [[5, 100, 60, 1]], [1], [0])
    rec.record_event("cluster-reweight", "0:0.5")
    assert len(Trace.load(path).windows) == 3  # artifact untouched


def test_capture_never_raises_into_serving(tmp_path):
    """Review-fix regression: an over-long key (past the trace's u16
    bound) is truncated at capture, never raised into the hot path."""
    rec = FlightRecorder(capacity=4, out_dir=str(tmp_path))
    rec.record_window(
        T0, [b"x" * 70_000, b"ok"], [[5, 100, 60, 1]] * 2,
        [1, 1], [0, 0],
    )
    path, n = rec.dump()
    assert n == 1
    w = Trace.load(path).windows[0]
    assert len(w.keys[0]) == 0xFFFF and w.keys[1] == b"ok"


def test_scheduled_injector_multi_firing_per_index():
    """Review-fix regression: one live check can fire several armed
    specs (a hang that stalls, then a transient that raises); replay
    must reproduce all of them at that index, in order."""
    from throttlecrab_tpu.faults import FaultInjector, InjectedDeviceError

    slept = []
    inj = FaultInjector.from_schedule(
        [("launch", "hang", 0, 0.25), ("launch", "transient", 0, 0.9)],
        sleep_fn=slept.append,
    )
    with pytest.raises(InjectedDeviceError):
        inj.check("launch")
    assert slept == [0.25]  # the stall replayed before the raise
    assert [(m, i) for _s, m, i, _a in inj.fired_schedule()] == [
        ("hang", 0), ("transient", 0)
    ]


def test_recorder_derives_tenant_ids(tmp_path):
    rec = FlightRecorder(capacity=8, out_dir=str(tmp_path))
    rec.record_window(
        T0, [b"acme:k1", b"globex:k2", b"bare", b"acme:k3"],
        [[5, 100, 60, 1]] * 4, [1, 1, 1, 1], [0, 0, 0, 0],
    )
    path, _ = rec.dump()
    w = Trace.load(path).windows[0]
    assert w.tenants[0] == w.tenants[3] != 0  # same tenant, same id
    assert w.tenants[1] not in (0, w.tenants[0])
    assert w.tenants[2] == 0  # bare key: no tenant


# -------------------------------------- record -> replay (engine) #


async def _drive_engine(windows: int, now_step_ns: int = NS // 2):
    from throttlecrab_tpu.harness.workload import make_keys
    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.types import ThrottleRequest
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    clock = {"now": T0}
    engine = BatchingEngine(
        TpuRateLimiter(capacity=2048), batch_size=32,
        max_linger_us=200, now_fn=lambda: clock["now"],
    )
    keys = make_keys("hotkey-abuse", windows * 32, 1000, seed=5)
    for step in range(windows):
        reqs = [
            ThrottleRequest(k, 4, 10, 60, 1)
            for k in keys[step * 32: (step + 1) * 32]
        ]
        await asyncio.gather(
            *[engine.throttle(r) for r in reqs], return_exceptions=True
        )
        clock["now"] += now_step_ns
    await engine.shutdown()


def test_engine_record_then_replay_byte_identical(tmp_path):
    """The acceptance core: capture through the real engine flush path,
    replay twice, diff byte-for-byte; replay also equals the recorded
    outcomes and the scalar oracle."""
    path = str(tmp_path / "eng.tctr")
    rec = FlightRecorder(
        mode="full", out_dir=str(tmp_path), path=path
    )
    arm(rec)
    try:
        asyncio.run(_drive_engine(10))
    finally:
        rec.close()
        disarm()
    trace = Trace.load(path)
    assert trace.n_rows() == 10 * 32

    v1 = outcome_vector(replay(trace, make_target("device", trace)))
    v2 = outcome_vector(replay(trace, make_target("device", trace)))
    assert v1 == v2, "two replays of one trace diverged"
    assert v1 == trace.outcome_vector(), "replay != recorded outcomes"

    report = differential_replay(trace, "device")
    assert report.ok, report.summary()


def test_disarmed_engine_records_nothing(tmp_path):
    assert FlightRecorder(capacity=4).windows_recorded == 0
    asyncio.run(_drive_engine(2))  # no recorder armed: must not blow up


# -------------------------------------------- differential replay #


def _hostile_trace():
    """Tier-fuzz-shaped traffic as a trace: degenerate probes
    (quantity 0), burst-1 (tolerance 0), cur-only params, invalid
    lanes, duplicate keys in one window, param churn mid-stream."""
    writer = TraceWriter()
    rng = np.random.default_rng(23)
    pool = [b"hz:%d" % i for i in range(12)]
    profiles = [
        (1, 5, 30, 1),              # burst 1: tolerance 0
        (5, 100, 60, 0),            # quantity-0 probe
        (3000, 60, 60, 1),          # cur tier only
        (0, 10, 60, 1),             # invalid params (burst 0)
        (4, 10, 60, 1),
        (2, 2, 600, 1),
    ]
    now = T0
    for step in range(30):
        n = int(rng.integers(2, 16))
        ks, ps = [], []
        for _ in range(n):
            ks.append(pool[int(rng.integers(len(pool)))])
            ps.append(profiles[int(rng.integers(len(profiles)))])
        writer.add_window(
            now, SOURCE_ENGINE, ks, np.asarray(ps, np.int64),
            np.zeros(n, np.uint8), np.zeros(n, np.uint8),
        )
        now += int(rng.integers(0, NS))
    return Trace.loads(writer.to_bytes())


def test_differential_replay_hostile_patterns_device():
    trace = _hostile_trace()
    got = replay(trace, make_target("device", trace))
    want = replay(trace, make_target("oracle", trace))
    for wi, ((ga, gs), (wa, ws)) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(gs, ws, err_msg=f"window {wi}")
        ok = ws == 0
        np.testing.assert_array_equal(
            ga[ok], wa[ok], err_msg=f"window {wi}"
        )


def test_differential_replay_synthetic_patterns_sharded():
    from conftest import require_devices

    require_devices(2)
    for pattern in ("diurnal", "flash-crowd", "slow-drift"):
        trace = synthesize(
            pattern, windows=8, batch=48, key_space=512, seed=3
        )
        report = differential_replay(trace, "sharded:2")
        assert report.ok, (pattern, report.summary())


def test_generated_trace_saves_and_replays(tmp_path):
    trace = synthesize(
        "diurnal", windows=6, batch=32, key_space=256, seed=9
    )
    path = str(tmp_path / "syn.tctr")
    save(trace, path)
    loaded = Trace.load(path)
    assert loaded.outcome_vector() == trace.outcome_vector()
    report = differential_replay(loaded, "device")
    assert report.ok, report.summary()


# --------------------------------------- deterministic fault replay #


def _supervised_chaos_run(injector, recorder=None):
    """One degrade -> recover lifecycle under a supervised limiter with
    `injector` armed; returns the per-window outcome planes."""
    from throttlecrab_tpu.faults import arm as arm_faults
    from throttlecrab_tpu.faults import disarm as disarm_faults
    from throttlecrab_tpu.server.supervisor import SupervisedLimiter
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    lim = TpuRateLimiter(capacity=512)
    lim.rate_limit_batch(["__warm__"], 5, 100, 60, 1, T0 - NS)
    sup = SupervisedLimiter(
        lim, retries=0, probe_interval_ms=1, sleep_fn=lambda s: None
    )
    keys = [f"cr:{i % 6}" for i in range(8)]
    outcomes = []
    arm_faults(injector)
    if recorder is not None:
        arm(recorder)
    try:
        now = T0
        for step in range(12):
            res = sup.rate_limit_batch(keys, 3, 10, 60, 1, now)
            outcomes.append((
                np.asarray(res.allowed, np.uint8).copy(),
                np.asarray(res.status, np.uint8).copy(),
            ))
            if recorder is not None:
                recorder.record_window(
                    now, keys, [[3, 10, 60, 1]] * len(keys),
                    res.allowed, res.status,
                )
            now += 10 * NS  # past the probe interval: recovery happens
        assert sup.state == "ok", "lifecycle never recovered"
        assert sup.degrade_count >= 1, "lifecycle never degraded"
    finally:
        disarm_faults()
        disarm()
    return outcomes


def test_fault_schedule_replay_reproduces_chaos_run(tmp_path):
    """Acceptance: a chaos run armed with THROTTLECRAB_FAULTS-style
    injection and trace capture, replayed from its trace, reproduces
    the identical per-window outcome vector and identical
    fired-injection sequence."""
    from throttlecrab_tpu.faults import FaultInjector, parse_spec

    path = str(tmp_path / "chaos.tctr")
    recorder = FlightRecorder(
        mode="full", out_dir=str(tmp_path), path=path,
        dump_on_degrade=False,
    )
    live = FaultInjector(parse_spec("launch:count:2"), seed=11)
    live_out = _supervised_chaos_run(live, recorder)
    recorder.close()
    live_schedule = live.fired_schedule()
    assert live_schedule, "the fault never fired: vacuous chaos run"

    trace = Trace.load(path)
    # The trace captured the exact firings and the lifecycle events.
    assert trace.injection_schedule() == live_schedule
    kinds = [e.kind for e in trace.events]
    assert "degrade" in kinds and "repromote" in kinds

    # Replay: schedule-armed injector, fresh supervised limiter.
    replayed = injector_from_trace(trace)
    replay_out = _supervised_chaos_run(replayed)
    assert outcome_vector(replay_out) == outcome_vector(live_out), (
        "fault replay drifted from the live chaos run"
    )
    assert replayed.fired_schedule() == live_schedule, (
        "replayed firing sequence differs"
    )


def test_scheduled_injector_fires_exact_indexes():
    from throttlecrab_tpu.faults import FaultInjector, InjectedDeviceError

    inj = FaultInjector.from_schedule(
        [("launch", "count", 1, 0.0), ("launch", "transient", 3, 0.5)]
    )
    inj.check("launch")  # index 0: passes
    with pytest.raises(InjectedDeviceError):
        inj.check("launch")  # index 1: fires
    inj.check("launch")  # index 2: passes
    with pytest.raises(InjectedDeviceError):
        inj.check("launch")  # index 3: fires
    inj.check("launch")  # index 4: passes
    inj.check("fetch")   # unscheduled site: passes
    assert [i[2] for i in inj.fired_schedule()] == [1, 3]


# ------------------------------------------------- dump-on-degrade #


def test_supervisor_degrade_dumps_flight_recorder(tmp_path):
    from throttlecrab_tpu.faults import FaultInjector
    from throttlecrab_tpu.faults import arm as arm_faults
    from throttlecrab_tpu.faults import disarm as disarm_faults
    from throttlecrab_tpu.faults import parse_spec
    from throttlecrab_tpu.server.supervisor import SupervisedLimiter
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    rec = FlightRecorder(capacity=64, out_dir=str(tmp_path))
    arm(rec)
    lim = TpuRateLimiter(capacity=256)
    lim.rate_limit_batch(["__warm__"], 5, 100, 60, 1, T0 - NS)
    sup = SupervisedLimiter(
        lim, retries=0, probe_interval_ms=10_000,
        sleep_fn=lambda s: None,
    )
    try:
        arm_faults(FaultInjector(parse_spec("launch:count:1"), seed=1))
        res = sup.rate_limit_batch(["k"], 5, 100, 60, 1, T0)
        assert res.allowed[0]  # host oracle served it
        assert sup.state == "degraded"
        # The dump rides a daemon thread; wait for the artifact.
        deadline = time.monotonic() + 10
        dumped = []
        while time.monotonic() < deadline and not dumped:
            dumped = glob.glob(os.path.join(str(tmp_path), "*.tctr"))
            time.sleep(0.05)
        assert dumped, "degrade produced no trace dump"
        trace = Trace.load(dumped[0])
        assert any(e.kind == "degrade" for e in trace.events)
        # The injection that killed the device is in the artifact too.
        assert trace.injections and trace.injections[0].site == "launch"
    finally:
        disarm_faults()
        disarm()


# ------------------------------------------------ /trace/dump route #


def test_http_trace_dump_route(tmp_path):
    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.http import HttpTransport
    from throttlecrab_tpu.server.metrics import Metrics
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    async def run():
        engine = BatchingEngine(
            TpuRateLimiter(capacity=256), batch_size=8,
            max_linger_us=100, now_fn=lambda: T0,
        )
        transport = HttpTransport("127.0.0.1", 0, engine, Metrics())
        # Disarmed: the route answers enabled:false, no 404 probing.
        status, payload, ctype = await transport._route(
            "GET", "/trace/dump", b""
        )
        assert status == 200 and b'"enabled": false' in payload

        rec = FlightRecorder(capacity=16, out_dir=str(tmp_path))
        arm(rec)
        rec.record_window(T0, [b"k"], [[5, 100, 60, 1]], [1], [0])
        status, payload, _ = await transport._route(
            "GET", "/trace/dump", b""
        )
        assert status == 200
        import json

        doc = json.loads(payload)
        assert doc["enabled"] and doc["windows"] == 1
        assert Trace.load(doc["path"]).n_rows() == 1
        await engine.shutdown()

    try:
        asyncio.run(run())
    finally:
        disarm()


# -------------------------------------------- fault-fired metrics #


def test_faults_injected_total_metric():
    from throttlecrab_tpu.faults import FaultInjector
    from throttlecrab_tpu.faults import arm as arm_faults
    from throttlecrab_tpu.faults import disarm as disarm_faults
    from throttlecrab_tpu.faults import parse_spec
    from throttlecrab_tpu.server.metrics import METRIC_NAMES, Metrics

    assert "throttlecrab_tpu_faults_injected_total" in METRIC_NAMES
    m = Metrics()
    # Disarmed: the name still exports (dashboards need the series).
    assert "throttlecrab_tpu_faults_injected_total 0" in (
        m.export_prometheus()
    )
    inj = FaultInjector(parse_spec("keymap:count:2"), seed=3)
    arm_faults(inj)
    try:
        for _ in range(3):
            try:
                inj.check("keymap")
            except Exception:
                pass
        text = m.export_prometheus()
        assert (
            'throttlecrab_tpu_faults_injected_total{site="keymap"} 2'
            in text
        ), text
    finally:
        disarm_faults()


# ------------------------------------------------- harness surface #


def test_loadgen_summary_surfaces_seed_and_pattern():
    from throttlecrab_tpu.harness.loadgen import PerfResult

    r = PerfResult(
        "http", 10, 1.0, 5, 5, 0, seed=42, key_pattern="flash-crowd"
    )
    s = r.summary()
    assert s["seed"] == 42 and s["key_pattern"] == "flash-crowd"


def test_harness_trace_roundtrip(tmp_path):
    """_write_harness_trace output loads and drives a replay schedule
    (the --record -> --replay loop, minus live sockets)."""
    from throttlecrab_tpu.harness.loadgen import _write_harness_trace

    rows = [
        ("k:1", 5, 100, 60, 1, True, T0),
        ("k:2", 5, 100, 60, 2, False, T0 + 1),
        ("k:3", 5, 100, 60, 1, None, T0 + 2),  # transport error
    ]
    path = str(tmp_path / "h.tctr")
    _write_harness_trace(path, [rows])
    trace = Trace.load(path)
    w = trace.windows[0]
    assert w.keys == [b"k:1", b"k:2", b"k:3"]
    assert w.allowed.tolist() == [1, 0, 0]
    assert w.status.tolist() == [0, 0, 3]
    np.testing.assert_array_equal(w.params[:, 0], [5, 5, 5])
    # The per-row quantity column survives the record -> replay loop
    # (replay schedules honor it; clients send it on every transport).
    np.testing.assert_array_equal(w.params[:, 3], [1, 2, 1])


def test_loadgen_seed_offsets_key_streams():
    from throttlecrab_tpu.harness.workload import make_keys

    a = make_keys("random", 50, 1000, seed=7)
    b = make_keys("random", 50, 1000, seed=7)
    c = make_keys("random", 50, 1000, seed=8)
    assert a == b and a != c
