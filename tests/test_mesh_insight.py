"""Mesh-native multi-tenant serving (ISSUE 6): insight on the sharded
mesh, namespace routing, and per-tenant isolation.

The acceptance contract:

  * sharded+insight decisions AND stored state (tat, expiry, AND the
    per-slot denied-hit heat) are bit-identical to the single-device
    oracle under the tier-fuzz key patterns;
  * `THROTTLECRAB_INSIGHT=0` restores 4-wide shard rows bit-identically
    on the mesh (kill switch = a different compiled program, not a
    traced branch);
  * the mesh top-K is GLOBAL (per-shard partial top-K merged over the
    `shard` axis in one launch) and its ids resolve to real keys
    through the per-shard keymaps;
  * sweeps clear the insight heat columns per shard;
  * the tenant layer: vectorized CRC32 routing bit-identical to zlib,
    psum-reduced per-tenant counters matching a host recount,
    tenant-affine routing making a tenant's keys shard-local, and slot
    quotas refusing one tenant's spray without touching its live keys
    or any other tenant;
  * `--shards N` + insight serves GET /stats with truthful mesh-global
    totals and per-tenant counters.
"""

import asyncio
import json
import zlib

import numpy as np
import pytest

from conftest import require_devices
from throttlecrab_tpu.harness.workload import make_keys
from throttlecrab_tpu.insight import InsightTier
from throttlecrab_tpu.parallel.sharded import (
    ShardedTpuRateLimiter,
    make_mesh,
    shard_of_key,
)
from throttlecrab_tpu.parallel.tenants import (
    TenantRegistry,
    crc32_rows,
    key_matrix,
    prefix_lens,
)
from throttlecrab_tpu.tpu.kernel import INS_WIDTH, unpack_deny
from throttlecrab_tpu.tpu.limiter import STATUS_TENANT_QUOTA, TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


@pytest.fixture(scope="module")
def mesh():
    require_devices(4)
    return make_mesh(4)


def _tenant_keys(rng, n, tenants=6, per_tenant=24):
    return [
        f"t{rng.integers(tenants)}:k{rng.integers(per_tenant)}"
        for _ in range(n)
    ]


def _per_key_state(lim, key):
    """(tat, expiry, deny) of one key on a sharded insight limiter."""
    d = lim.shard_of(key.encode())
    slot = dict(lim.keymaps[d].items())[key]
    return (
        int(np.asarray(lim.table.tat)[d, slot]),
        int(np.asarray(lim.table.expiry)[d, slot]),
        int(np.asarray(lim.table.deny)[d, slot]),
    )


# --------------------------------------------------------------------- #
# Routing: the vectorized CRC32 twin and tenant prefixes.


def test_vectorized_crc32_matches_zlib():
    rng = np.random.default_rng(11)
    keys = [
        bytes(rng.integers(0, 256, rng.integers(0, 40), dtype=np.uint8))
        for _ in range(300)
    ] + [b"", b":", b"t0:", b"plain-key", b"x" * 300]
    mat, lens = key_matrix(keys)
    got = crc32_rows(mat, lens)
    want = np.array([zlib.crc32(k) for k in keys], np.uint32)
    assert (got == want).all()
    for D in (2, 4, 8):
        assert (
            (got % np.uint32(D)).astype(np.int32)
            == np.array([shard_of_key(k, D) for k in keys], np.int32)
        ).all()


def test_prefix_lens_and_tenant_ids():
    keys = [b"acme:user:1", b"no-delim", b":leading", b"", b"acme:x"]
    mat, lens = key_matrix(keys)
    plens = prefix_lens(mat, lens, ord(":"))
    assert plens.tolist() == [4, 0, 0, 0, 4]
    reg = TenantRegistry(max_tenants=4)
    tids = [
        reg.tid_of(bytes(k[:p])) for k, p in zip(keys, plens)
    ]
    # acme gets one id; the three default-namespace keys share another.
    assert tids[0] == tids[4] and tids[1] == tids[2] == tids[3]
    assert tids[0] != tids[1]
    # Registry bound: extras collapse into the overflow bucket (id 0).
    for i in range(10):
        reg.tid_of(b"tenant-%d" % i)
    assert reg.tid_of(b"one-too-many") == 0


def test_oversized_key_routes_per_key(mesh):
    """One megabyte-scale key must not inflate the whole batch's
    routing matrix (O(n × longest key)): the batch falls back to the
    exact per-key path, and routing stays identical to the vectorized
    twin for every normal key."""
    from throttlecrab_tpu.parallel.tenants import KeyTooLong

    with pytest.raises(KeyTooLong):
        key_matrix([b"x" * (1 << 20), b"small"])
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=128, mesh=mesh,
        tenants=TenantRegistry(max_tenants=8, affinity=True),
    )
    big = "tbig:" + "x" * (1 << 20)
    keys = [f"ta:k{j}" for j in range(6)] + [big]
    res = lim.rate_limit_batch(keys, 5, 10, 60, 1, T0, wire=True)
    assert (np.asarray(res.status) == 0).all()
    for k in keys:
        # Fallback routing == the vectorized single-key twin.
        d = lim.shard_of(k.encode())
        assert k in dict(lim.keymaps[d].items()), k


def test_quota_spray_cannot_force_growth(mesh):
    """The documented guarantee: an at-quota tenant spraying fresh keys
    into a full shard is refused BEFORE the table grows — growth only
    serves within-quota demand."""
    reg = TenantRegistry(max_tenants=8, quota_frac=0.25, affinity=True)
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=64, mesh=mesh, tenants=reg, auto_grow=True,
    )
    cap_before = lim.table.capacity
    # Fill the abusive tenant to its quota (0.25 * 64 = 16 slots).
    lim.rate_limit_batch(
        [f"tq:f{j}" for j in range(16)], 3, 10, 3600, 1, T0
    )
    # Spray far past the shard's free-slot count: every key is over
    # quota, so the table must refuse WITHOUT growing.
    spray = [f"tq:s{j}" for j in range(200)]
    res = lim.rate_limit_batch(spray, 3, 10, 3600, 1, T0, wire=True)
    assert (np.asarray(res.status) == STATUS_TENANT_QUOTA).all()
    assert lim.table.capacity == cap_before
    assert lim.keymaps[0].capacity == cap_before
    # Within-quota demand from another tenant still grows as designed.
    other = [f"tz:s{j}" for j in range(80)]
    res2 = lim.rate_limit_batch(other, 3, 10, 3600, 1, T0, wire=True)
    assert (np.asarray(res2.status) == 0).sum() > 0
    assert lim.table.capacity > cap_before


# --------------------------------------------------------------------- #
# Differential: sharded+insight vs the single-device oracle.


@pytest.mark.parametrize("pattern", ["hotkey-abuse", "chaos"])
def test_sharded_insight_bit_identical_to_single_device(mesh, pattern):
    """Decisions AND stored state — tat, expiry, and the per-slot
    denied-hit heat — pinned bit-identical between the mesh and the
    single-device insight limiter under tier-fuzz key patterns
    (including quantity-0 probes, which force the degenerate path)."""
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=512, mesh=mesh, insight=True,
        tenants=TenantRegistry(max_tenants=8),
    )
    single = TpuRateLimiter(capacity=2048, keymap="python", insight=True)
    rng = np.random.default_rng(hash(pattern) % (1 << 31))
    stream = make_keys(pattern, 640, 800, seed=5)
    for i in range(8):
        ks = stream[i * 80 : (i + 1) * 80]
        qty = [0 if rng.random() < 0.05 else 1 for _ in ks]
        now = T0 + i * NS // 5
        r1 = lim.rate_limit_batch(ks, 4, 20, 60, qty, now, wire=True)
        r2 = single.rate_limit_batch(ks, 4, 20, 60, qty, now, wire=True)
        for name in ("allowed", "remaining", "reset_after_s",
                     "retry_after_s", "status"):
            g = np.asarray(getattr(r1, name))
            w = np.asarray(getattr(r2, name))
            assert (g == w).all(), (pattern, i, name)
        # (The scalar-oracle differential for the sharded mesh lives in
        # the tier fuzzer — scripts/fuzz_wire_tiers.py run_seed — which
        # now alternates insight-armed meshes; here the single-device
        # insight limiter IS the pinned oracle, state included.)
    # State bit-identity per key: the mesh rows equal the single-device
    # rows column for column, heat included.
    deny_1 = np.asarray(unpack_deny(single.table.state))
    tat_1 = np.asarray(single.table.tat)
    exp_1 = np.asarray(single.table.expiry)
    slots_1 = dict(single.keymap.items())
    checked = 0
    for k in set(stream):
        if k not in slots_1:
            continue
        s1 = slots_1[k]
        assert _per_key_state(lim, k) == (
            int(tat_1[s1]), int(exp_1[s1]), int(deny_1[s1]),
        ), k
        checked += 1
    assert checked > 50


def test_insight_kill_switch_bit_identity_on_mesh(mesh):
    """THROTTLECRAB_INSIGHT=0 on the mesh = 4-wide rows and decisions/
    state bit-identical to the insight build (a separate compiled
    program per width, never a traced branch)."""
    on = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True
    )
    off = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=False
    )
    assert on.table.state.shape[-1] == INS_WIDTH
    assert off.table.state.shape[-1] == 4
    stream = make_keys("hotkey-abuse", 480, 600, seed=9)
    for i in range(6):
        ks = stream[i * 80 : (i + 1) * 80]
        now = T0 + i * NS // 3
        r_on = on.rate_limit_batch(ks, 3, 10, 60, 1, now, wire=True)
        r_off = off.rate_limit_batch(ks, 3, 10, 60, 1, now, wire=True)
        for name in ("allowed", "remaining", "reset_after_s",
                     "retry_after_s"):
            assert (
                np.asarray(getattr(r_on, name))
                == np.asarray(getattr(r_off, name))
            ).all(), (i, name)
    assert (np.asarray(on.table.tat) == np.asarray(off.table.tat)).all()
    assert (
        np.asarray(on.table.expiry) == np.asarray(off.table.expiry)
    ).all()


# --------------------------------------------------------------------- #
# Mesh insight surfaces: totals, global top-K, decay, sweep.


def test_mesh_topk_is_global_and_resolves_keys(mesh):
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True
    )
    # Keys spread over shards, denied a controlled number of times
    # each: key i is hammered (4 + i) times with burst 2, so exactly 2
    # allow and (2 + i) deny.  (Burst 1 would allow EVERYTHING — the
    # ttl-0 dead-write quirk pinned in test_gcra_math.)
    keys = [f"hot{i}" for i in range(12)]
    for i, k in enumerate(keys):
        lim.rate_limit_batch([k] * (4 + i), 2, 1, 3600, 1, T0)
    want = {k: 2 + i for i, k in enumerate(keys)}
    tk = lim.table.insight_topk(12)
    vals = np.asarray(tk[0]).tolist()
    ids = np.asarray(tk[1]).tolist()
    assert vals == sorted(want.values(), reverse=True)
    from throttlecrab_tpu.insight.collector import ShardedSlotKeyResolver

    got = {
        k: v
        for v, k in zip(vals, ShardedSlotKeyResolver(lim).keys_for(ids))
        if v > 0
    }
    assert got == want
    # The keys really do live on several shards (global merge, not one
    # shard's view).
    assert len({lim.shard_of(k.encode()) for k in keys}) > 1
    # Decay halves every shard's heat.
    lim.table.insight_decay()
    tk2 = lim.table.insight_topk(12)
    assert np.asarray(tk2[0]).tolist() == sorted(
        (v // 2 for v in want.values()), reverse=True
    )


def test_sweep_clears_heat_per_shard(mesh):
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=128, mesh=mesh, insight=True
    )
    keys = [f"sw{i}" for i in range(40)]
    for _ in range(4):
        lim.rate_limit_batch(keys, 2, 10, 1, 1, T0)
    assert int(np.asarray(lim.table.deny).sum()) > 0
    freed = lim.sweep(T0 + 3600 * NS)
    assert freed == len(keys)
    # A vacated slot's heat dies with it on EVERY shard — a recycled
    # slot must not inherit the old key's counts.
    assert int(np.asarray(lim.table.deny).sum()) == 0
    assert len(lim) == 0


def test_insight_tier_on_mesh_truthful_stats(mesh):
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True,
        tenants=TenantRegistry(max_tenants=8),
    )
    tier = InsightTier(limiter=lim, poll_ms=1, decay_s=0)
    tier.prime()
    rng = np.random.default_rng(3)
    total = 0
    allowed_want = denied_want = 0
    for i in range(6):
        ks = _tenant_keys(rng, 96)
        res = lim.rate_limit_batch(ks, 2, 10, 60, 1, T0 + i * NS, wire=True)
        allowed_want += int(np.asarray(res.allowed).sum())
        total += len(ks)
        tier.maybe_poll(T0 + i * NS)
    denied_want = total - allowed_want
    tier.poll(T0 + 10 * NS)
    doc = tier.stats(state="ok")
    assert doc["totals"]["allowed"] == allowed_want
    assert doc["totals"]["denied"] == denied_want
    # The hot-key sketch resolved real keys through the shard keymaps.
    assert doc["top_denied"] and doc["top_denied"][0]["key"].startswith("t")
    # Per-tenant counters rode the launch psum and sum to the totals.
    tenants = doc["tenants"]
    assert sum(t["allowed"] for t in tenants.values()) == allowed_want
    assert sum(t["denied"] for t in tenants.values()) == denied_want


def test_growth_rebases_heat_deltas_without_double_count(mesh):
    """Sharded table growth re-bases the global slot-id encoding; the
    tier's next poll must re-baseline, NOT diff new ids against stale
    entries (which would re-record hot slots' whole cumulative counts
    into the sketch)."""
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=128, mesh=mesh, insight=True
    )
    tier = InsightTier(limiter=lim, poll_ms=1, decay_s=0)
    tier.prime()
    # 10 denials on one hot key (quantity 2 > burst 1: every
    # request denies), recorded by the first poll.
    lim.rate_limit_batch(["hot"] * 10, 1, 1, 3600, 2, T0)
    tier.poll(T0 + NS)
    count0 = dict(tier.sketch.top(4)).get("hot")
    assert count0 == 10
    # Grow (re-bases ids), then poll with NO new traffic: the sketch
    # must not re-record the cumulative 10.
    for km in lim.keymaps:
        km.grow(256)
    lim.table.grow(256)
    lim._grow_tenant_slots(256)
    tier.poll(T0 + 2 * NS)
    assert dict(tier.sketch.top(4)).get("hot") == 10
    # New denials after the re-base record their DELTA only.
    lim.rate_limit_batch(["hot"] * 4, 1, 1, 3600, 2, T0 + 3 * NS)
    tier.poll(T0 + 4 * NS)
    assert dict(tier.sketch.top(4)).get("hot") == 14


def test_engine_serves_stats_for_sharded_insight(mesh):
    """The ISSUE's acceptance surface: a sharded limiter + insight tier
    behind the engine answers GET /stats with truthful mesh-global
    totals and per-tenant counters."""
    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.http import HttpTransport
    from throttlecrab_tpu.server.metrics import Metrics
    from throttlecrab_tpu.server.types import ThrottleRequest

    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True,
        tenants=TenantRegistry(max_tenants=8),
    )
    tier = InsightTier(limiter=lim, poll_ms=1, decay_s=0)
    tier.prime()
    clock = {"now": T0}

    async def run():
        engine = BatchingEngine(
            lim, batch_size=16, max_linger_us=100,
            now_fn=lambda: clock["now"], insight=tier,
        )
        outcomes = []
        for step in range(4):
            reqs = [
                ThrottleRequest(f"t{i % 3}:web:{i}", 2, 10, 60, 1)
                for i in range(32)
            ]
            outcomes += await asyncio.gather(
                *[engine.throttle(r) for r in reqs]
            )
            clock["now"] += NS
        await engine.shutdown()
        tier.poll(clock["now"] + NS)
        t = HttpTransport("127.0.0.1", 0, engine, Metrics())
        status, payload, ctype = await t._route("GET", "/stats", b"")
        assert status == 200 and ctype == "application/json"
        return outcomes, json.loads(payload)

    outcomes, doc = asyncio.run(run())
    allowed_want = sum(1 for o in outcomes if o.allowed)
    assert doc["totals"]["allowed"] == allowed_want
    assert doc["totals"]["denied"] == len(outcomes) - allowed_want
    assert set(doc["tenants"]) == {"t0", "t1", "t2"}
    assert (
        sum(t["allowed"] for t in doc["tenants"].values()) == allowed_want
    )


# --------------------------------------------------------------------- #
# Tenant layer: counters, affinity, quotas.


def test_tenant_affinity_makes_keys_shard_local(mesh):
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh,
        tenants=TenantRegistry(max_tenants=16, affinity=True),
    )
    keys = [f"t{t}:k{j}" for t in range(8) for j in range(16)]
    lim.rate_limit_batch(keys, 5, 10, 60, 1, T0)
    for t in range(8):
        homes = {
            d
            for d, km in enumerate(lim.keymaps)
            for k, _ in km.items()
            if k.startswith(f"t{t}:")
        }
        assert len(homes) == 1, (t, homes)
    # Bare keys (no namespace) still spread by full-key hash.
    bare = [f"bare{i}" for i in range(64)]
    lim.rate_limit_batch(bare, 5, 10, 60, 1, T0)
    assert len({lim.shard_of(k.encode()) for k in bare}) > 1


def test_tenant_quota_isolates_without_touching_live_keys(mesh):
    reg = TenantRegistry(max_tenants=8, quota_frac=0.05, affinity=True)
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True, tenants=reg,
    )
    cap = int(0.05 * 256)  # 12 slots per tenant per shard
    # The abusive tenant sprays fresh keys; exactly `cap` allocate.
    spray = [f"t0:spray{j}" for j in range(64)]
    res = lim.rate_limit_batch(spray, 3, 10, 60, 1, T0, wire=True)
    status = np.asarray(res.status)
    assert (status == STATUS_TENANT_QUOTA).sum() == 64 - cap
    assert (status == 0).sum() == cap
    # Refused lanes look like errors, not denials (no garbage wire
    # values; transports map the status to the quota error string).
    refused = status == STATUS_TENANT_QUOTA
    assert not np.asarray(res.allowed)[refused].any()
    # Another tenant allocates freely — isolation, not global pressure.
    other = lim.rate_limit_batch(
        [f"t1:k{j}" for j in range(8)], 3, 10, 60, 1, T0, wire=True
    )
    assert (np.asarray(other.status) == 0).all()
    # The at-quota tenant's LIVE keys keep deciding normally.
    again = lim.rate_limit_batch(["t0:spray0"], 3, 10, 60, 1, T0 + 1,
                                 wire=True)
    assert int(again.status[0]) == 0
    # Rejections are visible per tenant.
    assert lim.tenant_stats()["t0"]["quota_rejections"] == 64 - cap
    # A sweep releases the quota with the slots.
    lim.sweep(T0 + 7200 * NS)
    fresh = lim.rate_limit_batch(
        [f"t0:post{j}" for j in range(4)], 3, 10, 60, 1,
        T0 + 7200 * NS, wire=True,
    )
    assert (np.asarray(fresh.status) == 0).all()


def test_tenant_counters_ride_the_scan_path_too(mesh):
    """dispatch_many (the engine's K-deep backlog path) accumulates the
    same per-tenant psum counters as the single-batch path."""
    reg = TenantRegistry(max_tenants=8)
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True, tenants=reg,
    )
    rng = np.random.default_rng(7)
    batches = []
    for j in range(3):
        ks = _tenant_keys(rng, 64, tenants=4)
        batches.append((ks, 2, 10, 60, 1, T0 + j))
    results = lim.rate_limit_many(batches, wire=True)
    want_allowed = sum(
        int(np.asarray(r.allowed).sum()) for r in results
    )
    stats = lim.tenant_stats()
    assert sum(t["allowed"] for t in stats.values()) == want_allowed
    assert sum(t["denied"] for t in stats.values()) == 3 * 64 - want_allowed


def test_snapshot_roundtrip_sharded_insight_tenants(mesh, tmp_path):
    """Save/restore across widened rows + tenant-affine routing: state
    survives, restored keys land on the routing-correct shards, and the
    quota bookkeeping is rebuilt (a restored slot must never be
    mistaken for a fresh allocation and quota-refused)."""
    from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

    reg = TenantRegistry(max_tenants=8, quota_frac=0.1, affinity=True)
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True, tenants=reg,
    )
    keys = [f"t{t}:k{j}" for t in range(3) for j in range(10)]
    for i in range(3):
        lim.rate_limit_batch(keys, 3, 10, 3600, 1, T0 + i)
    before = {k: _per_key_state(lim, k) for k in keys}
    path = str(tmp_path / "mesh-snap")
    save_snapshot(lim, path)

    reg2 = TenantRegistry(max_tenants=8, quota_frac=0.1, affinity=True)
    lim2 = ShardedTpuRateLimiter(
        capacity_per_shard=256, mesh=mesh, insight=True, tenants=reg2,
    )
    restored = load_snapshot(lim2, path + ".npz", now_ns=T0 + NS)
    assert restored == len(keys)
    for k in keys:
        # tat/expiry survive; heat restarts at zero (like the
        # single-device restore).
        assert _per_key_state(lim2, k)[:2] == before[k][:2], k
        assert _per_key_state(lim2, k)[2] == 0
    # Restored slots are quota-attributed: the next touch decides
    # normally instead of being treated as a fresh allocation.
    again = lim2.rate_limit_batch(keys[:5], 3, 10, 3600, 1, T0 + 2 * NS,
                                  wire=True)
    assert (np.asarray(again.status) == 0).all()
    assert lim2._tenant_used is not None
    assert int(sum(u.sum() for u in lim2._tenant_used)) == len(keys)


# --------------------------------------------------------------------- #
# Boot: loud warnings when a requested tier cannot be built.


def test_boot_warns_when_insight_tier_dropped(caplog):
    import logging

    from throttlecrab_tpu.server.config import Config
    from throttlecrab_tpu.server.metrics import Metrics
    from throttlecrab_tpu.server.store import create_insight

    class NoTableLimiter:
        pass

    cfg = Config(http=True)
    with caplog.at_level(logging.WARNING, logger="throttlecrab.store"):
        assert create_insight(cfg, Metrics(), NoTableLimiter(), None) is None
    assert any(
        "insight tier requested" in r.message for r in caplog.records
    )


def test_boot_warns_when_deny_cache_uncertifiable(mesh, caplog):
    import logging

    from throttlecrab_tpu.server.config import Config
    from throttlecrab_tpu.server.metrics import Metrics
    from throttlecrab_tpu.server.store import create_front_tier

    lim = ShardedTpuRateLimiter(capacity_per_shard=256, mesh=mesh)
    # An EXPLICIT (non-default) cache size warns loudly.
    cfg = Config(http=True, front_deny_cache=1024)
    with caplog.at_level(logging.INFO, logger="throttlecrab.store"):
        front = create_front_tier(cfg, Metrics(), lim)
    # Admission half still builds; the cache half was dropped loudly.
    assert front is not None and front.deny_cache is None
    dropped = [
        r for r in caplog.records if "cannot certify entries" in r.message
    ]
    assert dropped and dropped[0].levelno == logging.WARNING
    # The untouched DEFAULT stays informative, not alarming.
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="throttlecrab.store"):
        create_front_tier(Config(http=True), Metrics(), lim)
    dropped = [
        r for r in caplog.records if "cannot certify entries" in r.message
    ]
    assert dropped and dropped[0].levelno == logging.INFO


def test_tenant_quota_surfaces_as_overload(mesh):
    """A quota refusal is a capacity condition: the engine raises the
    protocol overload error (HTTP 503 / gRPC RESOURCE_EXHAUSTED), never
    a 500-class internal error."""
    from throttlecrab_tpu.server.engine import BatchingEngine, OverloadError
    from throttlecrab_tpu.server.types import ThrottleRequest

    reg = TenantRegistry(max_tenants=8, quota_frac=0.05, affinity=True)
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=64, mesh=mesh, tenants=reg,
    )
    clock = {"now": T0}

    async def run():
        eng = BatchingEngine(
            lim, batch_size=8, max_linger_us=100,
            now_fn=lambda: clock["now"],
        )
        outcomes = []
        for j in range(12):  # quota = 0.05 * 64 = 3 slots
            try:
                outcomes.append(
                    await eng.throttle(
                        ThrottleRequest(f"q:spray{j}", 3, 10, 3600, 1)
                    )
                )
            except Exception as e:
                outcomes.append(e)
            clock["now"] += 1_000_000
        await eng.shutdown()
        return outcomes

    outcomes = asyncio.run(run())
    overloads = [o for o in outcomes if isinstance(o, OverloadError)]
    assert len(overloads) == 12 - 3
    assert "quota" in str(overloads[0])


def test_mixed_batch_keeps_affine_routing(mesh):
    """A non-bytes hashable key in a batch (python keymap) must not
    change how the BYTES keys in that batch route: the per-key fallback
    uses the same tenant-affine hash as the vectorized path."""
    reg = TenantRegistry(max_tenants=8, affinity=True)
    lim = ShardedTpuRateLimiter(
        capacity_per_shard=128, mesh=mesh, tenants=reg,
    )
    clean = [f"ta:k{j}" for j in range(8)]
    lim.rate_limit_batch(clean, 5, 10, 60, 1, T0)
    mixed = clean + [("exotic", 1)]
    lim.rate_limit_batch(mixed, 5, 10, 60, 1, T0 + 1)
    # Every ta: key still lives on exactly one shard — no forked
    # buckets from the fallback path.
    homes = {
        d
        for d, km in enumerate(lim.keymaps)
        for k, _ in km.items()
        if isinstance(k, str) and k.startswith("ta:")
    }
    assert len(homes) == 1
    assert len(lim) == len(clean) + 1  # no duplicate slots


def test_tenant_config_validation():
    from throttlecrab_tpu.server.config import Config, ConfigError

    with pytest.raises(ConfigError):
        Config(http=True, tenant_max=0, tenant_affinity=True,
               shards=2).validate()
    with pytest.raises(ConfigError):
        Config(http=True, tenant_max=0, tenant_quota=0.5,
               shards=2).validate()
    with pytest.raises(ConfigError):  # isolation knobs need the mesh
        Config(http=True, tenant_affinity=True).validate()
    with pytest.raises(ConfigError):
        Config(http=True, tenant_quota=0.5).validate()
    with pytest.raises(ConfigError):
        Config(http=True, tenant_max=1, shards=2).validate()
    with pytest.raises(ConfigError):
        Config(http=True, tenant_delim="::", shards=2).validate()
    Config(http=True, shards=2, tenant_affinity=True,
           tenant_quota=0.1).validate()
    Config(http=True).validate()  # defaults stay valid on one device


# --------------------------------------------------------------------- #
# Harness: the noisy-neighbor scenario is replayable.


def test_noisy_neighbor_pattern_shape():
    ks = make_keys("noisy-neighbor", 4000, 64_000, seed=2)
    tenants = {k.split(":", 1)[0] for k in ks}
    assert "t0" in tenants and len(tenants) > 40
    n_abuse = sum(k.startswith("t0:") for k in ks)
    # ~50% of the stream is the abusive tenant; the rest spreads.
    assert 0.4 < n_abuse / len(ks) < 0.6
    # The abusive tenant both hammers a tiny hot set AND sprays fresh
    # keys (quota pressure); compliant tenants stay inside their range.
    t0_keys = {k for k in ks if k.startswith("t0:")}
    hot = [k for k in ks if k.startswith("t0:key:") and
           int(k.rsplit(":", 1)[1]) < 10]
    assert len(hot) > len(ks) // 4
    assert len(t0_keys) > 300  # the fresh-key spray
    # Determinism: same seed, same stream (replayable scenario).
    assert ks == make_keys("noisy-neighbor", 4000, 64_000, seed=2)


def test_loadgen_per_tenant_tally():
    from throttlecrab_tpu.harness.loadgen import PerfResult

    r = PerfResult("http", 0, 0.0, 0, 0, 0)
    r.track_tenant("t0:key:1", False)
    r.track_tenant("t0:key:1", False)
    r.track_tenant("t1:key:2", True)
    r.track_tenant("bare", None)
    s = r.tenant_summary()
    assert list(s)[0] == "t0"  # worst deny rate first
    assert s["t0"] == {
        "allowed": 0, "denied": 2, "errors": 0, "deny_rate": 1.0,
    }
    assert s["t1"]["allowed"] == 1
    assert s["(default)"]["errors"] == 1
