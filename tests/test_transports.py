"""In-process transport tests over real sockets.

The reference tests its transports against real listeners without external
processes (`grpc.rs:196-296`, `transport/redis_test.rs`); same here: each
test boots the transport on an ephemeral port, drives it with a raw client,
and asserts wire-level behavior — shared limiter state across transports
included (`tests/integration/multi_transport.rs:159-225`).
"""

import asyncio
import json

from throttlecrab_tpu.server.engine import BatchingEngine
from throttlecrab_tpu.server.http import HttpTransport
from throttlecrab_tpu.server.metrics import Metrics
from throttlecrab_tpu.server.redis import RedisTransport
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

T0 = 1_700_000_000 * 1_000_000_000


def make_stack(**engine_kwargs):
    metrics = Metrics(max_denied_keys=10)
    limiter = TpuRateLimiter(capacity=1024)
    engine = BatchingEngine(
        limiter,
        batch_size=engine_kwargs.pop("batch_size", 64),
        max_linger_us=engine_kwargs.pop("max_linger_us", 500),
        now_fn=lambda: T0,
        **engine_kwargs,
    )
    return engine, metrics


async def http_request(port, method, path, body=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    head = (
        f"{method} {path} HTTP/1.1\r\n"
        "Host: localhost\r\n"
        f"Content-Length: {len(payload)}\r\n"
        "Connection: close\r\n\r\n"
    ).encode()
    writer.write(head + payload)
    await writer.drain()
    raw = await reader.read(-1)
    writer.close()
    await writer.wait_closed()
    head_raw, _, body_raw = raw.partition(b"\r\n\r\n")
    status = int(head_raw.split(b" ", 2)[1])
    return status, body_raw


async def resp_command(reader, writer, *parts):
    frame = b"*%d\r\n" % len(parts)
    for part in parts:
        data = part.encode() if isinstance(part, str) else part
        frame += b"$%d\r\n%s\r\n" % (len(data), data)
    writer.write(frame)
    await writer.drain()
    return await asyncio.wait_for(reader.read(4096), timeout=2.0)


# ------------------------------------------------------------------ HTTP #


def test_http_throttle_health_metrics():
    async def main():
        engine, metrics = make_stack()
        transport = HttpTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port

        body = {"key": "u:1", "max_burst": 3, "count_per_period": 10,
                "period": 60}
        allowed = []
        for _ in range(5):
            status, raw = await http_request(port, "POST", "/throttle", body)
            assert status == 200
            allowed.append(json.loads(raw)["allowed"])

        status, raw = await http_request(port, "GET", "/health")
        assert (status, raw) == (200, b"OK")

        status, raw = await http_request(port, "GET", "/metrics")
        assert status == 200
        text = raw.decode()
        assert "throttlecrab_requests_total 5" in text
        assert 'transport="http"} 5' in text
        assert "throttlecrab_requests_allowed 3" in text
        assert "throttlecrab_requests_denied 2" in text
        assert 'throttlecrab_top_denied_keys{key="u:1",rank="1"} 2' in text

        await transport.stop()
        return allowed

    allowed = asyncio.run(main())
    assert allowed == [True, True, True, False, False]


def test_http_error_shapes():
    async def main():
        engine, metrics = make_stack()
        transport = HttpTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port

        # Malformed JSON → 400 with error payload.
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        bad = b"not json"
        writer.write(
            b"POST /throttle HTTP/1.1\r\nHost: x\r\nContent-Length: "
            + str(len(bad)).encode() + b"\r\nConnection: close\r\n\r\n" + bad
        )
        await writer.drain()
        raw = await reader.read(-1)
        writer.close()
        assert b" 400 " in raw.split(b"\r\n", 1)[0]
        assert b"error" in raw

        # Invalid params → 500 (engine-level error, like the reference).
        status, raw = await http_request(
            port, "POST", "/throttle",
            {"key": "k", "max_burst": -1, "count_per_period": 10,
             "period": 60},
        )
        assert status == 500
        assert b"invalid rate limit parameters" in raw

        # Unknown route → 404.
        status, _ = await http_request(port, "GET", "/nope")
        assert status == 404

        await transport.stop()

    asyncio.run(main())


def test_http_quantity_defaults_to_one():
    async def main():
        engine, metrics = make_stack()
        transport = HttpTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        body = {"key": "q", "max_burst": 10, "count_per_period": 100,
                "period": 60}
        _, raw = await http_request(port, "POST", "/throttle", body)
        first = json.loads(raw)
        await transport.stop()
        return first

    first = asyncio.run(main())
    assert first["allowed"] is True
    assert first["remaining"] == 9  # one token consumed


def test_http_keep_alive_pipelining():
    async def main():
        engine, metrics = make_stack()
        transport = HttpTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        body = json.dumps({"key": "ka", "max_burst": 10,
                           "count_per_period": 100, "period": 60}).encode()
        one = (
            b"POST /throttle HTTP/1.1\r\nHost: x\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        writer.write(one + one)  # two requests, one connection
        await writer.drain()
        data = b""
        while data.count(b"HTTP/1.1 200") < 2:
            chunk = await asyncio.wait_for(reader.read(4096), timeout=2.0)
            if not chunk:
                break
            data += chunk
        writer.close()
        await transport.stop()
        return data

    data = asyncio.run(main())
    assert data.count(b"HTTP/1.1 200") == 2


# ----------------------------------------------------------------- Redis #


def test_redis_ping_throttle_quit():
    async def main():
        engine, metrics = make_stack()
        transport = RedisTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)

        assert await resp_command(reader, writer, "PING") == b"+PONG\r\n"
        assert await resp_command(reader, writer, "PING", "hi") == (
            b"$2\r\nhi\r\n"
        )
        # Case-insensitive commands (redis/mod.rs:166).
        # burst 3 @ 10/60s: emission 6s, tolerance 12s → first hit leaves
        # remaining=2, reset_after=12s.
        out = await resp_command(reader, writer, "throttle", "rk", "3",
                                 "10", "60")
        assert out == b"*5\r\n:1\r\n:3\r\n:2\r\n:12\r\n:0\r\n"
        for _ in range(2):
            out = await resp_command(reader, writer, "THROTTLE", "rk", "3",
                                     "10", "60")
        assert out.startswith(b"*5\r\n:1\r\n")
        out = await resp_command(reader, writer, "THROTTLE", "rk", "3",
                                 "10", "60")
        assert out.startswith(b"*5\r\n:0\r\n")  # burst exhausted

        assert await resp_command(reader, writer, "QUIT") == b"+OK\r\n"
        assert await reader.read(16) == b""  # server closed

        await transport.stop()
        return metrics

    metrics = asyncio.run(main())
    assert metrics.requests_total == 4
    assert metrics.requests_denied == 1


def test_redis_error_cases():
    async def main():
        engine, metrics = make_stack()
        transport = RedisTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)

        out = await resp_command(reader, writer, "NOSUCH")
        assert out == b"-ERR unknown command 'NOSUCH'\r\n"
        out = await resp_command(reader, writer, "THROTTLE", "k")
        assert b"wrong number of arguments" in out
        out = await resp_command(reader, writer, "THROTTLE", "k", "abc",
                                 "10", "60")
        assert out == b"-ERR invalid max_burst\r\n"
        # Quantity argument works: burst 10 @ 100/60s, qty 5 → remaining 5,
        # reset_after 7.8s truncated to 7.
        out = await resp_command(reader, writer, "THROTTLE", "qk", "10",
                                 "100", "60", "5")
        assert out == b"*5\r\n:1\r\n:10\r\n:5\r\n:7\r\n:0\r\n"
        writer.close()
        await transport.stop()

    asyncio.run(main())


def test_redis_partial_frames_accumulate():
    async def main():
        engine, metrics = make_stack()
        transport = RedisTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        frame = b"*1\r\n$4\r\nPING\r\n"
        writer.write(frame[:5])
        await writer.drain()
        await asyncio.sleep(0.05)
        writer.write(frame[5:])
        await writer.drain()
        out = await asyncio.wait_for(reader.read(64), timeout=2.0)
        writer.close()
        await transport.stop()
        return out

    assert asyncio.run(main()) == b"+PONG\r\n"


def test_redis_malformed_input_closes_with_error():
    async def main():
        engine, metrics = make_stack()
        transport = RedisTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"*999999999999\r\n")
        await writer.drain()
        out = await asyncio.wait_for(reader.read(256), timeout=2.0)
        writer.close()
        await transport.stop()
        return out

    assert asyncio.run(main()).startswith(b"-ERR")


# ------------------------------------------------------------------ gRPC #


def test_grpc_throttle_roundtrip():
    import grpc.aio

    from throttlecrab_tpu.server.grpc import GrpcTransport
    from throttlecrab_tpu.server.proto import throttlecrab_pb2 as pb

    async def main():
        engine, metrics = make_stack()
        transport = GrpcTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port

        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            method = channel.unary_unary(
                "/throttlecrab.RateLimiter/Throttle",
                request_serializer=pb.ThrottleRequest.SerializeToString,
                response_deserializer=pb.ThrottleResponse.FromString,
            )
            results = []
            for _ in range(5):
                response = await method(
                    pb.ThrottleRequest(
                        key="g:1", max_burst=3, count_per_period=10,
                        period=60, quantity=1,
                    )
                )
                results.append(response.allowed)
            last = response
        await transport.stop()
        return results, last, metrics

    results, last, metrics = asyncio.run(main())
    assert results == [True, True, True, False, False]
    assert last.limit == 3
    assert last.retry_after >= 1
    assert metrics.requests_by_transport["grpc"] == 5


# ------------------------------------- shared state across transports #


def test_multi_transport_shared_limits():
    """One key, limits shared across HTTP and Redis
    (multi_transport.rs:159-225)."""

    async def main():
        engine, metrics = make_stack()
        http_t = HttpTransport("127.0.0.1", 0, engine, metrics)
        redis_t = RedisTransport("127.0.0.1", 0, engine, metrics)
        await http_t.start()
        await redis_t.start()

        body = {"key": "shared", "max_burst": 4, "count_per_period": 10,
                "period": 60}
        seq = []
        for _ in range(2):
            _, raw = await http_request(
                http_t.bound_port, "POST", "/throttle", body
            )
            seq.append(json.loads(raw)["allowed"])
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", redis_t.bound_port
        )
        for _ in range(3):
            out = await resp_command(reader, writer, "THROTTLE", "shared",
                                     "4", "10", "60")
            seq.append(out.startswith(b"*5\r\n:1\r\n"))
        writer.close()
        await http_t.stop()
        await redis_t.stop()
        return seq

    assert asyncio.run(main()) == [True, True, True, True, False]


def test_stop_with_open_connections_returns_promptly():
    """stop() must drop idle open connections (the reference aborts its
    transport tasks on shutdown) instead of waiting out the 5-minute idle
    read — Server.wait_closed() on 3.12+ waits for every handler."""

    async def main():
        engine, metrics = make_stack()
        http_t = HttpTransport("127.0.0.1", 0, engine, metrics)
        redis_t = RedisTransport("127.0.0.1", 0, engine, metrics)
        await http_t.start()
        await redis_t.start()

        # One live connection per transport, both left open and idle.
        r1, w1 = await asyncio.open_connection(
            "127.0.0.1", redis_t.bound_port
        )
        out = await resp_command(r1, w1, "THROTTLE", "sd", "3", "10", "60")
        assert out.startswith(b"*5\r\n:1\r\n")
        r2, w2 = await asyncio.open_connection(
            "127.0.0.1", http_t.bound_port
        )
        w2.write(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
        await w2.drain()
        await r2.read(64)  # keep-alive: handler stays in its read loop

        await asyncio.wait_for(redis_t.stop(), timeout=5.0)
        await asyncio.wait_for(http_t.stop(), timeout=5.0)
        for w in (w1, w2):
            w.close()

    asyncio.run(main())


# ------------------------------------------------- client deadlines #


class _Clock:
    def __init__(self, start=T0):
        self.now = start

    def __call__(self):
        return self.now


def make_deadline_stack():
    """batch_size=2 + huge linger: the deadline-carrying request parks
    in the queue until a second one fills the batch, so the test —
    not the scheduler — decides what the flush-time clock reads."""
    metrics = Metrics(max_denied_keys=10)
    limiter = TpuRateLimiter(capacity=1024)
    clock = _Clock()
    engine = BatchingEngine(
        limiter, batch_size=2, max_linger_us=10_000_000, now_fn=clock
    )
    return engine, metrics, clock


def test_http_deadline_header_sheds_504():
    """`X-Throttlecrab-Deadline-Ms` stamps a client deadline; a request
    still queued past it answers 504 while its batchmate — flushed in
    the same window — still gets a real decision."""

    async def main():
        engine, metrics, clock = make_deadline_stack()
        transport = HttpTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port

        async def with_deadline():
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port
            )
            payload = json.dumps(
                {"key": "dl", "max_burst": 3, "count_per_period": 10,
                 "period": 60}
            ).encode()
            writer.write((
                "POST /throttle HTTP/1.1\r\nHost: x\r\n"
                "X-Throttlecrab-Deadline-Ms: 5\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode() + payload)
            await writer.drain()
            raw = await reader.read(-1)
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            return int(head.split(b" ", 2)[1]), body

        t1 = asyncio.create_task(with_deadline())
        # Let it enqueue, then lapse its 5 ms budget on the virtual
        # clock before the batch-filling second request flushes.
        await asyncio.sleep(0.1)
        clock.now += 10 * 1_000_000
        status2, raw2 = await http_request(
            port, "POST", "/throttle",
            {"key": "dl2", "max_burst": 3, "count_per_period": 10,
             "period": 60},
        )
        status1, raw1 = await t1
        await transport.stop()
        return status1, raw1, status2, raw2, engine.deadline_shed

    status1, raw1, status2, raw2, shed = asyncio.run(main())
    assert status1 == 504
    assert b"deadline exceeded" in raw1
    assert status2 == 200 and json.loads(raw2)["allowed"]
    assert shed == 1


def test_redis_deadline_token_sheds_err():
    """THROTTLE's optional 7th token is a deadline in ms: an invalid
    one answers -ERR immediately; a lapsed one sheds the queued request
    with -ERR deadline exceeded (single RESP error channel)."""

    async def main():
        engine, metrics, clock = make_deadline_stack()
        transport = RedisTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        r1, w1 = await asyncio.open_connection("127.0.0.1", port)
        r2, w2 = await asyncio.open_connection("127.0.0.1", port)

        out = await resp_command(
            r1, w1, "THROTTLE", "dk", "3", "10", "60", "1", "abc"
        )
        assert out == b"-ERR invalid deadline_ms\r\n"

        t1 = asyncio.create_task(
            resp_command(
                r1, w1, "THROTTLE", "dk", "3", "10", "60", "1", "5"
            )
        )
        await asyncio.sleep(0.1)
        clock.now += 10 * 1_000_000
        out2 = await resp_command(r2, w2, "THROTTLE", "dk2", "3", "10",
                                  "60")
        out1 = await t1
        for w in (w1, w2):
            w.close()
        await transport.stop()
        return out1, out2, engine.deadline_shed

    out1, out2, shed = asyncio.run(main())
    assert out1 == b"-ERR deadline exceeded\r\n"
    assert out2.startswith(b"*5\r\n:1\r\n")
    assert shed == 1


def test_grpc_native_deadline_sheds_deadline_exceeded():
    """gRPC carries deadlines natively: the call's remaining budget
    maps onto the engine deadline, so a request whose budget lapses
    in-queue is shed host-side with DEADLINE_EXCEEDED instead of
    spending a device launch on an abandoned call."""
    import grpc
    import grpc.aio

    from throttlecrab_tpu.server.grpc import GrpcTransport
    from throttlecrab_tpu.server.proto import throttlecrab_pb2 as pb

    async def main():
        engine, metrics, clock = make_deadline_stack()
        transport = GrpcTransport("127.0.0.1", 0, engine, metrics)
        await transport.start()
        port = transport.bound_port
        async with grpc.aio.insecure_channel(
            f"127.0.0.1:{port}"
        ) as channel:
            method = channel.unary_unary(
                "/throttlecrab.RateLimiter/Throttle",
                request_serializer=pb.ThrottleRequest.SerializeToString,
                response_deserializer=pb.ThrottleResponse.FromString,
            )
            # 30 s real-time budget: far more than the test needs, so
            # the DEADLINE_EXCEEDED below can only come from the
            # engine's virtual-clock shed, not the client timer.
            t1 = asyncio.ensure_future(method(
                pb.ThrottleRequest(
                    key="gd", max_burst=3, count_per_period=10,
                    period=60, quantity=1,
                ),
                timeout=30.0,
            ))
            await asyncio.sleep(0.2)
            clock.now += 60 * 1_000_000_000
            ok = await method(
                pb.ThrottleRequest(
                    key="gd2", max_burst=3, count_per_period=10,
                    period=60, quantity=1,
                )
            )
            code = None
            try:
                await t1
            except grpc.aio.AioRpcError as e:
                code = e.code()
        await transport.stop()
        return code, ok.allowed, engine.deadline_shed

    code, ok, shed = asyncio.run(main())
    assert code == grpc.StatusCode.DEADLINE_EXCEEDED
    assert ok
    assert shed == 1
