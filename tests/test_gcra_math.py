"""GCRA engine tests, ported from the reference's `core/tests.rs`.

Virtual time: `now_ns` is an explicit input, so tests add whole-second /
millisecond offsets to a fixed base timestamp exactly like the reference
passes `now + Duration::from_secs(n)`.
"""

import pytest

from throttlecrab_tpu import (
    AdaptiveStore,
    CellError,
    PeriodicStore,
    ProbabilisticStore,
    RateLimiter,
)
from throttlecrab_tpu.core.i64 import I64_MAX

NS = 1_000_000_000
# Fixed virtual base; all time travel is expressed as offsets from it.
BASE = 1_753_700_000 * NS


def secs(n: float) -> int:
    return int(n * NS)


def millis(n: int) -> int:
    return n * NS // 1000


@pytest.fixture
def limiter():
    return RateLimiter(PeriodicStore())


def test_basic_rate_limiting(limiter):
    allowed, result = limiter.rate_limit("test", 5, 10, 60, 1, BASE)
    assert allowed
    assert result.limit == 5
    assert result.remaining == 4


def test_burst_capacity(limiter):
    for i in range(5):
        allowed, result = limiter.rate_limit("burst_test", 5, 10, 60, 1, BASE)
        assert allowed, f"request {i + 1} should be allowed"
        assert result.remaining == 5 - (i + 1)

    allowed, result = limiter.rate_limit("burst_test", 5, 10, 60, 1, BASE)
    assert not allowed
    assert result.remaining == 0
    assert result.retry_after_secs > 0


def test_rate_replenishment(limiter):
    allowed1, _ = limiter.rate_limit("replenish_test", 2, 60, 60, 1, BASE)
    allowed2, _ = limiter.rate_limit("replenish_test", 2, 60, 60, 1, BASE)
    assert allowed1 and allowed2

    allowed3, _ = limiter.rate_limit("replenish_test", 2, 60, 60, 1, BASE)
    assert not allowed3

    allowed4, _ = limiter.rate_limit(
        "replenish_test", 2, 60, 60, 1, BASE + secs(1)
    )
    assert allowed4


def test_different_keys(limiter):
    allowed1, _ = limiter.rate_limit("key1", 2, 2, 60, 1, BASE)
    allowed2, _ = limiter.rate_limit("key2", 2, 2, 60, 1, BASE)
    assert allowed1 and allowed2

    allowed3, _ = limiter.rate_limit("key1", 2, 2, 60, 1, BASE)
    assert allowed3
    allowed4, _ = limiter.rate_limit("key1", 2, 2, 60, 1, BASE)
    assert not allowed4

    allowed5, _ = limiter.rate_limit("key2", 2, 2, 60, 1, BASE)
    assert allowed5
    allowed6, _ = limiter.rate_limit("key2", 2, 2, 60, 1, BASE)
    assert not allowed6


def test_quantity_parameter(limiter):
    allowed1, result1 = limiter.rate_limit("quantity_test", 10, 10, 60, 5, BASE)
    assert allowed1
    assert result1.remaining == 5

    allowed2, result2 = limiter.rate_limit("quantity_test", 10, 10, 60, 6, BASE)
    assert not allowed2
    assert result2.remaining == 5

    allowed3, result3 = limiter.rate_limit("quantity_test", 10, 10, 60, 5, BASE)
    assert allowed3
    assert result3.remaining == 0


def test_negative_quantity_error(limiter):
    with pytest.raises(CellError):
        limiter.rate_limit("test", 10, 10, 60, -1, BASE)


def test_invalid_parameters(limiter):
    with pytest.raises(CellError):
        limiter.rate_limit("test", 0, 10, 60, 1, BASE)
    with pytest.raises(CellError):
        limiter.rate_limit("test", 10, 0, 60, 1, BASE)
    with pytest.raises(CellError):
        limiter.rate_limit("test", 10, 10, 0, 1, BASE)


def test_large_quantity_overflow_protection(limiter):
    allowed, _ = limiter.rate_limit(
        "overflow_test", 10, 10, 60, I64_MAX // 2, BASE
    )
    assert not allowed


def test_saturating_arithmetic(limiter):
    # Large burst capacity and large count per period must not blow up.
    limiter.rate_limit("saturate_test", I64_MAX // 1000, 100, 60, 1, BASE)
    limiter.rate_limit("saturate_test2", 10, I64_MAX // 1000, 60, 1, BASE)


def test_remaining_count_accuracy(limiter):
    burst, rate, period = 5, 10, 60  # 1 token / 6 s

    allowed, result = limiter.rate_limit("remaining_test", burst, rate, period, 1, BASE)
    assert allowed
    assert result.remaining == 4

    for i in range(2, 6):
        allowed, result = limiter.rate_limit(
            "remaining_test", burst, rate, period, 1, BASE
        )
        assert allowed, f"request {i} should be allowed"
        assert result.remaining == 5 - i

    allowed, result = limiter.rate_limit("remaining_test", burst, rate, period, 1, BASE)
    assert not allowed
    assert result.remaining == 0
    assert result.retry_after_secs > 0

    after_replenish = BASE + secs(6)
    allowed, result = limiter.rate_limit(
        "remaining_test", burst, rate, period, 1, after_replenish
    )
    assert allowed
    assert result.remaining == 0

    allowed, result = limiter.rate_limit(
        "remaining_test", burst, rate, period, 1, after_replenish
    )
    assert not allowed
    assert result.remaining == 0

    # Larger quantities.
    allowed, result = limiter.rate_limit(
        "quantity_remaining", burst, rate, period, 3, BASE
    )
    assert allowed
    assert result.remaining == 2

    allowed, result = limiter.rate_limit(
        "quantity_remaining", burst, rate, period, 3, BASE
    )
    assert not allowed
    assert result.remaining == 2

    allowed, result = limiter.rate_limit(
        "quantity_remaining", burst, rate, period, 2, BASE
    )
    assert allowed
    assert result.remaining == 0

    # High rate: 600/60s = 10/s.
    allowed, result = limiter.rate_limit("high_rate", 10, 600, 60, 1, BASE)
    assert allowed
    assert result.remaining == 9

    for _ in range(9):
        limiter.rate_limit("high_rate", 10, 600, 60, 1, BASE)

    allowed, result = limiter.rate_limit("high_rate", 10, 600, 60, 1, BASE + secs(1))
    assert allowed
    assert result.remaining < 10


@pytest.mark.parametrize(
    "store_factory", [PeriodicStore, AdaptiveStore, ProbabilisticStore]
)
def test_remaining_count_all_stores(store_factory):
    limiter = RateLimiter(store_factory())
    for i in range(1, 4):
        allowed, result = limiter.rate_limit("test_key", 3, 6, 60, 1, BASE)
        assert allowed, f"request {i} should be allowed"
        assert result.remaining == 3 - i

    allowed, result = limiter.rate_limit("test_key", 3, 6, 60, 1, BASE)
    assert not allowed
    assert result.remaining == 0

    # 6/60s = 1 token / 10 s.
    allowed, result = limiter.rate_limit("test_key", 3, 6, 60, 1, BASE + secs(10))
    assert allowed
    assert result.remaining == 0


def test_edge_cases_zero_remaining(limiter):
    # Exact replenishment timing: 120/60s = 2/s.
    allowed, result = limiter.rate_limit("exact_timing", 2, 120, 60, 1, BASE)
    assert allowed and result.remaining == 1
    allowed, result = limiter.rate_limit("exact_timing", 2, 120, 60, 1, BASE)
    assert allowed and result.remaining == 0

    allowed, result = limiter.rate_limit(
        "exact_timing", 2, 120, 60, 1, BASE + millis(500)
    )
    assert allowed and result.remaining == 0

    # Division-by-zero protection.
    with pytest.raises(CellError):
        limiter.rate_limit("zero_period", 10, 10, 0, 1, BASE)

    # Fractional tokens: 7/60s ≈ 8.57 s per token.
    allowed, result = limiter.rate_limit("fractional", 3, 7, 60, 1, BASE)
    assert allowed and result.remaining == 2
    limiter.rate_limit("fractional", 3, 7, 60, 1, BASE)
    limiter.rate_limit("fractional", 3, 7, 60, 1, BASE)

    allowed, _ = limiter.rate_limit("fractional", 3, 7, 60, 1, BASE + secs(8))
    assert not allowed
    allowed, result = limiter.rate_limit("fractional", 3, 7, 60, 1, BASE + secs(9))
    assert allowed and result.remaining == 0

    # Maximum values.
    allowed, result = limiter.rate_limit("max_burst", I64_MAX // 1000, 100, 60, 1, BASE)
    assert allowed
    assert result.remaining > 0


def test_quantity_variations_and_replenishment(limiter):
    # burst=10, 60/60s = 1/s.
    allowed, result = limiter.rate_limit("multi_quantity", 10, 60, 60, 5, BASE)
    assert allowed and result.remaining == 5

    allowed, result = limiter.rate_limit("multi_quantity", 10, 60, 60, 6, BASE)
    assert not allowed and result.remaining == 5

    allowed, result = limiter.rate_limit("multi_quantity", 10, 60, 60, 5, BASE)
    assert allowed and result.remaining == 0

    allowed, result = limiter.rate_limit(
        "multi_quantity", 10, 60, 60, 2, BASE + secs(3)
    )
    assert allowed and result.remaining == 1

    # Gradual replenishment: burst=5, 120/60s = 2/s.
    for ms, expected_available, expected_remaining in [
        (500, 1, 0),
        (1000, 2, 1),
        (1500, 3, 2),
        (2000, 4, 3),
        (2500, 5, 4),
    ]:
        key = f"gradual_replenish_{ms}"
        for _ in range(5):
            limiter.rate_limit(key, 5, 120, 60, 1, BASE)
        allowed, result = limiter.rate_limit(key, 5, 120, 60, 1, BASE + millis(ms))
        assert allowed, f"at {ms}ms should be allowed"
        assert result.remaining == expected_remaining, (
            f"at {ms}ms: {expected_available} available, expected "
            f"{expected_remaining} remaining after use"
        )


def test_complex_replenishment_scenarios(limiter):
    # Partial burst usage: burst=8, 240/60s = 4/s.
    allowed, result = limiter.rate_limit("partial_burst", 8, 240, 60, 6, BASE)
    assert allowed and result.remaining == 2

    allowed, result = limiter.rate_limit(
        "partial_burst", 8, 240, 60, 1, BASE + millis(500)
    )
    assert allowed and result.remaining == 3

    allowed, result = limiter.rate_limit(
        "partial_burst", 8, 240, 60, 1, BASE + millis(1500)
    )
    assert allowed and result.remaining == 6

    # Slow replenishment: burst=3, 6/60s = 1 per 10 s.
    for _ in range(3):
        limiter.rate_limit("slow_replenish", 3, 6, 60, 1, BASE)
    allowed, _ = limiter.rate_limit("slow_replenish", 3, 6, 60, 1, BASE + secs(5))
    assert not allowed
    allowed, result = limiter.rate_limit("slow_replenish", 3, 6, 60, 1, BASE + secs(10))
    assert allowed and result.remaining == 0
    allowed, result = limiter.rate_limit("slow_replenish", 3, 6, 60, 1, BASE + secs(20))
    assert allowed and result.remaining == 0

    # Fractional accumulation: burst=5, 100/60s = 0.6 s per token.
    for ms, should_allow, expected_remaining in [
        (600, True, 0),
        (1200, True, 1),
        (1800, True, 2),
        (2400, True, 3),
        (3000, True, 4),
    ]:
        key = f"fractional_accumulation_{ms}"
        for _ in range(5):
            limiter.rate_limit(key, 5, 100, 60, 1, BASE)
        allowed, result = limiter.rate_limit(key, 5, 100, 60, 1, BASE + millis(ms))
        assert allowed == should_allow, f"at {ms}ms"
        if allowed:
            assert result.remaining == expected_remaining, f"at {ms}ms"


def test_quantity_edge_cases(limiter):
    # Zero quantity is a free probe.
    allowed, result = limiter.rate_limit("zero_quantity", 10, 100, 60, 0, BASE)
    assert allowed
    assert result.remaining == 10

    with pytest.raises(CellError):
        limiter.rate_limit("neg_quantity", 10, 100, 60, -5, BASE)

    allowed, result = limiter.rate_limit("large_quantity", 5, 100, 60, 10, BASE)
    assert not allowed
    assert result.remaining == 5

    allowed, result = limiter.rate_limit("exact_burst", 10, 100, 60, 10, BASE)
    assert allowed
    assert result.remaining == 0

    # burst=20, 600/60s = 10/s.
    key = "large_quantity_replenish"
    allowed, result = limiter.rate_limit(key, 20, 600, 60, 15, BASE)
    assert allowed and result.remaining == 5

    allowed, result = limiter.rate_limit(key, 20, 600, 60, 12, BASE + secs(1))
    assert allowed and result.remaining == 3

    allowed, result = limiter.rate_limit(key, 20, 600, 60, 5, BASE + secs(1))
    assert not allowed and result.remaining == 3


def test_rapid_time_changes(limiter):
    allowed1, _ = limiter.rate_limit("time_jump", 3, 10, 60, 1, BASE)
    assert allowed1

    # Jump backward 5 seconds: must not raise.
    limiter.rate_limit("time_jump", 3, 10, 60, 1, BASE - secs(5))

    allowed2, _ = limiter.rate_limit("time_jump", 3, 10, 60, 1, BASE + secs(10))
    assert allowed2

    for i in range(5):
        jittered = BASE + secs(i) if i % 2 == 0 else BASE - secs(i)
        limiter.rate_limit("time_jitter", 10, 10, 60, 1, jittered)


def test_pre_epoch_clock_fallback(limiter):
    # A pre-epoch (negative) timestamp falls back to wall-clock minus one
    # period (rate_limiter.rs:126-144) instead of erroring.
    allowed, _ = limiter.rate_limit("skew", 5, 10, 60, 1, -NS)
    assert allowed


def test_burst_one_ttl_zero_quirk(limiter):
    # burst=1 means tolerance 0; the first allowed write stores TAT=now with
    # TTL 0, which is already expired at the same instant — so a second
    # check at the exact same timestamp is allowed again.  This mirrors the
    # reference's TTL formula (rate_limiter.rs:179-183) + expiry-filtering
    # get (periodic.rs:175-181).
    allowed, _ = limiter.rate_limit("b1", 1, 1, 60, 1, BASE)
    assert allowed
    allowed, _ = limiter.rate_limit("b1", 1, 1, 60, 1, BASE)
    assert allowed
    # In fact with burst=1 the stored TAT always equals `now` and the TTL is
    # always 0, so a burst-1 limiter never denies — at any timestamp.
    allowed, _ = limiter.rate_limit("b1", 1, 1, 60, 1, BASE + 1)
    assert allowed


def test_retry_after_when_denied(limiter):
    # burst=2, 60/60s: E=1s, tolerance=1s.
    allowed, result = limiter.rate_limit("retry", 2, 60, 60, 1, BASE)
    assert allowed
    assert result.retry_after_ns == 0
    assert result.reset_after_ns == NS  # tat=now, +tolerance
    assert result.remaining == 1

    allowed, result = limiter.rate_limit("retry", 2, 60, 60, 1, BASE)
    assert allowed
    assert result.remaining == 0
    assert result.reset_after_ns == 2 * NS

    allowed, result = limiter.rate_limit("retry", 2, 60, 60, 1, BASE)
    assert not allowed
    assert result.retry_after_ns == NS
    assert result.reset_after_ns == 2 * NS
    assert result.remaining == 0
