"""Front-tier (L3.5) differential suite: the deny cache must be
*invisible* in every decision and the admission controller must surface
each protocol's overload status.

The load-bearing property is exactness: a deny served from the cache
must be byte-identical — allowed, limit, remaining, and the *decayed*
reset/retry fields — to what the engine would have produced at the same
virtual timestamp.  The main test runs the same hot-key abuse stream
(param churn, probes, expiry jumps, a mid-run snapshot round trip)
through two real BatchingEngines — one with the front tier, one
without — and compares every response, across all three store
policies.  The shed tests pin the overload status on every transport:
HTTP 503, gRPC RESOURCE_EXHAUSTED, RESP -ERR, and the native C++ wire
paths (epoll RESP + HTTP).
"""

import asyncio
import os
import tempfile

import numpy as np
import pytest

from throttlecrab_tpu.front import (
    AdmissionController,
    DenyCache,
    FrontTier,
    OverloadError,
)
from throttlecrab_tpu.server.engine import BatchingEngine, ThrottleError
from throttlecrab_tpu.server.metrics import Metrics
from throttlecrab_tpu.server.types import ThrottleRequest
from throttlecrab_tpu.tpu.cleanup import (
    AdaptivePolicy,
    PeriodicPolicy,
    ProbabilisticPolicy,
)
from throttlecrab_tpu.tpu.limiter import (
    TpuRateLimiter,
    limiter_uses_bytes_keys,
)

NS = 1_000_000_000
T0 = 1_800_000_000 * NS


class VirtualClock:
    def __init__(self, start_ns=T0):
        self.now = start_ns

    def __call__(self):
        return self.now


def make_front(metrics=None, limiter=None, deny=True, admission=None):
    return FrontTier(
        DenyCache(4096) if deny else None,
        admission,
        metrics=metrics,
        bytes_keys=(
            limiter_uses_bytes_keys(limiter) if limiter is not None else False
        ),
    )


def make_engine(front=None, clock=None, policy=None, limiter=None,
                **kwargs):
    clock = clock or VirtualClock()
    limiter = limiter or TpuRateLimiter(capacity=1024)
    if front is not None:
        front.bytes_keys = limiter_uses_bytes_keys(limiter)
    engine = BatchingEngine(
        limiter,
        now_fn=clock,
        front=front,
        cleanup_policy=policy,
        batch_size=kwargs.pop("batch_size", 64),
        max_linger_us=kwargs.pop("max_linger_us", 500),
        **kwargs,
    )
    return engine, clock, limiter


def req(key="k", burst=10, count=100, period=60, quantity=1):
    return ThrottleRequest(key, burst, count, period, quantity)


def norm(r):
    """Comparable shape for a response-or-exception."""
    if isinstance(r, Exception):
        return (type(r).__name__, str(r))
    return (r.allowed, r.limit, r.remaining, r.reset_after, r.retry_after)


# ===================================================================== #
# The differential: cache-on == cache-off, request by request.
# ===================================================================== #


def _abuse_window(rng, pool, params, size):
    """One window of hot-key abuse traffic: ~85 % of rows hammer the
    3 hot keys (mostly denies after the first burst), the rest touch
    the cold tail; a sprinkle of quantity-0 probes, quantity-2 spends,
    and invalid params."""
    reqs = []
    for _ in range(size):
        r = rng.random()
        if r < 0.85:
            key = pool[int(rng.integers(0, 3))]  # hot
        else:
            key = pool[int(rng.integers(3, len(pool)))]
        burst, count, period = params[key]
        q = 1
        p = rng.random()
        if p < 0.015:
            # Free probe.  Kept rare on purpose: a probe makes its whole
            # launch window degenerate, which drops the cur output tier
            # and forfeits certification for every denial in the window.
            q = 0
        elif p < 0.08:
            q = 2
        elif p < 0.10:
            burst = -1  # per-request validation error
        reqs.append(req(key, burst, count, period, q))
    return reqs


def _draw_params(rng):
    # Tight limits with slow emission (em = period/count between ~2.5 s
    # and 90 s) so hot keys saturate fast and *stay* denied across many
    # windows of 0-3 s clock steps — the deny cache's serving regime —
    # while the 120-600 s expiry jumps still vacate buckets mid-run.
    burst = int(rng.integers(2, 6))
    period = int(rng.integers(10, 90))
    count = int(rng.integers(1, 5))
    return burst, count, period


_POLICIES = {
    # Short periods/thresholds so every policy actually fires sweeps
    # inside the run (the differential must hold across sweep points).
    "periodic": lambda: PeriodicPolicy(interval_ns=20 * NS),
    "probabilistic": lambda: ProbabilisticPolicy(probability=257),
    "adaptive": lambda: AdaptivePolicy(
        min_interval_ns=10 * NS, max_interval_ns=120 * NS,
        max_operations=700,
    ),
}


@pytest.mark.parametrize("policy_name", sorted(_POLICIES))
def test_differential_cache_on_vs_off(policy_name):
    """≥ 3.5k virtual-time requests per store policy (10.5k across the
    parametrization), every response identical with and without the
    deny cache — including decayed retry/reset on cache hits, param
    churn, expiry jumps, sweeps, and a mid-run snapshot restore."""
    from throttlecrab_tpu.tpu.snapshot import load_snapshot, save_snapshot

    rng = np.random.default_rng(
        0xF2047 + {"periodic": 1, "probabilistic": 2, "adaptive": 3}[
            policy_name
        ]
    )
    n_windows, window = 112, 32

    async def run():
        clock = VirtualClock()
        front = make_front()
        eng_a, _, lim_a = make_engine(
            front=front, clock=clock, policy=_POLICIES[policy_name]()
        )
        eng_b, _, lim_b = make_engine(
            clock=clock, policy=_POLICIES[policy_name]()
        )
        pool = [f"fk:{i}" for i in range(16)]
        params = {k: _draw_params(rng) for k in pool}
        total = hits_before_restore = 0
        for step in range(n_windows):
            if rng.random() < 0.10:  # param churn on a hot key
                k = pool[int(rng.integers(0, 3))]
                params[k] = _draw_params(rng)
            reqs = _abuse_window(rng, pool, params, window)
            got_a, got_b = await asyncio.gather(
                asyncio.gather(
                    *[eng_a.throttle(r) for r in reqs],
                    return_exceptions=True,
                ),
                asyncio.gather(
                    *[eng_b.throttle(r) for r in reqs],
                    return_exceptions=True,
                ),
            )
            for i, (a, b) in enumerate(zip(got_a, got_b)):
                assert norm(a) == norm(b), (
                    f"{policy_name} step {step} row {i} "
                    f"({reqs[i]}): {norm(a)} != {norm(b)}"
                )
            total += len(reqs)
            # Decay: repeats inside a deny window land at later nows.
            clock.now += int(rng.integers(0, 3 * NS))
            if rng.random() < 0.08:  # expiry jump: vacate buckets
                clock.now += int(rng.integers(120, 600)) * NS
            if step == n_windows // 2:
                # Snapshot round trip mid-run: the restore rewrites
                # bucket state, so the cache must start over.
                hits_before_restore = front.deny_cache.hits
                assert len(front.deny_cache) > 0
                await eng_a.shutdown()
                await eng_b.shutdown()
                with tempfile.TemporaryDirectory() as d:
                    path = os.path.join(d, "snap")
                    save_snapshot(lim_a, path)
                    lim_a2 = TpuRateLimiter(capacity=1024)
                    lim_b2 = TpuRateLimiter(capacity=1024)
                    load_snapshot(
                        lim_a2, path + ".npz", now_ns=clock.now,
                        front=front,
                    )
                    load_snapshot(lim_b2, path + ".npz", now_ns=clock.now)
                assert len(front.deny_cache) == 0
                eng_a, _, lim_a = make_engine(
                    front=front, clock=clock,
                    policy=_POLICIES[policy_name](), limiter=lim_a2,
                )
                eng_b, _, lim_b = make_engine(
                    clock=clock, policy=_POLICIES[policy_name](),
                    limiter=lim_b2,
                )
        await eng_a.shutdown()
        await eng_b.shutdown()
        return total, front, hits_before_restore

    total, front, hits_before_restore = asyncio.run(run())
    assert total >= 3500
    # The equality above is vacuous unless the cache actually served:
    # the abuse mix must produce a solid hit count on both run halves.
    assert hits_before_restore > 100
    assert front.deny_cache.hits > hits_before_restore + 100


def test_param_change_never_serves_stale_denials():
    """A cached denial under params P must not leak into requests with
    params Q, and an allowed decision under Q must invalidate P's
    cached denials (the bucket moved)."""

    async def run():
        clock = VirtualClock()
        front = make_front()
        eng, _, _ = make_engine(front=front, clock=clock)
        ctl, _, _ = make_engine(clock=clock)
        out = []
        p1 = dict(burst=2, count=1, period=60)  # em = 60 s, tol = 60 s
        p2 = dict(burst=50, count=1, period=60)
        seq = (
            [req("pk", **p1)] * 4       # saturate + cache the deny
            + [req("pk", **p1)]         # served from cache
            + [req("pk", **p2)]         # bigger burst: engine, allowed
            + [req("pk", **p1)] * 2     # must re-decide (bucket moved)
        )
        for r in seq:
            a = await eng.throttle(r)
            b = await ctl.throttle(r)
            out.append((norm(a), norm(b)))
            clock.now += NS // 2
        await eng.shutdown()
        await ctl.shutdown()
        return out, front

    out, front = asyncio.run(run())
    for a, b in out:
        assert a == b
    assert front.deny_cache.hits >= 1
    # The p2 allowed decision must have dropped pk's cached denials —
    # nothing may still claim the pre-write window.
    assert out[5][0][0] is True


def test_snapshot_restore_clears_cache_direct():
    front = make_front()
    front.deny_cache._entries[("k", (1, 1, 1, 1))] = object()
    front.deny_cache._by_key["k"] = {(1, 1, 1, 1)}
    front.on_restore()
    assert len(front.deny_cache) == 0
    assert front.deny_cache._by_key == {}


# ===================================================================== #
# Admission control: shed status on every transport.
# ===================================================================== #


class _AlwaysShed(AdmissionController):
    """Deterministic overload for transport tests (queue depth varies
    with scheduling; forcing the verdict pins the wire mapping)."""

    def __init__(self):
        super().__init__(max_pending=1)

    def admit(self, depth, peek):
        with self._lock:
            if peek:
                self.shed_peek += 1
            else:
                self.shed_consume += 1
        return False


def test_engine_sheds_with_overload_error():
    async def run():
        front = make_front(deny=False, admission=_AlwaysShed())
        eng, _, _ = make_engine(front=front)
        with pytest.raises(OverloadError):
            await eng.throttle(req())
        await eng.shutdown()

    asyncio.run(run())


def test_engine_depth_bound_sheds_deterministically():
    """The real controller: max_pending=1 admits the first (depth 0)
    and sheds the second (depth 1) while the first still lingers."""

    async def run():
        front = make_front(
            deny=False, admission=AdmissionController(max_pending=1)
        )
        eng, _, _ = make_engine(front=front, max_linger_us=200_000)
        t1 = asyncio.ensure_future(eng.throttle(req(key="d1")))
        await asyncio.sleep(0.01)  # t1 is pending, not yet flushed
        with pytest.raises(OverloadError):
            await eng.throttle(req(key="d2"))
        r1 = await t1
        await eng.shutdown()
        return r1, front

    r1, front = asyncio.run(run())
    assert r1.allowed
    assert front.admission.shed_consume == 1


def test_peek_class_sheds_first():
    """Probes (quantity 0) shed at peek_frac of the depth bound while
    consuming requests still pass."""
    adm = AdmissionController(max_pending=10, peek_frac=0.5)
    assert adm.admit(depth=6, peek=False)   # < 10: consuming passes
    assert not adm.admit(depth=6, peek=True)  # >= 10 * 0.5: probe sheds
    assert adm.shed_peek == 1 and adm.shed_consume == 0


def test_wait_bound_uses_ewma():
    adm = AdmissionController(max_pending=0, max_wait_us=100)
    assert adm.admit(depth=1000, peek=False)  # no samples yet: admit
    adm.record_launch(10, 0.001)  # 100 us per request
    assert adm.estimated_wait_us(5) == pytest.approx(500.0)
    assert not adm.admit(depth=5, peek=False)  # 500 us > 100 us bound
    assert adm.admit(depth=0, peek=False)


def test_http_shed_returns_503():
    from throttlecrab_tpu.server.http import HttpTransport

    from test_transports import http_request

    async def run():
        metrics = Metrics()
        front = make_front(metrics=metrics, deny=False,
                           admission=_AlwaysShed())
        eng, _, _ = make_engine(front=front)
        transport = HttpTransport("127.0.0.1", 0, eng, metrics)
        await transport.start()
        status, raw = await http_request(
            transport.bound_port, "POST", "/throttle",
            {"key": "s", "max_burst": 3, "count_per_period": 10,
             "period": 60},
        )
        await transport.stop()
        await eng.shutdown()
        return status, raw, metrics

    status, raw, metrics = asyncio.run(run())
    assert status == 503
    assert b"overloaded" in raw
    assert metrics.front_shed_consume == 1


def test_grpc_shed_returns_resource_exhausted():
    import grpc
    import grpc.aio

    from throttlecrab_tpu.server.grpc import GrpcTransport
    from throttlecrab_tpu.server.proto import throttlecrab_pb2 as pb

    async def run():
        metrics = Metrics()
        front = make_front(metrics=metrics, deny=False,
                           admission=_AlwaysShed())
        eng, _, _ = make_engine(front=front)
        transport = GrpcTransport("127.0.0.1", 0, eng, metrics)
        await transport.start()
        port = transport.bound_port
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
            method = ch.unary_unary(
                "/throttlecrab.RateLimiter/Throttle",
                request_serializer=pb.ThrottleRequest.SerializeToString,
                response_deserializer=pb.ThrottleResponse.FromString,
            )
            try:
                await method(
                    pb.ThrottleRequest(
                        key="s", max_burst=3, count_per_period=10,
                        period=60, quantity=1,
                    )
                )
                code = None
            except grpc.aio.AioRpcError as e:
                code = e.code()
        await transport.stop()
        await eng.shutdown()
        return code

    code = asyncio.run(run())
    import grpc

    assert code == grpc.StatusCode.RESOURCE_EXHAUSTED


def test_redis_shed_returns_err_overloaded():
    from throttlecrab_tpu.server.redis import RedisTransport

    from test_transports import resp_command

    async def run():
        metrics = Metrics()
        front = make_front(metrics=metrics, deny=False,
                           admission=_AlwaysShed())
        eng, _, _ = make_engine(front=front)
        transport = RedisTransport("127.0.0.1", 0, eng, metrics)
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        raw = await resp_command(
            reader, writer, "THROTTLE", "s", "3", "10", "60", "1"
        )
        writer.close()
        await transport.stop()
        await eng.shutdown()
        return raw

    raw = asyncio.run(run())
    assert raw.startswith(b"-ERR server overloaded")


# ===================================================================== #
# Native C++ wire paths (skipped without a toolchain, same as
# test_native_wire.py).
# ===================================================================== #


def _native_available():
    from throttlecrab_tpu.native import wire_available

    return wire_available()


needs_native = pytest.mark.skipif(
    not _native_available(), reason="no C++ toolchain for the wire server"
)


def _native_stack(transport_cls, front):
    metrics = Metrics(max_denied_keys=10)
    limiter = TpuRateLimiter(capacity=1024)
    front.metrics = metrics
    front.bytes_keys = limiter_uses_bytes_keys(limiter)
    transport = transport_cls(
        "127.0.0.1", 0, limiter, metrics,
        batch_size=64, max_linger_us=500, now_fn=lambda: T0, front=front,
    )
    return transport, metrics


@needs_native
def test_native_redis_shed_and_deny_cache():
    """The C++ epoll RESP path: shed rows answer -ERR server overloaded
    (ws_respond status 4), and a repeat denial is served byte-identical
    from the deny cache without a device launch."""
    from throttlecrab_tpu.server.native_redis import NativeRedisTransport

    async def shed():
        transport, _ = _native_stack(
            NativeRedisTransport,
            FrontTier(None, _AlwaysShed()),
        )
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        frame = b"*6\r\n$8\r\nTHROTTLE\r\n$1\r\ns\r\n$1\r\n3\r\n$2\r\n10\r\n$2\r\n60\r\n$1\r\n1\r\n"
        writer.write(frame)
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(4096), timeout=5.0)
        writer.close()
        await transport.stop()
        return raw

    raw = asyncio.run(shed())
    assert raw.startswith(b"-ERR server overloaded")

    async def deny_cache():
        transport, metrics = _native_stack(
            NativeRedisTransport, FrontTier(DenyCache(1024), None)
        )
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        replies = []
        for _ in range(4):  # burst 2: allow, allow, deny, deny(cached)
            frame = (
                b"*6\r\n$8\r\nTHROTTLE\r\n$2\r\nnk\r\n$1\r\n2\r\n"
                b"$2\r\n10\r\n$2\r\n60\r\n$1\r\n1\r\n"
            )
            writer.write(frame)
            await writer.drain()
            replies.append(
                await asyncio.wait_for(reader.read(4096), timeout=5.0)
            )
        launches = metrics.device_launches
        hits = metrics.front_deny_hits
        writer.close()
        await transport.stop()
        return replies, launches, hits, transport.front

    replies, launches, hits, front = asyncio.run(deny_cache())
    # Denied replies are byte-identical whether engine- or cache-served.
    assert replies[2] == replies[3]
    assert hits >= 1
    # The cached repeat must not have launched: fewer launches than
    # requests (3 at most: 2 allows + first deny).
    assert launches <= 3
    assert front.deny_cache.hits >= 1


@needs_native
def test_native_http_shed_returns_503():
    from throttlecrab_tpu.server.native_http import NativeHttpTransport

    async def run():
        transport, _ = _native_stack(
            NativeHttpTransport, FrontTier(None, _AlwaysShed())
        )
        await transport.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", transport.bound_port
        )
        body = (b'{"key": "s", "max_burst": 3, '
                b'"count_per_period": 10, "period": 60}')
        writer.write(
            b"POST /throttle HTTP/1.1\r\nHost: x\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\nConnection: close\r\n\r\n"
            + body
        )
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(4096), timeout=5.0)
        writer.close()
        await transport.stop()
        return raw

    raw = asyncio.run(run())
    head = raw.split(b"\r\n", 1)[0]
    assert b"503" in head and b"Service Unavailable" in head
    assert b"overloaded" in raw


# ===================================================================== #
# Deny-cache unit semantics.
# ===================================================================== #


def _prime(cache, key="u", burst=3, count=60, period=60, deny_q=3,
           now=T0):
    """Feed an allowed write that saturated the bucket (new TAT at the
    clamp, now + tol) then a certifying denial one ns later — the exact
    planes the engine's cur tier would hand over.  With burst 3 /
    count 60 / period 60: em = 1 s, tol = 2 s, and a quantity-3 denial
    opens a 3 s proven window (allow_at = tat + 3 em - tol)."""
    from throttlecrab_tpu.tpu.limiter import derive_params

    em, tol, _ = derive_params([burst], [count], [period])
    em, tol = int(em[0]), int(tol[0])
    inc = em * deny_q
    tat = now + tol  # saturated: the allowed write landed on the clamp
    cache.observe(key, burst, count, period, 1, now, True,
                  seq=1, cur_ns=tat)
    deny_now = now + 1
    cache.observe(
        key, burst, count, period, deny_q, deny_now, False, seq=2,
        cur_ns=tat,
    )
    return em, tol, inc, tat


def test_deny_cache_lookup_window_and_decay():
    cache = DenyCache(64)
    em, tol, inc, tat = _prime(cache)
    hit1 = cache.lookup("u", 3, 60, 60, 3, T0 + 2)
    hit2 = cache.lookup("u", 3, 60, 60, 3, T0 + 2 + NS)
    assert hit1 is not None and hit2 is not None
    # Decay: one second later, retry/reset shrink by exactly 1 s.
    assert hit1.retry_after_ns - hit2.retry_after_ns == NS
    assert hit1.reset_after_ns - hit2.reset_after_ns == NS
    assert cache.hits == 2


def test_deny_cache_misses_without_write_record():
    cache = DenyCache(64)
    # A denial with no observed allowed write can never certify.
    cache.observe("v", 3, 60, 60, 1, T0, False, seq=1, cur_ns=T0 + NS)
    assert cache.lookup("v", 3, 60, 60, 1, T0 + 1) is None
    assert len(cache) == 0


def test_deny_cache_allowed_invalidates():
    cache = DenyCache(64)
    _prime(cache)
    assert len(cache) == 1
    cache.observe("u", 30, 60, 60, 1, T0 + 2, True, seq=3,
                  cur_ns=T0 + 5 * NS)
    assert len(cache) == 0


def test_deny_cache_inflight_blocks_lookup():
    cache = DenyCache(64)
    _prime(cache)
    cache.begin_inflight("u")
    assert cache.lookup("u", 3, 60, 60, 3, T0 + 2) is None
    cache.end_inflight("u")
    assert cache.lookup("u", 3, 60, 60, 3, T0 + 2) is not None


def test_deny_cache_fail_window_drops_key_state():
    """A launch that fails AFTER its writes may have committed
    (fail_window) must release the hold AND conservatively drop the
    key's cached denials and write record — an unobserved allow may
    have moved the TAT, so neither can certify exactness any longer."""
    cache = DenyCache(64)
    _prime(cache)
    assert len(cache) == 1 and "u" in cache._records
    cache.begin_inflight("u")
    cache.fail_window(["u"])
    assert len(cache) == 0
    assert "u" not in cache._records
    # Hold released: a fresh prime certifies again.
    _prime(cache)
    assert cache.lookup("u", 3, 60, 60, 3, T0 + 2) is not None


def test_deny_cache_negative_now_misses():
    cache = DenyCache(64)
    _prime(cache)
    assert cache.lookup("u", 3, 60, 60, 3, -5) is None


def test_deny_cache_stale_seq_cannot_roll_back_record():
    cache = DenyCache(64)
    _prime(cache)  # record at seq 1, entry at seq 2
    # A late-arriving allowed observation from an older launch (seq 0)
    # must invalidate (an allow happened) but NOT overwrite the record.
    cache.observe("u", 3, 60, 60, 1, T0, True, seq=0, cur_ns=12345)
    assert len(cache) == 0
    rec = cache._records.get("u")
    assert rec is not None and rec[0] != 12345


def test_deny_cache_capacity_bound():
    cache = DenyCache(4)
    for i in range(8):
        _prime(cache, key=f"c{i}")
    assert len(cache) <= 4
    assert len(cache._records) <= 4


def test_deny_cache_record_refresh_defers_eviction():
    """Write-record eviction is FIFO by LAST write, not first insert:
    a hot key refreshed moments ago must outlive cold-tail churn."""
    cache = DenyCache(2)
    _prime(cache, key="hot")
    _prime(cache, key="cold1")
    # Refresh the hot key's write record (a new allowed observation).
    cache.observe("hot", 3, 60, 60, 1, T0 + NS, True, seq=10,
                  cur_ns=T0 + 3 * NS)
    # Cold churn evicts ONE record: it must be cold1, not hot.
    _prime(cache, key="cold2")
    assert "hot" in cache._records
    assert "cold1" not in cache._records


def test_deny_cache_sweep_drops_expired():
    cache = DenyCache(64)
    em, tol, inc, tat = _prime(cache)
    assert len(cache) == 1
    before = cache.stale_evictions
    # The bucket's true expiry is tat + tol (writer's TTL).
    n = cache.on_sweep(tat + tol + 1)
    assert n == 1 and len(cache) == 0
    assert cache.stale_evictions == before + 1
    assert cache.lookup("u", 3, 60, 60, 3, T0 + 2) is None


def test_front_metrics_exported():
    metrics = Metrics()
    front = make_front(metrics=metrics)
    metrics.set_front_stats_provider(front.stats)
    metrics.record_front_hit()
    metrics.record_front_shed(peek=True)
    metrics.record_front_shed(peek=False)
    metrics.record_front_stale(3)
    text = metrics.export_prometheus()
    assert "throttlecrab_tpu_front_deny_hits 1" in text
    assert 'throttlecrab_tpu_front_shed{class="peek"} 1' in text
    assert 'throttlecrab_tpu_front_shed{class="consume"} 1' in text
    assert "throttlecrab_tpu_front_stale_evictions 3" in text
    assert "throttlecrab_tpu_front_deny_cache_size 0" in text


def test_config_front_knobs_validated():
    from throttlecrab_tpu.server.config import Config, ConfigError
    from throttlecrab_tpu.server.store import create_front_tier

    with pytest.raises(ConfigError):
        Config(front_peek_frac=0.0).validate()
    with pytest.raises(ConfigError):
        Config(front_deny_cache=-1).validate()
    limiter = TpuRateLimiter(capacity=64)
    cfg = Config()
    front = create_front_tier(cfg, None, limiter)
    assert front is not None
    assert front.deny_cache is not None and front.admission is not None
    off = Config(front_deny_cache=0, front_max_pending=0,
                 front_max_wait_us=0)
    assert create_front_tier(off, None, limiter) is None
