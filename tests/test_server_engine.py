"""Micro-batching engine tests.

The engine is the actor replacement: these mirror the reference's actor
tests (`actor_tests.rs:33-70` — N concurrent hits on a burst-B key allow
exactly B) plus batching-specific behavior (coalescing, linger flush,
per-request validation errors, cleanup policy integration).  The limiter
underneath is the real TPU engine on the virtual-CPU backend.
"""

import asyncio

import pytest

from throttlecrab_tpu.server.engine import BatchingEngine, ThrottleError
from throttlecrab_tpu.server.metrics import Metrics
from throttlecrab_tpu.server.types import ThrottleRequest
from throttlecrab_tpu.tpu.cleanup import PeriodicPolicy
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

NS = 1_000_000_000
T0 = 1_700_000_000 * NS


class VirtualClock:
    def __init__(self, start_ns=T0):
        self.now = start_ns

    def __call__(self):
        return self.now


def make_engine(**kwargs):
    clock = kwargs.pop("clock", VirtualClock())
    limiter = TpuRateLimiter(capacity=1024)
    engine = BatchingEngine(limiter, now_fn=clock, **kwargs)
    return engine, clock


def run(coro):
    return asyncio.run(coro)


def req(key="k", burst=10, count=100, period=60, quantity=1):
    return ThrottleRequest(key, burst, count, period, quantity)


def test_actor_invariant_exactly_burst_allowed():
    """actor_tests.rs:33-70: 20 concurrent requests, burst 10 → 10 allowed."""

    async def main():
        engine, _ = make_engine(batch_size=64, max_linger_us=1000)
        results = await asyncio.gather(
            *[engine.throttle(req(burst=10, period=3600)) for _ in range(20)]
        )
        return [r.allowed for r in results]

    allowed = run(main())
    assert sum(allowed) == 10
    # Arrival order: the first 10 get through.
    assert all(allowed[:10]) and not any(allowed[10:])


def test_actor_invariant_holds_across_batches():
    """Same 20-tasks/burst-10 invariant, but with batch_size=4 so the
    wave spans several device launches (and the scan path): exactly 10
    allowed, still in arrival order."""

    async def main():
        engine, _ = make_engine(batch_size=4, max_linger_us=500)
        results = await asyncio.gather(
            *[engine.throttle(req(burst=10, period=3600)) for _ in range(20)]
        )
        return [r.allowed for r in results]

    allowed = run(main())
    assert sum(allowed) == 10
    assert all(allowed[:10]) and not any(allowed[10:])


def test_full_batch_flushes_without_linger():
    async def main():
        engine, _ = make_engine(batch_size=4, max_linger_us=10_000_000)
        results = await asyncio.wait_for(
            asyncio.gather(
                *[engine.throttle(req(key=f"k{i}")) for i in range(4)]
            ),
            timeout=2.0,
        )
        return results

    results = run(main())
    assert all(r.allowed for r in results)


def test_linger_flushes_partial_batch():
    async def main():
        engine, _ = make_engine(batch_size=4096, max_linger_us=5_000)
        return await asyncio.wait_for(engine.throttle(req()), timeout=2.0)

    response = run(main())
    assert response.allowed
    assert response.limit == 10


def test_validation_error_is_per_request():
    async def main():
        engine, _ = make_engine(batch_size=3, max_linger_us=1000)
        good1 = engine.throttle(req(key="a"))
        bad = engine.throttle(req(key="b", burst=-1))
        good2 = engine.throttle(req(key="c"))
        results = await asyncio.gather(good1, bad, good2, return_exceptions=True)
        return results

    r1, r2, r3 = run(main())
    assert r1.allowed
    assert isinstance(r2, ThrottleError)
    assert r3.allowed


def test_negative_quantity_error_message():
    async def main():
        engine, _ = make_engine(batch_size=1)
        try:
            await engine.throttle(req(quantity=-1))
        except ThrottleError as e:
            return str(e)

    assert "negative" in run(main())


def test_seconds_truncation_at_type_boundary():
    """types.rs:87-97: durations are whole seconds on the wire."""

    async def main():
        engine, _ = make_engine(batch_size=1)
        # burst 2 @ 3/s → emission ~333ms; third hit denied with
        # retry_after ≈ 333ms, which truncates to 0 whole seconds.
        r = None
        for _ in range(3):
            r = await engine.throttle(req(key="t", burst=2, count=3, period=1))
        return r

    response = run(main())
    assert not response.allowed
    assert response.retry_after == 0  # 333ms truncates to 0 whole seconds


def test_metrics_launch_accounting():
    async def main():
        metrics = Metrics()
        limiter = TpuRateLimiter(capacity=256)
        engine = BatchingEngine(
            limiter, batch_size=8, max_linger_us=1000,
            metrics=metrics, now_fn=VirtualClock(),
        )
        await asyncio.gather(
            *[engine.throttle(req(key=f"m{i}")) for i in range(8)]
        )
        return metrics

    metrics = run(main())
    assert metrics.device_launches >= 1
    assert metrics.batched_requests == 8
    assert metrics.max_batch <= 8


def test_cleanup_policy_sweeps_between_batches():
    async def main():
        clock = VirtualClock()
        policy = PeriodicPolicy(interval_ns=60 * NS)
        limiter = TpuRateLimiter(capacity=256)
        engine = BatchingEngine(
            limiter, batch_size=1, cleanup_policy=policy, now_fn=clock,
        )
        # period 1s → TTL ~1s; expire it, then advance past the interval.
        await engine.throttle(req(key="x", burst=1, count=1, period=1))
        assert len(limiter) == 1
        clock.now += 120 * NS
        await engine.throttle(req(key="y"))  # arms the policy clock
        clock.now += 120 * NS
        await engine.throttle(req(key="z"))  # fires the sweep
        return limiter

    limiter = run(main())
    assert len(limiter) <= 2  # "x" (and possibly "y") swept


def test_shutdown_resolves_inflight_futures_when_final_flush_raises():
    """Drain-correct shutdown: even when the final flush's launch
    raises, every in-flight future must resolve (ThrottleError), never
    hang — a stuck shutdown is the wedge this repo's round-5 verdict
    documents."""

    async def main():
        engine, _ = make_engine(batch_size=4096, max_linger_us=10_000_000)

        def boom(*a, **kw):
            raise RuntimeError("injected final-flush launch failure")

        engine.limiter.dispatch_many = boom
        engine.limiter.rate_limit_many = boom
        engine.limiter.rate_limit_batch = boom
        pending = [
            asyncio.ensure_future(engine.throttle(req(key=f"s{i}")))
            for i in range(5)
        ]
        await asyncio.sleep(0)  # requests land in the pending deque
        await asyncio.wait_for(engine.shutdown(), timeout=2.0)
        # Resolve (with the error), not hang: wait_for pins the "never
        # hang" half of the contract.
        return await asyncio.wait_for(
            asyncio.gather(*pending, return_exceptions=True), timeout=2.0
        )

    results = run(main())
    assert len(results) == 5
    assert all(isinstance(r, ThrottleError) for r in results)


def test_post_shutdown_requests_have_defined_status_per_transport():
    """After shutdown every transport maps the refusal to its
    protocol's error shape: engine ThrottleError("engine is shut
    down") → HTTP 500 {"error": ...} / RESP -ERR; /health says
    "shutdown"."""
    import json

    from throttlecrab_tpu.server.http import HttpTransport
    from throttlecrab_tpu.server.redis import RedisTransport
    from throttlecrab_tpu.server.resp import BulkString, Error

    async def main():
        engine, _ = make_engine(batch_size=8, max_linger_us=500)
        metrics = Metrics()
        await engine.shutdown()
        with pytest.raises(ThrottleError, match="shut down"):
            await engine.throttle(req(key="late"))

        http = HttpTransport("127.0.0.1", 0, engine, metrics)
        body = json.dumps(
            {"key": "late", "max_burst": 1, "count_per_period": 1,
             "period": 1}
        ).encode()
        status, payload, _ctype = await http._handle_throttle(body)
        health = await http._route("GET", "/health", b"")

        redis = RedisTransport("127.0.0.1", 0, engine, metrics)
        resp = await redis._handle_throttle(
            (BulkString("THROTTLE"), BulkString("late"), BulkString("1"),
             BulkString("1"), BulkString("1"))
        )
        return status, payload, health, resp

    status, payload, health, resp = run(main())
    assert status == 500
    assert "shut down" in json.loads(payload)["error"]
    assert health == (200, b"shutdown", "text/plain")
    assert isinstance(resp, Error)
    assert resp.value.startswith("ERR") and "shut down" in resp.value


def test_shutdown_flushes_then_refuses():
    async def main():
        engine, _ = make_engine(batch_size=4096, max_linger_us=10_000_000)
        pending = asyncio.ensure_future(engine.throttle(req(key="p")))
        await asyncio.sleep(0)  # request lands in the pending list
        await engine.shutdown()
        result = await pending
        with pytest.raises(ThrottleError):
            await engine.throttle(req(key="q"))
        return result

    assert run(main()).allowed


def test_oversized_wave_splits_into_batches():
    async def main():
        engine, _ = make_engine(batch_size=16, max_linger_us=1000)
        results = await asyncio.gather(
            *[engine.throttle(req(key=f"w{i % 5}", burst=50, period=3600))
              for i in range(100)]
        )
        return results

    results = run(main())
    assert all(r.allowed for r in results)  # 20 per key < burst 50


def test_double_buffered_backlog_preserves_exactness():
    """A deep backlog drains through overlapped dispatch/fetch launches;
    the burst accounting must stay exact across the launch boundary."""

    async def main():
        engine, _ = make_engine(
            batch_size=8, max_linger_us=500, max_scan_depth=2
        )
        # 64 concurrent hits on one burst-24 key: several scan windows,
        # dispatched with window N+1 in flight before N is fetched.
        results = await asyncio.gather(
            *[engine.throttle(req(key="db", burst=24, period=3600))
              for _ in range(64)]
        )
        return results

    results = run(main())
    assert sum(r.allowed for r in results) == 24


def test_dispatch_failure_fails_only_its_window():
    """A dispatch exception must fail that window's futures and leave the
    engine serving later requests."""

    async def main():
        engine, _ = make_engine(batch_size=4, max_linger_us=500)
        orig = engine.limiter.dispatch_many
        calls = {"n": 0}

        def flaky(batches, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected dispatch failure")
            return orig(batches, **kw)

        engine.limiter.dispatch_many = flaky
        first = await asyncio.gather(
            *[engine.throttle(req(key=f"f{i}")) for i in range(4)],
            return_exceptions=True,
        )
        second = await asyncio.gather(
            *[engine.throttle(req(key=f"g{i}")) for i in range(4)]
        )
        return first, second

    first, second = run(main())
    assert all(isinstance(r, ThrottleError) for r in first)
    assert all(r.allowed for r in second)


def test_adaptive_expired_ratio_fires_engine_sweep():
    """End-to-end adaptive trigger: traffic landing on expired entries
    feeds the kernel's device-side hit counter through the engine's
    drain (feed_expired_hits) into AdaptivePolicy, whose expired-ratio
    trigger fires a sweep BEFORE the 5 s time trigger could."""
    from throttlecrab_tpu.tpu.cleanup import AdaptivePolicy

    async def main():
        clock = VirtualClock()
        policy = AdaptivePolicy()
        limiter = TpuRateLimiter(capacity=1024)
        metrics = Metrics()
        engine = BatchingEngine(
            limiter, batch_size=128, max_linger_us=500,
            cleanup_policy=policy, now_fn=clock, metrics=metrics,
        )
        # 120 keys with ~1 s TTLs.
        await asyncio.gather(*[
            engine.throttle(req(key=f"e{i}", burst=1, count=1, period=1))
            for i in range(120)
        ])
        assert len(limiter) == 120
        # Expire them all; revisit 60 within the same policy window
        # (+2 s < the 5 s default interval, so only the ratio trigger
        # can fire: >50 hits, 60/120 = 0.5 > 0.25).
        clock.now += 2 * NS
        await asyncio.gather(*[
            engine.throttle(req(key=f"e{i}", burst=1, count=1, period=1))
            for i in range(60)
        ])
        # One more flush so the drained count reaches should_clean
        # (the hit fetch is throttled to 1/s and runs on the executor).
        clock.now += int(1.2 * NS)
        await engine.throttle(req(key="tick"))
        await asyncio.sleep(0.05)  # let the executor sweep land
        return limiter, policy, metrics

    limiter, policy, metrics = run(main())
    # The sweep collected the 60 still-expired entries (the revisited 60
    # were refreshed by their hits, exactly like the reference's
    # set_if_not_exists re-insert) and reset the policy's hit count.
    assert policy._last_total > 0  # after_sweep ran
    assert policy._expired == 0
    assert len(limiter) <= 62  # 120 + tick - 60 swept (y may survive)
    # The drained count is mirrored into /metrics.
    assert metrics.expired_hits == 60
    assert "throttlecrab_tpu_expired_hits 60" in metrics.export_prometheus()


# ------------------------------------------------- drain / deadlines #


def test_begin_drain_sheds_new_resolves_queued():
    """begin_drain() flips lame-duck serving: already-queued requests
    resolve with real decisions, new arrivals shed with OverloadError
    ("server draining" — 503, not a failure), and /health reports
    "draining" so balancers de-route before the listener closes."""
    from throttlecrab_tpu.server.engine import OverloadError

    async def main():
        engine, _ = make_engine(batch_size=64, max_linger_us=10_000_000)
        queued = [
            asyncio.ensure_future(engine.throttle(req(key=f"q{i}")))
            for i in range(3)
        ]
        await asyncio.sleep(0)  # requests land in the pending list
        engine.begin_drain()
        assert engine.health_state() == "draining"
        with pytest.raises(OverloadError, match="draining"):
            await engine.throttle(req(key="late"))
        await engine.drain()
        results = await asyncio.gather(*queued)
        return results, engine.drain_shed

    results, shed = run(main())
    assert all(r.allowed for r in results)
    assert shed == 1


def test_drain_then_shutdown_keeps_shutdown_semantics():
    """drain() is the graceful half; shutdown() after it must still
    pin the abrupt contract: health "shutdown" and ThrottleError (not
    OverloadError) for anything arriving after close."""

    async def main():
        engine, _ = make_engine(batch_size=64, max_linger_us=10_000_000)
        pending = asyncio.ensure_future(engine.throttle(req(key="p")))
        await asyncio.sleep(0)
        await engine.drain()
        result = await pending
        await engine.shutdown()
        assert engine.health_state() == "shutdown"
        with pytest.raises(ThrottleError):
            await engine.throttle(req(key="q"))
        return result

    assert run(main()).allowed


def test_deadline_shed_at_flush_spares_batchmates():
    """A queued request whose client deadline lapses before the flush
    sheds with DeadlineError — before any device dispatch — while its
    batchmates still get real decisions; deadline_default_ms stamps
    requests that carry no explicit deadline."""
    from throttlecrab_tpu.server.engine import DeadlineError

    async def main():
        clock = VirtualClock()
        engine, _ = make_engine(
            clock=clock, batch_size=64, max_linger_us=10_000_000,
            deadline_default_ms=50,
        )
        stale_req = req(key="a")
        stale = asyncio.ensure_future(engine.throttle(stale_req))
        await asyncio.sleep(0)
        # The default was stamped at ingest (absolute, engine clock).
        assert stale_req.deadline_ns == clock.now + 50 * 1_000_000
        clock.now += 100 * 1_000_000  # lapse it in-queue
        fresh_req = req(key="b")
        fresh_req.deadline_ns = clock.now + 1_000_000_000  # still live
        fresh = asyncio.ensure_future(engine.throttle(fresh_req))
        await asyncio.sleep(0)
        await engine.drain()  # flush everything queued
        with pytest.raises(DeadlineError, match="deadline exceeded"):
            await stale
        response = await fresh
        return response, engine.deadline_shed

    response, shed = run(main())
    assert response.allowed
    assert shed == 1
