"""Cleanup policy triggers and adaptation (tpu/cleanup.py)."""

from throttlecrab_tpu.tpu.cleanup import (
    AdaptivePolicy,
    PeriodicPolicy,
    ProbabilisticPolicy,
    make_policy,
)

NS = 1_000_000_000
BASE = 1_753_700_000 * NS


class TestPeriodic:
    def test_fires_on_interval(self):
        p = PeriodicPolicy(interval_ns=10 * NS)
        assert not p.should_clean(BASE, 0, 1000)  # seeds
        assert not p.should_clean(BASE + 9 * NS, 0, 1000)
        assert p.should_clean(BASE + 10 * NS, 0, 1000)
        p.after_sweep(BASE + 10 * NS, 5, 10)
        assert not p.should_clean(BASE + 19 * NS, 0, 1000)
        assert p.should_clean(BASE + 20 * NS, 0, 1000)


class TestProbabilistic:
    def test_fires_per_op_rule_over_ranges(self):
        # probability 10, prime ≡ 1 (mod 10) → fires when ops crosses a
        # multiple of 10.
        p = ProbabilisticPolicy(probability=10)
        p.record_ops(9)
        assert not p.should_clean(BASE, 0, 1000)
        p.record_ops(1)  # ops = 10
        assert p.should_clean(BASE, 0, 1000)
        p.after_sweep(BASE, 0, 0)
        assert not p.should_clean(BASE, 0, 1000)
        p.record_ops(25)  # crosses 20 and 30
        assert p.should_clean(BASE, 0, 1000)

    def test_batch_crossing(self):
        p = ProbabilisticPolicy(probability=1000)
        p.record_ops(999)
        assert not p.should_clean(BASE, 0, 1000)
        p.record_ops(4096)  # crosses 1000
        assert p.should_clean(BASE, 0, 1000)


class TestAdaptive:
    def test_time_trigger_and_doubling(self):
        p = AdaptivePolicy()
        start = p.current_interval_ns
        assert not p.should_clean(BASE, 0, 1 << 20)  # seeds
        t = BASE + start
        assert p.should_clean(t, 0, 1 << 20)
        p.after_sweep(t, 0, 100)  # nothing removed → interval doubles
        assert p.current_interval_ns == start * 2

    def test_halving_on_productive_sweep(self):
        p = AdaptivePolicy()
        p.should_clean(BASE, 0, 1 << 20)
        start = p.current_interval_ns
        p.after_sweep(BASE, 80, 100)  # >50% removed → halves
        assert p.current_interval_ns == max(start // 2, p.min_interval_ns)

    def test_ops_trigger(self):
        p = AdaptivePolicy(max_operations=5000)
        p.should_clean(BASE, 0, 1 << 20)
        p.record_ops(4999)
        assert not p.should_clean(BASE + 1, 0, 1 << 20)
        p.record_ops(1)
        assert p.should_clean(BASE + 1, 0, 1 << 20)

    def test_pressure_trigger(self):
        p = AdaptivePolicy()
        p.should_clean(BASE, 0, 1000)
        assert not p.should_clean(BASE + 1, 750, 1000)
        assert p.should_clean(BASE + 1, 751, 1000)

    def test_interval_clamped(self):
        p = AdaptivePolicy(min_interval_ns=NS, max_interval_ns=8 * NS)
        p.should_clean(BASE, 0, 1 << 20)
        for _ in range(10):
            p.after_sweep(BASE, 0, 0)
        assert p.current_interval_ns == 8 * NS
        for _ in range(10):
            p.after_sweep(BASE, 10, 10)
        assert p.current_interval_ns == NS


def test_factory():
    assert isinstance(make_policy("periodic"), PeriodicPolicy)
    assert isinstance(make_policy("adaptive"), AdaptivePolicy)
    assert isinstance(make_policy("probabilistic"), ProbabilisticPolicy)
    p = make_policy("periodic", cleanup_interval_secs=5)
    assert p.interval_ns == 5 * NS
