"""Cleanup policy triggers and adaptation (tpu/cleanup.py)."""

from throttlecrab_tpu.tpu.cleanup import (
    AdaptivePolicy,
    PeriodicPolicy,
    ProbabilisticPolicy,
    make_policy,
)

NS = 1_000_000_000
BASE = 1_753_700_000 * NS


class TestPeriodic:
    def test_fires_on_interval(self):
        p = PeriodicPolicy(interval_ns=10 * NS)
        assert not p.should_clean(BASE, 0, 1000)  # seeds
        assert not p.should_clean(BASE + 9 * NS, 0, 1000)
        assert p.should_clean(BASE + 10 * NS, 0, 1000)
        p.after_sweep(BASE + 10 * NS, 5, 10)
        assert not p.should_clean(BASE + 19 * NS, 0, 1000)
        assert p.should_clean(BASE + 20 * NS, 0, 1000)


class TestProbabilistic:
    def test_fires_per_op_rule_over_ranges(self):
        # probability 10, prime ≡ 1 (mod 10) → fires when ops crosses a
        # multiple of 10.
        p = ProbabilisticPolicy(probability=10)
        p.record_ops(9)
        assert not p.should_clean(BASE, 0, 1000)
        p.record_ops(1)  # ops = 10
        assert p.should_clean(BASE, 0, 1000)
        p.after_sweep(BASE, 0, 0)
        assert not p.should_clean(BASE, 0, 1000)
        p.record_ops(25)  # crosses 20 and 30
        assert p.should_clean(BASE, 0, 1000)

    def test_batch_crossing(self):
        p = ProbabilisticPolicy(probability=1000)
        p.record_ops(999)
        assert not p.should_clean(BASE, 0, 1000)
        p.record_ops(4096)  # crosses 1000
        assert p.should_clean(BASE, 0, 1000)


class TestAdaptive:
    def test_time_trigger_and_doubling(self):
        p = AdaptivePolicy()
        start = p.current_interval_ns
        assert not p.should_clean(BASE, 0, 1 << 20)  # seeds
        t = BASE + start
        assert p.should_clean(t, 0, 1 << 20)
        p.after_sweep(t, 0, 100)  # nothing removed → interval doubles
        assert p.current_interval_ns == start * 2

    def test_halving_on_productive_sweep(self):
        p = AdaptivePolicy()
        p.should_clean(BASE, 0, 1 << 20)
        start = p.current_interval_ns
        p.after_sweep(BASE, 80, 100)  # >50% removed → halves
        assert p.current_interval_ns == max(start // 2, p.min_interval_ns)

    def test_ops_trigger(self):
        p = AdaptivePolicy(max_operations=5000)
        p.should_clean(BASE, 0, 1 << 20)
        p.record_ops(4999)
        assert not p.should_clean(BASE + 1, 0, 1 << 20)
        p.record_ops(1)
        assert p.should_clean(BASE + 1, 0, 1 << 20)

    def test_pressure_trigger(self):
        p = AdaptivePolicy()
        p.should_clean(BASE, 0, 1000)
        assert not p.should_clean(BASE + 1, 750, 1000)
        assert p.should_clean(BASE + 1, 751, 1000)

    def test_interval_clamped(self):
        p = AdaptivePolicy(min_interval_ns=NS, max_interval_ns=8 * NS)
        p.should_clean(BASE, 0, 1 << 20)
        for _ in range(10):
            p.after_sweep(BASE, 0, 0)
        assert p.current_interval_ns == 8 * NS
        for _ in range(10):
            p.after_sweep(BASE, 10, 10)
        assert p.current_interval_ns == NS


def test_factory():
    assert isinstance(make_policy("periodic"), PeriodicPolicy)
    assert isinstance(make_policy("adaptive"), AdaptivePolicy)
    assert isinstance(make_policy("probabilistic"), ProbabilisticPolicy)
    p = make_policy("periodic", cleanup_interval_secs=5)
    assert p.interval_ns == 5 * NS


class TestAdaptiveExpiredRatio:
    """The expired-ratio trigger with its dynamic threshold, mirroring
    adaptive_cleanup.rs:150-163 (and the scalar oracle's
    core/store/adaptive.py _should_clean)."""

    def _seeded(self):
        p = AdaptivePolicy()
        assert not p.should_clean(BASE, 100, 100_000)  # seeds the clock
        return p

    def test_needs_more_than_50_hits(self):
        p = self._seeded()
        p.record_expired(50)
        # ratio 50/100 = 0.5 > any threshold, but the >50 floor gates it.
        assert not p.should_clean(BASE + NS, 100, 100_000)
        p.record_expired(1)
        assert p.should_clean(BASE + NS, 100, 100_000)

    def test_dynamic_threshold_unproductive_last_sweep(self):
        p = self._seeded()
        # Unproductive history: threshold = 0.2 * 1.25 = 0.25.
        p.after_sweep(BASE, 0, 1000)
        p.record_expired(60)
        assert not p.should_clean(BASE + NS, 300, 100_000)  # 0.2 <= 0.25
        p.record_expired(40)
        assert p.should_clean(BASE + NS, 300, 100_000)  # 0.33 > 0.25

    def test_dynamic_threshold_productive_last_sweep(self):
        p = self._seeded()
        # Productive history (removed > total/4): threshold = 0.1.
        p.after_sweep(BASE, 500, 1000)
        p.record_expired(60)
        assert p.should_clean(BASE + NS, 500, 100_000)  # 0.12 > 0.1

    def test_expired_hits_block_interval_doubling(self):
        # adaptive_cleanup.rs:187: removed == 0 only relaxes the interval
        # when no traffic hit an expired entry since the last sweep.
        p = self._seeded()
        start = p.current_interval_ns
        p.record_expired(10)
        p.after_sweep(BASE, 0, 1000)
        assert p.current_interval_ns == start
        p.after_sweep(BASE, 0, 1000)  # now expired == 0 again
        assert p.current_interval_ns == start * 2
        assert p._expired == 0  # reset on sweep


def test_kernel_expired_hits_ride_the_launch():
    """The device accumulator counts exactly the reference's signal: one
    hit per segment-leading valid request that lands on a REAL stored
    entry past its expiry — never first touches, never refreshed
    entries, never later ranks of the same segment."""
    import numpy as np

    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    NSEC = NS
    lim = TpuRateLimiter(capacity=256)
    t0 = BASE
    # 10 keys with a ~6 s TTL (burst 5, count 10, period 10 s with long
    # tolerance -> expiry = tat - now + tol later; use small period).
    keys = [f"k{i}" for i in range(10)]
    lim.rate_limit_batch(keys, 5, 10, 10, 1, t0)
    assert lim.table.expired_hits() == 0  # first touches are not hits

    # Hit them again while still live: no expired hits.
    lim.rate_limit_batch(keys, 5, 10, 10, 1, t0 + NSEC)
    assert lim.table.expired_hits() == 0

    # Far future: every stored entry is now expired; duplicates in the
    # batch still count ONE hit per key (rank-0 lanes only).
    far = t0 + 10_000 * NSEC
    lim.rate_limit_batch(keys + keys, 5, 10, 10, 1, far)
    assert lim.table.expired_hits() == 10

    # The refreshed entries are live again: no further hits.
    lim.rate_limit_batch(keys, 5, 10, 10, 1, far + NSEC)
    assert lim.table.expired_hits() == 10

    # Denied requests never reach the store's write path, so a DENIED
    # request on an expired entry is NOT a hit (mapstore.py
    # set_if_not_exists only runs for allowed requests; the oracle
    # counts nothing here either).
    far2 = far + 20_000 * NSEC
    lim.rate_limit_batch(keys, 5, 10, 10, 6, far2)  # q=6 > burst: denied
    assert lim.table.expired_hits() == 10


def test_take_expired_hits_throttles_fetch():
    from throttlecrab_tpu.tpu.limiter import TpuRateLimiter

    lim = TpuRateLimiter(capacity=64)
    t0 = BASE
    lim.rate_limit_batch(["a", "b"], 5, 10, 10, 1, t0)
    far = t0 + 10_000 * NS
    lim.rate_limit_batch(["a", "b"], 5, 10, 10, 1, far)
    assert lim.take_expired_hits(far) == 2
    # Second read within the throttle window: no fetch, no double count.
    lim.rate_limit_batch(["a", "b"], 5, 10, 10, 1, far + 20_000 * NS)
    assert lim.take_expired_hits(far + NS // 2) == 0
    # Past the window the delta arrives.
    assert lim.take_expired_hits(far + 2 * NS) == 2


def test_sharded_expired_hits_ride_the_counters():
    import numpy as np

    from conftest import require_devices
    from throttlecrab_tpu.parallel.sharded import (
        ShardedTpuRateLimiter,
        make_mesh,
    )

    require_devices(2)
    lim = ShardedTpuRateLimiter(capacity_per_shard=64, mesh=make_mesh(2))
    keys = [f"k{i}" for i in range(8)]
    t0 = BASE
    lim.rate_limit_batch(keys, 5, 10, 10, 1, t0)
    assert lim.take_expired_hits() == 0
    lim.rate_limit_batch(keys, 5, 10, 10, 1, t0 + 10_000 * NS)
    assert lim.take_expired_hits() == 8
    assert lim.take_expired_hits() == 0  # drained
