"""Shared store test suite, ported from `store/store_test_suite.rs`.

Every case runs against all three stores through the common Store protocol,
like the reference's `test_all_stores!` macro (`store_test_suite.rs:11-18`).
"""

import pytest

from throttlecrab_tpu import (
    AdaptiveStore,
    PeriodicStore,
    ProbabilisticStore,
    RateLimiter,
)
from throttlecrab_tpu.core.i64 import I64_MAX, I64_MIN

NS = 1_000_000_000
# Pure virtual time: stores seed their cleanup schedule lazily from the
# first operation's now_ns, so any base works.
BASE = 1_753_700_000 * NS
TTL = 60 * NS


@pytest.fixture(params=[PeriodicStore, AdaptiveStore, ProbabilisticStore])
def store(request):
    return request.param()


class TestBasicOps:
    def test_get_missing(self, store):
        assert store.get("missing", BASE) is None

    def test_set_and_get(self, store):
        assert store.set_if_not_exists_with_ttl("k", 42, TTL, BASE)
        assert store.get("k", BASE) == 42

    def test_set_if_not_exists_refuses_existing(self, store):
        assert store.set_if_not_exists_with_ttl("k", 1, TTL, BASE)
        assert not store.set_if_not_exists_with_ttl("k", 2, TTL, BASE)
        assert store.get("k", BASE) == 1


class TestCompareAndSwap:
    def test_cas_success(self, store):
        store.set_if_not_exists_with_ttl("k", 10, TTL, BASE)
        assert store.compare_and_swap_with_ttl("k", 10, 20, TTL, BASE)
        assert store.get("k", BASE) == 20

    def test_cas_wrong_old(self, store):
        store.set_if_not_exists_with_ttl("k", 10, TTL, BASE)
        assert not store.compare_and_swap_with_ttl("k", 99, 20, TTL, BASE)
        assert store.get("k", BASE) == 10

    def test_cas_missing_key(self, store):
        assert not store.compare_and_swap_with_ttl("nope", 1, 2, TTL, BASE)

    def test_cas_expired_key(self, store):
        store.set_if_not_exists_with_ttl("k", 10, TTL, BASE)
        later = BASE + TTL  # expiry == now → expired
        assert not store.compare_and_swap_with_ttl("k", 10, 20, TTL, later)

    def test_simulated_concurrent_cas(self, store):
        # Two actors read the same value; only the first CAS wins
        # (store_test_suite.rs:341-376).
        store.set_if_not_exists_with_ttl("shared", 100, TTL, BASE)
        seen = store.get("shared", BASE)
        assert store.compare_and_swap_with_ttl("shared", seen, 200, TTL, BASE)
        assert not store.compare_and_swap_with_ttl("shared", seen, 300, TTL, BASE)
        assert store.get("shared", BASE) == 200


class TestTTL:
    def test_expiry(self, store):
        store.set_if_not_exists_with_ttl("k", 7, TTL, BASE)
        assert store.get("k", BASE + TTL - 1) == 7
        assert store.get("k", BASE + TTL) is None  # expiry > now is strict
        assert store.get("k", BASE + TTL + 1) is None

    def test_one_ms_ttl(self, store):
        ttl = NS // 1000
        store.set_if_not_exists_with_ttl("k", 1, ttl, BASE)
        assert store.get("k", BASE) == 1
        assert store.get("k", BASE + ttl) is None

    def test_zero_ttl(self, store):
        store.set_if_not_exists_with_ttl("k", 1, 0, BASE)
        assert store.get("k", BASE) is None  # expires immediately

    def test_ttl_updated_on_cas(self, store):
        store.set_if_not_exists_with_ttl("k", 1, TTL, BASE)
        mid = BASE + TTL // 2
        assert store.compare_and_swap_with_ttl("k", 1, 2, TTL, mid)
        # Survives past the original expiry because CAS refreshed the TTL.
        assert store.get("k", BASE + TTL + 1) == 2
        assert store.get("k", mid + TTL) is None

    def test_set_over_expired_key(self, store):
        store.set_if_not_exists_with_ttl("k", 1, TTL, BASE)
        later = BASE + TTL + 1
        assert store.set_if_not_exists_with_ttl("k", 2, TTL, later)
        assert store.get("k", later) == 2


class TestValueRanges:
    def test_negative_tat(self, store):
        store.set_if_not_exists_with_ttl("k", -12345, TTL, BASE)
        assert store.get("k", BASE) == -12345
        assert store.compare_and_swap_with_ttl("k", -12345, -99999, TTL, BASE)
        assert store.get("k", BASE) == -99999

    def test_i64_extremes(self, store):
        store.set_if_not_exists_with_ttl("max", I64_MAX, TTL, BASE)
        store.set_if_not_exists_with_ttl("min", I64_MIN, TTL, BASE)
        assert store.get("max", BASE) == I64_MAX
        assert store.get("min", BASE) == I64_MIN
        assert store.compare_and_swap_with_ttl("max", I64_MAX, I64_MIN, TTL, BASE)
        assert store.get("max", BASE) == I64_MIN


class TestKeyShapes:
    def test_empty_key(self, store):
        assert store.set_if_not_exists_with_ttl("", 1, TTL, BASE)
        assert store.get("", BASE) == 1

    def test_long_key(self, store):
        key = "x" * 1000
        assert store.set_if_not_exists_with_ttl(key, 1, TTL, BASE)
        assert store.get(key, BASE) == 1

    def test_unicode_key(self, store):
        key = "пользователь:123:🔑"
        assert store.set_if_not_exists_with_ttl(key, 1, TTL, BASE)
        assert store.get(key, BASE) == 1


class TestStress:
    def test_500_keys(self, store):
        for i in range(500):
            assert store.set_if_not_exists_with_ttl(f"key_{i}", i, TTL, BASE)
        for i in range(500):
            assert store.get(f"key_{i}", BASE) == i
        for i in range(500):
            assert store.compare_and_swap_with_ttl(f"key_{i}", i, i * 2, TTL, BASE)
        for i in range(500):
            assert store.get(f"key_{i}", BASE) == i * 2


class TestFullScenario:
    def test_rate_limit_scenario(self, store):
        # Full GCRA flow through each store (store_test_suite.rs:541-598).
        limiter = RateLimiter(store)
        for i in range(3):
            allowed, result = limiter.rate_limit("user:1", 3, 30, 60, 1, BASE)
            assert allowed, f"request {i + 1}"
            assert result.remaining == 2 - i
        allowed, result = limiter.rate_limit("user:1", 3, 30, 60, 1, BASE)
        assert not allowed

        # 30/60s = one token per 2s.
        allowed, result = limiter.rate_limit("user:1", 3, 30, 60, 1, BASE + 2 * NS)
        assert allowed
        assert result.remaining == 0


class TestCleanup:
    def test_periodic_cleanup_removes_expired(self):
        store = PeriodicStore.builder().cleanup_interval(10).build()
        now = BASE
        for i in range(10):
            store.set_if_not_exists_with_ttl(f"k{i}", i, 5 * NS, now)
        assert len(store) == 10
        # Past the cleanup interval AND the TTLs: a mutating op sweeps.
        later = now + 11 * NS
        store.set_if_not_exists_with_ttl("fresh", 1, 60 * NS, later)
        assert len(store) == 1  # only "fresh" survives
        assert store.expired_count() == 10

    def test_adaptive_cleanup_interval_adapts(self):
        store = (
            AdaptiveStore.builder()
            .capacity(1000)
            .min_interval(1)
            .max_interval(300)
            .build()
        )
        start_interval = store.current_interval_ns
        now = BASE
        # Nothing expired at sweep time → interval doubles.
        store.set_if_not_exists_with_ttl("a", 1, 3600 * NS, now)
        later = now + store.current_interval_ns + NS
        store.set_if_not_exists_with_ttl("b", 2, 3600 * NS, later)
        assert store.current_interval_ns == min(start_interval * 2, 300 * NS)

    def test_adaptive_ops_count_trigger(self):
        store = AdaptiveStore.builder().max_operations(100).build()
        now = BASE
        for i in range(50):
            store.set_if_not_exists_with_ttl(f"k{i}", i, NS // 10, now)
        # All entries' TTLs (0.1s) lapse; op-count trigger fires within the
        # next 100 ops even though the time trigger is far away.
        later = now + NS
        for i in range(100):
            store.set_if_not_exists_with_ttl(f"fresh{i}", i, 3600 * NS, later)
        assert all(store.get(f"k{i}", later) is None for i in range(50))
        assert len(store) <= 100

    def test_adaptive_pressure_trigger_is_transient(self):
        # With >3/4 of capacity live (non-expired), the pressure trigger
        # must not degrade into a sweep per operation: the emulated
        # allocation grows like the reference's Rust HashMap capacity.
        store = AdaptiveStore.builder().capacity(100).build()
        now = BASE
        for i in range(5000):
            store.set_if_not_exists_with_ttl(f"k{i}", i, 3600 * NS, now)
        assert len(store) == 5000
        assert store.capacity * 3 // 4 >= 5000  # pressure trigger disarmed

    def test_probabilistic_cleanup_fires(self):
        store = ProbabilisticStore.builder().cleanup_probability(10).build()
        now = BASE
        for i in range(20):
            store.set_if_not_exists_with_ttl(f"k{i}", i, NS, now)
        later = now + 2 * NS
        # ~1 in 10 mutating ops sweeps; 100 ops guarantees several sweeps.
        for i in range(100):
            store.set_if_not_exists_with_ttl(f"fresh{i}", i, 3600 * NS, later)
        assert len(store) == 100  # the 20 expired entries were swept
