"""The wire fast path (compact i32 whole-second outputs + certified
with_degen compile-out) must be observationally identical to the exact ns
path modulo the documented wire truncation: seconds = ns // 1e9, remaining
saturated at i32::MAX — the reference's own type-boundary truncation
(types.rs:87-97) and proto narrowing (throttlecrab.proto:15-21).
"""

import numpy as np

from conftest import require_devices
import pytest

from throttlecrab_tpu.parallel.sharded import (
    ShardedTpuRateLimiter,
    make_mesh,
)
from throttlecrab_tpu.tpu.limiter import TpuRateLimiter, WireBatchResult

NS = 1_000_000_000
T0 = 1_700_000_000 * NS
I32_MAX = (1 << 31) - 1


def random_batches(rng, n_batches, with_degen):
    """Heterogeneous-parameter batches; degen sprinkles quantity-0 probes
    and burst-1 keys into the traffic."""
    batches = []
    for k in range(n_batches):
        n = int(rng.integers(3, 80))
        keys = [f"k{int(x)}" for x in rng.integers(0, 30, n)]
        burst = rng.integers(1 if with_degen else 2, 20, n).tolist()
        count = rng.integers(1, 1000, n).tolist()
        period = rng.integers(1, 3600, n).tolist()
        quantity = rng.integers(0 if with_degen else 1, 4, n).tolist()
        batches.append(
            (keys, burst, count, period, quantity, T0 + k * 77_000_000)
        )
    return batches


def assert_wire_matches(exact, wire):
    assert isinstance(wire, WireBatchResult)
    assert wire.allowed.tolist() == exact.allowed.tolist()
    assert wire.limit.tolist() == exact.limit.tolist()
    assert wire.status.tolist() == exact.status.tolist()
    want_rem = np.minimum(exact.remaining, I32_MAX)
    assert wire.remaining.tolist() == want_rem.tolist()
    assert wire.reset_after_s.tolist() == (
        np.minimum(exact.reset_after_ns // NS, I32_MAX).tolist()
    )
    assert wire.retry_after_s.tolist() == (
        np.minimum(exact.retry_after_ns // NS, I32_MAX).tolist()
    )


@pytest.mark.parametrize("degen", [False, True])
def test_wire_batch_matches_exact_path(degen):
    """Same traffic through two fresh limiters: wire vs exact must agree
    per request.  Covers both certification outcomes: degen-free traffic
    (with_degen compiled out) and traffic with quantity-0/burst-1."""
    rng = np.random.default_rng(11 if degen else 7)
    batches = random_batches(rng, 6, degen)

    exact = TpuRateLimiter(capacity=256)
    wired = TpuRateLimiter(capacity=256)
    for b in batches:
        e = exact.rate_limit_batch(*b)
        w = wired.rate_limit_batch(*b, wire=True)
        assert_wire_matches(e, w)


@pytest.mark.parametrize("degen", [False, True])
def test_wire_many_matches_exact_path(degen):
    rng = np.random.default_rng(23 if degen else 19)
    batches = random_batches(rng, 5, degen)

    exact = TpuRateLimiter(capacity=256)
    wired = TpuRateLimiter(capacity=256)
    want = [exact.rate_limit_batch(*b) for b in batches]
    got = wired.rate_limit_many(batches, wire=True)
    for e, w in zip(want, got):
        assert_wire_matches(e, w)


def test_wire_param_conflict_fallback_stays_wire():
    """The sequential fallback (param change mid-batch) must still return
    wire-unit results."""
    batches = [
        (["p", "p"], [5, 2], [10, 10], [60, 60], 1, T0),
        (["p"], 2, 10, 60, 1, T0 + 1),
    ]
    exact = TpuRateLimiter(capacity=64)
    want = [exact.rate_limit_batch(*b) for b in batches]
    wired = TpuRateLimiter(capacity=64)
    got = wired.rate_limit_many(batches, wire=True)
    for e, w in zip(want, got):
        assert_wire_matches(e, w)


# ---------------------------------------------------------------- sharded #


def test_sharded_wire_batch_matches_exact():
    require_devices(4)
    rng = np.random.default_rng(31)
    batches = random_batches(rng, 4, True)
    mesh_a = make_mesh(4)
    mesh_b = make_mesh(4)
    exact = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=mesh_a)
    wired = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=mesh_b)
    for b in batches:
        e = exact.rate_limit_batch(*b)
        w = wired.rate_limit_batch(*b, wire=True)
        assert_wire_matches(e, w)
    assert wired.total_allowed == exact.total_allowed
    assert wired.total_denied == exact.total_denied


@pytest.mark.parametrize("wire", [False, True])
def test_sharded_many_matches_sequential(wire):
    """ShardedTpuRateLimiter.rate_limit_many (one mesh launch for K
    sub-batches) == K sequential rate_limit_batch calls, including the
    psum-reduced counters."""
    require_devices(4)
    rng = np.random.default_rng(43)
    batches = random_batches(rng, 6, False)

    seq = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=make_mesh(4))
    want = [seq.rate_limit_batch(*b, wire=wire) for b in batches]
    scan = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=make_mesh(4))
    got = scan.rate_limit_many(batches, wire=wire)

    for k, (w, g) in enumerate(zip(want, got)):
        assert w.allowed.tolist() == g.allowed.tolist(), f"sub-batch {k}"
        assert w.remaining.tolist() == g.remaining.tolist(), f"sub-batch {k}"
        assert w.status.tolist() == g.status.tolist(), f"sub-batch {k}"
        if wire:
            assert w.reset_after_s.tolist() == g.reset_after_s.tolist()
            assert w.retry_after_s.tolist() == g.retry_after_s.tolist()
        else:
            assert w.reset_after_ns.tolist() == g.reset_after_ns.tolist()
            assert w.retry_after_ns.tolist() == g.retry_after_ns.tolist()
    assert scan.total_allowed == seq.total_allowed
    assert scan.total_denied == seq.total_denied


def test_sharded_many_cross_batch_state_carries():
    """Burst 10, 4 sub-batches x 4 hits on one key through the mesh scan:
    exactly 10 allowed in arrival order across the window."""
    require_devices(4)
    batches = [(["hot"] * 4, 10, 100, 3600, 1, T0 + k) for k in range(4)]
    lim = ShardedTpuRateLimiter(capacity_per_shard=64, mesh=make_mesh(4))
    results = lim.rate_limit_many(batches)
    allowed = [bool(a) for r in results for a in r.allowed]
    assert allowed == [True] * 10 + [False] * 6
    assert lim.total_allowed == 10 and lim.total_denied == 6


def test_engine_backlog_drains_through_sharded_scan(monkeypatch):
    """The serving engine's backlog path must take ONE multi-batch mesh
    launch when shards > 1 — the case that used to silently degrade to
    one-batch-per-launch.  The engine enters through dispatch_many (the
    double-buffered flush loop)."""
    require_devices(4)
    import asyncio

    from throttlecrab_tpu.server.engine import BatchingEngine
    from throttlecrab_tpu.server.types import ThrottleRequest

    limiter = ShardedTpuRateLimiter(
        capacity_per_shard=1024, mesh=make_mesh(4)
    )
    many_calls = []
    orig = limiter.dispatch_many

    def spy(batches, **kw):
        many_calls.append(len(batches))
        return orig(batches, **kw)

    monkeypatch.setattr(limiter, "dispatch_many", spy)

    async def main():
        engine = BatchingEngine(
            limiter, batch_size=32, max_linger_us=100_000,
            now_fn=lambda: T0,
        )
        return await asyncio.gather(
            *[
                engine.throttle(
                    ThrottleRequest(f"w{i % 40}", 50, 100, 3600, 1)
                )
                for i in range(300)
            ]
        )

    results = asyncio.run(main())
    assert all(r.allowed for r in results)
    assert many_calls and max(many_calls) > 1  # scan path engaged


def test_sharded_many_param_conflict_falls_back():
    require_devices(2)
    batches = [
        (["p", "p"], [5, 2], [10, 10], [60, 60], 1, T0),
        (["p"], 2, 10, 60, 1, T0 + 1),
    ]
    seq = ShardedTpuRateLimiter(capacity_per_shard=64, mesh=make_mesh(2))
    want = [seq.rate_limit_batch(*b) for b in batches]
    scan = ShardedTpuRateLimiter(capacity_per_shard=64, mesh=make_mesh(2))
    got = scan.rate_limit_many(batches)
    for w, g in zip(want, got):
        assert w.allowed.tolist() == g.allowed.tolist()
        assert w.remaining.tolist() == g.remaining.tolist()
        assert w.reset_after_ns.tolist() == g.reset_after_ns.tolist()
        assert w.retry_after_ns.tolist() == g.retry_after_ns.tolist()


def test_cur_mode_active_on_certified_traffic():
    """dispatch_many picks the 8 B/request "cur" device output for
    certified wire traffic and the results still match the exact path."""
    lim = TpuRateLimiter(capacity=256)
    handle = lim.dispatch_many(
        [(["a", "b", "a"], 10, 100, 60, 1, T0)], wire=True
    )
    assert getattr(handle, "_w32", False) or getattr(handle, "_cur", False), (
        "certified wire window should take a compact output tier"
    )
    res = handle.fetch()[0]
    assert isinstance(res, WireBatchResult)
    assert res.allowed.all() and res.limit[0] == 10

    lim2 = TpuRateLimiter(capacity=256)
    ref = lim2.rate_limit_batch(["a", "b", "a"], 10, 100, 60, 1, T0)
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_ns // NS)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_ns // NS)


def test_cur_mode_falls_back_on_big_tolerance():
    """tol >= 2^61 (fits_cur_wire fails) must fall back to the 4-plane
    compact output — same wire values, no overflow of the cur word."""
    from throttlecrab_tpu.tpu.limiter import derive_params, has_degenerate

    lim = TpuRateLimiter(capacity=256)
    # Non-degenerate but tol = em*(burst-1) = 3e18 >= 2^61: this batch
    # is exactly the case the fits_cur_wire guard exists for — it must
    # NOT be rejected by the degeneracy certificate (or this test would
    # pass without exercising the guard at all).
    big = (3_000_000_000, 1, 1, 1)  # burst, count, period(s), qty
    em, tol, invalid = derive_params(
        np.array([big[0]], np.int64), np.array([big[1]], np.int64),
        np.array([big[2]], np.int64),
    )
    assert not invalid[0] and tol[0] >= (1 << 61)
    assert not has_degenerate(
        np.array([True]), em, tol, np.array([big[3]], np.int64)
    )
    handle = lim.dispatch_many(
        [(["k"], big[0], big[1], big[2], big[3], T0)], wire=True
    )
    assert not getattr(handle, "_cur", True)
    res = handle.fetch()[0]
    assert bool(res.allowed[0])
    ref = TpuRateLimiter(capacity=256).rate_limit_batch(
        ["k"], big[0], big[1], big[2], big[3], T0, wire=True
    )
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)


def _fill_bucket_past_cur_bound(lim, key, t0):
    """Store a TAT >= 2^62 for `key`: tol ~3e18 >= 2^61 (4-plane path)
    and quantity big enough that the allowed write lands near now + tol.
    Returns the stored-state poisoning launch's params."""
    big = (3_000_000_000, 1, 1, 3_000_000_000)  # burst, count, period, qty
    res = lim.rate_limit_batch([key], *big, t0, wire=True)
    assert bool(res.allowed[0])  # the poisoning write actually happened
    return big


def test_cur_mode_respects_stored_state_across_launches():
    """Cross-launch half of the cur certificate (ADVICE r4): a prior
    big-tolerance launch persists a TAT >= 2^62; a later normal-tolerance
    launch on the same key must NOT take the cur path (its `cur*2+allowed`
    word would wrap and finish_cur would report retry_after 0 / huge
    remaining for denied lanes).  Twin limiter runs the same traffic
    entirely on the exact 4-plane path."""
    lim = TpuRateLimiter(capacity=256)
    twin = TpuRateLimiter(capacity=256)
    big = _fill_bucket_past_cur_bound(lim, "k", T0)
    twin.rate_limit_batch(["k"], *big, T0, wire=True)
    assert lim.table.cur_safe is False

    t1 = T0 + NS
    handle = lim.dispatch_many(
        [(["k", "other", "k"], 10, 100, 60, 1, t1)], wire=True
    )
    assert not getattr(handle, "_cur", True), (
        "poisoned stored state must disable the cur wire mode"
    )
    res = handle.fetch()[0]
    ref = twin.rate_limit_batch(
        ["k", "other", "k"], 10, 100, 60, 1, t1, wire=True
    )
    assert not bool(res.allowed[0])  # bucket full for ~95 years
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_s)
    # The denied lanes' oracle values are the saturated ones the wrapped
    # cur word would have corrupted (retry 0 / remaining up to i32max).
    assert ref.retry_after_s[0] == I32_MAX


def test_cur_mode_recovers_on_fresh_table_only():
    """cur_safe is sticky: certified traffic after the poisoning launch
    stays on the 4-plane path (the big TAT never expires), while a fresh
    limiter takes cur for identical traffic."""
    lim = TpuRateLimiter(capacity=256)
    _fill_bucket_past_cur_bound(lim, "k", T0)
    h = lim.dispatch_many([(["a", "b"], 10, 100, 60, 1, T0 + NS)], wire=True)
    assert not getattr(h, "_cur", True)
    h.fetch()

    fresh = TpuRateLimiter(capacity=256)
    h2 = fresh.dispatch_many(
        [(["a", "b"], 10, 100, 60, 1, T0 + NS)], wire=True
    )
    assert getattr(h2, "_w32", False) or getattr(h2, "_cur", False)
    h2.fetch()


def test_invalid_or_degen_lanes_do_not_poison_cur_safe():
    """Only a VALID lane with tol >= 2^61 can store a TAT >= 2^62 —
    rejected requests never write (their u32-wrapped garbage tolerance
    is meaningless), and quantity-0/emission-0 degens obey the same
    write bound — so neither may clear the sticky cur_safe flag or
    forfeit cur mode for later certified traffic."""
    lim = TpuRateLimiter(capacity=256)
    # burst=0 lane is rejected (status!=0) with wrapped tol ~4.3e18.
    r = lim.rate_limit_batch(
        ["a", "bad", "b"], [10, 0, 10], [100, 1, 100], [60, 1, 60], 1,
        T0, wire=True,
    )
    assert r.status[1] != 0 and r.allowed[0] and r.allowed[2]
    assert lim.table.cur_safe is True

    # Valid quantity-0 probe (degenerate, writes nothing beyond bound).
    lim.rate_limit_batch(["a"], 10, 100, 60, 0, T0, wire=True)
    assert lim.table.cur_safe is True

    # Certified traffic afterwards still takes a compact tier.
    h = lim.dispatch_many([(["a", "b"], 10, 100, 60, 1, T0 + NS)], wire=True)
    assert getattr(h, "_w32", False) or getattr(h, "_cur", False)
    h.fetch()

    # And a window CONTAINING a rejected lane still uses cur itself
    # (invalid lanes are don't-care in the wire output).
    h2 = lim.dispatch_many(
        [(["a", "bad2"], [10, 0], [100, 1], [60, 1], 1, T0 + 2 * NS)],
        wire=True,
    )
    assert getattr(h2, "_w32", False) or getattr(h2, "_cur", False)
    res = h2.fetch()[0]
    assert res.status[1] != 0
    assert lim.table.cur_safe is True


def test_sharded_cur_mode_respects_stored_state():
    """Same cross-launch guard on the mesh: the sharded table's cur_safe
    drops after a big-tolerance launch and dispatch_many stays on the
    4-plane path with oracle-equal wire values."""
    require_devices(2)
    mesh = make_mesh(2)
    lim = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=mesh)
    seq = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=mesh)
    big = (3_000_000_000, 1, 1, 3_000_000_000)
    r = lim.rate_limit_batch(["k"], *big, T0, wire=True)
    assert bool(r.allowed[0])
    seq.rate_limit_batch(["k"], *big, T0, wire=True)
    assert lim.table.cur_safe is False

    t1 = T0 + NS
    handle = lim.dispatch_many(
        [(["k", "other"], 10, 100, 60, 1, t1)], wire=True
    )
    res = handle.fetch()[0]
    ref = seq.rate_limit_batch(["k", "other"], 10, 100, 60, 1, t1, wire=True)
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_s)


def test_native_wire_window_cur_matches_python_path():
    """dispatch_wire_window (native prep + cur mode) returns the same
    wire values as rate_limit_batch for identical certified traffic."""
    from throttlecrab_tpu.native import toolchain_available

    if not toolchain_available():
        import pytest

        pytest.skip("no C++ toolchain")
    lim = TpuRateLimiter(capacity=256, keymap="native")
    keys = [b"x", b"y", b"x", b"z"]
    blob = b"".join(keys)
    offsets = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
    params = np.array(
        [[5, 100, 60, 1]] * 4, np.int64
    )  # burst, count, period, qty
    handle = lim.dispatch_wire_window([(blob, offsets, params)], T0)
    assert handle is not None
    res = handle.fetch()[0]

    lim2 = TpuRateLimiter(capacity=256)
    ref = lim2.rate_limit_batch(
        ["x", "y", "x", "z"], 5, 100, 60, 1, T0, wire=True
    )
    np.testing.assert_array_equal(res.allowed, ref.allowed)
    np.testing.assert_array_equal(res.remaining, ref.remaining)
    np.testing.assert_array_equal(res.reset_after_s, ref.reset_after_s)
    np.testing.assert_array_equal(res.retry_after_s, ref.retry_after_s)


@pytest.mark.parametrize("seed", range(2000, 2008))
def test_wire_tier_selection_differential_fuzz(seed):
    """Random wire traffic through dispatch_many: whatever output tier
    the dispatcher picks per window (w32 / cur / 4-plane, including
    tol_hwm crossings from occasional big-tolerance keys and degen
    probes) must produce the 4-plane twin's exact wire values."""
    rng = np.random.default_rng(seed)
    lim = TpuRateLimiter(capacity=512)
    twin = TpuRateLimiter(capacity=512)
    pool = [f"f{seed}k{i}" for i in range(10)]
    params = {}
    for k in pool:
        r = rng.random()
        if r < 0.15:  # big tolerance: forfeits w32, bumps tol_hwm
            params[k] = (int(rng.integers(2500, 10_000)), 60, 60)
        elif r < 0.25:  # degen probe material (quantity drawn 0 below)
            params[k] = (1, 1, 1)
        else:
            params[k] = (
                int(rng.integers(2, 200)),
                int(rng.integers(1, 1000)),
                int(rng.integers(1, 600)),
            )
    tiers = set()
    now = T0
    for step in range(8):
        n = int(rng.integers(2, 24))
        keys = [pool[rng.integers(len(pool))] for _ in range(n)]
        b = [params[k][0] for k in keys]
        c = [params[k][1] for k in keys]
        p = [params[k][2] for k in keys]
        q = [
            0 if (params[k][0] == 1 and rng.random() < 0.5) else 1
            for k in keys
        ]
        batch = (keys, b, c, p, q, now)
        h = lim.dispatch_many([batch], wire=True)
        tiers.add(
            "w32" if getattr(h, "_w32", False)
            else ("cur" if getattr(h, "_cur", False) else "planes")
        )
        res = h.fetch()[0]
        ref = twin.rate_limit_batch(*batch, wire=True)
        ctx = f"seed{seed} step{step}"
        np.testing.assert_array_equal(res.allowed, ref.allowed, ctx)
        np.testing.assert_array_equal(res.remaining, ref.remaining, ctx)
        np.testing.assert_array_equal(
            res.reset_after_s, ref.reset_after_s, ctx
        )
        np.testing.assert_array_equal(
            res.retry_after_s, ref.retry_after_s, ctx
        )
        np.testing.assert_array_equal(res.status, ref.status, ctx)
        now += int(rng.integers(0, 2 * NS))
    assert tiers  # at least one window decided (tier mix varies by seed)


def test_sharded_cur_and_w32_tiers_active():
    """Certified wire traffic through the sharded dispatcher takes the
    w32 tier (and values match the sequential per-batch twin); traffic
    past the w32 bounds but inside cur's falls back one rung."""
    require_devices(2)
    mesh = make_mesh(2)
    lim = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=mesh)
    seq = ShardedTpuRateLimiter(capacity_per_shard=128, mesh=make_mesh(2))

    batches = [
        ([f"s{i}" for i in range(12)], 10, 100, 60, 1, T0),
        ([f"s{i}" for i in range(6)] * 2, 10, 100, 60, 1, T0 + NS),
    ]
    h = lim.dispatch_many(batches, wire=True)
    assert getattr(h, "_w32", False)
    got = h.fetch()
    want = [seq.rate_limit_batch(*b, wire=True) for b in batches]
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.allowed, w.allowed)
        np.testing.assert_array_equal(g.remaining, w.remaining)
        np.testing.assert_array_equal(g.reset_after_s, w.reset_after_s)
        np.testing.assert_array_equal(g.retry_after_s, w.retry_after_s)

    # tol ~2999 s: past w32's reset field, inside cur's 2^61 bound.
    big = [(["t"], 3000, 60, 60, 1, T0 + 2 * NS)]
    h2 = lim.dispatch_many(big, wire=True)
    assert not getattr(h2, "_w32", True)
    assert h2._now_list is not None  # the cur tier took it
    got2 = h2.fetch()[0]
    want2 = seq.rate_limit_batch(*big[0], wire=True)
    np.testing.assert_array_equal(got2.remaining, want2.remaining)
    np.testing.assert_array_equal(got2.reset_after_s, want2.reset_after_s)
