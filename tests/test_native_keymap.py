"""C++ keymap vs the Python keymap: identical resolution + segment info."""

import numpy as np
import pytest

from throttlecrab_tpu.native import (
    NativeKeyMap,
    keymap_build_error,
    native_available,
    toolchain_available,
)
from throttlecrab_tpu.tpu.limiter import segment_info

if not native_available() and toolchain_available():
    pytest.fail(
        "C++ keymap failed to build with g++ present:\n"
        f"{keymap_build_error()}",
        pytrace=False,
    )
pytestmark = pytest.mark.skipif(
    not native_available(),
    reason=f"native keymap toolchain unavailable: {keymap_build_error()}",
)


def test_basic_resolution():
    km = NativeKeyMap(64)
    keys = [b"alpha", b"beta", b"alpha", b"gamma", b"beta", b"alpha"]
    valid = np.ones(len(keys), bool)
    slots, rank, is_last, n_full = km.resolve(keys, valid)
    assert n_full == 0
    assert len(km) == 3
    # Same key → same slot; different keys → different slots.
    assert slots[0] == slots[2] == slots[5]
    assert slots[1] == slots[4]
    assert len({slots[0], slots[1], slots[3]}) == 3
    # Segment info: ranks count occurrences, is_last marks finals.
    assert rank.tolist() == [0, 0, 1, 0, 1, 2]
    assert is_last.tolist() == [False, False, False, True, True, True]


def test_matches_python_segment_info():
    rng = np.random.RandomState(3)
    km = NativeKeyMap(128)
    for trial in range(5):
        n = int(rng.randint(1, 40))
        keys = [f"k{rng.randint(10)}".encode() for _ in range(n)]
        valid = rng.rand(n) > 0.2
        slots, rank, is_last, _ = km.resolve(keys, valid)
        rank2, is_last2 = segment_info(slots, valid)
        np.testing.assert_array_equal(rank, rank2, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(
            is_last, is_last2, err_msg=f"trial {trial}"
        )
        assert (slots[~valid] == -1).all()


def test_full_then_grow():
    km = NativeKeyMap(4)
    keys = [f"x{i}".encode() for i in range(8)]
    valid = np.ones(8, bool)
    slots, _, _, n_full = km.resolve(keys, valid)
    assert n_full == 4
    assert (slots >= 0).sum() == 4
    km.grow(16)
    missing = slots == -1
    slots2, _, _, n_full2 = km.resolve(keys, missing)
    assert n_full2 == 0
    merged = np.where(missing, slots2, slots)
    assert (merged >= 0).all()
    assert len(set(merged.tolist())) == 8
    assert len(km) == 8


def test_free_and_recycle():
    km = NativeKeyMap(16)
    keys = [f"k{i}".encode() for i in range(10)]
    valid = np.ones(10, bool)
    slots, _, _, _ = km.resolve(keys, valid)
    freed = km.free_slots(slots[:5])
    assert freed == 5
    assert len(km) == 5
    # Freed keys are re-insertable; surviving keys keep their slots.
    slots2, _, _, _ = km.resolve(keys, valid)
    assert (slots2[5:] == slots[5:]).all()
    assert len(km) == 10
    # Double free is a no-op.
    assert km.free_slots(slots[:5]) in range(0, 6)


def test_unicode_and_long_keys():
    km = NativeKeyMap(16)
    keys = ["пользователь:🔑".encode(), b"x" * 1000, b""]
    valid = np.ones(3, bool)
    slots, rank, is_last, n_full = km.resolve(keys, valid)
    assert n_full == 0
    assert len(set(slots.tolist())) == 3
    slots2, _, _, _ = km.resolve(keys, valid)
    assert (slots == slots2).all()


def test_churn_against_python_reference():
    rng = np.random.RandomState(11)
    km = NativeKeyMap(32)
    pydict: dict = {}
    for step in range(30):
        n = int(rng.randint(1, 20))
        keys = [f"c{rng.randint(30)}".encode() for _ in range(n)]
        valid = np.ones(n, bool)
        slots, _, _, n_full = km.resolve(keys, valid)
        assert n_full == 0
        for k, s in zip(keys, slots):
            if k in pydict:
                assert pydict[k] == s, f"slot moved for {k!r} at step {step}"
            else:
                pydict[k] = s
        if step % 7 == 6:
            drop = [k for i, k in enumerate(pydict) if i % 3 == 0]
            km.free_slots(np.array([pydict[k] for k in drop], np.int32))
            for k in drop:
                del pydict[k]
        assert len(km) == len(pydict)


def test_grow_keeps_probe_invariant():
    """Regression: grow_slots must keep nbuckets >= 2x capacity.  The old
    rehash sizing left nbuckets == capacity after a grow, so a full table
    spun forever on the next miss probe instead of reporting full."""
    from throttlecrab_tpu.native import NativeKeyMap

    km = NativeKeyMap(64)
    km.grow(128)
    keys = [b"g:%d" % i for i in range(129)]
    valid = np.ones(len(keys), bool)
    slots, _, _, n_full = km.resolve(keys, valid)
    assert n_full == 1  # 129 keys into 128 slots: one reported full
    assert (slots >= 0).sum() == 128
