"""Pallas row gather/scatter vs plain indexing (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from throttlecrab_tpu.tpu import pallas_ops


@pytest.mark.parametrize("B", [64, 512, 4096])
def test_row_gather_matches_indexing(B):
    rng = np.random.default_rng(1)
    N = 8192
    table = jnp.asarray(rng.integers(-(2**31), 2**31 - 1, (N, 4)), jnp.int32)
    idx = rng.integers(0, N, B).astype(np.int32)
    got = np.asarray(pallas_ops.row_gather(table, jnp.asarray(idx)))
    np.testing.assert_array_equal(got, np.asarray(table)[idx])


@pytest.mark.parametrize("B", [64, 512])
def test_row_scatter_matches_at_set(B):
    rng = np.random.default_rng(2)
    N = 8192
    base = rng.integers(-(2**31), 2**31 - 1, (N, 4)).astype(np.int32)
    # Unique target rows, as the kernel guarantees (scratch redirection).
    idx = rng.choice(N, B, replace=False).astype(np.int32)
    rows = rng.integers(-(2**31), 2**31 - 1, (B, 4)).astype(np.int32)

    expect = base.copy()
    expect[idx] = rows

    got = np.asarray(
        pallas_ops.row_scatter(
            jnp.asarray(base), jnp.asarray(idx), jnp.asarray(rows)
        )
    )
    np.testing.assert_array_equal(got, expect)


def test_gather_scatter_roundtrip():
    rng = np.random.default_rng(3)
    N, B = 4096, 256
    table = jnp.asarray(rng.integers(0, 1000, (N, 4)), jnp.int32)
    idx = jnp.asarray(rng.choice(N, B, replace=False).astype(np.int32))
    rows = pallas_ops.row_gather(table, idx)
    table2 = pallas_ops.row_scatter(table, idx, rows + 7)
    got = np.asarray(pallas_ops.row_gather(table2, idx))
    np.testing.assert_array_equal(got, np.asarray(rows) + 7)


def _equiv_workload():
    """One workload, built once; both runs load it from disk."""
    NS = 1_000_000_000
    BASE = 1_753_700_000 * NS
    K, B = 2, 64
    rng = np.random.default_rng(11)
    slots = rng.integers(0, 48, (K, B)).astype(np.int32)
    rank = np.zeros((K, B), np.int32)
    is_last = np.ones((K, B), bool)
    for k in range(K):
        seen: dict = {}
        for i in range(B):
            sl = int(slots[k, i])
            if sl in seen:
                rank[k, i] = seen[sl][0]
                seen[sl][0] += 1
                is_last[k, seen[sl][1]] = False
                seen[sl][1] = i
            else:
                seen[sl] = [1, i]
    em = np.full((K, B), 600_000_000, np.int64)
    now = BASE + np.arange(K, dtype=np.int64) * 50_000_000
    return slots, rank, is_last, em, now


# Shared by the in-process (flag off) and subprocess (flag on) runs.
_EQUIV_RUNNER = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import throttlecrab_tpu  # enables x64
from throttlecrab_tpu.tpu.kernel import (
    EMPTY_EXPIRY, gcra_scan_packed, pack_requests, pack_state, unpack_state,
)

tmp, tag = sys.argv[1], sys.argv[2]
from throttlecrab_tpu.tpu import pallas_ops
assert pallas_ops.enabled() == (tag == "pallas")
d = np.load(f"{tmp}/equiv_in.npz")
slots, rank, is_last, em, now = (
    d["slots"], d["rank"], d["is_last"], d["em"], d["now"]
)
K, B = slots.shape
packed = pack_requests(
    slots, rank, is_last, em, em * 4,
    np.ones((K, B), np.int64), np.ones((K, B), bool),
)
state = pack_state(
    jnp.zeros((512,), jnp.int64), jnp.full((512,), EMPTY_EXPIRY, jnp.int64)
)
st, out = gcra_scan_packed(state, jnp.asarray(packed), jnp.asarray(now))
tat, exp = (np.asarray(x) for x in unpack_state(st))
np.savez(f"{tmp}/equiv_{tag}.npz", out=np.asarray(out), tat=tat, exp=exp)
print("OK")
"""


def test_packed_scan_equivalent_with_pallas_rows(tmp_path):
    """gcra_scan_packed with THROTTLECRAB_PALLAS=1 (interpret mode on
    CPU) must decide identically to the XLA gather/scatter path.  Both
    runs happen in subprocesses (the flag is frozen at first trace) over
    the identical saved workload."""
    import os
    import subprocess
    import sys

    slots, rank, is_last, em, now = _equiv_workload()
    np.savez(
        tmp_path / "equiv_in.npz",
        slots=slots, rank=rank, is_last=is_last, em=em, now=now,
    )

    for tag, flag in (("plain", "0"), ("pallas", "1")):
        env = dict(os.environ)
        env["THROTTLECRAB_PALLAS"] = flag
        r = subprocess.run(
            [sys.executable, "-c", _EQUIV_RUNNER, str(tmp_path), tag],
            env=env, capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, f"{tag}: {r.stderr[-3000:]}"

    a = np.load(tmp_path / "equiv_plain.npz")
    b = np.load(tmp_path / "equiv_pallas.npz")
    for field in ("out", "tat", "exp"):
        np.testing.assert_array_equal(a[field], b[field], err_msg=field)
