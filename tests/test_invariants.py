"""Tier-1 tests for the static invariant suite (throttlecrab_tpu/analysis).

Three layers:

  * the real tree is clean — zero unwaived findings, zero stale
    waivers, well under the 30 s budget, and the CLI runs strict on a
    bare interpreter without importing jax;
  * per-checker synthetic fixtures — known-bad snippets are flagged
    with the right code and line, and the sanctioned patterns
    (saturating helpers, 2**61 guards, plain-int coercions, pragmas,
    static_argnames, shape-based control flow) pass;
  * the round-5 regression — stripping the big-tolerance refusal from
    ``fits_w32_wire`` (the ADVICE round-5 high finding) must produce a
    finding again;
  * the wave-3 protocol-surface family (wire / harden / status /
    fault / ktwin) — real anchor files are copied into a temp tree and
    mutated (an OP_* with no decoder, a decoder without the
    trailing-bytes check, a STATUS_* absent from one transport, a
    fault site with no hook, a flipped saturation predicate), and each
    rule must fire with the right code and symbol.
"""

from __future__ import annotations

import json
import re
import shutil
import subprocess
import sys
import textwrap
import time
from pathlib import Path

from throttlecrab_tpu.analysis import (
    CHECKER_CODES,
    CHECKERS,
    DEFAULT_BASELINE,
    apply_baseline,
    load_baseline,
    run_all,
    run_timed,
)
from throttlecrab_tpu.analysis import (
    fault_surface,
    i64_hygiene,
    jit_boundary,
    kernel_twins,
    registry,
    status_surface,
    twin_drift,
    wire_surface,
)
from throttlecrab_tpu.analysis.common import parse_baseline

REPO = Path(__file__).resolve().parent.parent
KERNEL_REL = "throttlecrab_tpu/tpu/kernel.py"


def _write(root: Path, rel: str, source: str) -> None:
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))


# ------------------------------------------------------------------ #
# The real tree


class TestRepoClean:
    def test_zero_unwaived_findings_and_fast(self):
        t0 = time.monotonic()
        findings = run_all(REPO)
        waivers = load_baseline(DEFAULT_BASELINE)
        unwaived, stale = apply_baseline(findings, waivers)
        elapsed = time.monotonic() - t0
        assert unwaived == [], "\n".join(f.format() for f in unwaived)
        assert stale == [], f"stale baseline waivers: {stale}"
        assert elapsed < 30.0, f"suite took {elapsed:.1f}s (budget 30s)"

    def test_baseline_waivers_all_used(self):
        """Every baseline entry must match >= 1 live finding (ratchet:
        audited exceptions that no longer exist must be deleted)."""
        findings = run_all(REPO)
        for w in load_baseline(DEFAULT_BASELINE):
            assert any(w.matches(f) for f in findings), (
                f"stale waiver: {w.code} {w.path} {w.symbol or w.line}"
            )

    def test_cli_strict_runs_without_jax(self):
        """The CLI must exit 0 in strict mode and must never import
        jax — the CI invariants job runs it with no jax installed."""
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_invariants.py"),
                "--strict",
                "--json",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["findings"] == []
        assert report["stale_waivers"] == []
        assert report["jax_imported"] is False
        assert report["elapsed_s"] < 30.0


# ------------------------------------------------------------------ #
# i64 hygiene fixtures


class TestI64Hygiene:
    def test_raw_op_flagged_with_code_and_line(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def advance(tat, tol):
                new_tat = tat + tol
                return new_tat
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        f = findings[0]
        assert f.code == "i64-raw-op"
        assert f.path == KERNEL_REL
        assert f.line == 2
        assert f.symbol == "advance"

    def test_augmented_assign_flagged(self, tmp_path):
        """`tat += tol` is the same wrap class with no BinOp node."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def advance(tat, tol):
                tat += tol
                return tat
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert findings[0].code == "i64-raw-op"
        assert findings[0].line == 2

    def test_guarded_augmented_assign_passes(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def advance(tat, tol):
                if tat >= (1 << 61) or tol >= (1 << 61):
                    return None
                tat += tol
                return tat
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_saturating_helper_passes(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            from .sat import sat_add

            def advance(tat, tol):
                return sat_add(tat, tol)
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_dominating_guard_passes(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol):
                if tat >= (1 << 61) or tol >= (1 << 61):
                    return None
                return tat + tol
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_guard_on_wrong_identifier_still_flags(self, tmp_path):
        """A 2**61 guard on one name must not license arithmetic on
        another — the precise shape of the round-5 bug."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol, hwm):
                if hwm >= (1 << 61):
                    return None
                return tol + hwm
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert "tol" in findings[0].message
        assert findings[0].line == 4

    def test_telemetry_comparison_is_not_a_guard(self, tmp_path):
        """A 2**61 comparison whose result is never acted on must not
        license later arithmetic — only a refusing guard dominates."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol):
                big = (tat >= (1 << 61)) or (tol >= (1 << 61))
                log(big)
                return tat + tol
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert findings[0].line == 4

    def test_clamp_without_refusal_is_not_a_guard(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol):
                if tol >= (1 << 61):
                    tol = 0
                return tat + tol
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert "tat" in findings[0].message

    def test_overflow_branch_is_not_licensed(self, tmp_path):
        """In `if tol >= 2**61: <body>` the body is the OVERFLOW side;
        raw arithmetic there is wrap-guaranteed and must flag even
        though the branch refuses."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol):
                if tol >= (1 << 61) or tat >= (1 << 61):
                    return tat + tol
                return 0
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert findings[0].line == 3

    def test_guard_inside_conditional_branch_does_not_leak(self, tmp_path):
        """A refusal guard that only runs when `flag` is true must not
        license arithmetic on the unconditional path."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol, flag):
                if flag:
                    if tol >= (1 << 61) or tat >= (1 << 61):
                        return None
                return tat + tol
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert findings[0].line == 5

    def test_reassignment_kills_guard_license(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol):
                if tol >= (1 << 61) or tat >= (1 << 61):
                    return None
                tol = load_foreign()
                return tat + tol
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        # tat kept its license (never reassigned); only tol lost it.
        assert "value(s) tol without" in findings[0].message

    def test_branch_reassignment_survives_branch_exit(self, tmp_path):
        """A license revoked by an in-branch reassignment must stay
        revoked after the branch — restore intersects, never resurrects."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol, flag, x):
                if tol >= (1 << 61):
                    return None
                if flag:
                    tol = x
                return tol + 1
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert [f.line for f in findings] == [6]

    def test_branch_coercion_does_not_leak(self, tmp_path):
        """int() on one branch must not mark the name safe on the
        other path."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tol, cheap, x):
                if cheap:
                    tol = int(x)
                return tol + 1
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert [f.line for f in findings] == [4]

    def test_np_all_guard_bounds_nothing(self, tmp_path):
        """np.all(x >= bound) false means only SOME lane is below —
        unlike np.any, it must not license the false branch."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            import numpy as np

            def bound(tol, x):
                if np.all(tol >= (1 << 61)):
                    raise ValueError()
                return tol + x
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert [f.line for f in findings] == [6]

    def test_np_any_refusal_licenses(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            import numpy as np

            def bound(tol, x):
                if np.any(tol >= (1 << 61)):
                    raise ValueError()
                return tol + x
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_tuple_rebind_revokes_safety(self, tmp_path):
        """A tuple-unpack rebinding a previously-coerced name must
        revoke its plain-Python-safe status."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(x, pairs):
                tol = int(x)
                tat, tol = pairs
                return tat + tol
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert [f.line for f in findings] == [4]

    def test_for_target_rebind_revokes_safety(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(x, tols):
                tol = int(x)
                out = 0
                for tol in tols:
                    out = tol + 1
                return out
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert [f.line for f in findings] == [5]

    def test_match_case_bodies_are_scanned(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol, kind):
                match kind:
                    case 0:
                        return tat + tol
                    case _:
                        return 0
            """,
        )
        findings = i64_hygiene.check(tmp_path)
        assert len(findings) == 1
        assert findings[0].line == 4

    def test_bounded_branch_is_licensed(self, tmp_path):
        """Inside `if x < bound:` one branch IS the bounded side; the
        compare licenses the branch bodies even without a refusal."""
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(tat, tol):
                out = 0
                if tat < (1 << 61) and tol < (1 << 61):
                    out = tat + tol
                return out
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_plain_python_int_math_passes(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def bound(max_tol, tol_hwm):
                hwm = int(tol_hwm)
                hwm = max(hwm, int(max_tol))
                return int(max_tol) + hwm
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_pragma_passes(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def wrap(now, tol):
                return now + tol  # inv: allow(i64-raw-op)
            """,
        )
        assert i64_hygiene.check(tmp_path) == []

    def test_insensitive_names_not_flagged(self, tmp_path):
        _write(
            tmp_path,
            KERNEL_REL,
            """\
            def pad(n, width):
                return n + width * 2
            """,
        )
        assert i64_hygiene.check(tmp_path) == []


# ------------------------------------------------------------------ #
# Twin drift fixtures


def _twin_tree(tmp_path: Path) -> Path:
    """A minimal tree with the real twin anchors copied in."""
    for rel in (
        KERNEL_REL,
        "throttlecrab_tpu/tpu/limiter.py",
        "throttlecrab_tpu/tpu/table.py",
        "throttlecrab_tpu/native.py",
        "throttlecrab_tpu/server/resp.py",
        "throttlecrab_tpu/server/engine.py",
        "throttlecrab_tpu/front/admission.py",
        "native/keymap.cpp",
        "native/wire_server.cpp",
    ):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    return tmp_path


class TestTwinDrift:
    def test_real_tree_in_sync(self):
        assert twin_drift.check(REPO) == []

    def test_constant_drift_flagged(self, tmp_path):
        root = _twin_tree(tmp_path)
        kernel = root / KERNEL_REL
        kernel.write_text(
            kernel.read_text().replace("PACK_WIDTH = 9", "PACK_WIDTH = 10")
        )
        findings = [
            f for f in twin_drift.check(root) if f.code == "twin-drift"
        ]
        assert any("PACK_WIDTH" in f.message for f in findings)

    def test_status_code_drift_flagged(self, tmp_path):
        root = _twin_tree(tmp_path)
        cpp = root / "native/keymap.cpp"
        cpp.write_text(
            cpp.read_text().replace(
                "STATUS_NEGATIVE_QUANTITY = 1", "STATUS_NEGATIVE_QUANTITY = 9"
            )
        )
        findings = [
            f for f in twin_drift.check(root) if f.code == "twin-drift"
        ]
        assert any(
            "STATUS_NEGATIVE_QUANTITY" in f.message for f in findings
        )

    def test_error_string_drift_flagged(self, tmp_path):
        root = _twin_tree(tmp_path)
        cpp = root / "native/wire_server.cpp"
        cpp.write_text(
            cpp.read_text().replace(
                "-ERR server overloaded", "-ERR overloaded"
            )
        )
        findings = [
            f for f in twin_drift.check(root) if f.code == "twin-drift"
        ]
        assert any("STATUS_OVERLOADED" in f.message for f in findings)

    def test_missing_anchor_is_loud(self, tmp_path):
        root = _twin_tree(tmp_path)
        (root / "native/keymap.cpp").unlink()
        findings = twin_drift.check(root)
        assert any(
            f.code == "twin-missing" and f.path == "native/keymap.cpp"
            for f in findings
        )

    def test_round5_fits_w32_wire_wrap_reintroduction_caught(
        self, tmp_path
    ):
        """Strip the tol >= 2**61 refusal from fits_w32_wire — the
        exact round-5 high finding — and the suite must flag it even
        though the function keeps its other 2**61 compares."""
        root = _twin_tree(tmp_path)
        kernel = root / KERNEL_REL
        src = kernel.read_text()
        pattern = re.compile(
            r"    if int\(tol\.max\(initial=0\)\) >= \(1 << 61\):\n"
            r"(        #.*\n)*        return False\n"
        )
        assert pattern.search(src), "guard block moved; update the test"
        kernel.write_text(pattern.sub("", src))
        findings = twin_drift.check(root)
        hits = [
            f
            for f in findings
            if f.code == "twin-guard-missing"
            and f.symbol == "fits_w32_wire"
            and "`tol`" in f.message
        ]
        assert hits, "round-5 wrap reintroduction was not caught"


# ------------------------------------------------------------------ #
# jit boundary fixtures


class TestJitBoundary:
    def test_branch_on_traced_value_flagged(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import jax

            @jax.jit
            def decide(x):
                if x > 0:
                    return x
                return -x
            """,
        )
        findings = jit_boundary.check(tmp_path)
        assert len(findings) == 1
        f = findings[0]
        assert f.code == "jit-branch"
        assert f.line == 5
        assert f.symbol == "decide"

    def test_derived_traced_local_flagged(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import jax

            @jax.jit
            def decide(x):
                y = x * 2
                assert y > 0
                return y
            """,
        )
        findings = jit_boundary.check(tmp_path)
        assert [f.code for f in findings] == ["jit-branch"]
        assert findings[0].line == 6

    def test_host_call_flagged(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import time

            import jax

            @jax.jit
            def decide(x):
                t = time.monotonic()
                return x + t
            """,
        )
        findings = jit_boundary.check(tmp_path)
        assert any(
            f.code == "jit-host-call" and "time.monotonic" in f.message
            for f in findings
        )

    def test_static_argnames_branch_passes(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            from functools import partial

            import jax

            @partial(jax.jit, static_argnames=("compact",))
            def decide(x, *, compact=False):
                if compact:
                    return x
                return x + 1
            """,
        )
        assert jit_boundary.check(tmp_path) == []

    def test_shape_based_control_flow_passes(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import jax

            @jax.jit
            def decide(packed):
                W = packed.shape[1]
                if W % 5:
                    raise ValueError("misaligned")
                B = W * 4 // 5
                return packed[:B]
            """,
        )
        assert jit_boundary.check(tmp_path) == []

    def test_pallas_kernel_body_scanned(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import jax
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                if x_ref[0] > 0:
                    o_ref[0] = x_ref[0]

            def run(x):
                return pl.pallas_call(_kernel, out_shape=x)(x)
            """,
        )
        findings = jit_boundary.check(tmp_path)
        assert any(
            f.code == "jit-branch" and f.symbol == "_kernel"
            for f in findings
        )

    def test_branch_on_traced_loop_variable_flagged(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import jax

            @jax.jit
            def decide(xs):
                total = 0
                for v in xs:
                    if v > 0:
                        total = total + v
                return total
            """,
        )
        findings = jit_boundary.check(tmp_path)
        assert any(
            f.code == "jit-branch" and f.line == 7 for f in findings
        )

    def test_static_loop_variable_passes(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            import jax

            @jax.jit
            def decide(x):
                out = x
                for i in range(3):
                    if i % 2:
                        out = out + 1
                return out
            """,
        )
        assert jit_boundary.check(tmp_path) == []

    def test_undecorated_function_ignored(self, tmp_path):
        _write(
            tmp_path,
            "throttlecrab_tpu/tpu/mod.py",
            """\
            def host_side(x):
                if x > 0:
                    return x
                return -x
            """,
        )
        assert jit_boundary.check(tmp_path) == []


# ------------------------------------------------------------------ #
# Knob / metric registry fixtures

_METRICS_FIXTURE = """\
METRIC_NAMES = (
    "throttlecrab_requests_total",
    "throttlecrab_ghost_metric",
)


def export():
    out = []
    out.append("throttlecrab_requests_total 5")
    n = 2
    out.append(f'throttlecrab_novel_metric{{shard="{n}"}} 1')
    return out
"""


class TestRegistry:
    def _tree(self, tmp_path, readme: str = "") -> Path:
        _write(
            tmp_path,
            "throttlecrab_tpu/server/config.py",
            """\
            import os

            KNOB = os.environ.get("THROTTLECRAB_BOGUS_KNOB")
            """,
        )
        (tmp_path / "throttlecrab_tpu/server/metrics.py").parent.mkdir(
            parents=True, exist_ok=True
        )
        (tmp_path / "throttlecrab_tpu/server/metrics.py").write_text(
            _METRICS_FIXTURE
        )
        (tmp_path / "README.md").write_text(readme)
        return tmp_path

    def test_undocumented_knob_flagged(self, tmp_path):
        root = self._tree(tmp_path)
        findings = registry.check(root)
        assert any(
            f.code == "knob-undocumented"
            and "THROTTLECRAB_BOGUS_KNOB" in f.message
            for f in findings
        )

    def test_documented_knob_passes(self, tmp_path):
        root = self._tree(
            tmp_path, readme="`THROTTLECRAB_BOGUS_KNOB` does things\n"
        )
        findings = registry.check(root)
        assert not any(f.code == "knob-undocumented" for f in findings)

    def test_prefix_of_documented_knob_still_flagged(self, tmp_path):
        """Documenting THROTTLECRAB_BOGUS_KNOB_EXTRA must not count as
        documentation for THROTTLECRAB_BOGUS_KNOB (substring trap)."""
        root = self._tree(
            tmp_path, readme="`THROTTLECRAB_BOGUS_KNOB_EXTRA` only\n"
        )
        findings = registry.check(root)
        assert any(
            f.code == "knob-undocumented"
            and "THROTTLECRAB_BOGUS_KNOB " in f.message
            for f in findings
        )

    def test_unregistered_metric_flagged(self, tmp_path):
        root = self._tree(tmp_path)
        findings = registry.check(root)
        hits = [f for f in findings if f.code == "metric-unregistered"]
        assert any(
            "throttlecrab_novel_metric" in f.message for f in hits
        )

    def test_stale_registry_entry_flagged(self, tmp_path):
        root = self._tree(tmp_path)
        findings = registry.check(root)
        assert any(
            f.code == "metric-stale"
            and "throttlecrab_ghost_metric" in f.message
            for f in findings
        )

    def test_docstring_mention_does_not_mask_stale_entry(self, tmp_path):
        """Prose in a docstring starting with a metric name is not an
        emission — the stale registry entry must still be flagged."""
        root = self._tree(tmp_path)
        (root / "throttlecrab_tpu/server/metrics.py").write_text(
            '"""throttlecrab_ghost_metric is incremented on sweeps."""\n'
            + _METRICS_FIXTURE
        )
        findings = registry.check(root)
        assert any(
            f.code == "metric-stale"
            and "throttlecrab_ghost_metric" in f.message
            for f in findings
        )

    def test_registered_and_emitted_metric_passes(self, tmp_path):
        root = self._tree(tmp_path)
        findings = registry.check(root)
        assert not any(
            "throttlecrab_requests_total" in f.message for f in findings
        )

    def test_fstring_prose_is_not_an_emission(self, tmp_path):
        """An f-string whose head merely starts with a metric-shaped
        token is prose, not an emission — no spurious unregistered
        finding."""
        root = self._tree(tmp_path)
        path = root / "throttlecrab_tpu/server/metrics.py"
        path.write_text(
            _METRICS_FIXTURE
            + "\n\ndef log(n):\n"
            + '    return f"throttlecrab_bogus_thing prose {n}"\n'
        )
        findings = registry.check(root)
        assert not any(
            "throttlecrab_bogus_thing" in f.message for f in findings
        )


# ------------------------------------------------------------------ #
# Baseline machinery


class TestBaseline:
    def test_parse_and_match(self):
        waivers = parse_baseline(
            '# comment\n'
            '[[waiver]]\n'
            'code = "i64-raw-op"\n'
            'path = "a/b.py"\n'
            'symbol = "f"\n'
            'reason = "audited"\n'
        )
        assert len(waivers) == 1
        w = waivers[0]
        from throttlecrab_tpu.analysis.common import Finding

        assert w.matches(Finding("i64-raw-op", "a/b.py", 3, "m", "Cls.f"))
        assert not w.matches(Finding("i64-raw-op", "a/b.py", 3, "m", "g"))
        assert not w.matches(Finding("jit-branch", "a/b.py", 3, "m", "f"))

    def test_stale_waiver_detected(self):
        from throttlecrab_tpu.analysis.common import Finding, Waiver

        findings = [Finding("i64-raw-op", "a.py", 1, "m", "f")]
        waivers = [
            Waiver("i64-raw-op", "a.py", symbol="f", reason="r"),
            Waiver("i64-raw-op", "gone.py", symbol="g", reason="r"),
        ]
        unwaived, stale = apply_baseline(findings, waivers)
        assert unwaived == []
        assert len(stale) == 1
        assert stale[0].path == "gone.py"

    def test_count_mismatch_violates_waiver(self):
        """A pinned count must match exactly: new unaudited arithmetic
        inside a waived function fails instead of riding the audit."""
        from throttlecrab_tpu.analysis.common import Finding, Waiver

        findings = [
            Finding("i64-raw-op", "a.py", 1, "m", "f"),
            Finding("i64-raw-op", "a.py", 2, "m", "f"),
        ]
        ok = [Waiver("i64-raw-op", "a.py", symbol="f", count=2, reason="r")]
        unwaived, violated = apply_baseline(findings, ok)
        assert unwaived == [] and violated == []
        pinned = [
            Waiver("i64-raw-op", "a.py", symbol="f", count=1, reason="r")
        ]
        unwaived, violated = apply_baseline(findings, pinned)
        assert unwaived == []  # still absorbed, but…
        assert violated == pinned  # …the outgrown waiver is reported

    def test_repo_baseline_counts_are_pinned(self):
        """Every symbol-scoped waiver in the shipped baseline must pin
        its match count — an unpinned one would absorb future raw ops
        in the most overflow-critical functions silently."""
        for w in load_baseline(DEFAULT_BASELINE):
            if w.symbol and not w.line:
                assert w.count > 0, (
                    f"waiver {w.symbol} must pin `count`"
                )

    def test_malformed_baseline_rejected(self):
        import pytest

        with pytest.raises(ValueError):
            parse_baseline("[[waiver]]\ncode = [1, 2]\n")
        with pytest.raises(ValueError):
            parse_baseline('code = "orphan key"\n')


# ------------------------------------------------------------------ #
# Concurrency checkers (lock / block / async)

_LOCKORDER_FIXTURE = """\
[[lock]]
name = "outer"
class = "Svc"
rank = 10
allow = "net"

[[lock]]
name = "inner"
class = "Svc"
rank = 20

[[blocking]]
call = "sendall"
kind = "net"

[[blocking]]
call = "time.sleep"
kind = "sleep"
"""

_SVC_REL = "throttlecrab_tpu/svc.py"
_LOCKORDER_REL = "throttlecrab_tpu/analysis/lockorder.toml"

_SVC_HEADER = """\
import threading


class Svc:
    def __init__(self):
        self.outer = threading.Lock()
        self.inner = threading.Lock()

"""


def _conc_tree(tmp_path, body: str, toml: str = _LOCKORDER_FIXTURE):
    _write(tmp_path, _SVC_REL, _SVC_HEADER + body)
    if toml is not None:
        _write(tmp_path, _LOCKORDER_REL, toml)
    return tmp_path


class TestLockOrder:
    def test_real_tree_clean_with_baseline(self):
        from throttlecrab_tpu.analysis import lock_order

        findings = run_all(REPO, checks={"lock"})
        waivers = load_baseline(DEFAULT_BASELINE)
        unwaived, _ = apply_baseline(findings, waivers)
        assert unwaived == [], "\n".join(f.format() for f in unwaived)
        assert lock_order  # imported and runnable

    def test_direct_inversion_flagged(self, tmp_path):
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def bad(self):
        with self.inner:
            with self.outer:
                pass
""",
        )
        findings = lock_order.check(root)
        hits = [f for f in findings if f.code == "lock-order"]
        assert len(hits) == 1
        assert hits[0].path == _SVC_REL
        assert "Svc.outer" in hits[0].message
        assert "Svc.inner" in hits[0].message

    def test_canonical_order_passes(self, tmp_path):
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def good(self):
        with self.outer:
            with self.inner:
                pass
""",
        )
        assert [
            f for f in lock_order.check(root) if f.code == "lock-order"
        ] == []

    def test_transitive_inversion_through_call_graph(self, tmp_path):
        """The PR-6/8 deadlock class: the nested acquisition hides one
        call away — the graph must still surface it, with the witness
        chain in the message."""
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def helper(self):
        with self.outer:
            pass

    def bad(self):
        with self.inner:
            self.helper()
""",
        )
        hits = [
            f
            for f in lock_order.check(root)
            if f.code == "lock-order"
        ]
        assert len(hits) == 1
        assert "via" in hits[0].message and "helper" in hits[0].message

    def test_sticky_acquire_region(self, tmp_path):
        """.acquire() holds to end of function (the cluster held-dict
        pattern): a later with-block on a lower rank must flag."""
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def bad(self):
        self.inner.acquire()
        try:
            with self.outer:
                pass
        finally:
            self.inner.release()
""",
        )
        assert any(
            f.code == "lock-order" for f in lock_order.check(root)
        )

    def test_pragma_waives_inversion(self, tmp_path):
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def audited(self):
        with self.inner:
            with self.outer:  # inv: allow(lock-order)
                pass
""",
        )
        assert [
            f for f in lock_order.check(root) if f.code == "lock-order"
        ] == []

    def test_unranked_lock_flagged(self, tmp_path):
        """A new threading.Lock creation site without a [[lock]] entry
        must fail: every lock takes a position in the order."""
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def boot(self):
        self.extra = threading.Lock()
""",
        )
        hits = [
            f
            for f in lock_order.check(root)
            if f.code == "lock-unranked"
        ]
        assert len(hits) == 1
        assert "Svc.extra" in hits[0].message

    def test_stale_lockorder_decl_flagged(self, tmp_path):
        """lockorder.toml staleness: an entry whose creation site is
        gone fails, so the declaration tracks the tree."""
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(
            tmp_path,
            """\
    def nop(self):
        pass
""",
            toml=_LOCKORDER_FIXTURE
            + '\n[[lock]]\nname = "ghost"\nclass = "Gone"\nrank = 30\n',
        )
        hits = [
            f
            for f in lock_order.check(root)
            if f.code == "lock-decl-stale"
        ]
        assert any("Gone.ghost" in f.message for f in hits)

    def test_missing_lockorder_toml_is_loud(self, tmp_path):
        from throttlecrab_tpu.analysis import lock_order

        root = _conc_tree(tmp_path, "    pass\n", toml=None)
        assert any(
            f.code == "lock-config-missing"
            for f in lock_order.check(root)
        )


class TestBlockingUnderLock:
    def test_send_under_unsanctioned_lock_flagged(self, tmp_path):
        """The PR-8 review-fix class: a socket send while a lock whose
        allow list lacks `net` is held."""
        from throttlecrab_tpu.analysis import blocking

        root = _conc_tree(
            tmp_path,
            """\
    def push(self, sock):
        with self.inner:
            sock.sendall(b"x")
""",
        )
        hits = [
            f
            for f in blocking.check(root)
            if f.code == "block-under-lock"
        ]
        assert len(hits) == 1
        assert "sendall" in hits[0].message
        assert "Svc.inner" in hits[0].message

    def test_allowed_kind_passes(self, tmp_path):
        from throttlecrab_tpu.analysis import blocking

        root = _conc_tree(
            tmp_path,
            """\
    def push(self, sock):
        with self.outer:
            sock.sendall(b"x")
""",
        )
        assert blocking.check(root) == []

    def test_transitive_blocking_flagged(self, tmp_path):
        from throttlecrab_tpu.analysis import blocking

        root = _conc_tree(
            tmp_path,
            """\
    def slow(self):
        import time

        time.sleep(1)

    def bad(self):
        with self.inner:
            self.slow()
""",
        )
        hits = [
            f
            for f in blocking.check(root)
            if f.code == "block-under-lock"
        ]
        assert len(hits) == 1
        assert "time.sleep" in hits[0].message
        assert "via" in hits[0].message


class TestAsyncBoundary:
    def test_lock_across_await_flagged(self, tmp_path):
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    async def bad(self):
        with self.inner:
            await self.refresh()

    async def refresh(self):
        pass
""",
        )
        hits = [
            f
            for f in async_boundary.check(root)
            if f.code == "async-lock-await"
        ]
        assert len(hits) == 1
        assert "Svc.inner" in hits[0].message

    def test_ranked_lock_acquire_in_async_flagged(self, tmp_path):
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    async def bad(self):
        with self.inner:
            pass
""",
        )
        assert any(
            f.code == "async-lock-acquire"
            for f in async_boundary.check(root)
        )

    def test_async_ok_lock_passes(self, tmp_path):
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    async def fine(self):
        with self.leaf:
            pass

    def boot(self):
        self.leaf = threading.Lock()
""",
            toml=_LOCKORDER_FIXTURE
            + '\n[[lock]]\nname = "leaf"\nclass = "Svc"\nrank = 90\n'
            + "async_ok = 1\n",
        )
        assert [
            f
            for f in async_boundary.check(root)
            if f.code == "async-lock-acquire"
        ] == []

    def test_blocking_call_in_async_flagged(self, tmp_path):
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    async def bad(self):
        import time

        time.sleep(0.1)
""",
        )
        hits = [
            f
            for f in async_boundary.check(root)
            if f.code == "async-blocking-call"
        ]
        assert len(hits) == 1
        assert "time.sleep" in hits[0].message

    def test_executor_routed_blocking_passes(self, tmp_path):
        """run_in_executor REFERENCES the blocking function; it must
        not count as a loop-side call."""
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    async def fine(self, loop):
        import time

        await loop.run_in_executor(None, time.sleep, 0.1)
""",
        )
        assert [
            f
            for f in async_boundary.check(root)
            if f.code == "async-blocking-call"
        ] == []

    def test_transitive_lock_acquire_on_loop_flagged(self, tmp_path):
        """The OP_RING class fixed this PR: an async handler calling a
        sync helper that takes a ranked lock."""
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    def adopt(self):
        with self.inner:
            pass

    async def handle(self):
        self.adopt()
""",
        )
        hits = [
            f
            for f in async_boundary.check(root)
            if f.code == "async-lock-acquire"
        ]
        assert len(hits) == 1
        assert "via" in hits[0].message and "adopt" in hits[0].message

    def test_loop_affine_api_from_thread_flagged(self, tmp_path):
        from throttlecrab_tpu.analysis import async_boundary

        root = _conc_tree(
            tmp_path,
            """\
    def worker(self):
        import asyncio

        asyncio.get_running_loop()

    async def spawn(self, loop):
        await loop.run_in_executor(None, self.worker)
""",
        )
        hits = [
            f
            for f in async_boundary.check(root)
            if f.code == "async-loop-affinity"
        ]
        assert len(hits) == 1
        assert "get_running_loop" in hits[0].message


class TestRegistryParity:
    _CONFIG = """\
    _SPEC = [
        ("cluster_vnodes", "THROTTLECRAB_CLUSTER_VNODES", 128, int,
         "vnodes"),
        ("shards", "THROTTLECRAB_NSHARDS", 1, int, "shards"),
    ]
    """

    def _tree(self, tmp_path, readme: str) -> Path:
        _write(
            tmp_path,
            "throttlecrab_tpu/server/config.py",
            self._CONFIG,
        )
        _write(
            tmp_path,
            "throttlecrab_tpu/server/metrics.py",
            'METRIC_NAMES = ()\n',
        )
        (tmp_path / "README.md").write_text(readme)
        return tmp_path

    def test_flag_knob_mismatch_flagged(self, tmp_path):
        """--shards paired with THROTTLECRAB_NSHARDS: the canonical
        derivation is THROTTLECRAB_SHARDS — both directions of the
        flag<->knob contract break, so it fails."""
        root = self._tree(
            tmp_path,
            "`THROTTLECRAB_CLUSTER_VNODES` and `THROTTLECRAB_NSHARDS`\n",
        )
        findings = registry.check(root)
        hits = [f for f in findings if f.code == "flag-knob-mismatch"]
        assert len(hits) == 1
        assert "THROTTLECRAB_SHARDS" in hits[0].message
        assert "--shards" in hits[0].message

    def test_matching_flag_knob_passes(self, tmp_path):
        root = self._tree(
            tmp_path,
            "`THROTTLECRAB_CLUSTER_VNODES` and `THROTTLECRAB_NSHARDS`\n",
        )
        findings = registry.check(root)
        assert not any(
            f.code == "flag-knob-mismatch"
            and "cluster_vnodes" in f.message
            for f in findings
        )

    def test_documented_but_unread_knob_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            "`THROTTLECRAB_CLUSTER_VNODES`, `THROTTLECRAB_NSHARDS`,\n"
            "and `THROTTLECRAB_GHOST_KNOB` control things\n",
        )
        findings = registry.check(root)
        hits = [f for f in findings if f.code == "knob-stale"]
        assert len(hits) == 1
        assert "THROTTLECRAB_GHOST_KNOB" in hits[0].message
        assert hits[0].path == "README.md"
        assert hits[0].line == 2

    def test_wildcard_doc_reference_is_not_a_knob(self, tmp_path):
        """Prose like `THROTTLECRAB_CLUSTER_*` names a family, not a
        knob — it must not produce a stale-doc finding."""
        root = self._tree(
            tmp_path,
            "`THROTTLECRAB_CLUSTER_VNODES`, `THROTTLECRAB_NSHARDS`;\n"
            "see the `THROTTLECRAB_CLUSTER_*` family and the\n"
            "`THROTTLECRAB_*` prefix convention\n",
        )
        findings = registry.check(root)
        assert not any(f.code == "knob-stale" for f in findings)


class TestCliOutput:
    def test_json_carries_timings_and_stable_ids(self):
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_invariants.py"),
                "--json",
                "--checks",
                "lock,block,async",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert set(report["checker_s"]) == {"lock", "block", "async"}
        for f in report["findings"]:
            assert f["id"].count(":") >= 2

    def test_runtime_budget_enforced(self):
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_invariants.py"),
                "--checks",
                "twin",
                "--max-seconds",
                "0.000001",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 1
        assert "runtime budget exceeded" in proc.stderr


# ------------------------------------------------------------------ #
# Wave 3: protocol-surface family (wire / harden / status / fault /
# ktwin).  Each fixture copies the real anchor files into a temp tree
# and mutates them — the mutation is the exact defect class the rule
# exists to catch, so these double as regression pins for the rules.

CLUSTER_REL = "throttlecrab_tpu/parallel/cluster.py"
PAIRS_REL = "throttlecrab_tpu/tpu/pallas_fused.py"
INJECTOR_REL = "throttlecrab_tpu/faults/injector.py"

_WIRE_RELS = (
    CLUSTER_REL,
    "throttlecrab_tpu/replay/trace.py",
    "throttlecrab_tpu/replay/player.py",
    "scripts/fuzz_wire_tiers.py",
)
_STATUS_RELS = (
    "throttlecrab_tpu/tpu/limiter.py",
    "throttlecrab_tpu/front/admission.py",
    "throttlecrab_tpu/server/engine.py",
    "throttlecrab_tpu/server/http.py",
    "throttlecrab_tpu/server/grpc.py",
    "throttlecrab_tpu/server/redis.py",
    "throttlecrab_tpu/server/native_redis.py",
    "native/wire_server.cpp",
)
_FAULT_RELS = (
    INJECTOR_REL,
    CLUSTER_REL,
    "throttlecrab_tpu/tpu/limiter.py",
    "throttlecrab_tpu/tpu/snapshot.py",
    "README.md",
)
_KTWIN_RELS = (
    "throttlecrab_tpu/tpu/sat.py",
    KERNEL_REL,
    PAIRS_REL,
)


def _copy_tree(tmp_path: Path, rels) -> Path:
    for rel in rels:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    return tmp_path


def _mutate(root: Path, rel: str, old: str, new: str) -> None:
    path = root / rel
    src = path.read_text()
    assert old in src, f"mutation anchor moved in {rel}: {old!r}"
    path.write_text(src.replace(old, new))


class TestWireSurface:
    def test_real_tree_clean(self):
        assert wire_surface.check_surface(REPO) == []

    def test_fixture_tree_clean(self, tmp_path):
        root = _copy_tree(tmp_path, _WIRE_RELS)
        assert wire_surface.check_surface(root) == []

    def test_op_without_decoder_fails_every_rung(self, tmp_path):
        """A new OP_* constant with no FRAME_DECODERS entry must fail
        the decoder, encoder, dispatch, and fuzzer rungs at once."""
        root = _copy_tree(tmp_path, _WIRE_RELS)
        path = root / CLUSTER_REL
        path.write_text(path.read_text() + "\nOP_PING = 99\n")
        codes = {
            f.code
            for f in wire_surface.check_surface(root)
            if f.symbol == "OP_PING"
        }
        assert codes == {
            "wire-decoder", "wire-encoder", "wire-dispatch", "wire-fuzz",
        }

    def test_missing_fuzzer_arm_flagged(self, tmp_path):
        """Dropping one maker from the fuzzer's op-keyed table — the
        exact OP_LEAVE/OP_DROUTE review-round gap — must fire."""
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, "scripts/fuzz_wire_tiers.py",
            "        OP_RING: mk_ring,\n", "",
        )
        findings = wire_surface.check_surface(root)
        assert any(
            f.code == "wire-fuzz" and f.symbol == "OP_RING"
            for f in findings
        )

    def test_unwired_table_entry_orphans_decoder(self, tmp_path):
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, CLUSTER_REL,
            '    OP_ROUTE_BATCH: ("route", decode_route),\n', "",
        )
        findings = wire_surface.check_surface(root)
        assert any(
            f.code == "wire-decoder" and f.symbol == "OP_ROUTE_BATCH"
            for f in findings
        )
        assert any(
            f.code == "wire-orphan" and f.symbol == "decode_route"
            for f in findings
        )

    def test_replayer_arm_loss_flagged(self, tmp_path):
        """Renaming the player's cluster-leave arm orphans OP_LEAVE's
        membership round-trip."""
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, "throttlecrab_tpu/replay/player.py",
            'elif event.kind == "cluster-leave":',
            'elif event.kind == "cluster-depart":',
        )
        findings = wire_surface.check_surface(root)
        assert any(
            f.code == "wire-replayer"
            and f.symbol == "OP_LEAVE"
            and f.path == "throttlecrab_tpu/replay/player.py"
            for f in findings
        )

    def test_recorder_loss_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _WIRE_RELS)
        path = root / CLUSTER_REL
        path.write_text(
            path.read_text().replace(
                'maybe_record_event("cluster-join"',
                'maybe_record_event("cluster-joined"',
            )
        )
        findings = wire_surface.check_surface(root)
        assert any(
            f.code == "wire-replayer"
            and f.symbol == "OP_JOIN"
            and f.path == CLUSTER_REL
            for f in findings
        )

    def test_missing_anchor_is_loud(self, tmp_path):
        root = _copy_tree(tmp_path, _WIRE_RELS)
        (root / "throttlecrab_tpu/replay/trace.py").unlink()
        findings = wire_surface.check_surface(root)
        assert any(
            f.code == "wire-missing"
            and f.path == "throttlecrab_tpu/replay/trace.py"
            for f in findings
        )


class TestDecodeHardening:
    def test_real_tree_clean(self):
        assert wire_surface.check_hardening(REPO) == []

    def test_trailing_bytes_check_required(self, tmp_path):
        """Stripping decode_batch's trailing-bytes rejection — the
        defect this PR fixed — must fire harden-trailing."""
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, CLUSTER_REL,
            "    if off != len(body):\n"
            '        raise ClusterProtocolError'
            '("trailing bytes after batch items")\n',
            "",
        )
        findings = wire_surface.check_hardening(root)
        assert any(
            f.code == "harden-trailing" and f.symbol == "decode_batch"
            for f in findings
        )

    def test_untyped_raise_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, CLUSTER_REL,
            'raise ClusterProtocolError("trailing bytes after batch items")',
            'raise ValueError("trailing bytes after batch items")',
        )
        findings = wire_surface.check_hardening(root)
        assert any(
            f.code == "harden-typed" and f.symbol == "decode_batch"
            for f in findings
        )

    def test_len_guard_before_unpack_required(self, tmp_path):
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, CLUSTER_REL,
            "    if len(body) < _REQ_HEAD.size:\n"
            '        raise ClusterProtocolError("short batch frame")\n',
            "",
        )
        findings = wire_surface.check_hardening(root)
        assert any(
            f.code == "harden-guard" and f.symbol == "decode_batch"
            for f in findings
        )

    def test_count_guard_before_allocation_required(self, tmp_path):
        """An unpacked count sizing np.empty without its raise-guard is
        the attacker-sized-allocation shape the RPC port must refuse."""
        root = _copy_tree(tmp_path, _WIRE_RELS)
        _mutate(
            root, CLUSTER_REL,
            "    if n > (len(body) - _REQ_HEAD.size) // min_item:\n"
            "        raise ClusterProtocolError"
            '(f"batch count {n} exceeds frame size")\n',
            "",
        )
        findings = wire_surface.check_hardening(root)
        assert any(
            f.code == "harden-count" and f.symbol == "decode_batch"
            for f in findings
        )


class TestStatusSurface:
    def test_real_tree_clean(self):
        assert status_surface.check(REPO) == []

    def test_fixture_tree_clean(self, tmp_path):
        root = _copy_tree(tmp_path, _STATUS_RELS)
        assert status_surface.check(root) == []

    def test_missing_message_entry_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _STATUS_RELS)
        _mutate(
            root, "throttlecrab_tpu/server/engine.py",
            '    STATUS_TENANT_QUOTA: "tenant capacity quota exceeded",\n',
            "",
        )
        findings = status_surface.check(root)
        assert any(
            f.code == "status-message"
            and f.symbol == "STATUS_TENANT_QUOTA"
            for f in findings
        )

    def test_transport_arm_loss_flagged(self, tmp_path):
        """An HTTP transport that stops catching OverloadError would
        turn 503s into generic 500s — the hand-wired arm is pinned."""
        root = _copy_tree(tmp_path, _STATUS_RELS)
        path = root / "throttlecrab_tpu/server/http.py"
        path.write_text(
            path.read_text().replace("OverloadError", "OverloadGoneError")
        )
        findings = status_surface.check(root)
        assert any(
            f.code == "status-transport"
            and f.symbol == "OverloadError"
            and f.path == "throttlecrab_tpu/server/http.py"
            for f in findings
        )

    def test_cpp_branch_loss_and_undeclared_value(self, tmp_path):
        root = _copy_tree(tmp_path, _STATUS_RELS)
        path = root / "native/wire_server.cpp"
        path.write_text(
            path.read_text().replace("status[i] == 5", "status[i] == 57")
        )
        findings = status_surface.check(root)
        assert any(
            f.code == "status-cpp" and f.symbol == "STATUS_TENANT_QUOTA"
            for f in findings
        )
        assert any(
            f.code == "status-cpp" and "57" in f.message
            for f in findings
        )

    def test_native_driver_branch_required(self, tmp_path):
        root = _copy_tree(tmp_path, _STATUS_RELS)
        path = root / "throttlecrab_tpu/server/native_redis.py"
        path.write_text(
            path.read_text().replace("STATUS_DEADLINE", "STATUS_DEADLINE_X")
        )
        findings = status_surface.check(root)
        codes = {
            (f.code, f.symbol)
            for f in findings
            if f.code == "status-native"
        }
        assert ("status-native", "STATUS_DEADLINE") in codes
        assert ("status-native", "STATUS_DEADLINE_X") in codes

    def test_duplicate_status_value_is_orphan(self, tmp_path):
        root = _copy_tree(tmp_path, _STATUS_RELS)
        _mutate(
            root, "throttlecrab_tpu/front/admission.py",
            "STATUS_OVERLOADED = 4", "STATUS_OVERLOADED = 6",
        )
        findings = status_surface.check(root)
        assert any(f.code == "status-orphan" for f in findings)


class TestFaultSurface:
    def test_real_tree_clean(self):
        assert fault_surface.check(REPO) == []

    def test_fixture_tree_clean(self, tmp_path):
        root = _copy_tree(tmp_path, _FAULT_RELS)
        assert fault_surface.check(root) == []

    def test_declared_but_unarmed_site_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _FAULT_RELS)
        _mutate(
            root, INJECTOR_REL,
            '"snapshot", "migrate", "leave",',
            '"snapshot", "migrate", "leave", "gremlin",',
        )
        findings = fault_surface.check(root)
        assert any(
            f.code == "fault-site" and f.symbol == "gremlin"
            for f in findings
        )
        assert any(
            f.code == "fault-doc" and f.symbol == "gremlin"
            for f in findings
        )

    def test_typod_hook_site_flagged_both_directions(self, tmp_path):
        """A typo'd site string at a hook call leaves the declared site
        dead AND arms an undeclared one — both must fire."""
        root = _copy_tree(tmp_path, _FAULT_RELS)
        _mutate(
            root, "throttlecrab_tpu/tpu/limiter.py",
            'maybe_fail("keymap")', 'maybe_fail("keymapp")',
        )
        findings = fault_surface.check(root)
        symbols = {
            f.symbol for f in findings if f.code == "fault-site"
        }
        assert {"keymap", "keymapp"} <= symbols

    def test_doc_row_removal_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _FAULT_RELS)
        readme = root / "README.md"
        kept = [
            line
            for line in readme.read_text().splitlines()
            if not line.startswith("| `migrate`")
        ]
        readme.write_text("\n".join(kept) + "\n")
        findings = fault_surface.check(root)
        assert any(
            f.code == "fault-doc" and f.symbol == "migrate"
            for f in findings
        )

    def test_mode_without_fire_arm_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _FAULT_RELS)
        _mutate(
            root, INJECTOR_REL,
            '"truncate", "fsyncfail",',
            '"truncate", "fsyncfail", "jitter",',
        )
        findings = fault_surface.check(root)
        assert any(
            f.code == "fault-mode" and f.symbol == "jitter"
            for f in findings
        )


class TestKernelTwins:
    def test_real_tree_clean(self):
        assert kernel_twins.check(REPO) == []

    def test_fixture_tree_clean(self, tmp_path):
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        assert kernel_twins.check(root) == []

    def test_saturation_predicate_drift_flagged(self, tmp_path):
        """Flip one overflow predicate on the pair side of sat_add —
        the IRs no longer match, so ktwin-drift must fire."""
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        _mutate(
            root, PAIRS_REL,
            "pos_of = _is_pos(a) & _is_pos(b) & _is_neg(s)",
            "pos_of = _is_pos(a) & _is_neg(b) & _is_neg(s)",
        )
        findings = kernel_twins.check(root)
        assert any(
            f.code == "ktwin-drift" and f.symbol == "_sat_add64"
            for f in findings
        )

    def test_unmarked_sat_reaching_form_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        path = root / KERNEL_REL
        path.write_text(
            path.read_text()
            + "\n\ndef sneaky_form(a, b):\n    return sat_add(a, b)\n"
        )
        findings = kernel_twins.check(root)
        assert any(
            f.code == "ktwin-unmarked" and f.symbol == "sneaky_form"
            for f in findings
        )

    def test_empty_marker_reason_flagged(self, tmp_path):
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        path = root / KERNEL_REL
        path.write_text(
            path.read_text()
            + "\n\ndef probe_form(a, b):  # twin: xla-only()\n"
            "    return sat_add(a, b)\n"
        )
        findings = kernel_twins.check(root)
        assert any(
            f.code == "ktwin-marker" and f.symbol == "probe_form"
            for f in findings
        )

    def test_marker_with_reason_passes(self, tmp_path):
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        path = root / KERNEL_REL
        path.write_text(
            path.read_text()
            + "\n\ndef probe_form(a, b):"
            "  # twin: xla-only(host-side scalar probe)\n"
            "    return sat_add(a, b)\n"
        )
        assert kernel_twins.check(root) == []

    def test_op_coverage_strip_flagged(self, tmp_path):
        """Remove every _min64 from the pair transcription while the
        XLA body still uses minimum — the coverage tier must fire."""
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        path = root / PAIRS_REL
        path.write_text(path.read_text().replace("_min64(", "_max64("))
        findings = kernel_twins.check(root)
        assert any(
            f.code == "ktwin-coverage" and "_min64" in f.message
            for f in findings
        )

    def test_vanished_manifest_twin_is_loud(self, tmp_path):
        root = _copy_tree(tmp_path, _KTWIN_RELS)
        _mutate(
            root, PAIRS_REL,
            "def _sat_add64(", "def _renamed_sat_add64(",
        )
        findings = kernel_twins.check(root)
        assert any(
            f.code == "ktwin-missing" and f.symbol == "_sat_add64"
            for f in findings
        )


class TestWave3Registry:
    def test_checker_codes_registry_total(self):
        """Every registered checker declares its code prefixes — the
        partial-run waiver filter depends on this map being total."""
        assert set(CHECKER_CODES) == set(CHECKERS)
        for name in ("wire", "harden", "status", "fault", "ktwin"):
            assert name in CHECKER_CODES

    def test_stale_wave3_waivers_ratchet(self):
        """A waiver written against any wave-3 rule that matches no
        finding must be reported stale — the new family ratchets from
        zero exactly like the older checkers."""
        from throttlecrab_tpu.analysis.common import Waiver

        findings = run_all(REPO)
        for code, path in (
            ("wire-fuzz", CLUSTER_REL),
            ("harden-trailing", CLUSTER_REL),
            ("status-transport", "throttlecrab_tpu/server/http.py"),
            ("fault-site", INJECTOR_REL),
            ("ktwin-drift", PAIRS_REL),
        ):
            w = Waiver(code, path, symbol="ghost", reason="r")
            unwaived, stale = apply_baseline(findings, [w])
            assert stale == [w], f"{code} waiver did not ratchet"

    def test_run_timed_rejects_unknown_checker(self):
        import pytest

        with pytest.raises(ValueError, match="unknown checks"):
            run_timed(REPO, checks={"nope"})

    def test_cli_rejects_unknown_checks_with_roster(self):
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_invariants.py"),
                "--checks",
                "nope",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 2
        assert "unknown checks" in proc.stderr
        assert "ktwin" in proc.stderr  # the valid roster is listed

    def test_cli_wave3_partial_run_times_each_checker(self):
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO / "scripts" / "check_invariants.py"),
                "--json",
                "--strict",
                "--checks",
                "wire,harden,status,fault,ktwin",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert set(report["checker_s"]) == {
            "wire", "harden", "status", "fault", "ktwin",
        }
        assert report["findings"] == []
        assert report["jax_imported"] is False
