# throttlecrab-tpu server image.
#
# Mirrors the reference's deployment surface (/root/reference/Dockerfile):
# same ports, same THROTTLECRAB_* switches — but the runtime here is
# Python/JAX plus a C++ wire layer built during the image build, so the
# base is slim-python rather than scratch.
#
# On a TPU host, run with the TPU runtime mounted and drop
# THROTTLECRAB_PLATFORM; on CPU-only hosts keep THROTTLECRAB_PLATFORM=cpu.

FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ curl \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY throttlecrab_tpu ./throttlecrab_tpu
COPY native ./native

# Portable baseline arch: the .so baked at build time must run on any
# deployment host, so no -march=native inside images.
ENV THROTTLECRAB_NATIVE_CFLAGS="-O3 -march=x86-64-v2"

RUN pip install --no-cache-dir jax numpy grpcio protobuf \
    && pip install --no-cache-dir -e . \
    # Build the native keymap + wire server now so startup is instant and
    # a toolchain problem fails the image build, not the deployment.
    && python -c "from throttlecrab_tpu.native import native_available, \
wire_available; assert native_available() and wire_available()"

# HTTP, gRPC, Redis/RESP
EXPOSE 8080 8070 6379

ENV THROTTLECRAB_HTTP=true
ENV THROTTLECRAB_GRPC=true
ENV THROTTLECRAB_REDIS=true
ENV THROTTLECRAB_LOG_LEVEL=info
ENV THROTTLECRAB_PLATFORM=cpu

CMD ["python", "-m", "throttlecrab_tpu.server"]
