"""Saturating i64 arithmetic lattices for the device kernels.

XLA's int64 ops wrap on overflow (two's complement); the GCRA contract needs
Rust-style saturating semantics (`rate_limiter.rs:160-238`).  These helpers
detect wrap and clamp, entirely with elementwise ops (VPU-friendly, no
data-dependent control flow).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

I64_MAX = (1 << 63) - 1
I64_MIN = -(1 << 63)


def sat_add(a, b):
    """i64 saturating a + b."""
    s = a + b
    pos_of = (a > 0) & (b > 0) & (s < 0)
    neg_of = (a < 0) & (b < 0) & (s >= 0)
    return jnp.where(pos_of, I64_MAX, jnp.where(neg_of, I64_MIN, s))


def sat_sub(a, b):
    """i64 saturating a - b."""
    d = a - b
    pos_of = (a >= 0) & (b < 0) & (d < 0)
    neg_of = (a < 0) & (b > 0) & (d >= 0)
    return jnp.where(pos_of, I64_MAX, jnp.where(neg_of, I64_MIN, d))


def sat_add_nn(a, b):
    """i64 saturating a + b for b >= 0 (most GCRA additions add a
    non-negative interval/tolerance): only positive overflow is
    possible, and it manifests exactly as s < a — one compare + one
    select instead of the general form's five ops."""
    s = a + b
    return jnp.where(s < a, I64_MAX, s)


def sat_sub_nn(a, b):
    """i64 saturating a - b for b >= 0: only negative overflow is
    possible, manifesting exactly as d > a."""
    d = a - b
    return jnp.where(d > a, I64_MIN, d)


def sat_mul_nonneg(a, b):
    """i64 saturating a * b for a, b >= 0 (the only case GCRA needs)."""
    safe_b = jnp.maximum(b, 1)
    overflow = (b > 0) & (a > I64_MAX // safe_b)
    return jnp.where(overflow, I64_MAX, a * b)


def div_trunc(a, b):
    """i64 division truncating toward zero (Rust `/`); b must be > 0.

    `lax.div` on integers matches C semantics (truncation), unlike
    jnp.floor_divide.
    """
    return lax.div(a, jnp.maximum(b, 1))
