"""Profiling / tracing hooks.

The reference documents external profiling (Instruments, perf, flamegraph —
`TESTING.md:112-143`) and ships structured logging; the TPU framework's
equivalent is the JAX profiler: `trace()` wraps any region in an xprof
trace you can open in TensorBoard/Perfetto, and `annotate()` labels device
launches so batch dispatch shows up as named spans.

Usage:
    from throttlecrab_tpu.tpu.profiling import trace, annotate

    with trace("/tmp/tc-trace"):        # captures device + host timeline
        engine_work()

    with annotate("gcra_batch"):        # names a span inside a trace
        table.check_batch(...)

The server exposes this as `THROTTLECRAB_PROFILE_DIR` — when set, the
engine records a trace of the first N launches after startup.
"""

from __future__ import annotations

from contextlib import contextmanager


@contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace (xprof) into `log_dir`."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span for host/device timelines (no-op outside a trace)."""
    import jax.profiler

    return jax.profiler.TraceAnnotation(name)
