"""Cleanup policies: when to run the expiry-compaction sweep.

The reference couples cleanup policy to its three store types
(`periodic.rs:128-142`, `adaptive_cleanup.rs:138-203`,
`probabilistic.rs:110-125`); here the sweep itself is one jitted mask over
the expiry column (kernel.sweep_expired) and the policy is a host object the
engine consults between batches.  The trigger/adaptation rules are preserved
verbatim, including the adaptive expired-ratio trigger: the per-op expired
hits the Rust store counted inline (`adaptive_cleanup.rs:233,267`) are
counted by the kernel itself (a device-resident accumulator riding every
launch, kernel.gcra_*_acc) and drained to the policy via
`record_expired` — fetched at most once per second, the policy's own
minimum interval, since its triggers have no sub-second semantics.

Policies are consulted with *batches* of operations (the engine processes
thousands of requests per step), so the probabilistic fire-check covers the
whole operation-count range at once.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.i64 import NS_PER_SEC

DEFAULT_CLEANUP_INTERVAL_SECS = 60
MIN_CLEANUP_INTERVAL_SECS = 1
MAX_CLEANUP_INTERVAL_SECS = 300
ADAPTIVE_DEFAULT_INTERVAL_SECS = 5
MAX_OPERATIONS_BEFORE_CLEANUP = 100_000
EXPIRED_RATIO_THRESHOLD = 0.2  # adaptive_cleanup.rs:16
# Ratio trigger floor — EXCLUSIVE bound, `expired_count > 50` verbatim
# (adaptive_cleanup.rs:150): exactly 50 hits never triggers.
MIN_EXPIRED_FOR_RATIO = 50
PROBABILISTIC_CLEANUP_MODULO = 1000
_PRIME = 2654435761


class CleanupPolicy:
    """Decides when the engine should sweep; see subclasses."""

    #: True when the policy consumes the expired-hit signal — the engine
    #: only pays the (throttled) device read for policies that want it.
    uses_expired_signal = False

    def record_ops(self, n: int) -> None:
        """Account `n` processed requests."""

    def record_expired(self, n: int) -> None:
        """Account `n` requests that landed on expired entries."""

    def should_clean(self, now_ns: int, live_keys: int, capacity: int) -> bool:
        raise NotImplementedError

    def after_sweep(self, now_ns: int, removed: int, total_before: int) -> None:
        """Observe a sweep's yield (for self-tuning policies)."""


class PeriodicPolicy(CleanupPolicy):
    """Fixed-interval sweeps (periodic.rs:128-142); default 60 s."""

    def __init__(
        self, interval_ns: int = DEFAULT_CLEANUP_INTERVAL_SECS * NS_PER_SEC
    ) -> None:
        self.interval_ns = interval_ns
        self._next_ns: Optional[int] = None

    def should_clean(self, now_ns, live_keys, capacity):
        if self._next_ns is None:
            self._next_ns = now_ns + self.interval_ns
            return False
        return now_ns >= self._next_ns

    def after_sweep(self, now_ns, removed, total_before):
        self._next_ns = now_ns + self.interval_ns


class ProbabilisticPolicy(CleanupPolicy):
    """Deterministic sampled sweeps (probabilistic.rs:110-125).

    The per-op rule fires when `(ops * 2654435761 mod 2^64) % p == 0`; over a
    batch of n ops the policy fires iff any op count in (prev, prev + n]
    satisfies it — checked exactly with a vectorized wrapping multiply (the
    u64 wrap makes the rule aperiodic past ops ≈ 6.9e9, so no divisor
    shortcut is valid).
    """

    def __init__(self, probability: int = PROBABILISTIC_CLEANUP_MODULO) -> None:
        self.probability = probability
        self._ops = 0
        self._fire = False

    def record_ops(self, n):
        prev = self._ops
        self._ops += n
        # probability 0 never fires (Rust is_multiple_of(0) ⇔ hash == 0,
        # unreachable for the odd-prime product with ops < 2^64).
        if self.probability <= 0 or self._fire or n <= 0:
            return
        ops = np.arange(prev + 1, prev + n + 1, dtype=np.uint64)
        hashed = ops * np.uint64(_PRIME)  # wraps mod 2^64
        if (hashed % np.uint64(self.probability) == 0).any():
            self._fire = True

    def should_clean(self, now_ns, live_keys, capacity):
        return self._fire

    def after_sweep(self, now_ns, removed, total_before):
        self._fire = False


class AdaptivePolicy(CleanupPolicy):
    """Self-tuning sweeps (adaptive_cleanup.rs:138-203).

    Triggers, in the reference's order: time >= next_cleanup; ops since
    last sweep >= max_operations; expired-hit ratio above a dynamic
    threshold (STRICTLY more than 50 hits — `expired_count > 50`,
    adaptive_cleanup.rs:150 — and hits/keys over 10 % after a
    productive sweep, i.e. the last sweep removed over a quarter of the
    table, else 25 %); or keys above 3/4 of table capacity.
    After each sweep the interval doubles (nothing removed and no
    expired hits seen) or halves (over half removed), clamped to
    [min_interval, max_interval].
    """

    uses_expired_signal = True

    def __init__(
        self,
        min_interval_ns: int = MIN_CLEANUP_INTERVAL_SECS * NS_PER_SEC,
        max_interval_ns: int = MAX_CLEANUP_INTERVAL_SECS * NS_PER_SEC,
        max_operations: int = MAX_OPERATIONS_BEFORE_CLEANUP,
    ) -> None:
        self.min_interval_ns = min_interval_ns
        self.max_interval_ns = max_interval_ns
        self.max_operations = max_operations
        self.current_interval_ns = ADAPTIVE_DEFAULT_INTERVAL_SECS * NS_PER_SEC
        self._next_ns: Optional[int] = None
        self._ops = 0
        self._expired = 0
        self._last_removed = 0
        self._last_total = 0

    def record_ops(self, n):
        self._ops += n

    def record_expired(self, n):
        self._expired += n

    def should_clean(self, now_ns, live_keys, capacity):
        if self._next_ns is None:
            self._next_ns = now_ns + self.current_interval_ns
        if now_ns >= self._next_ns:
            return True
        if self._ops >= self.max_operations:
            return True
        # Expired-ratio trigger with the dynamic threshold: clean at
        # half threshold when the last sweep was productive, else wait
        # until 125 % of it (adaptive_cleanup.rs:150-163).
        if self._expired > MIN_EXPIRED_FOR_RATIO:
            ratio = self._expired / max(live_keys, 1)
            if self._last_removed > self._last_total // 4:
                threshold = EXPIRED_RATIO_THRESHOLD / 2.0
            else:
                threshold = EXPIRED_RATIO_THRESHOLD * 1.25
            if ratio > threshold:
                return True
        if live_keys > capacity * 3 // 4:
            return True
        return False

    def after_sweep(self, now_ns, removed, total_before):
        # adaptive_cleanup.rs:187-195: the interval only relaxes when the
        # sweep found nothing AND no traffic hit an expired entry.
        if removed == 0 and self._expired == 0:
            self.current_interval_ns = min(
                self.current_interval_ns * 2, self.max_interval_ns
            )
        elif removed > total_before * 0.5:
            self.current_interval_ns = max(
                self.current_interval_ns // 2, self.min_interval_ns
            )
        self._last_removed = removed
        self._last_total = total_before
        self._next_ns = now_ns + self.current_interval_ns
        self._ops = 0
        self._expired = 0


def feed_expired_hits(policy, limiter, now_ns: int, force: bool = False) -> int:
    """Drain the limiter's expired-hit counter into a policy that wants
    it; returns the drained count (0 when throttled or inapplicable) so
    callers can mirror it into metrics.  Shared by every transport's
    sweep hook (engine._maybe_sweep and the native driver's); call
    under limiter_lock.

    `force=True` bypasses the fetch throttle — used just before a sweep
    so hits counted on-device are attributed to the pre-sweep window
    (after_sweep resets the policy's count; draining late would leak
    them into the fresh window and could fire a redundant ratio sweep).
    """
    if not getattr(policy, "uses_expired_signal", False):
        return 0
    take = getattr(limiter, "take_expired_hits", None)
    if take is None:
        return 0
    n = take(now_ns, 0) if force else take(now_ns)
    if n:
        policy.record_expired(n)
    return n


def make_policy(name: str, **kwargs) -> CleanupPolicy:
    """Factory mirroring the server's store selection (store.rs:57-87)."""
    name = name.lower()
    if name == "periodic":
        interval = kwargs.get("cleanup_interval_secs", DEFAULT_CLEANUP_INTERVAL_SECS)
        return PeriodicPolicy(int(interval * NS_PER_SEC))
    if name == "probabilistic":
        return ProbabilisticPolicy(
            int(kwargs.get("cleanup_probability", PROBABILISTIC_CLEANUP_MODULO))
        )
    if name == "adaptive":
        return AdaptivePolicy(
            min_interval_ns=int(
                kwargs.get("min_interval_secs", MIN_CLEANUP_INTERVAL_SECS) * NS_PER_SEC
            ),
            max_interval_ns=int(
                kwargs.get("max_interval_secs", MAX_CLEANUP_INTERVAL_SECS) * NS_PER_SEC
            ),
            max_operations=int(
                kwargs.get("max_operations", MAX_OPERATIONS_BEFORE_CLEANUP)
            ),
        )
    raise ValueError(f"unknown cleanup policy: {name!r}")
