"""Batched GCRA decision kernel.

One jitted function replaces the reference's request-at-a-time actor hot loop
(`rate_limiter.rs:146-238` behind `actor.rs:217-236`): it takes a tensor of B
requests (slot index + per-request GCRA parameters), gathers the per-slot
state (TAT + expiry) from the HBM-resident bucket table, computes all B
decisions with pure elementwise + segment ops (VPU work — no sort, no
data-dependent control flow), scatters the surviving state back, and returns
per-request results.  Buffers are donated, so the table is updated in place
batch after batch without reallocation.

Intra-batch duplicate keys
==========================

The reference serializes duplicate keys through its single-threaded CAS loop;
a batched kernel must reproduce that *sequential* semantics inside one batch.
The host keymap — which already walks every key to resolve slots — emits the
segment structure for free: for each request, `rank` (its key's occurrence
number within the batch) and `is_last` (whether it is the key's final
occurrence).  With that, the sequential fold per key is evaluated in closed
form — no device-side sort and no segment reductions (TPU scatter-adds
serialize; a measured ~0.5 ms per segment_sum).  For a segment with uniform
parameters (the engine guarantees each key has one (emission, tolerance,
quantity) per batch):

- **Main case** (`inc > 0 and tol > 0`): an allowed request advances TAT by
  `inc = emission * quantity`, a denied one leaves it unchanged, and the
  allow-condition `tat + inc <= now + tol` is monotone in the number of prior
  allows — so the allowed set is exactly a prefix of the segment whose length
  has the direct closed form `m_raw = floor((now + tol - t0) / inc)`.  The
  request at rank r is allowed iff `r < m_raw`; a denied request's observed
  TAT is `t0 + m_raw*inc` (denial implies `m_raw <= rank`, so the segment
  total never exceeds m_raw); and the write-back at the `is_last` position
  uses segment size `rank + 1`.  Every output follows per-position — no
  cross-position communication at all.  No mid-batch expiry is possible
  here: every allowed write has ttl >= tol > 0.

- **Degenerate case** (`inc == 0 or tol == 0`, i.e. quantity=0 probes,
  burst=1, or sub-ns emission intervals): an allowed write can carry ttl == 0
  and expire *instantly* (the burst-1 quirk pinned in
  tests/test_gcra_math.py::test_burst_one_ttl_zero_quirk), or carry a
  negative raw ttl that wraps to an effectively-immortal entry whose stored
  TAT then gets clamped *up* on re-read.  Model each request as a transition
  on the "view" v (the clamped/initialised TAT it observes): denial leaves v
  unchanged (absorbing — the next request sees the identical state), a dead
  write resets v to the fresh-miss value `now - emission`, and a live write
  moves v to `max(new_tat, now - tolerance)`.  Within one batch `now` is
  fixed, so the view orbit is eventually periodic with pre-period <= 1 and
  period <= 2: the entire segment is described by the three views
  v0, v1 = f(v0), v2 = f(v1) (with v3 = v1), and every request's outputs
  select among those three by rank parity.  All closed form, no scan.

Launch amortization
===================

The serving tunnel to the TPU has a multi-millisecond fixed cost per launch
and per device→host fetch, so the engine processes K micro-batches per
launch with `gcra_scan` (a `lax.scan` over stacked [K, B] inputs, each
sub-batch with its own server timestamp) and fetches one stacked [K, 4, B]
output.  Single-batch `gcra_batch` is the same body without the scan.

Within one launch the body still compiles to 5+ composed XLA ops per
sub-batch (unpack, gather, closed forms, pack, scatter), each
materializing intermediates to HBM; `pallas_fused.py`
(THROTTLECRAB_PALLAS_FUSED=1, dispatched by BucketTable/
ShardedBucketTable) fuses the whole window into one Pallas kernel with
the i64 math decomposed into i32 hi/lo pairs.  This module remains the
default path, the kill switch, and the bit-exactness oracle the fused
kernel is pinned against.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .sat import (
    I64_MAX,
    div_trunc,
    sat_add,
    sat_add_nn,
    sat_mul_nonneg,
    sat_sub,
    sat_sub_nn,
)

EMPTY_EXPIRY = -(1 << 63)  # expiry sentinel: always in the past

_U32 = (1 << 32) - 1

# Packed request row: one i32[PACK_WIDTH] word group per request, so a whole
# launch travels host→device as ONE buffer instead of eight arrays.  The
# serving tunnel charges a fixed ~6 ms per transfer *call* (measured round 4,
# docs/tpu-launch-profile.md), so eight device_puts per launch cost ~46 ms of
# pure per-call latency — one packed buffer pays it once.
#   w0 slot | w1 rank | w2 flags(bit0 is_last, bit1 valid)
#   w3/w4 emission lo/hi | w5/w6 tolerance lo/hi | w7/w8 quantity lo/hi
PACK_WIDTH = 9
PACK_FLAG_IS_LAST = 1
PACK_FLAG_VALID = 2


def _pallas_rows() -> bool:
    """Route the table row gather/scatter through the Pallas DMA kernels
    (pallas_ops.py; THROTTLECRAB_PALLAS=1).  Read at trace time — the
    first trace of each jit cache entry freezes the choice."""
    from . import pallas_ops

    return pallas_ops.enabled()


def pallas_fused_enabled() -> bool:
    """Whether decision windows route through the fused Pallas kernel
    (pallas_fused.py; THROTTLECRAB_PALLAS_FUSED).  The canonical parse,
    living here so the kill-switch check never imports the
    jax.experimental.pallas stack: with the knob unset (or any falsy
    spelling) the default composed-XLA path stays fully isolated from
    the fused module.  Truthy spellings match config._env_bool exactly
    — the _SPEC-registered flag and this env read must never disagree
    about whether the kill switch is engaged."""
    import os

    value = os.environ.get("THROTTLECRAB_PALLAS_FUSED", "")
    return value.lower() in ("1", "true", "yes", "on")


def pack_state(tat, expiry):
    """(i64[N], i64[N]) → i32[N, 4] rows [tat_lo, tat_hi, exp_lo, exp_hi].

    TPU scatter cost is per-row with poor i64 lowering; one 4×i32 row
    scatter is ~4.5x cheaper than two separate i64 scatters (measured on
    v5e), so the table lives split into 32-bit halves.
    """
    def split(x):
        lo = (x & _U32).astype(jnp.uint32).astype(jnp.int32)
        hi = (x >> 32).astype(jnp.int32)
        return lo, hi

    tat_lo, tat_hi = split(tat)
    exp_lo, exp_hi = split(expiry)
    return jnp.stack([tat_lo, tat_hi, exp_lo, exp_hi], axis=-1)


def unpack_state(state):
    """i32[..., W] rows → (tat i64[...], expiry i64[...]); extra
    columns (the insight-widened layout) are ignored."""
    def join(lo, hi):
        return (hi.astype(jnp.int64) << 32) | (lo.astype(jnp.int64) & _U32)

    return (
        join(state[..., 0], state[..., 1]),
        join(state[..., 2], state[..., 3]),
    )


# Insight-widened row: [tat_lo, tat_hi, exp_lo, exp_hi, deny_lo,
# deny_hi] — the per-slot denied-hit counter lives INSIDE the packed
# state row so the decision path's one row gather + one row scatter
# maintain it for free (scatter cost is per row, not per column —
# that's why the table is packed rows in the first place).
INS_WIDTH = 6


def unpack_deny(state):
    """Denied-hit counter column of insight-widened rows (i64[...])."""
    return (state[..., 5].astype(jnp.int64) << 32) | (
        state[..., 4].astype(jnp.int64) & _U32
    )


def _split_cols(x):
    """i64[...] → i32[..., 2] lo/hi column pair."""
    lo = (x & _U32).astype(jnp.uint32).astype(jnp.int32)
    hi = (x >> 32).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1)


def pack_requests(slots, rank, is_last, emission, tolerance, quantity, valid):
    """Host-side packing: [...]-shaped request arrays → i32[..., PACK_WIDTH].

    numpy fallback for the C++ assembler (native/keymap.cpp tk_assemble),
    which writes the same layout straight from key ids with no intermediate
    arrays.
    """
    import numpy as np

    out = np.empty(np.shape(slots) + (PACK_WIDTH,), np.int32)
    out[..., 0] = slots
    out[..., 1] = rank
    out[..., 2] = np.asarray(is_last, np.int32) * PACK_FLAG_IS_LAST + (
        np.asarray(valid, np.int32) * PACK_FLAG_VALID
    )
    for base, arr in ((3, emission), (5, tolerance), (7, quantity)):
        a = np.asarray(arr, np.int64)
        out[..., base] = (a & _U32).astype(np.uint32).view(np.int32)
        out[..., base + 1] = (a >> 32).astype(np.int32)
    return out


def fits_cur_wire(tolerance, now_ns) -> bool:
    """Certificate for the compact="cur" output mode (8 B/request).

    The mode transmits one i64 per request: `cur * 2 + allowed`, where
    `cur` is the request's observed TAT.  Exactness requires the shift to
    never overflow: cur <= now + tol, so `now < 2**61 and tol < 2**61`
    guarantees cur < 2**62 (and the certified fast path bounds cur below
    by ~-(2**52): t0 >= now - max(emission, tolerance) with the segment
    advance certified < 2**62).  tol >= 2**61 means a burst window over
    73 years; now >= 2**61 is a wall clock past year 2043 — the engine
    falls back to the 4-plane compact output for either.
    """
    import numpy as np

    return bool(now_ns < (1 << 61)) and bool(
        np.max(tolerance, initial=0) < (1 << 61)
    )


# compact="w32" field widths: allowed(1) + remaining(10) + reset_s(11)
# + retry_s(10) = 32.  The bounds are generous for real rate limits
# (remaining <= 1023 tokens of headroom, reset <= ~34 min, retry <=
# ~17 min); anything bigger falls back to compact="cur".
W32_REM_MAX = (1 << 10) - 1
W32_RESET_MAX = (1 << 11) - 1
W32_RETRY_MAX = (1 << 10) - 1


def fits_w32_wire(
    valid, emission, tolerance, quantity, now_ns, tol_hwm, now_hwm=0
) -> bool:
    """Certificate for the compact="w32" output mode (4 B/request).

    Exactness needs every valid lane's wire values inside the packed
    field widths.  From cur ∈ [now - max(em, tol), now + max(tol, hwm)]
    (the kernel clamps t0 below, the allow condition bounds new TATs
    above by now + tol, and every stored TAT is <= prior_now + hwm
    where `tol_hwm` is the table's high-water mark of valid tolerances
    ever launched — BucketTable.tol_hwm):

      remaining <= (tol + max(em, tol)) // em   <= W32_REM_MAX
      reset_s   <= (tol + hwm) // 1e9           <= W32_RESET_MAX
      retry_s   <= (inc + max(hwm - tol, 0)) // 1e9 <= W32_RETRY_MAX

    The stored-TAT bound `stored <= now + hwm` additionally needs this
    launch's clock at or past every prior launch's (`now_ns >= now_hwm`
    — BucketTable.now_hwm); a regressed clock can push reset_s past its
    field by the regression amount, so it forfeits w32 (the cur tier
    absorbs regressions fine).  Callers must ALSO hold the
    with_degen=False certificate (has_degenerate) — the degenerate
    views have no packable closed form — and now_ns >= 0.
    """
    import numpy as np

    v = np.asarray(valid, bool)
    if not bool(np.any(v)):
        return True
    if not 0 <= now_ns < (1 << 61):
        return False
    if now_ns < int(now_hwm):
        return False
    hwm = int(tol_hwm)
    if hwm >= (1 << 61):
        return False
    em = np.where(v, np.asarray(emission, np.int64), 1)
    tol = np.where(v, np.asarray(tolerance, np.int64), 0)
    q = np.where(v, np.asarray(quantity, np.int64), 0)
    if int(tol.max(initial=0)) >= (1 << 61):
        # A legal big-tolerance lane (e.g. burst 5e6 at em 1000 s) wraps
        # the int64 bound sums below and would falsely certify w32 while
        # the true reset is orders of magnitude past the 2047 s field —
        # and the stored TAT >= 2^62 would corrupt cur_safe for later
        # launches.  Mirror TK_PREP_BIGTOL / fits_w32_wire_agg's C++
        # twin: refuse before any arithmetic can wrap.
        return False
    hwm = max(hwm, int(tol.max(initial=0)))
    em_safe = np.maximum(em, 1)  # degen-free cert guarantees em > 0
    inc = em * q
    rem_bound = (tol + np.maximum(em, tol)) // em_safe
    reset_bound = (tol + hwm) // _NS_PER_SEC
    retry_bound = (inc + np.maximum(hwm - tol, 0)) // _NS_PER_SEC
    return bool(
        (np.where(v, rem_bound, 0) <= W32_REM_MAX).all()
        and (np.where(v, reset_bound, 0) <= W32_RESET_MAX).all()
        and (np.where(v, retry_bound, 0) <= W32_RETRY_MAX).all()
    )


def fits_w32_wire_agg(
    max_tol, min_tol, max_inc, rem_bound, now_ns, tol_hwm, now_hwm=0
) -> bool:
    """fits_w32_wire from precomputed valid-lane aggregates — the O(1)
    form fed by the C++ prep's `agg` output (native/keymap.cpp
    tk_prepare_batch), so the native serving path never re-walks the
    packed rows in Python.  `max_inc + (hwm - min_tol)` is the array
    version's per-lane retry bound taken conservatively (a lane's own
    inc with another lane's smaller tol can only over-estimate)."""
    if not 0 <= now_ns < (1 << 61) or now_ns < int(now_hwm):
        return False
    hwm = int(tol_hwm)
    if hwm >= (1 << 61):
        return False
    hwm = max(hwm, int(max_tol))
    if int(rem_bound) > W32_REM_MAX:
        return False
    if (int(max_tol) + hwm) // _NS_PER_SEC > W32_RESET_MAX:
        return False
    retry_bound = int(max_inc) + max(hwm - int(min_tol), 0)
    return retry_bound // _NS_PER_SEC <= W32_RETRY_MAX


def finish_w32(words):
    """Host-side unpack of the compact="w32" device output: i32 words →
    (allowed, remaining, reset_after_secs, retry_after_secs), all i32 —
    bit-exact to the 4-plane compact output on every valid lane (the
    device packed the final values; this is three shifts and masks, no
    reconstruction arithmetic)."""
    import numpy as np

    u = np.ascontiguousarray(words, np.int32).view(np.uint32)
    return (
        (u & 1).astype(np.int32),
        ((u >> 1) & np.uint32(W32_REM_MAX)).astype(np.int32),
        ((u >> 11) & np.uint32(W32_RESET_MAX)).astype(np.int32),
        ((u >> 22) & np.uint32(W32_RETRY_MAX)).astype(np.int32),
    )


def cur_wire_safe(valid, tolerance, now_ns) -> bool:
    """Valid-lane-masked fits_cur_wire, for batches that carry rejected
    or padding lanes.

    The cur certificate only concerns lanes that are actually decided
    and written: a rejected request's wrapped-garbage tolerance (e.g.
    burst 0 → u32-wrapped tol ~4.3e18) must neither forfeit the current
    launch's cur output (invalid lanes are don't-care in the wire) nor
    poison the table's cross-launch `cur_safe` flag.  The same bound
    serves both purposes because every allowed write is <= now + tol of
    its own lane (saturating paths included), so `now < 2^61` plus
    `tol < 2^61` on every VALID lane keeps all stored TATs < 2^62 —
    degenerate lanes (quantity-0 probes, zero emission, big-inc) obey
    the same write bound and need no special case.  tk_prepare_batch's
    PREP_BIGTOL is the C++ twin (it skips invalid lanes the same way).
    """
    import numpy as np

    return bool(now_ns < (1 << 61)) and not bool(
        np.any(np.asarray(valid) & (np.asarray(tolerance) >= (1 << 61)))
    )


def finish_cur(cur2, emission, tolerance, quantity, now_ns):
    """Host-side completion of the compact="cur" device output (numpy).

    Reconstructs the exact 4-plane compact wire values — (allowed,
    remaining, reset_after_secs, retry_after_secs), all i32 — from the
    single i64-per-request device output.  Under the fits_cur_wire +
    with_degen=False certificate every intermediate fits i64, so plain
    arithmetic reproduces the device's saturating ops bit-for-bit on
    every VALID lane.  (valid=False lanes are don't-care: the wire bit
    carries the masked `allowed & valid`, so a padding lane whose
    unmasked decision was "allowed" finishes with a nonzero retry where
    the 4-plane compact output has 0 — all consumers mask those lanes.)
    The C++ twin is native/keymap.cpp tk_finish (reads emission/
    tolerance/quantity straight from the packed request rows).
    """
    import numpy as np

    cur2 = np.asarray(cur2, np.int64)
    allowed = (cur2 & 1) != 0
    cur = cur2 >> 1  # arithmetic shift: exact for negative cur too
    em = np.asarray(emission, np.int64)
    tol = np.asarray(tolerance, np.int64)
    inc = em * np.asarray(quantity, np.int64)
    room = now_ns + tol - cur
    remaining = np.maximum(
        np.where(em > 0, room // np.where(em > 0, em, 1), 0), 0
    )
    reset = np.maximum(cur - now_ns + tol, 0)
    retry = np.where(allowed, 0, np.maximum(cur + inc - tol - now_ns, 0))
    i32max = _I32_MAX
    return (
        allowed.astype(np.int32),
        np.minimum(remaining, i32max).astype(np.int32),
        np.minimum(reset // 1_000_000_000, i32max).astype(np.int32),
        np.minimum(retry // 1_000_000_000, i32max).astype(np.int32),
    )


def _unpack_requests(packed, now):
    """i32[B, PACK_WIDTH] → the _gcra_body batch tuple (device side)."""

    def join(lo, hi):
        return (hi.astype(jnp.int64) << 32) | (lo.astype(jnp.int64) & _U32)

    flags = packed[..., 2]
    return (
        packed[..., 0],                                   # slots
        packed[..., 1].astype(jnp.int64),                 # rank
        (flags & PACK_FLAG_IS_LAST) != 0,                 # is_last
        join(packed[..., 3], packed[..., 4]),             # emission
        join(packed[..., 5], packed[..., 6]),             # tolerance
        join(packed[..., 7], packed[..., 8]),             # quantity
        (flags & PACK_FLAG_VALID) != 0,                   # valid
        now,
    )


def _request_outputs(t, inc, emission, tol, now):
    """Outcome of one GCRA check from state `t` (all i64, vectorized).

    Mirrors rate_limiter.rs:168-238 for a single request whose (possibly
    clamped or miss-initialised) TAT is `t`.
    Returns (allowed, remaining, reset_after, retry_after, new_tat, ttl).
    """
    new_tat = sat_add(t, inc)
    allow_at = sat_sub(new_tat, tol)
    allowed = now >= allow_at
    cur = jnp.where(allowed, new_tat, t)
    # WRAPPING add, not saturating: the reference computes burst_limit
    # with a wrapping i64 sum (rate_limiter.rs / core oracle
    # `wrap_i64(now + tol)`), so a tolerance big enough to overflow
    # now + tol wraps negative and `remaining` collapses to 0.  XLA's
    # plain i64 add has exactly those two's-complement semantics.
    burst_limit = now + tol  # inv: allow(i64-raw-op)
    room = sat_sub(burst_limit, cur)
    remaining = jnp.where(
        emission > 0, jnp.maximum(div_trunc(room, emission), 0), 0
    )
    reset_after = jnp.maximum(sat_add(sat_sub(cur, now), tol), 0)
    retry_after = jnp.where(
        allowed, 0, jnp.maximum(sat_sub(allow_at, now), 0)
    )
    ttl = sat_add(sat_sub(new_tat, now), tol)
    return allowed, remaining, reset_after, retry_after, new_tat, ttl


def _gcra_body(state, batch, *, with_degen=True, compact=False,
               count_expired=False):
    """Decide one micro-batch; returns (state, out), plus the batch's
    expired-hit count when count_expired=True.

    `state` is the packed i32[N, 4] table (see pack_state).

    with_degen=False compiles out the degenerate-case machinery — legal only
    when the host certifies the batch has no quantity-0, burst-1,
    zero-emission, or wrapped-negative-tolerance requests (the engine checks
    per batch via has_degenerate).  The certificate also guarantees
    tolerance > 0 and inc >= 0, so this path swaps the general saturating
    add/sub for the 2-op nonneg forms (sat.py sat_add_nn/sat_sub_nn) —
    together ~40% less VPU work than the exact path.

    compact=True returns i32[4, B] (allowed, remaining, reset_after_secs,
    retry_after_secs) instead of i64 nanosecond outputs — the exact wire
    semantics of the reference server, whose responses are whole seconds
    (types.rs:87-97) and whose gRPC proto is int32 (throttlecrab.proto:15-21).
    Values saturate at i32::MAX.  Halves the device→host bytes per decision.
    """
    (slots, rank, is_last, emission, tolerance, quantity, valid, now) = batch
    N = state.shape[0]
    now = now.astype(jnp.int64)
    # Insight-widened rows (INS_WIDTH: the per-slot denied-hit counter
    # rides columns 4/5 of the SAME packed row, so its maintenance is
    # absorbed by the one gather + one scatter the decision path already
    # pays — measured free on the CPU backend, where an extra scatter
    # op would cost ~40% of the whole launch).  Static shape ⇒ the
    # plain 4-wide table compiles the identical graph as before.
    ins = state.shape[-1] > 4
    # The Pallas DMA kernels move fixed 4-wide rows; insight-widened
    # tables take the plain gather/scatter (enable_insight documents
    # the exclusion).
    use_pallas = _pallas_rows() and not ins

    s = jnp.clip(slots, 0, N - 1).astype(jnp.int32)
    if use_pallas:
        from . import pallas_ops

        rows_g = pallas_ops.row_gather(state, s)
    else:
        rows_g = state[s]
    stored_tat, stored_exp = unpack_state(rows_g)
    stored_deny = unpack_deny(rows_g) if ins else None
    v = valid
    live = v & (stored_exp > now)

    em = emission
    tol = tolerance

    # The with_degen=False certificate (has_degenerate + the engine's
    # now_ns >= 0 validation; direct kernel callers must uphold both)
    # guarantees tol > 0, em >= 0, inc >= 0, now >= 0, AND
    # inc * MAX_SEGMENT < 2^63 — which licenses the 2-op nonneg
    # saturating forms below (every second operand is tol, em, now, or a
    # segment product) and PLAIN multiplies for the segment arithmetic
    # (a saturating multiply hides an i64 division in its overflow
    # probe).  No certified product can overflow, via two different
    # arguments: rank-bounded multipliers (quantity's inc, rank+1, and
    # min(m_raw, rank+1)) are <= MAX_SEGMENT with inc*MAX_SEGMENT
    # certified < 2^62; the UNCLAMPED m_raw multiplier is instead bounded
    # by the division identity m_raw = num // inc => m_raw*inc <= num.
    # On the exact path the same names bind the GENERAL ops, so
    # s_add/s_sub/s_mul carry no precondition there.
    if with_degen:
        s_add, s_sub, s_mul = sat_add, sat_sub, sat_mul_nonneg
    else:
        s_add, s_sub = sat_add_nn, sat_sub_nn

        def s_mul(a, b):
            return a * b

    inc = s_mul(em, quantity)

    # Initial TAT of the segment: stored value clamped to now - tol, or the
    # first-touch value now - emission (rate_limiter.rs:158-166).  Identical
    # at every position of a segment since all inputs are per-slot uniform.
    t0 = jnp.where(
        live, jnp.maximum(stored_tat, s_sub(now, tol)), s_sub(now, em)
    )

    # ---- main case: prefix closed form ------------------------------------
    # m_raw = how many sequential allows fit before the limit; rank r is
    # allowed iff r < m_raw.  Division is exact (inc > 0 in the main case).
    num = sat_sub(s_add(now, tol), t0)
    m_raw = jnp.maximum(div_trunc(num, inc), 0)
    allowed_main = rank < m_raw

    new_tat_r = s_add(t0, s_mul(rank + 1, inc))
    # Observed TAT: own new_tat when allowed; t0 + m_raw*inc when denied
    # (all m_raw allowed requests precede any denied one).  m_raw*inc
    # never overflows on the certified path: m_raw = num // inc, so the
    # product is <= num, itself bounded by now + tol - t0.
    tat_denied = s_add(t0, s_mul(m_raw, inc))
    cur_main = jnp.where(allowed_main, new_tat_r, tat_denied)
    # Segment write-back, evaluated at the is_last position where the
    # segment size is rank + 1.
    tat_fin_main = s_add(
        t0, s_mul(jnp.minimum(m_raw, rank + 1), inc)
    )

    # WRAPPING add (see _request_outputs): the reference's burst_limit
    # wraps on i64 overflow; a saturating add here made `remaining`
    # huge instead of 0 for wrapped-positive tolerances near i64::MAX
    # (caught by differential fuzzing, round 4).  The certified fast
    # path does NOT bound tol, so the overflow case is reachable there
    # too; for every non-overflowing input the plain add is identical
    # (and cheaper).  `num` above must STAY saturating — the closed
    # form's allow condition matches the oracle's saturating chain.
    burst_limit = now + tol  # inv: allow(i64-raw-op)
    room_main = sat_sub(burst_limit, cur_main)
    remaining_main = jnp.where(
        em > 0, jnp.maximum(div_trunc(room_main, em), 0), 0
    )
    reset_main = jnp.maximum(s_add(s_sub(cur_main, now), tol), 0)
    retry_main = jnp.where(
        allowed_main,
        0,
        jnp.maximum(s_sub(s_sub(s_add(cur_main, inc), tol), now), 0),
    )

    # The reference's adaptive store counts requests that land on an
    # entry past its expiry — but only via the WRITE path: an expired
    # entry makes get() return None, and only an ALLOWED request then
    # reaches set_if_not_exists, which sees the stale entry, counts the
    # hit, and refreshes it (adaptive_cleanup.rs:267; denied requests
    # never touch the store again, and later ranks of the segment see
    # the refreshed entry).  So the signal is: rank-0 valid lane, real
    # stored expiry (not the EMPTY_EXPIRY sentinel) <= now, and that
    # lane allowed.  (One knowing deviation: a ttl-0 "dead" write's
    # allowed re-hits within the same batch are not re-counted.)
    if count_expired:
        exp_hit_base = (
            v
            & (rank == 0)
            & (stored_exp != EMPTY_EXPIRY)
            & (stored_exp <= now)
        )

    # ---- degenerate case: three-view closed form ---------------------------
    if not with_degen:
        ins_row = None
        if ins:
            # Denied count of the whole segment, at its is_last lane:
            # the first min(m_raw, size) ranks were allowed, the rest
            # denied (the prefix closed form above).
            seg_n = rank + 1
            denied_seg = seg_n - jnp.minimum(m_raw, seg_n)
            ins_row = (
                stored_tat, stored_exp, stored_deny, denied_seg,
                v & is_last,
            )
        st_out = _finish(
            state, s, N, now, tol,
            allowed_main & v,
            remaining_main,
            reset_main,
            retry_main,
            (m_raw >= 1) & v & is_last,
            tat_fin_main,
            compact,
            s_add, s_sub,
            cur=cur_main,
            ins_row=ins_row,
        )
        if count_expired:
            n_exp = jnp.sum(
                (exp_hit_base & allowed_main).astype(jnp.int64)
            )
            return (*st_out, n_exp)
        return st_out

    degen = (inc == 0) | (tol == 0)

    def view_step(t):
        """One request's outputs from view t, plus the successor view.

        A write "dies" iff its raw ttl is exactly 0 (ttl < 0 wraps to a huge
        u64 duration in the reference — effectively immortal, see
        rate_limiter.rs:179-183 + core/i64.py wrap_u64); a live write's
        stored TAT is re-clamped to now - tol by the next reader.
        """
        outs = _request_outputs(t, inc, em, tol, now)
        allowed_t, _, _, _, new_t, ttl_t = outs
        dead = allowed_t & (ttl_t == 0)
        t_next = jnp.where(
            ~allowed_t,
            t,
            jnp.where(
                dead, sat_sub(now, em), jnp.maximum(new_t, sat_sub(now, tol))
            ),
        )
        return outs, t_next

    outs0, v1 = view_step(t0)
    outs1, v2 = view_step(v1)
    outs2, _ = view_step(v2)
    a0, a1, a2 = outs0[0], outs1[0], outs2[0]

    def pick(main, o0, o1, o2):
        """Select a degen output by rank: v0 at rank 0; then v1/v2 by parity
        until the first denial, which is absorbing (the view stops moving)."""
        alternating = jnp.where((rank - 1) % 2 == 0, o1, o2)
        tail = jnp.where(rank == 1, o1, jnp.where(a2, alternating, o2))
        degen_out = jnp.where(
            ~a0, o0, jnp.where(~a1, jnp.where(rank == 0, o0, o1),
                               jnp.where(rank == 0, o0, tail))
        )
        return jnp.where(degen, degen_out, main)

    allowed_out = pick(allowed_main, a0, a0 & a1, a0 & a1 & a2) & v
    remaining_out = pick(remaining_main, outs0[1], outs1[1], outs2[1])
    reset_out = pick(reset_main, outs0[2], outs1[2], outs2[2])
    retry_out = pick(retry_main, outs0[3], outs1[3], outs2[3])

    # ---- write-back --------------------------------------------------------
    # Evaluated at the is_last position, where own rank == segment size - 1.

    # Degenerate final state: the write of the last *allowed* rank L.
    # L = 0 if only rank 0 got through (or k == 1), L = 1 if denial started
    # at rank 2, else L = k-1 with the view alternating v1/v2.
    new0_t, new1_t, new2_t = outs0[4], outs1[4], outs2[4]
    last_rank = rank
    alt_last = jnp.where((last_rank - 1) % 2 == 0, new1_t, new2_t)
    tat_fin_degen = jnp.where(
        (last_rank == 0) | ~a1,
        new0_t,
        jnp.where(~a2 | (last_rank == 1), new1_t, alt_last),
    )
    wrote_degen = a0

    wrote = jnp.where(degen, wrote_degen, m_raw >= 1) & v & is_last
    tat_fin = jnp.where(degen, tat_fin_degen, tat_fin_main)
    ins_row = None
    if ins:
        # Segment denied counts, at the is_last lane.  Main case: the
        # prefix closed form (first min(m_raw, size) ranks allowed).
        # Degenerate case: the three-view orbit — nothing after the
        # first denial is allowed, so the allowed count is 0 / 1 /
        # min(2, size) / size by which view first denies.
        seg_n = rank + 1
        allowed_cnt_main = jnp.minimum(m_raw, seg_n)
        allowed_cnt_degen = jnp.where(
            ~a0,
            0,
            jnp.where(
                ~a1, 1, jnp.where(~a2, jnp.minimum(seg_n, 2), seg_n)
            ),
        )
        denied_seg = seg_n - jnp.where(
            degen, allowed_cnt_degen, allowed_cnt_main
        )
        ins_row = (
            stored_tat, stored_exp, stored_deny, denied_seg, v & is_last
        )
    st_out = _finish(
        state, s, N, now, tol,
        allowed_out, remaining_out, reset_out, retry_out,
        wrote, tat_fin, compact,
        sat_add, sat_sub,
        ins_row=ins_row,
    )
    if count_expired:
        # allowed_out already carries & v.
        n_exp = jnp.sum((exp_hit_base & allowed_out).astype(jnp.int64))
        return (*st_out, n_exp)
    return st_out


_I32_MAX = (1 << 31) - 1
_NS_PER_SEC = 1_000_000_000


def _finish(
    state, s, N, now, tol, allowed, remaining, reset_after,
    retry_after, wrote, tat_fin, compact,
    s_add, s_sub, cur=None, ins_row=None,
):
    """Write back the surviving state (one packed-row scatter) and stack the
    outputs.  `add_nn`/`sub_nn` are the caller's saturating ops (the
    certified fast path passes the 2-op nonneg forms).

    `ins_row` (insight-widened tables only) is (stored_tat, stored_exp,
    stored_deny, denied_seg, touch): the scatter then covers every
    decided segment's is_last lane — suppressed GCRA writes re-write
    their row's stored tat/expiry verbatim (bit-identical state) while
    the deny counter columns advance by the segment's denied count.
    Same one-row-scatter cost; unique_indices still holds (one is_last
    lane per slot).

    compact="cur" (certified path only — the degenerate views have no
    single `cur`) emits ONE i64 per request, `cur * 2 + allowed`, and
    leaves remaining/reset/retry to the host (kernel.finish_cur /
    native tk_finish): XLA dead-code-eliminates their two emulated i64
    divisions from the kernel, and the device→host fetch halves to
    8 B/request — the launch-dominating cost through the serving tunnel
    (docs/tpu-launch-profile.md).  Requires the fits_cur_wire
    certificate so the shift cannot overflow."""
    ttl_fin = s_add(s_sub(tat_fin, now), tol)
    # expiry = now + ttl; ttl < 0 wraps to a ~584-year duration in the
    # reference, which we saturate to "never expires".
    expiry_fin = jnp.where(ttl_fin < 0, I64_MAX, s_add(tat_fin, tol))

    # Suppressed writes land in the table's scratch tail (the last B rows,
    # beyond every real slot) at distinct indices, keeping the
    # unique_indices promise honest.
    B = s.shape[0]
    scratch = N - B + jnp.arange(B, dtype=jnp.int32)
    if ins_row is None:
        scatter_idx = jnp.where(wrote, s, scratch).astype(jnp.int32)
        rows = pack_state(tat_fin, expiry_fin)
    else:
        stored_tat, stored_exp, stored_deny, denied_seg, touch = ins_row
        rows = jnp.concatenate(
            [
                pack_state(
                    jnp.where(wrote, tat_fin, stored_tat),
                    jnp.where(wrote, expiry_fin, stored_exp),
                ),
                _split_cols(stored_deny + denied_seg),
            ],
            axis=-1,
        )
        scatter_idx = jnp.where(touch, s, scratch).astype(jnp.int32)
    if _pallas_rows() and ins_row is None:
        from . import pallas_ops

        state = pallas_ops.row_scatter(state, scatter_idx, rows)
    else:
        state = state.at[scatter_idx].set(
            rows, unique_indices=True, mode="drop"
        )

    # One stacked output → one device-to-host fetch.
    if compact == "cur":
        assert cur is not None, 'compact="cur" requires with_degen=False'
        # fits_cur_wire certifies |cur| < 2**62, so the shift-and-tag
        # word cannot overflow.
        out = cur * 2 + allowed.astype(jnp.int64)  # inv: allow(i64-raw-op)
    elif compact == "w32":
        # 4 B/request: the four exact wire values packed into one i32 —
        # allowed(1) | remaining(10) | reset_s(11) | retry_s(22..31).
        # Legal only under fits_w32_wire (host-checked bounds keep every
        # valid lane's fields inside their widths; invalid lanes may
        # overflow within their own don't-care word).  Halves the fetch
        # vs compact="cur"; the i64 divisions run on device (measured
        # free on v5e — docs/tpu-launch-profile.md).
        assert cur is not None, 'compact="w32" requires with_degen=False'
        out = (
            allowed.astype(jnp.int32)
            | (remaining.astype(jnp.int32) << 1)
            | ((reset_after // _NS_PER_SEC).astype(jnp.int32) << 11)
            | ((retry_after // _NS_PER_SEC).astype(jnp.int32) << 22)
        )
    elif compact:
        out = jnp.stack(
            [
                allowed.astype(jnp.int32),
                jnp.minimum(remaining, _I32_MAX).astype(jnp.int32),
                jnp.minimum(reset_after // _NS_PER_SEC, _I32_MAX).astype(
                    jnp.int32
                ),
                jnp.minimum(retry_after // _NS_PER_SEC, _I32_MAX).astype(
                    jnp.int32
                ),
            ]
        )
    else:
        out = jnp.stack(
            [
                allowed.astype(jnp.int64),
                remaining.astype(jnp.int64),
                reset_after.astype(jnp.int64),
                retry_after.astype(jnp.int64),
            ]
        )
    return state, out


@partial(
    jax.jit, donate_argnums=(0,), static_argnames=("with_degen", "compact")
)
def gcra_batch(
    state, slots, rank, is_last, emission, tolerance, quantity,
    valid, now, *, with_degen=True, compact=False,
):
    """Decide B rate-limit requests against the bucket table.

    Args:
      state:     i32[N, 4] packed (tat, expiry) rows (donated; see
                 pack_state).  The last B rows are scratch for suppressed
                 writes — real slots must stay below N - B.
      slots:     i32[B] slot index per request.
      rank:      i32[B] occurrence number of this request for its key.
      is_last:   bool[B] final occurrence of this key in the batch.
      emission:  i64[B] emission interval ns (>= 0; host f64 pipeline).
      tolerance: i64[B] delay variation tolerance ns.
      quantity:  i64[B] tokens requested (>= 0; validation is host-side).
      valid:     bool[B] False for padding / rejected requests.
      now:       i64 scalar, ns since epoch (server-side timestamp).
                 Must be >= 0 when with_degen=False (part of the fast
                 path's certificate; the engine validates it).

    Duplicate slots within the batch MUST share (emission, tolerance,
    quantity); the engine defers conflicting requests to a later batch to
    preserve exact arrival-order semantics.

    Returns (state, out[4, B]) where out rows are (allowed, remaining,
    reset_after, retry_after).
    """
    return _gcra_body(
        state,
        (
            slots,
            rank.astype(jnp.int64),
            is_last,
            emission,
            tolerance,
            quantity,
            valid,
            jnp.asarray(now, jnp.int64),
        ),
        with_degen=with_degen,
        compact=compact,
    )


@partial(
    jax.jit, donate_argnums=(0,), static_argnames=("with_degen", "compact")
)
def gcra_scan(
    state, slots, rank, is_last, emission, tolerance, quantity,
    valid, now, *, with_degen=True, compact=False,
):
    """K micro-batches in one launch: inputs stacked [K, B], now is i64[K].

    Amortizes the fixed per-launch and per-fetch cost of the serving tunnel;
    each sub-batch carries its own server timestamp and sees the table state
    left by the previous one (lax.scan carry), exactly as if dispatched
    separately.  Returns (state, out[K, 4, B]).
    """

    def step(state, batch):
        state, out = _gcra_body(
            state, batch, with_degen=with_degen, compact=compact
        )
        return state, out

    state, outs = jax.lax.scan(
        step,
        state,
        (
            slots,
            rank.astype(jnp.int64),
            is_last,
            emission,
            tolerance,
            quantity,
            valid,
            now.astype(jnp.int64),
        ),
    )
    return state, outs


@partial(
    jax.jit, donate_argnums=(0,), static_argnames=("with_degen", "compact")
)
def gcra_scan_packed(state, packed, now, *, with_degen=True, compact=False):
    """gcra_scan with the whole launch in ONE packed buffer.

    Args:
      state:  i32[N, 4] packed table rows (donated).
      packed: i32[K, B, PACK_WIDTH] request rows (see pack_requests).
      now:    i64[K] per-sub-batch server timestamps.

    Semantically identical to gcra_scan on the unpacked arrays; the packed
    form exists because the serving tunnel's fixed per-transfer cost
    dominates the launch budget (docs/tpu-launch-profile.md) — one
    host→device buffer per launch instead of eight.
    Returns (state, out[K, 4, B]).
    """

    def step(state, kb):
        packed_k, now_k = kb
        return _gcra_body(
            state,
            _unpack_requests(packed_k, now_k),
            with_degen=with_degen,
            compact=compact,
        )

    return jax.lax.scan(step, state, (packed, now.astype(jnp.int64)))


# By-id request words (native/keymap.cpp tk_assemble_ids):
#   low 32 bits: key id | high 32: rank(14) | is_last<<14 | valid<<15
# The device gathers (slot, emission, tolerance) from resident id rows —
# an i32[n_ids, 8] table built by BucketTable.upload_id_rows — so a
# request costs 8 bytes host→device instead of the 36-byte packed row.
# The tunnel moves 10-50 MB/s total, serialized across h2d/compute/d2h
# (scripts/probe_duplex.py), so request bytes are the throughput ceiling.
IDROW_WIDTH = 8


def pack_id_rows(slots, emission, tolerance, width=IDROW_WIDTH):
    """Host-side build of the resident by-id parameter rows:
    i32[n, width] = [slot, em_lo, em_hi, tol_lo, tol_hi, pad...].

    The by-id kernels read only columns 0-4, so any width >= 5 works;
    the default keeps the measured-on-hardware 8-wide layout
    (scripts/probe_byid_ablation.py's width ablation measures whether
    the narrower gather buys anything on a real chip).
    """
    import numpy as np

    if width < 5:
        raise ValueError("id rows need at least 5 columns")
    n = len(slots)
    rows = np.zeros((n, width), np.int32)
    rows[:, 0] = slots
    for base, arr in ((1, emission), (3, tolerance)):
        a = np.asarray(arr, np.int64)
        rows[:, base] = (a & _U32).astype(np.uint32).view(np.int32)
        rows[:, base + 1] = (a >> 32).astype(np.int32)
    return rows


def _rows_to_batch(rows, rank, is_last, valid, quantity, now_k):
    """Shared tail of the by-id scan steps: expand gathered id rows into
    the _gcra_body batch tuple.  One implementation so the host-words
    (gcra_scan_byid) and raw-ids (gcra_scan_ids) paths cannot drift."""

    def join(lo, hi):
        return (hi.astype(jnp.int64) << 32) | (lo.astype(jnp.int64) & _U32)

    return (
        rows[:, 0],                                   # slots
        rank,
        is_last,
        join(rows[:, 1], rows[:, 2]),                 # emission
        join(rows[:, 3], rows[:, 4]),                 # tolerance
        jnp.full(rank.shape, quantity, jnp.int64),    # quantity
        valid,
        now_k,
    )


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("with_degen", "compact"),
)
def gcra_scan_byid(
    state, id_rows, words, now, quantity, *, with_degen=True, compact=False,
):
    """gcra_scan fed by 8-byte request words + resident id rows.

    Args:
      state:    i32[N, 4] packed table rows (donated).
      id_rows:  i32[n_ids, IDROW_WIDTH] resident parameter rows (NOT
                donated — reused launch after launch; see pack_id_rows).
      words:    i64[K, B] request words (tk_assemble_ids layout).
      now:      i64[K] per-sub-batch timestamps.
      quantity: i64 scalar, uniform per launch (the bench/serving caller
                certifies uniformity before taking this path).

    Semantically identical to gcra_scan on the expanded arrays; requests
    whose valid bit is 0 are padding.  Returns (state, out) with `out`
    per the `compact` mode.
    """
    def step(state, kb):
        w, now_k = kb
        return _gcra_body(
            state,
            _byid_batch(w, now_k, id_rows, quantity),
            with_degen=with_degen,
            compact=compact,
        )

    return jax.lax.scan(step, state, (words, now.astype(jnp.int64)))


def _byid_batch(w, now_k, id_rows, quantity):
    """One sub-batch of 8-byte request words → the _gcra_body tuple
    (shared by gcra_scan_byid and its expired-counting twin)."""
    n_ids = id_rows.shape[0]
    idx = jnp.clip((w & _U32).astype(jnp.int32), 0, n_ids - 1)
    meta = w >> 32
    rows = id_rows[idx]
    # Same -1-slot defense as gcra_scan_ids: an unresolved id row
    # (resolve_all on a full table) carries slot -1, which would
    # otherwise clip to slot 0 and corrupt another key's bucket.
    valid = ((meta & (1 << 15)) != 0) & (rows[:, 0] >= 0)
    return _rows_to_batch(
        rows,
        meta & 0x3FFF,                                # rank (i64)
        (meta & (1 << 14)) != 0,                      # is_last
        valid,
        quantity,
        now_k,
    )


def _device_segments(segkey):
    """rank / is_last per lane from a per-lane segment key, on device.

    The host assemblers derive the duplicate-segment structure while
    walking the batch; this is the device twin: one stable argsort
    groups equal keys while preserving arrival order, a max-scan finds
    each run's start, and the inverse permutation (a second argsort —
    a gather, not a scatter) maps ranks back to arrival positions.
    ~0.09 ms per 4096-lane batch on v5e — cheaper than shipping the
    precomputed structure through the 15-50 MB/s tunnel.
    """
    B = segkey.shape[0]
    order = jnp.argsort(segkey, stable=True)
    sk = segkey[order]
    pos = jnp.arange(B, dtype=jnp.int32)
    run_start = jnp.concatenate(
        [jnp.ones((1,), bool), sk[1:] != sk[:-1]]
    )
    start_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(run_start, pos, 0)
    )
    rank_sorted = pos - start_pos
    last_sorted = jnp.concatenate(
        [sk[1:] != sk[:-1], jnp.ones((1,), bool)]
    )
    inv = jnp.argsort(order, stable=True)
    return rank_sorted[inv].astype(jnp.int64), last_sorted[inv]


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("with_degen", "compact"),
)
def gcra_scan_ids(
    state, id_rows, ids, now, quantity, *, with_degen=True, compact=False,
):
    """gcra_scan fed by RAW key ids — 4 bytes per request on the wire.

    The leanest launch: `ids` is i32[K, B] (negative = padding); the
    device gathers (slot, emission, tolerance) from the resident
    `id_rows` AND derives the duplicate-segment structure itself
    (_device_segments), so the host ships nothing but the id stream —
    no C++ assembly on the dispatch path at all.

    Segments are keyed by SLOT (like the host assemblers), so two ids
    sharing a slot still serialize exactly; padding lanes get per-lane
    sentinel keys beyond every real slot so they can never join — or
    split — a real segment.  Semantically identical to gcra_scan_byid
    on tk_assemble_ids words (pinned by tests/test_packed_path.py).
    """

    def step(state, kb):
        w, now_k = kb
        return _gcra_body(
            state,
            _ids_batch(w, now_k, id_rows, quantity),
            with_degen=with_degen,
            compact=compact,
        )

    return jax.lax.scan(step, state, (ids, now.astype(jnp.int64)))


def _ids_batch(w, now_k, id_rows, quantity):
    """One sub-batch of raw key ids → the _gcra_body tuple (shared by
    gcra_scan_ids and its expired-counting twin)."""
    n_ids = id_rows.shape[0]
    # In-range check mirrors the host assembler's n_bad contract: an
    # id beyond the resident rows (interned after upload, or
    # corrupt) must be invalid, never clipped onto another key.
    valid = (w >= 0) & (w < n_ids)
    idx = jnp.clip(w, 0, n_ids - 1)
    rows = id_rows[idx]
    slots = rows[:, 0]
    # An unresolved id row carries slot -1 (resolve_all on a full
    # table); never decide those against clipped slot 0.
    valid = valid & (slots >= 0)
    B = w.shape[0]
    pos = jnp.arange(B, dtype=jnp.int32)
    # Segment key: the slot for real lanes; a distinct out-of-range
    # sentinel per invalid lane (slots are clipped to [0, N) by the
    # kernel, so I32_MAX - pos can collide with nothing real).
    segkey = jnp.where(valid, slots, _I32_MAX - pos)
    rank, is_last = _device_segments(segkey)
    return _rows_to_batch(rows, rank, is_last, valid, quantity, now_k)


# ---- expired-hit accounting twins -------------------------------------- #
# Same decisions (bit-for-bit) as their namesakes plus a device-resident
# accumulator: a donated i64 scalar that grows by each sub-batch's
# expired-hit count (see _gcra_body count_expired — the signal behind the
# reference adaptive store's expired-ratio cleanup trigger,
# adaptive_cleanup.rs:150-163).  BucketTable routes every launch through
# these; the plain entry points above remain the public single-concern
# kernel API (tests, probes, examples, and external callers that bring
# their own state arrays).  Both halves share _gcra_body and the
# _byid_batch/_ids_batch builders, so they cannot drift.  The count
# rides the launch — no extra dispatch, no extra fetch; the host reads
# the scalar only when the cleanup policy wants it
# (BucketTable.expired_hits).


@partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_batch_acc(
    state, exp_acc, slots, rank, is_last, emission, tolerance, quantity,
    valid, now, *, with_degen=True, compact=False,
):
    """gcra_batch + expired-hit accumulation; returns (state, acc, out)."""
    state, out, n_exp = _gcra_body(
        state,
        (
            slots,
            rank.astype(jnp.int64),
            is_last,
            emission,
            tolerance,
            quantity,
            valid,
            jnp.asarray(now, jnp.int64),
        ),
        with_degen=with_degen,
        compact=compact,
        count_expired=True,
    )
    return state, exp_acc + n_exp, out


@partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_acc(
    state, exp_acc, slots, rank, is_last, emission, tolerance, quantity,
    valid, now, *, with_degen=True, compact=False,
):
    """gcra_scan + expired-hit accumulation; returns (state, acc, out)."""

    def step(carry, batch):
        st, acc = carry
        st, out, n = _gcra_body(
            st, batch, with_degen=with_degen, compact=compact,
            count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step,
        (state, exp_acc),
        (
            slots,
            rank.astype(jnp.int64),
            is_last,
            emission,
            tolerance,
            quantity,
            valid,
            now.astype(jnp.int64),
        ),
    )
    return state, exp_acc, outs


@partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_packed_acc(
    state, exp_acc, packed, now, *, with_degen=True, compact=False,
):
    """gcra_scan_packed + expired-hit accumulation."""

    def step(carry, kb):
        st, acc = carry
        p, now_k = kb
        st, out, n = _gcra_body(
            st, _unpack_requests(p, now_k),
            with_degen=with_degen, compact=compact, count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step, (state, exp_acc), (packed, now.astype(jnp.int64))
    )
    return state, exp_acc, outs


# ---- insight twins (L3.75 analytics) ------------------------------------ #
# Same decisions (bit-for-bit) as the *_acc kernels plus the insight
# accumulators riding the SAME launch: the per-slot denied-hit counter
# lives inside the widened state rows (INS_WIDTH — maintained by the
# decision path's own row gather/scatter, see _finish's ins_row), and
# `ins_counts` (i64[2] running [allowed, denied] totals) folds in after
# the scan from the launch's outputs — every output tier carries the
# valid-masked allowed bit, so the totals cost two reductions.  Used
# only when the BucketTable was built with insight enabled; with it off
# the plain *_acc kernels run on 4-wide rows and the XLA graph is
# untouched — the THROTTLECRAB_INSIGHT=0 kill switch is a different
# jit entry point + table layout, not a traced branch.  Everything is
# donated and device-resident; the host reads the accumulators only at
# the insight tier's throttled poll (BucketTable.insight_counts /
# insight_topk), so analytics add zero launches and zero fetches to the
# decision path.


def _lanes_allowed(out, compact):
    """The valid-masked allowed bit of any output tier, [..., B]."""
    if compact in ("cur", "w32"):
        return (out & 1) != 0
    return out[..., 0, :] != 0


def _insight_totals(ins_counts, valid, out, compact):
    """Advance the [allowed, denied] totals from one launch's outputs.
    Allowed planes are already masked with `valid`, so `valid &
    ~allowed` is exactly the decided-and-denied lanes; padding and
    rejected lanes count nowhere."""
    allowed = _lanes_allowed(out, compact)
    denied = valid & ~allowed
    return ins_counts + jnp.stack(
        [
            jnp.sum(allowed.astype(jnp.int64)),
            jnp.sum(denied.astype(jnp.int64)),
        ]
    )


@partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("with_degen", "compact"),
)
def gcra_batch_ins(
    state, exp_acc, ins_counts, slots, rank, is_last, emission,
    tolerance, quantity, valid, now, *, with_degen=True, compact=False,
):
    """gcra_batch_acc + insight accumulation; returns
    (state, exp_acc, ins_counts, out).  `state` must be INS_WIDTH rows.
    """
    state, out, n_exp = _gcra_body(
        state,
        (
            slots,
            rank.astype(jnp.int64),
            is_last,
            emission,
            tolerance,
            quantity,
            valid,
            jnp.asarray(now, jnp.int64),
        ),
        with_degen=with_degen,
        compact=compact,
        count_expired=True,
    )
    ins_counts = _insight_totals(ins_counts, valid, out, compact)
    return state, exp_acc + n_exp, ins_counts, out


@partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("with_degen", "compact"),
)
def gcra_scan_ins(
    state, exp_acc, ins_counts, slots, rank, is_last, emission,
    tolerance, quantity, valid, now, *, with_degen=True, compact=False,
):
    """gcra_scan_acc + insight accumulation (INS_WIDTH rows)."""

    def step(carry, batch):
        st, acc = carry
        st, out, n = _gcra_body(
            st, batch, with_degen=with_degen, compact=compact,
            count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step,
        (state, exp_acc),
        (
            slots,
            rank.astype(jnp.int64),
            is_last,
            emission,
            tolerance,
            quantity,
            valid,
            now.astype(jnp.int64),
        ),
    )
    ins_counts = _insight_totals(ins_counts, valid, outs, compact)
    return state, exp_acc, ins_counts, outs


@partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("with_degen", "compact"),
)
def gcra_scan_packed_ins(
    state, exp_acc, ins_counts, packed, now, *,
    with_degen=True, compact=False,
):
    """gcra_scan_packed_acc + insight accumulation (the valid flags
    come straight off the packed request rows; INS_WIDTH rows)."""

    def step(carry, kb):
        st, acc = carry
        p, now_k = kb
        st, out, n = _gcra_body(
            st, _unpack_requests(p, now_k),
            with_degen=with_degen, compact=compact, count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step, (state, exp_acc), (packed, now.astype(jnp.int64))
    )
    ins_counts = _insight_totals(
        ins_counts,
        (packed[..., 2] & PACK_FLAG_VALID) != 0,
        outs,
        compact,
    )
    return state, exp_acc, ins_counts, outs


@partial(jax.jit, static_argnames=("capacity", "k"))
def insight_topk(state, *, capacity, k):
    """Device-side partial top-K of the denied-hit counter column of an
    insight-widened table: (counts i64[k], slot ids i32[k]), highest
    first.  One tiny launch per insight poll (~1/s), never on the
    decision path; rows past `capacity` (the scratch tail) are
    excluded."""
    vals, idx = jax.lax.top_k(unpack_deny(state[:capacity]), k)
    return vals, idx.astype(jnp.int32)


@partial(jax.jit, donate_argnums=(0,))
def insight_decay(state):
    """Halve the denied-hit counter columns (the insight tier's
    periodic decay: old heat fades, so the top-K tracks the CURRENT hot
    set).  Floor division keeps counts exact against the host twin's
    `// 2`; tat/expiry columns pass through untouched."""
    return jnp.concatenate(
        [state[..., :4], _split_cols(unpack_deny(state) // 2)], axis=-1
    )


@partial(jax.jit, donate_argnums=(1,), static_argnames=("capacity",))
def sweep_expired_ins(now, state, capacity):
    """sweep_expired for insight-widened rows: a vacated slot's
    denied-hit count dies with it (the empty row zeroes ALL columns),
    or the next key recycled into the slot would inherit the old key's
    heat.  Returns (state, expired[:capacity])."""
    now = jnp.asarray(now, jnp.int64)
    _, expiry = unpack_state(state)
    expired = expiry <= now
    empty_rows = jnp.concatenate(
        [
            pack_state(
                jnp.zeros_like(expiry), jnp.full_like(expiry, EMPTY_EXPIRY)
            ),
            jnp.zeros(state.shape[:-1] + (state.shape[-1] - 4,), jnp.int32),
        ],
        axis=-1,
    )
    state = jnp.where(expired[:, None], empty_rows, state)
    return state, expired[:capacity]


@partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_byid_acc(
    state, exp_acc, id_rows, words, now, quantity, *,
    with_degen=True, compact=False,
):
    """gcra_scan_byid + expired-hit accumulation."""

    def step(carry, kb):
        st, acc = carry
        w, now_k = kb
        st, out, n = _gcra_body(
            st, _byid_batch(w, now_k, id_rows, quantity),
            with_degen=with_degen, compact=compact, count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step, (state, exp_acc), (words, now.astype(jnp.int64))
    )
    return state, exp_acc, outs


@partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_ids_acc(
    state, exp_acc, id_rows, ids, now, quantity, *,
    with_degen=True, compact=False,
):
    """gcra_scan_ids + expired-hit accumulation."""

    def step(carry, kb):
        st, acc = carry
        w, now_k = kb
        st, out, n = _gcra_body(
            st, _ids_batch(w, now_k, id_rows, quantity),
            with_degen=with_degen, compact=compact, count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step, (state, exp_acc), (ids, now.astype(jnp.int64))
    )
    return state, exp_acc, outs


# ---- 20-bit id stream ---------------------------------------------------- #
# The leanest host→device encoding for tables under 2^20 - 1 keys:
# 2.5 bytes per request in ONE fused u16 buffer (B low-16 lanes, then
# B/4 lanes of packed high nibbles), decoded on device with two gathers
# and shifts.  With the w32 output tier the whole round trip is
# 6.5 B/request (vs 8 for raw i32 ids + w32, 12 for ids + cur).

IDS20_SENTINEL = (1 << 20) - 1  # padding marker (never a real id)


def pack_ids20(ids):
    """i32[K, B] raw key ids (negative = padding) → u16[K, B + B//4].

    Requires B % 4 == 0 and every real id < 2^20 - 1 (the all-ones
    pattern is the padding sentinel; the device decodes it to an
    out-of-range id, which gcra_scan_ids' in-range check masks
    invalid — callers must also keep n_ids <= IDS20_SENTINEL so the
    sentinel can never alias a real key).
    """
    import numpy as np

    ids = np.asarray(ids)
    K, B = ids.shape
    if B % 4:
        raise ValueError("ids20 batch width must be a multiple of 4")
    if (ids >= IDS20_SENTINEL).any():
        raise ValueError(
            "ids must be < 2^20 - 1 for the 20-bit id stream"
        )
    u = np.where(ids < 0, IDS20_SENTINEL, ids).astype(np.uint32)
    lo = (u & 0xFFFF).astype(np.uint16)
    hi4 = (u >> 16).astype(np.uint16).reshape(K, B // 4, 4)
    hibuf = (
        hi4[..., 0]
        | (hi4[..., 1] << 4)
        | (hi4[..., 2] << 8)
        | (hi4[..., 3] << 12)
    )
    return np.concatenate([lo, hibuf], axis=1)


def _ids20_decode(buf, B):
    """One sub-batch's u16[B + B//4] stream → i32[B] ids (device)."""
    pos = jnp.arange(B, dtype=jnp.int32)
    lo = buf[:B].astype(jnp.int32)
    hw = buf[B + (pos >> 2)].astype(jnp.int32)
    hi = (hw >> ((pos & 3) * 4)) & 0xF
    return (hi << 16) | lo


@partial(
    jax.jit, donate_argnums=(0,), static_argnames=("with_degen", "compact")
)
def gcra_scan_ids20(
    state, id_rows, packed, now, quantity, *, with_degen=True, compact=False,
):
    """gcra_scan_ids fed by the 2.5 B/request 20-bit id stream.

    `packed` is u16[K, B + B//4] (pack_ids20); semantics are identical
    to gcra_scan_ids on the decoded ids (padding decodes to
    IDS20_SENTINEL, out of range for any conforming table, so the
    in-range check masks it exactly like a negative id).
    """
    W = packed.shape[1]
    if W % 5:
        # A misaligned buffer (e.g. a raw id stream handed to the wrong
        # kernel) would mis-split the high-nibble plane into in-range
        # garbage ids and decide against the wrong buckets; fail loudly
        # instead (pack_ids20 / check_many_ids20 enforce the same
        # contract for indirect callers).
        raise ValueError(
            f"ids20 stream width must be a multiple of 5 (got {W})"
        )
    B = W * 4 // 5

    def step(state, kb):
        buf, now_k = kb
        return _gcra_body(
            state,
            _ids_batch(_ids20_decode(buf, B), now_k, id_rows, quantity),
            with_degen=with_degen,
            compact=compact,
        )

    return jax.lax.scan(step, state, (packed, now.astype(jnp.int64)))


@partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_ids20_acc(
    state, exp_acc, id_rows, packed, now, quantity, *,
    with_degen=True, compact=False,
):
    """gcra_scan_ids20 + expired-hit accumulation."""
    W = packed.shape[1]
    if W % 5:
        raise ValueError(
            f"ids20 stream width must be a multiple of 5 (got {W})"
        )
    B = W * 4 // 5

    def step(carry, kb):
        st, acc = carry
        buf, now_k = kb
        st, out, n = _gcra_body(
            st,
            _ids_batch(_ids20_decode(buf, B), now_k, id_rows, quantity),
            with_degen=with_degen, compact=compact, count_expired=True,
        )
        return (st, acc + n), out

    (state, exp_acc), outs = jax.lax.scan(
        step, (state, exp_acc), (packed, now.astype(jnp.int64))
    )
    return state, exp_acc, outs


@partial(jax.jit, donate_argnums=(1,), static_argnames=("capacity",))
def sweep_expired(now, state, capacity):
    """Cleanup-as-compaction: vacate every expired slot, report which.

    The reference's `retain(|_, (_, expiry)| expiry > now)` sweep
    (`periodic.rs:131-141`) becomes a boolean mask over the expiry column;
    the host frees the corresponding key→slot entries from the returned
    mask (first `capacity` rows only — the rest is scratch).
    """
    now = jnp.asarray(now, jnp.int64)
    _, expiry = unpack_state(state)
    expired = expiry <= now
    empty_rows = pack_state(
        jnp.zeros_like(expiry), jnp.full_like(expiry, EMPTY_EXPIRY)
    )
    state = jnp.where(expired[:, None], empty_rows, state)
    return state, expired[:capacity]
