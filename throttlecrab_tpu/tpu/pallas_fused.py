"""Fused Pallas decision kernel: one launch per decision window.

The composed-XLA decision window (kernel.py `gcra_scan_packed_*`) is a
chain of 5+ XLA ops per sub-batch — request unpack, row gather, the
GCRA closed forms, output pack, row scatter — each materializing its
intermediates to HBM before the next op starts.  This module fuses the
ENTIRE per-window decision into a single `pallas_call`: the grid walks
the K sub-batches in order (the table state is the carried buffer, via
input/output aliasing), each grid step unpacks its `PACK_WIDTH`-wide
request rows from VMEM, pulls the per-slot state rows out of the
HBM-resident table through a RING-deep async-DMA pipeline, evaluates
the closed forms (main prefix + degenerate three-view orbit) entirely
in VPU registers, packs the wire outputs, and streams the surviving
rows back with a second DMA ring at unique indices.  No intermediate
ever round-trips HBM and the host dispatches ONE launch per window.

This is a *different thesis* from the retired row-movement kernels in
pallas_ops.py.  Those moved rows for a body that still ran as composed
XLA — and the on-device ablation showed row movement within noise
*inside one fused XLA computation*, so they were a no-go.  What that
ablation never measured is the cost attacked here: the inter-op HBM
round trips and the per-op dispatch overhead of the composed graph.
Their hard-won lowering lessons carry forward regardless: every loop
scalar is pinned to i32 (jax x64 makes Mosaic's scalar conversion
helper recurse on i64 induction variables), and serving batches arrive
padded to at least the ring depth (limiter MIN_PAD).

i64 math on 32-bit lanes
========================

TPU vector lanes are 32-bit; the i64 TAT/tolerance arithmetic is
therefore decomposed into (lo, hi) i32 pairs — the exact split the
packed table rows and request rows already store (kernel.pack_state /
pack_requests).  The helpers below reproduce the `sat.py` saturating
discipline bit-for-bit on pairs: wrapping pair add/sub with explicit
carries, the sign-pattern overflow clamps of `sat_add`/`sat_sub`, the
2-op nonneg forms of the certified fast path, a widening 32x32
multiply that powers both the wrapping i64 product and the
`sat_mul_nonneg` overflow probe (the 128-bit high half replaces the
hidden i64 division of XLA's probe), and a restoring 64-step long
division for the two closed-form quotients (`m_raw`, `remaining`) and
the whole-second wire fields.  Unsigned compares ride the usual
sign-bias trick (`x ^ 0x8000_0000` then signed compare).

Width polymorphism and the mesh
===============================

The kernel is a static `row_width ∈ {4, INS_WIDTH}` template: the
6-wide instantiation folds the denied-hit counter into the same row
DMAs (the counter columns advance at each segment's is_last lane,
exactly like the XLA `_finish` ins_row), so `THROTTLECRAB_INSIGHT=1`
and Pallas coexist — the insight→Pallas downgrade of the legacy row
kernels does not apply here.  `fused_window` is plain traceable JAX,
so `ShardedBucketTable`'s shard-mapped bodies call it per shard: each
device runs the identical fused program on its slice and the per-launch
counter psums are untouched.

Enable with THROTTLECRAB_PALLAS_FUSED=1 (read per dispatch on the
host, so the composed-XLA path stays the default and the kill switch).
Off-TPU the kernel runs in interpret mode — bit-exact, which is what
the differential tests pin, but orders of magnitude slower than the
compiled XLA path; interpret-mode numbers are excluded from benchmark
measurement (docs/benchmark-results.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kernel import (
    INS_WIDTH,
    PACK_FLAG_IS_LAST,
    PACK_FLAG_VALID,
    PACK_WIDTH,
    _insight_totals,
)

RING = 16  # row DMAs kept in flight per direction (gather / scatter)

_I32_MAX = (1 << 31) - 1
_NS_PER_SEC = 1_000_000_000
_SIGN = -(1 << 31)  # i32 sign bit, for the unsigned-compare bias trick


# The enable check deliberately does NOT live here: the dispatchers
# (table._fused_enabled, sharded._step/_scan_step) call
# kernel.pallas_fused_enabled, so the kill-switch read never pays this
# module's jax.experimental.pallas imports.  Flipping the env between
# launches takes effect immediately — the composed-XLA twins and the
# fused wrappers are separate jit entry points, never a traced branch.

# --------------------------------------------------------------------- #
# i64-as-(lo, hi) i32 pair arithmetic.
#
# A "pair" is a (lo, hi) tuple of i32 arrays: lo carries the low 32
# bits (as raw bits in a signed carrier), hi the high 32 (signed).
# Every helper mirrors one XLA i64 op from kernel.py/sat.py and is
# pinned bit-identical by tests/test_pallas_fused.py's property sweep.
# The raw `+ - * <<` below are the POINT: deliberately wrapping 32-bit
# half-word steps of exact 64-bit arithmetic, never i64 value math.
# --------------------------------------------------------------------- #


def _const64(v: int):
    """Python int (i64 range) -> constant pair.

    Components stay PYTHON ints (weakly-typed literals): a pallas
    kernel body may not capture array constants, and a literal mixed
    into any i32 array op inlines at i32 for free."""
    lo = v & 0xFFFFFFFF
    if lo >= 1 << 31:
        lo -= 1 << 32
    hi = (v >> 32) & 0xFFFFFFFF
    if hi >= 1 << 31:
        hi -= 1 << 32
    return lo, hi


_ZERO64 = _const64(0)
_ONE64 = _const64(1)
_I64MAX = _const64((1 << 63) - 1)
_I64MIN = _const64(-(1 << 63))
_EMPTY_EXPIRY64 = _I64MIN  # kernel.EMPTY_EXPIRY == i64::MIN


def _shrl(x, s):
    """Logical (zero-fill) right shift on the i32 bit carrier."""
    x = jnp.asarray(x)
    return lax.shift_right_logical(
        x, jnp.broadcast_to(jnp.asarray(s, x.dtype), x.shape)
    )


def _ult(a, b):
    """Unsigned 32-bit a < b on i32 carriers (sign-bias trick)."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def _add64(a, b):
    lo = a[0] + b[0]  # inv: allow(i64-raw-op)
    carry = _ult(lo, a[0]).astype(jnp.int32)
    return lo, a[1] + b[1] + carry  # inv: allow(i64-raw-op)


def _sub64(a, b):
    borrow = _ult(a[0], b[0]).astype(jnp.int32)
    return a[0] - b[0], a[1] - b[1] - borrow  # inv: allow(i64-raw-op)


def _eq64(a, b):
    return (a[0] == b[0]) & (a[1] == b[1])


def _lt64(a, b):
    """Signed 64-bit a < b."""
    return (a[1] < b[1]) | ((a[1] == b[1]) & _ult(a[0], b[0]))


def _le64(a, b):
    return _lt64(a, b) | _eq64(a, b)


def _ult64(a, b):
    """Unsigned 64-bit a < b."""
    return _ult(a[1], b[1]) | ((a[1] == b[1]) & _ult(a[0], b[0]))


def _is_neg(a):
    return a[1] < 0


def _is_zero(a):
    return (a[0] == 0) & (a[1] == 0)


def _is_pos(a):
    return ~_is_neg(a) & ~_is_zero(a)


def _sel64(c, a, b):
    return jnp.where(c, a[0], b[0]), jnp.where(c, a[1], b[1])


def _max64(a, b):
    return _sel64(_lt64(a, b), b, a)


def _min64(a, b):
    return _sel64(_lt64(a, b), a, b)


def _sat_add64(a, b):
    """sat.sat_add on pairs."""
    s = _add64(a, b)
    pos_of = _is_pos(a) & _is_pos(b) & _is_neg(s)
    neg_of = _is_neg(a) & _is_neg(b) & ~_is_neg(s)
    return _sel64(pos_of, _I64MAX, _sel64(neg_of, _I64MIN, s))


def _sat_sub64(a, b):
    """sat.sat_sub on pairs."""
    d = _sub64(a, b)
    pos_of = ~_is_neg(a) & _is_neg(b) & _is_neg(d)
    neg_of = _is_neg(a) & _is_pos(b) & ~_is_neg(d)
    return _sel64(pos_of, _I64MAX, _sel64(neg_of, _I64MIN, d))


def _sat_add_nn64(a, b):
    """sat.sat_add_nn on pairs (b >= 0: overflow iff s < a)."""
    s = _add64(a, b)
    return _sel64(_lt64(s, a), _I64MAX, s)


def _sat_sub_nn64(a, b):
    """sat.sat_sub_nn on pairs (b >= 0: overflow iff d > a)."""
    d = _sub64(a, b)
    return _sel64(_lt64(a, d), _I64MIN, d)


def _umul32(a, b):
    """Widening 32x32 -> 64 multiply (unsigned interpretation of the
    i32 bit carriers), as a pair.  16-bit half products; every partial
    is exact because (2^16-1)^2 < 2^32."""
    a0 = a & 0xFFFF
    a1 = _shrl(a, 16)
    b0 = b & 0xFFFF
    b1 = _shrl(b, 16)
    ll = a0 * b0  # inv: allow(i64-raw-op)
    mid1 = a0 * b1  # inv: allow(i64-raw-op)
    mid = mid1 + a1 * b0  # inv: allow(i64-raw-op)
    midc = _ult(mid, mid1).astype(jnp.int32)
    lo = ll + (mid << 16)  # inv: allow(i64-raw-op)
    k = _ult(lo, ll).astype(jnp.int32)
    hi = (
        a1 * b1 + _shrl(mid, 16) + (midc << 16) + k  # inv: allow(i64-raw-op)
    )
    return lo, hi


def _mul64_lo(a, b):
    """Wrapping i64 multiply on pairs (the certified fast path's plain
    product — the host certificate rules overflow out)."""
    lo, hi = _umul32(a[0], b[0])
    hi = hi + a[0] * b[1] + a[1] * b[0]  # inv: allow(i64-raw-op)
    return lo, hi


def _sat_mul_nonneg64(a, b):
    """sat.sat_mul_nonneg on pairs (operands >= 0 on every live lane,
    the only case GCRA needs — same contract as the XLA helper).

    XLA's overflow probe `a > I64_MAX // max(b, 1)` hides an i64
    division; for a, b >= 0 it is exactly `a*b >= 2^63`, read here off
    the 128-bit product: any nonzero contribution to the high 64 bits,
    or the sign bit of the low 64.
    """
    pll = _umul32(a[0], b[0])
    plh = _umul32(a[0], b[1])
    phl = _umul32(a[1], b[0])
    phh = _umul32(a[1], b[1])
    mid = _add64(plh, phl)
    cmid = _ult64(mid, plh)
    lo_hi = pll[1] + mid[0]  # inv: allow(i64-raw-op)
    k = _ult(lo_hi, pll[1])
    overflow = (
        (phh[0] != 0)
        | (phh[1] != 0)
        | cmid
        | (mid[1] != 0)
        | k
        | (lo_hi < 0)
    )
    return _sel64(overflow, _I64MAX, (pll[0], lo_hi))


def _udiv64(num, den):
    """Unsigned 64 / 64 restoring long division on pairs; den >= 1
    (callers clamp).  64 shift-compare-subtract rounds in a fori_loop —
    every loop scalar i32 (the pallas_ops lowering lesson).  Covers all
    kernel quotients: both closed-form divisions take nonneg operands
    after their max(.., 0) guards, matching lax.div's trunc-toward-zero
    there, and the whole-second wire fields divide nonneg ns values."""
    i32 = jnp.int32

    def body(i, carry):
        rlo, rhi, qlo, qhi = carry
        s = i32(63) - i
        bit = (
            jnp.where(
                s >= 32,
                _shrl(num[1], jnp.maximum(s - i32(32), 0)),
                _shrl(num[0], jnp.minimum(s, i32(31))),
            )
            & 1
        )
        rhi = (rhi << 1) | _shrl(rlo, 31)  # inv: allow(i64-raw-op)
        rlo = (rlo << 1) | bit  # inv: allow(i64-raw-op)
        ge = ~_ult64((rlo, rhi), den)
        nlo, nhi = _sub64((rlo, rhi), den)
        rlo = jnp.where(ge, nlo, rlo)
        rhi = jnp.where(ge, nhi, rhi)
        qhi = (qhi << 1) | _shrl(qlo, 31)  # inv: allow(i64-raw-op)
        qlo = (qlo << 1) | ge.astype(i32)  # inv: allow(i64-raw-op)
        return rlo, rhi, qlo, qhi

    z = jnp.zeros_like(num[0])
    _, _, qlo, qhi = lax.fori_loop(i32(0), i32(64), body, (z, z, z, z))
    return qlo, qhi


def _div_nonneg(num, den_raw):
    """max(div_trunc(num, den_raw), 0) on pairs — the exact shape both
    closed-form quotients take in kernel.py: negative numerators clamp
    to 0 (trunc toward zero then max), den_raw <= 0 divides by 1."""
    q = _udiv64(num, _max64(den_raw, _ONE64))
    return _sel64(_is_neg(num), _ZERO64, q)


def _clamp_i32(p):
    """jnp.minimum(x, i32::MAX).astype(int32) for nonneg pair x."""
    return jnp.where((p[1] != 0) | (p[0] < 0), jnp.int32(_I32_MAX), p[0])


def _div_sec_lo(p):
    """(nonneg ns pair // 1e9) low word — the wire seconds fields."""
    return _udiv64(p, _const64(_NS_PER_SEC))


# --------------------------------------------------------------------- #
# The GCRA closed forms on pairs: a lockstep transcription of
# kernel._gcra_body (+ its _finish / _request_outputs) with every i64
# op replaced by its pair twin.  Pure traced JAX over [B] vectors — the
# pallas kernel body calls it on VMEM-resident data, and the tests call
# it directly to pin it against the XLA body outside pallas too.
# --------------------------------------------------------------------- #


def _gcra_pairs(rows, packed, now, *, width, with_degen, compact):
    """Decide one sub-batch from gathered rows.

    Args:
      rows:   i32[B, width] gathered state rows.
      packed: i32[B, PACK_WIDTH] request rows (kernel.pack_requests).
      now:    scalar pair (the sub-batch server timestamp).

    Returns (rows_out i32[B, width], outs, n_exp i32 scalar) where
    `outs` is a tuple of i32 arrays per `compact`:
      False -> (lo[4, B], hi[4, B])   i64 ns planes, join outside
      True  -> (planes[4, B],)        exact i32 wire planes
      "cur" -> (lo[B], hi[B])         cur*2+allowed words, join outside
      "w32" -> (words[B],)            device-packed 4-byte wire words
    """
    rank = packed[:, 1]
    flags = packed[:, 2]
    is_last = (flags & PACK_FLAG_IS_LAST) != 0
    v = (flags & PACK_FLAG_VALID) != 0
    em = (packed[:, 3], packed[:, 4])
    tol = (packed[:, 5], packed[:, 6])
    q = (packed[:, 7], packed[:, 8])
    stored_tat = (rows[:, 0], rows[:, 1])
    stored_exp = (rows[:, 2], rows[:, 3])
    ins = width > 4
    live = v & _lt64(now, stored_exp)  # stored_exp > now

    if with_degen:
        s_add, s_sub, s_mul = _sat_add64, _sat_sub64, _sat_mul_nonneg64
    else:
        s_add, s_sub, s_mul = _sat_add_nn64, _sat_sub_nn64, _mul64_lo

    inc = s_mul(em, q)
    t0 = _sel64(
        live, _max64(stored_tat, s_sub(now, tol)), s_sub(now, em)
    )

    # ---- main case: prefix closed form (num stays general-saturating,
    # burst_limit stays wrapping — kernel.py documents both) ----------- #
    rank1 = (rank + 1, jnp.zeros_like(rank))
    num = _sat_sub64(s_add(now, tol), t0)
    m_raw = _div_nonneg(num, inc)
    allowed_main = _lt64((rank, jnp.zeros_like(rank)), m_raw)
    new_tat_r = s_add(t0, s_mul(rank1, inc))
    tat_denied = s_add(t0, s_mul(m_raw, inc))
    cur_main = _sel64(allowed_main, new_tat_r, tat_denied)
    tat_fin_main = s_add(t0, s_mul(_min64(m_raw, rank1), inc))

    burst_limit = _add64(now, tol)
    room_main = _sat_sub64(burst_limit, cur_main)
    remaining_main = _sel64(
        _is_pos(em), _div_nonneg(room_main, em), _ZERO64
    )
    reset_main = _max64(s_add(s_sub(cur_main, now), tol), _ZERO64)
    retry_main = _sel64(
        allowed_main,
        _ZERO64,
        _max64(s_sub(s_sub(s_add(cur_main, inc), tol), now), _ZERO64),
    )

    exp_hit_base = (
        v
        & (rank == 0)
        & ~_eq64(stored_exp, _EMPTY_EXPIRY64)
        & _le64(stored_exp, now)
    )

    if not with_degen:
        allowed_out = allowed_main & v
        remaining_out, reset_out, retry_out = (
            remaining_main, reset_main, retry_main,
        )
        wrote = _lt64(_ZERO64, m_raw) & v & is_last
        tat_fin = tat_fin_main
        cur_out = cur_main
        n_exp_mask = exp_hit_base & allowed_main
        if ins:
            seg_n = rank1
            denied_seg = _sub64(seg_n, _min64(m_raw, seg_n))
    else:
        # ---- degenerate case: three-view closed form ----------------- #
        degen = _is_zero(inc) | _is_zero(tol)

        def request_outputs(t):
            new_tat = _sat_add64(t, inc)
            allow_at = _sat_sub64(new_tat, tol)
            allowed = _le64(allow_at, now)
            cur = _sel64(allowed, new_tat, t)
            room = _sat_sub64(burst_limit, cur)
            remaining = _sel64(
                _is_pos(em), _div_nonneg(room, em), _ZERO64
            )
            reset = _max64(
                _sat_add64(_sat_sub64(cur, now), tol), _ZERO64
            )
            retry = _sel64(
                allowed,
                _ZERO64,
                _max64(_sat_sub64(allow_at, now), _ZERO64),
            )
            ttl = _sat_add64(_sat_sub64(new_tat, now), tol)
            return allowed, remaining, reset, retry, new_tat, ttl

        def view_step(t):
            outs = request_outputs(t)
            allowed_t, _, _, _, new_t, ttl_t = outs
            dead = allowed_t & _is_zero(ttl_t)
            t_next = _sel64(
                ~allowed_t,
                t,
                _sel64(
                    dead,
                    _sat_sub64(now, em),
                    _max64(new_t, _sat_sub64(now, tol)),
                ),
            )
            return outs, t_next

        outs0, v1 = view_step(t0)
        outs1, v2 = view_step(v1)
        outs2, _ = view_step(v2)
        a0, a1, a2 = outs0[0], outs1[0], outs2[0]
        # alternating/tail only reach the output for rank >= 2, so the
        # (rank-1)&1 parity equals the XLA (rank-1)%2 there.
        alt_even = ((rank - 1) & 1) == 0

        def pick(sel, main, o0, o1, o2):
            alternating = sel(alt_even, o1, o2)
            tail = sel(rank == 1, o1, sel(a2, alternating, o2))
            degen_out = sel(
                ~a0,
                o0,
                sel(
                    ~a1,
                    sel(rank == 0, o0, o1),
                    sel(rank == 0, o0, tail),
                ),
            )
            return sel(degen, degen_out, main)

        allowed_out = (
            pick(jnp.where, allowed_main, a0, a0 & a1, a0 & a1 & a2) & v
        )
        remaining_out = pick(
            _sel64, remaining_main, outs0[1], outs1[1], outs2[1]
        )
        reset_out = pick(_sel64, reset_main, outs0[2], outs1[2], outs2[2])
        retry_out = pick(_sel64, retry_main, outs0[3], outs1[3], outs2[3])

        new0_t, new1_t, new2_t = outs0[4], outs1[4], outs2[4]
        alt_last = _sel64(alt_even, new1_t, new2_t)
        tat_fin_degen = _sel64(
            (rank == 0) | ~a1,
            new0_t,
            _sel64(~a2 | (rank == 1), new1_t, alt_last),
        )
        wrote = (
            jnp.where(degen, a0, _lt64(_ZERO64, m_raw)) & v & is_last
        )
        tat_fin = _sel64(degen, tat_fin_degen, tat_fin_main)
        cur_out = None
        n_exp_mask = exp_hit_base & allowed_out
        if ins:
            seg_n = rank1
            allowed_cnt_main = _min64(m_raw, seg_n)
            two = _const64(2)
            allowed_cnt_degen = _sel64(
                ~a0,
                _ZERO64,
                _sel64(
                    ~a1,
                    _ONE64,
                    _sel64(~a2, _min64(seg_n, two), seg_n),
                ),
            )
            denied_seg = _sub64(
                seg_n, _sel64(degen, allowed_cnt_degen, allowed_cnt_main)
            )

    # ---- write-back (kernel._finish) --------------------------------- #
    ttl_fin = s_add(s_sub(tat_fin, now), tol)
    expiry_fin = _sel64(
        _is_neg(ttl_fin), _I64MAX, s_add(tat_fin, tol)
    )
    tat_w = _sel64(wrote, tat_fin, stored_tat)
    exp_w = _sel64(wrote, expiry_fin, stored_exp)
    cols = [tat_w[0], tat_w[1], exp_w[0], exp_w[1]]
    if ins:
        stored_deny = (rows[:, 4], rows[:, 5])
        deny_new = _add64(stored_deny, denied_seg)
        cols += [deny_new[0], deny_new[1]]
    rows_out = jnp.stack(cols, axis=-1)

    if compact == "cur":
        assert cur_out is not None, 'compact="cur" requires with_degen=False'
        wlo = (cur_out[0] << 1) | allowed_out.astype(  # inv: allow(i64-raw-op)
            jnp.int32
        )
        whi = (cur_out[1] << 1) | _shrl(  # inv: allow(i64-raw-op)
            cur_out[0], 31
        )
        outs = (wlo, whi)
    elif compact == "w32":
        assert cur_out is not None, 'compact="w32" requires with_degen=False'
        outs = (
            allowed_out.astype(jnp.int32)
            | (remaining_out[0] << 1)  # inv: allow(i64-raw-op)
            | (_div_sec_lo(reset_out)[0] << 11)  # inv: allow(i64-raw-op)
            | (_div_sec_lo(retry_out)[0] << 22),  # inv: allow(i64-raw-op)
        )
    elif compact:
        outs = (
            jnp.stack(
                [
                    allowed_out.astype(jnp.int32),
                    _clamp_i32(remaining_out),
                    _clamp_i32(_div_sec_lo(reset_out)),
                    _clamp_i32(_div_sec_lo(retry_out)),
                ]
            ),
        )
    else:
        z = jnp.zeros_like(rank)
        outs = (
            jnp.stack(
                [
                    allowed_out.astype(jnp.int32),
                    remaining_out[0],
                    reset_out[0],
                    retry_out[0],
                ]
            ),
            jnp.stack([z, remaining_out[1], reset_out[1], retry_out[1]]),
        )
    n_exp = jnp.sum(n_exp_mask, dtype=jnp.int32)
    return rows_out, outs, n_exp


# --------------------------------------------------------------------- #
# The pallas kernel: DMA rings around _gcra_pairs, one grid step per
# sub-batch, the table buffer carried across steps via aliasing.
# --------------------------------------------------------------------- #


def _dma_ring(n, copy):
    """Issue `n` row DMAs through a RING-deep in-flight window (the
    pallas_ops start/wait/drain discipline, all scalars i32)."""
    i32 = jnp.int32

    def body(i, _):
        @pl.when(i >= RING)
        def _():
            copy(i - i32(RING)).wait()

        copy(i).start()
        return i32(0)

    lax.fori_loop(i32(0), i32(n), body, i32(0))

    def drain(i, _):
        copy(i32(max(n - RING, 0)) + i).wait()
        return i32(0)

    lax.fori_loop(i32(0), i32(min(RING, n)), drain, i32(0))


def _make_kernel(B, width, with_degen, compact, n_out):
    def kernel(gs_ref, now_ref, packed_ref, state_in_ref, st_out, *rest):
        outs_refs = rest[:n_out]
        nexp_ref = rest[n_out]
        rows, rows_out, gsem, ssem = rest[n_out + 1:]
        del state_in_ref  # aliased with st_out; all access goes there
        k = pl.program_id(0)
        base = k * jnp.int32(B)

        def gcopy(i):
            return pltpu.make_async_copy(
                st_out.at[gs_ref[0, base + i]], rows.at[i], gsem.at[i % RING]
            )

        _dma_ring(B, gcopy)

        now = (now_ref[k, 0], now_ref[k, 1])
        new_rows, outs, n_exp = _gcra_pairs(
            rows[:],
            packed_ref[0],
            now,
            width=width,
            with_degen=with_degen,
            compact=compact,
        )
        rows_out[:] = new_rows
        for ref, val in zip(outs_refs, outs):
            ref[0] = val
        nexp_ref[0, 0] = n_exp

        def scopy(i):
            return pltpu.make_async_copy(
                rows_out.at[i], st_out.at[gs_ref[1, base + i]], ssem.at[i % RING]
            )

        _dma_ring(B, scopy)

    return kernel


def _join64(lo, hi):
    return (hi.astype(jnp.int64) << 32) | (  # inv: allow(i64-raw-op)
        lo.astype(jnp.int64) & 0xFFFFFFFF
    )


def fused_window(state, packed, now, *, with_degen=True, compact=False):
    """Decide one K-deep window in ONE fused launch (traceable JAX).

    Semantically identical to kernel.gcra_scan_packed + the expired-hit
    count of the *_acc twins: `state` is the i32[N, W] packed table
    (W in {4, INS_WIDTH}; the 6-wide template maintains the denied-hit
    columns in the same row traffic), `packed` is i32[K, B, PACK_WIDTH],
    `now` i64[K].  Returns (state, out, n_exp i64[K]) with `out` shaped
    exactly like the XLA twin's for the given `compact`.

    Callable from jit and from shard_map bodies (ShardedBucketTable) —
    each shard then runs the identical fused program on its slice.
    """
    state = jnp.asarray(state)
    packed = jnp.asarray(packed, jnp.int32)
    K, B, _pw = packed.shape
    N, width = state.shape
    assert _pw == PACK_WIDTH
    assert width in (4, INS_WIDTH)

    slots = packed[..., 0]
    flags = packed[..., 2]
    gather = jnp.clip(slots, 0, N - 1).astype(jnp.int32)
    # Suppressed-write lanes land in the scratch tail at distinct
    # indices (the same rows the XLA _finish uses), keeping the
    # unique-indices contract; real-slot rows whose GCRA write is
    # suppressed get their gathered bytes streamed back verbatim —
    # bit-identical state, no data-dependent DMA addressing.
    write_lane = ((flags & PACK_FLAG_IS_LAST) != 0) & (
        (flags & PACK_FLAG_VALID) != 0
    )
    scratch = (N - B + jnp.arange(B, dtype=jnp.int32))[None, :]
    scatter = jnp.where(write_lane, gather, scratch)
    gs = jnp.stack([gather.reshape(-1), scatter.reshape(-1)])
    now = jnp.asarray(now, jnp.int64)
    nows = jnp.stack(
        [
            (now & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32),
            (now >> 32).astype(jnp.int32),
        ],
        axis=-1,
    )

    if compact == "cur":
        out_shapes = [
            jax.ShapeDtypeStruct((K, B), jnp.int32),
            jax.ShapeDtypeStruct((K, B), jnp.int32),
        ]
        out_block = pl.BlockSpec((1, B), lambda k, *_: (k, 0))
    elif compact == "w32":
        out_shapes = [jax.ShapeDtypeStruct((K, B), jnp.int32)]
        out_block = pl.BlockSpec((1, B), lambda k, *_: (k, 0))
    elif compact:
        out_shapes = [jax.ShapeDtypeStruct((K, 4, B), jnp.int32)]
        out_block = pl.BlockSpec((1, 4, B), lambda k, *_: (k, 0, 0))
    else:
        out_shapes = [
            jax.ShapeDtypeStruct((K, 4, B), jnp.int32),
            jax.ShapeDtypeStruct((K, 4, B), jnp.int32),
        ]
        out_block = pl.BlockSpec((1, 4, B), lambda k, *_: (k, 0, 0))
    n_out = len(out_shapes)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(K,),
        in_specs=[
            pl.BlockSpec((1, B, PACK_WIDTH), lambda k, *_: (k, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            *([out_block] * n_out),
            pl.BlockSpec(
                (1, 1), lambda k, *_: (k, 0), memory_space=pltpu.SMEM
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, width), jnp.int32),
            pltpu.VMEM((B, width), jnp.int32),
            pltpu.SemaphoreType.DMA((RING,)),
            pltpu.SemaphoreType.DMA((RING,)),
        ],
    )
    res = pl.pallas_call(
        _make_kernel(B, width, with_degen, compact, n_out),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(state.shape, state.dtype),
            *out_shapes,
            jax.ShapeDtypeStruct((K, 1), jnp.int32),
        ),
        # Operand indices include the 2 scalar-prefetch args:
        # 0 = gs, 1 = nows, 2 = packed, 3 = state -> state aliases
        # output 0, so the table is updated in place launch after
        # launch exactly like the donated XLA twins.
        input_output_aliases={3: 0},
        interpret=jax.default_backend() != "tpu",
    )(gs, nows, packed, state)
    state = res[0]
    nexp = res[-1][:, 0].astype(jnp.int64)
    if compact == "cur":
        out = _join64(res[1], res[2])
    elif compact == "w32" or compact:
        out = res[1]
    else:
        out = _join64(res[1], res[2])
    return state, out, nexp


# --------------------------------------------------------------------- #
# Jitted drop-in twins for the kernel.py entry points BucketTable
# dispatches through (gcra_batch/scan/scan_packed _acc and _ins).
# --------------------------------------------------------------------- #


def pack_requests_traced(slots, rank, is_last, emission, tolerance,
                          quantity, valid):
    """kernel.pack_requests as traced jnp (device-side packing for the
    unpacked entry points and the shard-mapped bodies)."""
    def split(x):
        x = jnp.asarray(x, jnp.int64)
        lo = (x & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32)
        return lo, (x >> 32).astype(jnp.int32)

    flags = (
        jnp.asarray(is_last, jnp.int32) * PACK_FLAG_IS_LAST
        + jnp.asarray(valid, jnp.int32) * PACK_FLAG_VALID
    )
    em_lo, em_hi = split(emission)
    tol_lo, tol_hi = split(tolerance)
    q_lo, q_hi = split(quantity)
    return jnp.stack(
        [
            jnp.asarray(slots, jnp.int32),
            jnp.asarray(rank, jnp.int32),
            flags,
            em_lo, em_hi, tol_lo, tol_hi, q_lo, q_hi,
        ],
        axis=-1,
    )


@functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_packed_fused_acc(
    state, exp_acc, packed, now, *, with_degen=True, compact=False
):
    """Fused twin of kernel.gcra_scan_packed_acc."""
    state, out, nexp = fused_window(
        state, packed, now, with_degen=with_degen, compact=compact
    )
    return state, exp_acc + jnp.sum(nexp), out


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("with_degen", "compact"),
)
def gcra_scan_packed_fused_ins(
    state, exp_acc, ins_counts, packed, now, *, with_degen=True,
    compact=False,
):
    """Fused twin of kernel.gcra_scan_packed_ins (INS_WIDTH rows)."""
    packed = jnp.asarray(packed, jnp.int32)
    state, out, nexp = fused_window(
        state, packed, now, with_degen=with_degen, compact=compact
    )
    ins_counts = _insight_totals(
        ins_counts, (packed[..., 2] & PACK_FLAG_VALID) != 0, out, compact
    )
    return state, exp_acc + jnp.sum(nexp), ins_counts, out


@functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_scan_fused_acc(
    state, exp_acc, slots, rank, is_last, emission, tolerance, quantity,
    valid, now, *, with_degen=True, compact=False,
):
    """Fused twin of kernel.gcra_scan_acc ([K, B] unpacked inputs)."""
    packed = pack_requests_traced(
        slots, rank, is_last, emission, tolerance, quantity, valid
    )
    state, out, nexp = fused_window(
        state, packed, now, with_degen=with_degen, compact=compact
    )
    return state, exp_acc + jnp.sum(nexp), out


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("with_degen", "compact"),
)
def gcra_scan_fused_ins(
    state, exp_acc, ins_counts, slots, rank, is_last, emission, tolerance,
    quantity, valid, now, *, with_degen=True, compact=False,
):
    """Fused twin of kernel.gcra_scan_ins."""
    packed = pack_requests_traced(
        slots, rank, is_last, emission, tolerance, quantity, valid
    )
    state, out, nexp = fused_window(
        state, packed, now, with_degen=with_degen, compact=compact
    )
    ins_counts = _insight_totals(
        ins_counts, jnp.asarray(valid, bool), out, compact
    )
    return state, exp_acc + jnp.sum(nexp), ins_counts, out


@functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("with_degen", "compact")
)
def gcra_batch_fused_acc(
    state, exp_acc, slots, rank, is_last, emission, tolerance, quantity,
    valid, now, *, with_degen=True, compact=False,
):
    """Fused twin of kernel.gcra_batch_acc (single sub-batch)."""
    packed = pack_requests_traced(
        slots, rank, is_last, emission, tolerance, quantity, valid
    )[None]
    state, out, nexp = fused_window(
        state,
        packed,
        jnp.reshape(jnp.asarray(now, jnp.int64), (1,)),
        with_degen=with_degen,
        compact=compact,
    )
    return state, exp_acc + jnp.sum(nexp), out[0]


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("with_degen", "compact"),
)
def gcra_batch_fused_ins(
    state, exp_acc, ins_counts, slots, rank, is_last, emission, tolerance,
    quantity, valid, now, *, with_degen=True, compact=False,
):
    """Fused twin of kernel.gcra_batch_ins."""
    packed = pack_requests_traced(
        slots, rank, is_last, emission, tolerance, quantity, valid
    )[None]
    state, out, nexp = fused_window(
        state,
        packed,
        jnp.reshape(jnp.asarray(now, jnp.int64), (1,)),
        with_degen=with_degen,
        compact=compact,
    )
    out = out[0]
    ins_counts = _insight_totals(
        ins_counts, jnp.asarray(valid, bool), out, compact
    )
    return state, exp_acc + jnp.sum(nexp), ins_counts, out
