"""TPU execution backend: batched GCRA kernels over an HBM bucket table."""

from .keymap import PyKeyMap
from .kernel import EMPTY_EXPIRY, gcra_batch, sweep_expired
from .limiter import (
    STATUS_INVALID_PARAMS,
    STATUS_NEGATIVE_QUANTITY,
    STATUS_OK,
    BatchResult,
    TpuRateLimiter,
    derive_params,
)
from .table import BucketTable

__all__ = [
    "BatchResult",
    "BucketTable",
    "EMPTY_EXPIRY",
    "PyKeyMap",
    "STATUS_INVALID_PARAMS",
    "STATUS_NEGATIVE_QUANTITY",
    "STATUS_OK",
    "TpuRateLimiter",
    "derive_params",
    "gcra_batch",
    "sweep_expired",
]
