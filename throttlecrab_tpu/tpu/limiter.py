"""TpuRateLimiter: the batched, TPU-backed rate-limiting engine.

The TPU-native equivalent of `RateLimiter<S: Store>` (`rate_limiter.rs:42-58`)
plus the actor's serialized hot loop: requests arrive as whole batches,
string keys are resolved to table slots on the host, GCRA parameters are
derived with the reference's exact f64 pipeline, and all decisions execute in
one jitted device kernel against the HBM bucket table.

Exactness notes vs the scalar oracle (core/rate_limiter.py):

- Per-request validation errors (negative quantity / non-positive params) are
  reported in `BatchResult.status` instead of raising, since one bad request
  must not fail its batchmates (each transport maps status → its protocol
  error, like the reference server does per request).
- Duplicate keys in one batch are serialized with exact arrival-order
  semantics (see kernel.py).  A key whose *parameters change mid-batch* is
  split into consecutive param-runs processed as sub-rounds, preserving
  order.
- `now_ns` is a single server-side timestamp per batch (the reference server
  also stamps every request at the transport, `http.rs:127-128`).  The
  scalar-compat wrapper applies the pre-epoch clock-skew fallback per call.
- Emission intervals are clamped to i64::MAX ns (~292 years); the reference
  wraps them to negative i64 through `as_nanos() as i64` in that range.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..core.errors import InternalError, InvalidRateLimit, NegativeQuantity
from ..core.rate_limiter import RateLimitResult, normalize_now_ns
from ..faults import maybe_fail
from .keymap import PyKeyMap
from .table import BucketTable


def _native_available() -> bool:
    from ..native import native_available

    return native_available()

I64_MAX = (1 << 63) - 1

STATUS_OK = 0
STATUS_NEGATIVE_QUANTITY = 1
STATUS_INVALID_PARAMS = 2
STATUS_INTERNAL = 3
# 4 is the front tier's STATUS_OVERLOADED (front/admission.py).
# A NEW key refused by its tenant's slot-capacity quota (the sharded
# limiter's namespace layer, parallel/tenants.py); the tenant's
# existing keys keep deciding normally.
STATUS_TENANT_QUOTA = 5
# Request outlived its client deadline: shed host-side before device
# dispatch (server/engine.py) or at a cluster hop (parallel/cluster.py).
# Like 3/4, excluded from replay differentials — load-dependent, not a
# GCRA outcome.
STATUS_DEADLINE = 6


def segment_info(slots, mask):
    """Per-request duplicate-key structure for the kernel.

    For each masked-in request: `rank` = its key's occurrence number within
    the batch, `is_last` = whether it is the key's final occurrence.  One
    dict pass on the host — the C++ keymap computes this for free during
    slot resolution.
    """
    n = len(slots)
    rank = np.zeros(n, np.int32)
    is_last = np.ones(n, bool)
    state: dict = {}
    for i in np.flatnonzero(mask):
        sl = int(slots[i])
        st = state.get(sl)
        if st is None:
            state[sl] = [1, i]
        else:
            rank[i] = st[0]
            st[0] += 1
            is_last[st[1]] = False
            st[1] = i
    return rank, is_last


@dataclass
class BatchResult:
    """Per-request outcomes of one batch (numpy arrays, length B).

    `cur_ns` (optional) is each request's exact observed TAT — new TAT
    for allowed rows, effective TAT for denied rows — populated when the
    launch rode the compact="cur" output tier with `collect_cur=True`.
    The front tier's deny cache certifies entries from it; None
    elsewhere (invalid lanes carry garbage: consumers must gate on
    status).
    """

    allowed: np.ndarray
    limit: np.ndarray
    remaining: np.ndarray
    reset_after_ns: np.ndarray
    retry_after_ns: np.ndarray
    status: np.ndarray
    cur_ns: Optional[np.ndarray] = None


@dataclass
class WireBatchResult:
    """Per-request outcomes in wire units, from the compact kernel output.

    reset_after_s / retry_after_s are whole seconds and remaining saturates
    at i32::MAX — exactly what every transport emits (the reference
    truncates Durations to seconds at the type boundary, types.rs:87-97,
    and its gRPC proto is int32, throttlecrab.proto:15-21).  Fetching i32
    seconds instead of i64 nanoseconds halves device→host bytes per
    decision.
    """

    allowed: np.ndarray
    limit: np.ndarray
    remaining: np.ndarray
    reset_after_s: np.ndarray
    retry_after_s: np.ndarray
    status: np.ndarray
    # Exact observed TATs when fetched through the cur tier with
    # collect_cur=True (see BatchResult.cur_ns); None otherwise.
    cur_ns: Optional[np.ndarray] = None


# Segment arithmetic in the fast path multiplies inc by at most the
# batch size (segment ranks); certifying inc * MAX_SEGMENT < 2^62 on the
# host lets the kernel use plain multiplies instead of saturating ones
# (each saturating multiply hides an i64 division for its overflow
# probe).  Derived from the table scratch bound — the hard cap on batch
# width and therefore on any segment rank.  native/keymap.cpp mirrors
# the same certificate (tk_prepare_batch); a test pins the two together.
MAX_SEGMENT = BucketTable.SCRATCH
_MUL_SAFE = float(1 << 62)


def has_degenerate(valid, emission, tolerance, quantity) -> bool:
    """True when any valid request needs the kernel's exact path:
    quantity-0 probes, burst-1 (tolerance 0), zero emission intervals, a
    wrapped-negative tolerance (the reference's truncating
    emission*(burst-1) product can wrap, rate_limiter.rs:122), or an
    increment big enough that segment arithmetic could overflow i64.
    When absent the engine compiles the degenerate machinery out AND
    swaps the general saturating ops for cheap certified forms
    (`with_degen=False`) — certified per batch, so correctness never
    depends on traffic shape."""
    big_inc = (
        emission.astype(np.float64)
        * np.maximum(quantity, 1).astype(np.float64)
        * float(MAX_SEGMENT)
        >= _MUL_SAFE
    )
    return bool(
        np.any(
            valid
            & (
                (emission == 0)
                | (tolerance <= 0)
                | (quantity == 0)
                | big_inc
            )
        )
    )


def prepare_batch(n, max_burst, count_per_period, period, quantity):
    """Broadcast request params to length n, validate, derive GCRA params.

    The shared prologue of every batch engine (single-device and sharded).
    Returns (max_burst, quantity, emission, tolerance, status, valid).
    """
    max_burst = np.broadcast_to(np.asarray(max_burst, np.int64), (n,))
    count_per_period = np.broadcast_to(
        np.asarray(count_per_period, np.int64), (n,)
    )
    period = np.broadcast_to(np.asarray(period, np.int64), (n,))
    quantity = np.broadcast_to(np.asarray(quantity, np.int64), (n,))

    status = np.zeros(n, np.uint8)
    emission, tolerance, invalid = derive_params(
        max_burst, count_per_period, period
    )
    status[invalid] = STATUS_INVALID_PARAMS
    status[quantity < 0] = STATUS_NEGATIVE_QUANTITY
    valid = status == STATUS_OK
    return max_burst, quantity, emission, tolerance, status, valid


def param_rounds(rounds, slots, positions, emission, tolerance, quantity):
    """Assign arrival-order param-run rounds into `rounds` at `positions`.

    Round r holds each key's r-th maximal run of identical (emission,
    tolerance, quantity), so processing rounds in order reproduces the
    reference's sequential per-request semantics when a key's parameters
    change mid-batch.
    """
    state: dict = {}
    for i in positions:
        sl = int(slots[i])
        p = (int(emission[i]), int(tolerance[i]), int(quantity[i]))
        st = state.get(sl)
        if st is None:
            state[sl] = [p, 0]
        elif st[0] == p:
            rounds[i] = st[1]
        else:
            st[0] = p
            st[1] += 1
            rounds[i] = st[1]
    return rounds


def limiter_uses_bytes_keys(limiter) -> bool:
    """Whether a limiter's host keymap stores bytes keys (native backend)
    or str keys (python backend).  Transports that receive raw bytes must
    match the identity str-keyed transports use, or one client key becomes
    two buckets.  Works across TpuRateLimiter (.keymap), the sharded
    limiter (._bytes_keys), and cluster wrappers (delegated _bytes_keys).
    """
    km = getattr(limiter, "keymap", None)
    if km is not None:
        return bool(getattr(km, "BYTES_KEYS", False))
    return bool(getattr(limiter, "_bytes_keys", False))


def sequential_fallback(batches, decide_fn, error_result_fn, wire,
                        **decide_kw):
    """Decide a rate_limit_many window batch-by-batch when the scan path
    cannot express it (a key changed parameters mid-batch — the multi-round
    sub-protocol interleaves with later sub-batches in ways one scan can't;
    rare, and exactness beats speed there).

    Errors are isolated per batch: earlier batches' decisions are already
    committed on-device and must still be delivered; later batches after a
    failure return all-internal-error results.
    """
    out = []
    failed = False
    for b in batches:
        if failed:
            out.append(error_result_fn(len(b[0]), wire=wire))
            continue
        try:
            out.append(decide_fn(*b, wire=wire, **decide_kw))
        except Exception:
            failed = True
            out.append(error_result_fn(len(b[0]), wire=wire))
    return out


class ScalarCompatMixin:
    """Scalar `rate_limit` (the reference library API) over a batch engine.

    Mirrors `RateLimiter::rate_limit` (`rate_limiter.rs:102-117`): raising
    validation errors, applying the pre-epoch clock-skew fallback, and
    unpacking the single-request batch result.
    """

    def rate_limit(
        self,
        key,
        max_burst: int,
        count_per_period: int,
        period: int,
        quantity: int,
        now_ns: int,
    ):
        if quantity < 0:
            raise NegativeQuantity(quantity)
        if max_burst <= 0 or count_per_period <= 0 or period <= 0:
            raise InvalidRateLimit()
        now_ns = normalize_now_ns(now_ns, period)
        res = self.rate_limit_batch(
            [key], [max_burst], [count_per_period], [period], [quantity], now_ns
        )
        return bool(res.allowed[0]), RateLimitResult(
            limit=int(res.limit[0]),
            remaining=int(res.remaining[0]),
            reset_after_ns=int(res.reset_after_ns[0]),
            retry_after_ns=int(res.retry_after_ns[0]),
        )


def derive_params(max_burst, count_per_period, period):
    """(emission_ns, tolerance_ns, invalid) via the reference f64 pipeline.

    Mirrors `rate/mod.rs:164-176` (f64 multiply/divide, truncating u64 cast)
    and `rate_limiter.rs:122` (tolerance = emission * ((burst-1) as u32),
    with the product truncated to 64 bits).
    """
    max_burst = np.asarray(max_burst, np.int64)
    count_per_period = np.asarray(count_per_period, np.int64)
    period = np.asarray(period, np.int64)

    invalid = (max_burst <= 0) | (count_per_period <= 0) | (period <= 0)
    safe_count = np.where(count_per_period == 0, 1, count_per_period)
    emission_f = period.astype(np.float64) * 1e9 / safe_count.astype(np.float64)
    with np.errstate(invalid="ignore"):
        # Out-of-range casts are overridden by the I64_MAX clamp below;
        # numpy's warning about them is noise.
        emission = np.where(
            emission_f >= float(1 << 63),
            I64_MAX,
            emission_f.astype(np.int64),
        )
    emission = np.where(emission < 0, 0, emission)

    b32 = (max_burst - 1).astype(np.uint64) & np.uint64(0xFFFFFFFF)
    # Deliberately WRAPPING u64 product (rate_limiter.rs:122 semantics).
    tolerance = (
        emission.astype(np.uint64) * b32  # inv: allow(i64-raw-op)
    ).astype(np.int64)
    return emission, tolerance, invalid


class _ReadyLaunch:
    """dispatch_many handle whose results are already on the host (empty
    windows and the sequential multi-round fallback)."""

    def __init__(self, results: list) -> None:
        self._results = results

    def fetch(self) -> list:
        return self._results


class _PendingLaunch:
    """An in-flight device launch; `.fetch()` blocks on the device output
    and distributes it into per-batch results.  Created by dispatch_many —
    the device is already executing (or queued behind the table-state
    dependency chain) by the time the caller holds this."""

    def __init__(
        self, out_dev, prepared, valid_s, wire, cur=False, w32=False
    ) -> None:
        self._out_dev = out_dev
        self._prepared = prepared
        self._valid_s = valid_s
        self._wire = wire
        self._cur = cur
        self._w32 = w32

    def fetch(self) -> list:
        maybe_fail("fetch")
        out = np.asarray(self._out_dev)
        wire = self._wire
        if self._cur:
            from .kernel import finish_cur
        if self._w32:
            from .kernel import finish_w32
        results = []
        for j, (n, slots, rank, is_last, emission, tolerance, quantity,
                valid, now_ns, max_burst, status) in enumerate(
            self._prepared
        ):
            cur_plane = None
            if self._w32:
                # 4 B/request "w32" fetch: the device packed the exact
                # wire values; unpack is shifts and masks.
                o = np.stack(finish_w32(out[j, :n]))
            elif self._cur:
                # 8 B/request "cur" fetch, host-finished to the exact
                # i32 wire planes (kernel.finish_cur).
                o = np.stack(
                    finish_cur(
                        out[j, :n], emission, tolerance, quantity, now_ns
                    )
                )
                # The word is cur*2 + allowed; the arithmetic shift
                # recovers the exact observed TAT (the deny cache's
                # certification input — free on this tier).
                cur_plane = out[j, :n] >> 1
            else:
                o = out[j, :, :n]
            mask = self._valid_s[j, :n]
            fields = dict(
                allowed=(o[0] != 0) & mask,
                limit=np.where(valid, max_burst, 0),
                remaining=np.where(mask, o[1], 0),
                status=status,
                cur_ns=cur_plane,
            )
            if wire:
                results.append(
                    WireBatchResult(
                        reset_after_s=np.where(mask, o[2], 0),
                        retry_after_s=np.where(mask, o[3], 0),
                        **fields,
                    )
                )
            else:
                results.append(
                    BatchResult(
                        reset_after_ns=np.where(mask, o[2], 0),
                        retry_after_ns=np.where(mask, o[3], 0),
                        **fields,
                    )
                )
        return results


class _PendingWireLaunch:
    """In-flight launch from dispatch_wire_window; .fetch() distributes
    the compact device output into per-frame WireBatchResults.

    Two device output formats (limiter picks at dispatch):
      - 4-plane compact i32[K, 4, B] (`finish=None`), or
      - compact="cur" i64[K, B] — 8 B/request instead of 16 through the
        serving tunnel — completed to the exact i32 wire values by the
        native keymap's tk_finish (`finish` is the keymap.finish bound
        method; requires the certified non-degenerate path and
        fits_cur_wire, which the limiter checked before dispatch).
    """

    def __init__(
        self, out_dev, prepared, finish=None, now_ns=0, w32=False
    ) -> None:
        self._out_dev = out_dev
        self._prepared = prepared
        self._finish = finish
        self._now_ns = now_ns
        self._w32 = w32

    def fetch(self) -> list:
        maybe_fail("fetch")
        out = np.asarray(self._out_dev)
        if self._w32:
            from .kernel import finish_w32
        results = []
        for j, (packed, status, params) in enumerate(self._prepared):
            n = len(status)
            valid = (packed[:, 2] & 2) != 0
            cur_plane = None
            if self._w32:
                o = np.stack(finish_w32(out[j, :n]))
            elif self._finish is not None:
                o = self._finish(packed, out[j, :n], self._now_ns).T
                # cur*2 + allowed words: expose the exact observed TATs
                # for the front tier's deny cache (see BatchResult).
                cur_plane = out[j, :n] >> 1
            else:
                o = out[j, :, :n]
            results.append(
                WireBatchResult(
                    allowed=(o[0] != 0) & valid,
                    limit=np.where(valid, params[:, 0], 0),
                    remaining=np.where(valid, o[1], 0),
                    reset_after_s=np.where(valid, o[2], 0),
                    retry_after_s=np.where(valid, o[3], 0),
                    status=status,
                    cur_ns=cur_plane,
                )
            )
        return results


class TpuRateLimiter(ScalarCompatMixin):
    """Batched GCRA over a device bucket table + host keymap."""

    # Batches are padded to a power of two of at least MIN_PAD lanes:
    # few distinct jit-cache shapes as traffic varies, AND at least the
    # Pallas kernels' DMA ring depth (pallas_fused.RING == 16 == the
    # retired pallas_ops ring) so the fused path's pipelines never run
    # shorter than their in-flight window.
    MIN_PAD = 16

    def __init__(
        self,
        capacity: int = 1 << 20,
        keymap="python",
        device=None,
        auto_grow: bool = True,
        insight: bool = False,
    ) -> None:
        """`keymap` selects the host key→slot backend: "python" (default,
        hashable keys of any kind), "native" (C++ batch resolver, bytes
        keys), "auto" (native when the toolchain built it), or a ready
        keymap object exposing resolve/free_slots/grow/capacity.
        `insight=True` arms the L3.75 analytics accumulators on the
        table (see BucketTable.enable_insight); off, the decision path
        is bit-identical to a limiter built without the subsystem."""
        self.table = BucketTable(capacity, device=device, insight=insight)
        if keymap == "auto":
            keymap = "native" if _native_available() else "python"
        if keymap == "python":
            self.keymap = PyKeyMap(capacity)
        elif keymap == "native":
            from ..native import NativeKeyMap

            self.keymap = NativeKeyMap(capacity)
        else:
            self.keymap = keymap
        self.auto_grow = auto_grow
        self._exp_hits_read = 0
        self._exp_hits_last_fetch_ns: Optional[int] = None

    # ------------------------------------------------------------------ #

    def expired_hits_fetch_due(
        self, now_ns: int, min_period_ns: int = 1_000_000_000
    ) -> bool:
        """True when take_expired_hits would actually hit the device —
        lets callers on latency-sensitive threads (the asyncio engine)
        route the blocking scalar fetch to an executor instead."""
        last = self._exp_hits_last_fetch_ns
        return last is None or now_ns - last >= min_period_ns

    def take_expired_hits(
        self, now_ns: int, min_period_ns: int = 1_000_000_000
    ) -> int:
        """New expired-hit count since the last call, for the adaptive
        cleanup policy's expired-ratio trigger.

        The count lives in a device-resident accumulator that rides
        every decision launch for free (kernel gcra_*_acc); reading it
        is one scalar device→host fetch, so the read is throttled to
        once per `min_period_ns` (default 1 s — the policy's own minimum
        cleanup interval; all its triggers operate at >= 1 s
        granularity, so a staler signal is indistinguishable).  Returns
        0 between fetches."""
        last = self._exp_hits_last_fetch_ns
        if last is not None and now_ns - last < min_period_ns:
            return 0
        self._exp_hits_last_fetch_ns = now_ns
        total = self.table.expired_hits()
        delta = total - self._exp_hits_read
        self._exp_hits_read = total
        return delta

    def rate_limit_batch(
        self,
        keys,
        max_burst,
        count_per_period,
        period,
        quantity,
        now_ns: int,
        wire: bool = False,
        collect_cur: bool = False,
    ) -> BatchResult:
        """Decide a batch of requests at one server timestamp.

        `keys` is a sequence of hashable keys (str/bytes); the numeric
        parameters broadcast to its length.  `now_ns` must be >= 0.

        `wire=True` takes the serving fast path: compact i32 whole-second
        outputs (returns WireBatchResult) and the degenerate-case kernel
        machinery compiled out whenever this batch provably has no
        quantity-0 / burst-1 / zero-emission / wrapped-negative-tolerance
        request (see has_degenerate).

        `collect_cur=True` (wire mode only) rides the compact="cur"
        output tier when its certificate holds, attaching each request's
        exact observed TAT as `result.cur_ns` (what the front tier's
        deny cache certifies entries from); cur_ns is None whenever cur
        is uncertifiable.  Decisions are identical either way.
        """
        (n, max_burst, quantity, emission, tolerance, status, valid,
         slots, rank0, is_last0, rounds) = self._prepare_one(
            keys, max_burst, count_per_period, period, quantity, now_ns
        )
        maybe_fail("launch")
        degen = has_degenerate(valid, emission, tolerance, quantity)
        with_degen = not wire or degen
        from .kernel import cur_wire_safe

        params_cur_safe = cur_wire_safe(valid, tolerance, now_ns)
        use_cur = (
            wire
            and collect_cur
            and not degen
            and params_cur_safe
            and self.table.cur_safe
        )
        if use_cur:
            from .kernel import finish_cur

        pad = max(self.MIN_PAD, 1 << (n - 1).bit_length())
        slots_p = np.zeros(pad, np.int32)
        slots_p[:n] = slots
        em_p = np.zeros(pad, np.int64)
        em_p[:n] = emission
        tol_p = np.zeros(pad, np.int64)
        tol_p[:n] = tolerance
        q_p = np.zeros(pad, np.int64)
        q_p[:n] = quantity

        allowed = np.zeros(n, bool)
        remaining = np.zeros(n, np.int64)
        reset_after = np.zeros(n, np.int64)
        retry_after = np.zeros(n, np.int64)
        cur_plane = np.zeros(n, np.int64) if use_cur else None

        n_rounds = int(rounds.max()) + 1 if n else 1
        for r in range(n_rounds):
            mask = valid & (rounds == r)
            if not mask.any():
                continue
            valid_p = np.zeros(pad, bool)
            valid_p[:n] = mask
            if n_rounds == 1:
                # Segment info came for free from the keymap pass.
                rank = np.zeros(pad, np.int32)
                rank[:n] = rank0
                is_last = np.ones(pad, bool)
                is_last[:n] = is_last0
            else:
                rank, is_last = segment_info(slots_p, valid_p)
            out_dev = self.table.check_batch(
                slots_p, rank, is_last, em_p, tol_p, q_p, valid_p, now_ns,
                with_degen=with_degen, compact="cur" if use_cur else wire,
                params_cur_safe=params_cur_safe,
            )
            # One device→host fetch per round; rounds beyond 0 are rare.
            if use_cur:
                # cur*2 + allowed words: finish to the exact i32 wire
                # planes on the host and keep the observed-TAT plane.
                words = np.asarray(out_dev)[:n]
                out = np.stack(
                    finish_cur(words, emission, tolerance, quantity,
                               now_ns)
                )
                cur_plane[mask] = (words >> 1)[mask]
            else:
                out = np.asarray(out_dev)[:, :n]
            allowed[mask] = out[0][mask] != 0
            remaining[mask] = out[1][mask]
            reset_after[mask] = out[2][mask]
            retry_after[mask] = out[3][mask]

        limit = np.where(valid, max_burst, 0)
        if wire:
            return WireBatchResult(
                allowed=allowed,
                limit=limit,
                remaining=remaining,
                reset_after_s=reset_after,
                retry_after_s=retry_after,
                status=status,
                cur_ns=cur_plane,
            )
        return BatchResult(
            allowed=allowed,
            limit=limit,
            remaining=remaining,
            reset_after_ns=reset_after,
            retry_after_ns=retry_after,
            status=status,
        )

    # ------------------------------------------------------------------ #

    def _prepare_one(
        self, keys, max_burst, count_per_period, period, quantity, now_ns
    ):
        """Shared per-batch prologue: validate, derive params, resolve
        slots (growing on full), emit segment structure + conflict rounds.
        One implementation for both the single-batch and scan paths."""
        if now_ns < 0:
            raise ValueError(
                "batch now_ns must be non-negative; apply "
                "normalize_now_ns per request for pre-epoch clocks"
            )
        n = len(keys)
        if getattr(self.keymap, "BYTES_KEYS", False):
            keys = [k.encode() if isinstance(k, str) else k for k in keys]
        max_burst, quantity, emission, tolerance, status, valid = (
            prepare_batch(n, max_burst, count_per_period, period, quantity)
        )
        slots, rank0, is_last0, n_full = self.keymap.resolve(keys, valid)
        maybe_fail("keymap")
        while n_full:
            if not self.auto_grow:
                raise InternalError("bucket table full")
            new_capacity = max(self.keymap.capacity * 2, 1024)
            self.keymap.grow(new_capacity)
            self.table.grow(new_capacity)
            missing = valid & (slots == -1)
            slots2, _, _, n_full = self.keymap.resolve(keys, missing)
            slots = np.where(missing, slots2, slots)
            # Segment info must cover the merged batch.
            rank0, is_last0 = segment_info(slots, valid)
        rounds = self._conflict_rounds(
            slots, valid, emission, tolerance, quantity
        )
        return (n, max_burst, quantity, emission, tolerance, status, valid,
                slots, rank0, is_last0, rounds)

    @staticmethod
    def _error_result(n, status_code=STATUS_INTERNAL, wire=False):
        """All-requests-failed result (engine maps status → error)."""
        zeros = np.zeros(n, np.int64)
        status = np.full(n, status_code, np.uint8)
        if wire:
            return WireBatchResult(
                allowed=np.zeros(n, bool), limit=zeros, remaining=zeros,
                reset_after_s=zeros, retry_after_s=zeros, status=status,
            )
        return BatchResult(
            allowed=np.zeros(n, bool), limit=zeros, remaining=zeros,
            reset_after_ns=zeros, retry_after_ns=zeros, status=status,
        )

    def rate_limit_many(
        self, batches, wire: bool = False, collect_cur: bool = False
    ) -> list:
        """Decide K whole batches in ONE device launch (gcra_scan).

        `batches` is a list of (keys, max_burst, count_per_period, period,
        quantity, now_ns) tuples, in arrival order; each sub-batch sees the
        table state left by the previous one (lax.scan carry), exactly as K
        separate rate_limit_batch calls would — but with one launch and one
        fetch, amortizing the fixed dispatch cost that dominates when the
        serving engine drains a backlog.  Returns a list of BatchResult.

        Sub-batches whose keys change parameters mid-batch (conflict
        rounds > 0) fall back to the per-batch path, preserving exact
        ordering; that case is rare in serving traffic.
        """
        return self.dispatch_many(
            batches, wire=wire, collect_cur=collect_cur
        ).fetch()

    def dispatch_many(
        self, batches, wire: bool = False, collect_cur: bool = False
    ):
        """The dispatch half of rate_limit_many: host-prepare the window,
        launch it on the device, and return a handle whose `.fetch()`
        blocks for the results.

        `collect_cur=True` (wire mode only) asks for the exact observed
        TATs alongside the wire values: the dispatcher prefers the cur
        output tier over w32 (8 B/request instead of 4 — the TAT plane
        is what the front tier's deny cache certifies entries from) and
        attaches it as `result.cur_ns`.  Falls back to the 4-plane tier
        with cur_ns=None whenever cur is uncertifiable; decisions are
        identical either way.

        Device dispatch is asynchronous, so the caller can assemble and
        dispatch window N+1 while the device executes window N and only
        then fetch N's results — the double-buffering that hides the fixed
        per-launch round-trip cost of the serving tunnel (the engine's
        flush loop does exactly this).  Launches are sequenced by the
        donated table state, so results are identical to sequential calls.
        """
        if not batches:
            return _ReadyLaunch([])

        prepared = []
        width = self.MIN_PAD
        any_degen = False
        for keys, max_burst, count_per_period, period, quantity, now_ns in (
            batches
        ):
            (n, max_burst, quantity, emission, tolerance, status, valid,
             slots, rank, is_last, rounds) = self._prepare_one(
                keys, max_burst, count_per_period, period, quantity, now_ns
            )
            if rounds.any():
                return _ReadyLaunch(
                    sequential_fallback(
                        batches, self.rate_limit_batch,
                        self._error_result, wire,
                        collect_cur=collect_cur,
                    )
                )
            any_degen = any_degen or has_degenerate(
                valid, emission, tolerance, quantity
            )
            prepared.append(
                (n, slots, rank, is_last, emission, tolerance, quantity,
                 valid, now_ns, max_burst, status)
            )
            width = max(width, 1 << max(n - 1, 0).bit_length())

        K = len(prepared)
        # Pad the scan depth to a power of two with empty sub-batches so the
        # jit cache sees few distinct (K, width) shapes as backlog varies.
        K_pad = 1 << (K - 1).bit_length()
        shape = (K_pad, width)
        slots_s = np.zeros(shape, np.int32)
        rank_s = np.zeros(shape, np.int32)
        last_s = np.ones(shape, bool)
        em_s = np.zeros(shape, np.int64)
        tol_s = np.zeros(shape, np.int64)
        q_s = np.zeros(shape, np.int64)
        valid_s = np.zeros(shape, bool)
        now_s = np.full(K_pad, prepared[-1][8], np.int64)
        for j, (n, slots, rank, is_last, emission, tolerance, quantity,
                valid, now_ns, _mb, _st) in enumerate(prepared):
            slots_s[j, :n] = slots
            rank_s[j, :n] = rank
            last_s[j, :n] = is_last
            em_s[j, :n] = emission
            tol_s[j, :n] = tolerance
            q_s[j, :n] = quantity
            valid_s[j, :n] = valid
            now_s[j] = now_ns

        # One fused host→device buffer for the whole window: the serving
        # tunnel charges ~6 ms per transfer *call*, so eight per-array
        # transfers per launch would cost more than the device work
        # (docs/tpu-launch-profile.md).
        from .kernel import cur_wire_safe, fits_w32_wire, pack_requests

        packed = pack_requests(
            slots_s, rank_s, last_s, em_s, tol_s, q_s, valid_s
        )
        # The 8 B/request "cur" output halves the fetch whenever the
        # certified fast path applies and the valid-masked cur bound
        # holds (now/tol < 2^61); finished to identical wire values on
        # the host in _PendingLaunch.fetch.  table.cur_safe extends the
        # certificate across launches: a prior big-tolerance launch can
        # persist a TAT >= 2^62 whose cur word would wrap (ADVICE r4).
        now_max = int(now_s.max(initial=0))
        params_cur_safe = cur_wire_safe(valid_s, tol_s, now_max)
        max_tol = int(np.where(valid_s, tol_s, 0).max(initial=0))
        # Cheapest eligible output tier: w32 (4 B/request, device-packed
        # exact wire values) → cur (8 B, host-finished) → 4-plane i32.
        # w32's stored-TAT bound needs timestamps non-decreasing within
        # the window and no earlier than any prior launch's.
        use_w32 = (
            wire
            and not collect_cur
            and not any_degen
            and now_max < (1 << 61)
            and bool((np.diff(now_s) >= 0).all())
            and fits_w32_wire(
                valid_s, em_s, tol_s, q_s, int(now_s[0]),
                self.table.tol_hwm, self.table.now_hwm,
            )
        )
        use_cur = (
            not use_w32
            and wire
            and not any_degen
            and params_cur_safe
            and self.table.cur_safe
        )
        maybe_fail("launch")
        out_dev = self.table.check_many_packed(
            packed, now_s,
            with_degen=not wire or any_degen,
            compact="w32" if use_w32 else ("cur" if use_cur else wire),
            params_cur_safe=params_cur_safe,
            max_tolerance=max_tol,
        )
        return _PendingLaunch(
            out_dev, prepared, valid_s, wire, cur=use_cur, w32=use_w32
        )

    # ------------------------------------------------------------------ #

    def dispatch_wire_window(
        self, frames, now_ns: int, collect_cur: bool = False
    ):
        """The fully-native serving dispatch: each frame is
        (key_blob, offsets i64[n+1], params i64[n, 4]) exactly as the C++
        wire layer hands batches over.  One C++ call per frame validates,
        derives GCRA params (exact f64 pipeline), resolves slots, and
        writes the packed rows (native/keymap.cpp tk_prepare_batch);
        Python's per-batch work is reduced to pow-2 padding and the
        launch.  Returns a handle with .fetch() -> [WireBatchResult], or
        None when the window needs the exact Python path (non-native
        keymap, a mid-batch param change, or a full table — preparation
        is idempotent, so the fallback simply re-resolves)."""
        km = self.keymap
        if not hasattr(km, "prepare_batch"):
            return None
        if now_ns < 0:
            # Part of the with_degen=False certificate (kernel.py): the
            # nonneg saturating forms require now >= 0.  Same contract as
            # _prepare_one.
            raise ValueError(
                "batch now_ns must be non-negative; apply "
                "normalize_now_ns per request for pre-epoch clocks"
            )
        from ..native import PREP_BIGTOL, PREP_CONFLICT, PREP_DEGEN, PREP_FULL

        prepared = []
        width = self.MIN_PAD
        any_degen = False
        any_bigtol = False
        # Per-window w32-certificate aggregates, folded across frames
        # (C++ computes them per frame during the same prep pass).
        agg = np.empty(4, np.int64)
        max_tol = 0
        min_tol = 1 << 62
        max_inc = 0
        rem_bound = 0
        for blob, offsets, params in frames:
            packed, status, flags = km.prepare_batch(
                blob, offsets, params, agg=agg
            )
            if flags & (PREP_CONFLICT | PREP_FULL):
                return None
            any_degen = any_degen or bool(flags & PREP_DEGEN)
            any_bigtol = any_bigtol or bool(flags & PREP_BIGTOL)
            max_tol = max(max_tol, int(agg[0]))
            # agg[0] > 0 ⇔ the frame had a valid lane with tol > 0
            # (tol <= 0 lanes carry PREP_DEGEN, and any_degen refuses
            # w32 outright, so the 0-sentinel min never leaks in).
            if int(agg[0]) > 0:
                min_tol = min(min_tol, int(agg[1]))
            max_inc = max(max_inc, int(agg[2]))
            rem_bound = max(rem_bound, int(agg[3]))
            prepared.append((packed, status, params))
            n = len(status)
            width = max(width, 1 << max(n - 1, 0).bit_length())

        from .kernel import PACK_WIDTH

        # 8 B/request "cur" output (host-finished by C++ tk_finish) when
        # the certified fast path and the fits_cur_wire bound both hold;
        # else the 4-plane compact i32 output.  Same exact wire values
        # either way (tests/test_wire_path.py pins the equivalence).
        # table.cur_safe carries the certificate across launches (a
        # prior big-tol launch can store a TAT >= 2^62 — ADVICE r4).
        # PREP_BIGTOL is set only for VALID lanes (invalid params skip
        # derivation in tk_prepare_batch), and degenerate lanes obey the
        # same write bound, so bigtol + now alone decide state safety.
        params_cur_safe = not any_bigtol and now_ns < (1 << 61)
        use_cur = (
            not any_degen
            and params_cur_safe
            and self.table.cur_safe
            and hasattr(km, "finish")
        )
        K = len(prepared)
        K_pad = 1 << max(K - 1, 0).bit_length()
        stack = np.zeros((K_pad, width, PACK_WIDTH), np.int32)
        for j, (packed, _, _) in enumerate(prepared):
            stack[j, : len(packed)] = packed

        # w32 tier (4 B/request, device-packed exact wire values): the
        # certificate runs on the C++ prep's aggregates — no Python pass
        # over the rows, and the halved fetch repays the bookkeeping
        # many times over on the tunnel.
        use_w32 = False
        if not any_degen and not any_bigtol and not collect_cur:
            # collect_cur: the caller (a front-tier serving loop) wants
            # the observed-TAT plane, which only the cur tier carries.
            from .kernel import fits_w32_wire_agg

            use_w32 = fits_w32_wire_agg(
                max_tol, min_tol, max_inc, rem_bound, now_ns,
                self.table.tol_hwm, self.table.now_hwm,
            )
        use_cur = use_cur and not use_w32

        maybe_fail("launch")
        out_dev = self.table.check_many_packed(
            stack,
            np.full(K_pad, now_ns, np.int64),
            with_degen=any_degen,
            compact="w32" if use_w32 else ("cur" if use_cur else True),
            params_cur_safe=params_cur_safe,
            max_tolerance=max_tol,
        )
        if use_w32:
            return _PendingWireLaunch(out_dev, prepared, w32=True)
        if use_cur:
            return _PendingWireLaunch(
                out_dev, prepared, finish=km.finish, now_ns=now_ns
            )
        return _PendingWireLaunch(out_dev, prepared)

    def sweep(self, now_ns: int) -> int:
        """Run a cleanup sweep; returns the number of slots freed."""
        expired = self.table.sweep(now_ns)
        return self.keymap.free_slots(np.flatnonzero(expired))

    def __len__(self) -> int:
        return len(self.keymap)

    @property
    def total_capacity(self) -> int:
        """Slots available before growth (for capacity-pressure policies)."""
        return self.table.capacity

    # ------------------------------------------------------------------ #

    @staticmethod
    def _conflict_rounds(slots, valid, emission, tolerance, quantity):
        """Arrival-order rounds for keys whose params change mid-batch.

        Round r holds each key's r-th maximal run of identical parameters,
        so processing rounds in order reproduces the reference's sequential
        per-request semantics exactly.
        """
        n = len(slots)
        rounds = np.zeros(n, np.int32)
        if n == 0:
            return rounds
        vslots = slots[valid]
        if len(np.unique(vslots)) == len(vslots):
            return rounds  # no duplicates at all: single round

        uniq, first_idx, inv = np.unique(slots, return_index=True, return_inverse=True)
        canon = first_idx[inv]
        conflict = valid & (
            (emission != emission[canon])
            | (tolerance != tolerance[canon])
            | (quantity != quantity[canon])
        )
        if not conflict.any():
            return rounds
        return param_rounds(
            rounds, slots, np.flatnonzero(valid), emission, tolerance, quantity
        )
