"""HBM-resident bucket table: the TPU replacement for the HashMap stores.

Structure-of-Arrays layout instead of the reference's
`HashMap<String, (i64, Option<SystemTime>)>` (`periodic.rs:39-47`): string
keys are resolved to dense slot indices on the host (see keymap.py); the
device only ever sees integer slots.  Each slot's (TAT, expiry) pair is
stored as one packed i32[4] row — TPU scatters cost per *row*, and one 4×i32
row write is ~4.5x cheaper than two separate i64 scatters (see
kernel.pack_state).  16 bytes of HBM per slot — 1M keys is 16 MB — plus a
scratch tail of `SCRATCH` rows that absorbs suppressed writes at unique
indices.

All mutation goes through the donated-buffer kernels in kernel.py, so the
array is updated in place batch after batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .sat import I64_MAX
from .kernel import (
    EMPTY_EXPIRY,
    gcra_batch_acc,
    gcra_batch_ins,
    gcra_scan_acc,
    gcra_scan_byid_acc,
    gcra_scan_ids_acc,
    gcra_scan_ins,
    gcra_scan_packed_acc,
    gcra_scan_packed_ins,
    pack_id_rows,
    pack_state,
    sweep_expired,
    sweep_expired_ins,
    unpack_state,
)


# Stored-TAT bound for the compact="cur" output: the device emits
# `cur * 2 + allowed` in i64, and a denied lane's cur can be the STORED
# TAT verbatim (kernel t0 = max(stored_tat, now - tol) with m_raw = 0),
# so every live TAT must sit in [0, 2^62) for the shift to be exact.
# Launches whose params satisfy the per-launch certificate (no
# degenerate request, tol/now < 2^61) only ever write TATs in
# [0, now + tol] ⊂ [0, 2^62); any other launch may store values
# anywhere in i64 (the 4-plane paths handle those exactly — the cur
# shift alone would wrap).
CUR_TAT_BOUND = 1 << 62


def track_cur_safety(table, compact, params_cur_safe) -> None:
    """Cross-launch half of the compact="cur" certificate.

    fits_cur_wire (kernel.py) bounds only the CURRENT launch; a prior
    big-tolerance launch can persist a TAT >= 2^62 for a key, and a
    later normal-tolerance cur-mode launch on that key would wrap
    `cur * 2 + allowed`.  So the table tracks a sticky `cur_safe` flag:
    a launch preserves it iff its own params are certified — either
    implicitly (compact="cur" callers certify by contract) or via
    `params_cur_safe=True`.  Dispatchers consult `table.cur_safe`
    before choosing the cur wire mode.
    """
    if compact not in ("cur", "w32") and not params_cur_safe:
        # compact="w32" implies safety: its certificate (fits_w32_wire)
        # bounds every valid tolerance to seconds-scale, far below 2^61.
        table.cur_safe = False


def _host_max_now(now_ns):
    """Max launch timestamp for BucketTable.note_launch_now — host
    values only (a jax.Array reports unknown, saturating the mark)."""
    if isinstance(now_ns, jax.Array):
        return None
    a = np.asarray(now_ns, np.int64)
    return int(a.max(initial=0)) if a.ndim else int(a)


def _host_max_tol(valid, tolerance):
    """Masked max tolerance for BucketTable.note_max_tolerance — host
    arrays only (a jax.Array would force a device sync, so it reports
    unknown instead and the mark saturates)."""
    if isinstance(tolerance, jax.Array) or isinstance(valid, jax.Array):
        return None
    v = np.asarray(valid, bool)
    return int(
        np.where(v, np.asarray(tolerance, np.int64), 0).max(initial=0)
    )


def tats_cur_safe(tats) -> bool:
    """Host-side audit of raw i64 TAT values: True iff every one is in
    [0, CUR_TAT_BOUND) — the condition under which compact="cur"
    launches are exact against state holding them.  Snapshot restore
    uses this to re-derive `cur_safe` for foreign state."""
    tat = np.asarray(tats, np.int64)
    return tat.size == 0 or bool(
        ((tat >= 0) & (tat < CUR_TAT_BOUND)).all()
    )


def _fused_enabled() -> bool:
    """Route decision windows through the fused Pallas kernel
    (pallas_fused.py; THROTTLECRAB_PALLAS_FUSED=1).  Read per dispatch
    — the fused wrappers and the composed-XLA twins are separate jit
    entry points, so the flag flips between launches without retracing
    tricks and unset preserves byte-identical current behavior.  The
    check itself must not import pallas_fused: with the kill switch
    engaged the default path stays isolated from the experimental
    pallas stack (kernel.pallas_fused_enabled is the canonical parse).
    """
    from .kernel import pallas_fused_enabled

    return pallas_fused_enabled()


class StaleIdRowsError(RuntimeError):
    """Device-resident by-id parameter rows refer to slots the keymap has
    since remapped (sweep freed them or the table grew); re-run
    upload_id_rows before the next by-id launch."""


class ResidentIdRows:
    """Device-resident by-id parameter rows plus a staleness guard.

    Pins the keymap's `mutations` counter at build time; any later
    sweep, growth, or intern of new ids bumps it, and the next by-id
    launch raises StaleIdRowsError instead of silently deciding against
    stale or uncovered slots.
    """

    def __init__(self, rows: jax.Array, keymap) -> None:
        self.rows = rows
        self._keymap = keymap
        self._stamp = getattr(keymap, "mutations", 0)

    def rows_checked(self) -> jax.Array:
        current = getattr(self._keymap, "mutations", 0)
        if current != self._stamp:
            raise StaleIdRowsError(
                "by-id parameter rows are stale: the keymap remapped "
                f"slots since upload (mutations {self._stamp} -> "
                f"{current}); re-run upload_id_rows"
            )
        return self.rows


class HwmMarksMixin:
    """The compact="w32" certificate's cross-launch high-water marks,
    shared by BucketTable and ShardedBucketTable: every stored TAT is
    <= its writing launch's now + tol <= now_hwm + tol_hwm, which
    fits_w32_wire needs to bound reset/retry fields.  A launch that
    cannot report a value saturates its mark (w32 off until rebuild).
    Subclass __init__ sets `tol_hwm = now_hwm = 0`."""

    def note_max_tolerance(self, max_tol) -> None:
        """Record a launch's max valid-lane tolerance (None = unknown)."""
        if max_tol is None:
            self.tol_hwm = I64_MAX
        else:
            self.tol_hwm = max(self.tol_hwm, int(max_tol))

    def note_launch_now(self, now_ns) -> None:
        """Record a launch's max timestamp (None = unknown)."""
        if now_ns is None:
            self.now_hwm = I64_MAX
        else:
            self.now_hwm = max(self.now_hwm, int(now_ns))


class BucketTable(HwmMarksMixin):
    """Per-slot GCRA state on a single device."""

    SCRATCH = 1 << 16  # max batch size; scratch rows for suppressed writes

    def __init__(
        self, capacity: int, device=None, insight: bool = False
    ) -> None:
        self.capacity = capacity
        self.device = device
        self.state = self._alloc(capacity + self.SCRATCH)
        # Insight tier (L3.75) accumulators: a per-slot denied-hit
        # counter fused into the packed state rows (kernel.INS_WIDTH —
        # maintained by the decision path's own row gather/scatter, so
        # it is close to free) + running [allowed, denied] totals,
        # updated inside every decision launch (the gcra_*_ins kernel
        # twins) and read only at the insight tier's throttled poll.
        # Rides ONLY the engine serving paths (check_batch / check_many
        # / check_many_packed); the by-id bench paths bypass it.  Off
        # by default: the plain *_acc kernels run on 4-wide rows and
        # the decision path is bit-identical to a table built without
        # insight.
        self.insight = False
        self.ins_counts = None
        if insight:
            self.enable_insight()
        # True while every stored TAT provably sits in [0, 2^62) — the
        # cross-launch precondition of the compact="cur" wire mode (see
        # track_cur_safety).  Fresh state is all-zero TATs: safe.
        self.cur_safe = True
        # Device-resident expired-hit accumulator: donated through every
        # decision launch (kernel gcra_*_acc), read only on demand — the
        # signal behind the adaptive cleanup policy's expired-ratio
        # trigger (adaptive_cleanup.rs:150-163).
        ctx = (
            jax.default_device(self.device)
            if self.device is not None
            else _nullcontext()
        )
        with ctx:
            self.exp_acc = jnp.zeros((), jnp.int64)
        # High-water marks backing the compact="w32" certificate
        # (kernel.fits_w32_wire): every stored TAT is <= its writing
        # launch's now + tol <= now_hwm + tol_hwm, so a later launch at
        # now >= now_hwm can bound its reset/retry fields.  A launch at
        # an EARLIER now (clock regression / caller-supplied timestamp)
        # breaks that inequality, so w32 also requires now >= now_hwm.
        # Launches that cannot report their values saturate the marks.
        self.tol_hwm = 0
        self.now_hwm = 0

    def expired_hits(self) -> int:
        """Total expired-hit count since construction.  One scalar
        device→host fetch — callers throttle (see
        TpuRateLimiter.take_expired_hits)."""
        return int(self.exp_acc)

    # ---- insight tier (L3.75) accumulators ---------------------------- #

    def enable_insight(self) -> None:
        """Widen the state rows to kernel.INS_WIDTH (appending
        zero-initialized denied-hit counter columns), allocate the
        totals accumulator, and route decision launches through the
        gcra_*_ins kernel twins.  Idempotent.  The LEGACY Pallas
        row-movement kernels (THROTTLECRAB_PALLAS) only speak 4-wide
        rows, so an insight table always uses the plain XLA
        gather/scatter for them; the fused decision kernel
        (THROTTLECRAB_PALLAS_FUSED) is width-polymorphic — its 6-wide
        template folds the denied-hit counter into the same row DMAs,
        so insight and the fused Pallas path coexist with no downgrade.
        """
        from .kernel import INS_WIDTH

        if self.insight:
            return
        from . import pallas_ops

        if pallas_ops.enabled() and not _fused_enabled():
            # Loud, not silent: a THROTTLECRAB_PALLAS=1 deployment that
            # also enables insight loses its opted-in legacy DMA row
            # path — the operator should pick one (THROTTLECRAB_
            # INSIGHT=0 restores it, or THROTTLECRAB_PALLAS_FUSED=1
            # moves to the width-polymorphic fused kernel).  The fused
            # path carries INS_WIDTH rows natively: no warning there.
            import logging

            logging.getLogger("throttlecrab.table").warning(
                "insight-widened rows disable the legacy Pallas DMA "
                "row kernels (THROTTLECRAB_PALLAS=1 requested); "
                "decision launches use the plain XLA gather/scatter — "
                "set THROTTLECRAB_INSIGHT=0 to keep the legacy row "
                "path, or THROTTLECRAB_PALLAS_FUSED=1 for the "
                "width-polymorphic fused kernel"
            )
        ctx = (
            jax.default_device(self.device)
            if self.device is not None
            else _nullcontext()
        )
        with ctx:
            pad = jnp.zeros(
                (self.state.shape[0], INS_WIDTH - 4), jnp.int32
            )
            self.state = jnp.concatenate([self.state, pad], axis=-1)
            self.ins_counts = jnp.zeros((2,), jnp.int64)
        self.insight = True

    def insight_counts(self) -> tuple:
        """(allowed_total, denied_total) decided through the insight
        launch paths since construction.  One small device→host fetch
        that synchronizes on in-flight launches — callers throttle
        (the insight tier polls ~1/s)."""
        if not self.insight:
            return (0, 0)
        counts = np.asarray(self.ins_counts)
        return int(counts[0]), int(counts[1])

    def insight_topk(self, k: int):
        """Device-side partial top-K of the denied-hit counter column:
        (counts, slot_ids) DEVICE arrays, highest count first — the
        fetch is the caller's (np.asarray), so it can stay deferred.
        One tiny extra launch per call; the insight tier invokes it
        only at its poll cadence, never per decision."""
        from .kernel import insight_topk

        if not self.insight:
            return None
        k = max(1, min(int(k), self.capacity))
        return insight_topk(self.state, capacity=self.capacity, k=k)

    def insight_decay(self) -> None:
        """Halve the denied-hit counter columns (periodic heat decay)."""
        from .kernel import insight_decay

        if self.insight:
            self.state = insight_decay(self.state)

    def _alloc(self, rows: int) -> jax.Array:
        ctx = (
            jax.default_device(self.device)
            if self.device is not None
            else _nullcontext()
        )
        with ctx:
            return pack_state(
                jnp.zeros((rows,), jnp.int64),
                jnp.full((rows,), EMPTY_EXPIRY, jnp.int64),
            )

    @property
    def tat(self) -> jax.Array:
        """i64 TAT column (diagnostics/tests; excludes scratch)."""
        return unpack_state(self.state)[0][: self.capacity]

    @property
    def expiry(self) -> jax.Array:
        """i64 expiry column (diagnostics/tests; excludes scratch)."""
        return unpack_state(self.state)[1][: self.capacity]

    def check_batch(
        self,
        slots: np.ndarray,
        rank: np.ndarray,
        is_last: np.ndarray,
        emission: np.ndarray,
        tolerance: np.ndarray,
        quantity: np.ndarray,
        valid: np.ndarray,
        now_ns: int,
        with_degen: bool = True,
        compact: bool = False,
        params_cur_safe: bool = False,
    ) -> jax.Array:
        """Run one decision batch; updates the table state in place.

        Returns the stacked device output [4, B]: rows are (allowed,
        remaining, reset_after, retry_after) — fetch with one np.asarray.

        `params_cur_safe=True` asserts this launch's params satisfy the
        cur certificate (no degenerate request, tol/now < 2^61) so the
        table's `cur_safe` flag survives; compact="cur" implies it.
        """
        assert len(slots) <= self.SCRATCH, "batch exceeds scratch region"
        track_cur_safety(self, compact, params_cur_safe)
        self.note_max_tolerance(_host_max_tol(valid, tolerance))
        self.note_launch_now(_host_max_now(now_ns))
        args = (
            jnp.asarray(slots, jnp.int32),
            jnp.asarray(rank, jnp.int32),
            jnp.asarray(is_last, bool),
            jnp.asarray(emission, jnp.int64),
            jnp.asarray(tolerance, jnp.int64),
            jnp.asarray(quantity, jnp.int64),
            jnp.asarray(valid, bool),
            now_ns,
        )
        if _fused_enabled():
            from . import pallas_fused

            if self.insight:
                self.state, self.exp_acc, self.ins_counts, out = (
                    pallas_fused.gcra_batch_fused_ins(
                        self.state, self.exp_acc, self.ins_counts, *args,
                        with_degen=with_degen, compact=compact,
                    )
                )
            else:
                self.state, self.exp_acc, out = (
                    pallas_fused.gcra_batch_fused_acc(
                        self.state, self.exp_acc, *args,
                        with_degen=with_degen, compact=compact,
                    )
                )
        elif self.insight:
            self.state, self.exp_acc, self.ins_counts, out = (
                gcra_batch_ins(
                    self.state, self.exp_acc, self.ins_counts, *args,
                    with_degen=with_degen, compact=compact,
                )
            )
        else:
            self.state, self.exp_acc, out = gcra_batch_acc(
                self.state, self.exp_acc, *args,
                with_degen=with_degen, compact=compact,
            )
        return out

    def check_many(
        self,
        slots: np.ndarray,
        rank: np.ndarray,
        is_last: np.ndarray,
        emission: np.ndarray,
        tolerance: np.ndarray,
        quantity: np.ndarray,
        valid: np.ndarray,
        now_ns: np.ndarray,
        with_degen: bool = True,
        compact: bool = False,
        params_cur_safe: bool = False,
    ) -> jax.Array:
        """K stacked micro-batches ([K, B] inputs, i64[K] timestamps) in one
        launch; returns the [K, 4, B] stacked device output."""
        assert slots.shape[1] <= self.SCRATCH, "batch exceeds scratch region"
        track_cur_safety(self, compact, params_cur_safe)
        self.note_max_tolerance(_host_max_tol(valid, tolerance))
        self.note_launch_now(_host_max_now(now_ns))
        args = (
            jnp.asarray(slots, jnp.int32),
            jnp.asarray(rank, jnp.int32),
            jnp.asarray(is_last, bool),
            jnp.asarray(emission, jnp.int64),
            jnp.asarray(tolerance, jnp.int64),
            jnp.asarray(quantity, jnp.int64),
            jnp.asarray(valid, bool),
            jnp.asarray(now_ns, jnp.int64),
        )
        if _fused_enabled():
            from . import pallas_fused

            if self.insight:
                self.state, self.exp_acc, self.ins_counts, out = (
                    pallas_fused.gcra_scan_fused_ins(
                        self.state, self.exp_acc, self.ins_counts, *args,
                        with_degen=with_degen, compact=compact,
                    )
                )
            else:
                self.state, self.exp_acc, out = (
                    pallas_fused.gcra_scan_fused_acc(
                        self.state, self.exp_acc, *args,
                        with_degen=with_degen, compact=compact,
                    )
                )
        elif self.insight:
            self.state, self.exp_acc, self.ins_counts, out = (
                gcra_scan_ins(
                    self.state, self.exp_acc, self.ins_counts, *args,
                    with_degen=with_degen, compact=compact,
                )
            )
        else:
            self.state, self.exp_acc, out = gcra_scan_acc(
                self.state, self.exp_acc, *args,
                with_degen=with_degen, compact=compact,
            )
        return out

    def check_many_packed(
        self,
        packed,
        now_ns,
        with_degen: bool = True,
        compact=False,
        params_cur_safe: bool = False,
        max_tolerance=None,
    ) -> jax.Array:
        """K stacked micro-batches from ONE packed i32[K, B, PACK_WIDTH]
        buffer (see kernel.pack_requests); `now_ns` is i64[K].

        `compact` may be False (i64[K, 4, B] ns outputs), True (i32 wire
        planes), or "cur" (i64[K, B], one `cur*2+allowed` word per
        request for host-side completion via kernel.finish_cur / native
        tk_finish — requires with_degen=False and the fits_cur_wire
        certificate; 8 B/request, the cheapest device→host fetch).

        Unlike check_many this does NOT convert the output — it returns the
        device array untouched so a pipelined caller can defer the fetch
        (dispatch launch N+1 before reading launch N's results; the tunnel's
        dispatch path is fully asynchronous).  `packed` may be a numpy array
        or an already-transferred device array.
        """
        assert packed.shape[1] <= self.SCRATCH, "batch exceeds scratch region"
        track_cur_safety(self, compact, params_cur_safe)
        # Packed rows hide the tolerances; the caller reports its masked
        # max (None saturates the mark — see note_max_tolerance).
        self.note_max_tolerance(max_tolerance)
        self.note_launch_now(_host_max_now(now_ns))
        args = (
            packed
            if isinstance(packed, jax.Array)
            else jnp.asarray(packed, jnp.int32),
            jnp.asarray(now_ns, jnp.int64),
        )
        if _fused_enabled():
            from . import pallas_fused

            if self.insight:
                self.state, self.exp_acc, self.ins_counts, out = (
                    pallas_fused.gcra_scan_packed_fused_ins(
                        self.state, self.exp_acc, self.ins_counts, *args,
                        with_degen=with_degen, compact=compact,
                    )
                )
            else:
                self.state, self.exp_acc, out = (
                    pallas_fused.gcra_scan_packed_fused_acc(
                        self.state, self.exp_acc, *args,
                        with_degen=with_degen, compact=compact,
                    )
                )
        elif self.insight:
            self.state, self.exp_acc, self.ins_counts, out = (
                gcra_scan_packed_ins(
                    self.state, self.exp_acc, self.ins_counts, *args,
                    with_degen=with_degen, compact=compact,
                )
            )
        else:
            self.state, self.exp_acc, out = gcra_scan_packed_acc(
                self.state, self.exp_acc, *args,
                with_degen=with_degen, compact=compact,
            )
        return out

    def upload_id_rows(
        self, slots, emission, tolerance, keymap=None
    ):
        """Build and upload the by-id parameter rows for check_many_byid:
        i32[n_ids, IDROW_WIDTH] = [slot, em_lo/hi, tol_lo/hi, pad].  One
        untimed setup transfer; the rows then stay device-resident so a
        request costs 8 bytes on the wire instead of the 36-byte packed
        row (the tunnel's ~10-50 MB/s serialized link is the launch
        throughput ceiling — docs/tpu-launch-profile.md).

        A sweep or growth remaps slots and silently invalidates the
        uploaded rows; pass the `keymap` the slots came from to get a
        ResidentIdRows guard that raises StaleIdRowsError instead of
        deciding against stale slots (re-upload to refresh).  Without
        `keymap` the raw device array is returned and freshness is the
        caller's contract."""
        rows = jax.device_put(
            pack_id_rows(slots, emission, tolerance), self.device
        )
        # The rows' tolerances bound every future by-id write, so noting
        # them here covers all subsequent check_many_byid/ids launches
        # (which therefore skip per-launch reporting).
        self.note_max_tolerance(
            None
            if isinstance(tolerance, jax.Array)
            else int(np.max(np.asarray(tolerance, np.int64), initial=0))
        )
        if keymap is None:
            return rows
        return ResidentIdRows(rows, keymap)

    def check_many_byid(
        self,
        id_rows,
        words,
        now_ns,
        quantity: int = 1,
        with_degen: bool = True,
        compact=False,
        params_cur_safe: bool = False,
    ) -> jax.Array:
        """K stacked micro-batches of 8-byte request words (i64[K, B],
        tk_assemble_ids layout) against resident `id_rows` (a raw device
        array or a ResidentIdRows guard, which is freshness-checked).
        `quantity` is launch-uniform.  Returns the device output per
        `compact` (see check_many_packed) without fetching."""
        if isinstance(id_rows, ResidentIdRows):
            id_rows = id_rows.rows_checked()
        assert words.shape[1] <= self.SCRATCH, "batch exceeds scratch region"
        track_cur_safety(self, compact, params_cur_safe)
        self.note_launch_now(_host_max_now(now_ns))
        self.state, self.exp_acc, out = gcra_scan_byid_acc(
            self.state,
            self.exp_acc,
            id_rows,
            words
            if isinstance(words, jax.Array)
            else jnp.asarray(words, jnp.int64),
            jnp.asarray(now_ns, jnp.int64),
            quantity,
            with_degen=with_degen,
            compact=compact,
        )
        return out

    def check_many_ids(
        self,
        id_rows,
        ids,
        now_ns,
        quantity: int = 1,
        with_degen: bool = True,
        compact=False,
        params_cur_safe: bool = False,
    ) -> jax.Array:
        """K stacked micro-batches of RAW key ids (i32[K, B], negative =
        padding) against resident `id_rows`: 4 bytes per request on the
        wire, duplicate-segment structure derived on-device
        (kernel.gcra_scan_ids).  Accepts a ResidentIdRows guard like
        check_many_byid.  Returns the device output per `compact`."""
        if isinstance(id_rows, ResidentIdRows):
            id_rows = id_rows.rows_checked()
        assert ids.shape[1] <= self.SCRATCH, "batch exceeds scratch region"
        track_cur_safety(self, compact, params_cur_safe)
        self.note_launch_now(_host_max_now(now_ns))
        self.state, self.exp_acc, out = gcra_scan_ids_acc(
            self.state,
            self.exp_acc,
            id_rows,
            ids
            if isinstance(ids, jax.Array)
            else jnp.asarray(ids, jnp.int32),
            jnp.asarray(now_ns, jnp.int64),
            quantity,
            with_degen=with_degen,
            compact=compact,
        )
        return out

    def check_many_ids20(
        self,
        id_rows,
        packed,
        now_ns,
        quantity: int = 1,
        with_degen: bool = True,
        compact=False,
        params_cur_safe: bool = False,
    ) -> jax.Array:
        """K stacked micro-batches of 20-bit packed key ids
        (u16[K, B + B//4], kernel.pack_ids20): 2.5 bytes per request on
        the wire.  Requires the resident table to stay below the
        padding sentinel so padding can never alias a real key."""
        from .kernel import IDS20_SENTINEL, gcra_scan_ids20_acc

        if isinstance(id_rows, ResidentIdRows):
            id_rows = id_rows.rows_checked()
        if id_rows.shape[0] > IDS20_SENTINEL:
            raise ValueError(
                "20-bit id stream needs n_ids <= 2^20 - 1 (the padding "
                f"sentinel); table has {id_rows.shape[0]} id rows"
            )
        # Loudly reject a sibling API's buffer (raw i32 ids would be
        # silently truncated into in-range garbage decisions).
        if packed.shape[1] % 5 or packed.dtype != np.uint16:
            raise ValueError(
                "packed must be the u16[K, B + B//4] stream from "
                f"kernel.pack_ids20 (got {packed.dtype}"
                f"[..., {packed.shape[1]}])"
            )
        assert packed.shape[1] * 4 // 5 <= self.SCRATCH
        track_cur_safety(self, compact, params_cur_safe)
        self.note_launch_now(_host_max_now(now_ns))
        self.state, self.exp_acc, out = gcra_scan_ids20_acc(
            self.state,
            self.exp_acc,
            id_rows,
            packed
            if isinstance(packed, jax.Array)
            else jnp.asarray(packed, jnp.uint16),
            jnp.asarray(now_ns, jnp.int64),
            quantity,
            with_degen=with_degen,
            compact=compact,
        )
        return out

    def sweep(self, now_ns: int) -> np.ndarray:
        """Vacate expired slots; returns the boolean expired mask (host)."""
        if self.insight:
            # A vacated slot's denied-hit count dies with it: the slot
            # is about to be recycled for a different key.
            self.state, expired = sweep_expired_ins(
                now_ns, self.state, self.capacity
            )
        else:
            self.state, expired = sweep_expired(
                now_ns, self.state, self.capacity
            )
        return np.asarray(expired)

    def grow(self, new_capacity: int) -> None:
        """Double-style reallocation, like HashMap growth in the reference."""
        if new_capacity <= self.capacity:
            return
        extra = self._alloc(new_capacity - self.capacity)
        real = self.state[: self.capacity]
        scratch = self.state[self.capacity :]
        if self.insight:
            # New rows arrive 4-wide from _alloc; widen them to match
            # the insight row layout (zero heat).
            from .kernel import INS_WIDTH

            extra = jnp.concatenate(
                [
                    extra,
                    jnp.zeros(
                        (extra.shape[0], INS_WIDTH - 4), jnp.int32
                    ),
                ],
                axis=-1,
            )
        self.state = jnp.concatenate([real, extra[: new_capacity - self.capacity], scratch])
        self.capacity = new_capacity

    def live_count(self, now_ns: int) -> int:
        """Number of live (non-expired) entries; diagnostic only."""
        return int(jnp.sum(self.expiry > now_ns))


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False
