"""LEGACY Pallas TPU kernels for the bucket-table row gather/scatter.

STATUS: the ROW-MOVEMENT-ONLY design here is retired (NO-GO on
hardware — evidence below); it is NOT the fused decision kernel.
These kernels moved rows for a decision body that still ran as
composed XLA, and the on-device ablation showed row movement within
noise *inside one fused XLA computation* — a verdict on row movement
alone, not on fusion.  The successor, `pallas_fused.py`
(THROTTLECRAB_PALLAS_FUSED=1), fuses the ENTIRE per-window decision —
unpack, gather, closed forms in i32-pair arithmetic, pack, scatter —
into one launch, attacking the inter-op HBM round trips and dispatch
overhead this module's ablation never measured.  Do not read the
history below as condemning that work.

The round-4 hardware evidence (docs/tpu-launch-profile.md):

1. The CPU ablation that motivated these kernels (~85% of kernel time in
   row movement) does NOT transfer to the TPU: the on-device ablation
   measures `elementwise` (no gather, no scatter) within noise of the
   full body — on v5e the batch is latency-bound on the VPU pipeline,
   not on the row movement XLA emits.
2. The device-resident kernel already sustains ~10 M decisions/s; the
   end-to-end ceiling is the serving tunnel's ~10-50 MB/s link, which no
   kernel change can move.
3. The DMA-ring kernels themselves lower only after pinning every loop
   scalar to i32 (jax x64 makes Mosaic's scalar conversion recurse), and
   then the remote Mosaic compile helper crashes (HTTP 500, subprocess
   exit 1, no diagnostics) on the per-row 16-byte async copies — while
   trivial Pallas kernels compile and run fine through the same tunnel.

The design stands as documentation: a RING-deep window of per-row async
DMAs for gather and (unique-index) scatter, i64 GCRA arithmetic left to
XLA (TPU vector lanes are 32-bit; pallas_fused.py instead decomposes it
into i32 hi/lo pairs).  Enable with THROTTLECRAB_PALLAS=1,
set before the first kernel trace (each jit cache entry freezes the
choice at trace time).  Off-TPU the kernels run in interpret mode —
correct but orders of magnitude slower (the DMA ring is emulated); that
mode exists for the correctness tests, not for measurement.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_W = 4        # table row width (packed tat/expiry, kernel.pack_state)
RING = 16        # DMAs kept in flight per program
MAX_CHUNK = 512  # rows handled per grid program


def enabled() -> bool:
    """Whether the packed kernels route row movement through Pallas.
    Reads the environment on every call, so setting THROTTLECRAB_PALLAS
    before the first kernel trace is sufficient regardless of import
    order (traces cache the value per jit entry)."""
    return os.environ.get("THROTTLECRAB_PALLAS", "") not in ("", "0")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _chunk(B: int) -> int:
    c = min(MAX_CHUNK, B)
    while B % c:
        c //= 2
    if c < min(RING, B):
        # A chunk below the ring depth serializes the pipeline the
        # kernel exists to provide; callers pad batches to powers of two
        # (limiter MIN_PAD), so this only fires on misuse.
        raise ValueError(
            f"batch size {B} has no divisor >= {min(RING, B)} "
            f"<= {MAX_CHUNK}; pad the batch to a power of two"
        )
    return c


def _dma_pipeline(chunk: int, copy) -> None:
    """Issue `chunk` row DMAs through a RING-deep in-flight window.

    `copy(i)` must return the same descriptor for a given i on every
    call (start and wait reconstruct it); the start/wait/drain
    accounting lives here once so gather and scatter cannot diverge.
    """

    # All loop scalars pinned to i32: the package enables jax x64
    # globally, and i64 induction variables make Mosaic's scalar
    # conversion helper recurse forever at lowering time (observed on
    # v5e: RecursionError in _convert_helper).
    i32 = jnp.int32

    def body(i, _):
        @pl.when(i >= RING)
        def _():
            copy(i - i32(RING)).wait()

        copy(i).start()
        return i32(0)

    jax.lax.fori_loop(i32(0), i32(chunk), body, i32(0))

    def drain(i, _):
        copy(i32(max(chunk - RING, 0)) + i).wait()
        return i32(0)

    jax.lax.fori_loop(i32(0), i32(min(RING, chunk)), drain, i32(0))


def _gather_kernel(idx_ref, table_ref, out_ref, sem):
    base = pl.program_id(0) * jnp.int32(out_ref.shape[0])

    def copy(i):
        return pltpu.make_async_copy(
            table_ref.at[idx_ref[base + i]],
            out_ref.at[i],
            sem.at[i % RING],
        )

    _dma_pipeline(out_ref.shape[0], copy)


@functools.partial(jax.jit, static_argnames=())
def row_gather(table, idx):
    """rows[i] = table[idx[i]] — [B] random rows out of an HBM-resident
    [N, ROW_W] i32 table, via a RING-deep async-DMA pipeline."""
    B = idx.shape[0]
    chunk = _chunk(B)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B // chunk,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((chunk, ROW_W), lambda g, idx_ref: (g, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((RING,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, ROW_W), table.dtype),
        interpret=_interpret(),
    )(idx.astype(jnp.int32), table)


def _scatter_kernel(idx_ref, rows_ref, table_ref, out_ref, sem):
    base = pl.program_id(0) * jnp.int32(rows_ref.shape[0])

    def copy(i):
        return pltpu.make_async_copy(
            rows_ref.at[i],
            out_ref.at[idx_ref[base + i]],
            sem.at[i % RING],
        )

    _dma_pipeline(rows_ref.shape[0], copy)


@functools.partial(jax.jit, donate_argnums=(0,))
def row_scatter(table, idx, rows):
    """table[idx[i]] = rows[i] (idx unique by construction — the caller
    redirects suppressed writes to distinct scratch rows); the table is
    updated in place via input/output aliasing."""
    B = idx.shape[0]
    chunk = _chunk(B)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B // chunk,),
        in_specs=[
            pl.BlockSpec((chunk, ROW_W), lambda g, idx_ref: (g, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((RING,))],
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        # Operand indices include the scalar-prefetch arg: 0 = idx,
        # 1 = rows, 2 = table → table aliases the output.
        input_output_aliases={2: 0},
        interpret=_interpret(),
    )(idx.astype(jnp.int32), rows, table)
