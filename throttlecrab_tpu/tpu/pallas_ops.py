"""Pallas TPU kernels for the bucket-table row gather/scatter.

The CPU kernel ablation (scripts/probe_kernel_ablation.py, round 4) puts
~85% of the decision kernel's time in the random-row gather + scatter
over the [N, 4] i32 table; the GCRA math itself is cheap VPU work.  XLA
lowers a 4096-row random scatter conservatively, so these kernels do the
memory movement explicitly: a ring of small async DMAs (one 16-byte row
each) that overlap address latency instead of serializing on it, per
SURVEY §7.2 step 2's "drop to Pallas only if the gather/scatter
dominates" — which the ablation showed it does.

The i64 GCRA arithmetic stays in XLA (TPU vector lanes are 32-bit;
reimplementing 64-bit div/mul in-kernel would be all risk for no gain) —
the kernels move rows, XLA fuses the math between them.

Enable with THROTTLECRAB_PALLAS=1, set before the first kernel trace
(each jit cache entry freezes the choice at trace time).  Off-TPU the
kernels run in interpret mode — correct but orders of magnitude slower
(the DMA ring is emulated); that mode exists for the correctness tests,
not for measurement.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_W = 4        # table row width (packed tat/expiry, kernel.pack_state)
RING = 16        # DMAs kept in flight per program
MAX_CHUNK = 512  # rows handled per grid program


def enabled() -> bool:
    """Whether the packed kernels route row movement through Pallas.
    Reads the environment on every call, so setting THROTTLECRAB_PALLAS
    before the first kernel trace is sufficient regardless of import
    order (traces cache the value per jit entry)."""
    return os.environ.get("THROTTLECRAB_PALLAS", "") not in ("", "0")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _chunk(B: int) -> int:
    c = min(MAX_CHUNK, B)
    while B % c:
        c //= 2
    if c < min(RING, B):
        # A chunk below the ring depth serializes the pipeline the
        # kernel exists to provide; callers pad batches to powers of two
        # (limiter MIN_PAD), so this only fires on misuse.
        raise ValueError(
            f"batch size {B} has no divisor >= {min(RING, B)} "
            f"<= {MAX_CHUNK}; pad the batch to a power of two"
        )
    return c


def _dma_pipeline(chunk: int, copy) -> None:
    """Issue `chunk` row DMAs through a RING-deep in-flight window.

    `copy(i)` must return the same descriptor for a given i on every
    call (start and wait reconstruct it); the start/wait/drain
    accounting lives here once so gather and scatter cannot diverge.
    """

    def body(i, _):
        @pl.when(i >= RING)
        def _():
            copy(i - RING).wait()

        copy(i).start()
        return 0

    jax.lax.fori_loop(0, chunk, body, 0)

    def drain(i, _):
        copy(jnp.maximum(chunk - RING, 0) + i).wait()
        return 0

    jax.lax.fori_loop(0, min(RING, chunk), drain, 0)


def _gather_kernel(idx_ref, table_ref, out_ref, sem):
    base = pl.program_id(0) * out_ref.shape[0]

    def copy(i):
        return pltpu.make_async_copy(
            table_ref.at[idx_ref[base + i]],
            out_ref.at[i],
            sem.at[i % RING],
        )

    _dma_pipeline(out_ref.shape[0], copy)


@functools.partial(jax.jit, static_argnames=())
def row_gather(table, idx):
    """rows[i] = table[idx[i]] — [B] random rows out of an HBM-resident
    [N, ROW_W] i32 table, via a RING-deep async-DMA pipeline."""
    B = idx.shape[0]
    chunk = _chunk(B)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B // chunk,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((chunk, ROW_W), lambda g, idx_ref: (g, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((RING,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, ROW_W), table.dtype),
        interpret=_interpret(),
    )(idx.astype(jnp.int32), table)


def _scatter_kernel(idx_ref, rows_ref, table_ref, out_ref, sem):
    base = pl.program_id(0) * rows_ref.shape[0]

    def copy(i):
        return pltpu.make_async_copy(
            rows_ref.at[i],
            out_ref.at[idx_ref[base + i]],
            sem.at[i % RING],
        )

    _dma_pipeline(rows_ref.shape[0], copy)


@functools.partial(jax.jit, donate_argnums=(0,))
def row_scatter(table, idx, rows):
    """table[idx[i]] = rows[i] (idx unique by construction — the caller
    redirects suppressed writes to distinct scratch rows); the table is
    updated in place via input/output aliasing."""
    B = idx.shape[0]
    chunk = _chunk(B)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B // chunk,),
        in_specs=[
            pl.BlockSpec((chunk, ROW_W), lambda g, idx_ref: (g, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((RING,))],
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        # Operand indices include the scalar-prefetch arg: 0 = idx,
        # 1 = rows, 2 = table → table aliases the output.
        input_output_aliases={2: 0},
        interpret=_interpret(),
    )(idx.astype(jnp.int32), rows, table)
