"""Device-state snapshot / restore.

The reference intentionally has no persistence — rate-limit state is soft,
TTL-bounded, and a restart just resets buckets (SURVEY §5; the closest thing
is its capacity documentation, `docs/capacity-behavior.md`).  On the TPU the
whole table is two dense columns, which makes an optional point-in-time
snapshot nearly free: fetch the SoA arrays to host, pair them with the
keymap's key→slot assignment, and write one compressed npz.  Restoring hoists
the arrays straight back into HBM.

Snapshots are *best-effort soft state*: keys whose TTL lapsed between
snapshot and restore are dropped by the restore-time sweep, so a stale
snapshot degrades to an empty table — never to wrong decisions.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Union

import numpy as np

FORMAT_VERSION = 1


def save_snapshot(limiter, path: Union[str, Path]) -> int:
    """Write the limiter's live state to `path` (.npz); returns #keys saved.

    Works for TpuRateLimiter (single device).  Only live slots are saved:
    tat/expiry columns plus each slot's key bytes.
    """
    from .limiter import limiter_uses_bytes_keys

    path = Path(path)
    tat = np.asarray(limiter.table.tat)
    expiry = np.asarray(limiter.table.expiry)

    slots = []
    keys = []
    key_is_bytes = []
    key_codec = []  # 0 = surrogateescape, 1 = surrogatepass
    for key, slot in limiter.keymap.items():
        slots.append(slot)
        is_b = isinstance(key, (bytes, bytearray))
        key_is_bytes.append(is_b)
        if is_b:
            keys.append(bytes(key))
            key_codec.append(0)
        else:
            # surrogateescape round-trips keys decoded from raw bytes;
            # lone surrogates outside U+DC80-DCFF (JSON can deliver them)
            # need surrogatepass — record which codec per key so restore
            # reverses it exactly and one odd key can't lose a snapshot.
            try:
                keys.append(str(key).encode("utf-8", "surrogateescape"))
                key_codec.append(0)
            except UnicodeEncodeError:
                keys.append(str(key).encode("utf-8", "surrogatepass"))
                key_codec.append(1)
    slots = np.asarray(slots, np.int64)

    # Length-prefixed layout (offsets[n+1] + blob): binary-safe for keys
    # containing any byte, including NUL.
    offsets = np.zeros(len(keys) + 1, np.int64)
    if keys:
        np.cumsum([len(k) for k in keys], out=offsets[1:])
    key_blob = b"".join(keys)
    np.savez_compressed(
        path,
        version=np.int64(FORMAT_VERSION),
        capacity=np.int64(limiter.table.capacity),
        slots=slots,
        tat=tat[slots] if len(slots) else np.zeros(0, np.int64),
        expiry=expiry[slots] if len(slots) else np.zeros(0, np.int64),
        key_offsets=offsets,
        key_blob=np.frombuffer(key_blob, np.uint8),
        key_is_bytes=np.asarray(key_is_bytes, np.uint8),
        key_codec=np.asarray(key_codec, np.uint8),
        # The source keymap's key mode: a bytes-keyed (native) keymap
        # stores every key as bytes even when the transports spoke str —
        # the restore side needs this to translate identities correctly.
        source_bytes_keys=np.uint8(limiter_uses_bytes_keys(limiter)),
        meta=np.frombuffer(
            json.dumps({"n_keys": len(keys)}).encode(), np.uint8
        ),
    )
    return len(keys)


def load_snapshot(limiter, path: Union[str, Path], now_ns: int) -> int:
    """Restore a snapshot into a fresh limiter; returns #keys restored.

    `now_ns` gates restoration: entries already expired are skipped (the
    TTL contract holds across restarts).  The limiter must be empty.
    """
    from .limiter import limiter_uses_bytes_keys

    if len(limiter) != 0:
        raise ValueError("restore requires an empty limiter")
    path = Path(path)
    with np.load(path) as data:
        version = int(data["version"])
        if version != FORMAT_VERSION:
            raise ValueError(f"unsupported snapshot version {version}")
        tat = data["tat"]
        expiry = data["expiry"]
        offsets = data["key_offsets"]
        key_blob = data["key_blob"].tobytes()
        key_is_bytes = data["key_is_bytes"].astype(bool)
        key_codec = (
            data["key_codec"].astype(np.uint8)
            if "key_codec" in data
            else np.zeros(len(key_is_bytes), np.uint8)
        )
        source_bytes_keys = (
            bool(data["source_bytes_keys"])
            if "source_bytes_keys" in data
            else False
        )
        meta = json.loads(data["meta"].tobytes())

    n = len(offsets) - 1
    if meta["n_keys"] != n or len(tat) != n or len(expiry) != n:
        raise ValueError("corrupt snapshot: array lengths disagree")

    # Cross-backend identity translation: str-keyed transports look keys
    # up as str, bytes-keyed (native) keymaps as bytes.  A snapshot from a
    # native keymap marks everything bytes even though the transports used
    # str — restoring it into a python keymap must decode back to str
    # (surrogateescape: lossless for arbitrary bytes) or the restored
    # buckets would be silently unreachable.
    target_bytes_keys = limiter_uses_bytes_keys(limiter)
    live = expiry > now_ns
    restored = 0
    batch_keys = []
    batch_tat = []
    batch_exp = []
    for i in range(n):
        if not live[i]:
            continue
        raw = key_blob[offsets[i] : offsets[i + 1]]
        codec = "surrogatepass" if key_codec[i] else "surrogateescape"
        if target_bytes_keys:
            key = raw  # native keymaps hold bytes; str lookups encode
        elif source_bytes_keys and key_is_bytes[i]:
            key = raw.decode("utf-8", "surrogateescape")
        elif key_is_bytes[i]:
            key = raw  # genuinely-bytes key in a str keymap: keep as-is
        else:
            key = raw.decode("utf-8", codec)
        batch_keys.append(key)
        batch_tat.append(int(tat[i]))
        batch_exp.append(int(expiry[i]))
        restored += 1

    if restored:
        _bulk_insert(limiter, batch_keys, batch_tat, batch_exp)
    return restored


def _bulk_insert(limiter, keys, tats, expiries) -> None:
    """Allocate slots for `keys` and write their state rows directly."""
    import jax.numpy as jnp

    from .kernel import pack_state

    if getattr(limiter.keymap, "BYTES_KEYS", False):
        key_src = [
            k
            if isinstance(k, bytes)
            else k.encode("utf-8", "surrogateescape")
            for k in keys
        ]
    else:
        key_src = keys  # original identity preserved (str stays str)
    valid = np.ones(len(keys), bool)
    slots, _, _, n_full = limiter.keymap.resolve(key_src, valid)
    if n_full:
        raise ValueError("snapshot exceeds limiter capacity")
    rows = pack_state(
        jnp.asarray(tats, jnp.int64), jnp.asarray(expiries, jnp.int64)
    )
    limiter.table.state = limiter.table.state.at[
        jnp.asarray(slots, jnp.int32)
    ].set(rows)
