"""Device-state snapshot / restore.

The reference intentionally has no persistence — rate-limit state is soft,
TTL-bounded, and a restart just resets buckets (SURVEY §5; the closest thing
is its capacity documentation, `docs/capacity-behavior.md`).  On the TPU the
whole table is two dense columns, which makes an optional point-in-time
snapshot nearly free: fetch the SoA arrays to host, pair them with the
keymap's key→slot assignment, and write one compressed npz.  Restoring hoists
the arrays straight back into HBM.

Snapshots are *best-effort soft state*: keys whose TTL lapsed between
snapshot and restore are dropped by the restore-time sweep, so a stale
snapshot degrades to an empty table — never to wrong decisions.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Union

import numpy as np

from ..faults import fsync_with_faults, maybe_fail

FORMAT_VERSION = 2  # v2 adds the per-key `shard` column (v1 loads fine)

_U32 = (1 << 32) - 1


class SnapshotError(ValueError):
    """A snapshot file is corrupt, truncated, or otherwise unreadable.

    Subclasses ValueError so existing except-ValueError callers keep
    working; the boot path (server/__main__.py) catches it specifically
    to apply the THROTTLECRAB_SNAPSHOT_STRICT policy.
    """


def _encode_keys(keys):
    """keys → (key bytes, per-key is_bytes flag, per-key codec)."""
    out = []
    key_is_bytes = []
    key_codec = []  # 0 = surrogateescape, 1 = surrogatepass
    for key in keys:
        is_b = isinstance(key, (bytes, bytearray))
        key_is_bytes.append(is_b)
        if is_b:
            out.append(bytes(key))
            key_codec.append(0)
        else:
            # surrogateescape round-trips keys decoded from raw bytes;
            # lone surrogates outside U+DC80-DCFF (JSON can deliver them)
            # need surrogatepass — record which codec per key so restore
            # reverses it exactly and one odd key can't lose a snapshot.
            try:
                out.append(str(key).encode("utf-8", "surrogateescape"))
                key_codec.append(0)
            except UnicodeEncodeError:
                out.append(str(key).encode("utf-8", "surrogatepass"))
                key_codec.append(1)
    return out, key_is_bytes, key_codec


def export_state(limiter):
    """Fetch the limiter's live state host-side, without encoding it.

    Returns ``(keys, slots, shard, tat, expiry, capacity, n_shards)`` —
    original key objects (str/bytes exactly as the keymap holds them)
    plus i64 tat/expiry columns.  This is the shared first half of
    :func:`save_snapshot` and the launch supervisor's degraded-mode
    seeding (server/supervisor.py): on persistent device failure the
    supervisor exports this state to seed the host scalar oracle.

    A degraded SupervisedLimiter exports its host oracle's state (the
    freshest complete view — the device copy is stale the moment the
    oracle takes over); otherwise the device table is fetched.
    """
    local = getattr(limiter, "local", None)
    if local is not None:  # ClusterLimiter
        return export_state(local)
    degraded = getattr(limiter, "export_degraded_state", None)
    if degraded is not None:  # SupervisedLimiter
        host = degraded()
        if host is not None:
            keys, tats, exps = host
            n = len(keys)
            return (
                list(keys),
                np.full(n, -1, np.int64),
                np.zeros(n, np.int32),
                np.asarray(tats, np.int64),
                np.asarray(exps, np.int64),
                int(getattr(limiter, "total_capacity", 1 << 62)),
                1,
            )
        limiter = limiter.inner

    if hasattr(limiter, "keymaps"):  # ShardedTpuRateLimiter
        # [D, rows, 4] packed i32 — one gather off the mesh.
        state = np.asarray(limiter.table.state)
        per_shard = [km.items() for km in limiter.keymaps]
        keys = [k for p in per_shard for k, _ in p]
        slots = np.asarray(
            [s for p in per_shard for _, s in p], np.int64
        )
        shard = np.asarray(
            [d for d, p in enumerate(per_shard) for _ in p], np.int32
        )
        rows = state[shard, slots] if len(slots) else np.zeros(
            (0, 4), np.int32
        )
        tat = (rows[:, 1].astype(np.int64) << 32) | (
            rows[:, 0].astype(np.int64) & _U32
        )
        expiry = (rows[:, 3].astype(np.int64) << 32) | (
            rows[:, 2].astype(np.int64) & _U32
        )
        n_shards = int(getattr(limiter, "n_shards", 1))
    else:
        tat_col = np.asarray(limiter.table.tat)
        expiry_col = np.asarray(limiter.table.expiry)
        items = limiter.keymap.items()
        keys = [k for k, _ in items]
        slots = np.asarray([s for _, s in items], np.int64)
        shard = np.zeros(len(slots), np.int32)
        tat = tat_col[slots] if len(slots) else np.zeros(0, np.int64)
        expiry = (
            expiry_col[slots] if len(slots) else np.zeros(0, np.int64)
        )
        n_shards = 1
    return keys, slots, shard, tat, expiry, limiter.table.capacity, n_shards


def translate_key(
    raw: bytes,
    is_bytes: bool,
    codec: int,
    source_bytes_keys: bool,
    target_bytes_keys: bool,
):
    """Cross-backend key identity translation for restores.

    str-keyed transports look keys up as str, bytes-keyed (native)
    keymaps as bytes.  A snapshot from a native keymap marks everything
    bytes even though the transports used str — restoring it into a
    python keymap must decode back to str (surrogateescape: lossless
    for arbitrary bytes) or the restored buckets would be silently
    unreachable.  Shared by :func:`load_snapshot` and the checkpoint
    recovery scanner (persist/recovery.py), which must agree exactly.
    """
    if target_bytes_keys:
        return raw  # native keymaps hold bytes; str lookups encode
    if source_bytes_keys and is_bytes:
        return raw.decode("utf-8", "surrogateescape")
    if is_bytes:
        return raw  # genuinely-bytes key in a str keymap: keep as-is
    return raw.decode(
        "utf-8", "surrogatepass" if codec else "surrogateescape"
    )


def fsync_dir(path: Union[str, Path]) -> None:
    """fsync a directory so a just-renamed entry survives power loss.

    Best-effort: platforms/filesystems that refuse to open or fsync a
    directory degrade to the pre-fsync durability story rather than
    failing the save.
    """
    import os

    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _normalize(path: Union[str, Path]) -> Path:
    """np.savez_compressed appends .npz to suffix-less paths; normalize
    BOTH save and load so `--snapshot-path /data/state` round-trips
    (otherwise the save writes /data/state.npz and the restore's
    exists-check on /data/state silently never fires)."""
    path = Path(path)
    if path.suffix != ".npz":
        path = Path(str(path) + ".npz")
    return path


def export_snapshot_payload(limiter) -> dict:
    """Device/host export half of :func:`save_snapshot`.

    Touches only the limiter (device fetch + keymap walk) — no
    encoding, no compression, no file I/O — so it is the one part of a
    snapshot that belongs *under* the limiter lock.  The returned
    payload is self-contained: hand it to
    :func:`write_snapshot_payload` outside the lock.
    """
    from .limiter import limiter_uses_bytes_keys

    local = getattr(limiter, "local", None)
    if local is not None:  # ClusterLimiter
        return export_snapshot_payload(local)
    raw_keys, slots, shard, tat, expiry, capacity, n_shards = (
        export_state(limiter)
    )
    return {
        "keys": raw_keys,
        "slots": slots,
        "shard": shard,
        "tat": tat,
        "expiry": expiry,
        "capacity": capacity,
        "n_shards": n_shards,
        # The source keymap's key mode: a bytes-keyed (native) keymap
        # stores every key as bytes even when the transports spoke str —
        # the restore side needs this to translate identities correctly.
        "source_bytes_keys": limiter_uses_bytes_keys(limiter),
    }


def write_snapshot_payload(payload: dict, path: Union[str, Path]) -> int:
    """Encode + compress + durably write an exported payload to `path`.

    The slow half of :func:`save_snapshot`: npz compression and file
    I/O with no limiter access at all — call it with every limiter
    lock released.  Durable, not just atomic: the tmp file is fsynced
    before the rename and the parent directory after it, so a crash
    shortly after a "successful" save cannot surface an empty or torn
    file on ext4/xfs.
    """
    import os

    path = _normalize(path)
    keys, key_is_bytes, key_codec = _encode_keys(payload["keys"])

    # Length-prefixed layout (offsets[n+1] + blob): binary-safe for keys
    # containing any byte, including NUL.
    offsets = np.zeros(len(keys) + 1, np.int64)
    if keys:
        np.cumsum([len(k) for k in keys], out=offsets[1:])
    key_blob = b"".join(keys)
    # Atomic write: a kill mid-save must never clobber the previous good
    # snapshot (np.savez_compressed writes the destination in place).
    maybe_fail("snapshot")
    tmp = path.with_name(path.name + ".tmp")
    try:
        _write_npz_tmp(tmp, payload, offsets, key_blob, key_is_bytes,
                       key_codec, len(keys))
    except BaseException:
        # A failed (or unsynced) write must leave neither a torn final
        # file nor a stray tmp — the previous good snapshot stands.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)
    fsync_dir(path.parent)
    return len(keys)


def _write_npz_tmp(
    tmp, payload, offsets, key_blob, key_is_bytes, key_codec, n_keys
) -> None:
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            version=np.int64(FORMAT_VERSION),
            capacity=np.int64(payload["capacity"]),
            slots=payload["slots"],
            shard=payload["shard"],
            n_shards=np.int64(payload["n_shards"]),
            tat=payload["tat"],
            expiry=payload["expiry"],
            key_offsets=offsets,
            key_blob=np.frombuffer(key_blob, np.uint8),
            key_is_bytes=np.asarray(key_is_bytes, np.uint8),
            key_codec=np.asarray(key_codec, np.uint8),
            source_bytes_keys=np.uint8(payload["source_bytes_keys"]),
            meta=np.frombuffer(
                json.dumps({"n_keys": n_keys}).encode(), np.uint8
            ),
        )
        f.flush()
        fsync_with_faults("snapshot", f.fileno())


def save_snapshot(limiter, path: Union[str, Path]) -> int:
    """Write the limiter's live state to `path` (.npz); returns #keys saved.

    Works for TpuRateLimiter (single device), ShardedTpuRateLimiter
    (per-shard columns in one npz), and ClusterLimiter (delegates to the
    node's local limiter — each cluster node owns its key range, so a
    cluster snapshot is one file per node, like one RDB per Redis shard).
    Only live slots are saved: tat/expiry columns plus each slot's key
    bytes.  Composes :func:`export_snapshot_payload` (device half) and
    :func:`write_snapshot_payload` (I/O half); callers holding the
    limiter lock should run the two halves separately so compression
    and fsync happen outside it.
    """
    return write_snapshot_payload(export_snapshot_payload(limiter), path)


def load_snapshot(
    limiter, path: Union[str, Path], now_ns: int, front=None
) -> int:
    """Restore a snapshot into a fresh limiter; returns #keys restored.

    `now_ns` gates restoration: entries already expired are skipped (the
    TTL contract holds across restarts).  The limiter must be empty.
    `front` (an optional front.FrontTier) is fully invalidated — the
    restore rewrites bucket state out from under any cached denials.

    Shard topology is NOT part of the contract: a snapshot taken on D
    shards restores onto any shard count (including a single-device
    limiter, or vice versa) — keys are re-routed by the target's own
    key→shard hash at restore time.  ClusterLimiter targets restore into
    their local node (pair each node with its own snapshot file).
    """
    from .limiter import limiter_uses_bytes_keys

    local = getattr(limiter, "local", None)
    if local is not None:  # ClusterLimiter
        return load_snapshot(local, path, now_ns, front=front)

    if front is not None:
        front.on_restore()
    if len(limiter) != 0:
        raise ValueError("restore requires an empty limiter")
    path = _normalize(path)
    maybe_fail("snapshot")
    # Everything below reads attacker-or-corruption-shaped bytes: a
    # truncated npz raises BadZipFile/EOFError/zlib.error depending on
    # where the truncation landed, a damaged member raises ValueError,
    # and a missing column raises KeyError.  All of them must surface
    # as one typed SnapshotError so the boot path can apply the
    # THROTTLECRAB_SNAPSHOT_STRICT policy instead of crashing.
    import zipfile
    import zlib

    try:
        with np.load(path) as data:
            version = int(data["version"])
            if version not in (1, FORMAT_VERSION):
                raise SnapshotError(
                    f"unsupported snapshot version {version}"
                )
            tat = data["tat"]
            expiry = data["expiry"]
            offsets = data["key_offsets"]
            key_blob = data["key_blob"].tobytes()
            key_is_bytes = data["key_is_bytes"].astype(bool)
            key_codec = (
                data["key_codec"].astype(np.uint8)
                if "key_codec" in data
                else np.zeros(len(key_is_bytes), np.uint8)
            )
            source_bytes_keys = (
                bool(data["source_bytes_keys"])
                if "source_bytes_keys" in data
                else False
            )
            meta = json.loads(data["meta"].tobytes())
    except SnapshotError:
        raise
    except (
        OSError,
        KeyError,
        ValueError,
        EOFError,
        zipfile.BadZipFile,
        zlib.error,
        json.JSONDecodeError,
    ) as e:
        raise SnapshotError(
            f"corrupt or unreadable snapshot {path}: {e}"
        ) from e

    n = len(offsets) - 1
    if (
        n < 0
        or meta.get("n_keys") != n
        or len(tat) != n
        or len(expiry) != n
        or len(key_is_bytes) != n
        or len(key_codec) != n
    ):
        raise SnapshotError("corrupt snapshot: array lengths disagree")
    if n and (
        int(offsets[0]) != 0
        or bool((np.diff(offsets) < 0).any())
        or int(offsets[-1]) != len(key_blob)
    ):
        raise SnapshotError("corrupt snapshot: key offsets inconsistent")

    # Cross-backend identity translation: see translate_key.
    target_bytes_keys = limiter_uses_bytes_keys(limiter)
    live = expiry > now_ns
    restored = 0
    batch_keys = []
    batch_tat = []
    batch_exp = []
    for i in range(n):
        if not live[i]:
            continue
        raw = key_blob[offsets[i] : offsets[i + 1]]
        key = translate_key(
            raw,
            bool(key_is_bytes[i]),
            int(key_codec[i]),
            source_bytes_keys,
            target_bytes_keys,
        )
        batch_keys.append(key)
        batch_tat.append(int(tat[i]))
        batch_exp.append(int(expiry[i]))
        restored += 1

    if restored:
        restored = _bulk_insert(limiter, batch_keys, batch_tat, batch_exp)
    return restored


def _reattribute_tenants(limiter) -> None:
    """Rebuild a sharded limiter's per-tenant slot-quota bookkeeping
    after a bulk restore (no-op when the quota is unarmed): restored
    slots were allocated behind the prepare path's back, and an
    unattributed live slot would otherwise be mistaken for a fresh
    allocation — and could be quota-refused and freed, losing its
    restored state — on its first post-restore touch."""
    tos_list = getattr(limiter, "_tenant_of_slot", None)
    if tos_list is None:
        return
    reg = limiter.tenants
    for d, km in enumerate(limiter.keymaps):
        tos = tos_list[d]
        used = limiter._tenant_used[d]
        tos[:] = -1
        used[:] = 0
        for key, slot in km.items():
            kb = (
                key
                if isinstance(key, bytes)
                else str(key).encode("utf-8", "surrogateescape")
            )
            p = kb.find(reg.delim_byte)
            tid = reg.tid_of(kb[:p] if p > 0 else b"")
            if 0 <= slot < len(tos):
                tos[slot] = tid
                used[tid] += 1


def _bulk_insert(limiter, keys, tats, expiries) -> int:
    """Allocate slots for `keys` and write their state rows directly;
    returns the number actually inserted.

    Sharded targets re-route every key through the target's own
    key→shard hash (the snapshot's shard column is advisory only), so a
    D-shard snapshot restores onto any shard count."""
    import jax.numpy as jnp

    from .kernel import pack_state
    from .table import tats_cur_safe

    # Restored TATs are foreign state: the table's cross-launch
    # compact="cur" certificate (table.cur_safe) only survives if every
    # restored value sits in the proven-safe range (see track_cur_safety).
    if not tats_cur_safe(tats):
        limiter.table.cur_safe = False
    # The w32 tier's tighter bound needs the tolerance high-water mark
    # to cover restored state too: each entry's write-time tolerance is
    # recoverable as expiry - tat (kernel _finish: expiry = tat + tol,
    # saturated to i64max for never-expires — which correctly saturates
    # the mark and disables w32).
    tat_arr = np.asarray(tats, np.int64)
    exp_arr = np.asarray(expiries, np.int64)
    note = getattr(limiter.table, "note_max_tolerance", None)
    if note is not None:
        # expiry - tat can wrap i64 for pathological foreign entries
        # (negative tat with I64_MAX expiry); probe the difference in
        # f64 first (no wrap, error <= ~2^11 ns at i64 magnitudes) and
        # saturate anything at or beyond 2^61 — note(None) disables w32,
        # so over-saturating near the boundary is always safe.  The
        # surviving lanes are < 2^61 + rounding, so the int64 subtract
        # below cannot wrap.  All numpy, no per-element Python.
        diff_f = exp_arr.astype(np.float64) - tat_arr.astype(np.float64)
        sat = (exp_arr >= (1 << 62)) | (diff_f >= float(1 << 61))
        if bool(sat.any()):
            note(None)
        else:
            # Wrap-free: the f64 probe above saturated every lane whose
            # difference could approach 2**61.
            note(int((exp_arr - tat_arr).max(initial=0)))  # inv: allow(i64-raw-op)
    # The restored TATs also embed the WRITER's clock: tat <= writer_now
    # + tol, and a reader whose clock lags the writer would pass the w32
    # certificate while reset/retry overflow their fields.  Seeding
    # now_hwm with the max restored TAT restores the invariant
    # stored <= now_hwm + tol_hwm outright (tat <= max_tat), so w32
    # stays off exactly until the reader's clock catches up.
    note_now = getattr(limiter.table, "note_launch_now", None)
    if note_now is not None:
        restored_tat = int(tat_arr.max(initial=0))
        note_now(restored_tat if restored_tat < (1 << 62) else None)

    if hasattr(limiter, "keymaps"):  # ShardedTpuRateLimiter
        import jax

        D = limiter.n_shards
        by_shard: list = [[] for _ in range(D)]
        skipped = 0
        for i, k in enumerate(keys):
            if isinstance(k, bytes):
                kb = k
            else:
                try:
                    kb = str(k).encode()
                except UnicodeEncodeError:
                    # A lone-surrogate str key cannot be routed (the
                    # sharded limiter's own decide path strict-encodes
                    # keys the same way, so it could never serve this
                    # key anyway).  Skip it — one odd key must not lose
                    # the whole snapshot.
                    skipped += 1
                    continue
            # The LIMITER's routing, not the bare hash: tenant-affine
            # deployments route by namespace prefix, and a restored key
            # must land on the shard the serving path will probe.
            by_shard[limiter.shard_of(kb)].append(i)
        # np.array (not asarray): jax arrays surface as read-only views.
        state = np.array(limiter.table.state)  # [D, rows, W]
        for d, ix in enumerate(by_shard):
            if not ix:
                continue
            km = limiter.keymaps[d]
            if getattr(km, "BYTES_KEYS", False):
                key_src = [
                    keys[i]
                    if isinstance(keys[i], bytes)
                    else keys[i].encode("utf-8", "surrogateescape")
                    for i in ix
                ]
            else:
                key_src = [keys[i] for i in ix]
            valid = np.ones(len(ix), bool)
            slots, _, _, n_full = km.resolve(key_src, valid)
            if n_full:
                raise ValueError("snapshot exceeds limiter capacity")
            rows = np.asarray(
                pack_state(
                    jnp.asarray([tats[i] for i in ix], jnp.int64),
                    jnp.asarray([expiries[i] for i in ix], jnp.int64),
                )
            )
            if state.shape[-1] > rows.shape[-1]:
                # Insight-widened shard rows: restored keys start with
                # zero heat, like the single-device restore path.
                rows = np.concatenate(
                    [
                        rows,
                        np.zeros(
                            (len(ix), state.shape[-1] - rows.shape[-1]),
                            np.int32,
                        ),
                    ],
                    axis=-1,
                )
            state[d, slots] = rows
        limiter.table.state = jax.device_put(
            state, limiter.table.sharding
        )
        _reattribute_tenants(limiter)
        return len(keys) - skipped

    if getattr(limiter.keymap, "BYTES_KEYS", False):
        key_src = [
            k
            if isinstance(k, bytes)
            else k.encode("utf-8", "surrogateescape")
            for k in keys
        ]
    else:
        key_src = keys  # original identity preserved (str stays str)
    valid = np.ones(len(keys), bool)
    slots, _, _, n_full = limiter.keymap.resolve(key_src, valid)
    if n_full:
        raise ValueError("snapshot exceeds limiter capacity")
    rows = pack_state(
        jnp.asarray(tats, jnp.int64), jnp.asarray(expiries, jnp.int64)
    )
    width = limiter.table.state.shape[-1]
    if width > rows.shape[-1]:
        # Insight-widened rows: restored/re-promoted keys start with a
        # cold denied-hit counter (analytics are soft state; the host
        # sketch keeps the history that matters).
        rows = jnp.concatenate(
            [
                rows,
                jnp.zeros(
                    rows.shape[:-1] + (width - rows.shape[-1],),
                    jnp.int32,
                ),
            ],
            axis=-1,
        )
    limiter.table.state = limiter.table.state.at[
        jnp.asarray(slots, jnp.int32)
    ].set(rows)
    return len(keys)
