"""Host-side key→slot mapping.

The reference hashes string keys straight into its HashMap on every request
(`periodic.rs:151-209`); here the hot path is on the TPU, so the host's only
job is resolving string keys to dense slot indices.  This module provides
the pure-Python implementation; native/keymap.cpp is the drop-in C++
open-addressing version with the same interface, used when available for
multi-million-lookups-per-second workloads (see SURVEY.md §7.4 hard part 2).

Slot lifecycle: allocated on first sight of a key, recycled through a free
list when a cleanup sweep reports the slot expired (limiter.sweep).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np


class PyKeyMap:
    """Dict-backed key→slot table with a free list."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._map: dict = {}
        # Stack of free slots; pop from the end (low indices first).
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._rev: List[Optional[object]] = [None] * capacity
        # Bumped by every slot-remapping operation (sweep frees, growth);
        # device-resident id rows (table.ResidentIdRows) pin the value
        # they were built at and refuse to serve once it moves.
        self.mutations = 0

    def __len__(self) -> int:
        return len(self._map)

    def resolve(self, keys: Sequence, valid: np.ndarray):
        """Resolve each key to a slot, allocating on miss, and emit the
        kernel's duplicate-segment structure in the same pass.

        Returns (slots, rank, is_last, n_full): slots are -1 where `valid`
        is False or the table is full (n_full counts the latter; the caller
        grows and retries those).
        """
        n = len(keys)
        slots = np.full(n, -1, np.int32)
        rank = np.zeros(n, np.int32)
        is_last = np.ones(n, bool)
        n_full = 0
        get = self._map.get
        free = self._free
        batch_seen: dict = {}
        for i, key in enumerate(keys):
            if not valid[i]:
                continue
            slot = get(key)
            if slot is None:
                if not free:
                    n_full += 1
                    continue
                slot = free.pop()
                self._map[key] = slot
                self._rev[slot] = key
            slots[i] = slot
            st = batch_seen.get(slot)
            if st is None:
                batch_seen[slot] = [1, i]
            else:
                rank[i] = st[0]
                st[0] += 1
                is_last[st[1]] = False
                st[1] = i
        return slots, rank, is_last, n_full

    def free_slots(self, slot_indices: Iterable[int]) -> int:
        """Recycle slots reported expired by a sweep; returns count freed."""
        n = 0
        for slot in slot_indices:
            key = self._rev[slot]
            if key is None:
                continue
            del self._map[key]
            self._rev[slot] = None
            self._free.append(slot)
            n += 1
        if n:
            self.mutations += 1
        return n

    def grow(self, new_capacity: int) -> None:
        if new_capacity <= self.capacity:
            return
        self._free.extend(range(new_capacity - 1, self.capacity - 1, -1))
        self._rev.extend([None] * (new_capacity - self.capacity))
        self.capacity = new_capacity
        self.mutations += 1

    def items(self):
        """(key, slot) pairs for every live entry (snapshot export)."""
        return list(self._map.items())
