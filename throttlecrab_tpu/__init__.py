"""throttlecrab-tpu: a TPU-native GCRA rate-limiting framework.

A ground-up re-design of the capabilities of `lazureykis/throttlecrab`
(reference: /root/reference) for TPU hardware:

- **core**: scalar GCRA engine + in-memory stores with the exact semantics of
  the reference library (`throttlecrab/src/core/rate_limiter.rs:102-250`).
  Pure Python, used as the correctness oracle and CPU fallback.
- **tpu**: the TPU execution backend — a Structure-of-Arrays bucket table in
  HBM and a batched, jitted GCRA decision kernel (vmap'd over request
  tensors), with cleanup-as-compaction sweeps.
- **parallel**: multi-device sharding of the bucket table over a
  `jax.sharding.Mesh` with psum-reduced metrics.
- **server**: micro-batching front-end plus HTTP/JSON, Redis/RESP and gRPC
  transports mirroring the reference server's wire formats
  (`throttlecrab-server/src/transport/`).

Time is always an explicit input (integer nanoseconds since the Unix epoch),
never ambient state — the reference's key testability property
(`rate_limiter.rs:109`).
"""

from __future__ import annotations

import os

# The GCRA state (theoretical-arrival-time) is i64 nanoseconds since epoch;
# the device kernels need real int64, which JAX disables by default.  The
# framework owns the process (it is a server), so enable x64 before any JAX
# computation is traced.  Opt out with THROTTLECRAB_TPU_NO_X64=1.
if not os.environ.get("THROTTLECRAB_TPU_NO_X64"):
    try:
        import jax

        jax.config.update("jax_enable_x64", True)
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        pass

from .core.errors import (  # noqa: E402
    CellError,
    InternalError,
    InvalidRateLimit,
    NegativeQuantity,
)
from .core.rate import Rate  # noqa: E402
from .core.rate_limiter import RateLimiter, RateLimitResult  # noqa: E402
from .core.store import (  # noqa: E402
    AdaptiveStore,
    PeriodicStore,
    ProbabilisticStore,
    Store,
)

__version__ = "0.1.0"

__all__ = [
    "AdaptiveStore",
    "CellError",
    "InternalError",
    "InvalidRateLimit",
    "NegativeQuantity",
    "PeriodicStore",
    "ProbabilisticStore",
    "Rate",
    "RateLimiter",
    "RateLimitResult",
    "Store",
    "__version__",
]
