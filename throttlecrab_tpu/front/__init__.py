"""Front tier (L3.5): exact deny cache + admission control.

Sits between the transports (L4) and the batching engine (L3).  Under
abuse/hot-key traffic — the exact scenario rate limiters exist for —
most requests are denials, and GCRA's exact `retry_after` makes those
denials *provably* answerable without a device round trip (deny_cache).
Under overload, bounded shedding with two priority classes replaces the
engine's unbounded future queue (admission).  The worst-case traffic
becomes the cheapest traffic.

One FrontTier instance is shared by every transport driving the same
limiter (the asyncio engine and the native C++ wire drivers), so an
allowed decision on any transport invalidates cached denials for all of
them.  All methods are thread-safe.

Key identity matches the limiter's keymap (`bytes_keys`): the cache
normalizes str/bytes exactly like the transports do, so one client key
is one cache row no matter which wire it arrived on.
"""

from __future__ import annotations

from .admission import (  # noqa: F401  (re-exported API)
    OVERLOAD_MESSAGE,
    STATUS_OVERLOADED,
    AdmissionController,
    OverloadError,
)
from .deny_cache import DenyCache, DenyHit  # noqa: F401


class FrontTier:
    """Facade combining the deny cache and the admission controller."""

    def __init__(self, deny_cache=None, admission=None, metrics=None,
                 bytes_keys: bool = False) -> None:
        self.deny_cache = deny_cache
        self.admission = admission
        self.metrics = metrics
        self.bytes_keys = bytes_keys
        # Insight tier (L3.75), when attached: cache-served denials are
        # reported there so /stats totals cover ALL served denials, not
        # just device-decided ones (the cache exists precisely so the
        # hottest denials never reach the device).
        self.insight = None

    # ------------------------------------------------------------------ #

    def _norm_key(self, key):
        """Match the limiter keymap's key identity (one client key, one
        bucket, one cache row across str- and bytes-keyed transports).
        Returns None for keys the limiter itself cannot encode."""
        if self.bytes_keys:
            if isinstance(key, str):
                try:
                    return key.encode()
                except UnicodeEncodeError:
                    return None
            return key
        if isinstance(key, (bytes, bytearray)):
            return bytes(key).decode("utf-8", "surrogateescape")
        return key

    # ------------------------------------------------------------------ #

    def lookup(self, key, max_burst, count_per_period, period, quantity,
               now_ns):
        """Exact cached denial for this request, or None."""
        if self.deny_cache is None:
            return None
        k = self._norm_key(key)
        if k is None:
            return None
        stale_before = self.deny_cache.stale_evictions
        hit = self.deny_cache.lookup(
            k, max_burst, count_per_period, period, quantity, now_ns
        )
        self._flush_stale(stale_before)
        if hit is not None:
            if self.metrics is not None:
                self.metrics.record_front_hit()
            if self.insight is not None:
                self.insight.record_front_denied((k,))
        return hit

    def admit(self, depth: int, peek: bool) -> bool:
        if self.admission is None:
            return True
        ok = self.admission.admit(depth, peek)
        if not ok and self.metrics is not None:
            self.metrics.record_front_shed(peek)
        return ok

    def record_launch(self, n_requests: int, elapsed_s: float) -> None:
        if self.admission is not None:
            self.admission.record_launch(n_requests, elapsed_s)

    # ------------------------------------------------------------------ #

    def next_seq(self) -> int:
        # NB: `is not None`, not truthiness — DenyCache.__len__ makes an
        # *empty* cache falsy, and seq must advance from the first launch.
        if self.deny_cache is None:
            return 0
        return self.deny_cache.next_seq()

    def begin_inflight(self, key) -> None:
        if self.deny_cache is not None:
            k = self._norm_key(key)
            if k is not None:
                self.deny_cache.begin_inflight(k)

    def end_inflight(self, key) -> None:
        if self.deny_cache is not None:
            k = self._norm_key(key)
            if k is not None:
                self.deny_cache.end_inflight(k)

    def lookup_window(self, keys, max_burst, count_per_period, period,
                      quantity, now_ns, mark_inflight: bool = True):
        """Bulk exact-denial lookup for one shared-timestamp window
        (DenyCache.lookup_window); keys must already be normalized to
        the limiter's key identity (the native driver's are).  Returns
        (rows, n_hits); missing keys are marked in-flight when
        `mark_inflight` — release them via observe_window."""
        if self.deny_cache is None:
            return [None] * len(keys), 0
        stale_before = self.deny_cache.stale_evictions
        rows, n_hits = self.deny_cache.lookup_window(
            keys, max_burst, count_per_period, period, quantity, now_ns,
            mark_inflight=mark_inflight,
        )
        self._flush_stale(stale_before)
        if n_hits:
            if self.metrics is not None:
                self.metrics.record_front_hits(n_hits)
            if self.insight is not None:
                self.insight.record_front_denied(
                    k for k, r in zip(keys, rows) if r is not None
                )
        return rows, n_hits

    def observe_window(self, rows, now_ns, seq) -> None:
        """Bulk observe + in-flight release for one decided window
        (DenyCache.observe_window); rows are (key, mb, cpp, period, q,
        allowed, cur_ns) in arrival order, keys pre-normalized."""
        if self.deny_cache is None:
            return
        stale_before = self.deny_cache.stale_evictions
        self.deny_cache.observe_window(rows, now_ns, seq)
        self._flush_stale(stale_before)

    def release_window(self, keys) -> None:
        """Release in-flight holds for rows that never reached a launch
        (shed rows)."""
        if self.deny_cache is not None:
            self.deny_cache.release_window(keys)

    def fail_window(self, keys) -> None:
        """A launch failed after its writes may have committed: release
        the rows' holds and conservatively drop their keys' cached
        denials and write records (keys may be unnormalized)."""
        if self.deny_cache is None:
            return
        norm = []
        for key in keys:
            k = self._norm_key(key)
            if k is not None:
                norm.append(k)
        self.deny_cache.fail_window(norm)

    def observe(self, key, max_burst, count_per_period, period, quantity,
                now_ns, allowed, seq, cur_ns=None, reset_after_ns=None,
                retry_after_ns=None) -> None:
        if self.deny_cache is None:
            return
        k = self._norm_key(key)
        if k is None:
            return
        stale_before = self.deny_cache.stale_evictions
        self.deny_cache.observe(
            k, max_burst, count_per_period, period, quantity, now_ns,
            allowed, seq, cur_ns=cur_ns, reset_after_ns=reset_after_ns,
            retry_after_ns=retry_after_ns,
        )
        self._flush_stale(stale_before)

    def prewarm(self, keys) -> int:
        """Insight-tier feedback: refresh confirmed hot-denied keys to
        the back of the deny cache's eviction queues (nothing is
        created — exactness is untouched).  Keys may be unnormalized;
        returns the number of keys actually refreshed."""
        if self.deny_cache is None:
            return 0
        norm = []
        for key in keys:
            k = self._norm_key(key)
            if k is not None:
                norm.append(k)
        if not norm:
            return 0
        return self.deny_cache.prewarm(norm)

    def on_sweep(self, now_ns: int) -> None:
        if self.deny_cache is None:
            return
        n = self.deny_cache.on_sweep(now_ns)
        if n and self.metrics is not None:
            self.metrics.record_front_stale(n)

    def on_restore(self) -> None:
        """A snapshot restore rewrote bucket state: drop everything."""
        if self.deny_cache is not None:
            self.deny_cache.clear()

    def _flush_stale(self, before: int) -> None:
        if self.metrics is not None:
            delta = self.deny_cache.stale_evictions - before
            if delta:
                self.metrics.record_front_stale(delta)

    # ------------------------------------------------------------------ #

    def stats(self) -> dict:
        """Gauge snapshot for the metrics exporter."""
        out = {"deny_cache_size": 0}
        if self.deny_cache is not None:
            out["deny_cache_size"] = len(self.deny_cache)
        return out
