"""Admission control: bounded shedding instead of unbounded queueing.

The reference funnels every transport into a *bounded* mpsc channel
(`--buffer-size`); a full channel is backpressure.  The asyncio engine
has no such bound — every accepted request appends a future to the
pending deque — so a burst beyond device throughput stacks memory and
latency without limit.  The admission controller restores the bound and
makes it latency-aware:

  * **queue depth**: past `max_pending` requests already waiting, new
    arrivals shed immediately with an overload status (the reference's
    full-channel condition, surfaced instead of silently awaited);
  * **estimated wait**: the engine feeds per-launch (size, seconds)
    samples; an EWMA of per-request decide cost turns queue depth into
    an expected linger, and arrivals that would wait longer than
    `max_wait_us` shed even below the depth bound;
  * **two priority classes**: peek/read-only probes (quantity == 0 —
    they consume nothing and are advisory by contract) shed first, at
    `peek_frac` of either bound, keeping headroom for the consuming
    decisions that actually enforce limits.

Shedding is the *correct* overload behavior for a rate limiter: a
rate-limit check that waits out an unbounded queue protects nothing.
"""

from __future__ import annotations

import threading

OVERLOAD_MESSAGE = "server overloaded"

# Per-request status code for shed requests on the native wire path —
# continues tpu.limiter's STATUS_* space (0=ok .. 3=internal); the C++
# wire layer (native/wire_server.cpp ws_respond) maps it to HTTP 503 /
# RESP "-ERR server overloaded".
STATUS_OVERLOADED = 4

# Peek probes (quantity 0) shed at this fraction of each bound unless
# configured otherwise.
DEFAULT_PEEK_FRAC = 0.9

# EWMA smoothing for per-request decide cost (per launch sample).
_ALPHA = 0.2


class OverloadError(Exception):
    """Request shed by admission control; each transport maps it to its
    protocol's overload status (HTTP 503 / gRPC RESOURCE_EXHAUSTED /
    RESP -ERR)."""

    def __init__(self, message: str = OVERLOAD_MESSAGE) -> None:
        super().__init__(message)


class AdmissionController:
    """Queue-depth + estimated-wait shedding with peek/consume classes."""

    def __init__(
        self,
        max_pending: int = 0,
        max_wait_us: int = 0,
        peek_frac: float = DEFAULT_PEEK_FRAC,
    ) -> None:
        """`max_pending` bounds queued requests (0 disables);
        `max_wait_us` bounds the EWMA-estimated queue wait (0 disables);
        `peek_frac` scales both bounds for quantity-0 probes."""
        if max_pending < 0 or max_wait_us < 0:
            raise ValueError("admission bounds must be non-negative")
        if not 0.0 < peek_frac <= 1.0:
            raise ValueError("peek_frac must be in (0, 1]")
        self.max_pending = max_pending
        self.max_wait_us = max_wait_us
        self.peek_frac = peek_frac
        self._lock = threading.Lock()
        self._cost_us: float = 0.0  # EWMA per-request decide cost
        self.shed_peek = 0
        self.shed_consume = 0
        # Insight-tier feedback (L3.75): `hot_concentration` is the
        # share of recent denials landing on the hot set (set per poll
        # via set_hot_concentration); `hot_shed_weight` scales how hard
        # it tightens the PEEK bounds — consuming checks keep their
        # configured bounds, only advisory probes shed earlier when the
        # traffic is concentrated abuse.  Weight 0 (the default and the
        # THROTTLECRAB_INSIGHT=0 state) reproduces today's behavior
        # exactly.
        self.hot_concentration = 0.0
        self.hot_shed_weight = 0.0

    # ------------------------------------------------------------------ #

    def record_launch(self, n_requests: int, elapsed_s: float) -> None:
        """One decide launch finished: fold its per-request cost into
        the EWMA the wait estimate uses.  Called from executor/driver
        threads; the lock keeps the float update coherent."""
        if n_requests <= 0 or elapsed_s < 0:
            return
        sample_us = elapsed_s * 1e6 / n_requests
        with self._lock:
            if self._cost_us == 0.0:
                self._cost_us = sample_us
            else:
                self._cost_us += _ALPHA * (sample_us - self._cost_us)

    def estimated_wait_us(self, depth: int) -> float:
        return depth * self._cost_us

    # ------------------------------------------------------------------ #

    def set_hot_concentration(self, frac: float) -> None:
        """Feed the insight tier's hot-set concentration (clamped to
        [0, 1]); no lock needed — a float store is atomic and admit()
        tolerates any interleaving."""
        self.hot_concentration = min(max(float(frac), 0.0), 1.0)

    def admit(self, depth: int, peek: bool) -> bool:
        """Admit a new arrival given `depth` requests already pending?
        Counts the shed when refusing."""
        frac = self.peek_frac if peek else 1.0
        if peek and self.hot_shed_weight:
            # Concentrated abuse: tighten the peek bounds so advisory
            # probes yield headroom to the consuming checks absorbing
            # the attack.  Floor at 10% so peeks are throttled, never
            # starved outright.
            frac *= max(
                1.0 - self.hot_shed_weight * self.hot_concentration, 0.1
            )
        over = False
        if self.max_pending and depth >= self.max_pending * frac:
            over = True
        elif self.max_wait_us and self._cost_us:
            over = depth * self._cost_us > self.max_wait_us * frac
        if over:
            with self._lock:
                if peek:
                    self.shed_peek += 1
                else:
                    self.shed_consume += 1
        return not over
