"""Exact negative-decision cache: GCRA denials answered without the engine.

GCRA makes an *exact* deny cache possible where generic response caching
cannot be: a denial does not mutate the bucket, so once a key is denied
for `(params, quantity)` at stored TAT `S`, every identical request is
provably denied — with closed-form decayed `remaining`/`reset`/`retry`
fields — until the earliest of

  * ``allow_at = S + inc - tol``   (the deny window ends),
  * ``S + tol``                    (the request's own clamp horizon:
                                    past it the oracle clamps the stored
                                    TAT and the closed form changes),
  * the bucket's true expiry       (past it the engine sees an absent
                                    key and first-touch semantics apply),
  * any *allowed* decision for the key (the one thing that writes).

Everything here is plain Python integers; the oracle is
`core/rate_limiter.py` and every served field reproduces its math (and
therefore the kernel's, which is validated against it) bit for bit:

    tat_eff   = S                      (unclamped inside the window)
    remaining = max((now + tol - S) // em, 0)
    reset     = S + tol - now
    retry     = S + inc - tol - now

Exactness discipline — an entry is created only when ALL of:

  * the key's **last allowed write was observed with its exact new TAT**
    (the limiter's compact="cur" tier exposes it host-side for free, and
    the full-ns result planes recover it from `reset_after_ns`); the
    denial's observed TAT must equal it.  This rules out foreign state
    (snapshot restores, writes that predate the front tier) and the
    stored-vs-first-touch ambiguity;
  * the writing request's tolerance is known, so the bucket's *true*
    expiry `tat + tol_write` is known — a later denial under different
    params must not outlive the writer's TTL;
  * every quantity involved sits far below i64 saturation (< 2^61), so
    the reference's saturating arithmetic degenerates to plain ints.

Anything that fails a check simply misses to the engine: the cache can
only ever be *conservative*, never wrong.

Concurrency: one lock guards all state (the asyncio engine's event loop,
its executor threads, and the native wire driver all touch the cache).
Observations are ordered by a dispatch-time sequence number so a slow
fetch on one transport can never overwrite a newer write record from
another with stale state.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

NS_PER_SEC = 1_000_000_000

# All cached quantities must sit far below i64 saturation so the
# reference's sat_add/sat_sub/wrap_u64 pipeline reduces to plain int
# math.  2^61 ns is ~73 years — nothing a real rate limit reaches.
_BOUND = 1 << 61
_I32_MAX = (1 << 31) - 1


@dataclass(frozen=True)
class DenyHit:
    """A cache-served denial, in exact nanoseconds (transports truncate
    to whole seconds exactly like `ThrottleResponse.from_ns`)."""

    limit: int
    remaining: int
    reset_after_ns: int
    retry_after_ns: int

    @property
    def reset_after_s(self) -> int:
        return self.reset_after_ns // NS_PER_SEC

    @property
    def retry_after_s(self) -> int:
        return self.retry_after_ns // NS_PER_SEC


class _Entry:
    __slots__ = ("tat", "emission", "tolerance", "increment", "limit",
                 "expiry")

    def __init__(self, tat, emission, tolerance, increment, limit, expiry):
        self.tat = tat
        self.emission = emission
        self.tolerance = tolerance
        self.increment = increment
        self.limit = limit
        self.expiry = expiry  # the bucket's true expiry (writer's TTL)


# A key's last observed allowed write is a plain (tat, tol, seq) tuple:
# exact new TAT + the writer's tolerance (=> true expiry), guarded by
# dispatch order.  A tuple, not a class — one record is allocated per
# engine-decided allowed row, on the serving path.
_REC_TAT, _REC_TOL, _REC_SEQ = 0, 1, 2


def _derive_scalar(max_burst: int, count_per_period: int, period: int):
    """(emission_ns, tolerance_ns) via the limiter's exact pipeline, or
    None for invalid params — scalar wrapper over tpu.limiter
    derive_params so cached math can never drift from the kernel's."""
    from ..tpu.limiter import derive_params

    emission, tolerance, invalid = derive_params(
        [max_burst], [count_per_period], [period]
    )
    if bool(invalid[0]):
        return None
    return int(emission[0]), int(tolerance[0])


def _column(col):
    """Normalize one bulk-lookup param column to a plain-int sequence.
    numpy arrays convert wholesale (C-level, plain ints out); anything
    else passes through — stray np.int64 elements in a list still hash
    and compare equal to the int-keyed entries, just slower."""
    tolist = getattr(col, "tolist", None)
    return tolist() if tolist is not None else col


# Serving traffic reuses a handful of parameter triples across millions
# of requests; the numpy round trip per observe() would dominate the
# cache's own cost.  Bound the memo so hostile param churn cannot grow
# it without limit.
_MEMO_CAP = 4096


class DenyCache:
    """Bounded O(1) map from (key, params, quantity) to an exact deny
    window, plus the per-key last-write records that certify entries."""

    def __init__(self, capacity: int = 65536) -> None:
        if capacity <= 0:
            raise ValueError("deny cache capacity must be positive")
        self.capacity = capacity
        self._lock = threading.Lock()
        # (key, (mb, cpp, period, q)) -> _Entry, insertion-ordered for
        # O(1) FIFO eviction at capacity.
        self._entries: dict = {}
        # key -> set of param tuples with live entries (O(1) invalidation).
        self._by_key: dict = {}
        # key -> (tat, tol, seq) write record (bounded to `capacity`
        # keys, FIFO-ish eviction).
        self._records: dict = {}
        # key -> in-flight engine request count: while any same-key
        # request is being decided, lookups must miss (the in-flight
        # request may be allowed and mutate the bucket under us).
        self._inflight: dict = {}
        self._seq = 0
        # (mb, cpp, period) -> (emission, tolerance) | None, memoized.
        self._param_memo: dict = {}
        # Raw counters; the FrontTier facade mirrors them into Metrics.
        self.hits = 0
        self.stale_evictions = 0

    def _derive(self, mb, cpp, period):
        """Memoized _derive_scalar (callers hold self._lock)."""
        k = (mb, cpp, period)
        try:
            return self._param_memo[k]
        except KeyError:
            pass
        if len(self._param_memo) >= _MEMO_CAP:
            self._param_memo.clear()
        d = self._param_memo[k] = _derive_scalar(mb, cpp, period)
        return d

    # ------------------------------------------------------------------ #

    def __len__(self) -> int:
        return len(self._entries)

    def next_seq(self) -> int:
        """Dispatch-order stamp: call once per launch window, *before*
        dispatch, and pass to observe() so late-arriving results from a
        concurrent transport can't roll a write record backwards."""
        with self._lock:
            self._seq += 1
            return self._seq

    def begin_inflight(self, key) -> None:
        with self._lock:
            self._inflight[key] = self._inflight.get(key, 0) + 1

    def end_inflight(self, key) -> None:
        with self._lock:
            n = self._inflight.get(key, 0) - 1
            if n <= 0:
                self._inflight.pop(key, None)
            else:
                self._inflight[key] = n

    # ------------------------------------------------------------------ #

    def lookup(self, key, max_burst, count_per_period, period, quantity,
               now_ns):
        """Serve an exact denial, or None (engine decides).

        Misses when no entry, when any same-key request is in flight, or
        when `now_ns` has left the proven window (stale entries evict)."""
        if now_ns < 0:
            # Pre-epoch clocks take the oracle's normalize_now_ns
            # wall-clock fallback — not reproducible here; let the
            # engine decide.
            return None
        k = (key, (int(max_burst), int(count_per_period), int(period),
                   int(quantity)))
        with self._lock:
            e = self._entries.get(k)
            if e is None:
                return None
            if key in self._inflight:
                return None
            allow_at = e.tat + e.increment - e.tolerance
            horizon = min(allow_at, e.tat + e.tolerance, e.expiry)
            if now_ns >= horizon:
                self._evict(k)
                self.stale_evictions += 1
                return None
            room = now_ns + e.tolerance - e.tat
            remaining = room // e.emission if room >= 0 else 0
            self.hits += 1
            return DenyHit(
                limit=e.limit,
                remaining=remaining,
                reset_after_ns=e.tat + e.tolerance - now_ns,
                retry_after_ns=allow_at - now_ns,
            )

    def lookup_window(self, keys, max_burst, count_per_period, period,
                      quantity, now_ns, mark_inflight: bool = True):
        """Bulk lookup for one serving window (shared timestamp): one
        lock acquisition and one exact-denial computation per *distinct*
        (key, params, q) combo — under abuse traffic a window repeats a
        handful of combos thousands of times, so the common row costs a
        single dict probe instead of the full per-call path.

        The window memo is exact BECAUSE the timestamp is shared: a
        served denial is identical for every repeat (denials don't
        mutate), and once a combo misses, its repeats must keep missing
        (the miss row is about to reach the engine and may mutate the
        bucket) — the memo's miss sentinel is the in-window equivalent
        of the in-flight guard.

        `max_burst`/`count_per_period`/`period`/`quantity` are per-row
        sequences; `keys` is a sequence of normalized keys.  Returns
        (rows, n_hits) where rows[i] is None for engine-bound rows or a
        (limit, remaining, reset_after_ns, retry_after_ns) tuple.  With
        `mark_inflight` (the serving default), every missing key is
        marked in-flight before returning — callers MUST release each
        one (observe_window/end_inflight) after the engine decides."""
        n = len(keys)
        out = [None] * n
        if now_ns < 0:
            if mark_inflight:
                for key in keys:
                    self.begin_inflight(key)
            return out, 0
        _MISS = False  # sentinel distinct from any hit tuple
        memo: dict = {}
        memo_get = memo.get
        entries_get = self._entries.get
        inflight = self._inflight
        n_hits = 0
        stale = 0
        # Normalize the param columns ONCE: numpy's C-level tolist()
        # yields plain ints (~12 ns/element), where per-row indexing +
        # int() in the loop costs ~an order of magnitude more — at 90 %
        # hit rates this loop IS the serving path's cost.
        mb_c = _column(max_burst)
        cpp_c = _column(count_per_period)
        per_c = _column(period)
        q_c = _column(quantity)
        # Serving windows routinely share ONE param config across every
        # row (per-route limits); verifying that is one C-level count()
        # pass per column (~15 ns/element), and it collapses the hot
        # loop to a bare key-string dict probe — no per-row tuple
        # allocation at all.  A non-uniform window (the wire protocol
        # allows per-request params) takes the general per-row path.
        uniform = False
        if n > 32:
            try:
                uniform = (
                    mb_c.count(mb_c[0]) == n
                    and cpp_c.count(cpp_c[0]) == n
                    and per_c.count(per_c[0]) == n
                    and q_c.count(q_c[0]) == n
                )
            except (AttributeError, TypeError):
                uniform = False
        inflight_get = inflight.get
        with self._lock:
            if uniform:
                pq = (mb_c[0], cpp_c[0], per_c[0], q_c[0])
                for i, key in enumerate(keys):
                    r = memo_get(key)
                    if r is None:
                        kt = (key, pq)
                        e = entries_get(kt)
                        r = _MISS
                        if e is not None and key not in inflight:
                            tat = e.tat
                            tol = e.tolerance
                            allow_at = tat + e.increment - tol
                            horizon = min(allow_at, tat + tol, e.expiry)
                            if now_ns >= horizon:
                                self._evict(kt)
                                stale += 1
                            else:
                                room = now_ns + tol - tat
                                r = (
                                    e.limit,
                                    room // e.emission if room >= 0 else 0,
                                    tat + tol - now_ns,
                                    allow_at - now_ns,
                                )
                        memo[key] = r
                        if r is _MISS and mark_inflight:
                            inflight[key] = inflight_get(key, 0) + 1
                    elif r is _MISS and mark_inflight:
                        inflight[key] = inflight_get(key, 0) + 1
                    if r is not _MISS:
                        out[i] = r
                        n_hits += 1
                self.hits += n_hits
                self.stale_evictions += stale
                return out, n_hits
            for i, (key, mb, cpp, per, q) in enumerate(
                zip(keys, mb_c, cpp_c, per_c, q_c)
            ):
                kt = (key, (mb, cpp, per, q))
                r = memo_get(kt)
                if r is None:
                    e = entries_get(kt)
                    r = _MISS
                    if e is not None and key not in inflight:
                        tat = e.tat
                        tol = e.tolerance
                        allow_at = tat + e.increment - tol
                        horizon = min(allow_at, tat + tol, e.expiry)
                        if now_ns >= horizon:
                            self._evict(kt)
                            stale += 1
                        else:
                            room = now_ns + tol - tat
                            r = (
                                e.limit,
                                room // e.emission if room >= 0 else 0,
                                tat + tol - now_ns,
                                allow_at - now_ns,
                            )
                    memo[kt] = r
                    if r is _MISS and mark_inflight:
                        inflight[key] = inflight_get(key, 0) + 1
                elif r is _MISS and mark_inflight:
                    inflight[key] = inflight_get(key, 0) + 1
                if r is not _MISS:
                    out[i] = r
                    n_hits += 1
            self.hits += n_hits
            self.stale_evictions += stale
        return out, n_hits

    # ------------------------------------------------------------------ #

    def observe_window(self, rows, now_ns, seq) -> None:
        """Bulk observe for one decided window: one lock acquisition for
        all rows, releasing each row's in-flight hold (the bulk twin of
        observe + end_inflight).  `rows` is an iterable of (key,
        max_burst, count_per_period, period, quantity, allowed, cur_ns)
        tuples in arrival order; cur_ns may be None (allowed rows then
        invalidate without certifying; denied rows are skipped)."""
        now_ns = int(now_ns)
        inflight = self._inflight
        inflight_get = inflight.get
        inflight_pop = inflight.pop
        records = self._records
        records_get = records.get
        records_pop = records.pop
        by_key_pop = self._by_key.pop
        entries_pop = self._entries.pop
        derive = self._derive
        now_ok = 0 <= now_ns < _BOUND
        cap = self.capacity
        # Rows should carry plain Python ints (callers .tolist() their
        # result planes); stray numpy scalars still hash/compare equal,
        # just slower.  The allowed branch is _observe_allowed inlined:
        # under abuse traffic the engine's miss stream is dominated by
        # allowed cold-tail rows, so this loop body IS the observe
        # path's cost.
        with self._lock:
            for key, mb, cpp, period, q, allowed, cur_ns in rows:
                if allowed:
                    # The one mutating outcome: cached denials die.
                    s = by_key_pop(key, None)
                    if s is not None:
                        for pq in s:
                            entries_pop((key, pq), None)
                    rec = records_get(key)
                    if rec is not None and seq < rec[_REC_SEQ]:
                        pass  # stale cross-transport observation
                    elif q < 1 or cur_ns is None or not now_ok:
                        # Unquantified / uncertified write: poison.
                        records_pop(key, None)
                    else:
                        derived = derive(mb, cpp, period)
                        if derived is not None:
                            em, tol = derived
                            if (
                                0 < em < _BOUND
                                and 0 <= tol < _BOUND
                                and 0 <= cur_ns < _BOUND
                            ):
                                # Pop-then-reinsert: a refreshed key
                                # moves to the dict's end so FIFO
                                # eviction tracks last-write age, not
                                # first-insertion — hot keys must not
                                # be the first evicted.
                                records_pop(key, None)
                                records[key] = (cur_ns, tol, seq)
                                if len(records) > cap:
                                    records_pop(next(iter(records)))
                            else:
                                records_pop(key, None)
                elif cur_ns is not None:
                    self._observe_denied(
                        key, int(mb), int(cpp), int(period), int(q),
                        now_ns, seq, cur_ns, None, None,
                    )
                m = inflight_get(key, 0) - 1
                if m <= 0:
                    inflight_pop(key, None)
                else:
                    inflight[key] = m

    def release_window(self, keys) -> None:
        """Release in-flight holds for rows that never reached a launch
        (shed rows): the bulk twin of end_inflight.  For rows whose
        launch may have COMMITTED before the failure, use fail_window —
        a plain release would leave entries/records that an unobserved
        write has invalidated."""
        inflight = self._inflight
        with self._lock:
            for key in keys:
                m = inflight.get(key, 0) - 1
                if m <= 0:
                    inflight.pop(key, None)
                else:
                    inflight[key] = m

    def fail_window(self, keys) -> None:
        """A launch failed after its writes may have committed (e.g. a
        post-launch fetch error): release each row's in-flight hold AND
        conservatively drop the key's cached denials and write record —
        an unobserved allow may have moved the TAT, so neither can
        certify exactness any longer."""
        inflight = self._inflight
        records_pop = self._records.pop
        with self._lock:
            for key in keys:
                m = inflight.get(key, 0) - 1
                if m <= 0:
                    inflight.pop(key, None)
                else:
                    inflight[key] = m
                self._invalidate_key(key)
                records_pop(key, None)

    # ------------------------------------------------------------------ #

    def observe(self, key, max_burst, count_per_period, period, quantity,
                now_ns, allowed, seq, cur_ns=None, reset_after_ns=None,
                retry_after_ns=None) -> None:
        """Feed one engine-decided OK result, in arrival order.

        `cur_ns` is the request's exact observed TAT when the launch
        used the compact="cur" tier (new TAT for allowed rows, effective
        TAT for denied rows); full-ns results recover the same values
        from `reset_after_ns`/`retry_after_ns` instead.  Rows offering
        neither still invalidate on allowed — they just can't certify."""
        q = int(quantity)
        now_ns = int(now_ns)
        mb = int(max_burst)
        cpp = int(count_per_period)
        period = int(period)
        with self._lock:
            if allowed:
                self._observe_allowed(
                    key, mb, cpp, period, q, now_ns, seq, cur_ns,
                    reset_after_ns,
                )
            else:
                self._observe_denied(
                    key, mb, cpp, period, q, now_ns, seq, cur_ns,
                    reset_after_ns, retry_after_ns,
                )

    def _observe_allowed(self, key, mb, cpp, period, q, now_ns, seq,
                         cur_ns, reset_after_ns):
        # The one mutating outcome: every cached denial for the key dies.
        self._invalidate_key(key)
        rec = self._records.get(key)
        if rec is not None and seq < rec[_REC_SEQ]:
            return  # stale cross-transport observation; record is newer
        if q < 1:
            # A quantity-0 probe may or may not refresh the TTL on a
            # given backend; an unquantified write poisons the record.
            self._records.pop(key, None)
            return
        derived = self._derive(mb, cpp, period)
        if derived is None:
            return
        em, tol = derived
        if not (0 < em < _BOUND and 0 <= tol < _BOUND
                and 0 <= now_ns < _BOUND):
            self._records.pop(key, None)
            return
        if cur_ns is not None:
            tat = int(cur_ns)
        elif reset_after_ns is not None and 0 < int(reset_after_ns) < _BOUND:
            # allowed => current_tat = new_tat and reset = new_tat+tol-now
            tat = now_ns + int(reset_after_ns) - tol
        else:
            self._records.pop(key, None)
            return
        if not 0 <= tat < _BOUND:
            self._records.pop(key, None)
            return
        # Pop-then-reinsert so FIFO eviction tracks last-write age —
        # a refreshed hot key must not stay parked at the front of
        # the eviction queue.
        self._records.pop(key, None)
        self._records[key] = (tat, tol, seq)
        while len(self._records) > self.capacity:
            self._records.pop(next(iter(self._records)))

    def _observe_denied(self, key, mb, cpp, period, q, now_ns, seq,
                        cur_ns, reset_after_ns, retry_after_ns):
        if not 1 <= q <= _I32_MAX:
            # q=0 denials are no-ops; q > i32::MAX could push `remaining`
            # past where the wire tiers saturate and the ns planes don't.
            return
        rec = self._records.get(key)
        if rec is None:
            return  # last write not observed exactly: can't certify
        derived = self._derive(mb, cpp, period)
        if derived is None:
            return
        em, tol = derived
        if not (0 < em < _BOUND and 0 < tol < _BOUND
                and 0 <= now_ns < _BOUND):
            return
        inc = em * q
        if inc >= _BOUND:
            return
        if cur_ns is not None:
            tat = int(cur_ns)
        elif (
            reset_after_ns is not None
            and retry_after_ns is not None
            and 0 < int(reset_after_ns) < _BOUND
            and 0 < int(retry_after_ns) < _BOUND
            # Both planes must reconstruct the SAME TAT or something
            # saturated/clamped along the way.
            and now_ns + int(reset_after_ns) - tol
            == now_ns + int(retry_after_ns) - inc + tol
        ):
            tat = now_ns + int(reset_after_ns) - tol
        else:
            return
        if tat != rec[_REC_TAT]:
            return  # an unobserved write intervened (or first touch)
        rec_tol = rec[_REC_TOL]
        if not 0 <= tat < _BOUND or rec_tol >= _BOUND:
            return
        if now_ns >= tat + inc - tol:
            return  # inconsistent with a denial; refuse
        k = (key, (mb, cpp, period, q))
        if k not in self._entries and len(self._entries) >= self.capacity:
            self._evict(next(iter(self._entries)))
        self._entries.pop(k, None)
        self._entries[k] = _Entry(
            tat, em, tol, inc, int(mb), tat + rec_tol
        )
        self._by_key.setdefault(key, set()).add(k[1])

    # ------------------------------------------------------------------ #

    def _evict(self, k) -> None:
        self._entries.pop(k, None)
        key, pq = k
        s = self._by_key.get(key)
        if s is not None:
            s.discard(pq)
            if not s:
                del self._by_key[key]

    def _invalidate_key(self, key) -> None:
        s = self._by_key.pop(key, None)
        if s is not None:
            for pq in s:
                self._entries.pop((key, pq), None)

    def invalidate_key(self, key) -> None:
        with self._lock:
            self._invalidate_key(key)

    def prewarm(self, keys) -> int:
        """Refresh confirmed-hot keys against FIFO eviction (the
        insight tier's feedback loop): every live entry and write
        record for `keys` moves to the END of its eviction queue, so
        under cache pressure the hottest abuse keys — the ones the
        cache pays off most for — are the last evicted.  Exactness is
        untouched: nothing is created, only re-ordered; a key with no
        certified state is a no-op.  Returns the number of refreshed
        keys."""
        n = 0
        with self._lock:
            records = self._records
            entries = self._entries
            for key in keys:
                touched = False
                rec = records.pop(key, None)
                if rec is not None:
                    records[key] = rec
                    touched = True
                for pq in self._by_key.get(key, ()):
                    k = (key, pq)
                    e = entries.pop(k, None)
                    if e is not None:
                        entries[k] = e
                        touched = True
                if touched:
                    n += 1
        return n

    def on_sweep(self, now_ns: int) -> int:
        """Expiry sweep ran on the table at `now_ns`: drop every entry
        whose bucket it vacated (the slot is gone even for a later
        regressed clock).  Returns the eviction count."""
        with self._lock:
            dead = [
                k for k, e in self._entries.items() if e.expiry <= now_ns
            ]
            for k in dead:
                self._evict(k)
            for key in [
                key for key, r in self._records.items()
                if r[_REC_TAT] + r[_REC_TOL] <= now_ns
            ]:
                self._records.pop(key, None)
            self.stale_evictions += len(dead)
            return len(dead)

    def clear(self) -> None:
        """Full invalidation: snapshot restore / param-surface changes —
        anything that rewrites bucket state out from under the cache."""
        with self._lock:
            self._entries.clear()
            self._by_key.clear()
            self._records.clear()
