"""Checkpoint file format: CRC-framed columnar state, torn-write safe.

One checkpoint file is one generation — either a ``base`` (the full
live table) or a ``delta`` (only the slots dirtied since the previous
generation).  The frame is designed so that *any* torn write — a
prefix of the file, a hole, a bit flip — is detected on read and
surfaces as one typed :class:`CheckpointCorrupt`, never as silently
wrong restored state:

    MAGIC(4) | crc32(body) u32 | len(body) u64 | body
    body = header_len u32 | header JSON | key_offsets i64[n+1]
         | key_blob | key_is_bytes u8[n] | key_codec u8[n]
         | tat i64[n] | expiry i64[n]

The CRC covers the whole body (header included), and the length field
catches truncation even in the astronomically unlikely case a torn
prefix CRC-matches.  Columns reuse the snapshot encoding
(tpu/snapshot.py `_encode_keys` / `translate_key`) so the two
persistence formats cannot drift in key-identity semantics.

The manifest (``MANIFEST.json``) names the retained generation chains
newest-first; it is advisory — recovery falls back to a directory scan
when it is missing, torn, or stale (see persist/recovery.py).

All writes here are durable, not just atomic: payload fsync (through
the ``snapshot`` fault site's :func:`fsync_with_faults` chokepoint)
before the rename, directory fsync after.  An injected ``truncate``
fault promotes the torn tmp file into the *final* path before raising
— modeling the ext4/xfs crash shape where the rename is journaled
before the data blocks land — so chaos tests exercise recovery against
genuinely torn files, not just cleanly missing ones.
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Sequence, Union

import numpy as np

from ..faults import (
    TruncatedWriteError,
    file_write_with_faults,
    fsync_with_faults,
    maybe_fail,
)
from ..tpu.snapshot import _encode_keys, fsync_dir

MAGIC = b"TCKP"
FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"

_FRAME = struct.Struct("<IQ")  # crc32(body), len(body)


class CheckpointCorrupt(ValueError):
    """A checkpoint file or manifest is torn, truncated, or damaged.

    Subclasses ValueError (like SnapshotError) so generic callers keep
    working; the recovery scanner catches it to fall back
    generation-by-generation instead of refusing to boot.
    """


@dataclass
class CheckpointRecord:
    """One decoded checkpoint file."""

    kind: str  # "base" | "delta"
    generation: int
    base_generation: int
    created_ns: int
    capacity: int
    n_shards: int
    source_bytes_keys: bool
    keys_raw: List[bytes]
    key_is_bytes: np.ndarray  # bool[n]
    key_codec: np.ndarray  # u8[n]
    tat: np.ndarray  # i64[n]
    expiry: np.ndarray  # i64[n]


def checkpoint_name(generation: int, kind: str) -> str:
    """``ckpt-<gen 12 digits>-<kind>.tck`` — lexicographic == numeric."""
    return f"ckpt-{generation:012d}-{kind}.tck"


def parse_checkpoint_name(name: str) -> Optional[tuple]:
    """(generation, kind) for a checkpoint filename, else None."""
    if not (name.startswith("ckpt-") and name.endswith(".tck")):
        return None
    parts = name[len("ckpt-") : -len(".tck")].split("-")
    if len(parts) != 2 or parts[1] not in ("base", "delta"):
        return None
    try:
        return int(parts[0]), parts[1]
    except ValueError:
        return None


def encode_checkpoint(
    kind: str,
    generation: int,
    base_generation: int,
    created_ns: int,
    capacity: int,
    n_shards: int,
    source_bytes_keys: bool,
    keys: Sequence,
    tat: np.ndarray,
    expiry: np.ndarray,
) -> bytes:
    """Frame one generation's rows as a checkpoint blob."""
    enc_keys, key_is_bytes, key_codec = _encode_keys(keys)
    n = len(enc_keys)
    offsets = np.zeros(n + 1, np.int64)
    if enc_keys:
        np.cumsum([len(k) for k in enc_keys], out=offsets[1:])
    key_blob = b"".join(enc_keys)
    header = json.dumps(
        {
            "version": FORMAT_VERSION,
            "kind": kind,
            "generation": int(generation),
            "base_generation": int(base_generation),
            "created_ns": int(created_ns),
            "n_keys": n,
            "capacity": int(capacity),
            "n_shards": int(n_shards),
            "source_bytes_keys": bool(source_bytes_keys),
            "key_blob_len": len(key_blob),
        },
        sort_keys=True,
    ).encode()
    body = b"".join(
        (
            struct.pack("<I", len(header)),
            header,
            offsets.astype("<i8").tobytes(),
            key_blob,
            np.asarray(key_is_bytes, np.uint8).tobytes(),
            np.asarray(key_codec, np.uint8).tobytes(),
            np.asarray(tat, "<i8").tobytes(),
            np.asarray(expiry, "<i8").tobytes(),
        )
    )
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return MAGIC + _FRAME.pack(crc, len(body)) + body


def decode_checkpoint(blob: bytes, name: str = "?") -> CheckpointRecord:
    """Verify + decode a checkpoint blob; CheckpointCorrupt on damage."""
    head = len(MAGIC) + _FRAME.size
    if len(blob) < head or blob[: len(MAGIC)] != MAGIC:
        raise CheckpointCorrupt(f"{name}: bad magic or truncated frame")
    crc, body_len = _FRAME.unpack_from(blob, len(MAGIC))
    body = blob[head:]
    if len(body) != body_len:
        raise CheckpointCorrupt(
            f"{name}: torn body ({len(body)} of {body_len} bytes)"
        )
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise CheckpointCorrupt(f"{name}: CRC mismatch")
    try:
        (hlen,) = struct.unpack_from("<I", body, 0)
        header = json.loads(body[4 : 4 + hlen])
        n = int(header["n_keys"])
        blob_len = int(header["key_blob_len"])
        kind = header["kind"]
        if kind not in ("base", "delta") or n < 0 or blob_len < 0:
            raise CheckpointCorrupt(f"{name}: bad header fields")
        if int(header["version"]) != FORMAT_VERSION:
            raise CheckpointCorrupt(
                f"{name}: unsupported version {header['version']}"
            )
        pos = 4 + hlen
        want = pos + 8 * (n + 1) + blob_len + n + n + 8 * n + 8 * n
        if want != len(body):
            raise CheckpointCorrupt(f"{name}: column lengths disagree")
        offsets = np.frombuffer(body, "<i8", n + 1, pos)
        pos += 8 * (n + 1)
        key_blob = body[pos : pos + blob_len]
        pos += blob_len
        key_is_bytes = np.frombuffer(body, np.uint8, n, pos).astype(bool)
        pos += n
        key_codec = np.frombuffer(body, np.uint8, n, pos)
        pos += n
        tat = np.frombuffer(body, "<i8", n, pos)
        pos += 8 * n
        expiry = np.frombuffer(body, "<i8", n, pos)
        if n and (
            int(offsets[0]) != 0
            or bool((np.diff(offsets) < 0).any())
            or int(offsets[-1]) != blob_len
        ):
            raise CheckpointCorrupt(f"{name}: key offsets inconsistent")
        keys_raw = [
            key_blob[offsets[i] : offsets[i + 1]] for i in range(n)
        ]
    except CheckpointCorrupt:
        raise
    except (KeyError, ValueError, TypeError, struct.error) as e:
        raise CheckpointCorrupt(f"{name}: undecodable header: {e}") from e
    return CheckpointRecord(
        kind=kind,
        generation=int(header["generation"]),
        base_generation=int(header["base_generation"]),
        created_ns=int(header["created_ns"]),
        capacity=int(header["capacity"]),
        n_shards=int(header["n_shards"]),
        source_bytes_keys=bool(header["source_bytes_keys"]),
        keys_raw=keys_raw,
        key_is_bytes=key_is_bytes,
        key_codec=key_codec,
        tat=tat,
        expiry=expiry,
    )


def read_checkpoint(path: Union[str, Path]) -> CheckpointRecord:
    path = Path(path)
    maybe_fail("snapshot")
    try:
        blob = path.read_bytes()
    except OSError as e:
        raise CheckpointCorrupt(f"{path.name}: unreadable: {e}") from e
    return decode_checkpoint(blob, path.name)


def write_file_durable(path: Union[str, Path], blob: bytes) -> None:
    """tmp + write + fsync + rename + dir fsync; fault-site threaded.

    On an injected torn write the torn tmp is *promoted into the final
    path* before the error surfaces: the worst real crash shape is a
    rename that hits the journal before the data blocks do, leaving a
    torn file under the final name — recovery must survive exactly
    that, so that is what injection produces.
    """
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    try:
        with open(tmp, "wb") as f:
            file_write_with_faults("snapshot", f, blob)
            f.flush()
            fsync_with_faults("snapshot", f.fileno())
    except TruncatedWriteError:
        try:
            import os

            os.replace(tmp, path)
        except OSError:
            pass
        raise
    except BaseException:
        try:
            tmp.unlink()
        except OSError:
            pass
        raise
    import os

    os.replace(tmp, path)
    fsync_dir(path.parent)


# ------------------------------------------------------------------ #
# Manifest


def write_manifest(
    directory: Union[str, Path], chains: List[List[int]]
) -> None:
    """Durably record the retained chains, newest-first.

    Each chain is ``[base_gen, delta_gen, ...]`` in ascending
    generation order.  Advisory only: recovery re-verifies every file
    it names and falls back to a directory scan without it.
    """
    directory = Path(directory)
    blob = json.dumps(
        {"version": FORMAT_VERSION, "chains": chains}, sort_keys=True
    ).encode()
    write_file_durable(directory / MANIFEST_NAME, blob)


def read_manifest(
    directory: Union[str, Path],
) -> Optional[List[List[int]]]:
    """The manifest's chain list, or None when missing/corrupt."""
    path = Path(directory) / MANIFEST_NAME
    try:
        doc = json.loads(path.read_bytes())
        chains = doc["chains"]
        if not isinstance(chains, list):
            raise ValueError("chains is not a list")
        out = []
        for chain in chains:
            gens = [int(g) for g in chain]
            if not gens:
                raise ValueError("empty chain")
            out.append(gens)
        return out
    except (OSError, ValueError, TypeError, KeyError):
        return None
