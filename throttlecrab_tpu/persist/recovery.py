"""Boot-time checkpoint recovery: verify, fall back, restore warm.

The scanner's contract is the opposite of THROTTLECRAB_SNAPSHOT_STRICT:
a checkpoint directory is *best-effort durable state*, so corruption
never refuses boot — it narrows what gets restored.  Fallback is
generation-by-generation:

  1. Chains come from the manifest when it verifies, else from a
     directory scan (every ``ckpt-*.tck`` grouped into base +
     consecutive deltas) — a torn manifest costs nothing but the hint.
  2. Within the newest chain, every file re-verifies its CRC.  A
     corrupt *delta* drops itself and everything after it (the chain
     survives one generation shorter); a corrupt *base* abandons the
     whole chain for the next retained one.
  3. Only when every retained chain is unusable does the node boot
     empty — exactly what it would have done without checkpoints.

Dropping tail generations is safe by the GCRA clamp argument: the
restored TATs are older than live state was, and old TATs are
over-allow-only.  Restore-time TTL sweeping (``expiry > now``) and
shard re-routing both reuse the snapshot restore path
(`_bulk_insert`), so a chain written on D shards restores onto any
shard count.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Union

from ..tpu.snapshot import _bulk_insert, translate_key
from .format import (
    CheckpointCorrupt,
    CheckpointRecord,
    checkpoint_name,
    parse_checkpoint_name,
    read_checkpoint,
    read_manifest,
)

log = logging.getLogger("throttlecrab.persist")


@dataclass
class RecoveryResult:
    """What a boot-time recovery actually restored."""

    restored: int = 0
    generation: int = -1  # newest generation applied
    chain: List[int] = field(default_factory=list)
    corrupt_skipped: int = 0  # generations dropped as torn/corrupt
    chains: List[List[int]] = field(default_factory=list)
    used_manifest: bool = True


def scan_chains(directory: Union[str, Path]) -> List[List[int]]:
    """Reconstruct chains from filenames alone, newest-first.

    Each base starts a chain; a delta extends the chain whose tip is
    exactly one generation older (the writer never leaves holes, so a
    gap means a pruned or lost file and ends the chain there).
    """
    directory = Path(directory)
    try:
        entries = [
            parsed
            for entry in directory.iterdir()
            if (parsed := parse_checkpoint_name(entry.name)) is not None
        ]
    except OSError:
        return []
    entries.sort()
    chains: List[List[int]] = []
    for gen, kind in entries:
        if kind == "base":
            chains.append([gen])
        elif chains and chains[-1][-1] == gen - 1:
            chains[-1].append(gen)
        # else: orphan delta (its base was pruned/corrupted away) —
        # unusable without a base, skip it.
    chains.reverse()
    return chains


def _load_chain(
    directory: Path, chain: List[int], result: RecoveryResult
) -> Optional[List[CheckpointRecord]]:
    """Verify a chain's files; returns the usable prefix (base first),
    or None when the base itself is unusable.  Tail generations that
    fail verification are dropped and counted, not fatal."""
    records: List[CheckpointRecord] = []
    for i, gen in enumerate(chain):
        kind = "base" if i == 0 else "delta"
        try:
            rec = read_checkpoint(directory / checkpoint_name(gen, kind))
            if rec.kind != kind or rec.generation != gen:
                raise CheckpointCorrupt(
                    f"gen {gen}: header disagrees with filename"
                )
        except (CheckpointCorrupt, OSError) as e:
            dropped = len(chain) - i
            result.corrupt_skipped += dropped
            log.warning(
                "checkpoint gen %d unusable (%s): dropping %d "
                "generation(s) from the chain",
                gen,
                e,
                dropped,
            )
            if i == 0:
                return None  # corrupt base: the whole chain is gone
            break
        records.append(rec)
    return records


def recover_into(
    limiter,
    directory: Union[str, Path],
    now_ns: int,
    front=None,
) -> Optional[RecoveryResult]:
    """Restore the newest verifiable chain into an empty limiter.

    Returns None when the directory holds no usable chain at all (boot
    proceeds exactly as without checkpointing).  Never raises for
    corruption — only for a genuinely mis-shaped call (non-empty
    limiter) or state exceeding capacity.
    """
    from ..tpu.limiter import limiter_uses_bytes_keys

    local = getattr(limiter, "local", None)
    if local is not None:  # ClusterLimiter: restore the local node
        return recover_into(local, directory, now_ns, front=front)

    directory = Path(directory)
    if not directory.is_dir():
        return None
    result = RecoveryResult()
    chains = read_manifest(directory)
    if chains is None:
        result.used_manifest = False
        chains = scan_chains(directory)
    if not chains:
        return None

    records: Optional[List[CheckpointRecord]] = None
    chain_used: List[int] = []
    for chain in chains:
        records = _load_chain(directory, chain, result)
        if records:
            chain_used = chain[: len(records)]
            break
        records = None
    # Every retained chain carries the full retained-generation map so
    # the checkpointer resumes numbering past *everything* on disk.
    result.chains = [list(c) for c in chains]
    if records is None:
        return None

    if front is not None:
        front.on_restore()
    if len(limiter) != 0:
        raise ValueError("checkpoint recovery requires an empty limiter")

    # Merge base + deltas: ascending generation order, later rows
    # overwrite earlier (the writer's delta gathers full current rows,
    # so overwrite IS newest-wins).  Keys are translated to the
    # target's identity space first so a base written by a native
    # (bytes-keyed) build merges correctly with deltas for a python
    # target, and vice versa.
    target_bytes_keys = limiter_uses_bytes_keys(limiter)
    merged: Dict = {}
    for rec in records:
        for i, raw in enumerate(rec.keys_raw):
            key = translate_key(
                raw,
                bool(rec.key_is_bytes[i]),
                int(rec.key_codec[i]),
                rec.source_bytes_keys,
                target_bytes_keys,
            )
            merged[key] = (int(rec.tat[i]), int(rec.expiry[i]))

    keys, tats, exps = [], [], []
    for key, (tat, exp) in merged.items():
        if exp > now_ns:  # restore-time TTL sweep across the chain
            keys.append(key)
            tats.append(tat)
            exps.append(exp)
    if keys:
        result.restored = _bulk_insert(limiter, keys, tats, exps)
    result.generation = chain_used[-1]
    result.chain = chain_used
    from ..replay.recorder import maybe_record_event

    maybe_record_event(
        "checkpoint-recovery",
        f"gen={result.generation} rows={result.restored} "
        f"skipped={result.corrupt_skipped}",
        now_ns,
    )
    return result
