"""Crash durability: incremental checkpointing + torn-write recovery.

A background checkpointer (checkpoint.py) periodically writes
generation-numbered, CRC-checksummed, fsynced checkpoint files — a
full base plus incremental deltas of slots dirtied since the previous
generation — with a manifest naming the retained chains and bounded
retention.  A boot-time scanner (recovery.py) verifies checksums and
falls back generation-by-generation past torn or corrupt files, so an
unplanned death (SIGKILL, OOM, power loss) restarts warm instead of
empty.  Everything restored is over-allow-only by the GCRA clamp —
stale state can never manufacture a wrong deny.
"""

from .checkpoint import BASE_EVERY, Checkpointer  # noqa: F401
from .format import (  # noqa: F401
    MANIFEST_NAME,
    CheckpointCorrupt,
    CheckpointRecord,
    checkpoint_name,
    decode_checkpoint,
    encode_checkpoint,
    parse_checkpoint_name,
    read_checkpoint,
    read_manifest,
    write_manifest,
)
from .recovery import (  # noqa: F401
    RecoveryResult,
    recover_into,
    scan_chains,
)
