"""Background incremental checkpointer.

Periodically persists the limiter's live state as a generation chain:
a full **base** checkpoint, then incremental **delta** checkpoints of
only the slots dirtied since the previous generation.  Dirty tracking
rides the existing host observe/flush path (`note_keys` is called with
each decided window's keys) so the device hot loop is untouched; a
delta's cost scales with churn, not table size.

Crash-safety argument (the one ARCHITECTURE.md makes for every other
staleness in this system): restored TATs are only ever *older* than
live state, and GCRA clamps an old TAT up to `now` before deciding —
so a stale checkpoint, a missed dirty mark, or a dropped delta
generation is strictly **over-allow-only**.  Recovery can never
manufacture a deny the live server would not have issued.

Tick discipline mirrors the control plane (control/actuators): the
engine's housekeeping path calls `maybe_tick(now_ns, lock)` off the
event loop; inside, the *device export* happens under the limiter lock
(kind "device" — legal there) and encoding + CRC + fsync happen with
the lock released.  A failed write re-merges the dirty set so the next
tick retries with nothing lost; the generation number only advances on
a durable write.

Retention is bounded: every new base starts a new chain and prunes all
but the newest `retain` chains, so disk use is O(retain · table), and
a base every `base_every` deltas bounds both recovery replay length
and the cost of a single lost generation.
"""

from __future__ import annotations

import logging
import threading
import time
from pathlib import Path
from typing import Iterable, Optional, Union

from ..tpu.snapshot import export_snapshot_payload
from .format import (
    MANIFEST_NAME,
    checkpoint_name,
    encode_checkpoint,
    parse_checkpoint_name,
    write_file_durable,
    write_manifest,
)

log = logging.getLogger("throttlecrab.persist")


def _canon_key(key) -> bytes:
    """Canonical byte identity of a keymap/wire key — the same mapping
    ``_encode_keys`` (tpu/snapshot.py) uses on disk, so a str key noted
    by a transport matches the bytes the native keymap exports."""
    if isinstance(key, (bytes, bytearray)):
        return bytes(key)
    try:
        return str(key).encode("utf-8", "surrogateescape")
    except UnicodeEncodeError:
        return str(key).encode("utf-8", "surrogatepass")

#: Deltas per base when mode == "incremental": bounds recovery replay
#: length and the blast radius of one corrupt generation.
BASE_EVERY = 16


class Checkpointer:
    """Owns one checkpoint directory for one node's limiter."""

    def __init__(
        self,
        limiter,
        directory: Union[str, Path],
        interval_ns: int,
        retain: int = 2,
        mode: str = "incremental",
        base_every: int = BASE_EVERY,
        now_fn=time.time_ns,
    ) -> None:
        self.limiter = limiter
        self.directory = Path(directory)
        self.interval_ns = int(interval_ns)
        self.retain = max(1, int(retain))
        self.mode = mode
        self.base_every = max(1, int(base_every))
        self._now_fn = now_fn
        self._mu = threading.Lock()  # dirty set + counters
        self._tick_mu = threading.Lock()  # single writer at a time
        self._dirty: set = set()
        #: Next generation to write (recovery seeds it past the chain).
        self.generation = 0
        self._deltas_since_base = 0
        #: Chains on disk, newest-first, each [base, delta, ...].
        self._chains: list = []
        self._last_tick_ns = 0
        # Stats (exported via metric_stats):
        self.last_checkpoint_ns = 0
        self.last_generation = -1
        self.last_duration_s = 0.0
        self.last_bytes = 0
        self.checkpoints_total = 0
        self.write_errors = 0
        # Boot-recovery stats, stamped by note_recovery:
        self.recoveries = 0
        self.recovered_keys = 0
        self.corrupt_skipped = 0

    # -------------------------------------------------------------- #
    # Dirty tracking (host observe path)

    def note_keys(self, keys: Iterable) -> None:
        """Mark `keys` dirty for the next delta.  Over-marking is
        harmless (the delta gathers dirty ∩ live table); a missed mark
        is bounded by the next base and over-allow-only anyway."""
        if self.interval_ns <= 0:
            # Recovery/shutdown-flush-only mode: the only write is a
            # full base, which needs no marks — don't grow a set that
            # nothing will ever drain.
            return
        with self._mu:
            self._dirty.update(keys)

    def dirty_count(self) -> int:
        with self._mu:
            return len(self._dirty)

    # -------------------------------------------------------------- #
    # Tick discipline (engine housekeeping path)

    def tick_due(self, now_ns: int) -> bool:
        """Cheap pre-check the engine calls before paying an executor
        hop — same shape as control.tick_due / insight.poll_due."""
        return (
            self.interval_ns > 0
            and now_ns - self._last_tick_ns >= self.interval_ns
        )

    def maybe_tick(self, now_ns: int, lock=None) -> int:
        """Write one checkpoint if the interval elapsed; returns rows
        written (0 when not due / nothing dirty / another tick runs).

        Never raises: a background housekeeping path must not take the
        serving loop down with it — failures are counted, logged, and
        retried next interval with the dirty set re-merged."""
        if not self.tick_due(now_ns):
            return 0
        if not self._tick_mu.acquire(blocking=False):
            return 0  # another driver (engine vs native) is mid-write
        try:
            if not self.tick_due(now_ns):
                return 0
            self._last_tick_ns = now_ns
            try:
                return self.checkpoint_now(now_ns, lock=lock)
            except OSError as e:
                log.warning("checkpoint generation failed: %s", e)
                return 0
        finally:
            self._tick_mu.release()

    # -------------------------------------------------------------- #
    # The write itself

    def checkpoint_now(
        self,
        now_ns: Optional[int] = None,
        lock=None,
        force_base: bool = False,
    ) -> int:
        """Write one generation immediately; returns rows written.

        Raises OSError on write failure (the dirty set is re-merged
        first, so a later call retries losslessly) — `maybe_tick`
        catches it; explicit callers (tests, shutdown flush) see it.
        """
        if now_ns is None:
            now_ns = self._now_fn()
        want_base = (
            force_base
            or self.mode == "full"
            or self.last_generation < 0
            or self._deltas_since_base >= self.base_every
        )
        with self._mu:
            dirty = self._dirty
            self._dirty = set()
        if not want_base and not dirty:
            return 0  # idle interval: no state changed, no file
        # Device half under the lock, everything else outside it.
        if lock is not None:
            with lock:
                payload = export_snapshot_payload(self.limiter)
        else:
            payload = export_snapshot_payload(self.limiter)
        t0 = time.perf_counter()
        keys = payload["keys"]
        tat = payload["tat"]
        expiry = payload["expiry"]
        if want_base:
            kind = "base"
            idx = range(len(keys))
        else:
            # A dirtied key can have expired/evicted since its mark —
            # then it's simply absent from the export and the delta.
            # An all-expired dirty set still writes an (empty) delta so
            # the chain has no generation holes for recovery to
            # misread as torn.  Match on canonical byte identity: the
            # transports note wire (str) keys while a bytes-keyed
            # keymap exports bytes, and those must name the same row.
            kind = "delta"
            dirty_c = {_canon_key(k) for k in dirty}
            idx = [
                i for i, k in enumerate(keys) if _canon_key(k) in dirty_c
            ]
        gen = self.generation
        base_gen = (
            gen if want_base else (self._chains[0][0] if self._chains else gen)
        )
        blob = encode_checkpoint(
            kind,
            gen,
            base_gen,
            now_ns,
            payload["capacity"],
            payload["n_shards"],
            payload["source_bytes_keys"],
            [keys[i] for i in idx],
            [int(tat[i]) for i in idx],
            [int(expiry[i]) for i in idx],
        )
        path = self.directory / checkpoint_name(gen, kind)
        try:
            self.directory.mkdir(parents=True, exist_ok=True)
            write_file_durable(path, blob)
        except OSError:
            self.write_errors += 1
            with self._mu:
                self._dirty |= dirty  # nothing lost; retry next tick
            raise
        # Durable: advance the chain, then the advisory manifest.
        if want_base:
            self._chains.insert(0, [gen])
            self._deltas_since_base = 0
        else:
            if self._chains:
                self._chains[0].append(gen)
            else:
                self._chains.insert(0, [gen])
            self._deltas_since_base += 1
        self.generation = gen + 1
        self.last_generation = gen
        self.last_checkpoint_ns = now_ns
        self.last_bytes = len(blob)
        self.last_duration_s = time.perf_counter() - t0
        self.checkpoints_total += 1
        try:
            self._prune()
            write_manifest(self.directory, self._chains)
        except OSError as e:
            # The generation itself is durable; a directory-scan
            # recovery finds it without the manifest.
            self.write_errors += 1
            log.warning("checkpoint manifest/prune failed: %s", e)
        from ..replay.recorder import maybe_record_event

        maybe_record_event(
            "checkpoint", f"{kind} gen={gen} rows={len(idx)}", now_ns
        )
        return len(idx)

    def _prune(self) -> None:
        """Keep the newest `retain` chains; delete the rest's files."""
        if len(self._chains) <= self.retain:
            return
        dead, self._chains = (
            self._chains[self.retain :],
            self._chains[: self.retain],
        )
        keep = {g for chain in self._chains for g in chain}
        for entry in list(self.directory.iterdir()):
            parsed = parse_checkpoint_name(entry.name)
            if parsed is None or parsed[0] in keep:
                continue
            try:
                entry.unlink()
            except OSError:
                pass
        del dead

    # -------------------------------------------------------------- #
    # Lifecycle + surface

    def note_recovery(
        self, restored: int, corrupt_skipped: int, chains: list
    ) -> None:
        """Stamp boot-recovery results and resume generation numbering
        strictly past everything on disk (chains is the full retained
        list, newest-first, as recovery saw it)."""
        self.recoveries += 1
        self.recovered_keys += restored
        self.corrupt_skipped += corrupt_skipped
        self._chains = [list(c) for c in chains]
        highest = max(
            (g for chain in chains for g in chain), default=-1
        )
        self.generation = highest + 1
        # A fresh base after recovery re-anchors the chain: everything
        # recovered is immediately re-persisted without replaying the
        # old (possibly tail-dropped) deltas forever.
        self._deltas_since_base = self.base_every

    def stop(self, now_ns: Optional[int] = None) -> None:
        """Final flush on graceful shutdown (best-effort)."""
        try:
            with self._tick_mu:
                self.checkpoint_now(now_ns)
        except OSError as e:
            log.warning("final checkpoint flush failed: %s", e)

    def metric_stats(self) -> dict:
        """Gauges for server/metrics.py's checkpoint stats provider."""
        age_s = (
            (self._now_fn() - self.last_checkpoint_ns) / 1e9
            if self.last_checkpoint_ns
            else -1.0
        )
        return {
            "generation": float(self.last_generation),
            "age_seconds": age_s,
            "duration_seconds": self.last_duration_s,
            "bytes": float(self.last_bytes),
            "corrupt_skipped_total": float(self.corrupt_skipped),
            "recoveries_total": float(self.recoveries),
            "write_errors_total": float(self.write_errors),
            "dirty_pending": float(self.dirty_count()),
        }

    def health_suffix(self) -> str:
        """The /health annotation: last-checkpoint age in seconds."""
        if not self.last_checkpoint_ns:
            return "checkpoint_age_s=never"
        age = max(0.0, (self._now_fn() - self.last_checkpoint_ns) / 1e9)
        return f"checkpoint_age_s={age:.1f}"


__all__ = [
    "BASE_EVERY",
    "Checkpointer",
    "MANIFEST_NAME",
]
