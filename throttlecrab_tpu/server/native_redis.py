"""Native RESP transport: C++ epoll wire layer + Python device driver.

The C++ side (native/wire_server.cpp) owns the sockets: accept, RESP
parsing, PING/QUIT and protocol errors answered inline, THROTTLE requests
queued.  This module runs the *driver thread*: it blocks in
`ws_next_batch` (releasing the GIL), decides the batch on the device, and
hands the 5-integer results back to C++ for serialization — so the wire
path's per-request Python cost is zero, and the per-batch Python cost is
one `rate_limit_batch` call.

Same command semantics and hardening as the asyncio transport (redis.py)
and the reference (redis/mod.rs); the two are interchangeable via
`--redis-backend {python,native}`.

Shared state: pass the same limiter (and `limiter_lock`) used by the
asyncio engine so limits hold across every transport; the lock serializes
device access between the engine's executor thread and this driver.
"""

from __future__ import annotations

import ctypes
import logging
import threading
import time
from typing import Optional

import numpy as np

from ..front import STATUS_OVERLOADED
from ..native import get_wire_lib
from ..tpu.limiter import (
    STATUS_DEADLINE,
    STATUS_INTERNAL,
    WireBatchResult,
    limiter_uses_bytes_keys,
)

log = logging.getLogger("throttlecrab.redis.native")

NS_PER_SEC = 1_000_000_000


class NativeRedisTransport:
    """RESP on the C++ wire server; drop-in for RedisTransport."""

    name = "redis"
    PROTOCOL = 0  # wire_server.cpp: 0 = RESP, 1 = HTTP

    def __init__(
        self,
        host: str,
        port: int,
        limiter,
        metrics,
        batch_size: int = 4096,
        max_linger_us: int = 200,
        cleanup_policy=None,
        limiter_lock: Optional[threading.Lock] = None,
        now_fn=None,
        max_scan_depth: int = 16,
        front=None,
        insight=None,
        control=None,
        checkpointer=None,
    ) -> None:
        lib = get_wire_lib()
        if lib is None:
            raise RuntimeError("native wire server unavailable (no g++?)")
        self._lib = lib
        self.host = host
        self.port = port
        self.limiter = limiter
        self.metrics = metrics
        # Insight tier (L3.75): this driver thread runs its throttled
        # device poll between windows and pushes the /stats snapshot
        # into the C++ wire layer (HTTP protocol) alongside
        # health/metrics.
        self.insight = insight
        # Control plane (L3.9): this driver thread also drives the
        # throttled control tick, right after the insight poll (None —
        # the default — means no sensor read and no knob ever moves).
        self.control = control
        # Crash durability (persist/): decided keys mark dirty and this
        # driver thread drives the throttled checkpoint tick, same
        # discipline as insight/control.
        self.checkpointer = checkpointer
        # Front tier (L3.5): shared with the asyncio engine, so a deny
        # cached on one transport serves (and is invalidated by) all of
        # them.  The lookup runs in this driver BEFORE batch prep —
        # cache-hit rows never reach tk_prepare_batch or the device.
        self.front = front
        # Ask cur-capable dispatchers for the observed-TAT plane only
        # when a deny cache is attached (see engine.py).
        def cur_kw(method_name):
            if front is None or front.deny_cache is None:
                return {}
            import inspect

            try:
                params = inspect.signature(
                    getattr(limiter, method_name)
                ).parameters
            except (AttributeError, TypeError, ValueError):
                return {}
            return {"collect_cur": True} if "collect_cur" in params else {}

        self._collect_cur_kw = cur_kw("dispatch_wire_window")
        self._collect_cur_many_kw = cur_kw("rate_limit_many")
        self._collect_cur_batch_kw = cur_kw("rate_limit_batch")
        self.batch_size = batch_size
        self.max_linger_us = max_linger_us
        self.max_scan_depth = max_scan_depth
        self.cleanup_policy = cleanup_policy
        self.limiter_lock = limiter_lock or threading.Lock()
        self.now_fn = now_fn or time.time_ns
        self._h = lib.ws_create()
        self._driver: Optional[threading.Thread] = None
        self._running = False
        self.bound_port: Optional[int] = None
        # Reusable batch buffers.  key_buf must exceed the wire layer's
        # per-connection frame cap (64 KB) so any single accepted key fits
        # — ws_next_batch's progress guarantee depends on it.
        B = batch_size
        self._key_buf = ctypes.create_string_buffer(B * 256 + (128 << 10))
        self._offsets = np.zeros(B + 1, np.int64)
        # Stride 5: the wire layer appends a remaining-deadline-budget
        # column (ns; 0 = none, negative = expired at pop).
        self._params = np.zeros(5 * B, np.int64)
        self._cookie_gen = np.zeros(B, np.uint64)
        self._cookie_fd = np.zeros(B, np.int32)
        # Graceful drain: once set, /health (HTTP protocol) reports
        # "draining" so balancers stop routing here while the driver
        # keeps answering already-queued requests.
        self._draining = False

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        rc = self._lib.ws_start(
            self._h, self.host.encode(), self.port, self.PROTOCOL
        )
        if rc != 0:
            raise OSError(
                f"native {self.name} transport failed to bind {self.host}:"
                f"{self.port}"
            )
        self.bound_port = self._lib.ws_port(self._h)
        self._running = True
        self._driver = threading.Thread(
            target=self._drive, name=f"tk-native-{self.name}", daemon=True
        )
        self._driver.start()
        log.info(
            "native %s transport listening on %s:%d",
            self.name, self.host, self.bound_port,
        )

    async def serve_forever(self) -> None:
        import asyncio

        while self._running:
            await asyncio.sleep(0.5)
            if self._driver is not None and not self._driver.is_alive():
                raise RuntimeError("native redis driver thread died")

    async def drain(self) -> None:
        """Graceful-drain hook: advertise "draining" on /health (HTTP
        protocol) so balancers stop routing here.  The listener stays
        up and the driver keeps answering queued requests — the C++
        wire layer has no accept gate, so the health flip is the
        routing signal; stop() drops connections afterwards."""
        self._draining = True
        if self.PROTOCOL == 1:
            body = b"draining"
            self._lib.ws_set_health(self._h, body, len(body))

    async def stop(self) -> None:
        import asyncio

        self._running = False
        loop = asyncio.get_running_loop()
        # ws_stop is the poison pill: it flips the C++ running flag and
        # notifies the queue condvar, so a driver parked in
        # ws_next_batch (whose wait predicate includes !running) wakes
        # immediately instead of sleeping out its linger timeout.  It
        # also joins the IO thread — up to ~1 s of epoll_wait — so it
        # runs on the executor, never the event loop.
        await loop.run_in_executor(None, self._lib.ws_stop, self._h)
        driver = self._driver
        if driver is not None:
            await loop.run_in_executor(None, driver.join, 5)
            if driver.is_alive():
                # Most likely wedged inside a device launch (the one
                # block ws_stop cannot interrupt).  Leak it loudly —
                # and skip ws_destroy, which would free wire state the
                # thread may still touch.
                log.warning(
                    "native %s driver thread did not exit within 5 s "
                    "(stuck in a device launch?); leaking the thread "
                    "and its wire handle instead of corrupting state",
                    self.name,
                )
                self._leaked = True

    def __del__(self):
        h = getattr(self, "_h", None)
        if h and not getattr(self, "_leaked", False):
            self._lib.ws_destroy(h)
            self._h = None

    # ------------------------------------------------------------------ #

    def _next_batch(self, linger_us: int) -> int:
        return self._lib.ws_next_batch(
            self._h,
            linger_us,
            self.batch_size,
            self._key_buf,
            len(self._key_buf),
            self._offsets.ctypes.data_as(ctypes.c_void_p),
            self._params.ctypes.data_as(ctypes.c_void_p),
            self._cookie_gen.ctypes.data_as(ctypes.c_void_p),
            self._cookie_fd.ctypes.data_as(ctypes.c_void_p),
        )

    def _capture(self, n: int):
        """Snapshot the reusable batch buffers into a per-batch frame:
        (key_blob, offsets, params[n, 4], cookie_gen, cookie_fd,
        budgets[n]) — params is the exact shape dispatch_wire_window
        consumes (the deadline column is split off as `budgets`), with
        keys derived lazily only on the fallback path."""
        offsets = self._offsets[: n + 1].copy()
        # Copy only the used prefix, not the whole reusable buffer.
        blob = ctypes.string_at(self._key_buf, int(offsets[n]))
        params5 = self._params[: 5 * n].reshape(n, 5)
        params = params5[:, :4].copy()
        budgets = params5[:, 4].copy()
        return (
            blob,
            offsets,
            params,
            self._cookie_gen[:n].copy(),
            self._cookie_fd[:n].copy(),
            budgets,
        )

    def _keys_of(self, blob, offsets):
        keys = [
            blob[offsets[i] : offsets[i + 1]]
            for i in range(len(offsets) - 1)
        ]
        if not limiter_uses_bytes_keys(self.limiter):
            # Match the identity the str-keyed transports use, so one
            # client key maps to one bucket across HTTP/gRPC/RESP.
            # surrogateescape keeps arbitrary bytes unique and lossless.
            keys = [k.decode("utf-8", "surrogateescape") for k in keys]
        return keys

    def _drive(self) -> None:
        """The decide loop: block for a batch; when a full batch arrives
        (backlog — e.g. pipelined clients), drain up to max_scan_depth
        further batches without lingering and decide the whole window in
        ONE device launch (limiter.rate_limit_many), exactly like the
        asyncio engine's backlog path."""
        B = self.batch_size
        can_scan = hasattr(self.limiter, "rate_limit_many")
        self._push_metrics()
        last_metrics = time.monotonic()
        while self._running:
            try:
                if (
                    self.PROTOCOL == 1
                    and time.monotonic() - last_metrics > 1.0
                ):
                    self._push_metrics()
                    last_metrics = time.monotonic()
                n = self._next_batch(self.max_linger_us)
                if n <= 0:
                    continue
                batches = [self._capture(int(n))]
                while (
                    can_scan
                    and n == B
                    and len(batches) < self.max_scan_depth
                ):
                    n = self._next_batch(0)
                    if n <= 0:
                        break
                    batches.append(self._capture(int(n)))
                self._decide_window(batches)
            except Exception:
                log.exception("native redis driver error")
                if not self._running:
                    return

    def _front_filter(self, batch, now_ns, depth):
        """Run one captured frame through the front tier BEFORE batch
        prep: deny-cache hits get their exact denial filled in,
        admission-shed rows get the overload status, and only the
        surviving misses are compacted into a (blob, offsets, params)
        frame for the device.  The cache is consulted first — a hit
        never occupies the queue admission protects, so shedding it
        would turn a free exact denial into a 503 under exactly the
        abuse traffic this tier exists for.  Miss keys are marked
        in-flight until observed.  Rows whose deadline budget expired
        before pop are shed first (status 6) — the client stopped
        waiting, so neither a cached denial nor a device row helps."""
        blob, offsets, params, gen, fd, budgets = batch
        n = len(offsets) - 1
        front = self.front
        admission = front.admission
        deny = front.deny_cache
        status_pre = np.zeros(n, np.uint8)
        hit_vals = np.zeros((n, 5), np.int64)
        NS = 1_000_000_000
        q_col = params[:, 3].tolist()
        miss_pos: list = []
        miss_keys: list = []
        miss_norm: list = []
        if deny is not None:
            raw = [blob[offsets[i] : offsets[i + 1]] for i in range(n)]
            # The cache's key identity is the limiter keymap's: with a
            # str-keyed (python) keymap the wire's bytes decode exactly
            # like the transports do; with a bytes keymap (native, the
            # serving default) normalization is the identity and costs
            # nothing.
            if front.bytes_keys:
                norm = raw
            else:
                norm = [k.decode("utf-8", "surrogateescape") for k in raw]
            # Bulk lookup, one lock + one computation per distinct
            # (key, params, q) combo; misses are marked in-flight until
            # _observe_plan releases them.
            rows, _ = front.lookup_window(
                norm, params[:, 0], params[:, 1], params[:, 2],
                params[:, 3], now_ns,
            )
            shed_norm: list = []
            for i in range(n):
                if budgets[i] < 0:
                    status_pre[i] = STATUS_DEADLINE
                    if rows[i] is None:
                        # The bulk lookup marked this miss in-flight;
                        # it will never be observed, so free the hold.
                        shed_norm.append(norm[i])
                    continue
                hit = rows[i]
                if hit is not None:
                    status_pre[i] = 255  # marker: row served from cache
                    hit_vals[i] = (
                        0, hit[0], hit[1], hit[2] // NS, hit[3] // NS,
                    )
                    continue
                if admission is not None and not front.admit(
                    depth, q_col[i] == 0
                ):
                    status_pre[i] = STATUS_OVERLOADED
                    shed_norm.append(norm[i])
                    continue
                miss_pos.append(i)
                miss_keys.append(raw[i])
                miss_norm.append(norm[i])
            if shed_norm:
                # Shed rows never reach the engine: release the
                # in-flight holds the bulk lookup took for them.
                front.release_window(shed_norm)
        else:
            # Admission-only config: no cache, so the per-row key
            # slices/decodes are never needed — shed or pass through.
            for i in range(n):
                if budgets[i] < 0:
                    status_pre[i] = STATUS_DEADLINE
                elif admission is not None and not front.admit(
                    depth, q_col[i] == 0
                ):
                    status_pre[i] = STATUS_OVERLOADED
                else:
                    miss_pos.append(i)
            if len(miss_pos) != n:
                miss_keys = [
                    blob[offsets[i] : offsets[i + 1]] for i in miss_pos
                ]
        miss_idx = np.asarray(miss_pos, np.int64)
        m = len(miss_pos)
        if m == n:
            miss_frame = (blob, offsets, params)
            miss_params = params
        elif m:
            offsets_m = np.zeros(m + 1, np.int64)
            np.cumsum([len(k) for k in miss_keys], out=offsets_m[1:])
            miss_params = np.ascontiguousarray(params[miss_idx])
            miss_frame = (b"".join(miss_keys), offsets_m, miss_params)
        else:
            miss_frame = None
            miss_params = None
        return {
            "batch": batch,
            "n": n,
            "status_pre": status_pre,
            "hit_vals": hit_vals,
            "miss_idx": miss_idx,
            "miss_norm": miss_norm,
            "miss_frame": miss_frame,
            "miss_params": miss_params,
        }

    def _deadline_plan(self, batch):
        """No-front-tier twin of _front_filter for batches carrying
        expired rows: expired budgets answer status 6, live rows
        compact into the device frame.  Same plan shape _merge_plan
        consumes (no hits, no norm keys to observe)."""
        blob, offsets, params, gen, fd, budgets = batch
        n = len(offsets) - 1
        expired = budgets < 0
        status_pre = np.where(expired, STATUS_DEADLINE, 0).astype(np.uint8)
        miss_idx = np.flatnonzero(~expired)
        m = len(miss_idx)
        if m == n:
            miss_frame = (blob, offsets, params)
            miss_params = params
        elif m:
            keys = [blob[offsets[i] : offsets[i + 1]] for i in miss_idx]
            offsets_m = np.zeros(m + 1, np.int64)
            np.cumsum([len(k) for k in keys], out=offsets_m[1:])
            miss_params = np.ascontiguousarray(params[miss_idx])
            miss_frame = (b"".join(keys), offsets_m, miss_params)
        else:
            miss_frame = None
            miss_params = None
        return {
            "batch": batch,
            "n": n,
            "status_pre": status_pre,
            "hit_vals": np.zeros((n, 5), np.int64),
            "miss_idx": miss_idx,
            "miss_norm": [],
            "miss_frame": miss_frame,
            "miss_params": miss_params,
        }

    def _merge_plan(self, plan, res):
        """Fold a miss sub-frame's device results back into the full
        frame alongside cached hits and shed rows; returns the
        WireBatchResult-shaped object _respond_one serializes."""
        n = plan["n"]
        out = np.zeros((n, 5), np.int64)
        status = plan["status_pre"].copy()
        served = status == 255  # cache-hit marker → status OK on the wire
        if bool(served.any()):
            out[served] = plan["hit_vals"][served]
            status[served] = 0
        mi = plan["miss_idx"]
        if len(mi):
            if res is None:
                status[mi] = STATUS_INTERNAL
            else:
                status[mi] = res.status
                out[mi, 0] = res.allowed
                out[mi, 1] = res.limit
                out[mi, 2] = res.remaining
                out[mi, 3] = res.reset_after_s
                out[mi, 4] = res.retry_after_s
        return WireBatchResult(
            allowed=out[:, 0], limit=out[:, 1], remaining=out[:, 2],
            reset_after_s=out[:, 3], retry_after_s=out[:, 4],
            status=status,
        )

    def _observe_plan(self, plan, res, now_ns, seq) -> None:
        """Feed the miss rows' engine decisions to the deny cache and
        release their in-flight holds, in bulk (one lock for the whole
        window) — the native twin of engine._observe_window."""
        front = self.front
        norm = plan["miss_norm"]
        if res is None:
            # Post-launch failure: the writes may have committed, so
            # drop the keys' cached denials/write records along with
            # their holds.
            front.deny_cache.fail_window(norm)
            return
        params = plan["miss_params"]
        cur = getattr(res, "cur_ns", None)
        # One C-level tolist() per plane; per-element int(arr[i]) costs
        # ~10x and this loop runs once per device-decided request.
        status = res.status.tolist()
        allowed_col = res.allowed.tolist()
        cur_l = cur.tolist() if cur is not None else None
        params_l = params.tolist()
        rows = []
        for i, key in enumerate(norm):
            ok = status[i] == 0
            allowed = ok and bool(allowed_col[i])
            # Without the exact observed TAT (cur tier), a denial can't
            # certify — but an allowed row must still invalidate.
            c = cur_l[i] if (ok and cur_l is not None) else None
            p = params_l[i]
            rows.append((key, p[0], p[1], p[2], p[3], allowed, c))
        front.observe_window(rows, now_ns, seq)

    def _decide_frames(self, frames, now_ns):
        """Decide a window of (blob, offsets, params) frames on the
        device; returns (results, seq) with one WireBatchResult (or
        None after a post-launch failure) per frame."""
        if not frames:
            return [], 0
        results = None
        seq = 0
        front = self.front
        # Fast path: hand the raw wire frames to the fully-native prep —
        # one C++ call per batch validates, derives the GCRA params, and
        # writes the packed launch rows (limiter.dispatch_wire_window).
        wire_dispatch = getattr(self.limiter, "dispatch_wire_window", None)
        handle = None
        if wire_dispatch is not None:
            try:
                with self.limiter_lock:
                    # Dispatch-order stamp under the same lock that
                    # serializes launches across transports.
                    seq = front.next_seq() if front is not None else 0
                    handle = wire_dispatch(
                        frames, now_ns, **self._collect_cur_kw
                    )
            except Exception:
                # Failed BEFORE any launch committed state: the Python
                # fallback below may safely re-decide.
                log.exception("native wire dispatch failed")
                handle = None
        if handle is not None:
            try:
                results = handle.fetch()
            except Exception:
                # The launch already mutated the bucket table — the
                # decisions are committed even though we cannot read
                # them.  Re-deciding would debit every bucket twice, so
                # answer internal errors instead of falling back.
                log.exception("native wire fetch failed (post-launch)")
                results = [None] * len(frames)
        if results is None:
            try:
                with self.limiter_lock:
                    seq = front.next_seq() if front is not None else 0
                    # wire=True: compact i32 whole-second outputs straight
                    # off the device — the RESP/HTTP reply units — plus
                    # the degenerate machinery compiled out when
                    # certifiable.
                    windows = [
                        (
                            self._keys_of(b, o),
                            p[:, 0], p[:, 1], p[:, 2], p[:, 3],
                            now_ns,
                        )
                        for b, o, p in frames
                    ]
                    if (
                        hasattr(self.limiter, "rate_limit_many")
                        and len(windows) > 1
                    ):
                        results = self.limiter.rate_limit_many(
                            windows, wire=True,
                            **self._collect_cur_many_kw,
                        )
                    else:
                        results = [
                            self.limiter.rate_limit_batch(
                                *w, wire=True,
                                **self._collect_cur_batch_kw,
                            )
                            for w in windows
                        ]
            except Exception:
                log.exception("native redis decide failed")
                results = [None] * len(frames)
        return results, seq

    def _decide_window(self, batches) -> None:
        now_ns = self.now_fn()
        front = self.front
        use_front = front is not None and (
            front.deny_cache is not None or front.admission is not None
        )
        n_expired = sum(int((b[5] < 0).sum()) for b in batches)
        if use_front:
            depth = int(self._lib.ws_queue_depth(self._h))
            plans = [
                self._front_filter(b, now_ns, depth) for b in batches
            ]
            frames = [
                p["miss_frame"] for p in plans
                if p["miss_frame"] is not None
            ]
        elif n_expired:
            plans = [self._deadline_plan(b) for b in batches]
            frames = [
                p["miss_frame"] for p in plans
                if p["miss_frame"] is not None
            ]
        else:
            plans = None
            frames = [(b, o, p) for b, o, p, _, _, _ in batches]
        if n_expired and self.metrics is not None:
            self.metrics.record_deadline_shed(n_expired)
        launched_n = sum(len(f[1]) - 1 for f in frames)
        t0 = time.monotonic()
        results, seq = self._decide_frames(frames, now_ns)
        if frames and front is not None:
            front.record_launch(launched_n, time.monotonic() - t0)
        any_launch = bool(frames)
        if plans is not None:
            # Re-align miss results with their plans, observe the engine
            # rows, and merge hits/sheds/engine decisions per frame.
            merged = []
            it = iter(results)
            for plan in plans:
                res = (
                    next(it) if plan["miss_frame"] is not None else None
                )
                if front is not None and front.deny_cache is not None:
                    self._observe_plan(plan, res, now_ns, seq)
                merged.append(self._merge_plan(plan, res))
            results = merged
        self._maybe_record(batches, results, now_ns)
        # Metrics: ONE aggregated record for the whole window — it was
        # one device launch (record_batch bumps device_launches, so
        # per-sub-batch calls would overcount launches by up to
        # max_scan_depth and wreck the coalescing ratio).
        tot_allowed = tot_denied = tot_errors = 0
        denied_keys: list = []
        track_denied = (
            self.metrics is not None
            and self.metrics.top_denied is not None
        )
        for (blob, offsets, _p, gen, fd, _b), res in zip(batches, results):
            n_a, n_d, n_e, dk = self._respond_one(
                blob, offsets, gen, fd, res, track_denied
            )
            tot_allowed += n_a
            tot_denied += n_d
            tot_errors += n_e
            denied_keys.extend(dk)
            any_launch = any_launch or res is not None
        if self.insight is not None:
            # Throttled (~1/s) insight poll; this driver thread may
            # block on the device, exactly like its decide launches.
            self.insight.maybe_poll(now_ns, self.limiter_lock)
        if self.control is not None:
            # Throttled control tick, same discipline.  The native wire
            # layer holds its own pending queue device-side of this
            # driver, so depth 0 is the honest engine-queue reading —
            # admission's EWMA wait still carries the launch-cost
            # signal.
            self.control.maybe_tick(now_ns, self.limiter_lock)
        if self.checkpointer is not None:
            if frames:
                # Launched rows mark dirty for the next delta (raw wire
                # key bytes — the identity the keymap holds on this
                # path, so the delta gather matches the export).
                self.checkpointer.note_keys(
                    k
                    for b, o, _p in frames
                    for k in self._keys_of(b, o)
                )
            # Throttled checkpoint write: device export under
            # limiter_lock, encode + fsync outside it — this driver
            # thread blocks on the device for its decides anyway.
            self.checkpointer.maybe_tick(now_ns, self.limiter_lock)
        if self.metrics is not None and (
            any_launch or tot_errors
        ):
            self.metrics.record_batch(
                self.name,
                n_allowed=tot_allowed,
                n_denied=tot_denied,
                n_errors=tot_errors,
                denied_keys=denied_keys,
                # Only requests that actually rode the launch count
                # toward the batching/coalescing gauges.
                batch=(
                    launched_n
                    if plans is not None
                    else tot_allowed + tot_denied + tot_errors
                ),
                launches=1 if frames else 0,
            )
        self._maybe_sweep(now_ns, sum(len(b[1]) - 1 for b in batches))

    def _maybe_record(self, batches, results, now_ns) -> None:
        """Flight-recorder capture (replay/): the native twin of
        engine._maybe_record — per-batch, already off any event loop
        (this is the driver thread), one None check when disarmed."""
        from ..replay.recorder import active_recorder
        from ..replay.trace import SOURCE_NATIVE

        rec = active_recorder()
        if rec is None:
            return
        for (blob, offsets, params, _gen, _fd, _budgets), res in zip(
            batches, results
        ):
            n = len(offsets) - 1
            keys = [
                blob[offsets[i]: offsets[i + 1]] for i in range(n)
            ]
            if res is None:
                allowed = np.zeros(n, np.uint8)
                status = np.full(n, STATUS_INTERNAL, np.uint8)
            else:
                allowed = res.allowed
                status = res.status
            rec.record_window(
                now_ns, keys, params.reshape(n, 4), allowed, status,
                source=SOURCE_NATIVE,
            )

    def _respond_one(
        self, blob, offsets, cookie_gen, cookie_fd, res, track_denied
    ):
        """Serialize one sub-batch's replies; returns (n_allowed,
        n_denied, n_errors, denied_keys) for the caller's aggregate."""
        n = len(offsets) - 1
        results = np.zeros(5 * n, np.int64)
        if res is None:
            status = np.full(n, STATUS_INTERNAL, np.uint8)
        else:
            status = np.ascontiguousarray(res.status, np.uint8)
            out = results.reshape(n, 5)
            out[:, 0] = res.allowed
            out[:, 1] = res.limit
            out[:, 2] = res.remaining
            out[:, 3] = res.reset_after_s
            out[:, 4] = res.retry_after_s
        self._lib.ws_respond(
            self._h,
            n,
            np.ascontiguousarray(cookie_gen).ctypes.data_as(
                ctypes.c_void_p
            ),
            np.ascontiguousarray(cookie_fd).ctypes.data_as(ctypes.c_void_p),
            results.ctypes.data_as(ctypes.c_void_p),
            status.ctypes.data_as(ctypes.c_void_p),
        )
        ok = status == 0
        allowed_mask = results.reshape(n, 5)[:, 0] != 0
        if track_denied:
            denied_keys = [
                blob[offsets[i] : offsets[i + 1]].decode("utf-8", "replace")
                for i in np.flatnonzero(~allowed_mask & ok)
            ]
        else:
            denied_keys = []
        return (
            int((allowed_mask & ok).sum()),
            int((~allowed_mask & ok).sum()),
            int((~ok).sum()),
            denied_keys,
        )

    def _push_metrics(self) -> None:
        """GET /metrics, GET /health and GET /stats are served from
        these snapshots (HTTP protocol; the wire layer answers all
        three without a Python round-trip — pushed once per second from
        the drive loop)."""
        if self.PROTOCOL != 1:
            return
        if self.metrics is not None:
            text = self.metrics.export_prometheus().encode()
            self._lib.ws_set_metrics(self._h, text, len(text))
        from .supervisor import supervisor_state

        if self._draining:
            state = "draining"
        else:
            state = supervisor_state(self.limiter)
        body = b"OK" if state == "ok" else state.encode()
        if self.checkpointer is not None:
            # Last-checkpoint age rides /health only when durability is
            # armed (the bare "OK" body is a wire contract otherwise) —
            # same rule as the python HTTP route.
            body += b" " + self.checkpointer.health_suffix().encode()
        self._lib.ws_set_health(self._h, body, len(body))
        if self.insight is not None:
            from .metrics import merge_cluster_stats

            # Cluster deployments: the membership/handoff/replica view
            # rides the same pushed snapshot (shared helper keeps it in
            # lockstep with the python HTTP route).
            stats = merge_cluster_stats(
                self.insight.stats_json(state=state), self.limiter
            ).encode()
            self._lib.ws_set_stats(self._h, stats, len(stats))

    def _maybe_sweep(self, now_ns: int, n_ops: int) -> None:
        """Policy state is shared with the asyncio engine — all policy
        interaction happens under limiter_lock (see engine._maybe_sweep)."""
        policy = self.cleanup_policy
        if policy is None:
            return
        from ..tpu.cleanup import feed_expired_hits

        n_hits = 0
        with self.limiter_lock:
            policy.record_ops(n_ops)
            # Did the throttled drain just hit the device?  Then the
            # pre-sweep force drain below would be a redundant second
            # blocking fetch (same lock hold, nothing launched between).
            fetched = getattr(
                self.limiter, "expired_hits_fetch_due", lambda t: False
            )(now_ns)
            n_hits += feed_expired_hits(policy, self.limiter, now_ns)
            live = len(self.limiter)
            capacity = getattr(self.limiter, "total_capacity", 1 << 62)
            if not policy.should_clean(now_ns, live, capacity):
                freed = None
            else:
                # Attribute on-device hits to the window this sweep
                # closes (see engine._maybe_sweep); this driver thread
                # already sweeps inline, so the blocking fetch is
                # acceptable here.
                if not fetched:
                    n_hits += feed_expired_hits(
                        policy, self.limiter, now_ns, force=True
                    )
                freed = self.limiter.sweep(now_ns)
                policy.after_sweep(now_ns, freed, live)
        if freed is not None and self.front is not None:
            # Swept buckets are gone even for a later regressed clock:
            # drop the deny-cache entries they backed.
            self.front.on_sweep(now_ns)
        if self.metrics is not None:
            if n_hits:
                self.metrics.record_expired_hits(n_hits)
            if freed is not None:
                self.metrics.record_sweep(freed)
