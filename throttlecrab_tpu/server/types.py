"""Shared request/response types (reference: throttlecrab-server/src/types.rs).

`ThrottleResponse` carries whole *seconds* for reset_after/retry_after — the
reference truncates its internal Durations to seconds at the type boundary
(`types.rs:87-97`), and both its HTTP JSON and gRPC proto expose integer
seconds.  The engine keeps nanoseconds internally and truncates here.
"""

from __future__ import annotations

from dataclasses import dataclass

NS_PER_SEC = 1_000_000_000


@dataclass
class ThrottleRequest:
    """One rate-limit check (types.rs:32-45); timestamp is server-side.

    `deadline_ns` is the optional client deadline, absolute in the
    engine's now_fn clock (None = no deadline — byte-identical legacy
    behavior).  Requests still queued past it are shed at flush time,
    before any device dispatch, with STATUS_DEADLINE semantics."""

    key: str
    max_burst: int
    count_per_period: int
    period: int
    quantity: int = 1
    deadline_ns: int | None = None


@dataclass
class ThrottleResponse:
    """Decision returned to every transport (types.rs:74-85)."""

    allowed: bool
    limit: int
    remaining: int
    reset_after: int  # whole seconds (truncated)
    retry_after: int  # whole seconds (truncated)

    @classmethod
    def from_ns(
        cls,
        allowed: bool,
        limit: int,
        remaining: int,
        reset_after_ns: int,
        retry_after_ns: int,
    ) -> "ThrottleResponse":
        return cls(
            allowed=allowed,
            limit=limit,
            remaining=remaining,
            reset_after=reset_after_ns // NS_PER_SEC,
            retry_after=retry_after_ns // NS_PER_SEC,
        )
