"""Failure-domain supervision: retry, degrade, re-promote.

The serving stack's single point of hardware failure is the device: a
TPU claim dying surfaces as ``UNAVAILABLE``-shaped launch/fetch errors
(VERDICT.md round 5), and before this module the engine's only answer
was to fail the whole batch (`engine.py` launch except-branch).  The
reference's actor survives because it never leaves the host; this is
the TPU-native equivalent — a supervised launch path with an explicit
state machine:

    ok → retrying → degraded → recovering → ok

* **retrying** — a launch raised a *transient* (UNAVAILABLE-shaped)
  error; retry with bounded exponential backoff.  Deterministic errors
  (bad params, keymap capacity) are never retried — retrying cannot
  fix them and would triple the latency of every poisoned batch.
* **degraded** — transient retries exhausted: the device is declared
  down.  The bucket table is snapshotted host-side (tpu/snapshot.py
  ``export_state``) into a ``core/`` scalar-GCRA oracle over a
  MapStore — the CPU fallback the core layer exists to be — and every
  decision continues with bit-identical GCRA semantics at host
  throughput.  The front tier's deny cache stays valid: the oracle
  continues from the exact TATs the cache was certified against.
* **recovering** — a probe launch (reserved key, quantity-0 free
  probe) succeeded: host-mutated buckets are bulk-inserted back into
  the device table (snapshot ``_bulk_insert``), the deny cache is
  invalidated through the existing ``on_restore`` hook (the restore
  rewrote bucket state), and the state returns to ok.  Keys untouched
  while degraded keep their device rows — the oracle was seeded from
  them, so nothing is lost or double-counted in either direction.

``SupervisedLimiter`` duck-types the limiter API the batching engine
and the native wire drivers consume, so wrapping the device limiter
once supervises every transport (they all share the same limiter and
``limiter_lock``; all supervised calls run inside that lock, which is
what serializes state transitions with decisions).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

from ..core.store.mapstore import MapStore

log = logging.getLogger("throttlecrab.supervisor")

NS_PER_SEC = 1_000_000_000
I32_MAX = (1 << 31) - 1

STATE_OK = "ok"
STATE_RETRYING = "retrying"
STATE_DEGRADED = "degraded"
STATE_RECOVERING = "recovering"
#: /metrics gauge encoding of the state machine.
STATE_GAUGE = {
    STATE_OK: 0,
    STATE_RETRYING: 1,
    STATE_DEGRADED: 2,
    STATE_RECOVERING: 3,
}

#: The reserved key the recovery probe decides (quantity-0 free probe:
#: consumes nothing; one keymap slot is the total footprint).
PROBE_KEY = "__throttlecrab_supervisor_probe__"

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"

#: Message fragments that mark a device/runtime error as transient —
#: the strings PJRT/gRPC put on a lost or flapping device.  Injected
#: faults (faults/injector.py) produce the same shapes on purpose, so
#: chaos tests exercise this exact classifier.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "DEADLINE EXCEEDED",
    "ABORTED",
    "CONNECTION RESET",
    "SOCKET CLOSED",
    "FAILED TO CONNECT",
    "DEVICE OR RESOURCE BUSY",
)


def classify_exception(exc: BaseException) -> str:
    """TRANSIENT (retry may help) vs DETERMINISTIC (it cannot).

    Validation errors, keymap capacity exhaustion and other logic
    errors re-raise on every attempt; only infrastructure-shaped
    failures (lost device, reset socket, deadline) earn a retry.
    """
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    msg = str(exc).upper()
    if any(marker in msg for marker in _TRANSIENT_MARKERS):
        return TRANSIENT
    return DETERMINISTIC


def supervisor_of(limiter):
    """The SupervisedLimiter inside `limiter`'s wrapper chain, or None
    (walks ClusterLimiter.local)."""
    seen = 0
    while limiter is not None and seen < 4:
        if isinstance(limiter, SupervisedLimiter):
            return limiter
        limiter = getattr(limiter, "local", None)
        seen += 1
    return None


def supervisor_state(limiter) -> str:
    """The serving state for /health: "ok" when unsupervised."""
    sup = supervisor_of(limiter)
    return sup.state if sup is not None else STATE_OK


# ------------------------------------------------------------------ #
# Host oracle: the core/ scalar engine behind the batch API.


class _OracleStore(MapStore):
    """MapStore without an inline cleanup policy: the supervisor sweeps
    explicitly through the engine's cleanup path."""

    def _maybe_cleanup(self, now_ns: int) -> None:
        pass

    @property
    def data(self):
        return self._data


class HostOracle:
    """The ``core/`` scalar GCRA limiter shaped like the batch API.

    Decisions are bit-identical to the device kernel by construction —
    the scalar path *is* the repo's differential-test oracle.  Keys are
    normalized exactly like the device keymap (str→bytes when the
    keymap is bytes-keyed) so one client key stays one bucket across
    the degrade/re-promote boundary.
    """

    def __init__(self, bytes_keys: bool = False, insight=None) -> None:
        from ..core.rate_limiter import RateLimiter

        self.bytes_keys = bytes_keys
        #: Insight tier (L3.75): decided rows feed it so /stats totals
        #: stay truthful while the device accumulators are frozen.
        self.insight = insight
        self.store = _OracleStore()
        self._rl = RateLimiter(self.store)
        #: Keys whose buckets the host wrote (allowed decisions) — the
        #: exact set re-promotion must push back to the device.
        self.mutated: set = set()

    def _norm(self, key):
        if self.bytes_keys and isinstance(key, str):
            return key.encode()
        return key

    def seed(self, keys, tats, expiries) -> int:
        """Install exported device rows as the oracle's starting state."""
        data = self.store.data
        for key, tat, exp in zip(keys, tats, expiries):
            data[self._norm(key)] = (int(tat), int(exp))
        return len(keys)

    def export_mutated(self, now_ns: int):
        """(keys, tats, expiries) of live host-written buckets — what
        re-promotion bulk-inserts back into the device table."""
        keys, tats, exps = [], [], []
        data = self.store.data
        for key in self.mutated:
            entry = data.get(key)
            if entry is None:
                continue
            tat, exp = entry
            if exp is not None and exp <= now_ns:
                continue  # TTL lapsed while degraded: nothing to restore
            keys.append(key)
            tats.append(int(tat))
            exps.append(int(exp))
        return keys, tats, exps

    def rate_limit_batch(
        self, keys, max_burst, count_per_period, period, quantity,
        now_ns: int, wire: bool = False, collect_cur: bool = False,
    ):
        """One shared-timestamp batch through the scalar engine, row by
        row in arrival order (the actor semantics the kernel reproduces
        with segment ranks)."""
        from ..core.errors import (
            InternalError,
            InvalidRateLimit,
            NegativeQuantity,
        )
        from ..tpu.limiter import (
            STATUS_INTERNAL,
            STATUS_INVALID_PARAMS,
            STATUS_NEGATIVE_QUANTITY,
            BatchResult,
            WireBatchResult,
        )

        n = len(keys)
        mb = np.broadcast_to(np.asarray(max_burst, np.int64), (n,))
        cp = np.broadcast_to(np.asarray(count_per_period, np.int64), (n,))
        pd = np.broadcast_to(np.asarray(period, np.int64), (n,))
        qt = np.broadcast_to(np.asarray(quantity, np.int64), (n,))

        allowed = np.zeros(n, bool)
        limit = np.zeros(n, np.int64)
        remaining = np.zeros(n, np.int64)
        reset_ns = np.zeros(n, np.int64)
        retry_ns = np.zeros(n, np.int64)
        status = np.zeros(n, np.uint8)
        for i in range(n):
            key = self._norm(keys[i])
            try:
                ok, res = self._rl.rate_limit(
                    key, int(mb[i]), int(cp[i]), int(pd[i]), int(qt[i]),
                    now_ns,
                )
            except NegativeQuantity:
                status[i] = STATUS_NEGATIVE_QUANTITY
                continue
            except InvalidRateLimit:
                status[i] = STATUS_INVALID_PARAMS
                continue
            except InternalError:
                status[i] = STATUS_INTERNAL
                continue
            allowed[i] = ok
            limit[i] = res.limit
            remaining[i] = res.remaining
            reset_ns[i] = res.reset_after_ns
            retry_ns[i] = res.retry_after_ns
            if ok:
                self.mutated.add(key)

        if self.insight is not None:
            # Degraded-mode accounting: the scalar path reports its OK
            # rows so /stats stays truthful while the device (and its
            # accumulators) is down.
            ok_rows = np.flatnonzero(status == 0)
            self.insight.record_host_rows(
                [self._norm(keys[int(i)]) for i in ok_rows],
                allowed[ok_rows].tolist(),
            )

        if wire:
            # The wire truncation every transport emits (seconds,
            # i32-clamped) — identical to the cluster forwarder's
            # host-side conversion and the compact kernel output.
            return WireBatchResult(
                allowed=allowed,
                limit=limit,
                remaining=np.minimum(remaining, I32_MAX),
                reset_after_s=np.minimum(reset_ns // NS_PER_SEC, I32_MAX),
                retry_after_s=np.minimum(retry_ns // NS_PER_SEC, I32_MAX),
                status=status,
            )
        return BatchResult(
            allowed=allowed,
            limit=limit,
            remaining=remaining,
            reset_after_ns=reset_ns,
            retry_after_ns=retry_ns,
            status=status,
        )

    def rate_limit_many(
        self, batches, wire: bool = False, collect_cur: bool = False
    ) -> list:
        return [
            self.rate_limit_batch(*batch, wire=wire) for batch in batches
        ]

    def sweep(self, now_ns: int) -> int:
        return self.store._sweep(now_ns)

    def __len__(self) -> int:
        return len(self.store)


# ------------------------------------------------------------------ #


class SupervisedLimiter:
    """The device limiter behind the failure-domain state machine.

    Duck-types the limiter API (rate_limit_batch / rate_limit_many /
    dispatch_many / dispatch_wire_window / sweep / __len__ — each of
    the optional methods offered only when the wrapped limiter offers
    it); everything else delegates to the wrapped limiter.  All decide
    paths must run under the caller's ``limiter_lock`` — the same
    contract the unwrapped limiter already has — which is what makes
    state transitions atomic with respect to decisions.
    """

    def __init__(
        self,
        inner,
        retries: int = 3,
        backoff_us: int = 2000,
        backoff_max_us: int = 50_000,
        probe_interval_ms: int = 1000,
        mode: str = "degrade",
        metrics=None,
        front=None,
        insight=None,
        sleep_fn=None,
    ) -> None:
        import inspect
        import time

        self.insight = insight
        self.inner = inner
        self.retries = max(int(retries), 0)
        self.backoff_s = max(backoff_us, 0) / 1e6
        self.backoff_max_s = max(backoff_max_us, backoff_us, 0) / 1e6
        self.probe_interval_ns = max(probe_interval_ms, 1) * 1_000_000
        self.mode = mode  # "degrade" | "fail"
        self.metrics = metrics
        self.front = front
        self._sleep = sleep_fn or time.sleep
        self._mu = threading.Lock()  # supervisor state (health reads race)
        self._state = STATE_OK
        self._oracle: Optional[HostOracle] = None
        self._last_probe_ns = 0
        # Diagnostics, mirrored into /metrics by the server.
        self.retry_count = 0
        self.degrade_count = 0
        self.repromote_count = 0
        #: Capacity-change hooks (run_server wires these to the cluster
        #: tier's schedule_reweight): a node whose device died serves
        #: from the host oracle at a fraction of device throughput, so
        #: it announces a reduced ring weight and its neighbours absorb
        #: the difference; re-promotion restores it.  Called UNDER the
        #: limiter lock, so hooks must only schedule work (never take
        #: cluster locks inline).
        self.on_degrade = None
        self.on_repromote = None

        def params_of(fn):
            try:
                return inspect.signature(fn).parameters
            except (TypeError, ValueError):
                return {}

        self._batch_kw = {
            p
            for p in ("wire", "collect_cur")
            if p in params_of(inner.rate_limit_batch)
        }
        # Offer each optional API only when the wrapped limiter offers
        # it — the engine and the native drivers feature-detect with
        # hasattr, and advertising an API the inner can't back would
        # silently change which path they pick.
        if hasattr(inner, "rate_limit_many"):
            self._many_kw = {
                p
                for p in ("wire", "collect_cur")
                if p in params_of(inner.rate_limit_many)
            }
            self.rate_limit_many = self._rate_limit_many
        if hasattr(inner, "dispatch_many"):
            self._dispatch_kw = {
                p
                for p in ("wire", "collect_cur")
                if p in params_of(inner.dispatch_many)
            }
            self.dispatch_many = self._dispatch_many
        if hasattr(inner, "dispatch_wire_window"):
            self._wire_window_kw = {
                p
                for p in ("collect_cur",)
                if p in params_of(inner.dispatch_wire_window)
            }
            self.dispatch_wire_window = self._dispatch_wire_window
        if hasattr(inner, "expired_hits_fetch_due"):
            self.expired_hits_fetch_due = self._expired_hits_fetch_due
        if hasattr(inner, "take_expired_hits"):
            self.take_expired_hits = self._take_expired_hits

    # -- state ---------------------------------------------------------- #

    @property
    def state(self) -> str:
        return self._state

    @property
    def degraded(self) -> bool:
        return self._state in (STATE_DEGRADED, STATE_RECOVERING)

    def _set_state(self, state: str) -> None:
        with self._mu:
            self._state = state

    def _cas_state(self, expect, state: str) -> None:
        """Transition only from `expect` (tuple of states): the lock-free
        fetch path runs concurrently with dispatch-side transitions, and
        an unconditional write could undo a concurrent degrade (flipping
        DEGRADED back to OK would orphan the oracle and its mutations)."""
        with self._mu:
            if self._state in expect:
                self._state = state

    def export_degraded_state(self):
        """(keys, tats, expiries) of the host oracle while degraded,
        else None — snapshot.export_state consults this so a shutdown
        snapshot taken mid-outage captures the freshest state."""
        oracle = self._oracle
        if not self.degraded or oracle is None:
            return None
        data = oracle.store.data
        keys = list(data.keys())
        tats = [data[k][0] for k in keys]
        exps = [
            data[k][1] if data[k][1] is not None else (1 << 62)
            for k in keys
        ]
        return keys, tats, exps

    def __getattr__(self, name):
        # Everything not supervised (keymap, table, total_capacity,
        # keymaps, ...) belongs to the wrapped limiter.
        return getattr(self.inner, name)

    def __len__(self) -> int:
        if self.degraded and self._oracle is not None:
            return len(self._oracle)
        return len(self.inner)

    # -- supervised call core ------------------------------------------- #

    def _note_retry(self, exc, attempt) -> None:
        self.retry_count += 1
        if self.metrics is not None:
            self.metrics.record_supervisor_retry()
        log.warning(
            "transient device fault (attempt %d/%d): %s",
            attempt + 1, self.retries + 1, exc,
        )

    def _supervised(self, device_fn, host_fn, now_ns):
        """Run a device operation under the state machine.

        ok/retrying: try the device, retrying transient faults with
        bounded exponential backoff; exhaustion degrades (mode
        "degrade") or re-raises (mode "fail").  degraded: serve from
        the host oracle, probing the device on the configured cadence
        (driven by the caller's now_ns, so virtual-time tests control
        it).  Deterministic errors always raise — they are the
        request's fault, not the device's.
        """
        if self.degraded:
            if self._probe_due(now_ns):
                self._try_recover(now_ns)
            if self.degraded:
                return host_fn()
            # fall through: recovered, decide on the device
        delay = self.backoff_s
        last_exc = None
        for attempt in range(self.retries + 1):
            try:
                out = device_fn()
                self._cas_state((STATE_RETRYING,), STATE_OK)
                return out
            except Exception as exc:
                if classify_exception(exc) != TRANSIENT:
                    raise
                last_exc = exc
                self._cas_state((STATE_OK, STATE_RETRYING), STATE_RETRYING)
                self._note_retry(exc, attempt)
                if attempt < self.retries:
                    if delay > 0:
                        self._sleep(delay)
                    delay = min(delay * 2, self.backoff_max_s)
        # Transient retries exhausted: the device is down.
        if self.mode != "degrade":
            raise last_exc
        self._degrade(now_ns, last_exc)
        if host_fn is None:
            # dispatch_wire_window has no direct host form — the caller
            # sees the degraded state and takes its documented fallback.
            return None
        return host_fn()

    def _degrade(self, now_ns: int, exc) -> None:
        from ..tpu.limiter import limiter_uses_bytes_keys
        from ..tpu.snapshot import export_state

        log.error(
            "device failure persists after %d retries; degrading to "
            "the host scalar oracle: %s", self.retries + 1, exc,
        )
        oracle = HostOracle(
            bytes_keys=limiter_uses_bytes_keys(self.inner),
            insight=self.insight,
        )
        try:
            keys, _slots, _shard, tats, exps, _cap, _d = export_state(
                self.inner
            )
            n = oracle.seed(keys, tats, exps)
            log.info("host oracle seeded with %d live buckets", n)
        except Exception:
            # The same dead device that forced the degrade can refuse
            # the table fetch: soft state — start empty rather than
            # shed traffic (snapshot.py's stale-snapshot contract).
            log.exception(
                "host-side table snapshot failed; host oracle starts "
                "empty (soft state)"
            )
        self._oracle = oracle
        self._last_probe_ns = now_ns
        self.degrade_count += 1
        if self.metrics is not None:
            self.metrics.record_supervisor_degrade()
        self._set_state(STATE_DEGRADED)
        # Flight recorder (replay/): a persistent degrade is exactly the
        # failure a post-mortem trace exists for — stamp the timeline
        # and dump the ring.  The dump runs on its own daemon thread
        # (request_degrade_dump): this path holds the limiter lock and
        # must never block on file I/O.
        from ..replay.recorder import active_recorder, maybe_record_event

        maybe_record_event("degrade", str(exc), now_ns=now_ns)
        recorder = active_recorder()
        if recorder is not None:
            recorder.request_degrade_dump()
        if self.on_degrade is not None:
            try:
                self.on_degrade()
            except Exception:
                log.exception("on_degrade hook failed")

    def _probe_due(self, now_ns: int) -> bool:
        return now_ns - self._last_probe_ns >= self.probe_interval_ns

    def _try_recover(self, now_ns: int) -> bool:
        """Probe the device; on success re-promote the host state."""
        self._set_state(STATE_RECOVERING)
        self._last_probe_ns = now_ns
        try:
            kw = {"wire": True} if "wire" in self._batch_kw else {}
            self.inner.rate_limit_batch(
                [PROBE_KEY], 1, 1, 1, 0, now_ns, **kw
            )
        except Exception as exc:
            log.info("device probe failed; staying degraded: %s", exc)
            self._set_state(STATE_DEGRADED)
            return False
        try:
            from ..tpu.snapshot import _bulk_insert

            keys, tats, exps = self._oracle.export_mutated(now_ns)
            if keys:
                _bulk_insert(self.inner, keys, tats, exps)
            if self.front is not None:
                # The bulk insert rewrote bucket state out from under
                # any cached denials.
                self.front.on_restore()
        except Exception:
            # Retry the whole promotion at the next probe: the mutated
            # set keeps accumulating, and re-inserting a key twice
            # writes the same (or newer) state — idempotent.
            log.exception("re-promotion failed; staying degraded")
            self._set_state(STATE_DEGRADED)
            return False
        log.info(
            "device recovered; re-promoted %d host-mutated buckets",
            len(keys),
        )
        from ..replay.recorder import maybe_record_event

        maybe_record_event(
            "repromote", f"{len(keys)} buckets", now_ns=now_ns
        )
        self._oracle = None
        self.repromote_count += 1
        if self.metrics is not None:
            self.metrics.record_supervisor_repromote()
        self._set_state(STATE_OK)
        if self.on_repromote is not None:
            try:
                self.on_repromote()
            except Exception:
                log.exception("on_repromote hook failed")
        return True

    # -- the limiter API ------------------------------------------------ #

    def _kw(self, allowed, wire, collect_cur):
        kw = {}
        if "wire" in allowed:
            kw["wire"] = wire
        if "collect_cur" in allowed:
            kw["collect_cur"] = collect_cur
        return kw

    def rate_limit_batch(
        self, keys, max_burst, count_per_period, period, quantity,
        now_ns: int, wire: bool = False, collect_cur: bool = False,
    ):
        kw = self._kw(self._batch_kw, wire, collect_cur)
        return self._supervised(
            lambda: self.inner.rate_limit_batch(
                keys, max_burst, count_per_period, period, quantity,
                now_ns, **kw,
            ),
            lambda: self._oracle.rate_limit_batch(
                keys, max_burst, count_per_period, period, quantity,
                now_ns, wire=wire,
            ),
            now_ns,
        )

    def _rate_limit_many(
        self, batches, wire: bool = False, collect_cur: bool = False
    ) -> list:
        if not batches:
            return []
        kw = self._kw(self._many_kw, wire, collect_cur)
        now_ns = batches[-1][5]
        return self._supervised(
            lambda: self.inner.rate_limit_many(batches, **kw),
            lambda: self._oracle.rate_limit_many(batches, wire=wire),
            now_ns,
        )

    def _dispatch_many(
        self, batches, wire: bool = False, collect_cur: bool = False
    ):
        from ..tpu.limiter import _ReadyLaunch

        if not batches:
            return _ReadyLaunch([])
        kw = self._kw(self._dispatch_kw, wire, collect_cur)
        now_ns = batches[-1][5]
        out = self._supervised(
            lambda: self.inner.dispatch_many(batches, **kw),
            lambda: _ReadyLaunch(
                self._oracle.rate_limit_many(batches, wire=wire)
            ),
            now_ns,
        )
        if isinstance(out, _ReadyLaunch):
            return out
        return _SupervisedHandle(self, out)

    def _dispatch_wire_window(
        self, frames, now_ns: int, collect_cur: bool = False
    ):
        # Degraded (and degrade-on-exhaustion): return None — the
        # native driver's documented fallback re-decides the window
        # through rate_limit_many/rate_limit_batch on THIS wrapper,
        # which routes it to the host oracle.  Preparation is
        # idempotent, so the re-decide is safe (the device never
        # committed anything).
        if self.degraded:
            if self._probe_due(now_ns):
                self._try_recover(now_ns)
            if self.degraded:
                return None
        kw = (
            {"collect_cur": collect_cur}
            if "collect_cur" in self._wire_window_kw
            else {}
        )
        try:
            out = self._supervised(
                lambda: self.inner.dispatch_wire_window(
                    frames, now_ns, **kw
                ),
                None,
                now_ns,
            )
        except Exception:
            if not self.degraded:
                raise
            return None  # just degraded: fall back to the host path
        if out is None or self.degraded:
            # None also covers the inner dispatcher's own fallbacks
            # (python keymap, mid-batch param change, full table).
            return None
        return _SupervisedHandle(self, out)

    def supervised_fetch(self, fetch_fn):
        """Retry a deferred fetch through the same classifier.

        Decisions are committed on-device before any fetch, and a
        fetch is a read — retrying it can never double-count, so
        transient fetch faults are absorbed exactly like launch
        faults.  Exhaustion re-raises: the window's futures fail (the
        results are unreadable), and the *next launch* drives the
        degrade decision under the limiter lock, where the state
        machine is allowed to transition.
        """
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                out = fetch_fn()
                # CAS: this thread holds no limiter_lock, and a plain
                # write could undo a dispatch thread's concurrent
                # transition into DEGRADED.
                self._cas_state((STATE_RETRYING,), STATE_OK)
                return out
            except Exception as exc:
                if classify_exception(exc) != TRANSIENT:
                    raise
                self._cas_state((STATE_OK, STATE_RETRYING), STATE_RETRYING)
                self._note_retry(exc, attempt)
                if attempt >= self.retries:
                    raise
                if delay > 0:
                    self._sleep(delay)
                delay = min(delay * 2, self.backoff_max_s)

    def sweep(self, now_ns: int) -> int:
        if self.degraded and self._oracle is not None:
            return self._oracle.sweep(now_ns)
        return self.inner.sweep(now_ns)

    def _expired_hits_fetch_due(self, now_ns: int, *a, **kw) -> bool:
        if self.degraded:
            return False  # no device to fetch from
        return self.inner.expired_hits_fetch_due(now_ns, *a, **kw)

    def _take_expired_hits(self, now_ns: int, *a, **kw) -> int:
        if self.degraded:
            return 0
        return self.inner.take_expired_hits(now_ns, *a, **kw)


class _SupervisedHandle:
    """Wraps a dispatch handle so deferred fetches ride the classifier."""

    def __init__(self, supervisor: SupervisedLimiter, handle) -> None:
        self._sup = supervisor
        self._handle = handle

    def fetch(self):
        return self._sup.supervised_fetch(self._handle.fetch)
