"""throttlecrab-tpu server: micro-batching front-end + wire transports.

The TPU-native re-design of `throttlecrab-server`: where the reference funnels
every transport's requests through one mpsc channel into a single-threaded
actor (`actor.rs:102-236`), this server coalesces them into fixed-size
batches and decides thousands per device launch (engine.py).  The wire
surface is identical: HTTP/JSON, gRPC, and Redis/RESP speaking the reference
schemas, shared state across all three, server-side timestamps, Prometheus
metrics and `THROTTLECRAB_*` configuration.
"""

from .config import Config
from .engine import BatchingEngine
from .metrics import Metrics
from .types import ThrottleRequest, ThrottleResponse

__all__ = [
    "BatchingEngine",
    "Config",
    "Metrics",
    "ThrottleRequest",
    "ThrottleResponse",
]
