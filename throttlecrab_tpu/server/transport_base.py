"""Shared asyncio-transport scaffolding: connection-handler tracking and
shutdown that drops open connections.

The reference aborts its transport tasks on shutdown (main.rs:154-169), so
idle connections never delay exit.  asyncio's Server.wait_closed() (3.12+)
instead waits for every connection handler — these helpers give the HTTP
and RESP transports the reference behavior from one implementation.
"""

from __future__ import annotations

import asyncio


class ConnTrackingMixin:
    """Tracks live connection-handler tasks so stop() can cancel them."""

    def _init_conn_tracking(self) -> None:
        self._conn_tasks: set = set()

    async def drain(self) -> None:
        """Graceful-drain hook: stop accepting NEW connections while
        established ones keep serving (they see OverloadError once the
        engine drains; stop() later drops them).  No-op for transports
        without a closable listener."""
        server = getattr(self, "_server", None)
        if server is not None:
            server.close()

    def _track_conn(self):
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        return task

    def _untrack_conn(self, task) -> None:
        self._conn_tasks.discard(task)

    async def _stop_dropping_conns(self, server) -> None:
        """Close the listener, then cancel handlers until wait_closed()
        returns.  Cancelling in a retry loop covers two races: a handler
        task created just before close() that has not registered yet, and
        a handler re-entering an awaitable (writer.wait_closed) after a
        first cancellation."""
        server.close()
        while True:
            for task in list(self._conn_tasks):
                task.cancel()
            try:
                await asyncio.wait_for(server.wait_closed(), timeout=0.2)
                return
            except asyncio.TimeoutError:
                continue
